#!/bin/bash
# TPU relay probe daemon: logs a timestamped probe every 5 min; touches .tpu_healthy on success.
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  out=$(timeout 90 python -c "import jax; d=jax.devices(); print(d)" 2>&1 | tail -1)
  rc=$?
  echo "$ts rc=$rc ${out:0:200}" >> /root/repo/TPU_PROBES.log
  if [ "$rc" -eq 0 ] && echo "$out" | grep -qi tpu; then
    touch /root/repo/.tpu_healthy
  fi
  sleep 300
done
