#!/bin/bash
# TPU relay probe daemon v4: pure jax.devices() probe (no allocations — safe
# to kill), 300s budget, every 10 min. Touches .tpu_healthy on success and
# fires .on_heal_playbook.sh ONCE per wedged->healthy transition (detached),
# so a window that opens while no one is watching still gets burned on the
# priority list (bench -> tpu test tier -> serving bench).
ERRF=/tmp/.tpu_probe_err
# single-instance guard (round 4): session handoffs/restarts kept
# spawning duplicate daemons; the flock releases on any process death
exec 8>/tmp/.probe_daemon.lock
flock -n 8 || {
  echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) duplicate daemon start suppressed" \
    >> /root/repo/TPU_PROBES.log
  exit 0
}
# seed from the persisted marker so a daemon restart while healthy does not
# count as a heal transition — UNLESS no burn was ever recorded on this
# boot (/tmp/.window_burned is stamped by the playbook and cleared by
# reboot), which covers a wedge+heal cycle that happened while the daemon
# was down. Missing a window costs a round; a duplicate burn costs minutes.
PREV=wedged
[ -f /root/repo/.tpu_healthy ] && [ -f /tmp/.window_burned ] && PREV=healthy
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  # exec 8>&- closes the lock FD for the SUBSHELL itself, not just the
  # probe child — an orphaned in-flight probe must not hold the lock
  raw=$(exec 8>&-; timeout 300 python -c "import jax; print('DEV', jax.devices())" 2>"$ERRF")
  rc=$?
  out=$(printf '%s\n' "$raw" | grep DEV | tail -1)
  if [ "$rc" -eq 0 ] && [ -n "$out" ]; then
    echo "$ts rc=0 ${out:0:160}" >> /root/repo/TPU_PROBES.log
    touch /root/repo/.tpu_healthy
    if [ "$PREV" = wedged ]; then
      # launch unconditionally: the playbook's flock is the single
      # instance guard (one mechanism, self-releasing on death)
      echo "$ts heal transition: launching playbook" >> /root/repo/TPU_PROBES.log
      # 8>&-: children must NOT inherit the daemon's lock FD, or a
      # long-running playbook would block the daemon's own restart
      nohup /root/repo/.on_heal_playbook.sh >/dev/null 2>&1 8>&- &
    fi
    PREV=healthy
  else
    err=$(tail -c 200 "$ERRF" | tr '\n' ' ')
    echo "$ts rc=$rc out='${out:0:80}' err='${err}'" >> /root/repo/TPU_PROBES.log
    rm -f /root/repo/.tpu_healthy
    PREV=wedged
  fi
  sleep 600 8>&-  # no lock FD: an orphaned sleep must not block restart
done
