#!/bin/bash
# TPU relay probe daemon v3: pure jax.devices() probe (no allocations — safe
# to kill), 300s budget, every 10 min. Touches .tpu_healthy on success.
# Captures the probe's own exit code before piping (a pipeline would report
# tail's rc) and keeps the stderr tail so failure modes are diagnosable from
# TPU_PROBES.log alone.
ERRF=/tmp/.tpu_probe_err
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  raw=$(timeout 300 python -c "import jax; print('DEV', jax.devices())" 2>"$ERRF")
  rc=$?
  out=$(printf '%s\n' "$raw" | grep DEV | tail -1)
  if [ "$rc" -eq 0 ] && [ -n "$out" ]; then
    echo "$ts rc=0 ${out:0:160}" >> /root/repo/TPU_PROBES.log
    touch /root/repo/.tpu_healthy
  else
    err=$(tail -c 200 "$ERRF" | tr '\n' ' ')
    echo "$ts rc=$rc out='${out:0:80}' err='${err}'" >> /root/repo/TPU_PROBES.log
    rm -f /root/repo/.tpu_healthy
  fi
  sleep 600
done
