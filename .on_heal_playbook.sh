#!/bin/bash
# TPU-window playbook: launched by .probe_daemon.sh ONCE per wedged->healthy
# transition. Burns the window in priority order, SIGTERM-first (timeout's
# default) so a hung stage can't leave a dead pool claim the way a KILLed
# allocation does. Everything logs to TPU_WINDOW.log for the round report.
#
# Stage order (VERDICT r3 #1/#3/#6 + weak #7): the headline bench first,
# then the SAFE tier (previously-hardware-validated flash units + profile
# captures), then the serving throughput number, and the risky first-contact
# Mosaic compiles LAST — tools/tpu_burndown.py runs those one subprocess at
# a time, health-probing after each, with the known relay-killer (dropout
# hardware PRNG) at the very end. A wedge mid-burndown can no longer take
# the bench/profile/serving artifacts down with it.
set -u
LOG=/root/repo/TPU_WINDOW.log
ts() { date -u +%Y-%m-%dT%H:%M:%SZ; }
# single-instance guard: flock on a held fd releases on ANY process death
# (SIGKILL/OOM included), so a killed burn can never wedge future windows
exec 9>/tmp/.on_heal_playbook.lock
if ! flock -n 9; then
  echo "$(ts) playbook already running (lock held); exiting" >> "$LOG"
  exit 0
fi
touch /tmp/.window_burned
echo "$(ts) window opened — playbook start" >> "$LOG"

cd /root/repo

probe_or_stop() {
  timeout 300 python -c "import jax; jax.devices()" >/dev/null 2>&1 || {
    echo "$(ts) relay unhealthy after $1; playbook stops" >> "$LOG"; exit 0; }
}

# 1) headline bench (its orchestrator probes + falls back internally and
#    persists BENCH_TPU_SNAPSHOT.json itself on a real TPU number)
echo "$(ts) stage 1: bench.py" >> "$LOG"
timeout 1500 python bench.py > /tmp/.window_bench.json 2>/tmp/.window_bench.log
rc=$?
echo "$(ts) bench rc=$rc: $(cat /tmp/.window_bench.json 2>/dev/null)" >> "$LOG"
probe_or_stop "bench"

# 1b) measured peaks (VERDICT r4 #3): plain jitted matmul + stream —
#     the safest op class — then re-emit ROOFLINE.json with measured
#     peaks.  Roofline itself is pure CPU arithmetic.
echo "$(ts) stage 1b: measure_peaks" >> "$LOG"
timeout 900 python tools/measure_peaks.py >> "$LOG" 2>&1
rc=$?
echo "$(ts) measure_peaks rc=$rc" >> "$LOG"
[ $rc -eq 0 ] && timeout 120 python tools/roofline.py >> "$LOG" 2>&1
probe_or_stop "measure_peaks"

# 2) safe tier: hardware-validated flash kernels + xplane profile captures +
#    fused-serving correctness — per-unit subprocesses, health-probed.
#    Outer timeout = budget + 400s headroom (post-unit wedge probe 300s +
#    SIGTERM grace) so a wedge at the budget edge still records its culprit.
echo "$(ts) stage 2: burndown --phase safe" >> "$LOG"
timeout 2400 python tools/tpu_burndown.py --phase safe --budget 1800 \
    >> "$LOG" 2>&1
rc=$?
echo "$(ts) burndown safe rc=$rc" >> "$LOG"
[ $rc -eq 2 ] && { echo "$(ts) relay wedged in safe tier; stop" >> "$LOG"; exit 0; }
# rc=0 means the burndown's own final health probe just passed — only
# re-probe when the stage ended abnormally (e.g. outer-timeout kill)
[ $rc -ne 0 ] && probe_or_stop "safe tier"

# 2b) summarize any xplane captures the safe tier produced (pure file
#     reads — cannot touch the relay); bubble ratios + top ops land in
#     PROFILES_SUMMARY.json for the round report
timeout 300 python tools/analyze_xplane.py >> "$LOG" 2>&1

# 3) serving decode benchmark on the chip -> SERVING_TPU_SNAPSHOT.json
#    (repo root on the path — ambient PYTHONPATH only carries axon)
echo "$(ts) stage 3: bench_decode" >> "$LOG"
timeout 1200 env PYTHONPATH="/root/repo:${PYTHONPATH:-}" \
    python benchmarks/bench_decode.py > /tmp/.window_decode.json \
    2>/tmp/.window_decode.log
rc=$?
echo "$(ts) bench_decode rc=$rc: $(tail -c 400 /tmp/.window_decode.json 2>/dev/null)" >> "$LOG"
# validate + extract + atomically persist in ONE python process so a
# half-valid output can never clobber the last good serving snapshot
[ $rc -eq 0 ] && python - <<'EOF' >> "$LOG" 2>&1
import json, os
lines = [l.strip() for l in open('/tmp/.window_decode.json')
         if l.strip().startswith('{')]
rec = json.loads(lines[-1])
assert rec.get('detail', {}).get('tpu') is True, 'not a TPU record'
tmp = '/root/repo/SERVING_TPU_SNAPSHOT.json.tmp'
with open(tmp, 'w') as f:
    json.dump(rec, f); f.write('\n')
os.replace(tmp, '/root/repo/SERVING_TPU_SNAPSHOT.json')
print('serving snapshot persisted')
EOF
# no probe here: stage 4's burndown begins with its own health probe and
# exits cleanly (relay_down) if bench_decode wedged the relay

# 4) risky first-contact Mosaic compiles, safest->riskiest, dropout PRNG
#    (the 2026-07-31 relay-wedger) LAST; aborts itself on a wedge
echo "$(ts) stage 4: burndown --phase risky" >> "$LOG"
timeout 3000 python tools/tpu_burndown.py --phase risky --budget 2500 \
    >> "$LOG" 2>&1
rc=$?
echo "$(ts) burndown risky rc=$rc" >> "$LOG"

echo "$(ts) playbook complete" >> "$LOG"
