#!/bin/bash
# TPU-window playbook: launched by .probe_daemon.sh ONCE per wedged->healthy
# transition. Burns the window in priority order, SIGTERM-first (timeout's
# default) so a hung stage can't leave a dead pool claim the way a KILLed
# allocation does. Everything logs to TPU_WINDOW.log for the round report.
set -u
LOG=/root/repo/TPU_WINDOW.log
ts() { date -u +%Y-%m-%dT%H:%M:%SZ; }
# single-instance guard: flock on a held fd releases on ANY process death
# (SIGKILL/OOM included), so a killed burn can never wedge future windows
exec 9>/tmp/.on_heal_playbook.lock
if ! flock -n 9; then
  echo "$(ts) playbook already running (lock held); exiting" >> "$LOG"
  exit 0
fi
touch /tmp/.window_burned
echo "$(ts) window opened — playbook start" >> "$LOG"

cd /root/repo

# 1) headline bench (its orchestrator probes + falls back internally)
echo "$(ts) stage 1: bench.py" >> "$LOG"
timeout 1500 python bench.py > /tmp/.window_bench.json 2>/tmp/.window_bench.log
rc=$?
echo "$(ts) bench rc=$rc: $(cat /tmp/.window_bench.json 2>/dev/null)" >> "$LOG"
# keep the last GOOD snapshot: only overwrite on success with parseable JSON
if [ $rc -eq 0 ] && python -c "import json,sys; json.load(open('/tmp/.window_bench.json'))" 2>/dev/null; then
  cp /tmp/.window_bench.json /root/repo/BENCH_TPU_SNAPSHOT.json
fi

# stop if the relay died mid-stage (don't pile more claims on a wedge)
timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1 || {
  echo "$(ts) relay unhealthy after bench; playbook stops" >> "$LOG"; exit 0; }

# 2) real-TPU test tier: Mosaic-compile every Pallas kernel, hardware-PRNG
#    dropout checks, profile captures
echo "$(ts) stage 2: pytest -m tpu" >> "$LOG"
timeout 2400 python -m pytest tests/ -m tpu -q \
    > /tmp/.window_tputests.log 2>&1
rc=$?
echo "$(ts) pytest -m tpu rc=$rc: $(tail -1 /tmp/.window_tputests.log)" >> "$LOG"
cp /tmp/.window_tputests.log /root/repo/TPU_TESTS.log 2>/dev/null

timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1 || {
  echo "$(ts) relay unhealthy after tpu tests; playbook stops" >> "$LOG"; exit 0; }

# 3) serving decode benchmark on the chip (repo root on the path — the
# ambient PYTHONPATH only carries the axon sitecustomize)
echo "$(ts) stage 3: bench_decode" >> "$LOG"
timeout 900 env PYTHONPATH="/root/repo:${PYTHONPATH:-}" \
    python benchmarks/bench_decode.py > /tmp/.window_decode.log 2>&1
rc=$?
echo "$(ts) bench_decode rc=$rc: $(tail -2 /tmp/.window_decode.log | tr '\n' ' ')" >> "$LOG"

echo "$(ts) playbook complete" >> "$LOG"
