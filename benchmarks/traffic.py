"""Seed-deterministic serving traffic: the workload half of the
self-healing harness.

A ``TrafficSpec`` describes a workload the way a serving fleet sees
one — diurnal or bursty arrival rates, mixed tenants with mixed
priorities, mixed prompt lengths, a shared-prefix population (K system
prompts a fraction of requests reuse), sticky sessions — and
``generate(spec)`` expands it into per-step arrival lists that are a
pure function of ``spec.seed``. Every chaos comparison in
``bench_selfheal.py`` and ``tests/test_selfheal.py`` replays the SAME
schedule with remediation off vs on, so the only difference between
the two runs is the control loop under test.

``drive(gw, arrivals, ttft_slo_s, tick=...)`` is the matching load
loop: submit each step's arrivals (typed sheds are counted, not
raised), advance the gateway one tick, invoke the caller's hook (where
the remediator/autoscaler tick), and record per-step and per-request
outcomes. The result carries the two numbers the self-heal acceptance
gate cares about:

  * ``goodput_frac`` — completions within the TTFT SLO over ALL
    offered requests (sheds and failures count against goodput);
  * ``first_breach_step`` / ``last_breach_step`` — the SLO incident
    window in steps; ``recovery_steps`` is its length, i.e. how long
    the fleet took to get from the first out-of-SLO completion back to
    (and staying) in-SLO.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TrafficSpec", "TrafficRequest", "TrafficResult",
           "generate", "drive"]


@dataclass(frozen=True)
class TrafficSpec:
    """One deterministic workload description (see module docstring)."""

    seed: int = 0
    steps: int = 120
    vocab: int = 2048
    base_rate: float = 0.4          # expected arrivals per step
    pattern: str = "diurnal"        # diurnal | bursty | steady
    period: int = 80                # diurnal cycle length, steps
    swing: float = 0.5              # diurnal amplitude (frac of base)
    burst_at: Optional[int] = None  # bursty: burst window start step
    burst_len: int = 20
    burst_rate: float = 2.0         # arrivals/step inside the burst
    burst_tenant: str = "burst"
    tenants: Tuple[Tuple[str, float], ...] = (
        ("interactive", 0.7), ("batch", 0.3))
    prompt_lo: int = 8
    prompt_hi: int = 40
    new_lo: int = 4
    new_hi: int = 12
    n_shared: int = 3               # shared-prefix population size
    shared_len: int = 24
    shared_frac: float = 0.5        # frac of requests reusing a prefix
    session_frac: float = 0.3       # frac carrying a sticky session id
    n_sessions: int = 8


@dataclass
class TrafficRequest:
    """One scheduled arrival."""

    at_step: int
    prompt: np.ndarray
    max_new_tokens: int
    tenant: str
    priority: str
    session_id: Optional[str] = None


def _rate_at(spec: TrafficSpec, t: int) -> float:
    rate = spec.base_rate
    if spec.pattern == "diurnal":
        rate *= 1.0 + spec.swing * np.sin(2.0 * np.pi * t / spec.period)
    if spec.pattern == "bursty" or spec.burst_at is not None:
        if spec.burst_at is not None and \
                spec.burst_at <= t < spec.burst_at + spec.burst_len:
            rate += spec.burst_rate
    return max(0.0, rate)


def generate(spec: TrafficSpec) -> List[List[TrafficRequest]]:
    """Per-step arrival lists, a pure function of ``spec`` (one seeded
    RNG drives arrivals, tenants, lengths, and prefixes in a fixed
    draw order)."""
    rng = np.random.RandomState(spec.seed)
    shared = [rng.randint(0, spec.vocab, (spec.shared_len,))
              for _ in range(spec.n_shared)]
    names = [t for t, _ in spec.tenants]
    weights = np.asarray([w for _, w in spec.tenants], float)
    weights = weights / weights.sum()
    out: List[List[TrafficRequest]] = []
    for t in range(spec.steps):
        n = int(rng.poisson(_rate_at(spec, t)))
        in_burst = (spec.burst_at is not None
                    and spec.burst_at <= t < spec.burst_at
                    + spec.burst_len)
        batch: List[TrafficRequest] = []
        for _ in range(n):
            # burst arrivals beyond the base rate belong to the burst
            # tenant (the noisy neighbor the shed policy should name)
            if in_burst and rng.random_sample() > \
                    spec.base_rate / max(_rate_at(spec, t), 1e-9):
                tenant = spec.burst_tenant
            else:
                tenant = names[int(rng.choice(len(names), p=weights))]
            priority = "low" if tenant == "batch" else "high"
            tail_len = int(rng.randint(spec.prompt_lo,
                                       spec.prompt_hi + 1))
            if rng.random_sample() < spec.shared_frac:
                head = shared[int(rng.randint(spec.n_shared))]
                prompt = np.concatenate(
                    [head, rng.randint(0, spec.vocab, (tail_len,))])
            else:
                prompt = rng.randint(0, spec.vocab, (tail_len,))
            sid = (f"s{int(rng.randint(spec.n_sessions))}"
                   if rng.random_sample() < spec.session_frac else None)
            batch.append(TrafficRequest(
                at_step=t, prompt=prompt,
                max_new_tokens=int(rng.randint(spec.new_lo,
                                               spec.new_hi + 1)),
                tenant=tenant, priority=priority, session_id=sid))
        out.append(batch)
    return out


@dataclass
class TrafficResult:
    """Outcome of one driven schedule."""

    ttft_slo_s: float
    submitted: int = 0
    shed: int = 0
    completions: int = 0
    in_slo: int = 0
    failed: int = 0
    ttfts: List[float] = field(default_factory=list)
    # per-step series (index = step): queue depth, completions, worst
    # TTFT completed that step (None when none completed)
    queue_depth: List[int] = field(default_factory=list)
    step_completions: List[int] = field(default_factory=list)
    step_worst_ttft: List[Optional[float]] = field(default_factory=list)
    first_breach_step: Optional[int] = None
    last_breach_step: Optional[int] = None

    @property
    def offered(self) -> int:
        return self.submitted + self.shed

    @property
    def goodput_frac(self) -> float:
        return self.in_slo / max(self.offered, 1)

    @property
    def recovery_steps(self) -> int:
        """Steps from the first out-of-SLO completion until the fleet
        was back (and stayed) in-SLO; 0 when no breach ever happened."""
        if self.first_breach_step is None:
            return 0
        return self.last_breach_step - self.first_breach_step + 1

    def summary(self) -> Dict[str, object]:
        return {"offered": self.offered, "submitted": self.submitted,
                "shed": self.shed, "completions": self.completions,
                "failed": self.failed, "in_slo": self.in_slo,
                "goodput_frac": round(self.goodput_frac, 4),
                "ttft_p99_ms": round(_p99(self.ttfts) * 1e3, 3)
                if self.ttfts else None,
                "first_breach_step": self.first_breach_step,
                "last_breach_step": self.last_breach_step,
                "recovery_steps": self.recovery_steps}


def _p99(xs: Sequence[float]) -> float:
    s = sorted(xs)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def drive(gw, arrivals: List[List[TrafficRequest]], ttft_slo_s: float,
          tick: Optional[Callable[[int], None]] = None,
          max_drain_steps: int = 4000) -> TrafficResult:
    """Run ``arrivals`` against ``gw``: one gateway step per schedule
    step (plus drain steps until the queue empties), ``tick(step)``
    after each — the hook where a remediator/autoscaler advances.
    Typed rejections (quota, queue capacity, infeasible deadline) are
    counted as sheds, not raised."""
    res = TrafficResult(ttft_slo_s=ttft_slo_s)
    meta: Dict[int, int] = {}           # gid -> submit step

    def _submit(step_i: int, batch: List[TrafficRequest]):
        for tr in batch:
            try:
                gid = gw.submit(tr.prompt, tr.max_new_tokens,
                                tenant=tr.tenant, priority=tr.priority,
                                session_id=tr.session_id)
            except Exception:   # typed Overloaded / DeadlineExceeded
                res.shed += 1
                continue
            meta[gid] = step_i
            res.submitted += 1

    def _harvest(step_i: int, done: List[int]):
        worst = None
        for gid in done:
            req = gw._finished.get(gid)
            if req is None or gid not in meta:
                continue
            res.completions += 1
            ttft = ((req.first_token_t - req.submit_t)
                    if req.first_token_t is not None else None)
            if ttft is not None:
                res.ttfts.append(ttft)
                worst = ttft if worst is None else max(worst, ttft)
                if ttft <= ttft_slo_s:
                    res.in_slo += 1
                else:
                    if res.first_breach_step is None:
                        res.first_breach_step = step_i
                    res.last_breach_step = step_i
            gw.pop_result(gid)
            meta.pop(gid, None)
        # requests that FAILED (deadline, attempt budget) surface on
        # the failed map — count them so goodput sees every casualty
        for gid in [g for g in list(meta) if g in gw._failed]:
            res.failed += 1
            meta.pop(gid, None)
            gw._failed.pop(gid, None)
        res.queue_depth.append(len(gw._queue))
        res.step_completions.append(len(done))
        res.step_worst_ttft.append(worst)

    step_i = 0
    for batch in arrivals:
        _submit(step_i, batch)
        done = gw.step()
        if tick is not None:
            tick(step_i)
        _harvest(step_i, done)
        step_i += 1
    drained = 0
    while gw._has_work() and drained < max_drain_steps:
        done = gw.step()
        if tick is not None:
            tick(step_i)
        _harvest(step_i, done)
        step_i += 1
        drained += 1
    return res
