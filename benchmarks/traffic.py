"""Seed-deterministic serving traffic: the workload half of the
self-healing harness.

A ``TrafficSpec`` describes a workload the way a serving fleet sees
one — diurnal or bursty arrival rates, mixed tenants with mixed
priorities, mixed prompt lengths, a shared-prefix population (K system
prompts a fraction of requests reuse), sticky sessions — and
``generate(spec)`` expands it into per-step arrival lists that are a
pure function of ``spec.seed``. Every chaos comparison in
``bench_selfheal.py`` and ``tests/test_selfheal.py`` replays the SAME
schedule with remediation off vs on, so the only difference between
the two runs is the control loop under test.

``drive(gw, arrivals, ttft_slo_s, tick=...)`` is the matching load
loop: submit each step's arrivals (typed sheds are counted, not
raised), advance the gateway one tick, invoke the caller's hook (where
the remediator/autoscaler tick), and record per-step and per-request
outcomes. The result carries the two numbers the self-heal acceptance
gate cares about:

  * ``goodput_frac`` — completions within the TTFT SLO over ALL
    offered requests (sheds and failures count against goodput);
  * ``first_breach_step`` / ``last_breach_step`` — the SLO incident
    window in steps; ``recovery_steps`` is its length, i.e. how long
    the fleet took to get from the first out-of-SLO completion back to
    (and staying) in-SLO.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TrafficSpec", "TrafficRequest", "TrafficResult",
           "generate", "drive"]


@dataclass(frozen=True)
class TrafficSpec:
    """One deterministic workload description (see module docstring)."""

    seed: int = 0
    steps: int = 120
    vocab: int = 2048
    base_rate: float = 0.4          # expected arrivals per step
    pattern: str = "diurnal"        # diurnal | bursty | steady
    period: int = 80                # diurnal cycle length, steps
    swing: float = 0.5              # diurnal amplitude (frac of base)
    burst_at: Optional[int] = None  # bursty: burst window start step
    burst_len: int = 20
    burst_rate: float = 2.0         # arrivals/step inside the burst
    burst_tenant: str = "burst"
    tenants: Tuple[Tuple[str, float], ...] = (
        ("interactive", 0.7), ("batch", 0.3))
    prompt_lo: int = 8
    prompt_hi: int = 40
    new_lo: int = 4
    new_hi: int = 12
    n_shared: int = 3               # shared-prefix population size
    shared_len: int = 24
    shared_frac: float = 0.5        # frac of requests reusing a prefix
    session_frac: float = 0.3       # frac carrying a sticky session id
    n_sessions: int = 8
    # agentic multi-turn population: a fraction of arrivals are
    # conversations that PAUSE after each turn (tool call, human think
    # time) and come back ``gap`` steps later with a continuation.
    # 0.0 keeps legacy schedules byte-identical — the agentic branch
    # draws from the RNG only when enabled, so every seed published
    # before this field existed still expands to the same schedule.
    agentic_frac: float = 0.0
    agentic_turns_lo: int = 1       # follow-up turns per conversation
    agentic_turns_hi: int = 3
    agentic_gap_lo: int = 2         # pause length between turns, steps
    agentic_gap_hi: int = 6
    agentic_cont_lo: int = 4        # continuation prompt tokens per turn
    agentic_cont_hi: int = 10


@dataclass
class TrafficRequest:
    """One scheduled arrival."""

    at_step: int
    prompt: np.ndarray
    max_new_tokens: int
    tenant: str
    priority: str
    session_id: Optional[str] = None
    # agentic conversations: every follow-up turn is pre-drawn at
    # generate() time (gap, continuation tokens, decode budget) so the
    # whole multi-turn exchange is a pure function of the seed
    turns_left: int = 0
    resume_gaps: Tuple[int, ...] = ()
    cont_tokens: Tuple[np.ndarray, ...] = ()
    turn_new: Tuple[int, ...] = ()


def _rate_at(spec: TrafficSpec, t: int) -> float:
    rate = spec.base_rate
    if spec.pattern == "diurnal":
        rate *= 1.0 + spec.swing * np.sin(2.0 * np.pi * t / spec.period)
    if spec.pattern == "bursty" or spec.burst_at is not None:
        if spec.burst_at is not None and \
                spec.burst_at <= t < spec.burst_at + spec.burst_len:
            rate += spec.burst_rate
    return max(0.0, rate)


def generate(spec: TrafficSpec) -> List[List[TrafficRequest]]:
    """Per-step arrival lists, a pure function of ``spec`` (one seeded
    RNG drives arrivals, tenants, lengths, and prefixes in a fixed
    draw order)."""
    rng = np.random.RandomState(spec.seed)
    shared = [rng.randint(0, spec.vocab, (spec.shared_len,))
              for _ in range(spec.n_shared)]
    agentic_seq = 0
    names = [t for t, _ in spec.tenants]
    weights = np.asarray([w for _, w in spec.tenants], float)
    weights = weights / weights.sum()
    out: List[List[TrafficRequest]] = []
    for t in range(spec.steps):
        n = int(rng.poisson(_rate_at(spec, t)))
        in_burst = (spec.burst_at is not None
                    and spec.burst_at <= t < spec.burst_at
                    + spec.burst_len)
        batch: List[TrafficRequest] = []
        for _ in range(n):
            # burst arrivals beyond the base rate belong to the burst
            # tenant (the noisy neighbor the shed policy should name)
            if in_burst and rng.random_sample() > \
                    spec.base_rate / max(_rate_at(spec, t), 1e-9):
                tenant = spec.burst_tenant
            else:
                tenant = names[int(rng.choice(len(names), p=weights))]
            priority = "low" if tenant == "batch" else "high"
            tail_len = int(rng.randint(spec.prompt_lo,
                                       spec.prompt_hi + 1))
            if rng.random_sample() < spec.shared_frac:
                head = shared[int(rng.randint(spec.n_shared))]
                prompt = np.concatenate(
                    [head, rng.randint(0, spec.vocab, (tail_len,))])
            else:
                prompt = rng.randint(0, spec.vocab, (tail_len,))
            sid = (f"s{int(rng.randint(spec.n_sessions))}"
                   if rng.random_sample() < spec.session_frac else None)
            turns = 0
            gaps: Tuple[int, ...] = ()
            conts: Tuple[np.ndarray, ...] = ()
            turn_new: Tuple[int, ...] = ()
            # every agentic draw lives behind this gate: with
            # agentic_frac == 0 the RNG stream is untouched and legacy
            # schedules replay byte-identically
            if spec.agentic_frac > 0.0 and \
                    rng.random_sample() < spec.agentic_frac:
                turns = int(rng.randint(spec.agentic_turns_lo,
                                        spec.agentic_turns_hi + 1))
                gaps = tuple(int(rng.randint(spec.agentic_gap_lo,
                                             spec.agentic_gap_hi + 1))
                             for _ in range(turns))
                conts = tuple(
                    rng.randint(0, spec.vocab,
                                (int(rng.randint(spec.agentic_cont_lo,
                                                 spec.agentic_cont_hi
                                                 + 1)),))
                    for _ in range(turns))
                turn_new = tuple(int(rng.randint(spec.new_lo,
                                                 spec.new_hi + 1))
                                 for _ in range(turns))
                # agentic conversations own a dedicated session-id
                # space: pause/resume must not collide with the sticky
                # single-turn session population
                sid = f"agent{agentic_seq}"
                agentic_seq += 1
            batch.append(TrafficRequest(
                at_step=t, prompt=prompt,
                max_new_tokens=int(rng.randint(spec.new_lo,
                                               spec.new_hi + 1)),
                tenant=tenant, priority=priority, session_id=sid,
                turns_left=turns, resume_gaps=gaps, cont_tokens=conts,
                turn_new=turn_new))
        out.append(batch)
    return out


@dataclass
class TrafficResult:
    """Outcome of one driven schedule."""

    ttft_slo_s: float
    submitted: int = 0
    shed: int = 0
    completions: int = 0
    in_slo: int = 0
    failed: int = 0
    ttfts: List[float] = field(default_factory=list)
    # per-step series (index = step): queue depth, completions, worst
    # TTFT completed that step (None when none completed)
    queue_depth: List[int] = field(default_factory=list)
    step_completions: List[int] = field(default_factory=list)
    step_worst_ttft: List[Optional[float]] = field(default_factory=list)
    first_breach_step: Optional[int] = None
    last_breach_step: Optional[int] = None
    # agentic multi-turn accounting: every resumed completion is
    # audited — its prompt must extend the session's prior context
    # (prefix integrity), and when the caller supplies ``exact_ref``
    # its tokens must match the uninterrupted reference bitwise
    resumed: int = 0
    resume_exact: int = 0
    resume_mismatch: int = 0

    @property
    def offered(self) -> int:
        return self.submitted + self.shed

    @property
    def goodput_frac(self) -> float:
        return self.in_slo / max(self.offered, 1)

    @property
    def recovery_steps(self) -> int:
        """Steps from the first out-of-SLO completion until the fleet
        was back (and stayed) in-SLO; 0 when no breach ever happened."""
        if self.first_breach_step is None:
            return 0
        return self.last_breach_step - self.first_breach_step + 1

    def summary(self) -> Dict[str, object]:
        return {"offered": self.offered, "submitted": self.submitted,
                "shed": self.shed, "completions": self.completions,
                "failed": self.failed, "in_slo": self.in_slo,
                "goodput_frac": round(self.goodput_frac, 4),
                "ttft_p99_ms": round(_p99(self.ttfts) * 1e3, 3)
                if self.ttfts else None,
                "first_breach_step": self.first_breach_step,
                "last_breach_step": self.last_breach_step,
                "recovery_steps": self.recovery_steps,
                "resumed": self.resumed,
                "resume_exact": self.resume_exact,
                "resume_mismatch": self.resume_mismatch}


def _p99(xs: Sequence[float]) -> float:
    s = sorted(xs)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def drive(gw, arrivals: List[List[TrafficRequest]], ttft_slo_s: float,
          tick: Optional[Callable[[int], None]] = None,
          max_drain_steps: int = 4000,
          exact_ref: Optional[Callable[[np.ndarray, int],
                                       Sequence[int]]] = None
          ) -> TrafficResult:
    """Run ``arrivals`` against ``gw``: one gateway step per schedule
    step (plus drain steps until the queue AND pending agentic
    follow-ups empty), ``tick(step)`` after each — the hook where a
    remediator/autoscaler advances. Typed rejections (quota, queue
    capacity, infeasible deadline) are counted as sheds, not raised.

    Agentic conversations (``TrafficRequest.turns_left > 0``) pause
    after each completed turn — the gateway's ``pause_session``
    session-pins the KV chain and publishes the durable manifest when a
    store is attached — and come back ``resume_gaps[i]`` steps later
    via ``resume_session`` (falling back to a plain ``submit`` of the
    recorded context on gateways without session support). Every
    resumed completion is audited: the resumed prompt must extend the
    session's prior context bitwise, and ``exact_ref(prompt, max_new)``
    (when given — typically a solo reference generate, returning the
    FULL ``prompt ⧺ completion`` sequence) must reproduce the delivered
    sequence exactly."""
    res = TrafficResult(ttft_slo_s=ttft_slo_s)
    # gid -> (submit step, request, turn index; -1 = opening turn)
    meta: Dict[int, Tuple[int, TrafficRequest, int]] = {}
    followups: Dict[int, List[Tuple[TrafficRequest, int]]] = {}
    sess_ctx: Dict[str, np.ndarray] = {}    # sid -> prompt + delivered

    def _submit(step_i: int, batch: List[TrafficRequest]):
        for tr in batch:
            try:
                gid = gw.submit(tr.prompt, tr.max_new_tokens,
                                tenant=tr.tenant, priority=tr.priority,
                                session_id=tr.session_id)
            except Exception:   # typed Overloaded / DeadlineExceeded
                res.shed += 1
                continue
            meta[gid] = (step_i, tr, -1)
            res.submitted += 1

    def _resume(step_i: int, tr: TrafficRequest, turn: int):
        cont = tr.cont_tokens[turn]
        mnt = tr.turn_new[turn]
        sid = tr.session_id
        try:
            if hasattr(gw, "resume_session") and sid in sess_ctx:
                gid = gw.resume_session(
                    sid, new_tokens=cont, max_new_tokens=mnt,
                    tenant=tr.tenant, priority=tr.priority,
                    fallback_tokens=sess_ctx[sid])
            else:
                base = sess_ctx.get(sid)
                prompt = (np.concatenate([base, cont])
                          if base is not None else cont)
                gid = gw.submit(prompt, mnt, tenant=tr.tenant,
                                priority=tr.priority, session_id=sid)
        except Exception:       # shed follow-ups count like any shed
            res.shed += 1
            return
        meta[gid] = (step_i, tr, turn)
        res.submitted += 1
        res.resumed += 1

    def _due(step_i: int):
        for tr, turn in followups.pop(step_i, []):
            _resume(step_i, tr, turn)

    def _harvest(step_i: int, done: List[int]):
        worst = None
        for gid in done:
            req = gw._finished.get(gid)
            if req is None or gid not in meta:
                continue
            _, tr, turn = meta[gid]
            res.completions += 1
            ttft = ((req.first_token_t - req.submit_t)
                    if req.first_token_t is not None else None)
            if ttft is not None:
                res.ttfts.append(ttft)
                worst = ttft if worst is None else max(worst, ttft)
                if ttft <= ttft_slo_s:
                    res.in_slo += 1
                else:
                    if res.first_breach_step is None:
                        res.first_breach_step = step_i
                    res.last_breach_step = step_i
            sid = tr.session_id
            if sid is not None and tr.turns_left > 0:
                prompt = np.asarray(req.prompt, np.int64).reshape(-1)
                delivered = np.asarray(req.delivered, np.int64)
                if turn >= 0:   # a resumed turn: audit it
                    prior = sess_ctx.get(sid)
                    ok = (prior is not None
                          and len(prompt) >= len(prior)
                          and bool(np.array_equal(prompt[:len(prior)],
                                                  prior)))
                    if ok and exact_ref is not None:
                        # exact_ref follows the repo-wide generate
                        # convention: it returns the FULL sequence
                        # (prompt ⧺ completion), so compare full vs full
                        want = np.asarray(
                            exact_ref(prompt, req.max_new_tokens),
                            np.int64)
                        got = np.concatenate([prompt, delivered])
                        ok = bool(np.array_equal(got, want))
                    if ok:
                        res.resume_exact += 1
                    else:
                        res.resume_mismatch += 1
                sess_ctx[sid] = np.concatenate([prompt, delivered])
                if turn + 1 < tr.turns_left:
                    if hasattr(gw, "pause_session"):
                        # pin + publish; a torn publish returns False
                        # and the later resume falls back — that
                        # degradation is exactly what the audit checks
                        gw.pause_session(sid)
                    at = step_i + 1 + tr.resume_gaps[turn + 1]
                    followups.setdefault(at, []).append((tr, turn + 1))
            gw.pop_result(gid)
            meta.pop(gid, None)
        # requests that FAILED (deadline, attempt budget) surface on
        # the failed map — count them so goodput sees every casualty
        for gid in [g for g in list(meta) if g in gw._failed]:
            res.failed += 1
            meta.pop(gid, None)
            gw._failed.pop(gid, None)
        res.queue_depth.append(len(gw._queue))
        res.step_completions.append(len(done))
        res.step_worst_ttft.append(worst)

    step_i = 0
    for batch in arrivals:
        _due(step_i)
        _submit(step_i, batch)
        done = gw.step()
        if tick is not None:
            tick(step_i)
        _harvest(step_i, done)
        step_i += 1
    drained = 0
    while (gw._has_work() or followups) and drained < max_drain_steps:
        _due(step_i)
        done = gw.step()
        if tick is not None:
            tick(step_i)
        _harvest(step_i, done)
        step_i += 1
        drained += 1
    return res
