"""Self-healing fleet benchmark: chaos scenarios with remediation
off vs on under seeded traffic.

Three scenarios, each replayed twice over the IDENTICAL ``traffic.py``
schedule — once with the fleet on its own (the gateway's built-in
death-requeue is always active; nothing else), once with the closed
loop attached (``AnomalyDetector`` + ``GatewayProbe`` feeding an
``AutoRemediator``, an ``Autoscaler`` as its scale executor, and a
tight-window ``SLOMonitor`` for the tenant-burst shed):

  * ``straggler``    — a ``gateway.step.r1`` chaos delay makes one
    replica slow; remediation should NAME and drain it (token-exact
    requeue) so TTFT returns in-SLO.
  * ``kill_replica`` — a ``serving.step`` transient-error burst kills
    one replica mid-stream; remediation should scale a replacement up
    off the queue-depth spike.
  * ``tenant_burst`` — a burst tenant floods arrivals; remediation
    should shed that tenant when the TTFT SLO burns (and un-shed on
    resolution).

Emits the ``BENCH_TRAFFIC_r<NN>.json`` lane artifact gated by
``tools/bench_guard.py`` (``traffic:`` lane): headline value =
remediation-ON goodput_frac in the straggler scenario, with
``detail.recovery_steps_on`` feeding the inverse recovery-rate series.
Same ONE-stdout-line contract as every bench.
"""
import json
import os
import sys
import time

import numpy as np

_BENCH_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_DIR = os.path.dirname(_BENCH_DIR)
sys.path.insert(0, _BENCH_DIR)
sys.path.insert(0, _REPO_DIR)
import traffic  # noqa: E402  (sibling script, not a package)

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM  # noqa: E402

TTFT_SLO_S = 0.08
STRAGGLE_S = 0.25


def _model():
    cfg = GPT2Config(vocab_size=2048, hidden_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     max_position_embeddings=256, dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m, cfg


def _factory(model):
    from paddle_tpu.inference.serving import ContinuousBatcher

    def make(name):
        return ContinuousBatcher(model, max_batch=4, s_max=128,
                                 compile=False)
    return make


def _build_gateway(make):
    from paddle_tpu.inference.gateway import Gateway
    gw = Gateway(policy="least_loaded", max_queue_depth=128)
    gw.add_replica("r0", make("r0"))
    gw.add_replica("r1", make("r1"))
    return gw


def _warmup(gw, vocab):
    """Build the anomaly baselines (and the engines' compiled prefill
    rungs) BEFORE any chaos arms: both replicas step with work for
    >= min_samples ticks, across EVERY pow2 prompt rung the traffic
    will hit — a first-touch prefill compile mid-run would register as
    a huge step and fire a false per-replica spike."""
    rng = np.random.RandomState(99)
    for _ in range(3):
        for n in (6, 12, 20, 28, 40, 48):
            gw.submit(rng.randint(0, vocab, (n,)), 4, tenant="warmup")
    gw.run_until_done()
    gw.reset_stats()


# per-scenario policy tables: each drill arms the rule(s) a deployment
# would pair with that failure class. The tenant-burst table carries NO
# drain rule — burst load legitimately slows every replica's steps, and
# draining half the capacity on that spike is the misfire the policy
# table exists to prevent.
_POLICIES = {
    "straggler": (("tpot_spike", "drain_replica", 2, 10.0),),
    "kill_replica": (("queue_depth_spike", "scale_up", 3, 5.0),),
    "tenant_burst": (("slo_breach:traffic_ttft", "shed_tenant", 1, 15.0),
                     ("queue_depth_spike", "scale_up", 3, 10.0)),
}


def _attach(gw, make, scenario):
    """The closed loop: probe -> detector -> remediator (+ autoscaler
    + tight-window SLO monitor for the shed path)."""
    from paddle_tpu.inference.gateway.autoscaler import Autoscaler
    from paddle_tpu.observability.anomaly import (AnomalyDetector,
                                                  GatewayProbe)
    from paddle_tpu.observability.slo import SLO, BurnWindow, SLOMonitor
    from paddle_tpu.resilience.remediator import (AutoRemediator,
                                                  FlapGuard, PolicyRule)
    # above the ~2-4x robust-z that honest prefill-heavy steps reach
    detector = AnomalyDetector(threshold=10.0, min_samples=8)
    probe = GatewayProbe(gw, detector)
    monitor = SLOMonitor(
        [SLO("traffic_ttft", "gateway.ttft_seconds", TTFT_SLO_S,
             objective=0.9)],
        windows=[BurnWindow(fast_s=0.5, slow_s=1.5,
                            burn_threshold=3.0)])
    guard = FlapGuard(max_actions=4, window_s=30.0, freeze_s=60.0)
    asc = Autoscaler(gw, make, min_replicas=1, max_replicas=4,
                     queue_high=10, hysteresis=4, cooldown_s=5.0,
                     flap_guard=guard)
    policy = tuple(PolicyRule(sig, act, hysteresis=h, cooldown_s=c)
                   for sig, act, h, c in _POLICIES[scenario])
    rem = AutoRemediator(gw, monitor=monitor, detector=detector,
                         policy=policy, replica_factory=make,
                         autoscaler=asc, flap_guard=guard)
    return rem, probe


def _scenario(name, make, vocab, spec, chaos=None, remediate=False):
    from paddle_tpu.resilience.chaos import arm_scenario, disarm
    disarm()
    gw = _build_gateway(make)
    rem = probe = None
    if remediate:
        # the probe attaches BEFORE warmup so the anomaly detector's
        # per-replica baselines are built from HEALTHY steps — chaos
        # arms only after
        rem, probe = _attach(gw, make, name)
    _warmup(gw, vocab)
    if chaos:
        arm_scenario(chaos)
    tick = (lambda step: rem.tick()) if rem is not None else None
    try:
        res = traffic.drive(gw, traffic.generate(spec), TTFT_SLO_S,
                            tick=tick)
    finally:
        disarm()
        if probe is not None:
            probe.close()
    out = res.summary()
    if rem is not None:
        out["remediator"] = rem.summary()
        out["actions"] = [a.to_dict() for a in rem.executed()]
    return out


def main():
    paddle.seed(0)
    model, cfg = _model()
    make = _factory(model)
    vocab = cfg.vocab_size
    t0 = time.perf_counter()

    base = dict(seed=3, steps=70, vocab=vocab, base_rate=0.5,
                prompt_lo=6, prompt_hi=24, new_lo=3, new_hi=8)
    scenarios = {
        "straggler": dict(
            spec=traffic.TrafficSpec(**base),
            chaos=(f"seed=0; gateway.step.r1:delay:"
                   f"delay_s={STRAGGLE_S},after=2,count=1000")),
        "kill_replica": dict(
            # load-bound on purpose: one survivor cannot keep up, so
            # the scale-up's extra capacity (not noise) decides the run
            spec=traffic.TrafficSpec(**dict(base, base_rate=1.2)),
            chaos="seed=0; serving.step:transient_error:after=20,count=3"),
        "tenant_burst": dict(
            spec=traffic.TrafficSpec(**dict(
                base, pattern="steady", burst_at=15, burst_len=25,
                burst_rate=2.5)),
            chaos=None),
    }

    detail = {"ttft_slo_ms": TTFT_SLO_S * 1e3, "tpu": False,
              "scenarios": {}}
    with paddle.no_grad():
        for name, kw in scenarios.items():
            off = _scenario(name, make, vocab, kw["spec"],
                            chaos=kw["chaos"], remediate=False)
            on = _scenario(name, make, vocab, kw["spec"],
                           chaos=kw["chaos"], remediate=True)
            detail["scenarios"][name] = {"off": off, "on": on}

    st = detail["scenarios"]["straggler"]
    detail["goodput_frac_on"] = st["on"]["goodput_frac"]
    detail["goodput_frac_off"] = st["off"]["goodput_frac"]
    detail["recovery_steps_on"] = st["on"]["recovery_steps"]
    detail["recovery_steps_off"] = st["off"]["recovery_steps"]
    detail["actions_on"] = sum(
        len(s["on"].get("actions", ()))
        for s in detail["scenarios"].values())
    # a token-accounting divergence through drain/requeue raises inside
    # drive(); reaching this line IS the token-exactness proof
    detail["token_exact"] = True
    detail["elapsed_s"] = round(time.perf_counter() - t0, 2)

    line = {
        "metric": "traffic_selfheal_goodput_frac",
        "value": detail["goodput_frac_on"],
        "unit": "frac",
        "vs_baseline": 0.0,
        "detail": detail,
    }
    try:
        with open(_traffic_round_path(), "w") as f:
            json.dump(line, f, indent=1)
            f.write("\n")
    except OSError:
        pass  # artifact write must never sink the bench number
    print(json.dumps(line))


def _traffic_round_path():
    """Next BENCH_TRAFFIC_r<NN>.json slot (the traffic lane)."""
    import glob
    import re
    rounds = []
    for p in glob.glob(os.path.join(_REPO_DIR, "BENCH_TRAFFIC_r*.json")):
        m = re.search(r"BENCH_TRAFFIC_r(\d+)\.json$",
                      os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)))
    n = (max(rounds) + 1) if rounds else 0
    return os.path.join(_REPO_DIR, f"BENCH_TRAFFIC_r{n:02d}.json")


if __name__ == "__main__":
    main()
