"""Serving-path quantization benchmark: two arms, one bench line.

**Tier-capacity arm** (the ``bench_prefix_churn`` workload, quantized):
the same Zipf churn stream runs tiered twice at the SAME host byte
budget — fp blobs vs ``tier_quant='int8'`` blobs. The quantized arm's
spilled chains cost ~1/4 the bytes (int8 codes + per-head scales vs
fp32), so the budget holds ~4x the chains; the arm reports the measured
capacity ratio (raw spill bytes over as-stored spill bytes), both hit
rates, and generated-token agreement with the fp arm.

**int8-weights arm**: the same decode workload driven twice through the
paged batcher — fp weights vs ``serving_quantize``'d int8 weights (the
model is briefly trained first so logits are sharp; random-init argmax
near-ties flip under any perturbation and would measure the MODEL, not
the quantizer). Reports decode tokens/s, TPOT p50, and the greedy
token-match rate vs fp.

Headline number = the int8-weights arm's decode tokens/s. Detail carries
``token_match_rate`` (the ``quant:`` bench_guard lane gates it as a
second series — a quality regression fails as loudly as a speed one)
and ``tier_capacity_ratio``.

Bench line lands in ``BENCH_QUANT_r<NN>.json`` at the repo root. Same
JSON contract as bench.py: ONE stdout line
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": ...}
vs_baseline stays 0.0 — the reference publishes no comparable figure.
"""
import json
import os
import sys
import time

import numpy as np

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_DIR)

import paddle_tpu as paddle                                    # noqa: E402
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM  # noqa: E402

BLOCK_SIZE = 16
PREFIX_BLOCKS = 3
N_PREFIXES = 16
N_PAGES = 22
MAX_BATCH = 2
S_MAX = 96
TAIL_TOKENS = 5
NEW_TOKENS = 4
N_REQUESTS = 48
ZIPF_A = 0.5
HOST_GIB = 0.25

TRAIN_STEPS = 40           # sharpen logits so greedy argmax is stable
DECODE_PROMPTS = 12
DECODE_NEW = 16


def _model(train: bool = False):
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     max_position_embeddings=128, dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    if train:
        import paddle_tpu.nn.functional as F
        from paddle_tpu import optimizer
        rng = np.random.RandomState(0)
        data = paddle.to_tensor(rng.randint(0, 128, (4, 33)))
        opt = optimizer.AdamW(learning_rate=1e-2,
                              parameters=m.parameters())
        for _ in range(TRAIN_STEPS):
            logits = m(data[:, :-1])
            loss = F.cross_entropy(
                logits.reshape([-1, cfg.vocab_size]),
                data[:, 1:].reshape([-1]))
            loss.backward()
            opt.step()
            opt.clear_grad()
    m.eval()
    return m, cfg


def _churn_workload(vocab):
    rng = np.random.RandomState(0)
    prefixes = [rng.randint(0, vocab, (BLOCK_SIZE * PREFIX_BLOCKS,))
                for _ in range(N_PREFIXES)]
    w = 1.0 / np.arange(1, N_PREFIXES + 1) ** ZIPF_A
    w /= w.sum()
    picks = rng.choice(N_PREFIXES, size=N_REQUESTS, p=w)
    prompts = [np.concatenate([prefixes[p],
                               rng.randint(0, vocab, (TAIL_TOKENS,))])
               for p in picks]
    return prefixes, prompts


def _spill_counters():
    from paddle_tpu.observability import get_registry
    out = {"raw": 0, "blob": 0}
    for s in get_registry().snapshot():
        if s.get("name") == "serving.prefix_spill_raw_bytes":
            out["raw"] = s.get("value", 0)
        elif s.get("name") == "serving.prefix_spill_blob_bytes":
            out["blob"] = s.get("value", 0)
    return out


def _tier_arm(model, prefixes, prompts, tier_quant):
    """One tiered churn run; returns hit rate, outputs, spill byte
    deltas, and the zero-leak audit evidence."""
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    before = _spill_counters()
    bt = PagedContinuousBatcher(
        model, max_batch=MAX_BATCH, s_max=S_MAX, block_size=BLOCK_SIZE,
        n_pages=N_PAGES, compile=False, policy="ondemand",
        prefix_cache=True, host_kv_gib=HOST_GIB, tier_quant=tier_quant)
    try:
        for pre in prefixes:
            bt.submit(pre, NEW_TOKENS)
        bt.run_until_done(max_steps=60000)
        base = bt.prefix_cache.stats()
        rids = [bt.submit(p, NEW_TOKENS) for p in prompts]
        res = bt.run_until_done(max_steps=60000)
        outs = [res[r] for r in rids]
        st = bt.prefix_cache.stats()
        bt.audit_pages()                  # raises on any leak
        rep = bt.prefix_cache.audit_tiers()
        after = _spill_counters()
        hit = st["hit_tokens"] - base["hit_tokens"]
        miss = st["miss_tokens"] - base["miss_tokens"]
        return {
            "hit_rate": round(hit / max(hit + miss, 1), 4),
            "outs": outs,
            "host_bytes": int(rep.get("host_bytes", 0)),
            "spill_raw": int(after["raw"] - before["raw"]),
            "spill_blob": int(after["blob"] - before["blob"]),
            "promotions": int(st["promotions"]),
            "promotion_failures": int(st["promotion_failures"]),
        }
    finally:
        bt.close()


def _weights_arm(model, cfg, quantize):
    """One decode run; returns tokens/s, TPOT p50, and the outputs."""
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    if quantize:
        from paddle_tpu.quantization import serving_quantize
        model = serving_quantize(model)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (20,))
               for _ in range(DECODE_PROMPTS)]
    bt = PagedContinuousBatcher(model, max_batch=MAX_BATCH, s_max=64,
                                block_size=BLOCK_SIZE, compile=True)
    try:
        # warmup: pay the jit traces before the timed window so the
        # arms compare steady-state decode, not compile time
        bt.submit(prompts[0], 2)
        bt.run_until_done(max_steps=9000)
        # best-of-2 repetitions: sub-2ms CPU-proxy steps carry enough
        # scheduler jitter to swamp a few-percent effect; min() is the
        # standard denoiser (outs are deterministic, identical each rep)
        best_rate, best_p50, outs = 0.0, float("inf"), None
        for _ in range(2):
            rids = [bt.submit(p, DECODE_NEW) for p in prompts]
            step_times = []
            t0 = time.perf_counter()
            results = {}
            steps = 0
            while bt._has_work():
                s0 = time.perf_counter()
                for rid in bt.step():
                    results[rid] = bt.pop_result(rid)
                step_times.append(time.perf_counter() - s0)
                steps += 1
                if steps > 60000:
                    raise RuntimeError("decode arm did not drain")
            wall = time.perf_counter() - t0
            outs = [results[r] for r in rids]
            times = np.sort(np.asarray(step_times))
            new_tokens = DECODE_PROMPTS * DECODE_NEW
            best_rate = max(best_rate, new_tokens / max(wall, 1e-9))
            best_p50 = min(best_p50, float(times[len(times) // 2]))
        report = (getattr(model, "_serving_quant_report", None)
                  if quantize else None)
        return {
            "tokens_per_s": round(best_rate, 2),
            "tpot_p50_ms": round(best_p50 * 1e3, 3),
            "outs": outs,
            "quant_report": (
                {"layers_quantized": report["layers_quantized"],
                 "layers_fallback": report["layers_fallback"],
                 "bytes_saved": report["bytes_saved"]}
                if report else None),
        }
    finally:
        bt.close()


def _round_path():
    import glob
    import re
    rounds = []
    for p in glob.glob(os.path.join(_REPO_DIR, "BENCH_QUANT_r*.json")):
        m = re.search(r"BENCH_QUANT_r(\d+)\.json$", os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)))
    n = (max(rounds) + 1) if rounds else 0
    return os.path.join(_REPO_DIR, f"BENCH_QUANT_r{n:02d}.json")


def main():
    on_tpu = False
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        pass

    # -- tier-capacity arm (random-init model is fine: both runs share
    #    it, and the comparison is fp-blob vs int8-blob storage) -------
    model, cfg = _model(train=False)
    prefixes, prompts = _churn_workload(cfg.vocab_size)
    with paddle.no_grad():
        fp_tier = _tier_arm(model, prefixes, prompts, tier_quant=None)
        q_tier = _tier_arm(model, prefixes, prompts, tier_quant="int8")
    pfx = BLOCK_SIZE * PREFIX_BLOCKS
    tier_match = float(np.mean(
        [np.mean(a[pfx:] == b[pfx:])
         for a, b in zip(fp_tier["outs"], q_tier["outs"])]))
    capacity_ratio = round(
        q_tier["spill_raw"] / max(q_tier["spill_blob"], 1), 2)

    # -- int8-weights arm (sharpened model: measure the quantizer, not
    #    random-logit argmax ties) -------------------------------------
    tmodel, tcfg = _model(train=True)
    with paddle.no_grad():
        fp_dec = _weights_arm(tmodel, tcfg, quantize=False)
        q_dec = _weights_arm(tmodel, tcfg, quantize=True)
    token_match = float(np.mean(
        [np.mean(a[20:] == b[20:])
         for a, b in zip(fp_dec["outs"], q_dec["outs"])]))

    detail = {
        "tpu": on_tpu,
        # tier arm
        "tier_capacity_ratio": capacity_ratio,
        "tier_hit_rate_fp": fp_tier["hit_rate"],
        "tier_hit_rate_int8": q_tier["hit_rate"],
        "tier_host_bytes_fp": fp_tier["host_bytes"],
        "tier_host_bytes_int8": q_tier["host_bytes"],
        "tier_spill_raw_bytes": q_tier["spill_raw"],
        "tier_spill_blob_bytes": q_tier["spill_blob"],
        "tier_token_match_rate": round(tier_match, 4),
        "tier_promotions": q_tier["promotions"],
        "tier_promotion_failures": q_tier["promotion_failures"],
        # weights arm
        "tokens_per_s_fp": fp_dec["tokens_per_s"],
        "tokens_per_s_int8": q_dec["tokens_per_s"],
        "tpot_p50_ms_fp": fp_dec["tpot_p50_ms"],
        "tpot_p50_ms_int8": q_dec["tpot_p50_ms"],
        # CPU-proxy honesty: the int8 arm re-converts every weight each
        # step (XLA:CPU has no int8 matmul), a ~1/batch-fraction FLOP
        # tax with no bandwidth to win back at this scale — the HBM win
        # this arm exists for is a TPU effect; re-measure on relay heal
        "tpot_penalty_frac": round(
            q_dec["tpot_p50_ms"] / max(fp_dec["tpot_p50_ms"], 1e-9) - 1,
            4),
        "token_match_rate": round(token_match, 4),
        "quant_report": q_dec["quant_report"],
        "audit_clean": True,       # the tier arms raised otherwise
    }
    line = {
        "metric": "quant_serving_decode_tokens_per_sec",
        "value": q_dec["tokens_per_s"],
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": detail,
    }
    try:
        with open(_round_path(), "w") as f:
            json.dump(line, f, indent=1)
            f.write("\n")
    except OSError:
        pass  # artifact write must never sink the bench number
    print(json.dumps(line))


if __name__ == "__main__":
    main()
