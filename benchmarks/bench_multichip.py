"""Multi-chip SPMD mesh benchmark: the scaled-down PLAN_7B trained
through the runtime mesh layer (``distributed.mesh.MeshRuntime``).

Headline number = sharded train tokens/sec of the fused donating
TrainStep compiled with the 2x2 ``(fsdp, tensor)`` mesh plan (ZeRO-3
storage sharding, gather-at-use) on the CPU proxy's forced device grid
— on TPU the same code spans real chips. detail carries what the lane
actually gates:

  * ``memory``: the runtime/static live-bytes cross-check — XLA's
    measured per-chip resident state vs ``analysis/memory.py``'s
    prediction (``state_ratio`` must sit within 10%) plus the
    liveness-walk peak soundness bound;
  * ``comm_bytes_by_axis``: the analytic per-step collective volume the
    roofline attribution splits the MFU gap with;
  * the single-device reference rate for context (NOT a gate — 4
    virtual CPU devices share the same cores, so the proxy's sharded
    rate measures overhead, not speedup).

Same JSON contract as bench.py: ONE stdout line
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": ...}
vs_baseline stays 0.0 — the reference publishes no multi-chip figure.

The bench line also lands in ``MULTICHIP_r<NN>.json`` at the repo root:
the multichip lane of ``tools/bench_guard.py``'s trajectory gate,
disjoint from the train (``BENCH_r*``) and gateway
(``BENCH_GATEWAY_r*``) lanes by filename prefix. (Rounds r01-r05 of
this prefix predate the lane and hold raw dry-run wrappers; the guard
skips them as unparsable history rather than gating on them.)
"""
import json
import os
import time

import numpy as np

import paddle_tpu as paddle

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MESH_AXES = {"data": 1, "fsdp": 2, "tensor": 2}
WARMUP_STEPS = 2
TIMED_STEPS = 8


def _make_model(on_tpu):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2752, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=16,
                          max_position_embeddings=2048)
        batch, seq = 8, 512
    else:
        # the scaled-down PLAN_7B the analysis tests price (same shape
        # family, every dim divisible by the 2x2 mesh)
        cfg = LlamaConfig(vocab_size=2000, hidden_size=256,
                          intermediate_size=688, num_hidden_layers=4,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=512)
        batch, seq = 2, 64
    return LlamaForCausalLM(cfg), cfg, batch, seq


def _build_step(model, plan):
    import paddle_tpu.optimizer as optim
    from paddle_tpu import jit as jit_mod

    opt = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def fn(ids, labels):
        out = model(ids)
        logits = out[0] if isinstance(out, (tuple, list)) else out
        return paddle.nn.functional.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))

    return jit_mod.TrainStep(fn, opt, mesh_plan=plan)


def _rate(step, ids, labels, batch, seq):
    for _ in range(WARMUP_STEPS):
        step(ids, labels)
    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        loss = step(ids, labels)
    float(np.asarray(loss._data))           # block on the last step
    dt = time.perf_counter() - t0
    return batch * seq * TIMED_STEPS / dt


def _round_path():
    """Next MULTICHIP_r<NN>.json slot (continues the existing lane)."""
    import glob
    import re
    rounds = [0]
    for p in glob.glob(os.path.join(_REPO_DIR, "MULTICHIP_r*.json")):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)) + 1)
    return os.path.join(_REPO_DIR, f"MULTICHIP_r{max(rounds):02d}.json")


def main():
    import jax
    from paddle_tpu.distributed.mesh import MeshRuntime

    on_tpu = jax.devices()[0].platform == "tpu"
    model, cfg, batch, seq = _make_model(on_tpu)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       size=(batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                          size=(batch, seq)))

    rt = MeshRuntime(MESH_AXES)
    plan = rt.train_plan(budget_gib=16.0)
    step = _build_step(model, plan)
    sharded_rate = _rate(step, ids, labels, batch, seq)
    memory = step.mesh_memory_report(ids, labels)

    ref_model, _, _, _ = _make_model(on_tpu)
    ref_rate = _rate(_build_step(ref_model, None), ids, labels, batch, seq)

    detail = {
        "tpu": on_tpu,
        "mesh": dict(rt.axes),
        "n_devices": rt.size,
        "params": ref_model.num_params(),
        "batch": batch,
        "seq": seq,
        "timed_steps": TIMED_STEPS,
        "single_device_tokens_per_s": round(ref_rate, 2),
        "memory": {k: (round(v, 4) if isinstance(v, float) else v)
                   for k, v in memory.items()},
        "comm_bytes_by_axis": {k: round(v, 1) for k, v in
                               plan.collective_bytes_by_axis().items()},
    }
    line = {
        "metric": "multichip_sharded_train_tokens_per_sec",
        "value": round(sharded_rate, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": detail,
    }
    try:
        with open(_round_path(), "w") as f:
            json.dump(line, f, indent=1)
            f.write("\n")
    except OSError:
        pass  # artifact write must never sink the bench number
    print(json.dumps(line))


if __name__ == "__main__":
    main()
