"""Tiered radix KV cache benchmark: durable prefix hit rates under churn.

The workload is built to NOT fit on the device: a working set of K
shared prefixes whose pages total ~4x the device page pool, re-referenced
with a Zipf popularity skew (seeded — the stream replays exactly). A
device-only radix cache churns: every admission evicts someone else's
chain, so re-references mostly re-prefill. With the host tier armed
(``host_kv_gib``), eviction DEMOTES chains to pinned host DRAM instead of
dropping them, and a re-reference promotes them back with an async
``device_put`` overlapped with decode — the hit rate becomes durable.

Headline number = the tiered run's measured-window hit rate
(hit_tokens / (hit+miss)); detail carries the device-only control run on
the SAME stream, promotion-latency p50/p99 from the serving histogram,
demotion/promotion traffic, the decode-overlap evidence (steps that ran
with a promotion in flight / per-step p99 wall time for both runs — a
promotion stall would show as a tiered-only spike), token-exactness of
tiered vs device-only outputs, and the zero-leak audits.

Bench line lands in ``BENCH_PREFIX_r<NN>.json`` at the repo root — the
``prefix:`` lane of ``tools/bench_guard.py`` (the tiered hit rate gates
directly; promotion p99 gates as an inverse rate series).

Same JSON contract as bench.py: ONE stdout line
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": ...}
vs_baseline stays 0.0 — the reference publishes no comparable figure.
"""
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BLOCK_SIZE = 16
PREFIX_BLOCKS = 3                  # 48-token shared prefixes
N_PREFIXES = 32                    # working set: 96 prefix pages ...
N_PAGES = 22                       # ... over a 22-page device pool (~4x)
MAX_BATCH = 2
S_MAX = 96
TAIL_TOKENS = 5                    # unique per-request suffix
NEW_TOKENS = 4
N_REQUESTS = 96                    # measured Zipf draws
ZIPF_A = 0.5


def _model():
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     max_position_embeddings=128, dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m, cfg


def _workload(vocab):
    """(prefixes, measured request prompts) — one seeded stream shared
    by both runs so the comparison is request-for-request."""
    rng = np.random.RandomState(0)
    prefixes = [rng.randint(0, vocab, (BLOCK_SIZE * PREFIX_BLOCKS,))
                for _ in range(N_PREFIXES)]
    # Zipf over prefix ranks: p_i ~ 1/i^a, truncated to the working set
    w = 1.0 / np.arange(1, N_PREFIXES + 1) ** ZIPF_A
    w /= w.sum()
    picks = rng.choice(N_PREFIXES, size=N_REQUESTS, p=w)
    prompts = [np.concatenate([prefixes[p],
                               rng.randint(0, vocab, (TAIL_TOKENS,))])
               for p in picks]
    return prefixes, prompts


def _run_stream(model, prefixes, prompts, host_kv_gib):
    """Warm the cache with one pass over the working set, then drive the
    measured Zipf stream through a manual step loop (per-step wall
    times + promotion-overlap accounting). Returns the measured-window
    hit rate, outputs, and the run's cache/step evidence."""
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    bt = PagedContinuousBatcher(
        model, max_batch=MAX_BATCH, s_max=S_MAX, block_size=BLOCK_SIZE,
        n_pages=N_PAGES, compile=False, policy="ondemand",
        prefix_cache=True, host_kv_gib=host_kv_gib)
    try:
        for pre in prefixes:                       # cold first touches
            bt.submit(pre, NEW_TOKENS)
        bt.run_until_done(max_steps=60000)
        base = bt.prefix_cache.stats()

        rids = [bt.submit(p, NEW_TOKENS) for p in prompts]
        step_times, steps, overlap_steps = [], 0, 0
        while bt._has_work():
            promo_pending = getattr(bt, "_promo", None) is not None
            decoding = bool(bt._slot_req)
            t0 = time.perf_counter()
            bt.step()
            step_times.append(time.perf_counter() - t0)
            steps += 1
            if promo_pending and decoding:
                overlap_steps += 1
            if steps > 60000:
                raise RuntimeError("churn stream did not drain")
        outs = [bt.pop_result(r) for r in rids]

        st = bt.prefix_cache.stats()
        hit = st["hit_tokens"] - base["hit_tokens"]
        miss = st["miss_tokens"] - base["miss_tokens"]
        free_after = bt.audit_pages()              # raises on any leak
        times = np.sort(np.asarray(step_times))
        return {
            "hit_rate": round(hit / max(hit + miss, 1), 4),
            "hit_tokens": int(hit), "miss_tokens": int(miss),
            "cache": {k: int(v) for k, v in st.items()},
            "outs": outs,
            "steps": steps, "overlap_steps": overlap_steps,
            "step_p50_ms": round(
                float(times[len(times) // 2]) * 1e3, 3),
            "step_p99_ms": round(
                float(times[min(len(times) - 1,
                                int(len(times) * 0.99))]) * 1e3, 3),
            "free_pages_after": int(free_after),
        }
    finally:
        bt.close()


def _prefix_round_path():
    import glob
    import re
    rounds = []
    for p in glob.glob(os.path.join(_REPO_DIR, "BENCH_PREFIX_r*.json")):
        m = re.search(r"BENCH_PREFIX_r(\d+)\.json$", os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)))
    n = (max(rounds) + 1) if rounds else 0
    return os.path.join(_REPO_DIR, f"BENCH_PREFIX_r{n:02d}.json")


def main():
    on_tpu = False
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        pass
    model, cfg = _model()
    prefixes, prompts = _workload(cfg.vocab_size)

    with paddle.no_grad():
        dev = _run_stream(model, prefixes, prompts, host_kv_gib=None)
        tiered = _run_stream(model, prefixes, prompts, host_kv_gib=0.25)

    token_exact = (len(dev["outs"]) == len(tiered["outs"]) and all(
        np.array_equal(a, b)
        for a, b in zip(dev["outs"], tiered["outs"])))

    from paddle_tpu.observability import get_registry
    h = get_registry().histogram("serving.prefix_promotion_seconds")
    promo_ms = {}
    for q, tag in ((0.5, "p50"), (0.99, "p99")):
        v = h.quantile(q)
        promo_ms[tag] = None if v is None else round(v * 1e3, 3)

    detail = {
        "tpu": on_tpu,
        "device_pool_pages": N_PAGES,
        "working_set_pages": N_PREFIXES * PREFIX_BLOCKS,
        "prefixes": N_PREFIXES, "requests": N_REQUESTS,
        "zipf_a": ZIPF_A,
        "device_only_hit_rate": dev["hit_rate"],
        "tiered_hit_rate": tiered["hit_rate"],
        "token_exact": bool(token_exact),
        "promotion_latency_p50_ms": promo_ms["p50"],
        "promotion_latency_p99_ms": promo_ms["p99"],
        "promotions": tiered["cache"]["promotions"],
        "promotion_failures": tiered["cache"]["promotion_failures"],
        "demotions": tiered["cache"]["demotions"],
        "demoted_bytes": tiered["cache"]["demoted_bytes"],
        "overlap_steps": tiered["overlap_steps"],
        "tiered_steps": tiered["steps"],
        "device_only_steps": dev["steps"],
        "step_p99_ms_device_only": dev["step_p99_ms"],
        "step_p99_ms_tiered": tiered["step_p99_ms"],
        "audit_clean": True,       # _run_stream raised otherwise
    }
    line = {
        "metric": "prefix_churn_hit_rate",
        "value": tiered["hit_rate"],
        "unit": "frac",
        "vs_baseline": 0.0,
        "detail": detail,
    }
    try:
        with open(_prefix_round_path(), "w") as f:
            json.dump(line, f, indent=1)
            f.write("\n")
    except OSError:
        pass  # artifact write must never sink the bench number
    print(json.dumps(line))


if __name__ == "__main__":
    main()
