"""Durable-session resume benchmark: pipelined tiered promotion vs
serial, plus the crash-resume (manifest-only) leg.

The scenario is one long agentic conversation that PAUSES mid-task: its
first turn is served, ``pause_session`` session-pins the KV chain and
publishes the crash-safe manifest, then churn traffic demotes the
pinned chain off the device (host tier, spilling to disk — the pin
keeps it no lower than the last tier). The measured number is the
RESUME: resubmitting the session's context streams the demoted chain
back through the multi-slot promotion pipeline instead of
re-prefilling.

Three legs, one seeded workload:

  * pipelined — ``promo_slots`` chunks of ``promo_chunk_blocks`` blocks
    in flight at once (blob reads overlap device transfers);
  * serial    — ``promo_slots=1, promo_chunk_blocks=None``, the legacy
    single-submission promotion, same stream;
  * crash     — a FRESH batcher sharing only the manifest store (the
    replica died): resume resolves the manifest and full-prefills,
    token-exact.

Every resumed completion is compared bitwise against an uninterrupted
two-turn baseline. Headline = resume goodput (session context tokens
per second of resume wall time, pipelined); detail carries
``time_to_resume_ms`` (the inverse-gated ``session:`` bench_guard
series), both variants' times (min over ``REPS`` — latency, so min is
the stable estimator), and the zero-leak audits.

Bench line lands in ``BENCH_SESSION_r<NN>.json`` at the repo root — the
``session:`` lane of ``tools/bench_guard.py``. Same JSON contract as
bench.py: ONE stdout line; vs_baseline stays 0.0 (the reference
publishes no comparable figure).
"""
import json
import os
import sys
import tempfile
import time

import numpy as np

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_DIR)

import paddle_tpu as paddle                                  # noqa: E402
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM  # noqa: E402

BLOCK_SIZE = 16
SESSION_BLOCKS = 13                # 208-token first-turn prompt
CONT_TOKENS = 7                    # the follow-up turn's new input
NEW_TOKENS = 6
N_PAGES = 34
MAX_BATCH = 2
S_MAX = 240
CHURN_PROMPTS = 10                 # enough to cycle the pool repeatedly
CHURN_BLOCKS = 5
HOST_KV_GIB = 0.0008               # ~4 blocks of host tier ...
DISK_KV_GIB = 0.05                 # ... so the chain spills to disk
PIPE_SLOTS = 3                     # pipelined leg geometry
PIPE_CHUNK = 5
REPS = 4
SID = "agent-bench"


def _model():
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=768,
                     num_hidden_layers=2, num_attention_heads=4,
                     max_position_embeddings=256, dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m, cfg


def _workload(vocab):
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, vocab, (BLOCK_SIZE * SESSION_BLOCKS,))
    cont = rng.randint(0, vocab, (CONT_TOKENS,))
    churn = [rng.randint(0, vocab, (BLOCK_SIZE * CHURN_BLOCKS + 3,))
             for _ in range(CHURN_PROMPTS)]
    return prompt, cont, churn


def _batcher(model, store_dir, promo_slots, promo_chunk_blocks):
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    return PagedContinuousBatcher(
        model, max_batch=MAX_BATCH, s_max=S_MAX, block_size=BLOCK_SIZE,
        n_pages=N_PAGES, compile=False, policy="ondemand",
        prefix_cache=True, host_kv_gib=HOST_KV_GIB,
        disk_kv_dir=os.path.join(store_dir, "kv_disk"),
        disk_kv_gib=DISK_KV_GIB, promo_slots=promo_slots,
        promo_chunk_blocks=promo_chunk_blocks,
        session_store=os.path.join(store_dir, "sessions"))


def _baseline(model, prompt, cont):
    """The uninterrupted two-turn reference: same conversation, no
    pause/churn/resume — the bitwise ground truth."""
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    bt = PagedContinuousBatcher(
        model, max_batch=MAX_BATCH, s_max=S_MAX, block_size=BLOCK_SIZE,
        n_pages=N_PAGES, compile=False, policy="ondemand",
        prefix_cache=True)
    try:
        r1 = bt.submit(prompt, NEW_TOKENS)
        # results are the FULL sequence (prompt + generated) — out1
        # is already the session context after turn one
        out1 = bt.run_until_done(max_steps=60000)[r1]
        r2 = bt.submit(np.concatenate([out1, cont]), NEW_TOKENS)
        out2 = bt.run_until_done(max_steps=60000)[r2]
        return out1, out2
    finally:
        bt.close()


def _pause_churn_resume(model, store_dir, prompt, cont, churn,
                        promo_slots, promo_chunk_blocks):
    """One full leg: first turn -> pause (pin + publish) -> churn (the
    pinned chain demotes to host/disk) -> timed resume through the
    promotion stream. Returns outputs + the resume wall time."""
    bt = _batcher(model, store_dir, promo_slots, promo_chunk_blocks)
    try:
        r1 = bt.submit(prompt, NEW_TOKENS)
        out1 = bt.run_until_done(max_steps=60000)[r1]
        published = bt.pause_session(SID, out1)

        for p in churn:
            bt.submit(p, NEW_TOKENS)
        bt.run_until_done(max_steps=60000)
        pinned = bt._session_pins.get(SID, [])
        demoted = sum(1 for n in pinned if n.residency != "device")

        toks = bt.resume_session(SID)
        assert toks is not None, "manifest did not resolve"
        # the promotion-stream wall time (submission -> last chunk
        # installed) comes from the serving histogram: it isolates the
        # piece the pipeline changes from prefill/decode noise
        from paddle_tpu.observability import get_registry
        h = get_registry().histogram("serving.prefix_promotion_seconds")
        sum0 = h._sum
        t0 = time.perf_counter()
        r2 = bt.submit(np.concatenate([toks, cont]), NEW_TOKENS)
        outs = bt.run_until_done(max_steps=60000)
        dt = time.perf_counter() - t0
        out2 = outs[r2]
        free_after = bt.audit_pages()          # raises on any leak
        st = bt.prefix_cache.stats()
        return {"out1": out1, "out2": out2, "resume_s": dt,
                "promo_stream_s": h._sum - sum0,
                "published": bool(published), "pinned": len(pinned),
                "demoted_before_resume": int(demoted),
                "promotions": int(st["promotions"]),
                "pin_drops": int(st["session_pin_drops"]),
                "free_pages_after": int(free_after)}
    finally:
        bt.close()


def _crash_resume(model, store_dir, cont):
    """Replica death: a fresh batcher that shares nothing but the
    manifest store resolves the session and full-prefills."""
    bt = _batcher(model, store_dir, promo_slots=PIPE_SLOTS,
                  promo_chunk_blocks=PIPE_CHUNK)
    try:
        toks = bt.resume_session(SID)
        if toks is None:
            return None
        r = bt.submit(np.concatenate([toks, cont]), NEW_TOKENS)
        out = bt.run_until_done(max_steps=60000)[r]
        bt.audit_pages()
        return out
    finally:
        bt.close()


def _session_round_path():
    import glob
    import re
    rounds = []
    for p in glob.glob(os.path.join(_REPO_DIR, "BENCH_SESSION_r*.json")):
        m = re.search(r"BENCH_SESSION_r(\d+)\.json$",
                      os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)))
    n = (max(rounds) + 1) if rounds else 0
    return os.path.join(_REPO_DIR, f"BENCH_SESSION_r{n:02d}.json")


def main():
    on_tpu = False
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        pass
    model, cfg = _model()
    prompt, cont, churn = _workload(cfg.vocab_size)

    with paddle.no_grad():
        base1, base2 = _baseline(model, prompt, cont)
        # one untimed warmup leg per geometry: first-touch trace/compile
        # of the install scatters must not bias the first timed rep
        for slots, csize in ((PIPE_SLOTS, PIPE_CHUNK), (1, None)):
            with tempfile.TemporaryDirectory(prefix="bench_session_") as d:
                _pause_churn_resume(model, d, prompt, cont, churn,
                                    slots, csize)
        runs = {"pipelined": [], "serial": []}
        for _ in range(REPS):
            for name, (slots, csize) in (
                    ("pipelined", (PIPE_SLOTS, PIPE_CHUNK)),
                    ("serial", (1, None))):
                with tempfile.TemporaryDirectory(
                        prefix="bench_session_") as d:
                    runs[name].append(_pause_churn_resume(
                        model, d, prompt, cont, churn, slots, csize))
        with tempfile.TemporaryDirectory(prefix="bench_session_") as d:
            leg = _pause_churn_resume(model, d, prompt, cont, churn,
                                      promo_slots=PIPE_SLOTS,
                                      promo_chunk_blocks=PIPE_CHUNK)
            crash = _crash_resume(model, d, cont)

    def _exact(leg):
        return bool(np.array_equal(leg["out1"], base1)
                    and np.array_equal(leg["out2"], base2))

    token_exact = all(_exact(leg) for legs in runs.values()
                      for leg in legs)
    # latency: min over reps is the stable estimator (noise only adds)
    t_pipe = min(leg["resume_s"] for leg in runs["pipelined"])
    t_serial = min(leg["resume_s"] for leg in runs["serial"])
    ps_pipe = min(leg["promo_stream_s"] for leg in runs["pipelined"])
    ps_serial = min(leg["promo_stream_s"] for leg in runs["serial"])
    rep = runs["pipelined"][0]
    ctx_tokens = len(prompt) + NEW_TOKENS + CONT_TOKENS
    goodput = ctx_tokens / max(t_pipe, 1e-9)

    detail = {
        "tpu": on_tpu,
        "session_blocks": SESSION_BLOCKS,
        "context_tokens": ctx_tokens,
        "published": rep["published"],
        "pinned_blocks": rep["pinned"],
        "demoted_before_resume": rep["demoted_before_resume"],
        "promotions": rep["promotions"],
        "session_pin_drops": rep["pin_drops"],
        "time_to_resume_ms": round(t_pipe * 1e3, 3),
        "time_to_resume_ms_pipelined": round(t_pipe * 1e3, 3),
        "time_to_resume_ms_serial": round(t_serial * 1e3, 3),
        "promo_stream_ms_pipelined": round(ps_pipe * 1e3, 3),
        "promo_stream_ms_serial": round(ps_serial * 1e3, 3),
        "pipelined_beats_serial": bool(ps_pipe < ps_serial),
        "token_exact": token_exact,
        "crash_resume_exact": bool(
            crash is not None and np.array_equal(crash, base2)),
        "audit_clean": True,       # _pause_churn_resume raised otherwise
    }
    line = {
        "metric": "session_resume_goodput",
        "value": round(goodput, 3),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": detail,
    }
    try:
        with open(_session_round_path(), "w") as f:
            json.dump(line, f, indent=1)
            f.write("\n")
    except OSError:
        pass  # artifact write must never sink the bench number
    print(json.dumps(line))


if __name__ == "__main__":
    main()
