"""Serving decode benchmark (VERDICT r2 #6 + r3 #6: the serving path).

Headline number = steady-state tokens/sec of the PAGED CONTINUOUS BATCHER
with fused admission — the actual serving configuration (vLLM-style paged
KV blocks, chunked prefill, decode+prefill in one executable). Same JSON
contract as bench.py: ONE line
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": ...}
with detail.tpu / detail.captured_at, so the heal playbook can persist it
as SERVING_TPU_SNAPSHOT.json.

Variant sweep in detail (reference analog: the inference engine's
performance surface, fluid/inference/api/analysis_predictor.h:100):
  - naive full-recompute, eager KV cache, paged eager, int8 compiled —
    CPU only (regression tracking; through the remote relay they are
    dispatch-bound and burn window time without new information)
  - kv_cache_compiled: ONE jit.to_static executable reused per step
  - batcher / fused batcher: tokens/sec + slot occupancy from the
    batcher's own stats counters

Runs on whatever backend is ambient (TPU when the axon relay is alive;
CPU otherwise — the number is tagged).
"""
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _steady_rate(step_fn, iters=32, warmup=4):
    """steps/sec of a repeated single-token step (batch handled inside)."""
    for _ in range(warmup):
        step_fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        step_fn()
    dt = time.perf_counter() - t0
    return iters / dt


SERVING_SNAPSHOT_PATH = os.path.join(_REPO_DIR, "SERVING_TPU_SNAPSHOT.json")


def _last_serving_snapshot():
    """Newest hardware serving record, or None. Only a record the heal
    playbook persisted from a real chip (detail.tpu true + captured_at)
    qualifies — a CPU line must never masquerade as hardware evidence."""
    try:
        with open(SERVING_SNAPSHOT_PATH) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return None
    det = snap.get("detail", {})
    if det.get("tpu") is True and det.get("captured_at"):
        return snap
    return None


def main():
    paddle.seed(0)
    on_tpu = False
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        pass
    if on_tpu:
        # GPT-2-124M-class serving config: big enough that the decode step
        # is real MXU work, small enough that the few executables compile
        # inside the playbook's stage budget through the remote tunnel.
        try:
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(_REPO_DIR, ".jax_cache"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
        except Exception:
            pass
        cfg = GPT2Config(vocab_size=32000, hidden_size=768,
                         num_hidden_layers=12, num_attention_heads=12,
                         max_position_embeddings=1024, dropout=0.0)
        batch, ctx, s_max = 8, 256, 512
        full_sweep = False
    else:
        cfg = GPT2Config(vocab_size=2048, hidden_size=256,
                         num_hidden_layers=4, num_attention_heads=8,
                         max_position_embeddings=512, dropout=0.0)
        batch, ctx, s_max = 4, 128, 256
        full_sweep = True
    model = GPT2ForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, ctx)))

    detail = {"params": model.num_params(), "batch": batch, "context": ctx,
              "cache": s_max, "tpu": on_tpu}
    with paddle.no_grad():
        if full_sweep:
            # naive full-recompute step at the starting context length
            def naive_step():
                out = model(ids)
                np.asarray(out._data[:, -1])  # block

            detail["naive_steps_per_s"] = round(_steady_rate(naive_step,
                                                             iters=8), 3)

            # kv-cache eager
            logits, caches, t = model.prefill(ids, s_max)
            tok = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (batch, 1)))
            state = {"caches": caches, "t": t}

            def eager_step():
                _, state["caches"], state["t"] = model.decode_step(
                    tok, state["caches"], state["t"])

            detail["kv_cache_eager_steps_per_s"] = round(
                _steady_rate(eager_step, iters=8), 3)

            # paged block cache (vLLM-style) decode step, eager — measured
            # on the fp32 model so it compares against kv_cache_eager
            _, pstate = model.paged_prefill(ids, block_size=64)
            ptok = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (batch,)))
            pbox = {"s": pstate}

            def paged_step():
                _, pbox["s"] = model.paged_decode_step(ptok, pbox["s"])

            detail["paged_eager_steps_per_s"] = round(
                _steady_rate(paged_step, iters=8), 3)

        # kv-cache compiled (ONE executable reused per step) — every backend
        tok = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, 1)))
        compiled = jit.to_static(model.decode_step)
        _, caches2, t2 = model.prefill(ids, s_max)
        state2 = {"caches": caches2, "t": t2}

        def compiled_step():
            _, state2["caches"], state2["t"] = compiled(
                tok, state2["caches"], state2["t"])

        rate = _steady_rate(compiled_step)
        detail["kv_cache_compiled_steps_per_s"] = round(rate, 3)
        detail["kv_cache_compiled_tokens_per_s"] = round(rate * batch, 2)

        if full_sweep:
            # int8 weight-only variant (mutates `model` in place)
            n_q = nn.quant.quantize_linear_layers(model)
            compiled_q = jit.to_static(model.decode_step)
            _, caches3, t3 = model.prefill(ids, s_max)
            state3 = {"caches": caches3, "t": t3}

            def int8_step():
                _, state3["caches"], state3["t"] = compiled_q(
                    tok, state3["caches"], state3["t"])

            detail["kv_cache_int8_steps_per_s"] = round(
                _steady_rate(int8_step), 3)
            detail["int8_linears"] = n_q

    # continuous batching end-to-end: staggered requests through the
    # paged batcher (compiled donated step + chunked prefill), the actual
    # serving configuration — reports tokens/sec and occupancy from the
    # batcher's own stats counters. Fresh fp model: the int8 pass above
    # may have mutated `model` in place.
    paddle.seed(0)
    serving_model = GPT2ForCausalLM(cfg)
    serving_model.eval()
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    new_toks = 32
    req_lens = [ctx - 17, ctx, ctx + 13, ctx - 5, ctx + 29, ctx]

    def drive(batcher):
        # warmup request compiles the chunk/decode executables, then the
        # counters reset so the measured window is steady-state serving
        batcher.submit(rng.randint(0, cfg.vocab_size, (ctx,)), 8)
        batcher.run_until_done()
        batcher.reset_stats()
        for ln in req_lens:
            batcher.submit(rng.randint(0, cfg.vocab_size, (ln,)), new_toks)
        batcher.run_until_done()
        return batcher.stats()

    b = PagedContinuousBatcher(serving_model, max_batch=batch, s_max=s_max,
                               block_size=64, prefill_chunk=64,
                               policy="ondemand", compile=True)
    s = drive(b)
    detail["batcher_tokens_per_s"] = round(s["tokens_per_sec"], 2)
    detail["batcher_slot_utilization"] = round(s["slot_utilization"], 3)
    detail["batcher_requests"] = s["completed_requests"]

    # fused admission (vLLM unified scheduling): decode + prefill share
    # one executable, so admission no longer pauses decoding. The batcher
    # never mutates weights, so the fp serving model is reusable.
    # decode_block=8 on TPU: pure-decode phases run 8 steps per dispatch
    # with on-device greedy feedback — through the remote relay each
    # dispatch costs network latency that dwarfs the 124M decode step's
    # compute, so per-call amortization IS the serving-throughput lever.
    # CPU keeps block=None so the fallback number stays comparable with
    # prior rounds.
    decode_block = 8 if on_tpu else None
    bf = PagedContinuousBatcher(serving_model, max_batch=batch, s_max=s_max,
                                block_size=64, prefill_chunk=64,
                                policy="ondemand", fused_admission=True,
                                decode_block=decode_block,
                                compile=True)
    sf = drive(bf)
    detail["fused_batcher_tokens_per_s"] = round(sf["tokens_per_sec"], 2)
    detail["fused_batcher_slot_utilization"] = round(
        sf["slot_utilization"], 3)
    detail["fused_batcher_steps"] = sf["steps"]
    detail["decode_block"] = decode_block
    detail["decode_blocks_dispatched"] = sf.get("decode_blocks", 0)

    if on_tpu:
        detail["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())
    else:
        # CPU fallback carries the last hardware number (VERDICT r4 #8,
        # mirroring bench.py's last_tpu pattern): a wedged-relay round
        # still surfaces the newest real serving snapshot, honestly
        # timestamped by its own captured_at.
        snap = _last_serving_snapshot()
        if snap is not None:
            detail["last_tpu"] = snap
    # headline = the fused paged batcher, ALWAYS — taking a max would let a
    # fused-admission regression silently hide behind the plain batcher.
    # vs_baseline stays 0.0: the reference publishes no serving figure to
    # normalize against (BASELINE.md).
    detail["occupancy"] = round(sf["slot_utilization"], 3)
    print(json.dumps({
        "metric": "paged_serving_decode_tokens_per_sec",
        "value": round(sf["tokens_per_sec"], 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
