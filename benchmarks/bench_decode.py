"""KV-cache decode benchmark (VERDICT r2 #6: the serving decode path).

Measures steady-state incremental-decode throughput on GPT-2:
  - naive: re-run the full forward over the growing context per token
    (what the round-2 serving example timed)
  - kv_cache: model.decode_step over the dense KV cache, eager
  - kv_cache_compiled: ONE jit.to_static executable reused every step
    (static shapes — the XLA analog of the reference's fused
    masked_multihead_attention_kernel.cu decode kernel)
  - kv_cache_int8: compiled + weight-only int8 Linears

Prints one JSON line: steady-state tokens/sec for the compiled cache path
plus per-variant detail. Runs on whatever backend is ambient (TPU when the
axon relay is alive; CPU otherwise — the number is tagged).
"""
import json
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM


def _steady_rate(step_fn, iters=32, warmup=4):
    """tokens/sec of a repeated single-token step (batch handled inside)."""
    for _ in range(warmup):
        step_fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        step_fn()
    dt = time.perf_counter() - t0
    return iters / dt


def main():
    paddle.seed(0)
    on_tpu = False
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        pass
    # sized to be meaningful but CPU-runnable; on TPU this is still tiny
    cfg = GPT2Config(vocab_size=2048, hidden_size=256, num_hidden_layers=4,
                     num_attention_heads=8, max_position_embeddings=512,
                     dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    model.eval()
    batch, ctx, s_max = 4, 128, 256
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, ctx)))

    detail = {"params": model.num_params(), "batch": batch, "context": ctx,
              "cache": s_max, "tpu": on_tpu}
    with paddle.no_grad():
        # naive full-recompute step at the starting context length
        def naive_step():
            out = model(ids)
            np.asarray(out._data[:, -1])  # block

        detail["naive_steps_per_s"] = round(_steady_rate(naive_step,
                                                         iters=8), 3)

        # kv-cache eager
        logits, caches, t = model.prefill(ids, s_max)
        tok = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, 1)))
        state = {"caches": caches, "t": t}

        def eager_step():
            _, state["caches"], state["t"] = model.decode_step(
                tok, state["caches"], state["t"])

        detail["kv_cache_eager_steps_per_s"] = round(
            _steady_rate(eager_step, iters=8), 3)

        # kv-cache compiled (ONE executable reused per step)
        compiled = jit.to_static(model.decode_step)
        _, caches2, t2 = model.prefill(ids, s_max)
        state2 = {"caches": caches2, "t": t2}

        def compiled_step():
            _, state2["caches"], state2["t"] = compiled(
                tok, state2["caches"], state2["t"])

        rate = _steady_rate(compiled_step)
        detail["kv_cache_compiled_steps_per_s"] = round(rate, 3)

        # paged block cache (vLLM-style) decode step, eager — measured on
        # the fp32 model so it compares against kv_cache_eager, not int8
        _, pstate = model.paged_prefill(ids, block_size=64)
        ptok = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (batch,)))
        pbox = {"s": pstate}

        def paged_step():
            _, pbox["s"] = model.paged_decode_step(ptok, pbox["s"])

        detail["paged_eager_steps_per_s"] = round(
            _steady_rate(paged_step, iters=8), 3)

        # int8 weight-only variant
        n_q = nn.quant.quantize_linear_layers(model)
        compiled_q = jit.to_static(model.decode_step)
        _, caches3, t3 = model.prefill(ids, s_max)
        state3 = {"caches": caches3, "t": t3}

        def int8_step():
            _, state3["caches"], state3["t"] = compiled_q(
                tok, state3["caches"], state3["t"])

        detail["kv_cache_int8_steps_per_s"] = round(
            _steady_rate(int8_step), 3)
        detail["int8_linears"] = n_q

    # continuous batching end-to-end: staggered requests through the
    # paged batcher (compiled donated step + chunked prefill), the actual
    # serving configuration — reports tokens/sec and occupancy from the
    # batcher's own stats counters. Fresh fp model: the int8 pass above
    # mutated `model` in place.
    paddle.seed(0)
    serving_model = GPT2ForCausalLM(cfg)
    serving_model.eval()
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    b = PagedContinuousBatcher(serving_model, max_batch=batch, s_max=s_max,
                               block_size=64, prefill_chunk=64,
                               policy="ondemand", compile=True)
    # warmup request compiles the chunk + decode executables, then the
    # counters reset so the measured window is steady-state serving
    b.submit(rng.randint(0, cfg.vocab_size, (ctx,)), 8)
    b.run_until_done()
    b.reset_stats()
    req_lens = [ctx - 17, ctx, ctx + 13, ctx - 5, ctx + 29, ctx]
    for ln in req_lens:
        b.submit(rng.randint(0, cfg.vocab_size, (ln,)), 32)
    b.run_until_done()
    s = b.stats()
    detail["batcher_tokens_per_s"] = round(s["tokens_per_sec"], 2)
    detail["batcher_slot_utilization"] = round(s["slot_utilization"], 3)
    detail["batcher_requests"] = s["completed_requests"]

    # fused admission (vLLM unified scheduling): decode + prefill share
    # one executable, so admission no longer pauses decoding. The batcher
    # never mutates weights, so the fp serving model is reusable.
    bf = PagedContinuousBatcher(serving_model, max_batch=batch, s_max=s_max,
                                block_size=64, prefill_chunk=64,
                                policy="ondemand", fused_admission=True,
                                compile=True)
    bf.submit(rng.randint(0, cfg.vocab_size, (ctx,)), 8)
    bf.run_until_done()
    bf.reset_stats()
    for ln in req_lens:
        bf.submit(rng.randint(0, cfg.vocab_size, (ln,)), 32)
    bf.run_until_done()
    sf = bf.stats()
    detail["fused_batcher_tokens_per_s"] = round(sf["tokens_per_sec"], 2)
    detail["fused_batcher_steps"] = sf["steps"]

    toks_per_s = rate * batch
    print(json.dumps({
        "metric": "gpt2_kv_cache_decode_throughput",
        "value": round(toks_per_s, 2),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
