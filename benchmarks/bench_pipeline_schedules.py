"""Pipeline schedule comparison: FThenB vs 1F1B vs interleaved VPP.

Prints one JSON line per schedule: wall-time per train_batch on the
8-device mesh plus the PLAN-derived liveness/bubble metrics (peak
in-flight activations per stage and the theoretical bubble fraction).
On real TPU hardware the same script under `paddle_tpu.profiler` yields
device timelines for bubble measurement; on the CPU mesh the plan metrics
are the schedule evidence (VERDICT #7's measurement scaffold).

Run: python benchmarks/bench_pipeline_schedules.py
"""
import json
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
from paddle_tpu.distributed.fleet.pipeline_parallel import (
    PipelineParallel, PipelineParallelWithInterleave)
from paddle_tpu.distributed.fleet.pipeline_schedules import (
    generate_schedule, max_inflight_per_stage)

HIDDEN = 64


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(HIDDEN, HIDDEN)

    def forward(self, x):
        return nn.functional.relu(self.fc(x))


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(HIDDEN, 8)

    def forward(self, x):
        return self.fc(x)


def theoretical_bubble(kind, S, C, M):
    """Fraction of stage-rounds idle in the plan's simulated timeline."""
    plan = generate_schedule(kind, S, C, M)
    # simulate round occupancy: each unit takes one round on its stage
    busy = len(plan)
    # total rounds = critical path under the plan's order
    stage_free = [0] * S
    done_time = {}
    t_end = 0
    for kindu, c, m in plan:
        s = c % S
        dep = 0
        if kindu == "F" and c > 0:
            dep = done_time.get(("F", c - 1, m), 0)
        elif kindu == "B":
            dep = done_time.get(("F", c, m), 0)
            if c < C - 1:
                dep = max(dep, done_time.get(("B", c + 1, m), 0))
        start = max(stage_free[s], dep)
        stage_free[s] = start + 1
        done_time[(kindu, c, m)] = start + 1
        t_end = max(t_end, start + 1)
    return 1.0 - busy / (t_end * S)


def run(kind, vpp):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 4}
    cfg = {"accumulate_steps": 8}
    if kind != "VPP":
        cfg["schedule_mode"] = kind
    strategy.pipeline_configs = cfg
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    n_blocks = 4 * vpp * 2 - 1
    layers = PipelineLayer(
        [LayerDesc(Block) for _ in range(n_blocks)] + [LayerDesc(Head)],
        num_stages=4, topology=hcg.topology(),
        loss_fn=lambda o, l: nn.functional.cross_entropy(o, l).mean(),
        num_virtual_pipeline_stages=vpp)
    cls = PipelineParallelWithInterleave if vpp > 1 else PipelineParallel
    pp = cls(layers, hcg, strategy)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=pp.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, HIDDEN).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)))
    pp.train_batch([x, y], opt)  # warm
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        pp.train_batch([x, y], opt)
    dt = (time.perf_counter() - t0) / iters
    C = layers.num_chunks
    peak = max_inflight_per_stage(list(pp.schedule_trace), 4)
    print(json.dumps({
        "schedule": kind, "chunks": C, "micro": 8,
        "ms_per_batch": round(dt * 1000, 1),
        "peak_inflight_per_stage": peak,
        "theoretical_bubble": round(theoretical_bubble(kind, 4, C, 8), 4),
    }), flush=True)
    from paddle_tpu.distributed.fleet import topology as _topo
    _topo.set_hybrid_communicate_group(None)


def main():
    paddle.seed(0)
    run("FThenB", 1)
    run("1F1B", 1)
    run("VPP", 2)


if __name__ == "__main__":
    main()
