"""BASELINE.md config 1: ResNet-50, single-device dygraph train throughput.

Prints one JSON line {metric, value, unit, detail}. CPU runs a tiny proxy;
TPU runs the real config.
"""
import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import amp, jit, nn, optimizer
    from paddle_tpu.models import resnet50
    from paddle_tpu.vision.models import resnet18

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        model = resnet50()
        batch, size, iters = 64, 224, 10
    else:
        model = resnet18(num_classes=10)
        batch, size, iters = 4, 64, 2

    paddle.seed(0)
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    loss_fn = nn.CrossEntropyLoss()
    # the auto_cast context casts the image input per-op (conv white list)
    step = jit.TrainStep(lambda x, y: loss_fn(model(x), y), opt,
                         amp=dict(level="O2", dtype="bfloat16"))

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, size, size).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 10, (batch,)).astype("int64"))
    step(x, y)           # eager discovery
    float(step(x, y))    # compile + warm

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    final = float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "resnet_train_images_per_sec",
        "value": round(batch * iters / dt, 2),
        "unit": "images/s",
        "detail": {"batch": batch, "size": size, "iters": iters,
                   "final_loss": round(final, 4),
                   "device": jax.devices()[0].platform},
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        print(json.dumps({"metric": "resnet_train_images_per_sec",
                          "value": 0.0, "unit": "images/s",
                          "detail": {"error": str(e)[:200]}}))
        sys.exit(0)
