"""Serving control-plane benchmark: routed throughput through the
multi-replica gateway.

Headline number = end-to-end tokens/sec of a 2-replica gateway
(least-loaded routing, mixed-priority tenants) driving compiled
ContinuousBatcher replicas — the full control-plane path: admission,
quota charge, priority queue, routing, replica stepping, token delivery.
detail carries the latency SLO surface (TTFT p50/p99, TPOT p50/p99, in
milliseconds, from the gateway's own histograms) plus a per-policy
routed-rate sweep (least_loaded / affinity / weighted_rr), and the
gateway.* telemetry series snapshot to BENCH_TELEMETRY.jsonl.

Same JSON contract as bench.py: ONE stdout line
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": ...}
vs_baseline stays 0.0 — the reference publishes no gateway figure to
normalize against (BASELINE.md).

A second, SHARED-PREFIX workload (K system prompts × N tenants × M
requests, seeded) drives paged replicas with the radix prefix cache +
KV-aware affinity routing on vs off, measuring prefix hit-rate and
steady-state TTFT (the cache-warming cold prefills run before the
measured window, like the compile warm-up above). Its bench line lands
in ``BENCH_GATEWAY_r<NN>.json`` at the repo root — the gateway lane of
``tools/bench_guard.py``'s trajectory gate, separate from the train
lane by filename prefix.
"""
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_gateway(model, policy, n_replicas, max_batch, s_max,
                   compile):
    from paddle_tpu.inference.gateway import Gateway
    from paddle_tpu.inference.serving import ContinuousBatcher
    gw = Gateway(policy=policy)
    for i in range(n_replicas):
        gw.add_replica(f"r{i}", ContinuousBatcher(
            model, max_batch=max_batch, s_max=s_max, compile=compile))
    return gw


def _drive(gw, rng, vocab, ctx, n_requests, new_toks):
    """Warm the replicas' executables on one request, then push a
    staggered mixed-priority load and measure the steady window."""
    gw.submit(rng.randint(0, vocab, (ctx,)), 4, tenant="warmup")
    gw.run_until_done()
    gw.reset_stats()
    t0 = time.perf_counter()
    for i in range(n_requests):
        ln = ctx + (i * 7) % 32 - 16
        gw.submit(rng.randint(0, vocab, (ln,)), new_toks,
                  tenant=("interactive", "batch")[i % 3 == 2],
                  priority=("high", "low")[i % 3 == 2],
                  session_id=f"s{i % 4}")
    gw.run_until_done()
    dt = time.perf_counter() - t0
    s = gw.stats()
    return s["delivered_tokens"] / dt, s


def _build_paged_gateway(model, n_replicas, max_batch, s_max, n_pages,
                         block_size, compile, prefix_cache):
    from paddle_tpu.inference.gateway import Gateway
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    gw = Gateway(policy="affinity")
    for i in range(n_replicas):
        gw.add_replica(f"r{i}", PagedContinuousBatcher(
            model, max_batch=max_batch, s_max=s_max,
            block_size=block_size, n_pages=n_pages, compile=compile,
            policy="ondemand", prefix_cache=prefix_cache,
            prompt_buckets="pow2"))
    return gw


def _shared_prefix_prompts(rng, vocab, n_sys, sys_len, n_requests,
                           tail_lo, tail_hi):
    """Deterministic shared-prefix workload: each request is one of
    ``n_sys`` system prompts plus a per-request tail (round-robin over
    the system prompts, so every one stays warm)."""
    sys_prompts = [rng.randint(0, vocab, (sys_len,))
                   for _ in range(n_sys)]
    prompts = []
    for i in range(n_requests):
        tail = rng.randint(0, vocab,
                           (int(rng.randint(tail_lo, tail_hi)),))
        prompts.append(np.concatenate([sys_prompts[i % n_sys], tail]))
    return sys_prompts, prompts


def _cache_totals(gw):
    hit = miss = 0
    for rep in gw.pool.replicas():
        c = getattr(rep.batcher, "prefix_cache", None)
        if c is not None:
            hit += c.hit_tokens
            miss += c.miss_tokens
    return hit, miss


def _drive_prompts(gw, prompts, new_toks, max_steps=200000):
    """Submit ``prompts``, drive to completion, and harvest per-request
    TTFT from the gateway's own request records BEFORE popping them —
    registry histograms are process-cumulative, so an on-vs-off
    comparison inside one process must not read them."""
    t0 = time.perf_counter()
    gids = [gw.submit(p, new_toks, tenant=f"t{i % 4}")
            for i, p in enumerate(prompts)]
    for _ in range(max_steps):
        gw.step()
        if not gw._has_work():
            break
    dt = time.perf_counter() - t0
    ttfts, toks = [], 0
    for g in gids:
        req = gw._finished[g]
        ttfts.append(req.first_token_t - req.submit_t)
        toks += len(req.delivered)
        gw.pop_result(g)
    return toks / dt, ttfts


def _p99(xs):
    s = sorted(xs)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def _shared_prefix_bench(model, vocab, on_tpu, compile):
    """Prefix cache on vs off over the same seeded workload; returns the
    gateway-lane detail dict. Sized so the CPU proxy finishes fast."""
    if on_tpu:
        n_sys, sys_len, n_req, tails = 4, 128, 24, (16, 48)
        max_batch, s_max, n_pages, bs, new_toks = 4, 512, 160, 16, 12
    else:
        n_sys, sys_len, n_req, tails = 3, 96, 18, (8, 24)
        max_batch, s_max, n_pages, bs, new_toks = 4, 192, 96, 16, 6
    out = {"system_prompts": n_sys, "system_len": sys_len,
           "requests": n_req, "new_tokens": new_toks}
    runs = {}
    for label, cache_on in (("on", True), ("off", False)):
        rng = np.random.RandomState(7)   # identical workload both runs
        sys_prompts, prompts = _shared_prefix_prompts(
            rng, vocab, n_sys, sys_len, n_req, *tails)
        gw = _build_paged_gateway(model, 2, max_batch, s_max, n_pages,
                                  bs, compile, cache_on)
        # warm phase: compile warm-up + the K cold system-prompt
        # prefills (cache population) stay OUT of the measured window.
        # Tails span the pow2 suffix rungs so the cache-on path's
        # NARROW suffix-prefill executables (dec_base append mode at
        # widths bucket(tail)) are compiled before measurement, same as
        # the cache-off path's full-width prefill.
        warm_tails = (tails[0], (tails[0] + tails[1]) // 2, tails[1])
        for sp in sys_prompts:
            for wt in warm_tails:
                gw.submit(np.concatenate(
                    [sp, rng.randint(0, vocab, (wt,))]), 4,
                    tenant="warmup")
        gw.run_until_done()
        hit0, miss0 = _cache_totals(gw)
        # goodput attribution over the measured window only: snapshot
        # the recorder's trace ids so warmup/cold prefills stay out
        from paddle_tpu.observability.ledger import ledger_from_waterfalls
        from paddle_tpu.observability.trace_context import get_recorder
        from paddle_tpu.observability.waterfall import build_waterfalls
        rec = get_recorder()
        pre_ids = set(rec.trace_ids())
        rate, ttfts = _drive_prompts(gw, prompts, new_toks)
        hit1, miss1 = _cache_totals(gw)
        meas_spans = [s for s in rec.spans()
                      if s.trace_id not in pre_ids]
        led = ledger_from_waterfalls(build_waterfalls(meas_spans))
        runs[label] = {"rate": rate, "ttfts": ttfts,
                       "hit": hit1 - hit0, "miss": miss1 - miss0,
                       "ledger": led.summary()}
        if label == "on":
            led.publish()   # ledger.* series join the telemetry snapshot
        for rep in gw.pool.replicas():
            rep.batcher.audit_pages()   # pages_leaked must stay 0
    hit, miss = runs["on"]["hit"], runs["on"]["miss"]
    out["prefix_hit_rate"] = round(hit / max(hit + miss, 1), 4)
    out["ttft_p99_ms_cache_on"] = round(_p99(runs["on"]["ttfts"]) * 1e3, 3)
    out["ttft_p99_ms_cache_off"] = round(_p99(runs["off"]["ttfts"]) * 1e3, 3)
    out["ttft_p99_improvement"] = round(
        1.0 - _p99(runs["on"]["ttfts"]) / max(_p99(runs["off"]["ttfts"]),
                                              1e-9), 4)
    out["shared_tokens_per_s_cache_on"] = round(runs["on"]["rate"], 2)
    out["shared_tokens_per_s_cache_off"] = round(runs["off"]["rate"], 2)
    # trace-derived goodput (observability.ledger over the measured
    # window's waterfalls): cache-on must spend a larger fraction of its
    # chip-seconds on non-waste — bench_guard gates this like throughput
    for label in ("on", "off"):
        ls = runs[label]["ledger"]
        out[f"goodput_frac_cache_{label}"] = round(ls["goodput_frac"], 4)
        out[f"prefill_chip_s_cache_{label}"] = round(
            ls["by_phase"].get("prefill", 0.0), 4)
    out["waste_seconds_cache_on"] = {
        k: round(v, 4)
        for k, v in runs["on"]["ledger"]["waste_seconds"].items()}

    # control: NO shared prefix — the cache must not tax the miss path
    ctl = {}
    for label, cache_on in (("on", True), ("off", False)):
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, vocab,
                               (sys_len + int(rng.randint(*tails)),))
                   for _ in range(n_req)]
        gw = _build_paged_gateway(model, 2, max_batch, s_max, n_pages,
                                  bs, compile, cache_on)
        gw.submit(rng.randint(0, vocab, (sys_len,)), 4, tenant="warmup")
        gw.run_until_done()
        rate, _ = _drive_prompts(gw, prompts, new_toks)
        ctl[label] = round(rate, 2)
    out["no_shared_tokens_per_s_cache_on"] = ctl["on"]
    out["no_shared_tokens_per_s_cache_off"] = ctl["off"]
    return out


def _gateway_round_path():
    """Next BENCH_GATEWAY_r<NN>.json slot: continue the gateway lane if
    it exists, else start it at the train lane's current round so the
    two trajectories roughly align."""
    import glob
    import re
    rounds = []
    for pat, rx in (("BENCH_GATEWAY_r*.json",
                     r"BENCH_GATEWAY_r(\d+)\.json$"),):
        for p in glob.glob(os.path.join(_REPO_DIR, pat)):
            m = re.search(rx, os.path.basename(p))
            if m:
                rounds.append(int(m.group(1)))
    if not rounds:
        for p in glob.glob(os.path.join(_REPO_DIR, "BENCH_r*.json")):
            m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(p))
            if m:
                rounds.append(int(m.group(1)) - 1)
    n = (max(rounds) + 1) if rounds else 0
    return os.path.join(_REPO_DIR, f"BENCH_GATEWAY_r{n:02d}.json")


def main():
    paddle.seed(0)
    on_tpu = False
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        pass
    if on_tpu:
        cfg = GPT2Config(vocab_size=32000, hidden_size=768,
                         num_hidden_layers=12, num_attention_heads=12,
                         max_position_embeddings=1024, dropout=0.0)
        ctx, s_max, max_batch, n_requests, new_toks = 256, 512, 4, 12, 32
        compile = True
    else:
        cfg = GPT2Config(vocab_size=2048, hidden_size=256,
                         num_hidden_layers=4, num_attention_heads=8,
                         max_position_embeddings=512, dropout=0.0)
        ctx, s_max, max_batch, n_requests, new_toks = 64, 192, 4, 9, 16
        compile = True
    model = GPT2ForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)

    detail = {"params": model.num_params(), "replicas": 2,
              "max_batch_per_replica": max_batch, "requests": n_requests,
              "new_tokens": new_toks, "tpu": on_tpu}
    with paddle.no_grad():
        rates = {}
        headline_stats = None
        for policy in ("least_loaded", "affinity", "weighted_rr"):
            gw = _build_gateway(model, policy, 2, max_batch, s_max,
                                compile)
            rate, s = _drive(gw, rng, cfg.vocab_size, ctx, n_requests,
                             new_toks)
            rates[policy] = round(rate, 2)
            if policy == "least_loaded":
                headline_stats = s
    detail["routed_tokens_per_s"] = rates

    from paddle_tpu.observability import get_registry, write_jsonl
    reg = get_registry()
    for name, key in (("gateway.ttft_seconds", "ttft"),
                      ("gateway.tpot_seconds", "tpot")):
        h = reg.histogram(name)
        for q, tag in ((0.5, "p50"), (0.99, "p99")):
            v = h.quantile(q)
            detail[f"{key}_{tag}_ms"] = (None if v is None
                                         else round(v * 1e3, 3))
    detail["completions"] = headline_stats["completions"]
    detail["requeued"] = headline_stats["requeued"]

    with paddle.no_grad():
        shared = _shared_prefix_bench(model, cfg.vocab_size, on_tpu,
                                      compile)
    detail["shared_prefix"] = shared
    gw_line = {
        "metric": "gateway_shared_prefix_tokens_per_sec",
        "value": shared["shared_tokens_per_s_cache_on"],
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": dict(shared, tpu=on_tpu),
    }
    try:
        with open(_gateway_round_path(), "w") as f:
            json.dump(gw_line, f, indent=1)
            f.write("\n")
    except OSError:
        pass  # artifact write must never sink the bench number
    if on_tpu:
        detail["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())
    try:
        snap_path = os.path.join(_REPO_DIR, "BENCH_TELEMETRY.jsonl")
        write_jsonl(snap_path, extra={"bench": "gateway", "tpu": on_tpu})
    except Exception:
        pass  # telemetry must never sink the bench number

    print(json.dumps({
        "metric": "gateway_routed_tokens_per_sec",
        "value": rates["least_loaded"],
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
