"""Serving control-plane benchmark: routed throughput through the
multi-replica gateway.

Headline number = end-to-end tokens/sec of a 2-replica gateway
(least-loaded routing, mixed-priority tenants) driving compiled
ContinuousBatcher replicas — the full control-plane path: admission,
quota charge, priority queue, routing, replica stepping, token delivery.
detail carries the latency SLO surface (TTFT p50/p99, TPOT p50/p99, in
milliseconds, from the gateway's own histograms) plus a per-policy
routed-rate sweep (least_loaded / affinity / weighted_rr), and the
gateway.* telemetry series snapshot to BENCH_TELEMETRY.jsonl.

Same JSON contract as bench.py: ONE stdout line
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": ...}
vs_baseline stays 0.0 — the reference publishes no gateway figure to
normalize against (BASELINE.md).
"""
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_gateway(model, policy, n_replicas, max_batch, s_max,
                   compile):
    from paddle_tpu.inference.gateway import Gateway
    from paddle_tpu.inference.serving import ContinuousBatcher
    gw = Gateway(policy=policy)
    for i in range(n_replicas):
        gw.add_replica(f"r{i}", ContinuousBatcher(
            model, max_batch=max_batch, s_max=s_max, compile=compile))
    return gw


def _drive(gw, rng, vocab, ctx, n_requests, new_toks):
    """Warm the replicas' executables on one request, then push a
    staggered mixed-priority load and measure the steady window."""
    gw.submit(rng.randint(0, vocab, (ctx,)), 4, tenant="warmup")
    gw.run_until_done()
    gw.reset_stats()
    t0 = time.perf_counter()
    for i in range(n_requests):
        ln = ctx + (i * 7) % 32 - 16
        gw.submit(rng.randint(0, vocab, (ln,)), new_toks,
                  tenant=("interactive", "batch")[i % 3 == 2],
                  priority=("high", "low")[i % 3 == 2],
                  session_id=f"s{i % 4}")
    gw.run_until_done()
    dt = time.perf_counter() - t0
    s = gw.stats()
    return s["delivered_tokens"] / dt, s


def main():
    paddle.seed(0)
    on_tpu = False
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        pass
    if on_tpu:
        cfg = GPT2Config(vocab_size=32000, hidden_size=768,
                         num_hidden_layers=12, num_attention_heads=12,
                         max_position_embeddings=1024, dropout=0.0)
        ctx, s_max, max_batch, n_requests, new_toks = 256, 512, 4, 12, 32
        compile = True
    else:
        cfg = GPT2Config(vocab_size=2048, hidden_size=256,
                         num_hidden_layers=4, num_attention_heads=8,
                         max_position_embeddings=512, dropout=0.0)
        ctx, s_max, max_batch, n_requests, new_toks = 64, 192, 4, 9, 16
        compile = True
    model = GPT2ForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)

    detail = {"params": model.num_params(), "replicas": 2,
              "max_batch_per_replica": max_batch, "requests": n_requests,
              "new_tokens": new_toks, "tpu": on_tpu}
    with paddle.no_grad():
        rates = {}
        headline_stats = None
        for policy in ("least_loaded", "affinity", "weighted_rr"):
            gw = _build_gateway(model, policy, 2, max_batch, s_max,
                                compile)
            rate, s = _drive(gw, rng, cfg.vocab_size, ctx, n_requests,
                             new_toks)
            rates[policy] = round(rate, 2)
            if policy == "least_loaded":
                headline_stats = s
    detail["routed_tokens_per_s"] = rates

    from paddle_tpu.observability import get_registry, write_jsonl
    reg = get_registry()
    for name, key in (("gateway.ttft_seconds", "ttft"),
                      ("gateway.tpot_seconds", "tpot")):
        h = reg.histogram(name)
        for q, tag in ((0.5, "p50"), (0.99, "p99")):
            v = h.quantile(q)
            detail[f"{key}_{tag}_ms"] = (None if v is None
                                         else round(v * 1e3, 3))
    detail["completions"] = headline_stats["completions"]
    detail["requeued"] = headline_stats["requeued"]
    if on_tpu:
        detail["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())
    try:
        snap_path = os.path.join(_REPO_DIR, "BENCH_TELEMETRY.jsonl")
        write_jsonl(snap_path, extra={"bench": "gateway", "tpu": on_tpu})
    except Exception:
        pass  # telemetry must never sink the bench number

    print(json.dumps({
        "metric": "gateway_routed_tokens_per_sec",
        "value": rates["least_loaded"],
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
