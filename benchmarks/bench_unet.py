"""BASELINE.md config 5 proxy: diffusion-UNet training throughput —
conv + group-norm + self/cross attention, the Stable-Diffusion kernel mix.

The reference lists the full SD UNet as an external-model config; this
trains the in-tree diffusion family (models/unet.py: time-conditioned
UNet + DDPM noise-prediction loss) so the bench exercises exactly the
kernels the family ships (conv2d / GroupNorm / attention fused by XLA,
flash kernel on TPU).
"""
import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer
    from paddle_tpu.models import UNetModel, ddpm_loss, unet_tiny_config

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = unet_tiny_config(base_channels=128, channel_mults=(1, 2, 4),
                               num_res_blocks=2, attn_levels=(1, 2),
                               num_heads=8, groups=32)
        size, batch, iters = 64, 8, 10
    else:
        cfg = unet_tiny_config()
        size, batch, iters = 16, 2, 2
    paddle.seed(0)
    model = UNetModel(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = jit.TrainStep(
        lambda x, t, n: ddpm_loss(model, x, t, n), opt)

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, size, size).astype("float32"))
    t = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int64))
    n = paddle.to_tensor(rng.randn(batch, 3, size, size).astype("float32"))
    step(x, t, n)
    float(step(x, t, n))

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, t, n)
    final = float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "unet_ddpm_train_images_per_sec",
        "value": round(batch * iters / dt, 2),
        "unit": "images/s",
        "detail": {"params": model.num_params(), "size": size,
                   "batch": batch, "final_loss": round(final, 5),
                   "device": jax.devices()[0].platform},
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        print(json.dumps({"metric": "unet_ddpm_train_images_per_sec",
                          "value": 0.0, "unit": "images/s",
                          "detail": {"error": str(e)[:200]}}))
        sys.exit(0)
