"""BASELINE.md config 5 proxy: diffusion-UNet-style block throughput —
conv + group-norm + attention, the Stable-Diffusion kernel mix.

The reference lists the full SD UNet as an external-model config; this
stands up the kernel tier it exercises (conv2d / GroupNorm / self-attn
fused by XLA, flash kernel on TPU).
"""
import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import jit, nn, optimizer
    import paddle_tpu.nn.functional as F

    class ResBlock(nn.Layer):
        def __init__(self, ch):
            super().__init__()
            self.n1 = nn.GroupNorm(8, ch)
            self.c1 = nn.Conv2D(ch, ch, 3, padding=1)
            self.n2 = nn.GroupNorm(8, ch)
            self.c2 = nn.Conv2D(ch, ch, 3, padding=1)

        def forward(self, x):
            h = self.c1(F.silu(self.n1(x)))
            return x + self.c2(F.silu(self.n2(h)))

    class AttnBlock(nn.Layer):
        def __init__(self, ch):
            super().__init__()
            self.norm = nn.GroupNorm(8, ch)
            self.qkv = nn.Conv2D(ch, 3 * ch, 1)
            self.proj = nn.Conv2D(ch, ch, 1)
            self.ch = ch

        def forward(self, x):
            b, c, hgt, wid = x.shape
            qkv = self.qkv(self.norm(x))
            qkv = qkv.reshape([b, 3, c, hgt * wid]).transpose([1, 0, 3, 2])
            q, k, v = qkv[0], qkv[1], qkv[2]        # [b, hw, c]
            att = F.scaled_dot_product_attention(
                q.unsqueeze(2), k.unsqueeze(2), v.unsqueeze(2))
            att = att.squeeze(2).transpose([0, 2, 1]).reshape(
                [b, c, hgt, wid])
            return x + self.proj(att)

    class MiniUNet(nn.Layer):
        def __init__(self, ch=64):
            super().__init__()
            self.inc = nn.Conv2D(3, ch, 3, padding=1)
            self.down = nn.Conv2D(ch, ch * 2, 3, stride=2, padding=1)
            self.mid1 = ResBlock(ch * 2)
            self.attn = AttnBlock(ch * 2)
            self.mid2 = ResBlock(ch * 2)
            self.up = nn.Conv2DTranspose(ch * 2, ch, 4, stride=2, padding=1)
            self.out = nn.Conv2D(ch, 3, 3, padding=1)

        def forward(self, x):
            h = self.inc(x)
            m = self.mid2(self.attn(self.mid1(self.down(h))))
            return self.out(self.up(m) + h)

    on_tpu = jax.devices()[0].platform == "tpu"
    ch, size, batch, iters = (128, 64, 8, 10) if on_tpu else (32, 16, 2, 2)
    paddle.seed(0)
    model = MiniUNet(ch)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = jit.TrainStep(
        lambda x, t: ((model(x) - t) ** 2).mean(), opt)

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, size, size).astype("float32"))
    t = paddle.to_tensor(rng.randn(batch, 3, size, size).astype("float32"))
    step(x, t)
    float(step(x, t))

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, t)
    final = float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "unet_block_train_images_per_sec",
        "value": round(batch * iters / dt, 2),
        "unit": "images/s",
        "detail": {"channels": ch, "size": size, "batch": batch,
                   "final_loss": round(final, 5),
                   "device": jax.devices()[0].platform},
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        print(json.dumps({"metric": "unet_block_train_images_per_sec",
                          "value": 0.0, "unit": "images/s",
                          "detail": {"error": str(e)[:200]}}))
        sys.exit(0)
