"""BASELINE.md config 4: BERT-large with auto-parallel TP over a mesh.

On real hardware: v5e-16 mesh. Offline validation: 8 virtual CPU devices
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python benchmarks/bench_bert_tp.py
"""
import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer
    from paddle_tpu.distributed.auto_parallel import (ProcessMesh, Replicate,
                                                      Shard, shard_tensor)
    from paddle_tpu.models import BertConfig, BertForMaskedLM

    devs = jax.devices()
    n = len(devs)
    on_tpu = devs[0].platform == "tpu"
    if on_tpu and n >= 4:
        cfg = BertConfig(vocab_size=30522, hidden_size=1024,
                         num_hidden_layers=24, num_attention_heads=16,
                         intermediate_size=4096)
        batch, seq, iters = 16, 512, 10
    else:
        cfg = BertConfig(vocab_size=256, hidden_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=128,
                         max_position_embeddings=128)
        batch, seq, iters = 4, 32, 2

    mp = 2 if n % 2 == 0 else 1
    dp = n // mp
    mesh = ProcessMesh(np.arange(n).reshape(dp, mp), dim_names=["dp", "mp"])

    paddle.seed(0)
    model = BertForMaskedLM(cfg)
    # the model zoo's Megatron plan: qkv/intermediate column-parallel,
    # attention-out/output row-parallel over the mp axis
    from paddle_tpu.models import shard_bert
    shard_bert(model, mesh, mp_axis="mp")
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())

    def loss_fn(ids, mlm_labels):
        out = model(ids, labels=mlm_labels)
        return out[-1] if isinstance(out, (list, tuple)) else out

    step = jit.TrainStep(loss_fn, opt)
    rng = np.random.RandomState(0)
    place = [Shard(0), Replicate()]
    ids = shard_tensor(paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq))), mesh, place)
    labels = shard_tensor(paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq))), mesh, place)
    step(ids, labels)
    float(step(ids, labels))

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "bert_tp_train_tokens_per_sec",
        "value": round(batch * seq * iters / dt, 2),
        "unit": "tokens/s",
        "detail": {"mesh": [dp, mp], "batch": batch, "seq": seq,
                   "final_loss": round(final, 4),
                   "device": devs[0].platform},
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        print(json.dumps({"metric": "bert_tp_train_tokens_per_sec",
                          "value": 0.0, "unit": "tokens/s",
                          "detail": {"error": str(e)[:200]}}))
        sys.exit(0)
