"""BASELINE.md config 2: GPT-2 124M through to_static + AMP bf16.

Exercises the compiled path (capture -> one XLA executable).
"""
import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import amp, jit, optimizer
    from paddle_tpu.models import GPT2Config, GPT2ForCausalLM

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:  # GPT-2 124M
        cfg = GPT2Config(vocab_size=50257, hidden_size=768,
                         num_hidden_layers=12, num_attention_heads=12,
                         max_position_embeddings=1024)
        batch, seq, iters = 8, 512, 10
    else:
        cfg = GPT2Config(vocab_size=256, hidden_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         max_position_embeddings=128)
        batch, seq, iters = 2, 64, 2

    paddle.seed(0)
    model = GPT2ForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=True)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    step = jit.TrainStep(lambda i, l: model(i, labels=l)[1], opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    step(ids, labels)
    float(step(ids, labels))

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "gpt2_train_tokens_per_sec",
        "value": round(batch * seq * iters / dt, 2),
        "unit": "tokens/s",
        "detail": {"params": model.num_params(), "batch": batch, "seq": seq,
                   "final_loss": round(final, 4),
                   "device": jax.devices()[0].platform,
                   "amp": "O2 bf16"},
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        print(json.dumps({"metric": "gpt2_train_tokens_per_sec",
                          "value": 0.0, "unit": "tokens/s",
                          "detail": {"error": str(e)[:200]}}))
        sys.exit(0)
