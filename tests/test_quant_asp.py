"""Tests for quantization (QAT/PTQ, reference python/paddle/quantization)
and ASP n:m sparsity (reference python/paddle/incubate/asp)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.incubate import asp
from paddle_tpu.quantization import (QAT, PTQ, AbsmaxObserver, EMAObserver,
                                     FakeQuanterWithAbsMaxObserver,
                                     HistObserver, QuantConfig, QuantedConv2D,
                                     QuantedLinear, convert, quant_dequant)


def _np(t):
    return np.asarray(t._data)


def _mlp():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


# -- fake quant primitive -----------------------------------------------------

def test_quant_dequant_rounds_to_grid():
    x = paddle.to_tensor(np.array([0.0, 0.1, 0.5, -1.0], dtype=np.float32))
    scale = paddle.to_tensor(np.float32(1.0))
    q = _np(quant_dequant(x, scale, bit_length=8))
    grid = 1.0 / 127
    np.testing.assert_allclose(q, np.round(_np(x) / grid) * grid, rtol=1e-6)


def test_quant_dequant_ste_gradient_is_identity():
    x = paddle.to_tensor(np.array([0.3, -0.7], dtype=np.float32),
                         stop_gradient=False)
    q = quant_dequant(x, paddle.to_tensor(np.float32(1.0)))
    q.sum().backward()
    np.testing.assert_allclose(_np(x.grad), [1.0, 1.0], rtol=1e-6)


# -- observers ----------------------------------------------------------------

def test_observers_track_scale():
    a = AbsmaxObserver()
    a.observe(np.array([1.0, -3.0]))
    a.observe(np.array([2.0]))
    assert float(a.scales()) == 3.0

    e = EMAObserver(moving_rate=0.5)
    e.observe(np.array([4.0]))
    e.observe(np.array([2.0]))
    assert float(e.scales()) == pytest.approx(3.0)

    h = HistObserver(bins_count=64, percent=1.0)
    h.observe(np.linspace(-1, 1, 100))
    assert 0.9 <= float(h.scales()) <= 1.1


# -- QAT ----------------------------------------------------------------------

def test_qat_swaps_and_trains():
    net = _mlp()
    q_config = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                           weight=FakeQuanterWithAbsMaxObserver())
    qat = QAT(q_config)
    qnet = qat.quantize(net, inplace=False)
    kinds = [type(l).__name__ for l in qnet.sublayers()]
    assert kinds.count("QuantedLinear") == 2
    # original model untouched
    assert not any(isinstance(l, QuantedLinear) for l in net.sublayers())

    opt = optimizer.Adam(learning_rate=0.05, parameters=qnet.parameters())
    lossf = nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 8)
                         .astype(np.float32))
    y = paddle.to_tensor((np.random.RandomState(1).rand(16) * 4)
                         .astype(np.int64))
    losses = []
    for _ in range(8):
        loss = lossf(qnet(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # trains through fake-quant (STE)


def test_qat_type_config_limits_swap():
    net = _mlp()
    cfg = QuantConfig()
    cfg.add_type_config(nn.Linear, weight=FakeQuanterWithAbsMaxObserver())
    qnet = QAT(cfg).quantize(net)
    quanted = [l for l in qnet.sublayers() if isinstance(l, QuantedLinear)]
    assert len(quanted) == 2
    assert all(l.activation_quanter is None for l in quanted)


def test_qat_conv_swap():
    net = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU())
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                      weight=FakeQuanterWithAbsMaxObserver())
    qnet = QAT(cfg).quantize(net)
    assert any(isinstance(l, QuantedConv2D) for l in qnet.sublayers())
    x = paddle.to_tensor(np.random.randn(2, 1, 8, 8).astype(np.float32))
    assert tuple(qnet(x).shape) == (2, 4, 8, 8)


# -- PTQ ----------------------------------------------------------------------

def test_ptq_calibrate_and_convert():
    net = _mlp()
    cfg = QuantConfig(activation=AbsmaxObserver(), weight=AbsmaxObserver())
    ptq = PTQ(cfg)
    pnet = ptq.quantize(net)
    x = paddle.to_tensor(np.random.RandomState(2).randn(32, 8)
                         .astype(np.float32))
    ref = _np(pnet(x))  # calibration pass (observers only: exact output)
    np.testing.assert_allclose(ref, _np(net(x)), rtol=1e-5, atol=1e-6)

    inet = ptq.convert(pnet)
    kinds = [type(l).__name__ for l in inet.sublayers()]
    assert kinds.count("_ConvertedLinear") == 2
    out = _np(inet(x))
    # int8 weights: close to the float output
    assert np.abs(out - ref).max() < 0.1 * (np.abs(ref).max() + 1)
    # int8 storage really is int8
    lin = [l for l in inet.sublayers()
           if type(l).__name__ == "_ConvertedLinear"][0]
    assert str(lin.w_int8.dtype) in ("int8", "paddle.int8")


# -- ASP ----------------------------------------------------------------------

def test_mask_1d_2of4():
    w = np.array([[0.1, -0.9, 0.5, 0.2, 1.0, 0.05, -0.3, 0.01]],
                 dtype=np.float32)
    mask = asp.compute_mask_1d(w, 2, 4)
    assert mask.shape == w.shape
    groups = mask.reshape(-1, 4).sum(axis=-1)
    np.testing.assert_array_equal(groups, [2, 2])
    # the kept entries are the two largest magnitudes per group
    assert mask[0, 1] == 1 and mask[0, 2] == 1
    assert mask[0, 4] == 1 and mask[0, 6] == 1


def test_mask_2d_row_and_col_budget():
    w = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    mask = asp.compute_mask_2d(w, 2, 4)
    for i0 in range(0, 8, 4):
        for j0 in range(0, 8, 4):
            tile = mask[i0:i0 + 4, j0:j0 + 4]
            assert (tile.sum(axis=0) <= 2).all()
            assert (tile.sum(axis=1) <= 2).all()


def test_prune_model_and_decorate():
    net = _mlp()
    densities = asp.prune_model(net, n=2, m=4)
    assert densities  # at least the two Linear weights
    for name, d in densities.items():
        assert d == pytest.approx(0.5, abs=0.01)
    w0 = net[0].weight
    assert asp.check_sparsity(w0, 2, 4)

    opt = asp.decorate(optimizer.Adam(learning_rate=0.05,
                                      parameters=net.parameters()))
    lossf = nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 8)
                         .astype(np.float32))
    y = paddle.to_tensor((np.random.RandomState(1).rand(16) * 4)
                         .astype(np.int64))
    for _ in range(3):
        loss = lossf(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    # masks survived training steps
    assert asp.check_sparsity(net[0].weight, 2, 4)
    assert asp.calculate_density(net[0].weight) == pytest.approx(0.5,
                                                                 abs=0.01)


def test_asp_excluded_layers():
    net = _mlp()
    asp.set_excluded_layers(["0.weight"])
    try:
        densities = asp.prune_model(net, 2, 4)
        assert all("0.weight" not in k for k in densities)
        assert asp.calculate_density(net[0].weight) == 1.0
    finally:
        asp.reset_excluded_layers()


class TestStaticQuantization:
    """static/quantization.py — PTQ calibration, KL threshold, pass shims
    (reference: test/quantization/test_post_training_quantization_*.py)."""

    def _model_and_data(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        x = paddle.to_tensor(rng.randn(32, 8).astype("float32"))
        return model, x, rng

    def test_cal_kl_threshold_clips_outliers(self):
        from paddle_tpu.static.quantization import cal_kl_threshold
        rng = np.random.RandomState(0)
        acts = np.concatenate([np.abs(rng.randn(100000)),
                               [50.0]]).astype("float32")
        hist, edges = np.histogram(acts, bins=2048)
        thr = cal_kl_threshold(hist, float(edges[1] - edges[0]))
        assert thr < 10.0, "KL calibration should clip the outlier tail"

    def test_post_training_quantization_accuracy(self):
        from paddle_tpu.static.quantization import PostTrainingQuantization
        model, x, rng = self._model_and_data()
        ref = np.asarray(model(x)._data)

        def gen():
            for _ in range(40):
                yield rng.randn(8).astype("float32")

        for algo in ("KL", "abs_max", "hist"):
            ptq = PostTrainingQuantization(model=model, sample_generator=gen,
                                           batch_size=8, algo=algo)
            q = ptq.quantize()
            out = np.asarray(q(x)._data)
            err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
            assert err < 0.1, f"{algo}: int8 PTQ error too large ({err})"

    def test_transform_then_freeze_passes(self):
        from paddle_tpu.static.quantization import (QuantizationFreezePass,
                                                    QuantizationTransformPass)
        model, x, _ = self._model_and_data()
        ref = np.asarray(model(x)._data)
        qat_model = QuantizationTransformPass().apply(model)
        qat_model(x)  # one observation step
        frozen = QuantizationFreezePass().apply(qat_model)
        out = np.asarray(frozen(x)._data)
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.1
        # frozen form holds int8 weights
        from paddle_tpu.quantization import QuantedLinear
        names = [type(l).__name__ for _, l in frozen.named_sublayers()]
        assert "_ConvertedLinear" in names

    def test_out_scale_passes(self):
        from paddle_tpu.static.quantization import (OutScaleForInferencePass,
                                                    OutScaleForTrainingPass)
        model, x, _ = self._model_and_data()
        m = OutScaleForTrainingPass().apply(model)
        m(x)
        m = OutScaleForInferencePass().apply(m)
        assert len(m._out_threshold_scales) > 0
        assert all(s > 0 for s in m._out_threshold_scales.values())

    def test_weight_only_quant(self):
        from paddle_tpu.static.quantization import quant_post_dynamic
        model, x, _ = self._model_and_data()
        ref = np.asarray(model(x)._data)
        for qtype in ("abs_max", "channel_wise_abs_max"):
            q = quant_post_dynamic(model=model, quantize_type=qtype)
            out = np.asarray(q(x)._data)
            assert np.abs(out - ref).max() / np.abs(ref).max() < 0.05
