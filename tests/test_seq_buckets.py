"""Dynamic-shape (sequence) bucketing for to_static (SURVEY §7 hard (d)).

The reference handles dynamic shapes by guard + re-trace per shape
(jit/sot/.../function_graph.py:143); XLA wants static shapes, so varying
lengths pad up to power-of-two buckets and reuse O(log n) executables.
These tests pin: two distinct lengths hit the SAME executable with
matching numerics (VERDICT r2 #7's done-criterion), and the tail masking
keeps bidirectional attention exact.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM


@pytest.mark.quick
def test_causal_lm_two_lengths_one_executable():
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=32,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    ids10 = paddle.to_tensor(rng.randint(0, 128, (2, 10)))
    ids13 = paddle.to_tensor(rng.randint(0, 128, (2, 13)))
    with paddle.no_grad():
        ref10 = m(ids10).numpy()
        ref13 = m(ids13).numpy()
        static = jit.to_static(m.forward, seq_buckets=(16, 32))
        out10 = static(ids10).numpy()
        out13 = static(ids13).numpy()
    # both lengths pad to bucket 16 → ONE cache entry / executable
    assert len(static._cache) == 1
    assert out10.shape == ref10.shape and out13.shape == ref13.shape
    np.testing.assert_allclose(out10, ref10, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out13, ref13, rtol=1e-5, atol=1e-5)


def test_longer_length_next_bucket():
    paddle.seed(1)
    cfg = GPT2Config(vocab_size=64, hidden_size=32, num_hidden_layers=1,
                     num_attention_heads=2, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(1)
    static = jit.to_static(m.forward, seq_buckets=(8, 16, 32))
    with paddle.no_grad():
        for s in (5, 7, 12, 30):
            ids = paddle.to_tensor(rng.randint(0, 64, (1, s)))
            out = static(ids).numpy()
            ref = m(ids).numpy()
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    # lengths 5,7 → bucket 8; 12 → 16; 30 → 32: exactly three executables
    assert len(static._cache) == 3


def test_bidirectional_tail_mask_synthesized():
    """Non-causal attention needs the tail keys blocked; seq_mask_arg
    makes the wrapper synthesize the keep-mask."""
    paddle.seed(2)
    lin = nn.Linear(16, 16)

    def encode(x, attn_mask=None):
        q = lin(x)
        return nn.functional.scaled_dot_product_attention(
            q.reshape([1, x.shape[1], 2, 8]),
            q.reshape([1, x.shape[1], 2, 8]),
            q.reshape([1, x.shape[1], 2, 8]),
            attn_mask=attn_mask, is_causal=False)

    rng = np.random.RandomState(2)
    x = paddle.to_tensor(rng.randn(1, 11, 16).astype("float32"))
    with paddle.no_grad():
        ref = encode(x).numpy()
        static = jit.to_static(encode, seq_buckets=(16,),
                               seq_mask_arg="attn_mask")
        out = static(x).numpy()
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_caller_mask_padded_to_bucket():
    """A caller's own additive mask is padded with blocking values."""
    paddle.seed(3)
    lin = nn.Linear(16, 16)

    def encode(x, attn_mask=None):
        q = lin(x)
        return nn.functional.scaled_dot_product_attention(
            q.reshape([1, x.shape[1], 2, 8]),
            q.reshape([1, x.shape[1], 2, 8]),
            q.reshape([1, x.shape[1], 2, 8]),
            attn_mask=attn_mask, is_causal=False)

    rng = np.random.RandomState(3)
    s = 10
    x = paddle.to_tensor(rng.randn(1, s, 16).astype("float32"))
    mask = paddle.to_tensor((rng.randn(1, 1, s, s) * 0.5).astype("float32"))
    with paddle.no_grad():
        ref = encode(x, attn_mask=mask).numpy()
        static = jit.to_static(encode, seq_buckets=(16,),
                               seq_mask_arg="attn_mask")
        out = static(x, attn_mask=mask).numpy()
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_exact_bucket_size_passthrough():
    """A length already at a bucket boundary skips padding entirely."""
    paddle.seed(4)
    cfg = GPT2Config(vocab_size=64, hidden_size=32, num_hidden_layers=1,
                     num_attention_heads=2, max_position_embeddings=32,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(4).randint(0, 64, (1, 16)))
    with paddle.no_grad():
        static = jit.to_static(m.forward, seq_buckets=(16,))
        out = static(ids).numpy()
        ref = m(ids).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_donate_args_inference_and_grad_guard():
    """to_static(donate_args=...): the donated input buffer is consumed
    (serving caches update in place); grad-mode calls at a donating
    signature raise instead of corrupting the tape."""
    def step(x, cache):
        new_cache = cache + x.sum()
        return x * 2.0, new_cache

    fn = jit.to_static(step, donate_args=(1,))
    x = paddle.to_tensor(np.ones((4,), np.float32))
    with paddle.no_grad():
        cache = paddle.to_tensor(np.zeros((8,), np.float32))
        fn(x, cache)  # call 1: eager discovery
        cache2 = paddle.to_tensor(np.zeros((8,), np.float32))
        out, new_cache = fn(x, cache2)  # call 2: compiled + donated
        np.testing.assert_allclose(new_cache.numpy(), np.full((8,), 4.0))
        assert cache2._data.is_deleted()  # buffer consumed by donation
    # grad mode at the same signature must refuse loudly
    xg = paddle.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    cache3 = paddle.to_tensor(np.zeros((8,), np.float32))
    with pytest.raises(RuntimeError, match="inference-only"):
        fn(xg, cache3)
