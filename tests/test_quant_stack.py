"""Serving-path quantization stack (round 22): int8 KV pages, quantized
tier blobs, and int8 serving weights, wired end-to-end.

Five layers, <60s total:

  * observers + convert — the all-zero-first-batch HistObserver
    regression (degenerate [0, 1e-8] edges must re-initialize on the
    first nonzero batch), PTQ ``convert()`` round-trip error bounds
    across shapes/seeds, per-channel at least as tight as per-tensor,
    and the ``QuantedConv2D`` swap-walk reaching nested sublayers;
  * serving_quantize — quality bound on the sharpened tiny GPT (the
    40-step data-seed-0 recipe: greedy token-match >= 0.99, end-to-end
    logit MAE <= 0.05 — measured ~0.005), the per-layer fp fallback
    tripping on a planted per-tensor outlier (and NOT tripping
    channelwise), mesh ``serving_weight_spec`` placement staying
    numerically inert, and the ``quant.*`` counters;
  * kv_quant — constructor guards (whitelist, calibration prerequisite,
    the cache_quant/draft_model exclusions), int8 page pools decoding
    within the match bound vs fp, and the ``serving.kv_quant_*`` gauges;
  * tier_quant — demoted chains stored as int8+scale blobs at ~1/4 the
    raw bytes (spill counters), promotion dequantizing on install
    (``quant.dequant_seconds`` observed), hit parity and generated-token
    agreement with the fp-tier run, zero-leak ``audit_pages`` +
    ``audit_tiers``, the calibration digest in ``model_identity``, and
    the pause -> quantized demotion -> corrupt-blob -> resume drill
    degrading to an audited, token-exact full prefill;
  * tooling — the ``quant:`` bench_guard lane gating BOTH the decode
    tokens/s headline and the synthesized token-match series,
    ``telemetry_dump --prefix-stats`` spill columns (legacy line
    unchanged when the counters are absent), and the ledger's
    ``dequant`` waste row.
"""
import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference.serving import PagedContinuousBatcher
from paddle_tpu.inference.session_store import model_identity
from paddle_tpu.quantization import (PTQ, AbsmaxObserver,
                                     ChannelAbsmaxObserver,
                                     FakeQuanterWithAbsMaxObserver,
                                     HistObserver, QAT, QuantConfig,
                                     QuantedConv2D, QuantedLinear,
                                     serving_quantize)

pytestmark = pytest.mark.quant

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BLOCK = 16


def _np(t):
    return np.asarray(t._data)


@pytest.fixture(scope="module")
def sharp_lm():
    """Briefly trained tiny GPT: random-init argmax near-ties flip under
    any perturbation and would measure the MODEL, not the quantizer —
    40 AdamW steps on a fixed seed-0 batch sharpen the logits enough
    that the int8 stack's greedy decode matches fp exactly (the recipe
    the bench's weights arm uses)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    rng = np.random.RandomState(0)
    data = paddle.to_tensor(rng.randint(0, 128, (4, 33)))
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    for _ in range(40):
        logits = m(data[:, :-1])
        loss = F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                               data[:, 1:].reshape([-1]))
        loss.backward()
        opt.step()
        opt.clear_grad()
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _no_stale_calibration(sharp_lm):
    """kv_quant tests calibrate the shared model; everything else (and
    every tier_quant constructor) requires scales to be absent."""
    sharp_lm.calibrate_cachekv_int8(None)
    yield
    sharp_lm.calibrate_cachekv_int8(None)


def _ref(lm, prompt, n):
    return np.asarray(lm.generate(np.asarray(prompt).reshape(1, -1),
                                  max_new_tokens=n)).reshape(-1)


def _counter(name):
    from paddle_tpu.observability.metrics import get_registry
    return sum(s.get("value", 0) for s in get_registry().snapshot()
               if s.get("name") == name)


def _gauge(name):
    from paddle_tpu.observability.metrics import get_registry
    for s in get_registry().snapshot():
        if s.get("name") == name and s.get("type") == "gauge":
            return s.get("value")
    return None


def _hist_count(name):
    from paddle_tpu.observability.metrics import get_registry
    return sum(s.get("count", 0) for s in get_registry().snapshot()
               if s.get("name") == name)


# -- observers + convert ------------------------------------------------------

def test_hist_observer_survives_all_zero_first_batch():
    data = np.random.RandomState(0).randn(4096).astype(np.float32)
    ref = HistObserver(bins_count=256)
    ref.observe(data)
    # regression: a zeros-only first batch used to pin the edges to
    # [0, 1e-8]; every later re-bin collapsed the accumulated mass into
    # bin 0 and scales() returned ~1e-8 no matter the real data
    obs = HistObserver(bins_count=256)
    obs.observe(np.zeros(512, np.float32))
    obs.observe(data)
    assert float(obs.scales()) > 0.1
    assert float(obs.scales()) == pytest.approx(float(ref.scales()),
                                                rel=0.05)
    # zeros-only stays at the defined fallback scale
    z = HistObserver(bins_count=256)
    z.observe(np.zeros(64, np.float32))
    assert float(z.scales()) == 1.0


@pytest.mark.parametrize("seed,shape", [(0, (8, 16)), (1, (16, 64)),
                                        (2, (7, 33))])
def test_ptq_convert_roundtrip_error_bound(seed, shape):
    rng = np.random.RandomState(seed)
    lin = nn.Linear(*shape)
    net = nn.Sequential(lin)
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                          weight=AbsmaxObserver()))
    pnet = ptq.quantize(net)
    x = paddle.to_tensor(rng.randn(32, shape[0]).astype(np.float32))
    ref = _np(net(x))
    inet = ptq.convert(pnet)
    out = _np(inet(x))
    # absmax int8: per-element weight error <= scale/254; the matmul
    # accumulates ~in_features of them — bound the output rel error
    denom = max(float(np.abs(ref).max()), 1e-6)
    assert float(np.abs(out - ref).max()) / denom < 0.05
    # per-output-channel scales can only tighten the reconstruction
    cptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                           weight=ChannelAbsmaxObserver()))
    cnet = cptq.convert(cptq.quantize(net))
    w = _np(lin.weight)
    for layers in (inet, cnet):
        conv = [l for l in layers.sublayers()
                if type(l).__name__ == "_ConvertedLinear"][0]
        sc = (_np(conv.scale) if not isinstance(conv.scale, float)
              else conv.scale)
        werr = np.abs(_np(conv.w_int8).astype(np.float32)
                      * (sc / conv._qmax) - w).max()
        if layers is inet:
            per_tensor_err = werr
    assert werr <= per_tensor_err + 1e-7


def test_quanted_conv2d_swap_walk_reaches_nested_layers():
    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(2, 4, 3, padding=1)
            self.act = nn.ReLU()

        def forward(self, x):
            return self.act(self.conv(x))

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.stem = nn.Conv2D(1, 2, 3, padding=1)
            self.block = Block()
            self.head = nn.Linear(4 * 8 * 8, 5)

        def forward(self, x):
            h = self.block(self.stem(x))
            return self.head(h.reshape([x.shape[0], -1]))

    net = Net()
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                      weight=FakeQuanterWithAbsMaxObserver())
    qnet = QAT(cfg).quantize(net)
    kinds = [type(l).__name__ for l in qnet.sublayers()]
    assert kinds.count("QuantedConv2D") == 2     # stem AND nested block
    assert kinds.count("QuantedLinear") == 1
    assert isinstance(qnet.block.conv, QuantedConv2D)
    assert isinstance(qnet.head, QuantedLinear)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 1, 8, 8).astype(np.float32))
    assert tuple(qnet(x).shape) == (2, 5)
    # the original model is untouched by the walk
    assert not any(isinstance(l, (QuantedConv2D, QuantedLinear))
                   for l in net.sublayers())


# -- serving_quantize ---------------------------------------------------------

def test_serving_quantize_quality_bound_and_report(sharp_lm):
    before_q = _counter("quant.layers_quantized")
    before_f = _counter("quant.layers_fallback")
    q = serving_quantize(sharp_lm)
    rep = q._serving_quant_report
    assert rep["layers_quantized"] >= 1 and rep["bytes_saved"] > 0
    assert rep["err_bound"] == pytest.approx(0.02)
    assert _counter("quant.layers_quantized") - before_q == \
        rep["layers_quantized"]
    assert _counter("quant.layers_fallback") - before_f == \
        rep["layers_fallback"]
    # documented quality bound: logit MAE <= 0.05 (measured ~0.005 on
    # this recipe) and greedy token-match >= 0.99 vs the fp model
    x = paddle.to_tensor(np.random.RandomState(5).randint(0, 128, (4, 24)))
    with paddle.no_grad():
        mae = float(np.abs(_np(sharp_lm(x)) - _np(q(x))).mean())
    assert mae <= 0.05, mae
    match = []
    with paddle.no_grad():
        for s in range(3):
            p = np.random.RandomState(100 + s).randint(0, 128, (20,))
            match.append(np.mean(_ref(sharp_lm, p, 10)[20:]
                                 == _ref(q, p, 10)[20:]))
    assert float(np.mean(match)) >= 0.99, match


def test_serving_quantize_fallback_trips_on_planted_outlier():
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    w = _np(net[0].weight).copy()
    w[:, 0] *= 200.0          # one huge column starves per-tensor scales
    net[0].weight.set_value(paddle.to_tensor(w.astype(np.float32)))
    per_tensor = serving_quantize(net, channelwise=False)
    rep = per_tensor._serving_quant_report
    assert rep["layers_fallback"] >= 1
    assert rep["layers"]["0"]["quantized"] is False
    # per-channel scales isolate the outlier column: same layer passes
    chan = serving_quantize(net, channelwise=True)
    crep = chan._serving_quant_report
    assert crep["layers"]["0"]["quantized"] is True
    assert crep["layers"]["0"]["rel_err"] < rep["layers"]["0"]["rel_err"]


def test_serving_quantize_mesh_placement_is_numerically_inert():
    from paddle_tpu.distributed.mesh import MeshRuntime
    paddle.seed(9)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    rt = MeshRuntime({"tensor": 2})
    assert rt.serving_weight_spec((16, 32)) == (None, "tensor")
    plain = serving_quantize(net)
    placed = serving_quantize(net, mesh=rt)
    assert placed._serving_quant_report["layers_quantized"] == \
        plain._serving_quant_report["layers_quantized"]
    x = paddle.to_tensor(np.random.RandomState(3)
                         .randn(4, 16).astype(np.float32))
    with paddle.no_grad():
        np.testing.assert_allclose(_np(plain(x)), _np(placed(x)),
                                   rtol=1e-5, atol=1e-6)


# -- kv_quant: int8 KV pages --------------------------------------------------

def test_kv_quant_constructor_guards(sharp_lm):
    def mk(**kw):
        kw.setdefault("max_batch", 2)
        kw.setdefault("s_max", 64)
        kw.setdefault("block_size", BLOCK)
        kw.setdefault("compile", False)
        return PagedContinuousBatcher(sharp_lm, **kw)

    with pytest.raises(ValueError, match="unknown kv_quant"):
        mk(kv_quant="int4")
    with pytest.raises(ValueError, match="calibrate_cachekv_int8"):
        mk(kv_quant="int8")      # no calibrated scales on the model
    with pytest.raises(ValueError, match="pick one"):
        mk(kv_quant="int8", cache_quant="dynamic_int8")
    with pytest.raises(ValueError, match="unknown tier_quant"):
        mk(tier_quant="fp8")
    with pytest.raises(ValueError, match="prefix_cache"):
        mk(tier_quant="int8")    # tier blobs need the tiered cache
    sharp_lm.calibrate_cachekv_int8(
        np.random.RandomState(0).randint(0, 128, (2, 32)))
    with pytest.raises(ValueError, match="redundant"):
        mk(tier_quant="int8", prefix_cache=True, host_kv_gib=0.01)
    with pytest.raises(ValueError, match="draft_model"):
        mk(kv_quant="int8", draft_model=sharp_lm)


def test_kv_quant_int8_pages_match_fp_within_bound(sharp_lm):
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, 128, (20,)).astype(np.int64)
               for _ in range(3)]

    def run(**kw):
        bt = PagedContinuousBatcher(sharp_lm, max_batch=2, s_max=64,
                                    block_size=BLOCK, compile=False, **kw)
        try:
            with paddle.no_grad():
                rids = [bt.submit(p, 6) for p in prompts]
                res = bt.run_until_done(max_steps=60000)
            pool_dtype = str(bt._state["layers"][0][0].dtype)
            bt.audit_pages()
            return [res[r] for r in rids], pool_dtype
        finally:
            bt.close()

    fp_outs, fp_dtype = run()
    assert "int8" not in fp_dtype
    sharp_lm.calibrate_cachekv_int8(
        np.random.RandomState(0).randint(0, 128, (2, 32)))
    q_outs, q_dtype = run(kv_quant="int8")
    assert "int8" in q_dtype
    assert _gauge("serving.kv_quant_enabled") == 1
    assert _gauge("serving.kv_quant_bytes_saved") > 0
    match = float(np.mean([np.mean(a[20:] == b[20:])
                           for a, b in zip(fp_outs, q_outs)]))
    assert match >= 0.99, match


# -- tier_quant: int8 demotion blobs ------------------------------------------

def _tiered(lm, tmp, host_bytes, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("s_max", 96)
    kw.setdefault("block_size", BLOCK)
    kw.setdefault("n_pages", 12)
    kw.setdefault("compile", False)
    kw.setdefault("policy", "ondemand")
    kw.setdefault("prefix_cache", True)
    kw.setdefault("host_kv_gib", host_bytes / 2**30)
    return PagedContinuousBatcher(lm, **kw)


def _churn(bt, seed=3, n=8, length=51):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        bt.submit(rng.randint(0, 128, (length,)).astype(np.int64), 4)
    bt.run_until_done(max_steps=60000)


def test_tier_quant_spill_capacity_promote_and_audits(sharp_lm, tmp_path):
    rng = np.random.RandomState(17)
    prefixes = [rng.randint(0, 128, (3 * BLOCK,)).astype(np.int64)
                for _ in range(4)]
    prompts = [np.concatenate([prefixes[i % 4],
                               rng.randint(0, 128, (5,))]).astype(np.int64)
               for i in range(8)]

    def run(tier_quant):
        raw0 = _counter("serving.prefix_spill_raw_bytes")
        blob0 = _counter("serving.prefix_spill_blob_bytes")
        bt = _tiered(sharp_lm, tmp_path, host_bytes=6 * 16384,
                     tier_quant=tier_quant)
        try:
            with paddle.no_grad():
                for p in prefixes:
                    bt.submit(p, 4)
                bt.run_until_done(max_steps=60000)
                rids = [bt.submit(p, 4) for p in prompts]
                res = bt.run_until_done(max_steps=60000)
            st = bt.prefix_cache.stats()
            bt.audit_pages()                     # raises on any leak
            rep = bt.prefix_cache.audit_tiers()  # raises on byte drift
            return {
                "outs": [res[r] for r in rids],
                "raw": _counter("serving.prefix_spill_raw_bytes") - raw0,
                "blob": _counter("serving.prefix_spill_blob_bytes")
                        - blob0,
                "promotions": st["promotions"],
                "failures": st["promotion_failures"],
                "host_bytes": rep.get("host_bytes", 0),
            }
        finally:
            bt.close()

    fp = run(None)
    dq0 = _hist_count("quant.dequant_seconds")
    q = run("int8")
    assert fp["raw"] == fp["blob"]               # fp blobs spill as-is
    assert q["raw"] > 0 and q["blob"] > 0
    assert q["raw"] / q["blob"] >= 3.5           # int8 codes + scales
    assert q["promotions"] > 0 and q["failures"] == 0
    assert _hist_count("quant.dequant_seconds") > dq0
    if fp["host_bytes"] and q["host_bytes"]:
        assert q["host_bytes"] < fp["host_bytes"]
    match = float(np.mean([np.mean(a[3 * BLOCK:] == b[3 * BLOCK:])
                           for a, b in zip(fp["outs"], q["outs"])]))
    assert match >= 0.99, match


def test_model_identity_folds_calibration_digest(sharp_lm):
    base = model_identity(sharp_lm)
    assert ":q" not in base
    sharp_lm.calibrate_cachekv_int8(
        np.random.RandomState(0).randint(0, 128, (2, 32)))
    with_scales = model_identity(sharp_lm)
    assert with_scales.startswith(base) and ":q" in with_scales
    assert model_identity(sharp_lm) == with_scales     # stable
    # calibration drift changes the identity -> a durable resume under
    # different scales degrades to a full re-prefill, never a wrong
    # dequantize
    sharp_lm._cachekv_scales[0] = {
        k: np.asarray(v) * 1.5
        for k, v in sharp_lm._cachekv_scales[0].items()}
    assert model_identity(sharp_lm) != with_scales


def test_session_resume_drill_quantized_demotion_corrupt_blob(
        sharp_lm, tmp_path):
    """Pause -> churn demotes the pinned chain as int8 blobs all the way
    to disk -> every blob is corrupted -> resume still resolves the
    manifest, every promotion fails (audited), and the continuation
    degrades to a full fp prefill that is token-exact vs the
    uninterrupted conversation."""
    rng = np.random.RandomState(23)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (5,)).astype(np.int64)
    base1 = _ref(sharp_lm, prompt, 6)
    base2 = _ref(sharp_lm, np.concatenate([base1, cont]), 6)

    disk = os.path.join(str(tmp_path), "kv_disk")
    bt = _tiered(sharp_lm, tmp_path, host_bytes=2 * 4400,  # ~2 int8 blobs
                 tier_quant="int8", disk_kv_dir=disk, disk_kv_gib=0.01,
                 session_store=os.path.join(str(tmp_path), "sessions"))
    try:
        with paddle.no_grad():
            rid = bt.submit(prompt, 6)
            out1 = bt.run_until_done(max_steps=60000)[rid]
            np.testing.assert_array_equal(out1, base1)
            assert bt.pause_session("conv", out1) is True
            _churn(bt)
            pins = bt._session_pins["conv"]
            res = {n.residency for n in pins}
            # pin-through-demotion held: off device, never dropped
            assert res <= {"host", "disk"} and res, res
            assert bt.prefix_cache.stats()["session_pin_drops"] == 0
            # corrupt every blob in BOTH tiers (recorded sizes stay, so
            # the byte-accounting audit still balances)
            blobs = glob.glob(os.path.join(disk, "kv_*.npz"))
            assert blobs
            for p in blobs:
                with open(p, "wb") as f:
                    f.write(b"not an npz")
            ht = bt.prefix_cache.host_tier
            for k in list(ht.keys()):
                ht._blobs[k] = (object(), ht.nbytes_of(k))
            toks = bt.resume_session("conv")
            np.testing.assert_array_equal(toks, out1)  # manifest path
            fails0 = bt.prefix_cache.stats()["promotion_failures"]
            rid2 = bt.submit(np.concatenate([toks, cont]), 6)
            out2 = bt.run_until_done(max_steps=60000)[rid2]
            # degraded to full prefill -> fp numerics -> bitwise exact
            np.testing.assert_array_equal(out2, base2)
            assert bt.prefix_cache.stats()["promotion_failures"] > fails0
            bt.audit_pages()
    finally:
        bt.close()


def test_session_resume_rides_quantized_promotion(sharp_lm, tmp_path):
    """Same drill without corruption: the resume promotes the int8
    blobs back (dequantizing on install) and the continuation stays
    within the quality bound of the uninterrupted conversation."""
    rng = np.random.RandomState(29)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (5,)).astype(np.int64)
    base1 = _ref(sharp_lm, prompt, 6)
    base2 = _ref(sharp_lm, np.concatenate([base1, cont]), 6)

    bt = _tiered(sharp_lm, tmp_path, host_bytes=6 * 16384,
                 tier_quant="int8",
                 session_store=os.path.join(str(tmp_path), "sessions"))
    try:
        with paddle.no_grad():
            rid = bt.submit(prompt, 6)
            out1 = bt.run_until_done(max_steps=60000)[rid]
            np.testing.assert_array_equal(out1, base1)
            assert bt.pause_session("conv", out1) is True
            _churn(bt)
            pins = bt._session_pins["conv"]
            assert "gone" not in {n.residency for n in pins}
            toks = bt.resume_session("conv")
            np.testing.assert_array_equal(toks, out1)
            rid2 = bt.submit(np.concatenate([toks, cont]), 6)
            out2 = bt.run_until_done(max_steps=60000)[rid2]
            assert bt.prefix_cache.stats()["promotions"] > 0
            # quantized promotion is an approximation: the bound is the
            # match rate, not bitwise equality (fp fallbacks stay exact)
            assert float(np.mean(out2[-6:] == base2[-6:])) >= 0.99
            bt.audit_pages()
            bt.prefix_cache.audit_tiers()
    finally:
        bt.close()


# -- tooling ------------------------------------------------------------------

def test_bench_guard_quant_lane_gates_speed_and_match(tmp_path):
    hist = [410.0, 430.0, 425.0, 440.0]

    def write(rnd, value, match):
        (tmp_path / f"BENCH_QUANT_r{rnd:02d}.json").write_text(
            json.dumps({"metric": "quant_serving_decode_tokens_per_sec",
                        "value": value, "unit": "tokens/s",
                        "detail": {"tpu": False,
                                   "token_match_rate": match}}))

    for i, v in enumerate(hist):
        write(i, v, 1.0)

    def guard():
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_guard.py"),
             "--check", "--dir", str(tmp_path), "--json"],
            capture_output=True, text=True)

    ok = guard()
    assert ok.returncode == 0, ok.stdout + ok.stderr
    report = json.loads(ok.stdout)
    speed_key = "quant:quant_serving_decode_tokens_per_sec/cpu"
    match_key = "quant:quant_token_match_rate/cpu"
    assert report["series"][speed_key]["status"] == "pass"
    assert report["series"][match_key]["status"] == "pass"
    assert all(k.startswith("quant:") for k in report["series"])
    # a tokens/s collapse gates
    write(4, 0.8 * hist[-1], 1.0)
    bad = guard()
    assert bad.returncode == 1
    assert json.loads(bad.stdout)["series"][speed_key]["status"] == \
        "regression"
    # a QUALITY collapse gates even with the speed headline flat: the
    # synthesized match series fails as loudly as the tokens/s one
    write(4, hist[-1], 0.85)
    bad2 = guard()
    assert bad2.returncode == 1
    assert json.loads(bad2.stdout)["series"][match_key]["status"] == \
        "regression"


def _dump_prefix_stats(tmp_path, series):
    """Run telemetry_dump --prefix-stats over a hand-written one-rank
    spool holding exactly ``series`` (the process-global registry would
    leak counters from the serving tests above)."""
    import importlib.util
    spool = tmp_path / "rank00000.jsonl"
    lines = [{"kind": "meta", "rank": 0, "world_size": 1, "host": "h",
              "pid": 1, "t": 0.0},
             {"kind": "metrics", "t": 1.0, "series": series}]
    spool.write_text("".join(json.dumps(l) + "\n" for l in lines))
    spec = importlib.util.spec_from_file_location(
        "telemetry_dump", os.path.join(REPO, "tools", "telemetry_dump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, spool


def test_telemetry_dump_prefix_stats_spill_columns(tmp_path, capsys):
    base = [{"name": "serving.prefix_hit_tokens", "type": "counter",
             "value": 80},
            {"name": "serving.prefix_miss_tokens", "type": "counter",
             "value": 20}]
    quant = base + [
        {"name": "serving.prefix_spill_raw_bytes", "type": "counter",
         "value": 65536},
        {"name": "serving.prefix_spill_blob_bytes", "type": "counter",
         "value": 16640},
        {"name": "serving.kv_host_bytes", "type": "gauge",
         "value": 16640}]
    mod, _ = _dump_prefix_stats(tmp_path, quant)
    assert mod.main(["--fleet", str(tmp_path), "--prefix-stats"]) == 0
    out = capsys.readouterr().out
    line = next(l for l in out.splitlines()
                if l.startswith("# fleet prefix-stats "))
    stats = json.loads(line[len("# fleet prefix-stats "):])
    assert stats["spill_raw_bytes"] == 65536
    assert stats["spill_blob_bytes"] == 16640
    assert stats["spill_compression"] == pytest.approx(3.94, abs=0.01)
    assert stats["host_blob_bytes"] == 16640

    # legacy fleets (no spill counters) keep the line byte-identical:
    # none of the new columns appear
    mod2, _ = _dump_prefix_stats(tmp_path, base)
    assert mod2.main(["--fleet", str(tmp_path), "--prefix-stats"]) == 0
    out2 = capsys.readouterr().out
    line2 = next(l for l in out2.splitlines()
                 if l.startswith("# fleet prefix-stats "))
    stats2 = json.loads(line2[len("# fleet prefix-stats "):])
    assert "spill_raw_bytes" not in stats2
    assert "spill_compression" not in stats2
    assert "host_blob_bytes" not in stats2
    assert stats2["hit_rate"] == 0.8


def test_ledger_charges_dequant_waste():
    from paddle_tpu.observability.ledger import (GoodputLedger,
                                                 WASTE_CATEGORIES)
    assert "dequant" in WASTE_CATEGORIES

    class Stub:
        def snapshot(self):
            return [{"name": "quant.dequant_seconds", "type": "histogram",
                     "sum": 0.25, "count": 3},
                    {"name": "other.series", "type": "histogram",
                     "sum": 9.0, "count": 1}]

    led = GoodputLedger()
    assert led.add_dequant_from_registry(Stub()) == pytest.approx(0.25)
    assert led.waste["dequant"] == pytest.approx(0.25)
    assert led.chip_s == pytest.approx(0.25)
    assert led.goodput_frac == pytest.approx(0.0)   # all-waste ledger
    # empty registry is a no-op
    led2 = GoodputLedger()

    class Empty:
        def snapshot(self):
            return []

    assert led2.add_dequant_from_registry(Empty()) == 0.0
    assert led2.waste["dequant"] == 0.0
