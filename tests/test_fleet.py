"""Fleet telemetry plane + flight recorder (ISSUE 9, tier-1 ``fleet``).

Covers, bottom-up:

  * the binary ring journal — wraparound, reopen (epoch bump + seq
    continuity, geometry adopted from the file), corrupt-slot skip;
  * registry-wide default labels — no ``rank`` label in a single-process
    world (byte-identical output), env-stamped when the launcher env is
    present, explicit overrides;
  * dropped-span surfacing — ``TraceRecorder.dropped``, chrome-trace
    metadata, and the one-time warning;
  * shard aggregation over synthetic rank shards — counter sum,
    histogram bucket merge with re-estimated quantiles, per-rank gauges,
    skew gauges, straggler / desync / missing-rank findings;
  * ``tools/bench_guard.py --relay`` — the wedged-relay gate;
  * the end-to-end 3-process chaos drill: ``kill_rank`` takes rank 2
    down mid-``all_reduce``; survivors' shards aggregate, the typed
    findings name the collective and the rank, and ``tools/blackbox.py
    postmortem`` replays the victim's ring (< 60s wall clock).
"""
import json
import logging
import os
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLEET_WORKER = os.path.join(REPO, "tests", "helpers",
                            "mp_fleet_worker.py")


# -- flight recorder: ring journal -------------------------------------------

def test_ring_wraparound_keeps_last_n(tmp_path):
    from paddle_tpu.observability.flight import FlightRecorder, read_ring
    path = str(tmp_path / "r.ring")
    rec = FlightRecorder(path, slots=8, slot_size=128, rank=3)
    for i in range(20):
        rec.record("tick", i=i)
    rec.close()
    events = read_ring(path)
    assert [e["i"] for e in events] == list(range(12, 20))
    assert [e["_seq"] for e in events] == list(range(12, 20))
    assert all(e["_rank"] == 3 for e in events)


def test_ring_reopen_bumps_epoch_and_continues_seq(tmp_path):
    from paddle_tpu.observability.flight import FlightRecorder, read_ring
    path = str(tmp_path / "r.ring")
    rec = FlightRecorder(path, slots=8, slot_size=128, rank=0)
    for i in range(3):
        rec.record("before", i=i)
    assert rec.epoch == 0
    rec.close()
    # reopen with DIFFERENT ctor geometry: the file's shape wins
    rec2 = FlightRecorder(path, slots=64, slot_size=512, rank=0)
    assert rec2.nslots == 8 and rec2.slot_size == 128
    assert rec2.epoch == 1
    assert rec2.seq == 3          # cursor recovered by max-seq scan
    rec2.record("after", i=99)
    rec2.close()
    events = read_ring(path)
    assert [e["kind"] for e in events] == ["before"] * 3 + ["after"]
    assert [e["_epoch"] for e in events] == [0, 0, 0, 1]
    assert events[-1]["_seq"] == 3


def test_ring_corrupt_slot_skipped_not_fatal(tmp_path):
    from paddle_tpu.observability.flight import FlightRecorder, read_ring
    path = str(tmp_path / "r.ring")
    rec = FlightRecorder(path, slots=8, slot_size=128, rank=0)
    for i in range(4):
        rec.record("tick", i=i)
    rec.close()
    with open(path, "r+b") as f:      # scribble over slot 1 (seq 1)
        f.seek(64 + 1 * 128)
        f.write(b"\xff" * 64)
    events = read_ring(path)
    assert [e["i"] for e in events] == [0, 2, 3]


def test_ring_oversized_payload_truncates(tmp_path):
    from paddle_tpu.observability.flight import FlightRecorder, read_ring
    path = str(tmp_path / "r.ring")
    rec = FlightRecorder(path, slots=4, slot_size=64, rank=0)
    rec.record("big", blob="x" * 500)
    rec.close()
    (ev,) = read_ring(path)
    assert ev["kind"] == "big" and ev.get("truncated") is True


# -- metrics: registry-wide default labels -----------------------------------

@pytest.fixture
def fresh_env(monkeypatch):
    from paddle_tpu.observability.fleet import reset_spool
    from paddle_tpu.observability.flight import reset_flight
    monkeypatch.delenv("PADDLE_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("PADDLE_TRAINERS_NUM", raising=False)
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    reset_spool()
    reset_flight()
    yield monkeypatch
    reset_spool()
    reset_flight()


def test_default_labels_absent_single_process(fresh_env):
    from paddle_tpu.observability.metrics import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("fleettest.c").inc(2)
    reg.gauge("fleettest.g").set(1.5)
    for s in reg.snapshot(include_native=False):
        assert "rank" not in s["labels"], s


def test_default_labels_stamp_rank_from_env(fresh_env):
    from paddle_tpu.observability.metrics import MetricsRegistry
    fresh_env.setenv("PADDLE_TRAINERS_NUM", "4")
    fresh_env.setenv("PADDLE_TRAINER_ID", "2")
    reg = MetricsRegistry()
    reg.counter("fleettest.c").inc(1)
    reg.histogram("fleettest.h").observe(0.1)
    snap = reg.snapshot(include_native=False)
    assert snap and all(s["labels"]["rank"] == "2" for s in snap)
    # explicit series labels survive the merge
    reg.counter("fleettest.lc", labelnames=("op",)).labels(op="x").inc()
    snap = reg.snapshot(include_native=False)
    lc = next(s for s in snap if s["name"] == "fleettest.lc")
    assert lc["labels"] == {"rank": "2", "op": "x"}


def test_default_labels_explicit_override(fresh_env):
    from paddle_tpu.observability.metrics import MetricsRegistry
    reg = MetricsRegistry()
    reg.set_default_labels(rank="7", host="hX")
    reg.counter("fleettest.c").inc()
    (s,) = reg.snapshot(include_native=False)
    assert s["labels"] == {"rank": "7", "host": "hX"}
    reg.clear_default_labels()
    (s,) = reg.snapshot(include_native=False)
    assert s["labels"] == {}


# -- trace recorder: dropped-span surfacing ----------------------------------

def test_dropped_spans_property_metadata_and_one_time_warning(
        fresh_env, caplog):
    from paddle_tpu.observability.trace_context import (TraceRecorder,
                                                        TraceSpan)
    rec = TraceRecorder(capacity=2)
    spans = [TraceSpan(f"{i:016x}", "s") for i in range(4)]
    with caplog.at_level(logging.WARNING,
                         logger="paddle_tpu.observability.trace_context"):
        for sp in spans:
            rec.record(sp)
    assert rec.dropped == 2
    assert rec.capacity == 2
    warnings = [r for r in caplog.records
                if "trace recorder full" in r.getMessage()]
    assert len(warnings) == 1            # one-time, not per drop
    doc = rec.to_chrome()
    assert doc["metadata"] == {"dropped_spans": 2, "capacity": 2}
    rec.clear()
    assert rec.dropped == 0
    assert rec.to_chrome()["metadata"]["dropped_spans"] == 0


# -- fleet aggregation over synthetic shards ---------------------------------

def _write_shard(dirpath, rank, records, world=3):
    path = os.path.join(dirpath, f"rank{rank:05d}.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "meta", "rank": rank,
                            "world_size": world, "host": "h",
                            "pid": 100 + rank, "t": 0.0}) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")
    return path


def _hist_series(counts, count, total, mn, mx):
    return {"name": "fleettest.lat", "type": "histogram", "labels": {},
            "buckets": [1.0, 2.0], "bucket_counts": counts,
            "count": count, "sum": total, "min": mn, "max": mx,
            "quantiles": {}}


def test_fleet_series_counter_sum_histogram_merge_gauge_per_rank(
        tmp_path):
    from paddle_tpu.observability.fleet import FleetAggregator
    d = str(tmp_path)
    _write_shard(d, 0, [{"kind": "metrics", "t": 1.0, "series": [
        {"name": "fleettest.c", "type": "counter",
         "labels": {"rank": "0", "op": "x"}, "value": 2},
        {"name": "fleettest.g", "type": "gauge", "labels": {"rank": "0"},
         "value": 1.5, "peak": 2.0},
        _hist_series([1, 2, 0], 3, 4.0, 0.5, 1.8)]}])
    _write_shard(d, 1, [{"kind": "metrics", "t": 1.1, "series": [
        {"name": "fleettest.c", "type": "counter",
         "labels": {"rank": "1", "op": "x"}, "value": 3},
        {"name": "fleettest.g", "type": "gauge", "labels": {"rank": "1"},
         "value": 7.0, "peak": 7.0},
        _hist_series([0, 1, 3], 4, 9.0, 0.9, 5.0)]}])
    agg = FleetAggregator(d)
    assert agg.ranks() == [0, 1]
    series = {(s["name"], tuple(sorted(s["labels"].items()))): s
              for s in agg.fleet_series()}
    c = series[("fleettest.c", (("op", "x"),))]
    assert c["value"] == 5 and c["ranks"] == [0, 1]
    assert "rank" not in c["labels"]
    h = series[("fleettest.lat", ())]
    assert h["bucket_counts"] == [1, 3, 3]
    assert h["count"] == 7 and h["sum"] == pytest.approx(13.0)
    assert h["min"] == 0.5 and h["max"] == 5.0
    # merged cumulative buckets: p50 target 3.5 -> bound 2.0; p99 spills
    # past the finite buckets -> merged max
    assert h["quantiles"]["p50"] == 2.0
    assert h["quantiles"]["p99"] == 5.0
    g0 = series[("fleettest.g", (("rank", "0"),))]
    g1 = series[("fleettest.g", (("rank", "1"),))]
    assert g0["value"] == 1.5 and g1["value"] == 7.0
    rr = series[("fleet.ranks_reporting", ())]
    assert rr["value"] == 2.0


def test_findings_straggler_desync_missing_rank(tmp_path):
    from paddle_tpu.observability.fleet import FleetAggregator
    d = str(tmp_path)

    def coll(phase, op, seq, t):
        return {"kind": "collective", "phase": phase, "op": op,
                "seq": seq, "t": t}

    base = 100.0
    # seq 1: clean. seq 2: rank 1 arrives 0.5s late (straggler).
    # seq 3: rank 2 entered a DIFFERENT op (desync). seq 4: rank 2
    # enters and never exits, then goes silent while 0/1 keep writing.
    for rank, skew2 in ((0, 0.0), (1, 0.5), (2, 0.01)):
        recs = [coll("enter", "all_reduce", 1, base),
                coll("exit", "all_reduce", 1, base + 0.01),
                coll("enter", "all_reduce", 2, base + 1 + skew2),
                coll("exit", "all_reduce", 2, base + 1.6),
                coll("enter",
                     "broadcast" if rank == 2 else "all_reduce",
                     3, base + 2),
                coll("exit",
                     "broadcast" if rank == 2 else "all_reduce",
                     3, base + 2.1),
                coll("enter", "all_reduce", 4, base + 3)]
        if rank != 2:
            recs.append({"kind": "event", "name": "watchdog_abort",
                         "t": base + 8.0})
        _write_shard(d, rank, recs)
    agg = FleetAggregator(d)
    by_kind = {}
    for f in agg.findings():
        by_kind.setdefault(f.kind, []).append(f)
    (strag,) = by_kind["straggler"]
    assert strag.op == "all_reduce" and strag.seq == 2
    assert strag.rank == 1 and strag.skew_s == pytest.approx(0.5, 0.05)
    (desync,) = by_kind["desync"]
    assert desync.seq == 3 and desync.rank == 2
    assert desync.op == "broadcast"
    assert desync.detail["op_by_rank"]["2"] == "broadcast"
    (missing,) = by_kind["missing_rank"]
    assert missing.rank == 2 and missing.op == "all_reduce"
    assert missing.seq == 4
    assert missing.detail["silent_for_s"] == pytest.approx(5.0, 0.1)
    # survivors blocked in the same seq-4 enter are NOT missing
    assert all(f.rank == 2 for f in by_kind["missing_rank"])
    # skew gauges ride the fleet series
    skews = [s for s in agg.fleet_series()
             if s["name"] == "collective.skew_seconds"]
    assert {(s["labels"]["op"], s["labels"]["quantile"])
            for s in skews} >= {("all_reduce", "p50"),
                                ("all_reduce", "p99")}


def test_spool_roundtrip_and_torn_tail_tolerated(tmp_path, fresh_env):
    from paddle_tpu.observability import fleet
    fresh_env.setenv("PADDLE_TELEMETRY_DIR", str(tmp_path))
    fresh_env.setenv("PADDLE_TRAINERS_NUM", "2")
    fresh_env.setenv("PADDLE_TRAINER_ID", "1")
    fleet.reset_spool()
    fleet.spool_event("hello", x=1)
    fleet.spool_metrics()
    tok = fleet.on_collective_enter("all_reduce")
    assert tok is not None
    fleet.on_collective_exit(tok, "all_reduce")
    sp = fleet.get_spool()
    assert sp is not None and sp.path.endswith("rank00001.jsonl")
    with open(sp.path, "a") as f:      # simulate a crash mid-line
        f.write('{"kind": "event", "na')
    agg = fleet.FleetAggregator(str(tmp_path))
    shard = agg.shards[1]
    assert shard.meta["world_size"] == 2
    assert [e["name"] for e in shard.events] == ["hello"]
    assert len(shard.snapshots) == 1
    assert [c["phase"] for c in shard.collectives] == ["enter", "exit"]
    assert agg.collective_timeline()[0]["op_by_rank"] == {1: "all_reduce"}


# -- bench_guard --relay ------------------------------------------------------

def _guard(args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_guard.py")]
        + args, capture_output=True, text=True)


def _bench_round(tmp_path, n, **kw):
    parsed = {"metric": "m", "value": 1.0, "detail": {}}
    parsed.update(kw.pop("parsed", {}))
    rec = {"n": n, "rc": kw.pop("rc", 0), "parsed": parsed}
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))


def test_bench_guard_relay_gate(tmp_path):
    # r01 ok (derived from detail.tpu), then four not-ok rounds: the
    # default window-4 tail is all-bad -> exit 1 with the trend line
    _bench_round(tmp_path, 1, parsed={"detail": {"tpu": True}})
    _bench_round(tmp_path, 2,
                 parsed={"detail": {"fallback": "tpu_unreachable"}})
    _bench_round(tmp_path, 3, rc=1)                  # round_failed
    _bench_round(tmp_path, 4, parsed={"relay": "bench_failed"})
    _bench_round(tmp_path, 5, parsed={"relay": "unreachable"})
    bad = _guard(["--relay", "--dir", str(tmp_path)])
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "RELAY_WEDGED" in bad.stdout
    assert "last ok round: r01" in bad.stdout
    assert "r04=bench_failed" in bad.stdout          # the trend line
    # widening the window to include the ok round passes
    ok = _guard(["--relay", "--relay-window", "5", "--dir",
                 str(tmp_path)])
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # a fresh ok round clears the gate at the default window too
    _bench_round(tmp_path, 6, parsed={"relay": "ok"})
    ok2 = _guard(["--relay", "--dir", str(tmp_path), "--json"])
    assert ok2.returncode == 0
    rep = json.loads(ok2.stdout)
    assert rep["status"] == "pass" and rep["last_ok_round"] == 6


# -- the 3-process kill drill -------------------------------------------------

def _launch_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PADDLE_COLLECTIVE_WATCHDOG"] = "1"
    env.pop("XLA_FLAGS", None)   # conftest's 8-device forcing: 1/proc
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    return env


def test_three_rank_kill_drill_fleet_forensics(tmp_path, monkeypatch):
    """Acceptance drill: chaos kills rank 2 mid-all_reduce in a
    3-process world; the survivors' shards merge into a fleet view, the
    straggler + missing-rank findings name the op and ranks, and the
    blackbox postmortem replays the victim's ring."""
    t0 = time.monotonic()
    tele = tmp_path / "telemetry"
    tele.mkdir()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "3", "--log_dir", str(tmp_path / "logs"),
         FLEET_WORKER, str(tele)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=_launch_env())
    logs = ""
    log_root = tmp_path / "logs"
    if log_root.exists():
        for f in sorted(log_root.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()
    assert proc.returncode == 0, (
        f"launch rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}\nlogs:{logs[-4000:]}")
    for r in range(3):
        assert f"MPFLEET_START rank={r}/3" in logs, logs[-4000:]
    assert "MPFLEET_VICTIM_ALIVE rank=2" in logs, logs[-4000:]
    # the kill fired: nobody completed all 8 steps
    assert "MPFLEET_OK" not in logs, logs[-4000:]

    # 1) merged fleet view holds every rank's series (victim included —
    #    its shard is complete up to the kill)
    from paddle_tpu.observability.fleet import FleetAggregator
    agg = FleetAggregator(str(tele))
    assert agg.ranks() == [0, 1, 2], agg.ranks()
    names = {s["name"] for s in agg.fleet_series()}
    assert "collective_calls_total" in names
    assert "fleet.ranks_reporting" in names
    calls = next(s for s in agg.fleet_series()
                 if s["name"] == "collective_calls_total"
                 and s["labels"].get("op") == "all_reduce")
    assert sorted(calls["ranks"]) == [0, 1, 2]
    # spans from every rank landed on the shared clock
    span_ranks = {s["rank"] for s in agg.spans()}
    assert span_ranks == {0, 1, 2}, span_ranks

    # 2) typed findings name the collective and the rank. Threshold 2s:
    #    the victim is silent for ~4s (the watchdog timeout) before the
    #    survivors' last writes; the survivors themselves differ only by
    #    watchdog poll jitter (<1s) and must NOT be flagged.
    monkeypatch.setenv("PADDLE_FLEET_SILENCE_THRESHOLD", "2.0")
    findings = agg.findings()
    by_kind = {}
    for f in findings:
        by_kind.setdefault(f.kind, []).append(f)
    assert "missing_rank" in by_kind, [str(f) for f in findings]
    (missing,) = by_kind["missing_rank"]
    assert missing.rank == 2 and missing.op == "all_reduce"
    stragglers = by_kind.get("straggler", [])
    assert any(f.rank == 1 and f.op == "all_reduce"
               for f in stragglers), [str(f) for f in findings]

    # 3) the victim's ring journal survived the os._exit and replays in
    #    order, ending on the chaos injection
    from paddle_tpu.observability.flight import build_postmortem
    pm = build_postmortem(str(tele))
    assert set(pm["ranks"]) == {"0", "1", "2"}
    victim = pm["ranks"]["2"]
    assert victim["last_event"]["kind"] == "chaos"
    assert victim["last_event"]["point"] == "collective.enter"
    assert victim["last_event"]["fault"] == "kill_rank"
    assert victim["suspect_death"] is not None
    assert victim["open_collectives"], victim
    from paddle_tpu.observability.flight import read_ring
    ring = read_ring(os.path.join(str(tele), "flight-rank00002.ring"))
    seqs = [e["_seq"] for e in ring]
    assert seqs == sorted(seqs)
    kinds = [e["kind"] for e in ring]
    assert "collective_enter" in kinds and "span_open" in kinds
    assert kinds[-1] == "chaos"
    # enter of the fatal collective precedes the chaos event
    assert kinds.index("chaos") > len(kinds) - 3

    # both CLIs render the same story (launched concurrently — each
    # pays a full interpreter+package import, the dominant cost here)
    bb_p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "blackbox.py"),
         "postmortem", "--dir", str(tele)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_launch_env())
    td_p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools",
                                      "telemetry_dump.py"),
         "--fleet", str(tele)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_launch_env())
    bb_out, bb_err = bb_p.communicate(timeout=60)
    td_out, td_err = td_p.communicate(timeout=60)
    assert bb_p.returncode == 0, bb_out + bb_err
    assert "SUSPECT DEATH" in bb_out
    assert "rank 2:" in bb_out
    assert "chaos" in bb_out
    assert td_p.returncode == 0, td_out + td_err
    assert "collective_calls_total" in td_out
    assert '"kind": "missing_rank"' in td_out

    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"fleet drill took {elapsed:.1f}s (budget 60)"
