"""Tests for the BERT family, amp.debugging, and paddle.utils."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.models import (BertForMaskedLM,
                               BertForSequenceClassification, BertModel,
                               bert_tiny_config, shard_bert)


def _ids(b, s, v, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randint(0, v, (b, s)))


# -- BERT ----------------------------------------------------------------------

def test_bert_backbone_shapes():
    cfg = bert_tiny_config()
    m = BertModel(cfg)
    m.eval()
    ids = _ids(2, 16, cfg.vocab_size)
    seq, pooled = m(ids)
    assert tuple(seq.shape) == (2, 16, cfg.hidden_size)
    assert tuple(pooled.shape) == (2, cfg.hidden_size)
    assert m.num_params() > 0


def test_bert_attention_mask_blocks_padding():
    cfg = bert_tiny_config()
    m = BertModel(cfg)
    m.eval()
    ids = _ids(1, 8, cfg.vocab_size)
    mask_full = paddle.to_tensor(np.ones((1, 8), np.int64))
    mask_half = paddle.to_tensor(
        np.array([[1, 1, 1, 1, 0, 0, 0, 0]], np.int64))
    seq_full, _ = m(ids, attention_mask=mask_full)
    seq_half, _ = m(ids, attention_mask=mask_half)
    # masking the tail must change the attended representations
    assert not np.allclose(np.asarray(seq_full._data)[:, :4],
                           np.asarray(seq_half._data)[:, :4])


def test_bert_sequence_classification_trains():
    cfg = bert_tiny_config(num_hidden_layers=1, hidden_size=64,
                           num_attention_heads=2, intermediate_size=128)
    m = BertForSequenceClassification(cfg, num_classes=2)
    opt = optimizer.AdamW(learning_rate=5e-3, parameters=m.parameters())
    ids = _ids(8, 12, cfg.vocab_size)
    # learnable signal: label = parity of first token
    labels = paddle.to_tensor(
        (np.asarray(ids._data)[:, 0] % 2).astype(np.int64))
    losses = []
    for _ in range(8):
        _, loss = m(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_masked_lm_loss_and_ignore_index():
    cfg = bert_tiny_config(num_hidden_layers=1)
    m = BertForMaskedLM(cfg)
    ids = _ids(2, 8, cfg.vocab_size)
    labels_np = np.full((2, 8), -100, np.int64)
    labels_np[:, 2] = 5  # only one predicted position
    _, loss = m(ids, labels=paddle.to_tensor(labels_np))
    assert np.isfinite(float(loss))


def test_shard_bert_multichip():
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    cfg = bert_tiny_config()
    m = BertForSequenceClassification(cfg)
    mesh = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
    shard_bert(m, mesh, mp_axis="mp")
    sharded = [p for p in m.parameters() if p._dist_attr is not None]
    assert len(sharded) >= 1 + 4 * cfg.num_hidden_layers
    ids = _ids(4, 16, cfg.vocab_size)
    m.eval()
    logits = m(ids)
    assert tuple(logits.shape) == (4, 2)


# -- amp.debugging -------------------------------------------------------------

def test_operator_stats_collection(capsys):
    from paddle_tpu.amp import debugging as dbg
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    with dbg.collect_operator_stats():
        y = paddle.matmul(x, x)
        z = (y + 1).sum()
    out = capsys.readouterr().out
    assert "matmul" in out
    assert "op list" in out
    # collection stopped: no hook overhead afterwards
    from paddle_tpu.ops.registry import _DEBUG_HOOK
    assert _DEBUG_HOOK[0] is None


def test_tensor_checker_catches_nan():
    from paddle_tpu.amp import debugging as dbg
    cfg = dbg.TensorCheckerConfig(
        enable=True, debug_mode=dbg.DebugMode.CHECK_NAN_INF_AND_ABORT)
    dbg.enable_tensor_checker(cfg)
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match="NaN|Inf"):
            _ = x / x  # 0/0 -> NaN
    finally:
        dbg.disable_tensor_checker()
    # disabled again: same op passes
    x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    _ = x / x


def test_check_numerics_direct():
    from paddle_tpu.amp import debugging as dbg
    ok = paddle.to_tensor(np.ones(3, np.float32))
    assert dbg.check_numerics(ok, "okop")
    bad = paddle.to_tensor(np.array([np.nan], np.float32))
    with pytest.raises(FloatingPointError):
        dbg.check_numerics(bad, "badop")


def test_nan_check_via_set_flags():
    # the reference workflow: the FLAG alone activates scanning
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError):
            _ = x / x
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
    x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    _ = x / x  # no error once off


def test_tensor_checker_dump_and_compare(tmp_path):
    from paddle_tpu.amp import debugging as dbg
    import os
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    for d in (d1, d2):
        cfg = dbg.TensorCheckerConfig(
            enable=True, debug_mode=dbg.DebugMode.CHECK_NAN_INF,
            output_dir=d, checked_op_list=["matmul"])
        dbg.enable_tensor_checker(cfg)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        paddle.matmul(x, x)
        dbg.disable_tensor_checker()
        assert any(f.endswith(".npz") for f in os.listdir(d))
    out = str(tmp_path / "cmp.csv")
    f1 = os.path.join(d1, os.listdir(d1)[0])
    f2 = os.path.join(d2, os.listdir(d2)[0])
    dbg.compare_accuracy(f1, f2, out)
    content = open(out).read()
    assert "max_abs_err" in content and "matmul" in content


def test_geometric_out_size_covers_all_dst():
    from paddle_tpu import geometric
    x = paddle.to_tensor(np.array([[1.], [2.], [3.]], np.float32))
    dst = paddle.to_tensor(np.array([0, 1, 4]))
    src = paddle.to_tensor(np.array([0, 1, 2]))
    out = np.asarray(geometric.send_u_recv(x, src, dst)._data)
    assert out.shape == (5, 1)
    assert out[4, 0] == 3.0  # message to node 4 NOT dropped


def test_model_average_guards_and_state():
    from paddle_tpu.incubate.optimizer import ModelAverage
    net = nn.Linear(2, 2)
    avg = ModelAverage(0.15, parameters=net.parameters(),
                       min_average_window=10)
    with pytest.raises(RuntimeError, match="before any step"):
        avg.apply()
    avg.step()
    sd = avg.state_dict()
    assert sd["@avg_window_updates"] == 1
    avg2 = ModelAverage(0.15, parameters=net.parameters(),
                        min_average_window=10)
    avg2.set_state_dict(sd)
    assert avg2._window_updates == 1
    assert avg.get_lr() == 0.0  # inherited surface works


# -- utils ---------------------------------------------------------------------

def test_unique_name_generate_and_guard():
    from paddle_tpu.utils import unique_name
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"
        assert unique_name.generate("fc") == "fc_1"
        assert unique_name.generate("conv") == "conv_0"
        with unique_name.guard():
            assert unique_name.generate("fc") == "fc_0"  # fresh namespace
        assert unique_name.generate("fc") == "fc_2"


def test_deprecated_decorator():
    from paddle_tpu.utils import deprecated

    @deprecated(update_to="new_fn", since="2.0")
    def old_fn():
        return 42

    with pytest.warns(DeprecationWarning, match="new_fn"):
        assert old_fn() == 42

    @deprecated(level=2)
    def gone_fn():
        return 0

    with pytest.raises(RuntimeError, match="deprecated"):
        gone_fn()


def test_flops_linear_and_conv():
    from paddle_tpu.utils import flops
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    n = flops(net, (4, 16))
    assert n == 2 * 4 * 16 * 32 + 2 * 4 * 32 * 8

    conv = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1))
    n2 = flops(conv, (1, 3, 8, 8))
    assert n2 == 2 * (8 * 8 * 8) * (3 * 3 * 3)


def test_try_import_and_require_version():
    from paddle_tpu.utils import require_version, try_import
    assert try_import("json") is not None
    with pytest.raises(ImportError):
        try_import("definitely_not_a_module_xyz")
    assert require_version("0.0.1")
    with pytest.raises(Exception):
        require_version("99.0.0")
