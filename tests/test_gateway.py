"""Serving control plane: multi-replica gateway drills
(paddle_tpu.inference.gateway).

The acceptance bars:
  * routing policies (least-loaded, session/bucket affinity, weighted
    round-robin) over a 2-replica pool produce TOKEN-EXACT outputs vs
    solo ``generate``;
  * per-tenant quotas and the two-level priority queue keep a
    low-priority tenant completing under saturating high-priority load;
  * a chaos-killed replica's in-flight requests requeue onto survivors
    (``gateway.requeued`` > 0) and finish with zero lost or duplicated
    tokens — streaming consumers see the failover transparently.

Everything is single-threaded and deterministic: the gateway's step()
IS the simulation harness (no multiprocessing).
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.gateway import (DispatchQueue, Gateway,
                                          PRIORITY_LOW, TenantQuotas,
                                          TokenBucket)
from paddle_tpu.inference.serving import ContinuousBatcher
from paddle_tpu.resilience import (DeadlineExceeded, Overloaded,
                                   arm_scenario, disarm)

pytestmark = pytest.mark.gateway


@pytest.fixture(autouse=True)
def _disarm():
    disarm()
    yield
    disarm()


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 128, size=n).astype(np.int64) for n in sizes]


def _ref(lm, prompt, n):
    return np.asarray(lm.generate(prompt.reshape(1, -1),
                                  max_new_tokens=n)).reshape(-1)


def _batcher(lm, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("s_max", 64)
    return ContinuousBatcher(lm, compile=False, **kw)


# -- unit pieces --------------------------------------------------------------

def test_token_bucket_refills_on_injected_clock():
    t = [0.0]
    b = TokenBucket(rate=10.0, burst=20.0, clock=lambda: t[0])
    assert b.try_take(20)            # starts full
    assert not b.try_take(1)         # empty; nothing charged on refusal
    t[0] = 0.5                       # +5 tokens
    assert b.level == pytest.approx(5.0)
    assert b.try_take(5) and not b.try_take(0.1)
    t[0] = 100.0
    assert b.level == pytest.approx(20.0)   # capped at burst

    q = TenantQuotas({"metered": TokenBucket(1.0, 4.0, clock=lambda: t[0])})
    assert q.admit("unmetered", 10_000)     # no bucket -> unlimited
    assert q.admit("metered", 4) and not q.admit("metered", 1)


def test_dispatch_queue_low_share_prevents_starvation():
    class R:
        def __init__(self, tag, pr):
            self.tag, self.priority = tag, pr

    q = DispatchQueue(low_share=3)
    for i in range(6):
        q.push(R(f"h{i}", 0))
    q.push(R("low", PRIORITY_LOW))
    order = [q.pop().tag for _ in range(len(q))]
    # every 3rd dispatch serves the low lane: the batch request lands at
    # position 3, not dead last
    assert order == ["h0", "h1", "low", "h2", "h3", "h4", "h5"]


# -- token-exact routing ------------------------------------------------------

def test_gateway_least_loaded_token_exact_across_two_replicas(lm):
    prompts = _prompts(0, (5, 9, 7, 12))
    refs = [_ref(lm, p, 8) for p in prompts]
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    gids = [gw.submit(p, 8) for p in prompts]
    out = gw.run_until_done()
    for g, ref in zip(gids, refs):
        assert np.array_equal(out[g], ref)
    # 4 requests over 2x2 slots: least-loaded spreads — both engines served
    assert all(r.batcher.stats()["completed_requests"] == 2
               for r in gw.pool.replicas())
    assert gw.stats()["completions"] == 4


def test_gateway_session_affinity_sticks_and_stays_exact(lm):
    from paddle_tpu.observability.metrics import get_registry
    hits0 = get_registry().counter(
        "gateway.route.affinity_hit", "").value
    # two sessions in DIFFERENT prompt buckets (6 -> rung 8, 20 -> rung
    # 32), two turns each, a turn at a time so turn 2 has a sticky target
    prompts = _prompts(1, (6, 20, 6, 20))
    refs = [_ref(lm, p, 6) for p in prompts]
    gw = Gateway(policy="affinity")
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    gids, serving = [], {}
    for i, p in enumerate(prompts):
        sid = f"s{i % 2}"
        gids.append(gw.submit(p, 6, session_id=sid))
        gw.step()
        serving.setdefault(sid, set()).add(
            gw.router._sessions[sid])
    out = gw.run_until_done()
    for g, ref in zip(gids, refs):
        assert np.array_equal(out[g], ref)
    # each session's turns all landed on ONE replica
    assert all(len(reps) == 1 for reps in serving.values())
    assert get_registry().counter(
        "gateway.route.affinity_hit", "").value > hits0

    # bucket warmth without a session: a same-rung prompt prefers the
    # replica that already compiled that prefill rung, even when it is
    # the busier one
    gw2 = Gateway(policy="affinity")
    gw2.add_replica("r0", _batcher(lm, max_batch=4))
    gw2.add_replica("r1", _batcher(lm, max_batch=4))
    gw2.submit(_prompts(2, (6,))[0], 6)
    gw2.step()                               # r0 warms rung 8, load 1
    gw2.submit(_prompts(3, (7,))[0], 6)      # rung 8 again
    gw2.step()
    assert gw2.pool.get("r0").load == 2      # warm beat least-loaded
    gw2.run_until_done()


def test_gateway_weighted_rr_respects_weights(lm):
    prompts = _prompts(2, (4, 4, 4, 4, 4, 4))
    refs = [_ref(lm, p, 4) for p in prompts]
    gw = Gateway(policy="weighted_rr")
    gw.add_replica("heavy", _batcher(lm, max_batch=8), weight=2.0)
    gw.add_replica("light", _batcher(lm, max_batch=8), weight=1.0)
    gids = [gw.submit(p, 4) for p in prompts]
    gw.step()                        # all 6 dispatch into 8+8 free slots
    loads = {r.name: r.load for r in gw.pool.replicas()}
    assert loads == {"heavy": 4, "light": 2}     # smooth 2:1 split
    out = gw.run_until_done()
    for g, ref in zip(gids, refs):
        assert np.array_equal(out[g], ref)


# -- quotas / priorities / SLO ------------------------------------------------

def test_gateway_tenant_quota_sheds_typed(lm):
    gw = Gateway(quotas=TenantQuotas(
        {"free": TokenBucket(rate=0.0, burst=20.0)}))
    gw.add_replica("r0", _batcher(lm))
    gw.submit(np.arange(4), 8, tenant="free")       # cost 12: fits
    with pytest.raises(Overloaded):
        gw.submit(np.arange(4), 8, tenant="free")   # bucket exhausted
    gw.submit(np.arange(4), 8, tenant="paid")       # unmetered tenant fine
    assert len(gw.run_until_done()) == 2


def test_gateway_low_priority_tenant_not_starved(lm):
    """Saturating high-priority load on a 1-slot replica: the low lane's
    guaranteed share still gets the batch request through EARLY, not
    after the entire high backlog."""
    gw = Gateway(low_share=2)
    gw.add_replica("r0", _batcher(lm, max_batch=1))
    high = [gw.submit(p, 4, tenant="interactive")
            for p in _prompts(3, (4, 4, 4, 4))]
    low = gw.submit(_prompts(4, (4,))[0], 4, tenant="batch",
                    priority="low")
    finish_order = []
    for _ in range(500):
        finish_order += gw.step()
        if not gw._has_work():
            break
    assert set(finish_order) == set(high) | {low}
    # low_share=2 -> the low request is the 2nd dispatch on the single
    # slot; it must beat at least the last three high requests
    assert finish_order.index(low) <= 1


def test_gateway_slo_admission_and_queue_expiry(lm):
    gw = Gateway(slo_tpot_s=10.0)            # absurd TPOT estimate
    gw.add_replica("r0", _batcher(lm))
    with pytest.raises(DeadlineExceeded):    # 10 tokens can't fit 0.5s
        gw.submit(np.arange(4), 10, deadline_s=0.5)
    assert gw.stats()["infeasible"] == 1

    gw2 = Gateway()                          # no replicas: work waits
    gid = gw2.submit(np.arange(4), 4, deadline_s=0.0)
    time.sleep(0.001)
    gw2.step()
    with pytest.raises(DeadlineExceeded):
        gw2.result(gid)
    st = gw2.stats()
    assert st["deadline_expired"] == 1 and st["shed"] == 0


def test_gateway_queue_capacity_sheds_typed(lm):
    gw = Gateway(max_queue_depth=1)
    gw.submit(np.arange(4), 4)
    with pytest.raises(Overloaded):
        gw.submit(np.arange(4), 4)
    assert gw.stats()["shed"] == 1


# -- lifecycle / failure drills ----------------------------------------------

def test_gateway_drain_routes_around_and_remove(lm):
    prompts = _prompts(5, (5, 7, 9))
    refs = [_ref(lm, p, 5) for p in prompts]
    gw = Gateway()
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    g0 = gw.submit(prompts[0], 5)
    gw.step()                                # lands on r0 (least loaded tie)
    gw.drain_replica("r0")
    g1, g2 = gw.submit(prompts[1], 5), gw.submit(prompts[2], 5)
    out = gw.run_until_done()
    for g, ref in zip((g0, g1, g2), refs):
        assert np.array_equal(out[g], ref)
    # drained replica finished its in-flight work but took nothing new
    assert gw.pool.get("r0").batcher.stats()["completed_requests"] == 1
    assert gw.pool.get("r1").batcher.stats()["completed_requests"] == 2
    gw.remove_replica("r0")                  # empty + drained: clean remove
    assert "r0" not in gw.pool


def test_gateway_replica_death_requeues_token_exact(lm):
    """THE failover drill: chaos kills one replica mid-decode (its step
    exhausts the pool's retry policy); every in-flight request resumes
    on the survivor and completes token-exact — zero lost or duplicated
    tokens, gateway.requeued > 0. A streaming consumer rides through the
    failover without noticing."""
    prompts = _prompts(6, (5, 9, 7, 11))
    refs = [_ref(lm, p, 10) for p in prompts]
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    gids = [gw.submit(p, 10) for p in prompts]
    sess = gw.open_stream(gids[0])
    # 3 consecutive serving.step faults == the pool retry budget -> the
    # replica holding them dies; deterministic seed + hit counting picks
    # a mid-flight moment (after=6 engine steps across the pool)
    arm_scenario("seed=0; serving.step:transient_error:after=6,count=3")
    streamed = list(sess)                    # consumer-paced: drives step()
    for _ in range(1000):
        if not gw._has_work():
            break
        gw.step()
    s = gw.stats()
    assert s["requeued"] > 0
    alive = [r for r in gw.pool.replicas() if r.alive]
    assert len(alive) == 1                   # exactly one casualty
    # the duplicated-work interval is tagged: the survivor's prompt
    # re-prefill carries requeue_recompute=1 (the interrupted spans mark
    # what was cut short; THIS marks what gets paid twice), and the
    # goodput ledger prices it as waste.requeue_recompute
    from paddle_tpu.observability import (build_waterfalls, get_recorder,
                                          ledger_from_waterfalls)
    tids = {gw._finished[g].trace.trace_id for g in gids
            if gw._finished[g].trace is not None}
    wfs = [w for w in build_waterfalls(get_recorder().spans())
           if w.trace_id in tids]
    recomputes = [seg for w in wfs for seg in w.segments
                  if seg.tags.get("requeue_recompute")]
    assert recomputes and all(seg.name == "prefill" for seg in recomputes)
    assert all(seg.tags.get("replica") == alive[0].name
               for seg in recomputes)        # charged to the survivor
    led = ledger_from_waterfalls(wfs)
    assert led.waste["requeue_recompute"] > 0.0
    for g, ref in zip(gids, refs):
        assert np.array_equal(gw.pop_result(g), ref)  # zero lost/dup tokens
    assert streamed == [int(t) for t in refs[0][len(prompts[0]):]]
    assert s["completions"] == 4 and s["failures"] == 0


def test_gateway_tp_shard_group_member_death_requeues_token_exact(lm):
    """Tensor-parallel flavor of the failover drill: replica r0 is a
    2-way TP shard group (weights P(None,'tensor'), KV sharded on
    heads). Chaos kills ONE group member mid-decode; the batcher's
    heartbeat raises the non-retryable TPMemberDied, the pool declares
    the WHOLE group dead (a member held 1/2 of the weights), and every
    in-flight request resumes token-exact on the plain survivor."""
    from paddle_tpu.distributed.mesh import MeshRuntime
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

    # a private model instance: shard_serving re-places its weights on
    # the mesh, which must not leak into the module-scoped fixture
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    tp_lm = GPT2ForCausalLM(cfg)
    tp_lm.eval()

    prompts = _prompts(6, (5, 9, 7, 11))
    refs = [_ref(lm, p, 10) for p in prompts]
    gw = Gateway(policy="least_loaded")
    b0 = _batcher(tp_lm)
    group = MeshRuntime({"tensor": 2}).shard_serving(b0, group_name="tp0")
    gw.add_replica("r0", b0)
    gw.add_replica("r1", _batcher(lm))
    rep0 = gw.pool.get("r0")
    assert rep0.shard_group is group and "tp=tp0x2" in repr(rep0)

    gids = [gw.submit(p, 10) for p in prompts]
    arm_scenario("seed=0; serving.tp_member:transient_error:after=6,count=1")
    for _ in range(1000):
        if not gw._has_work():
            break
        gw.step()
    s = gw.stats()
    assert s["requeued"] > 0
    assert [r.name for r in gw.pool.replicas() if not r.alive] == ["r0"]
    assert group.failed_members == ["tp0/tensor1"]
    assert rep0.describe()["shard_group"]["failed"] == ["tp0/tensor1"]
    for g, ref in zip(gids, refs):
        assert np.array_equal(gw.pop_result(g), ref)  # zero lost/dup tokens
    assert s["completions"] == 4 and s["failures"] == 0


def test_affinity_policy_prefers_deepest_cached_prefix():
    """KV-aware tier: the replica advertising the deepest chain-hash
    match wins over session/bucket warmth and load order."""
    from paddle_tpu.inference.gateway import SessionAffinityPolicy
    from paddle_tpu.inference.prefix_cache import RadixPrefixCache
    from paddle_tpu.observability.metrics import get_registry

    class FakeRep:
        def __init__(self, name, cache, load=0):
            self.name, self._cache, self.load = name, cache, load
            self.warm_buckets = set()

        def prefix_summary(self):
            return None if self._cache is None else self._cache.summary()

    deep = RadixPrefixCache(4)
    deep.insert(np.arange(8), [0, 1], 0, 2)         # 2 cached blocks
    shallow = RadixPrefixCache(4)
    shallow.insert(np.arange(4), [0], 0, 1)         # 1 cached block
    reps = [FakeRep("a", shallow), FakeRep("b", deep, load=5),
            FakeRep("c", None)]
    pol = SessionAffinityPolicy()

    class Req:
        prompt = np.arange(12)
        session_id = "sticky"
        bucket = None
    pol._sessions["sticky"] = "a"                   # stickiness says a…
    px = get_registry().counter("gateway.route.prefix_hit", "t")
    before = px.value
    assert pol.select(Req(), reps).name == "b"      # …prefix depth wins
    assert px.value - before == 1
    # no cached prefix anywhere -> the classic tiers take over (session)
    class Cold:
        prompt = np.arange(100, 112)
        session_id = "sticky"
        bucket = None
    assert pol.select(Cold(), reps).name == "a"


def test_gateway_failover_with_speculation_reprefixes(lm):
    """Round-13 drill: paged replicas with the radix prefix cache AND a
    draft model attached; chaos kills one mid-decode. The lost/dup-token
    guard must hold (token-exact results), the requeued requests must
    re-match their cached prefix on the survivor, and no page may leak."""
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    from paddle_tpu.models.gpt import GPT2ForCausalLM
    from paddle_tpu.observability.metrics import get_registry

    paddle.seed(42)
    draft = GPT2ForCausalLM(lm.config)              # disagreeing draft
    draft.eval()
    rng = np.random.RandomState(21)
    shared = rng.randint(0, 128, (16,)).astype(np.int64)   # 2 blocks of 8
    prompts = [np.concatenate([shared, t])
               for t in _prompts(22, (5, 7, 6, 9))]
    refs = [_ref(lm, p, 16) for p in prompts]

    def paged(seed_tag):
        return PagedContinuousBatcher(
            lm, max_batch=2, s_max=64, block_size=8, n_pages=32,
            compile=False, prefix_cache=True, draft_model=draft,
            draft_k=3)

    gw = Gateway(policy="affinity")
    gw.add_replica("r0", paged("r0"))
    gw.add_replica("r1", paged("r1"))
    gids = [gw.submit(p, 16) for p in prompts]
    arm_scenario("seed=0; serving.step:transient_error:after=6,count=3")
    dead = None
    for _ in range(2000):
        gw.step()
        dead = next((r for r in gw.pool.replicas() if not r.alive), None)
        if dead is not None:
            break
    assert dead is not None, "chaos never killed a replica"
    survivor = next(r for r in gw.pool.replicas() if r.alive)
    hits_before = survivor.batcher.prefix_cache.hit_tokens
    for _ in range(2000):
        if not gw._has_work():
            break
        gw.step()
    s = gw.stats()
    assert s["requeued"] > 0 and s["failures"] == 0
    # zero lost/duplicated tokens: exact output through spec + failover
    # (the gateway's accounting guard would have raised on divergence)
    for g, ref in zip(gids, refs):
        assert np.array_equal(gw.pop_result(g), ref)
    # requeued requests re-matched the shared prefix on the survivor
    assert survivor.batcher.prefix_cache.hit_tokens > hits_before
    assert survivor.batcher.spec_stats["rounds"] > 0
    survivor.batcher.audit_pages()
    assert get_registry().gauge("serving.pages_leaked", "t").value == 0


# -- streaming ----------------------------------------------------------------

def test_gateway_streaming_delivery_and_backpressure(lm):
    prompt = _prompts(7, (6,))[0]
    ref = _ref(lm, prompt, 8)
    gw = Gateway()
    gw.add_replica("r0", _batcher(lm, max_batch=4))
    sess = gw.stream(prompt, 8, max_buffered=2)
    while not sess.throttled:                # decode until buffer fills
        gw.step()
    late = gw.submit(_prompts(8, (4,))[0], 4)
    gw.step()
    # full buffer pauses INTAKE: the late request stays in the gateway
    # queue while the throttle holds
    assert gw.stats()["queue_depth"] == 1
    got = sess.read_available()              # consumer catches up
    gw.step()
    assert gw.stats()["queue_depth"] == 0    # late request dispatched
    got += list(sess)
    assert got == [int(t) for t in ref[len(prompt):]]
    gw.run_until_done()                      # flush whatever remains
    assert len(gw.pop_result(late)) == 8     # 4 prompt + 4 generated
    assert np.array_equal(gw.pop_result(sess.gid), ref)
    with pytest.raises(KeyError):
        gw.open_stream(sess.gid)             # finished: no longer live
