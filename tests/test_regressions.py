"""Regression tests for review findings (round 1 code review)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
import paddle_tpu.nn.functional as F


def test_setitem_keeps_gradient_flow():
    # leaf case: grads must reach the mutated leaf (zeros at overwritten slots)
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    x[0] = 5.0
    x.sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0])

    # non-leaf case: grads flow through to the producer
    a = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    b = a * 3
    b[0] = 7.0
    b.sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), [0.0, 3.0])


def test_double_backward_without_retain_raises():
    a = paddle.to_tensor([2.0], stop_gradient=False)
    b = (a * a).sum()
    b.backward()
    with pytest.raises(RuntimeError, match="retain_graph"):
        b.backward()


def test_retain_graph_allows_second_backward():
    a = paddle.to_tensor([2.0], stop_gradient=False)
    b = (a * a).sum()
    b.backward(retain_graph=True)
    b.backward()
    np.testing.assert_allclose(a.grad.numpy(), [8.0])


def test_attention_dropout_active_in_training():
    paddle.seed(0)
    q = paddle.randn([2, 8, 2, 4])
    out_train = F.scaled_dot_product_attention(q, q, q, dropout_p=0.9,
                                               training=True)
    out_eval = F.scaled_dot_product_attention(q, q, q, dropout_p=0.9,
                                              training=False)
    # with p=0.9 the dropped output must differ from the deterministic one
    assert not np.allclose(out_train.numpy(), out_eval.numpy())


def test_grad_scaler_external_unscale_not_double():
    p = paddle.core.tensor.Parameter(np.array([1.0], "float32"))
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    loss = (p * 1.0).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)          # user unscales to clip
    g_after_unscale = p.grad.numpy().copy()
    scaler.step(opt)              # must NOT unscale again
    np.testing.assert_allclose(g_after_unscale, [1.0])
    np.testing.assert_allclose(p.numpy(), [0.0])  # p - lr*1.0


def test_nll_loss_weighted_mean():
    logp = paddle.to_tensor(np.log(np.full((2, 2), 0.5, "float32")))
    label = paddle.to_tensor([0, 1])
    w = paddle.to_tensor([1.0, 3.0])
    loss = F.nll_loss(logp, label, weight=w)
    # sum(w_i * l_i) / sum(w_i) = (1*0.693 + 3*0.693)/4 = 0.693
    np.testing.assert_allclose(loss.item(), np.log(2.0), rtol=1e-5)


def test_max_pool_ceil_mode():
    x = paddle.randn([1, 1, 5, 5])
    out = F.max_pool2d(x, 2, stride=2, ceil_mode=True)
    assert out.shape == [1, 1, 3, 3]
    out_floor = F.max_pool2d(x, 2, stride=2, ceil_mode=False)
    assert out_floor.shape == [1, 1, 2, 2]


def test_max_pool_return_mask():
    x = paddle.to_tensor(np.arange(16).reshape(1, 1, 4, 4).astype("float32"))
    out, mask = F.max_pool2d(x, 2, return_mask=True)
    assert out.shape == [1, 1, 2, 2]
    assert mask.shape == [1, 1, 2, 2]
    np.testing.assert_array_equal(out.numpy().reshape(-1), [5, 7, 13, 15])
    np.testing.assert_array_equal(mask.numpy().reshape(-1), [5, 7, 13, 15])


def test_adamw_decay_param_filter():
    p1 = paddle.core.tensor.Parameter(np.array([1.0], "float32"),
                                      name="w_weight")
    p2 = paddle.core.tensor.Parameter(np.array([1.0], "float32"),
                                      name="b_bias")
    opt = optimizer.AdamW(
        learning_rate=0.0, weight_decay=0.5, parameters=[p1, p2],
        apply_decay_param_fun=lambda n: "bias" not in n)
    (p1.sum() + p2.sum()).backward()
    opt.step()
    # lr=0 -> only decay term would move params; but decay is multiplied by lr
    np.testing.assert_allclose(p1.numpy(), [1.0])
    np.testing.assert_allclose(p2.numpy(), [1.0])
    # now with lr>0: p1 decays, p2 does not (beyond adam term which is equal)
    opt2 = optimizer.AdamW(
        learning_rate=0.1, weight_decay=0.5, parameters=[p1, p2],
        apply_decay_param_fun=lambda n: "bias" not in n)
    p1.clear_grad(); p2.clear_grad()
    (p1.sum() + p2.sum()).backward()
    opt2.step()
    assert p1.item() < p2.item()


def test_recompute_param_grads_flow():
    """Closure parameters must receive grads through recompute even when all
    explicit inputs are frozen (the pipeline/recompute_interval case)."""
    from paddle_tpu.distributed import fleet
    lin = nn.Linear(8, 8)
    x = paddle.randn([2, 8])  # stop_gradient=True (data)
    y = fleet.recompute(lambda t: lin(t).tanh(), x)
    y.sum().backward()
    assert lin.weight.grad is not None
    assert lin.bias.grad is not None
    # matches the non-recompute grads
    lin2 = nn.Linear(8, 8)
    lin2.weight._set_data(lin.weight._data)
    lin2.bias._set_data(lin.bias._data)
    lin2(paddle.to_tensor(x.numpy())).tanh().sum().backward()
    np.testing.assert_allclose(lin.weight.grad.numpy(),
                               lin2.weight.grad.numpy(), rtol=1e-5)


def test_recompute_respects_paddle_grad_no_mutation():
    """paddle.grad through a recompute region must not touch .grad, and must
    return grads for closure params when requested."""
    from paddle_tpu.distributed import fleet
    lin = nn.Linear(6, 6)
    x = paddle.randn([2, 6])
    x.stop_gradient = False
    y = fleet.recompute(lambda t: lin(t).tanh(), x)
    gx, gw = paddle.grad(y.sum(), [x, lin.weight])
    assert gx is not None and gw is not None
    assert lin.weight.grad is None  # no side effects
    assert x.grad is None
    # grads match a plain backward
    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    lin(x2).tanh().sum().backward()
    np.testing.assert_allclose(gw.numpy(), lin.weight.grad.numpy(), rtol=1e-5)
    np.testing.assert_allclose(gx.numpy(), x2.grad.numpy(), rtol=1e-5)


def test_recompute_frozen_region_not_taped():
    from paddle_tpu.distributed import fleet
    lin = nn.Linear(4, 4)
    for p in lin.parameters():
        p.stop_gradient = True
    x = paddle.randn([2, 4])  # frozen data
    y = fleet.recompute(lambda t: lin(t).tanh(), x)
    assert y.stop_gradient  # no tape node was recorded
