"""Static-analysis subsystem (paddle_tpu.analysis).

All four engines, one flagging and one passing fixture per rule:
  DF001..DF006  — jaxpr dataflow analyses / registry alias audit
  TS101..TS105  — AST trace-safety lint
  SH201..SH204  — SPMD shard-safety (jaxpr propagation + PLAN_7B audit)
  MEM301/MEM302 — liveness peak-HBM budgeting (jaxpr + plan + serving)
plus the pass-registry integration (diagnostic passes via apply_pass),
the observability findings counters, the suppression/baseline machinery,
and the tier-1 lint gate (``pytest -m lint``) that runs tools/tpu_lint.py
over the shipped tree (paddle_tpu/, examples/, tools/, benchmarks/) AND
the tools/shard_check.py PLAN_7B gate with a combined <10s runtime guard.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import ast_lint
from paddle_tpu.analysis import findings as findings_mod
from paddle_tpu.static import ir

try:
    from jax._src.core import ClosedJaxpr, Jaxpr
except ImportError:  # pragma: no cover
    from jax.core import ClosedJaxpr, Jaxpr

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


def _tensor(shape, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(*shape).astype("float32"))


# ---------------------------------------------------------------------------
# DF001 — shape/dtype + structural consistency
# ---------------------------------------------------------------------------

def test_df001_flags_corrupt_jaxpr():
    closed = jax.make_jaxpr(lambda x: jnp.tanh(jnp.exp(x)))(1.0)
    jp = closed.jaxpr
    # "a transform pass dropped a producer": first eqn removed by hand
    bad = ClosedJaxpr(Jaxpr(jp.constvars, jp.invars, jp.outvars,
                            jp.eqns[1:], jp.effects), closed.consts)
    fs = analysis.check_shapes(bad)
    assert "DF001" in _rules(fs)
    assert any("before it is defined" in f.message for f in fs)


@pytest.mark.quick
def test_df001_passes_healthy_program():
    def fn(x):
        return paddle.tanh(x) + 1.0
    prog = ir.IrProgram.trace(fn, _tensor((3, 4)))
    assert analysis.check_shapes(prog) == []


# ---------------------------------------------------------------------------
# DF002 — dead code
# ---------------------------------------------------------------------------

def test_df002_flags_dead_eqns_and_passes_after_dce():
    def fn(x):
        dead = paddle.exp(x) * 3.0  # never reaches the output
        return paddle.tanh(x)
    prog = ir.IrProgram.trace(fn, _tensor((3, 4)))
    fs = analysis.check_dead_code(prog)
    assert "DF002" in _rules(fs)
    clean = ir.apply_pass(prog, "dead_code_elimination")
    assert analysis.check_dead_code(clean) == []


# ---------------------------------------------------------------------------
# DF003 — unused inputs
# ---------------------------------------------------------------------------

def test_df003_flags_unused_input_and_passes_when_used():
    def uses_one(x, y):
        return paddle.tanh(x)
    prog = ir.IrProgram.trace(uses_one, _tensor((2, 2)), _tensor((2, 2), 1))
    fs = analysis.check_unused_inputs(prog)
    assert "DF003" in _rules(fs)
    assert any("input #1" in f.message for f in fs)

    def uses_both(x, y):
        return x + y
    prog2 = ir.IrProgram.trace(uses_both, _tensor((2, 2)),
                               _tensor((2, 2), 1))
    assert analysis.check_unused_inputs(prog2) == []


# ---------------------------------------------------------------------------
# DF004 — collective ordering (the SPMD deadlock lint)
# ---------------------------------------------------------------------------

def _rank_jaxpr(fn, *args):
    return jax.make_jaxpr(fn, axis_env=[("i", 2)])(*args)


def test_df004_flags_mismatched_two_rank_program():
    # rank0: psum; psum      rank1: ppermute; psum  -> deadlock at #0
    r0 = _rank_jaxpr(lambda v: lax.psum(lax.psum(v, "i"), "i"), 1.0)
    r1 = _rank_jaxpr(
        lambda v: lax.psum(
            jnp.sum(lax.ppermute(v, "i", [(0, 1), (1, 0)])), "i"),
        jnp.ones((2,)))
    fs = analysis.check_collective_order([r0, r1])
    assert "DF004" in _rules(fs)
    assert any(f.severity == "error" and "deadlock" in f.message
               for f in fs)


def test_df004_passes_identical_rank_schedules():
    mk = lambda: _rank_jaxpr(
        lambda v: lax.psum(v, "i") + lax.pmax(v, "i"), 1.0)
    assert analysis.check_collective_order([mk(), mk()]) == []


def test_df004_flags_four_rank_missing_mid_sequence_collective():
    # three ranks run psum; pmax; psum — rank2 skips the mid pmax and
    # goes straight to its second psum: divergence at collective #1
    full = lambda: _rank_jaxpr(
        lambda v: lax.psum(lax.pmax(lax.psum(v, "i"), "i"), "i"), 1.0)
    missing = _rank_jaxpr(
        lambda v: lax.psum(lax.psum(v, "i"), "i"), 1.0)
    names = ["r0", "r1", "r2", "r3"]
    fs = analysis.check_collective_order(
        [full(), full(), missing, full()], rank_names=names)
    assert "DF004" in _rules(fs)
    hits = [f for f in fs if f.rule == "DF004"]
    assert len(hits) == 1                      # only the deviant rank
    assert hits[0].extra["ranks"] == ["r0", "r2"]
    assert hits[0].extra["index"] == 1         # mid-sequence, not #0
    assert "pmax" in hits[0].message


def test_df004_passes_identical_four_rank_schedules():
    mk = lambda: _rank_jaxpr(
        lambda v: lax.psum(lax.pmax(lax.psum(v, "i"), "i"), "i"), 1.0)
    assert analysis.check_collective_order(
        [mk() for _ in range(4)], rank_names=list("abcd")) == []


def test_df004_flags_divergent_cond_branches():
    closed = _rank_jaxpr(
        lambda p, x: lax.cond(p, lambda v: lax.psum(v, "i"),
                              lambda v: v, x), True, 1.0)
    fs = analysis.check_collective_order(closed)
    assert "DF004" in _rules(fs)
    assert any("branch" in f.message for f in fs)


def test_df004_passes_agreeing_cond_branches():
    closed = _rank_jaxpr(
        lambda p, x: lax.cond(p, lambda v: lax.psum(v, "i"),
                              lambda v: lax.psum(v * 2.0, "i"), x),
        True, 1.0)
    assert analysis.check_collective_order(closed) == []


def test_collective_schedule_recurses_into_pjit():
    closed = _rank_jaxpr(
        lambda x: jax.jit(lambda v: lax.psum(v, "i"))(x), 1.0)
    sched = analysis.collective_schedule(closed)
    assert [(prim, axes) for _, prim, axes in sched] == [("psum", ("i",))]


# ---------------------------------------------------------------------------
# DF005 — NaN-prone patterns
# ---------------------------------------------------------------------------

def test_df005_flags_log_of_unclamped_sub():
    closed = jax.make_jaxpr(lambda a, b: jnp.log(a - b))(1.0, 2.0)
    assert "DF005" in _rules(analysis.check_nan_prone(closed))


def test_df005_flags_div_by_unclamped_sub():
    closed = jax.make_jaxpr(lambda a, b: a / (a - b))(1.0, 2.0)
    assert "DF005" in _rules(analysis.check_nan_prone(closed))


def test_df005_passes_clamped_sub():
    closed = jax.make_jaxpr(
        lambda a, b: jnp.log(jnp.maximum(a - b, 1e-6)))(1.0, 2.0)
    assert analysis.check_nan_prone(closed) == []


# ---------------------------------------------------------------------------
# DF006 — inplace/donation alias audit
# ---------------------------------------------------------------------------

def test_df006_shipped_registry_is_clean():
    assert analysis.audit_inplace_aliases() == []


def test_df006_metadata_is_explicit_on_registry_entries():
    from paddle_tpu.ops.registry import get_alias
    exp_alias = get_alias(paddle.exp.op_name)
    assert exp_alias["preserves_shape"] and exp_alias["preserves_dtype"]
    cast_alias = get_alias(paddle.cast.op_name)
    assert not cast_alias["preserves_dtype"]
    reshape_alias = get_alias(paddle.reshape.op_name)
    assert not reshape_alias["preserves_shape"]


def test_df006_flags_wrong_and_missing_metadata(monkeypatch):
    from paddle_tpu.ops import inplace as inplace_mod
    from paddle_tpu.ops import registry

    @registry.defop(name="_lint_probe_tobool", differentiable=False)
    def _tobool(x):
        return x > 0

    @registry.defop(name="_lint_probe_plain", differentiable=False)
    def _plain(x):
        return x * 2

    try:
        # wrong: claims dtype-preserving but maps float32 -> bool
        registry.declare_alias("_lint_probe_tobool", preserves_dtype=True)
        ns = {"tobool": registry.get_op("_lint_probe_tobool"),
              "plain": registry.get_op("_lint_probe_plain")}
        monkeypatch.setattr(inplace_mod, "_INPLACE_NAMES",
                            ["tobool", "plain"])
        fs = analysis.audit_inplace_aliases(namespace=ns)
        assert any(f.rule == "DF006" and "preserves_dtype" in f.message
                   for f in fs)
        assert any(f.rule == "DF006" and "no alias metadata" in f.message
                   for f in fs)
    finally:
        registry.OP_REGISTRY.pop("_lint_probe_tobool", None)
        registry.OP_REGISTRY.pop("_lint_probe_plain", None)


def test_inplace_shape_contract_enforced():
    # the declared-metadata fix: a broadcast that would GROW the tensor
    # now raises instead of silently rebinding a larger buffer
    x = paddle.to_tensor(np.ones((1,), dtype="float32"))
    y = paddle.to_tensor(np.ones((3,), dtype="float32"))
    with pytest.raises(ValueError, match="grow"):
        paddle.add_(x, y)
    # the legitimate same-shape path still works
    z = paddle.to_tensor(np.ones((3,), dtype="float32"))
    paddle.add_(z, y)
    np.testing.assert_allclose(np.asarray(z._data), 2.0)


# ---------------------------------------------------------------------------
# pass-registry integration
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_diagnostic_passes_registered_and_applied():
    for name in analysis.DIAGNOSTIC_PASS_NAMES:
        assert name in ir.list_passes()
        assert ir.is_analysis_pass(name)
    assert not ir.is_analysis_pass("dead_code_elimination")

    def fn(x, y):
        dead = paddle.exp(x)
        return paddle.tanh(x)
    prog = ir.IrProgram.trace(fn, _tensor((2, 3)), _tensor((2, 3), 1))
    out = ir.apply_pass(prog, ["check_dead_code", "check_unused_inputs"])
    assert out.closed is prog.closed          # analysis never rewrites
    assert {"DF002", "DF003"} <= _rules(out.findings)
    assert out.applied_passes == ["check_dead_code", "check_unused_inputs"]
    # transform passes still transform, and keep accumulated findings
    opt = ir.apply_pass(out, "dead_code_elimination")
    assert opt.num_ops() < prog.num_ops()
    assert _rules(opt.findings) == _rules(out.findings)


def test_analyze_helper_runs_all_rules():
    def fn(x):
        return paddle.log(x - 1.0)
    prog = ir.IrProgram.trace(fn, _tensor((2, 2)))
    fs = analysis.analyze(prog)
    assert "DF005" in _rules(fs)


# ---------------------------------------------------------------------------
# TS101..TS104 — AST trace-safety lint
# ---------------------------------------------------------------------------

TS101_BAD = """
import paddle_tpu as paddle

@paddle.jit.to_static
def f(x):
    s = x * 2
    return float(s.sum())
"""

TS101_ITEM_BAD = """
from paddle_tpu import jit

@jit.to_static
def f(x):
    return x.mean().item()
"""

TS101_GOOD = """
def f(x):
    return float(x.sum())   # eager: host sync is fine outside jit
"""


def test_ts101_flags_host_sync_in_jit():
    assert "TS101" in _rules(ast_lint.lint_source(TS101_BAD))
    assert "TS101" in _rules(ast_lint.lint_source(TS101_ITEM_BAD))


def test_ts101_passes_outside_jit():
    assert ast_lint.lint_source(TS101_GOOD) == []


TS102_BAD = """
import jax

@jax.jit
def f(x):
    if x.sum() > 0:
        return x + 1
    return x - 1
"""

TS102_GOOD = """
import jax

@jax.jit
def f(x, training=True):
    if training:              # literal-defaulted param: static config
        return x + 1
    return x - 1
"""


def test_ts102_flags_data_dependent_branch():
    fs = ast_lint.lint_source(TS102_BAD)
    assert "TS102" in _rules(fs)


def test_ts102_passes_static_config_branch():
    assert "TS102" not in _rules(ast_lint.lint_source(TS102_GOOD))


TS103_BAD = """
import jax

def serve(fns, x):
    outs = []
    for fn in fns:
        step = jax.jit(fn)    # one compile per iteration
        outs.append(step(x))
    return outs
"""

TS103_GOOD = """
import jax

def serve(fns, x):
    steps = [jax.jit(f) for f in fns]
    return None
"""


def test_ts103_flags_jit_in_loop():
    assert "TS103" in _rules(ast_lint.lint_source(TS103_BAD))


def test_ts103_passes_hoisted_jit():
    assert "TS103" not in _rules(ast_lint.lint_source(TS103_GOOD))


TS104_BAD = """
import jax

TRACE_LOG = []

@jax.jit
def f(x):
    print(x)
    TRACE_LOG.append(x)
    return x * 2
"""

TS104_GOOD = """
import jax

@jax.jit
def f(x):
    print("entering f")       # constant print: harmless trace-time noise
    return x * 2
"""


def test_ts104_flags_trace_side_effects():
    fs = [f for f in ast_lint.lint_source(TS104_BAD) if f.rule == "TS104"]
    msgs = " ".join(f.message for f in fs)
    assert "print" in msgs and "TRACE_LOG" in msgs


def test_ts104_passes_constant_print():
    assert "TS104" not in _rules(ast_lint.lint_source(TS104_GOOD))


# ---------------------------------------------------------------------------
# suppressions + baseline
# ---------------------------------------------------------------------------

def test_inline_suppression_on_line():
    src = TS101_BAD.replace("return float(s.sum())",
                            "return float(s.sum())  # tpu-lint: disable=TS101")
    assert "TS101" not in _rules(ast_lint.lint_source(src))


def test_inline_suppression_on_def_line_covers_function():
    src = TS101_BAD.replace("def f(x):",
                            "def f(x):  # tpu-lint: disable=TS101")
    assert "TS101" not in _rules(ast_lint.lint_source(src))


def test_file_wide_suppression():
    src = "# tpu-lint: disable-file=TS101\n" + TS101_BAD
    assert "TS101" not in _rules(ast_lint.lint_source(src))


def test_baseline_roundtrip(tmp_path):
    fs = ast_lint.lint_source(TS101_BAD, path="pkg/mod.py")
    assert fs
    path = str(tmp_path / "baseline.json")
    findings_mod.write_baseline(fs, path)
    baseline = findings_mod.load_baseline(path)
    assert findings_mod.apply_baseline(fs, baseline) == []
    # a different finding is NOT masked by the baseline
    other = ast_lint.lint_source(TS102_BAD, path="pkg/other.py")
    assert findings_mod.apply_baseline(other, baseline) == other


def test_rule_catalog_is_stable():
    assert set(findings_mod.RULES) >= {
        "DF001", "DF002", "DF003", "DF004", "DF005", "DF006",
        "TS101", "TS102", "TS103", "TS104", "TS105",
        "SH201", "SH202", "SH203", "SH204", "MEM301", "MEM302",
        "CC401", "CC402", "CC403", "CC404", "CC405", "CC406"}
    for rule, meta in findings_mod.RULES.items():
        assert meta["severity"] in ("error", "warning")
        assert meta["doc"]
    assert findings_mod.RULES["SH201"]["severity"] == "error"
    assert findings_mod.RULES["MEM301"]["severity"] == "error"


# ---------------------------------------------------------------------------
# TS105 — fresh closure capture (silent recompile-per-call)
# ---------------------------------------------------------------------------

TS105_BAD = """
import numpy as np
import jax

def make_step(scale):
    table = np.array([1.0, 2.0, 3.0])
    @jax.jit
    def step(x):
        return x * table * scale
    return step
"""

TS105_CTOR_BAD = """
import numpy as np
import jax

def make_step():
    mask = np.tril(np.ones((4, 4)))
    def step(x):
        return x * mask
    return jax.jit(step)
"""

TS105_GOOD_MODULE_SCOPE = """
import numpy as np
import jax

TABLE = np.array([1.0, 2.0, 3.0])

def make_step(scale):
    @jax.jit
    def step(x):
        return x * TABLE * scale
    return step
"""

TS105_GOOD_ARGUMENT = """
import numpy as np
import jax

def make_step():
    table = np.array([1.0, 2.0, 3.0])
    @jax.jit
    def step(x, table):
        return x * table
    return step
"""


def test_ts105_flags_fresh_capture_in_decorated_closure():
    fs = [f for f in ast_lint.lint_source(TS105_BAD) if f.rule == "TS105"]
    assert len(fs) == 1
    assert "table" in fs[0].message and "recompile" in fs[0].message


def test_ts105_flags_fresh_capture_via_jit_ctor():
    assert "TS105" in _rules(ast_lint.lint_source(TS105_CTOR_BAD))


def test_ts105_passes_module_scope_and_argument():
    assert ast_lint.lint_source(TS105_GOOD_MODULE_SCOPE) == []
    assert ast_lint.lint_source(TS105_GOOD_ARGUMENT) == []


def test_ts105_suppressed_on_enclosing_def_line():
    src = TS105_BAD.replace("def make_step(scale):",
                            "def make_step(scale):  # tpu-lint: disable=TS105")
    assert "TS105" not in _rules(ast_lint.lint_source(src))


# ---------------------------------------------------------------------------
# SH201..SH204 — SPMD shard-safety (jaxpr propagation)
# ---------------------------------------------------------------------------

from paddle_tpu.analysis import memory as memory_mod  # noqa: E402
from paddle_tpu.analysis import sharding as sharding_mod  # noqa: E402


def _load_plan():
    with open(os.path.join(REPO, "PLAN_7B.json")) as fh:
        return json.load(fh)


def _load_roofline():
    with open(os.path.join(REPO, "ROOFLINE.json")) as fh:
        return json.load(fh)


def test_sh201_flags_non_divisible_input_and_passes_divisible():
    closed = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones((3, 4)))
    fs = analysis.check_sharding(closed, {"x": 2}, in_specs=[("x", None)])
    assert "SH201" in _rules(fs)
    assert all(f.severity == "error" for f in fs if f.rule == "SH201")
    closed2 = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones((4, 4)))
    assert analysis.check_sharding(
        closed2, {"x": 2}, in_specs=[("x", None)]) == []


def test_sh202_flags_one_sided_contraction_and_passes_matched():
    fn = lambda x, w: x @ w
    closed = jax.make_jaxpr(fn)(jnp.ones((8, 16)), jnp.ones((16, 4)))
    fs = analysis.check_sharding(
        closed, {"x": 4}, in_specs=[(None, "x"), (None, None)])
    assert "SH202" in _rules(fs)
    assert any("all-gather" in f.message for f in fs)
    # both operands sharded on the contraction dim: Partial out, no gather
    assert analysis.check_sharding(
        closed, {"x": 4}, in_specs=[(None, "x"), ("x", None)]) == []


def test_sh202_flags_elementwise_placement_disagreement():
    fn = lambda a, b: a + b
    closed = jax.make_jaxpr(fn)(jnp.ones((8, 8)), jnp.ones((8, 8)))
    fs = analysis.check_sharding(
        closed, {"x": 2, "y": 2}, in_specs=[("x", None), ("y", None)])
    assert "SH202" in _rules(fs)
    assert analysis.check_sharding(
        closed, {"x": 2}, in_specs=[("x", None), ("x", None)]) == []


def test_sh202_propagation_resolves_partial_through_psum():
    def fn(x, w):
        return lax.psum(x @ w, "i")
    closed = jax.make_jaxpr(fn, axis_env=[("i", 4)])(
        jnp.ones((8, 16)), jnp.ones((16, 4)))
    res = analysis.propagate_placements(
        closed, {"i": 4}, in_specs=[(None, "i"), ("i", None)])
    out_var = closed.jaxpr.outvars[0]
    assert res.var_specs[out_var].partial == frozenset()
    assert res.collective_bytes > 0


def test_sh203_flags_over_budget_and_passes_generous():
    closed = jax.make_jaxpr(
        lambda v: lax.psum(v, "i"), axis_env=[("i", 2)])(
        jnp.ones((1024, 1024)))
    fs = analysis.check_sharding(
        closed, {"i": 2}, collective_budget_bytes=10.0)
    assert "SH203" in _rules(fs)
    assert analysis.check_sharding(
        closed, {"i": 2}, collective_budget_bytes=1e12) == []


def test_sh203_plan_level_roofline_budget():
    plan, roof = _load_plan(), _load_roofline()
    # the shipped plan is compute-bound under the real roofline
    assert [f for f in analysis.check_plan_sharding(plan, roofline=roof)
            if f.rule == "SH203"] == []
    # a starved interconnect makes every variant ICI-bound
    starved = dict(roof, peak_ici=1e9)
    fs = analysis.check_plan_sharding(plan, roofline=starved)
    assert {f.extra["variant"] for f in fs if f.rule == "SH203"} \
        == {"s2", "s3", "s3_full"}


def test_sh204_flags_replicated_param_and_passes_sharded():
    params = {"w": ((4096, 4096), None),      # big, divisible, replicated
              "ln": ((4096,), None)}          # small: below min_bytes
    fs = analysis.check_fsdp_replication(params, {"z": 16}, "z")
    assert [f.rule for f in fs] == ["SH204"]
    assert fs[0].extra["param"] == "w"
    sharded = {"w": ((4096, 4096), ("z", None))}
    assert analysis.check_fsdp_replication(sharded, {"z": 16}, "z") == []


def test_divisible_dim_is_single_sourced():
    from paddle_tpu.distributed.sharding import _divisible_dim
    for shape, deg in [((7, 8), 4), ((16, 3), 4), ((5, 7), 2), ((8,), 8)]:
        assert _divisible_dim(shape, deg) \
            == analysis.divisible_dim(shape, deg)


# ---------------------------------------------------------------------------
# MEM301/MEM302 — liveness peak-HBM (jaxpr level)
# ---------------------------------------------------------------------------

def test_mem301_flags_tiny_budget_and_passes_generous():
    closed = jax.make_jaxpr(lambda x: jnp.tanh(x) @ x.T)(
        jnp.ones((256, 256)))
    fs = memory_mod.check_hbm(closed, budget_gib=1e-6)
    assert "MEM301" in _rules(fs)
    assert all(f.severity == "error" for f in fs if f.rule == "MEM301")
    fs = memory_mod.check_hbm(closed, budget_gib=64.0, donate=(0,))
    assert "MEM301" not in _rules(fs)


def test_mem302_flags_missing_donation_and_passes_donated():
    # x (4 MiB) dies at exp, whose registry alias metadata permits reuse
    closed = jax.make_jaxpr(lambda x: jnp.exp(x))(jnp.ones((1024, 1024)))
    fs = memory_mod.check_hbm(closed)
    assert [f.rule for f in fs] == ["MEM302"]
    assert "donate" in fs[0].message
    assert memory_mod.check_hbm(closed, donate=(0,)) == []


def test_peak_hbm_estimate_credits_donated_reuse():
    closed = jax.make_jaxpr(lambda x: jnp.exp(x))(
        jnp.ones((1024, 1024), jnp.float32))
    plain = memory_mod.peak_hbm_estimate(closed)
    donated = memory_mod.peak_hbm_estimate(closed, donate=(0,))
    mib = 1 << 20
    assert plain["peak_bytes"] == 8 * mib      # input + fresh output
    assert donated["peak_bytes"] == 4 * mib    # output reuses the input
    assert plain["missed_donations"] and not donated["missed_donations"]


# ---------------------------------------------------------------------------
# MEM301/MEM302 + SH201 — plan-level gate (PLAN_7B.json)
# ---------------------------------------------------------------------------

def test_plan_memory_shipped_variants_pass():
    plan = _load_plan()
    rows = []
    fs = memory_mod.check_plan_memory(plan, rows=rows)
    # documented-infeasible baselines (fits_v5e_16gib: false) are not
    # errors; the MEM302 headroom pointer to s3_full is expected
    assert not findings_mod.has_errors(fs)
    assert {f.rule for f in fs} <= {"MEM302"}
    by_name = {r["variant"]: r for r in rows}
    assert by_name["s3_full"]["fits"]
    # the recorded-bytes model reproduces the recorded live GiB
    assert abs(by_name["s2"]["live_gib"] - 47.384) < 0.01
    assert abs(by_name["s3_full"]["live_gib"] - 12.141) < 0.01


def test_mem301_flags_oversubscribed_s2_at_batch_64():
    plan = _load_plan()
    fs = memory_mod.check_plan_memory(plan, batch=64)
    flagged = {f.extra["variant"] for f in fs if f.rule == "MEM301"}
    assert "s2" in flagged
    assert findings_mod.has_errors(fs)
    s2 = [f for f in fs if f.rule == "MEM301"
          and f.extra["variant"] == "s2"][0]
    assert s2.extra["live_gib"] > 100          # 4x activations over 47 GiB


def test_mem302_plan_points_at_fitting_sibling():
    plan = _load_plan()
    fs = memory_mod.check_plan_memory(plan)
    sibs = {f.extra["variant"]: f.extra["sibling"] for f in fs
            if f.rule == "MEM302"}
    assert sibs == {"s2": "s3_full", "s3": "s3_full"}


def test_plan_sharding_shipped_mesh_passes_and_mesh7_flags_sh201():
    plan, roof = _load_plan(), _load_roofline()
    assert analysis.check_plan_sharding(plan, roofline=roof) == []
    fs = analysis.check_plan_sharding(plan, mesh_size=7)
    assert "SH201" in _rules(fs)
    flagged = {f.extra["param"] for f in fs if f.rule == "SH201"}
    assert "embed" in flagged and "wq" in flagged


def test_serving_buckets_shipped_pass_and_flag_paths():
    plan = _load_plan()
    rep = memory_mod.serving_bucket_report(plan)
    assert rep["findings"] == []
    assert all(r["fits"] for r in rep["rows"])
    assert max(r["bucket"] for r in rep["rows"]) == 2048
    # tiny budget: KV cache blows through it -> MEM301
    rep = memory_mod.serving_bucket_report(plan, hbm_gib=0.5)
    assert "MEM301" in {f.rule for f in rep["findings"]}
    # 7 chips cannot split 32 attention heads -> SH201
    rep = memory_mod.serving_bucket_report(plan, mesh_size=7)
    assert "SH201" in {f.rule for f in rep["findings"]}


# ---------------------------------------------------------------------------
# observability: analysis.findings{rule=...} counters
# ---------------------------------------------------------------------------

def test_analysis_passes_feed_metrics_registry():
    from paddle_tpu.observability import get_registry
    def fn(x):
        dead = paddle.exp(x) * 3.0
        return paddle.tanh(x)
    prog = ir.IrProgram.trace(fn, _tensor((3, 4)))
    fam = get_registry().counter(
        "analysis.findings",
        "findings emitted by static-analysis passes, by rule",
        labelnames=("rule",))
    expected = len(analysis.check_dead_code(prog))
    assert expected >= 1
    before = fam.labels(rule="DF002").value
    ir.apply_pass(prog, "check_dead_code")
    assert fam.labels(rule="DF002").value == before + expected


# ---------------------------------------------------------------------------
# CLI + tier-1 lint gate
# ---------------------------------------------------------------------------

def _run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_lint.py"),
         *args], cwd=cwd, capture_output=True, text=True)


def _run_shard_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "shard_check.py"),
         *args], cwd=cwd, capture_output=True, text=True)


@pytest.mark.lint
@pytest.mark.quick
def test_lint_gate_shipped_tree_is_clean_and_fast():
    t0 = time.monotonic()
    proc = _run_cli("paddle_tpu", "examples", "tools", "benchmarks")
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # runtime guard: the gate must never threaten the tier-1 timeout
    assert elapsed < 10.0, f"lint gate took {elapsed:.1f}s"


@pytest.mark.lint
@pytest.mark.quick
def test_shard_check_gate_shipped_plan_is_clean_and_fast():
    t0 = time.monotonic()
    proc = _run_shard_cli()
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "s3_full" in proc.stdout
    assert elapsed < 10.0, f"shard_check gate took {elapsed:.1f}s"


@pytest.mark.lint
@pytest.mark.quick
def test_trace_analyze_gate_demo_workload_attributes_cleanly():
    """The attribution CLI is part of the lint lane: trace_analyze
    --json over the gateway demo workload must produce complete
    waterfalls, a balanced goodput ledger, and no findings parse
    errors — the smoke gate for the observability.{waterfall,ledger,
    anomaly} stack."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_analyze.py"),
         "--json", "--top", "3"], cwd=REPO, capture_output=True,
        text=True)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["n_traces"] >= 3 and payload["incomplete"] == 0
    assert payload["requests"] and payload["requests"][0]["critical_path"]
    led = payload["ledger"]
    assert led["chip_seconds"] > 0.0 and 0.0 < led["goodput_frac"] <= 1.0
    assert set(led["waste_seconds"]) == {
        "bucket_pad", "requeue_recompute", "evicted_prefix_recompute",
        "speculation_rejected", "recompile", "dequant"}
    assert {"prefill", "decode"} <= set(led["by_phase"])
    assert {"prefill", "decode"} <= set(payload["critical_path_summary"])
    # in-process demo + analysis; generous vs the 10s lint budget
    # because this one boots jax AND runs serving traffic
    assert elapsed < 30.0, f"trace_analyze gate took {elapsed:.1f}s"


@pytest.mark.lint
@pytest.mark.quick
def test_ckpt_inspect_gate_selftest_is_clean_and_fast():
    """tools/ckpt_inspect.py rides the lint lane: its --selftest builds
    a synthetic checkpoint root (one sound step, one torn step, then a
    corrupted payload) with hand-crafted npy bytes and asserts its own
    verdicts — stdlib only, no jax import, so it stays within the 10s
    lint budget."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
         "--selftest"], cwd=REPO, capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selftest" in (proc.stdout + proc.stderr).lower()
    assert elapsed < 10.0, f"ckpt_inspect selftest took {elapsed:.1f}s"


@pytest.mark.lint
@pytest.mark.quick
def test_session_inspect_gate_selftest_is_clean_and_fast():
    """tools/session_inspect.py rides the lint lane: its --selftest
    builds a synthetic session root (sound, torn-publish debris, token
    bit-rot under stale CRCs, chain-hash drift under a re-sealed
    document CRC) and asserts every verdict — stdlib only, no
    numpy/jax import, so it stays within the 10s lint budget."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "session_inspect.py"),
         "--selftest"], cwd=REPO, capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selftest" in (proc.stdout + proc.stderr).lower()
    assert elapsed < 10.0, f"session_inspect selftest took {elapsed:.1f}s"


def test_shard_check_cli_flags_oversubscribed_batch():
    proc = _run_shard_cli("--batch", "64", "--json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    flagged = {f["extra"]["variant"] for f in payload["findings"]
               if f["rule"] == "MEM301"}
    assert "s2" in flagged


def test_shard_check_cli_flags_non_divisible_mesh():
    proc = _run_shard_cli("--mesh", "7", "--json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert any(f["rule"] == "SH201" for f in payload["findings"])


def test_shard_check_cli_what_if_budget_passes():
    # a 64 GiB chip swallows every shipped variant -> exit 0, no MEM302
    proc = _run_shard_cli("--hbm-gib", "64", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert all(r["fits"] for r in payload["variants"])


def test_cli_flags_errors_nonzero_and_emits_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(TS101_BAD)
    proc = _run_cli("--json", "--baseline", "none", str(bad),
                    cwd=str(tmp_path))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert any(f["rule"] == "TS101" for f in payload["findings"])


def test_cli_baseline_accepts_known_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(TS101_BAD)
    base = tmp_path / "base.json"
    proc = _run_cli("--write-baseline", "--baseline", str(base), str(bad),
                    cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_cli("--baseline", str(base), str(bad), cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# CC401-CC404 — static lock-discipline rules (analysis/concurrency.py)
# ---------------------------------------------------------------------------

from paddle_tpu.analysis import concurrency  # noqa: E402


CC401_BAD = """
import threading
A = threading.Lock()
B = threading.Lock()

def forward():
    with A:
        with B:
            pass

def backward():
    with B:
        with A:
            pass
"""

CC401_GOOD = """
import threading
A = threading.Lock()
B = threading.Lock()

def forward():
    with A:
        with B:
            pass

def backward():
    with A:
        with B:
            pass
"""

CC401_TRANSITIVE_BAD = """
import threading
A = threading.Lock()
B = threading.Lock()

def inner():
    with B:
        pass

def forward():
    with A:
        inner()          # A -> B through the call graph

def backward():
    with B:
        with A:
            pass
"""


def test_cc401_flags_lock_order_cycle():
    assert "CC401" in _rules(concurrency.analyze_source(CC401_BAD, "m.py"))


def test_cc401_passes_consistent_order():
    assert "CC401" not in _rules(concurrency.analyze_source(CC401_GOOD, "m.py"))


def test_cc401_sees_acquisitions_through_the_call_graph():
    fs = concurrency.analyze_source(CC401_TRANSITIVE_BAD, "m.py")
    assert "CC401" in _rules(fs)


CC402_BAD = """
import threading
import time
LOCK = threading.Lock()

def slow_path():
    with LOCK:
        time.sleep(0.5)
"""

CC402_GOOD = """
import threading
import time
LOCK = threading.Lock()

def slow_path():
    with LOCK:
        x = 1
    time.sleep(0.5)
"""


def test_cc402_flags_blocking_call_under_lock():
    assert "CC402" in _rules(concurrency.analyze_source(CC402_BAD, "m.py"))


def test_cc402_passes_blocking_call_outside_lock():
    assert "CC402" not in _rules(concurrency.analyze_source(CC402_GOOD, "m.py"))


CC403_BAD = """
import threading

class Owner:
    def __init__(self):
        self._lock = threading.Lock()
        self._callbacks = []

    def fire(self):
        with self._lock:
            for cb in self._callbacks:
                cb("event")
"""

CC403_GOOD = """
import threading

class Owner:
    def __init__(self):
        self._lock = threading.Lock()
        self._callbacks = []

    def fire(self):
        with self._lock:
            cbs = list(self._callbacks)
        for cb in cbs:
            cb("event")
"""


def test_cc403_flags_callback_under_lock():
    assert "CC403" in _rules(concurrency.analyze_source(CC403_BAD))


def test_cc403_passes_callback_after_snapshot():
    assert "CC403" not in _rules(concurrency.analyze_source(CC403_GOOD))


CC404_BAD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def sneak(self):
        self._n = 0          # bare write to lock-guarded state
"""

CC404_GOOD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def reset(self):
        with self._lock:
            self._n = 0
"""


def test_cc404_flags_unguarded_write_to_guarded_state():
    fs = concurrency.analyze_source(CC404_BAD)
    assert "CC404" in _rules(fs)
    assert any("sneak" in f.message for f in fs if f.rule == "CC404")


def test_cc404_passes_when_every_write_is_guarded():
    assert "CC404" not in _rules(concurrency.analyze_source(CC404_GOOD))


def test_cc404_exempts_init_time_writes():
    # __init__ constructs the state the lock will guard — not a race
    fs = concurrency.analyze_source(CC404_GOOD)
    assert not any(f.line <= 7 for f in fs if f.rule == "CC404")


def test_cc_suppression_comment_is_honored():
    src = CC402_BAD.replace("time.sleep(0.5)",
                            "time.sleep(0.5)  # tpu-lint: disable=CC402")
    assert "CC402" not in _rules(concurrency.analyze_source(src, "m.py"))


def test_cc_rules_have_catalog_severities():
    assert findings_mod.RULES["CC401"]["severity"] == "error"
    assert findings_mod.RULES["CC405"]["severity"] == "error"
    assert findings_mod.RULES["CC402"]["severity"] == "warning"


# ---------------------------------------------------------------------------
# CC405/CC406 — the runtime lock witness (utils/locks.py)
# ---------------------------------------------------------------------------


def _fresh_witness(monkeypatch, budget_s=None, value="1"):
    from paddle_tpu.utils import locks
    monkeypatch.setenv("PADDLE_LOCK_WITNESS", value)
    return locks.reset_witness(budget_s=budget_s)


def test_cc405_two_thread_inversion_drill(monkeypatch):
    """The seeded deadlock drill: thread 1 takes A then B, thread 2
    takes B then A (run to completion sequentially, so the drill can
    never actually deadlock) — the witness MUST record the CC405 order
    inversion."""
    import threading

    from paddle_tpu.utils import locks
    _fresh_witness(monkeypatch)
    a, b = locks.TracedLock("drill.A"), locks.TracedLock("drill.B")

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    for fn in (forward, backward):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    found = [f for f in locks.get_witness().findings
             if f["rule"] == "CC405"]
    assert found, "inversion not witnessed"
    assert {"drill.A", "drill.B"} == set(found[0]["locks"])
    # and the typed Finding surface sees it too
    assert "CC405" in {f.rule for f in locks.witness_findings()}


def test_cc405_consistent_order_twin_stays_silent(monkeypatch):
    import threading

    from paddle_tpu.utils import locks
    _fresh_witness(monkeypatch)
    a, b = locks.TracedLock("twin.A"), locks.TracedLock("twin.B")

    def forward():
        with a:
            with b:
                pass

    for _ in range(2):
        t = threading.Thread(target=forward)
        t.start()
        t.join()
    assert not locks.get_witness().findings


def test_cc405_strict_mode_raises_and_releases(monkeypatch):
    from paddle_tpu.utils import locks
    _fresh_witness(monkeypatch, value="strict")
    a, b = locks.TracedLock("strict.A"), locks.TracedLock("strict.B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locks.LockOrderInversion):
            a.acquire()
    # the refused acquisition must not leave either lock held
    assert a.acquire(timeout=0.1)
    a.release()


def test_cc406_over_budget_hold_is_recorded(monkeypatch):
    from paddle_tpu.utils import locks
    _fresh_witness(monkeypatch, budget_s=0.005)
    lk = locks.TracedLock("budget.L")
    with lk:
        time.sleep(0.02)
    w = locks.get_witness()
    assert any(f["rule"] == "CC406" for f in w.findings)
    assert w.max_hold("budget.L") >= 0.005


def test_witness_dump_roundtrips_through_audit(tmp_path, monkeypatch):
    import threading

    from paddle_tpu.utils import locks
    _fresh_witness(monkeypatch)
    a, b = locks.TracedLock("rt.A"), locks.TracedLock("rt.B")

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    for fn in (forward, backward):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    path = tmp_path / "witness_test.json"
    locks.dump_witness(str(path))
    fs = concurrency.audit_witness_paths([str(tmp_path)])
    assert "CC405" in _rules(fs)


def test_witness_off_hands_out_raw_locks(monkeypatch):
    """The <1%% overhead guard, proven structurally: with the witness
    off the factories return RAW threading primitives — the hot path
    pays literally zero instrumentation."""
    import threading

    from paddle_tpu.utils import locks
    monkeypatch.delenv("PADDLE_LOCK_WITNESS", raising=False)
    assert type(locks.TracedLock("x")) is type(threading.Lock())
    assert type(locks.TracedRLock("x")) is type(threading.RLock())
    assert not locks.witness_enabled()


@pytest.mark.quick
def test_witness_off_overhead_under_one_percent(monkeypatch):
    """Belt to the structural suspenders: time a serving-step-shaped
    critical section (dict bookkeeping under a lock) with a plain
    threading.Lock vs a witness-off TracedLock. Identical types, so
    the budget only needs to absorb timer noise."""
    import threading

    from paddle_tpu.utils import locks
    monkeypatch.delenv("PADDLE_LOCK_WITNESS", raising=False)

    def drive(lk, n=20000):
        state = {}
        t0 = time.perf_counter()
        for i in range(n):
            with lk:
                state[i & 63] = i
        return time.perf_counter() - t0

    raw, traced = threading.Lock(), locks.TracedLock("serve.step")
    drive(raw), drive(traced)                      # warm both paths
    t_raw = min(drive(raw) for _ in range(3))
    t_traced = min(drive(traced) for _ in range(3))
    # same type -> same cost; 25% headroom swallows scheduler noise in
    # a shared CI box while still catching any accidental wrapper
    assert t_traced < t_raw * 1.25, (t_raw, t_traced)


@pytest.mark.lint
@pytest.mark.quick
def test_race_check_gate_shipped_tree_is_clean_and_fast():
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "race_check.py"),
         "paddle_tpu", "tools", "benchmarks"],
        cwd=REPO, capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # runtime guard: the gate must never threaten the tier-1 timeout
    assert elapsed < 10.0, f"race_check gate took {elapsed:.1f}s"


def test_race_check_cli_flags_cycle_and_respects_baseline(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(CC401_BAD)

    def run(*args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "race_check.py"),
             *args], cwd=str(tmp_path), capture_output=True, text=True)

    proc = run("--json", "--baseline", "none", str(bad))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert any(f["rule"] == "CC401" for f in payload["findings"])
    base = tmp_path / "base.json"
    proc = run("--write-baseline", "--baseline", str(base), str(bad))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = run("--baseline", str(base), str(bad))
    assert proc.returncode == 0, proc.stdout + proc.stderr
