"""Tests for the breadth namespaces: paddle.linalg, paddle.fft,
paddle.signal, and paddle.distribution (reference test dirs: test/fft,
test/distribution, test/legacy_test linalg op tests)."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _np(t):
    return np.asarray(t._data)


# -- linalg ------------------------------------------------------------------

def test_linalg_namespace_matches_numpy():
    rng = np.random.RandomState(0)
    a = rng.randn(4, 4).astype(np.float32)
    spd = (a @ a.T + 4 * np.eye(4)).astype(np.float32)
    x = paddle.to_tensor(spd)

    np.testing.assert_allclose(_np(paddle.linalg.inv(x)), np.linalg.inv(spd),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(paddle.linalg.det(x)), np.linalg.det(spd),
                               rtol=1e-4)
    L = _np(paddle.linalg.cholesky(x))
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    s = _np(paddle.linalg.svdvals(x))
    np.testing.assert_allclose(s, np.linalg.svd(spd, compute_uv=False),
                               rtol=1e-4)


def test_linalg_lu_roundtrip():
    rng = np.random.RandomState(1)
    a = rng.randn(5, 5).astype(np.float32)
    lu_mat, piv = paddle.linalg.lu(paddle.to_tensor(a))
    P, L, U = paddle.linalg.lu_unpack(lu_mat, piv)
    np.testing.assert_allclose(_np(P) @ _np(L) @ _np(U), a, rtol=1e-4,
                               atol=1e-4)


def test_linalg_matrix_exp():
    a = np.array([[0.0, 1.0], [-1.0, 0.0]], dtype=np.float32)  # rotation gen
    E = _np(paddle.linalg.matrix_exp(paddle.to_tensor(a)))
    expect = np.array([[math.cos(1), math.sin(1)],
                       [-math.sin(1), math.cos(1)]], dtype=np.float32)
    np.testing.assert_allclose(E, expect, rtol=1e-5, atol=1e-6)


def test_linalg_householder_product_matches_explicit():
    # explicit product of (I - tau v v^T) against the accumulated version
    rng = np.random.RandomState(2)
    m, n = 4, 3
    a = rng.randn(m, n).astype(np.float64)
    tau = rng.rand(n).astype(np.float64)
    Q = _np(paddle.linalg.householder_product(paddle.to_tensor(a),
                                              paddle.to_tensor(tau)))
    ref = np.eye(m)
    for i in range(n):
        v = a[:, i].copy()
        v[:i] = 0.0
        v[i] = 1.0
        ref = ref @ (np.eye(m) - tau[i] * np.outer(v, v))
    np.testing.assert_allclose(Q, ref[:, :n], rtol=1e-4, atol=1e-5)


def test_linalg_svd_lowrank():
    rng = np.random.RandomState(3)
    base = rng.randn(20, 3).astype(np.float32)
    a = base @ rng.randn(3, 15).astype(np.float32)  # rank 3
    U, S, V = paddle.linalg.svd_lowrank(paddle.to_tensor(a), q=3)
    approx = _np(U) @ np.diag(_np(S)) @ _np(V).T
    np.testing.assert_allclose(approx, a, rtol=1e-3, atol=1e-3)


def test_linalg_cond_vector_matrix_norm():
    a = np.diag([4.0, 2.0]).astype(np.float32)
    assert float(paddle.linalg.cond(paddle.to_tensor(a))) == pytest.approx(2.0)
    v = paddle.to_tensor(np.array([3.0, 4.0], dtype=np.float32))
    assert float(paddle.linalg.vector_norm(v)) == pytest.approx(5.0)
    m = paddle.to_tensor(np.eye(3, dtype=np.float32) * 2)
    assert float(paddle.linalg.matrix_norm(m, "fro")) == pytest.approx(
        math.sqrt(12), rel=1e-5)


# -- fft ---------------------------------------------------------------------

def test_fft_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(_np(paddle.fft.fft(t)), np.fft.fft(x),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(_np(paddle.fft.rfft(t)), np.fft.rfft(x),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(_np(paddle.fft.fft2(t)), np.fft.fft2(x),
                               rtol=1e-3, atol=1e-3)


def test_fft_roundtrip_and_norms():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 32).astype(np.float32)
    t = paddle.to_tensor(x)
    for norm in ("backward", "forward", "ortho"):
        back = paddle.fft.ifft(paddle.fft.fft(t, norm=norm), norm=norm)
        np.testing.assert_allclose(_np(back).real, x, rtol=1e-4, atol=1e-4)
    back_r = paddle.fft.irfft(paddle.fft.rfft(t), n=32)
    np.testing.assert_allclose(_np(back_r), x, rtol=1e-4, atol=1e-4)


def test_fft_helpers():
    np.testing.assert_allclose(_np(paddle.fft.fftfreq(8, d=0.5)),
                               np.fft.fftfreq(8, d=0.5))
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    np.testing.assert_allclose(_np(paddle.fft.fftshift(x)),
                               np.fft.fftshift(np.arange(8)))


def test_fft_differentiable():
    x = paddle.to_tensor(np.random.RandomState(2).randn(16).astype(np.float32),
                         stop_gradient=False)
    y = paddle.fft.rfft(x)
    energy = (y.abs() ** 2).sum()
    energy.backward()
    g = _np(x.grad)
    # Parseval: d/dx sum|X|^2 = 2*N*x for rfft of real signal (approximately,
    # accounting for one/two-sided bins) — just check finite and nonzero
    assert np.all(np.isfinite(g)) and np.abs(g).sum() > 0


# -- signal ------------------------------------------------------------------

def test_stft_istft_roundtrip():
    rng = np.random.RandomState(0)
    sig = rng.randn(2, 256).astype(np.float32)
    t = paddle.to_tensor(sig)
    n_fft = 64
    win = paddle.to_tensor(np.hanning(n_fft).astype(np.float32))
    spec = paddle.signal.stft(t, n_fft=n_fft, hop_length=16, window=win)
    assert tuple(spec.shape) == (2, n_fft // 2 + 1, 256 // 16 + 1)
    rec = paddle.signal.istft(spec, n_fft=n_fft, hop_length=16, window=win,
                              length=256)
    np.testing.assert_allclose(_np(rec), sig, rtol=1e-3, atol=1e-3)


def test_frame_overlap_add():
    x = paddle.to_tensor(np.arange(10, dtype=np.float32))
    f = paddle.signal.frame(x, frame_length=4, hop_length=2)
    assert tuple(f.shape) == (4, 4)
    np.testing.assert_allclose(_np(f)[0], [0, 1, 2, 3])
    np.testing.assert_allclose(_np(f)[1], [2, 3, 4, 5])
    y = paddle.signal.overlap_add(f, hop_length=2)
    # middle samples are double-counted by the 50% overlap
    assert _np(y).shape == (10,)


# -- distribution -------------------------------------------------------------

def test_normal_moments_and_log_prob():
    d = D.Normal(loc=1.0, scale=2.0)
    assert float(d.mean) == pytest.approx(1.0)
    assert float(d.variance) == pytest.approx(4.0)
    lp = float(d.log_prob(paddle.to_tensor(1.0)))
    assert lp == pytest.approx(-math.log(2.0 * math.sqrt(2 * math.pi)))
    assert float(d.cdf(paddle.to_tensor(1.0))) == pytest.approx(0.5)
    s = d.sample((5000,))
    assert abs(float(s.mean()) - 1.0) < 0.15
    assert abs(float(s.std()) - 2.0) < 0.15


def test_normal_rsample_reparameterized_grad():
    loc = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
    scale = paddle.to_tensor(np.float32(1.5), stop_gradient=False)
    d = D.Normal(loc=loc, scale=scale)
    s = d.rsample((64,))
    s.sum().backward()
    assert float(loc.grad) == pytest.approx(64.0)  # d(loc + scale*eps)/dloc
    assert np.isfinite(float(scale.grad))


def test_uniform_and_entropy():
    d = D.Uniform(low=0.0, high=4.0)
    assert float(d.entropy()) == pytest.approx(math.log(4.0))
    assert float(d.log_prob(paddle.to_tensor(2.0))) == pytest.approx(
        -math.log(4.0))
    s = _np(d.sample((2000,)))
    assert s.min() >= 0 and s.max() < 4


def test_categorical_sample_logprob_entropy():
    logits = paddle.to_tensor(np.log(np.array([0.1, 0.2, 0.7],
                                              dtype=np.float32)))
    d = D.Categorical(logits)
    lp = _np(d.log_prob(paddle.to_tensor(np.array([2]))))
    assert lp[0] == pytest.approx(math.log(0.7), rel=1e-4)
    ent = float(d.entropy())
    expect = -(0.1 * math.log(0.1) + 0.2 * math.log(0.2)
               + 0.7 * math.log(0.7))
    assert ent == pytest.approx(expect, rel=1e-4)
    paddle.seed(0)
    s = _np(d.sample((4000,)))
    assert abs((s == 2).mean() - 0.7) < 0.05


def test_bernoulli_and_kl():
    p = D.Bernoulli(paddle.to_tensor(np.float32(0.3)))
    q = D.Bernoulli(paddle.to_tensor(np.float32(0.5)))
    kl = float(D.kl_divergence(p, q))
    expect = 0.3 * math.log(0.3 / 0.5) + 0.7 * math.log(0.7 / 0.5)
    assert kl == pytest.approx(expect, rel=1e-3)


def test_kl_normal_closed_form():
    p = D.Normal(0.0, 1.0)
    q = D.Normal(1.0, 2.0)
    kl = float(D.kl_divergence(p, q))
    expect = math.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    assert kl == pytest.approx(expect, rel=1e-5)


def test_gamma_beta_dirichlet_moments():
    g = D.Gamma(concentration=3.0, rate=2.0)
    assert float(g.mean) == pytest.approx(1.5)
    assert float(g.variance) == pytest.approx(0.75)
    b = D.Beta(2.0, 3.0)
    assert float(b.mean) == pytest.approx(0.4)
    dd = D.Dirichlet(paddle.to_tensor(np.array([1.0, 2.0, 3.0],
                                               dtype=np.float32)))
    np.testing.assert_allclose(_np(dd.mean), [1 / 6, 2 / 6, 3 / 6],
                               rtol=1e-5)
    s = dd.sample((100,))
    np.testing.assert_allclose(_np(s.sum(axis=-1)), np.ones(100), rtol=1e-4)


def test_lognormal_and_exponential():
    ln = D.LogNormal(0.0, 0.5)
    assert float(ln.mean) == pytest.approx(math.exp(0.125), rel=1e-5)
    ex = D.Exponential(rate=2.0)
    assert float(ex.mean) == pytest.approx(0.5)
    assert float(ex.cdf(paddle.to_tensor(1.0))) == pytest.approx(
        1 - math.exp(-2.0), rel=1e-5)


def test_multivariate_normal():
    cov = np.array([[2.0, 0.5], [0.5, 1.0]], dtype=np.float32)
    d = D.MultivariateNormal(paddle.to_tensor(np.zeros(2, np.float32)),
                             covariance_matrix=paddle.to_tensor(cov))
    np.testing.assert_allclose(_np(d.variance), np.diag(cov), rtol=1e-5)
    import scipy.stats as st
    v = np.array([0.3, -0.2], dtype=np.float32)
    lp = float(d.log_prob(paddle.to_tensor(v)))
    assert lp == pytest.approx(
        st.multivariate_normal(np.zeros(2), cov).logpdf(v), rel=1e-4)


def test_transformed_distribution_lognormal_equiv():
    base = D.Normal(0.0, 1.0)
    td = D.TransformedDistribution(base, [D.ExpTransform()])
    ref = D.LogNormal(0.0, 1.0)
    v = paddle.to_tensor(np.float32(1.7))
    assert float(td.log_prob(v)) == pytest.approx(float(ref.log_prob(v)),
                                                  rel=1e-5)


def test_transform_forward_inverse():
    t = D.ChainTransform([D.AffineTransform(1.0, 2.0), D.TanhTransform()])
    x = paddle.to_tensor(np.array([0.1, -0.3], dtype=np.float32))
    y = t.forward(x)
    back = t.inverse(y)
    np.testing.assert_allclose(_np(back), _np(x), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(y), np.tanh(1 + 2 * _np(x)), rtol=1e-5)


def test_stickbreaking_simplex():
    t = D.StickBreakingTransform()
    x = paddle.to_tensor(np.array([0.5, -1.0, 2.0], dtype=np.float32))
    y = _np(t.forward(x))
    assert y.shape == (4,)
    assert y.sum() == pytest.approx(1.0, rel=1e-5)
    assert (y > 0).all()
    back = _np(t.inverse(paddle.to_tensor(y)))
    np.testing.assert_allclose(back, _np(x), rtol=1e-4, atol=1e-4)


def test_independent_reinterprets_batch():
    base = D.Normal(paddle.to_tensor(np.zeros((3, 4), np.float32)),
                    paddle.to_tensor(np.ones((3, 4), np.float32)))
    ind = D.Independent(base, 1)
    assert ind.batch_shape == (3,)
    assert ind.event_shape == (4,)
    v = paddle.to_tensor(np.zeros((3, 4), np.float32))
    lp = _np(ind.log_prob(v))
    assert lp.shape == (3,)
    unit = D.Normal(0.0, 1.0)
    assert lp[0] == pytest.approx(
        4 * float(unit.log_prob(paddle.to_tensor(0.0))), rel=1e-5)


def test_poisson_and_geometric():
    po = D.Poisson(rate=3.0)
    assert float(po.mean) == 3.0
    lp = float(po.log_prob(paddle.to_tensor(2.0)))
    assert lp == pytest.approx(2 * math.log(3) - 3 - math.log(2), rel=1e-4)
    ge = D.Geometric(probs=0.25)
    assert float(ge.mean) == pytest.approx(3.0)


def test_kl_unregistered_raises():
    with pytest.raises(NotImplementedError):
        D.kl_divergence(D.Normal(0.0, 1.0), D.Uniform(0.0, 1.0))


# -- breadth ops (round-1 additions) ------------------------------------------

def test_diagonal_unflatten_take():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(_np(paddle.diagonal(x)), [0, 5, 10])
    u = paddle.unflatten(paddle.to_tensor(np.zeros(24, np.float32)), 0,
                         [4, -1])
    assert tuple(u.shape) == (4, 6)
    t = paddle.take(x, paddle.to_tensor(np.array([0, 5, 11])))
    np.testing.assert_allclose(_np(t), [0, 5, 11])
    tw = paddle.take(x, paddle.to_tensor(np.array([12])), mode="wrap")
    np.testing.assert_allclose(_np(tw), [0])


def test_tensordot_and_trapezoid():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
    np.testing.assert_allclose(_np(paddle.tensordot(a, b, axes=1)),
                               _np(a) @ _np(b))
    y = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    assert float(paddle.trapezoid(y)) == pytest.approx(4.0)
    x = paddle.to_tensor(np.array([0.0, 1.0, 3.0], np.float32))
    assert float(paddle.trapezoid(y, x=x)) == pytest.approx(
        np.trapezoid([1, 2, 3], [0, 1, 3]))


def test_kthvalue_mode_quantile():
    x = paddle.to_tensor(np.array([[3.0, 1.0, 2.0],
                                   [5.0, 5.0, 4.0]], np.float32))
    v, i = paddle.kthvalue(x, 2)
    np.testing.assert_allclose(_np(v), [2.0, 5.0])
    mv, mi = paddle.mode(x)
    np.testing.assert_allclose(_np(mv)[1], 5.0)
    assert int(_np(mi)[1]) == 1  # last occurrence of the mode value
    q = paddle.quantile(paddle.to_tensor(
        np.arange(5, dtype=np.float32)), 0.5)
    assert float(q) == pytest.approx(2.0)
    nx = paddle.to_tensor(np.array([1.0, np.nan, 3.0], np.float32))
    assert float(paddle.nanquantile(nx, 0.5)) == pytest.approx(2.0)


def test_scatter_view_family():
    x = paddle.to_tensor(np.zeros((3, 4), np.float32))
    out = paddle.select_scatter(x, paddle.to_tensor(
        np.ones(4, np.float32)), axis=0, index=1)
    np.testing.assert_allclose(_np(out)[1], 1.0)
    np.testing.assert_allclose(_np(out)[0], 0.0)

    out2 = paddle.slice_scatter(x, paddle.to_tensor(
        np.ones((3, 2), np.float32)), axes=[1], starts=[1], ends=[3],
        strides=[1])
    np.testing.assert_allclose(_np(out2)[:, 1:3], 1.0)
    np.testing.assert_allclose(_np(out2)[:, 0], 0.0)

    v = paddle.view(x, [4, 3])
    assert tuple(v.shape) == (4, 3)
    va = paddle.view_as(x, paddle.to_tensor(np.zeros((2, 6))))
    assert tuple(va.shape) == (2, 6)

    filled = paddle.index_fill(x, paddle.to_tensor(np.array([0, 2])), 0, 7.0)
    np.testing.assert_allclose(_np(filled)[0], 7.0)
    np.testing.assert_allclose(_np(filled)[1], 0.0)


def test_new_ops_differentiable():
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 4)
                         .astype(np.float32), stop_gradient=False)
    y = paddle.diagonal(x).sum() + paddle.tensordot(x, x, axes=[[0, 1],
                                                                [0, 1]])
    y.backward()
    g = _np(x.grad)
    expect = np.eye(3, 4) + 2 * _np(x)
    np.testing.assert_allclose(g, expect, rtol=1e-5)


def test_view_dtype_scales_last_dim():
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    half = paddle.view(x, "float16")
    assert tuple(half.shape) == (2, 8)
    back = paddle.view(half, "float32")
    assert tuple(back.shape) == (2, 4)
    np.testing.assert_allclose(_np(back), 1.0)


def test_kthvalue_validates_k():
    x = paddle.to_tensor(np.arange(4, dtype=np.float32))
    with pytest.raises(ValueError, match="out of range"):
        paddle.kthvalue(x, 0)
    with pytest.raises(ValueError, match="out of range"):
        paddle.kthvalue(x, 5)


def test_nan_checker_does_not_break_jit():
    from paddle_tpu import jit
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        @jit.to_static  # full_graph=True default: must NOT raise
        def f(x):
            return (x * 2).sum()

        x = paddle.to_tensor(np.ones(8, np.float32))
        assert float(f(x)) == 16.0
        assert float(f(x)) == 16.0  # compiled pass with hook active
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_debug_step_window_gates_checks():
    from paddle_tpu.amp import debugging as dbg
    from paddle_tpu import nn, optimizer
    cfg = dbg.TensorCheckerConfig(enable=True, debug_step=(2, 100))
    dbg.enable_tensor_checker(cfg)
    try:
        net = nn.Linear(2, 2)
        opt = optimizer.SGD(learning_rate=1e9,  # guarantees overflow later
                            parameters=net.parameters())
        x = paddle.to_tensor(np.ones((1, 2), np.float32) * 1e20)
        # steps 0-1: window closed, nan outputs pass silently
        bad = paddle.to_tensor(np.array([np.inf], np.float32))
        _ = bad - bad  # nan, but step 0 < window start -> unchecked
        opt.step(); opt.clear_grad()
        opt.step(); opt.clear_grad()
        # now inside the window: checking active
        with pytest.raises(FloatingPointError):
            _ = bad - bad
    finally:
        dbg.disable_tensor_checker()
