"""nn long-tail surface (nn/functional_extras.py, nn/layers_extra.py).

Reference test model: test/legacy_test/test_pool3d_op.py, test_unpool_op,
test_conv*_transpose_op, per-loss op tests, test_ctc_align/test_warpctc,
test_warprnnt, test_affine_grid/test_grid_sampler, test_beam_search_decode.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(7)


def _t(a, d="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=d))


def _np(x):
    return np.asarray(x._data)


class TestPooling3D:
    def test_max_avg_pool3d(self):
        x = _t(RNG.randn(2, 3, 8, 8, 8))
        assert list(F.max_pool3d(x, 2).shape) == [2, 3, 4, 4, 4]
        out = F.avg_pool3d(x, 2, stride=2)
        ref = _np(x).reshape(2, 3, 4, 2, 4, 2, 4, 2).mean((3, 5, 7))
        np.testing.assert_allclose(_np(out), ref, atol=1e-5)

    def test_adaptive_pools(self):
        x = _t(RNG.randn(2, 3, 9, 9, 9))
        assert list(F.adaptive_avg_pool3d(x, 3).shape) == [2, 3, 3, 3, 3]
        assert list(F.adaptive_max_pool3d(x, 2).shape) == [2, 3, 2, 2, 2]
        x1 = _t(RNG.randn(2, 3, 12))
        out = F.adaptive_max_pool1d(x1, 4)
        ref = _np(x1).reshape(2, 3, 4, 3).max(-1)
        np.testing.assert_allclose(_np(out), ref, atol=1e-6)

    def test_unpool_roundtrip(self):
        x = _t(RNG.randn(1, 2, 6, 6))
        pooled, mask = F.max_pool2d(x, 2, return_mask=True)
        un = F.max_unpool2d(pooled, mask, 2)
        assert un.shape == x.shape
        # every pooled max lands back at its original position
        np.testing.assert_allclose(_np(un).max(), _np(x).max(), atol=1e-6)
        nz = _np(un) != 0
        assert nz.sum() == np.prod(pooled.shape)

    def test_unpool_1d_3d(self):
        x1 = _t(RNG.randn(1, 2, 8))
        p1, m1 = F.max_pool1d(x1, 2, return_mask=True)
        assert list(F.max_unpool1d(p1, m1, 2).shape) == [1, 2, 8]
        x3 = _t(RNG.randn(1, 2, 4, 4, 4))
        p3, m3 = F.max_pool3d(x3, 2, return_mask=True)
        assert list(F.max_unpool3d(p3, m3, 2).shape) == [1, 2, 4, 4, 4]

    def test_fractional_pool(self):
        x = _t(RNG.randn(1, 2, 9, 9))
        out = F.fractional_max_pool2d(x, 3, random_u=0.4)
        assert list(out.shape) == [1, 2, 3, 3]
        # every output value is a real input value
        assert np.isin(_np(out), _np(x)).all()


class TestConvTranspose:
    def test_conv1d_transpose_shape_and_value(self):
        x = _t(np.ones((1, 1, 4)))
        w = _t(np.ones((1, 1, 2)))
        out = F.conv1d_transpose(x, w, stride=2)
        assert list(out.shape) == [1, 1, 8]
        # stride-2 transpose of ones with kernel ones -> all ones
        np.testing.assert_allclose(_np(out), 1.0)

    def test_conv3d_transpose_shape(self):
        x = _t(RNG.randn(2, 3, 4, 4, 4))
        w = _t(RNG.randn(3, 5, 3, 3, 3) * 0.1)
        out = F.conv3d_transpose(x, w, stride=2)
        assert list(out.shape) == [2, 5, 9, 9, 9]

    def test_layer_classes(self):
        conv = nn.Conv1DTranspose(2, 3, 3)
        assert list(conv(_t(RNG.randn(1, 2, 8))).shape) == [1, 3, 10]
        conv3 = nn.Conv3DTranspose(2, 3, 3)
        assert list(conv3(_t(RNG.randn(1, 2, 4, 4, 4))).shape) \
            == [1, 3, 6, 6, 6]


class TestLossZoo:
    def test_ctc_loss_matches_brute_force(self):
        T, B, C, L = 4, 1, 3, 2
        logits = RNG.randn(T, B, C).astype("float32")
        labels = np.array([[1, 2]], dtype="int64")
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))

        def collapse(path, blank=0):
            out, prev = [], None
            for s in path:
                if s != prev and s != blank:
                    out.append(s)
                prev = s
            return out

        total = -np.inf
        for path in itertools.product(range(C), repeat=T):
            if collapse(path) == [1, 2]:
                total = np.logaddexp(total, sum(
                    lp[i, 0, s] for i, s in enumerate(path)))
        loss = F.ctc_loss(_t(logits), _t(labels, "int64"),
                          _t([T], "int64"), _t([L], "int64"),
                          reduction="none")
        assert abs(float(_np(loss)[0]) + total) < 1e-4

    def test_ctc_gradient(self):
        logits = _t(RNG.randn(5, 2, 4))
        logits.stop_gradient = False
        loss = F.ctc_loss(logits, _t([[1, 2], [3, 1]], "int64"),
                          _t([5, 5], "int64"), _t([2, 2], "int64"))
        loss.backward()
        assert np.isfinite(_np(logits.grad)).all()

    def test_rnnt_loss_matches_hand_dp(self):
        B, T, U, C = 1, 2, 1, 3
        logits = RNG.randn(B, T, U + 1, C).astype("float32")
        lab = np.array([[1]], dtype="int64")
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        a01 = lp[0, 0, 0, 1]
        a10 = lp[0, 0, 0, 0]
        a11 = np.logaddexp(a01 + lp[0, 0, 1, 0], a10 + lp[0, 1, 0, 1])
        ref = -(a11 + lp[0, 1, 1, 0])
        loss = F.rnnt_loss(_t(logits), _t(lab, "int64"), _t([T], "int64"),
                           _t([U], "int64"), reduction="none")
        assert abs(float(_np(loss)[0]) - ref) < 1e-4

    @pytest.mark.parametrize("fn,args", [
        ("dice_loss", lambda: (_t(np.abs(RNG.rand(4, 5))),
                               _t(RNG.randint(0, 5, (4, 1)), "int64"))),
        ("poisson_nll_loss", lambda: (_t(RNG.randn(8)),
                                      _t(np.abs(RNG.randn(8))))),
        ("soft_margin_loss", lambda: (_t(RNG.randn(6)),
                                      _t(np.sign(RNG.randn(6))))),
        ("multi_margin_loss", lambda: (_t(RNG.randn(4, 5)),
                                       _t([0, 1, 2, 3], "int64"))),
        ("cosine_embedding_loss", lambda: (_t(RNG.randn(4, 8)),
                                           _t(RNG.randn(4, 8)),
                                           _t([1, -1, 1, -1], "int64"))),
        ("triplet_margin_loss", lambda: (_t(RNG.randn(4, 8)),
                                         _t(RNG.randn(4, 8)),
                                         _t(RNG.randn(4, 8)))),
    ])
    def test_losses_finite_scalar(self, fn, args):
        out = getattr(F, fn)(*args())
        assert np.isfinite(float(_np(out)))

    def test_sigmoid_focal_reduces_easy_examples(self):
        logit = _t([10.0, -10.0])       # confident correct predictions
        label = _t([1.0, 0.0])
        easy = float(_np(F.sigmoid_focal_loss(logit, label)))
        hard = float(_np(F.sigmoid_focal_loss(_t([0.0, 0.0]), label)))
        assert easy < hard

    def test_gaussian_nll_prefers_correct_variance(self):
        x = _t(RNG.randn(100))
        lab = x + _t(RNG.randn(100) * 0.1)
        good = float(_np(F.gaussian_nll_loss(x, lab, _t(np.full(100, 0.01)))))
        bad = float(_np(F.gaussian_nll_loss(x, lab, _t(np.full(100, 100.0)))))
        assert good < bad

    def test_margin_ce_equals_ce_at_zero_margin(self):
        import jax
        logits = _t(RNG.rand(4, 10) * 0.8 - 0.4)
        lab = _t([1, 2, 3, 4], "int64")
        mce = F.margin_cross_entropy(logits, lab, margin1=1.0, margin2=0.0,
                                     margin3=0.0, scale=1.0)
        ref = -np.take_along_axis(
            np.asarray(jax.nn.log_softmax(logits._data)),
            np.array([[1], [2], [3], [4]]), 1).mean()
        assert abs(float(_np(mce)) - ref) < 1e-5


class TestSpatialTransformer:
    def test_identity_affine(self):
        theta = _t(np.array([[[1.0, 0, 0], [0, 1.0, 0]]]))
        grid = F.affine_grid(theta, [1, 1, 5, 5])
        x = _t(RNG.randn(1, 1, 5, 5))
        out = F.grid_sample(x, grid, align_corners=True)
        np.testing.assert_allclose(_np(out), _np(x), atol=1e-5)

    def test_translation_shifts(self):
        theta = _t(np.array([[[1.0, 0, 0.5], [0, 1.0, 0]]]))
        grid = F.affine_grid(theta, [1, 1, 4, 4])
        x = _t(np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
        out = F.grid_sample(x, grid, mode="nearest")
        # sampling 0.5 to the right in normalized coords -> columns shift
        assert not np.allclose(_np(out), _np(x))

    def test_grid_sample_border_padding(self):
        x = _t(np.ones((1, 1, 3, 3)))
        theta = _t(np.array([[[2.0, 0, 0], [0, 2.0, 0]]]))  # zoom out
        grid = F.affine_grid(theta, [1, 1, 3, 3])
        out_border = F.grid_sample(x, grid, padding_mode="border")
        np.testing.assert_allclose(_np(out_border), 1.0)
        out_zero = F.grid_sample(x, grid, padding_mode="zeros")
        assert _np(out_zero).min() == 0.0


class TestMiscLayers:
    def test_shuffles(self):
        x = _t(RNG.randn(1, 8, 4, 4))
        un = F.pixel_unshuffle(F.pixel_shuffle(x, 2), 2)
        np.testing.assert_allclose(_np(un), _np(x), atol=1e-6)
        cs = F.channel_shuffle(x, 2)
        # shuffle twice with inverse group count restores order
        back = F.channel_shuffle(cs, 4)
        np.testing.assert_allclose(_np(back), _np(x), atol=1e-6)

    def test_sequence_mask_and_zeropad(self):
        m = F.sequence_mask(_t([2, 4], "int32"), maxlen=5)
        np.testing.assert_array_equal(
            _np(m), [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])
        out = F.zeropad2d(_t(RNG.randn(1, 2, 3, 3)), [1, 2, 3, 4])
        assert list(out.shape) == [1, 2, 10, 6]

    def test_spectral_norm_sigma_one(self):
        sn = nn.SpectralNorm([6, 10], power_iters=20)
        w = _t(RNG.randn(6, 10) * 3)
        wn = sn(w)
        sigma = np.linalg.svd(_np(wn), compute_uv=False)[0]
        assert abs(sigma - 1.0) < 0.05

    def test_beam_search_decode(self):
        class Cell:
            def __call__(self, tokens, states):
                logits = paddle.to_tensor(np.tile(
                    np.array([[0.1, 5.0, 0.2, 3.0]], dtype="float32"),
                    (tokens.shape[0], 1)))
                return logits, states

        dec = nn.BeamSearchDecoder(Cell(), start_token=0, end_token=1,
                                   beam_size=2)
        ids, scores = nn.dynamic_decode(dec, [_t(np.zeros((2, 3)))],
                                        max_step_num=6)
        assert _np(ids).shape[0] == 2 and _np(scores).shape == (2, 2)
        # best beam should pick the end token (highest logit) immediately
        assert _np(ids)[0, 0, 0] == 1

    def test_unflatten_softmax2d(self):
        assert list(nn.Unflatten(1, [2, 3])(_t(RNG.randn(4, 6))).shape) \
            == [4, 2, 3]
        out = nn.Softmax2D()(_t(RNG.randn(1, 3, 2, 2)))
        np.testing.assert_allclose(_np(out).sum(axis=1), 1.0, atol=1e-5)

    def test_inplace_activations(self):
        x = _t(RNG.randn(8))
        ref = np.tanh(_np(x))
        F.tanh_(x)
        np.testing.assert_allclose(_np(x), ref, atol=1e-6)


class TestHSigmoid:
    def _manual(self, x, nodes, codes, w, b):
        out = np.zeros((x.shape[0], 1), np.float32)
        for i in range(x.shape[0]):
            total = 0.0
            for k in range(nodes.shape[1]):
                nd = nodes[i, k]
                if nd < 0:
                    continue
                z = float(x[i] @ w[nd] + b[nd, 0])
                p = 1.0 / (1.0 + np.exp(-z))
                c = codes[i, k]
                total += -(c * np.log(p) + (1 - c) * np.log(1 - p))
            out[i, 0] = total
        return out

    def test_default_tree_matches_manual(self):
        rng = np.random.RandomState(0)
        N, D, C = 4, 6, 7
        x = rng.randn(N, D).astype(np.float32)
        lab = rng.randint(0, C, (N,))
        w = rng.randn(C - 1, D).astype(np.float32) * 0.3
        b = rng.randn(C - 1, 1).astype(np.float32) * 0.1
        out = paddle.nn.functional.hsigmoid_loss(
            paddle.to_tensor(x), paddle.to_tensor(lab), C,
            paddle.to_tensor(w), paddle.to_tensor(b))
        # rebuild the walk in numpy (same heap coding)
        L = int(np.ceil(np.log2(C)))
        nodes = np.zeros((N, L), np.int64)
        codes = np.zeros((N, L), np.float32)
        cur = lab + C - 1
        for k in range(L):
            nodes[:, k] = (cur - 1) // 2
            codes[:, k] = (cur % 2 == 1)
            cur = (cur - 1) // 2
        np.testing.assert_allclose(out.numpy(),
                                   self._manual(x, nodes, codes, w, b),
                                   rtol=1e-5, atol=1e-5)

    def test_custom_tree(self):
        """is_custom path: caller-provided Huffman walk (VERDICT/round-1
        gap: previously NotImplementedError)."""
        rng = np.random.RandomState(1)
        N, D = 3, 5
        w = rng.randn(4, D).astype(np.float32) * 0.3
        b = rng.randn(4, 1).astype(np.float32) * 0.1
        x = rng.randn(N, D).astype(np.float32)
        # ragged walks padded with -1
        nodes = np.array([[0, 1, -1], [0, 2, 3], [0, -1, -1]], np.int64)
        codes = np.array([[1, 0, 0], [0, 1, 1], [1, 0, 0]], np.float32)
        out = paddle.nn.functional.hsigmoid_loss(
            paddle.to_tensor(x), paddle.to_tensor(np.zeros(N, np.int64)),
            5, paddle.to_tensor(w), paddle.to_tensor(b),
            path_table=paddle.to_tensor(nodes),
            path_code=paddle.to_tensor(codes))
        np.testing.assert_allclose(out.numpy(),
                                   self._manual(x, nodes, codes, w, b),
                                   rtol=1e-5, atol=1e-5)
        # layer-level custom mode
        layer = paddle.nn.HSigmoidLoss(D, 5, is_custom=True)
        res = layer(paddle.to_tensor(x), paddle.to_tensor(np.zeros(N, np.int64)),
                    path_table=paddle.to_tensor(nodes),
                    path_code=paddle.to_tensor(codes))
        assert res.shape == [N, 1]
        res.sum().backward()
        assert layer.weight.grad is not None
