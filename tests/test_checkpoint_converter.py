"""Reference .pdparams converter tests (VERDICT #10).

The reference pickles state dicts with reduce_varbase -> (name, ndarray)
tuples (framework/io.py:355) plus a StructuredToParameterName@@ table
(io.py:128). We synthesize files in that exact wire format, convert, and
pin model logits — the offline half of the reference's pretrained story.
"""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils.checkpoint_converter import (convert_state_dict,
                                                   load_pdparams,
                                                   load_pretrained,
                                                   save_pdparams)


def _reference_style_file(state, path):
    """Write exactly what a real paddle.save .pdparams unpickles to."""
    save_dict = {}
    table = {}
    for i, (k, v) in enumerate(state.items()):
        save_dict[k] = (f"param_{i}", np.asarray(v))  # (tensor_name, data)
        table[k] = f"param_{i}"
    save_dict["StructuredToParameterName@@"] = table
    with open(path, "wb") as f:
        pickle.dump(save_dict, f, protocol=2)


@pytest.mark.quick
def test_convert_reference_wire_format(tmp_path):
    sd = {"fc.weight": np.random.RandomState(0).randn(4, 3),
          "fc.bias": np.zeros(3)}
    p = str(tmp_path / "m.pdparams")
    _reference_style_file(sd, p)
    out = load_pdparams(p)
    assert set(out) == {"fc.weight", "fc.bias"}
    np.testing.assert_allclose(out["fc.weight"], sd["fc.weight"])


def test_convert_legacy_plain_ndarrays(tmp_path):
    sd = {"w": np.ones((2, 2))}
    p = str(tmp_path / "legacy.pdparams")
    with open(p, "wb") as f:
        pickle.dump(sd, f, protocol=2)
    out = load_pdparams(p)
    np.testing.assert_allclose(out["w"], 1.0)


def test_nested_opt_state_conversion():
    raw = {"LR_Scheduler": {"last_epoch": 3},
           "moment1": {"p0": ("t0", np.ones(2))},
           "StructuredToParameterName@@": {}}
    out = convert_state_dict(raw)
    assert out["LR_Scheduler"]["last_epoch"] == 3
    np.testing.assert_allclose(out["moment1"]["p0"], 1.0)


def test_resnet50_pretrained_roundtrip(tmp_path, monkeypatch):
    """resnet50(pretrained=True) loads a reference-format checkpoint and
    reproduces the source model's logits on a fixed input."""
    from paddle_tpu.vision.models import resnet50
    paddle.seed(42)
    src = resnet50(num_classes=10)
    sd = {k: v.numpy() for k, v in src.state_dict().items()}
    home = tmp_path / "ckpts"
    home.mkdir()
    _reference_style_file(sd, str(home / "resnet50.pdparams"))
    monkeypatch.setenv("PADDLE_TPU_PRETRAINED_HOME", str(home))

    paddle.seed(7)  # different init — loading must overwrite it
    model = resnet50(pretrained=True, num_classes=10)
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(1, 3, 64, 64).astype("float32"))
    src.eval(); model.eval()
    with paddle.no_grad():
        np.testing.assert_allclose(model(x).numpy(), src(x).numpy(),
                                   rtol=1e-5, atol=1e-5)


def test_pretrained_missing_file_message(monkeypatch, tmp_path):
    from paddle_tpu.vision.models import alexnet
    monkeypatch.setenv("PADDLE_TPU_PRETRAINED_HOME", str(tmp_path))
    with pytest.raises(FileNotFoundError, match="alexnet.pdparams"):
        alexnet(pretrained=True)


def test_pretrained_key_mismatch_raises(monkeypatch, tmp_path):
    from paddle_tpu.vision.models import mobilenet_v1
    _reference_style_file({"not.a.key": np.ones(2)},
                          str(tmp_path / "mobilenet_v1.pdparams"))
    monkeypatch.setenv("PADDLE_TPU_PRETRAINED_HOME", str(tmp_path))
    with pytest.raises(ValueError, match="mismatch"):
        mobilenet_v1(pretrained=True)


def test_save_pdparams_roundtrip(tmp_path):
    """Our writer emits the reference wire format our loader reads."""
    sd = {"a": np.arange(6.0).reshape(2, 3), "step": 5}
    p = str(tmp_path / "out.pdparams")
    save_pdparams(sd, p)
    out = load_pdparams(p)
    np.testing.assert_allclose(out["a"], sd["a"])
    assert out["step"] == 5
