"""incubate.nn fused layer/functional tier.

Reference test model: test/legacy_test/test_fused_attention_op.py,
test_fused_feedforward_op.py, test_fused_bias_dropout_residual_layer_norm_op.py,
test_fused_multi_transformer_op.py — each fused op is checked against a
composition of unfused ops / NumPy.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn as inn
import paddle_tpu.incubate.nn.functional as IF

RNG = np.random.RandomState(1234)
B, S, E, H = 2, 6, 16, 4
D = E // H


def _t(a):
    return paddle.to_tensor(np.asarray(a, dtype="float32"))


def _np(x):
    return np.asarray(x._data)


def _softmax(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=axis, keepdims=True)


def _layer_norm_np(x, scale, bias, eps=1e-5):
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    out = (x - mean) / np.sqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


class TestFusedFunctional:
    def test_fused_feedforward_matches_unfused(self):
        x = RNG.randn(B, S, E).astype("float32")
        w1 = RNG.randn(E, 32).astype("float32") * 0.1
        w2 = RNG.randn(32, E).astype("float32") * 0.1
        s1 = RNG.rand(E).astype("float32") + 0.5
        b1 = RNG.randn(E).astype("float32") * 0.1
        out = IF.fused_feedforward(
            _t(x), _t(w1), _t(w2), ln1_scale=_t(s1), ln1_bias=_t(b1),
            dropout1_rate=0.0, dropout2_rate=0.0, activation="relu",
            pre_layer_norm=True)
        h = _layer_norm_np(x, s1, b1)
        ref = x + np.maximum(h @ w1, 0.0) @ w2
        np.testing.assert_allclose(_np(out), ref, atol=1e-4)

    def test_fused_feedforward_grad_flows(self):
        x = _t(RNG.randn(B, S, E) * 0.1)
        x.stop_gradient = False
        w1 = _t(RNG.randn(E, 32) * 0.1)
        w1.stop_gradient = False
        w2 = _t(RNG.randn(32, E) * 0.1)
        w2.stop_gradient = False
        out = IF.fused_feedforward(x, w1, w2, dropout1_rate=0.0,
                                   dropout2_rate=0.0, activation="gelu",
                                   pre_layer_norm=True)
        out.sum().backward()
        assert x.grad is not None and np.isfinite(_np(x.grad)).all()
        assert w1.grad is not None and np.isfinite(_np(w1.grad)).all()

    def test_fused_bias_dropout_residual_layer_norm(self):
        x = RNG.randn(B, S, E).astype("float32")
        res = RNG.randn(B, S, E).astype("float32")
        bias = RNG.randn(E).astype("float32") * 0.1
        out = IF.fused_bias_dropout_residual_layer_norm(
            _t(x), _t(res), bias=_t(bias), dropout_rate=0.0)
        ref = _layer_norm_np(res + x + bias, None, None)
        np.testing.assert_allclose(_np(out), ref, atol=1e-4)

    def test_fused_multi_head_attention_matches_unfused(self):
        x = RNG.randn(B, S, E).astype("float32")
        qkv_w = (RNG.randn(3, H, D, E) * 0.2).astype("float32")
        lin_w = (RNG.randn(E, E) * 0.2).astype("float32")
        out = IF.fused_multi_head_attention(
            _t(x), _t(qkv_w), _t(lin_w), pre_layer_norm=True,
            dropout_rate=0.0, attn_dropout_rate=0.0)
        h = _layer_norm_np(x, None, None)
        qkv = np.einsum("bse,thde->bsthd", h, qkv_w)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        ctx = np.einsum("bhqk,bkhd->bqhd", _softmax(scores), v)
        ref = x + ctx.reshape(B, S, E) @ lin_w
        np.testing.assert_allclose(_np(out), ref, atol=1e-4)

    def test_fused_mha_cache_append(self):
        x = RNG.randn(B, 1, E).astype("float32")
        qkv_w = (RNG.randn(3, H, D, E) * 0.2).astype("float32")
        lin_w = np.eye(E, dtype="float32")
        cache = RNG.randn(2, B, H, 3, D).astype("float32")
        out, new_cache = IF.fused_multi_head_attention(
            _t(x), _t(qkv_w), _t(lin_w), cache_kv=_t(cache),
            dropout_rate=0.0, attn_dropout_rate=0.0, pre_layer_norm=True)
        assert list(new_cache.shape) == [2, B, H, 4, D]
        np.testing.assert_allclose(_np(new_cache)[:, :, :, :3], cache,
                                   atol=1e-6)

    def test_fused_multi_transformer_decode_cache(self):
        layers = 2
        mt = inn.FusedMultiTransformer(E, H, 32, num_layers=layers)
        mt.eval()
        x = _t(RNG.randn(B, 4, E) * 0.1)
        caches = [_t(np.zeros((2, B, H, 8, D))) for _ in range(layers)]
        out, caches = mt(x, caches=caches)
        assert list(out.shape) == [B, 4, E]
        # decode one token at time_step=4
        x1 = _t(RNG.randn(B, 1, E) * 0.1)
        out1, caches = mt(x1, caches=caches, time_step=_t(np.array(4)))
        assert list(out1.shape) == [B, 1, E]
        assert len(caches) == layers

    def test_fused_linear_and_matmul_bias(self):
        x = RNG.randn(5, E).astype("float32")
        w = RNG.randn(E, 8).astype("float32")
        b = RNG.randn(8).astype("float32")
        out = IF.fused_linear(_t(x), _t(w), _t(b))
        np.testing.assert_allclose(_np(out), x @ w + b, atol=1e-5)
        out2 = IF.fused_matmul_bias(_t(x), _t(w.T), _t(b), transpose_y=True)
        np.testing.assert_allclose(_np(out2), x @ w + b, atol=1e-5)
        out3 = IF.fused_linear_activation(_t(x), _t(w), _t(b),
                                          activation="relu")
        np.testing.assert_allclose(_np(out3), np.maximum(x @ w + b, 0),
                                   atol=1e-5)

    def test_fused_layer_norm_residual(self):
        x = RNG.randn(B, S, E).astype("float32")
        res = RNG.randn(B, S, E).astype("float32")
        w = RNG.rand(E).astype("float32") + 0.5
        out, res_out = IF.fused_layer_norm(_t(x), _t(w), None, 1e-5,
                                           begin_norm_axis=2, residual=_t(res))
        np.testing.assert_allclose(_np(res_out), x + res, atol=1e-5)
        np.testing.assert_allclose(_np(out), _layer_norm_np(x + res, w, None),
                                   atol=1e-4)

    def test_fused_rms_norm(self):
        x = RNG.randn(B, S, E).astype("float32")
        w = RNG.rand(E).astype("float32") + 0.5
        out = IF.fused_rms_norm(_t(x), _t(w), None, 1e-6, begin_norm_axis=2)
        rstd = 1.0 / np.sqrt((x * x).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(_np(out), x * rstd * w, atol=1e-4)

    def test_fused_dropout_add(self):
        x = RNG.randn(B, S, E).astype("float32")
        y = RNG.randn(B, S, E).astype("float32")
        out = IF.fused_dropout_add(_t(x), _t(y), p=0.0)
        np.testing.assert_allclose(_np(out), x + y, atol=1e-6)
        out_drop = IF.fused_dropout_add(_t(x), _t(y), p=1.0)
        np.testing.assert_allclose(_np(out_drop), y, atol=1e-6)

    def test_fused_ec_moe(self):
        n_exp, ff = 3, 8
        x = RNG.randn(B, S, E).astype("float32")
        gate = RNG.randn(B, S, n_exp).astype("float32")
        w0 = (RNG.randn(n_exp, E, ff) * 0.1).astype("float32")
        b0 = np.zeros((n_exp, 1, ff), dtype="float32")
        w1 = (RNG.randn(n_exp, ff, E) * 0.1).astype("float32")
        b1 = np.zeros((n_exp, 1, E), dtype="float32")
        out = IF.fused_ec_moe(_t(x), _t(gate), _t(w0), _t(b0), _t(w1),
                              _t(b1), "relu")
        probs = _softmax(gate)
        ref = np.zeros_like(x)
        for e in range(n_exp):
            ref += probs[..., e:e + 1] * (
                np.maximum(x @ w0[e] + b0[e], 0) @ w1[e] + b1[e])
        np.testing.assert_allclose(_np(out), ref, atol=1e-4)

    def test_fused_dot_product_attention(self):
        q = _t(RNG.randn(B, S, H, D) * 0.3)
        out = IF.fused_dot_product_attention(q, q, q, is_causal_masking=True,
                                             dropout_prob=0.0)
        assert list(out.shape) == [B, S, H, D]


class TestDecodeAttention:
    def test_masked_multihead_attention(self):
        smax = 8
        t = 2
        cache = RNG.randn(2, B, H, smax, D).astype("float32")
        x = RNG.randn(B, 3 * H * D).astype("float32")
        out, new_cache = IF.masked_multihead_attention(
            _t(x), cache_kv=_t(cache),
            sequence_lengths=_t(np.full((B, 1), t, dtype="int32")))
        qkv = x.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        ck, cv = cache[0].copy(), cache[1].copy()
        ck[:, :, t], cv[:, :, t] = k, v
        ref = np.zeros((B, H, D), dtype="float32")
        for b in range(B):
            for h in range(H):
                s = ck[b, h, :t + 1] @ q[b, h] / np.sqrt(D)
                p = _softmax(s[None])[0]
                ref[b, h] = p @ cv[b, h, :t + 1]
        np.testing.assert_allclose(_np(out).reshape(B, H, D), ref, atol=1e-5)
        np.testing.assert_allclose(_np(new_cache)[0], ck, atol=1e-6)

    def test_block_multihead_attention_prefill_and_decode(self):
        bs, max_blocks = 4, 16
        kc = np.zeros((max_blocks, H, bs, D), dtype="float32")
        vc = np.zeros((max_blocks, H, bs, D), dtype="float32")
        bt = np.array([[0, 1, 2, 3], [4, 5, 6, 7]], dtype="int32")
        slens = np.array([5, 7], dtype="int32")
        T = int(slens.sum())
        qkv = RNG.randn(T, 3 * H * D).astype("float32")
        cu = np.array([0, 5, 12], dtype="int32")
        zeros = np.zeros((2, 1), dtype="int32")
        out, _, kc2, vc2 = IF.block_multihead_attention(
            _t(qkv), _t(kc), _t(vc),
            seq_lens_encoder=_t(slens.reshape(-1, 1)),
            seq_lens_decoder=_t(zeros),
            seq_lens_this_time=_t(slens.reshape(-1, 1)),
            padding_offsets=None, cum_offsets=None,
            cu_seqlens_q=_t(cu.reshape(-1, 1)),
            cu_seqlens_k=_t(cu.reshape(-1, 1)),
            block_tables=_t(bt), block_size=bs)
        q3 = qkv.reshape(T, 3, H, D)
        # causal ref for the last token of sequence 1
        ref = np.zeros((H, D), dtype="float32")
        for h in range(H):
            s = q3[5:12, 1][:, h] @ q3[11, 0, h] / np.sqrt(D)
            ref[h] = _softmax(s[None])[0] @ q3[5:12, 2][:, h]
        np.testing.assert_allclose(_np(out)[11].reshape(H, D), ref,
                                   atol=1e-5)
        # decode one token per sequence
        qkv_d = RNG.randn(2, 3 * H * D).astype("float32")
        cu_d = np.array([0, 1, 2], dtype="int32")
        out2, _, _, _ = IF.block_multihead_attention(
            _t(qkv_d), kc2, vc2,
            seq_lens_encoder=_t(zeros),
            seq_lens_decoder=_t(slens.reshape(-1, 1)),
            seq_lens_this_time=_t(np.ones((2, 1), dtype="int32")),
            padding_offsets=None, cum_offsets=None,
            cu_seqlens_q=_t(cu_d.reshape(-1, 1)),
            cu_seqlens_k=_t(cu_d.reshape(-1, 1)),
            block_tables=_t(bt), block_size=bs)
        d3 = qkv_d.reshape(2, 3, H, D)
        k_all = np.concatenate([q3[:5, 1], d3[0:1, 1]], 0)
        v_all = np.concatenate([q3[:5, 2], d3[0:1, 2]], 0)
        ref_d = np.zeros((H, D), dtype="float32")
        for h in range(H):
            s = k_all[:, h] @ d3[0, 0, h] / np.sqrt(D)
            ref_d[h] = _softmax(s[None])[0] @ v_all[:, h]
        np.testing.assert_allclose(_np(out2)[0].reshape(H, D), ref_d,
                                   atol=1e-5)

    def test_variable_length_attention_masks_and_zero_pads(self):
        sq = 6
        q = RNG.randn(B, H, sq, D).astype("float32")
        lens = np.array([[4], [6]], dtype="int32")
        out = IF.variable_length_memory_efficient_attention(
            _t(q), _t(q), _t(q), _t(lens), _t(lens), causal=True)
        assert abs(_np(out)[0, :, 4:]).sum() == 0.0
        # row 0 of seq 0 attends only to itself under causal → equals v[0]
        np.testing.assert_allclose(_np(out)[0, :, 0], q[0, :, 0], atol=1e-5)


class TestFusedLayers:
    def test_encoder_layer_shapes_and_grad(self):
        enc = inn.FusedTransformerEncoderLayer(E, H, 32, dropout_rate=0.0)
        x = _t(RNG.randn(B, S, E) * 0.2)
        x.stop_gradient = False
        out = enc(x)
        assert list(out.shape) == [B, S, E]
        out.sum().backward()
        grads = [p.grad for p in enc.parameters()]
        assert any(g is not None for g in grads)

    def test_fused_linear_layer(self):
        lin = inn.FusedLinear(E, 8)
        out = lin(_t(RNG.randn(B, E)))
        assert list(out.shape) == [B, 8]

    def test_fused_dropout_layers(self):
        da = inn.FusedDropoutAdd(p=0.3)
        da.eval()
        x = _t(RNG.randn(B, E))
        y = _t(RNG.randn(B, E))
        np.testing.assert_allclose(_np(da(x, y)), _np(x) + _np(y), atol=1e-6)
        d = inn.FusedDropout(p=0.5)
        d.eval()
        np.testing.assert_allclose(_np(d(x)), _np(x), atol=1e-6)
        with pytest.raises(ValueError):
            inn.FusedDropout(p=1.5)

    def test_fused_ec_moe_layer(self):
        moe = inn.FusedEcMoe(E, 32, 4, act_type="gelu")
        x = _t(RNG.randn(B, S, E))
        gate = _t(RNG.randn(B, S, 4))
        assert list(moe(x, gate).shape) == [B, S, E]

    def test_memory_efficient_attention_matches_sdpa(self):
        from paddle_tpu.incubate.nn.memory_efficient_attention import (
            LowerTriangularMask)
        import paddle_tpu.nn.functional as F
        q = _t(RNG.randn(B, S, H, D) * 0.3)
        out = inn.memory_efficient_attention(q, q, q,
                                             attn_bias=LowerTriangularMask(),
                                             p=0.0)
        ref = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        np.testing.assert_allclose(_np(out), _np(ref), atol=1e-5)
