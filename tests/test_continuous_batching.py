"""Continuous batching over the compiled KV-cache decode step.

Reference serving loop analog (AnalysisPredictor + request scheduling);
the TPU design point is ONE static-shape decode executable + host-side
slot admission/eviction. Exactness bar: every request's output equals the
single-request generate() result, regardless of arrival order or slot
reuse.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatcher
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM


def _model():
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _ref(m, prompt, n):
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None, :])
    with paddle.no_grad():
        return m.generate(ids, max_new_tokens=n).numpy()[0]


def test_batched_requests_match_single_generate():
    m = _model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 9, 12, 7)]
    ns = [6, 4, 8, 5]
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=4, s_max=32, compile=False)
        rids = [b.submit(p, n) for p, n in zip(prompts, ns)]
        outs = b.run_until_done()
    for rid, p, n in zip(rids, prompts, ns):
        np.testing.assert_array_equal(outs[rid], _ref(m, p, n),
                                      err_msg=f"request {rid}")


def test_staggered_arrival_and_slot_reuse():
    """More requests than slots: later arrivals admit into freed slots
    mid-run and still match their solo decode exactly."""
    m = _model()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 128, (s,)) for s in (4, 6, 8, 5, 7, 9)]
    ns = [3, 7, 4, 6, 5, 4]
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        rids = [b.submit(p, n) for p, n in zip(prompts[:3], ns[:3])]
        early = []
        for _ in range(3):
            early += b.step()
        # new work arrives while the batch is mid-flight
        rids += [b.submit(p, n) for p, n in zip(prompts[3:], ns[3:])]
        outs = b.run_until_done()
        for rid in early:  # manual-step finishes are popped explicitly
            outs[rid] = b.pop_result(rid)
    assert b.active == 0
    for rid, p, n in zip(rids, prompts, ns):
        np.testing.assert_array_equal(outs[rid], _ref(m, p, n),
                                      err_msg=f"request {rid}")


def test_compiled_step_matches_eager_batcher():
    m = _model()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 11)]
    with paddle.no_grad():
        b1 = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        for p in prompts:
            b1.submit(p, 5)
        ref = b1.run_until_done()
        b2 = ContinuousBatcher(m, max_batch=2, s_max=32, compile=True)
        rids = [b2.submit(p, 5) for p in prompts]
        outs = b2.run_until_done()
    for rid in rids:
        np.testing.assert_array_equal(outs[rid], ref[rid])


def test_eos_early_stop():
    m = _model()
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 128, (6,))
    ref = _ref(m, prompt, 10)
    gen = ref[6:]
    # pick the 3rd generated token as "EOS": the batcher must stop there
    eos = int(gen[2])
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=2, s_max=32, eos_id=eos,
                              compile=False)
        rid = b.submit(prompt, 10)
        outs = b.run_until_done()
    got = outs[rid]
    assert len(got) <= len(ref)
    assert int(got[-1]) == eos
    np.testing.assert_array_equal(got, ref[:len(got)])


def test_capacity_validation():
    m = _model()
    b = ContinuousBatcher(m, max_batch=1, s_max=16, compile=False)
    with pytest.raises(ValueError, match="capacity"):
        b.submit(np.arange(10), 10)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        ContinuousBatcher(m, max_batch=1, s_max=128, compile=False)


def test_step_reports_admission_finishes_and_results_pop():
    """Review regressions: a request finishing AT admission must be
    reported by that step() call; run_until_done pops its run's results
    so a reused batcher neither leaks nor re-reports stale rids."""
    m = _model()
    rng = np.random.RandomState(4)
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        rid1 = b.submit(rng.randint(0, 128, (5,)), 1)  # finishes at admit
        done = b.step()
        assert rid1 in done
        # idle batcher: step() reports nothing (not historical finishes)
        assert b.step() == []
        out1 = b.pop_result(rid1)
        assert len(out1) == 6
        with pytest.raises(KeyError):
            b.result(rid1)
        # a second run returns ONLY its own rids
        rid2 = b.submit(rng.randint(0, 128, (4,)), 3)
        outs = b.run_until_done()
        assert set(outs) == {rid2}


def test_run_until_done_budget_raises():
    m = _model()
    rng = np.random.RandomState(5)
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=1, s_max=32, compile=False)
        for _ in range(3):
            b.submit(rng.randint(0, 128, (4,)), 4)
        with pytest.raises(RuntimeError, match="remain after"):
            b.run_until_done(max_steps=2)


def test_sampled_batching_is_seeded_and_diverse():
    """do_sample in the batcher: reproducible under a seed; differs from
    greedy at temperature 1."""
    m = _model()
    rng = np.random.RandomState(6)
    prompts = [rng.randint(0, 128, (6,)) for _ in range(2)]
    with paddle.no_grad():
        def run(seed):
            b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False,
                                  do_sample=True, temperature=1.0,
                                  seed=seed)
            rids = [b.submit(p, 6) for p in prompts]
            outs = b.run_until_done()
            return [outs[r].tolist() for r in rids]

        s1, s2, s3 = run(7), run(7), run(8)
        g = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        rids = [g.submit(p, 6) for p in prompts]
        gouts = g.run_until_done()
        greedy = [gouts[r].tolist() for r in rids]
    assert s1 == s2
    assert s1 != s3
    assert s1 != greedy


def test_batcher_serves_llama():
    """The batcher is model-agnostic: the GQA flagship serves through the
    same slots, token-exact vs its solo generate."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            num_attention_heads=4, num_key_value_heads=2,
                            vocab_size=128, max_position_embeddings=64)
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 9, 12)]
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        rids = [b.submit(p, 5) for p in prompts]
        outs = b.run_until_done()
        for rid, p in zip(rids, prompts):
            ids = paddle.to_tensor(np.asarray(p, np.int64)[None, :])
            ref = m.generate(ids, max_new_tokens=5).numpy()[0]
            np.testing.assert_array_equal(outs[rid], ref)
