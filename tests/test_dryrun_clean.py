"""The driver's multi-chip dryrun must be SPMD-clean.

VERDICT r2 #3: MULTICHIP_r02 passed but GSPMD logged an involuntary full
rematerialization (a tensor replicated mid-step — on a real pod, an
all-gather of exactly the kind ZeRO-3 exists to avoid). The fix is the
activation anchor installed by shard_llama(batch_axes=, sep_axis=) plus the
vocab-parallel (never hidden-sharded) embedding table; this test pins both
by grepping the compiled-step log. Reference analog: the spmd_rules
(phi/infermeta/spmd_rules/*) exist to keep placement transitions efficient;
here the assertion is on XLA's own partitioner diagnostics.

Tiering: this pin lives in the slow tier — the driver itself runs the
full dryrun every round (MULTICHIP_r0N.json), and one variant's compile
alone (~90 s) would eat a third of the smoke budget (VERDICT r3 weak #6).
`pytest tests/` (the full suite) always runs it.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_dryrun():
    env = dict(os.environ)
    env.pop("GRAFT_DRYRUN_VARIANTS", None)  # pin: ALL variants, like the
    # driver (the env var is a debug knob only)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('OK8')"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1500)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "OK8" in proc.stdout
    # dryrun_multichip pipes the sanitized subprocess's stderr through, so
    # GSPMD diagnostics from the compiled step land here.
    assert "Involuntary full rematerialization" not in proc.stderr, \
        proc.stderr[-3000:]


@pytest.mark.slow
def test_dryrun_all_variants_no_involuntary_remat():
    _run_dryrun()


@pytest.mark.slow
def test_dryrun_16dev_flagship_s3full():
    """VERDICT r4 #4: the flagship v5e-16 topology — s3_full (ZeRO-3 over
    a 16-wide data axis, full remat, scanned stack) — must EXECUTE on a
    16-virtual-device mesh, SPMD-clean. dryrun_multichip(16) runs the
    standard variants AND the dedicated flagship leg (n % 16 == 0)."""
    env = dict(os.environ)
    # the flagship leg is the new coverage; one standard variant keeps
    # the run inside the tier budget (the n=8 test covers all variants)
    env["GRAFT_DRYRUN_VARIANTS"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(16); "
         "print('OK16')"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1500)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "OK16" in proc.stdout
    assert "Involuntary full rematerialization" not in proc.stderr, \
        proc.stderr[-3000:]
