"""Distributed checkpoint tests: sharded save + reshard-on-load.

Reference coverage model: test/auto_parallel reshard/converter tests and
distributed/checkpoint unit tests (SURVEY.md §2.19, §4) on the 8-device CPU
mesh.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import (ProcessMesh, Replicate, Shard,
                                    load_state_dict, save_state_dict,
                                    shard_tensor)
from paddle_tpu.distributed.checkpoint import Metadata


def _mesh(shape, names):
    return ProcessMesh(np.arange(int(np.prod(shape))).reshape(shape), names)


def test_save_load_replicated(tmp_path):
    w = paddle.to_tensor(np.arange(24, dtype="float32").reshape(4, 6))
    sd = {"w": w}
    save_state_dict(sd, str(tmp_path))
    w2 = paddle.zeros([4, 6])
    target = {"w": w2}
    load_state_dict(target, str(tmp_path))
    np.testing.assert_allclose(target["w"].numpy(), w.numpy())


def test_save_sharded_load_differently_sharded(tmp_path):
    mesh_a = _mesh((8,), ["x"])
    mesh_b = _mesh((4, 2), ["a", "b"])
    src = shard_tensor(
        paddle.to_tensor(np.arange(64, dtype="float32").reshape(8, 8)),
        mesh_a, [Shard(0)])
    save_state_dict({"w": src}, str(tmp_path))

    dst = shard_tensor(paddle.zeros([8, 8]), mesh_b, [Shard(1), Shard(0)])
    target = {"w": dst}
    load_state_dict(target, str(tmp_path))
    np.testing.assert_allclose(target["w"].numpy(),
                               np.arange(64, dtype="float32").reshape(8, 8))
    # sharding of the target is preserved
    assert len(target["w"]._data.sharding.device_set) == 8


def test_save_sharded_load_replicated_and_back(tmp_path):
    mesh = _mesh((8,), ["x"])
    w = shard_tensor(
        paddle.to_tensor(np.arange(32, dtype="float32").reshape(8, 4)),
        mesh, [Shard(0)])
    save_state_dict({"w": w}, str(tmp_path))
    repl = {"w": paddle.zeros([8, 4])}
    load_state_dict(repl, str(tmp_path))
    np.testing.assert_allclose(repl["w"].numpy(), w.numpy())


def test_nested_state_dict_and_extra_state(tmp_path):
    model = nn.Linear(4, 4)
    opt = optimizer.AdamW(learning_rate=0.1, parameters=model.parameters())
    model(paddle.randn([2, 4])).sum().backward()
    opt.step()
    sd = {"model": model.state_dict(), "opt": opt.state_dict(),
          "epoch": 7}
    save_state_dict(sd, str(tmp_path))

    model2 = nn.Linear(4, 4)
    opt2 = optimizer.AdamW(learning_rate=0.1,
                           parameters=model2.parameters())
    model2(paddle.randn([2, 4])).sum().backward()
    opt2.step()
    target = {"model": model2.state_dict(), "opt": opt2.state_dict(),
              "epoch": 0}
    load_state_dict(target, str(tmp_path))
    assert target["epoch"] == 7
    np.testing.assert_allclose(target["model"]["weight"].numpy(),
                               model.weight.numpy())


def test_missing_key_raises(tmp_path):
    save_state_dict({"w": paddle.ones([2, 2])}, str(tmp_path))
    with pytest.raises(KeyError):
        load_state_dict({"v": paddle.zeros([2, 2])}, str(tmp_path))


def test_shape_mismatch_raises(tmp_path):
    save_state_dict({"w": paddle.ones([2, 2])}, str(tmp_path))
    with pytest.raises(ValueError):
        load_state_dict({"w": paddle.zeros([4, 2])}, str(tmp_path))


def test_async_save(tmp_path):
    from paddle_tpu.framework.io import wait_async_saves
    w = paddle.to_tensor(np.ones((4, 4), dtype="float32"))
    save_state_dict({"w": w}, str(tmp_path), async_save=True)
    wait_async_saves()
    target = {"w": paddle.zeros([4, 4])}
    load_state_dict(target, str(tmp_path))
    np.testing.assert_allclose(target["w"].numpy(), 1.0)


def test_bf16_roundtrip(tmp_path):
    w = paddle.to_tensor(np.arange(16, dtype="float32").reshape(4, 4)).astype(
        "bfloat16")
    save_state_dict({"w": w}, str(tmp_path))
    target = {"w": paddle.zeros([4, 4]).astype("bfloat16")}
    load_state_dict(target, str(tmp_path))
    np.testing.assert_allclose(
        target["w"].astype("float32").numpy(),
        np.arange(16, dtype="float32").reshape(4, 4))
