"""Pallas kernel tier tests (interpret mode on the CPU mesh).

Reference coverage model: the fused-kernel unit tests under
test/legacy_test/test_flash_attention.py etc. (SURVEY.md §4); kernels run
interpreted off-TPU so the same suite gates both backends.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.flash_attention import (flash_attention_pallas,
                                                   supported)


def _dense(q, k, v, causal):
    d = q.shape[-1]
    qt = jnp.einsum("bshd->bhsd", q)
    kt = jnp.einsum("bshd->bhsd", k)
    vt = jnp.einsum("bshd->bhsd", v)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    if causal:
        n = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool)), s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.einsum("bhsd->bshd", out)


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_dense(causal):
    b, s, h, d = 2, 256, 2, 64
    q, k, v = _rand((b, s, h, d), 0), _rand((b, s, h, d), 1), \
        _rand((b, s, h, d), 2)
    out = flash_attention_pallas(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense(q, k, v, causal)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_dense(causal):
    b, s, h, d = 1, 256, 1, 32
    q, k, v = _rand((b, s, h, d), 3), _rand((b, s, h, d), 4), \
        _rand((b, s, h, d), 5)

    def f(q, k, v):
        return flash_attention_pallas(q, k, v, causal=causal,
                                      interpret=True).sum()

    def g(q, k, v):
        return _dense(q, k, v, causal).sum()

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


def test_flash_supported_gate():
    assert supported(1024, 64)
    assert not supported(1000, 64)   # seq not divisible by blocks
    assert not supported(1024, 63)   # head dim not 8-aligned
    assert not supported(64, 64)     # seq below one q block


def test_sdpa_routes_by_flag():
    """CPU backend never routes to pallas; the flag gate is honored."""
    from paddle_tpu.nn.functional import _pallas_attention_eligible
    q = paddle.randn([1, 128, 2, 64])
    assert not _pallas_attention_eligible(q, q, None, 0.0)  # cpu backend
    paddle.set_flags({"FLAGS_use_pallas_attention": False})
    try:
        assert not _pallas_attention_eligible(q, q, None, 0.0)
    finally:
        paddle.set_flags({"FLAGS_use_pallas_attention": True})
