"""Pallas kernel tier tests (interpret mode on the CPU mesh).

Reference coverage model: the fused-kernel unit tests under
test/legacy_test/test_flash_attention.py etc. (SURVEY.md §4); kernels run
interpreted off-TPU so the same suite gates both backends. The v2 kernel's
feature matrix (GQA, additive mask, varlen, arbitrary lengths) is pinned
against a dense reference, matching FlashAttnKernel/FlashAttnUnpaddedKernel
(phi/kernels/gpu/flash_attn_kernel.cu:128).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.flash_attention import (flash_attention_pallas,
                                                   supported)


def _dense(q, k, v, causal, mask=None, seqlens=None):
    d = q.shape[-1]
    hq, hkv = q.shape[2], k.shape[2]
    if hkv != hq:  # GQA reference: expand kv heads
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = jnp.einsum("bshd->bhsd", q)
    kt = jnp.einsum("bshd->bhsd", k)
    vt = jnp.einsum("bshd->bhsd", v)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    if mask is not None:
        s = s + mask
    if causal:
        n = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool)), s, -jnp.inf)
    if seqlens is not None:
        n = q.shape[1]
        cols = jnp.arange(n)[None, None, None, :]
        rows = jnp.arange(n)[None, None, :, None]
        sl = seqlens[:, None, None, None]
        s = jnp.where((cols < sl) & (rows < sl), s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)  # fully-masked rows
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.einsum("bhsd->bshd", out)


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), jnp.float32)


@pytest.mark.quick
@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_dense(causal):
    b, s, h, d = 2, 256, 2, 64
    q, k, v = _rand((b, s, h, d), 0), _rand((b, s, h, d), 1), \
        _rand((b, s, h, d), 2)
    out = flash_attention_pallas(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense(q, k, v, causal)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_dense(causal):
    b, s, h, d = 1, 256, 1, 32
    q, k, v = _rand((b, s, h, d), 3), _rand((b, s, h, d), 4), \
        _rand((b, s, h, d), 5)

    def f(q, k, v):
        return flash_attention_pallas(q, k, v, causal=causal,
                                      interpret=True).sum()

    def g(q, k, v):
        return _dense(q, k, v, causal).sum()

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_native(causal):
    """K/V stay at kv-head count; the kernel's index map expands the group."""
    b, s, hq, hkv, d = 2, 256, 4, 2, 32
    q = _rand((b, s, hq, d), 6)
    k, v = _rand((b, s, hkv, d), 7), _rand((b, s, hkv, d), 8)
    out = flash_attention_pallas(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense(q, k, v, causal)),
                               rtol=1e-5, atol=1e-5)


def test_flash_gqa_grads():
    b, s, hq, hkv, d = 1, 256, 4, 2, 16
    q = _rand((b, s, hq, d), 9)
    k, v = _rand((b, s, hkv, d), 10), _rand((b, s, hkv, d), 11)

    def f(q, k, v):
        return flash_attention_pallas(q, k, v, causal=True,
                                      interpret=True).sum()

    def g(q, k, v):
        return _dense(q, k, v, True).sum()

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


def test_flash_additive_mask():
    b, s, h, d = 1, 256, 2, 32
    q, k, v = _rand((b, s, h, d), 12), _rand((b, s, h, d), 13), \
        _rand((b, s, h, d), 14)
    mask = jnp.asarray(
        np.random.RandomState(15).randn(b, 1, s, s) * 2, jnp.float32)
    out = flash_attention_pallas(q, k, v, causal=False, attn_mask=mask,
                                 interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v, False, mask=mask)),
        rtol=1e-5, atol=1e-5)

    def f(q):
        return flash_attention_pallas(q, k, v, causal=False, attn_mask=mask,
                                      interpret=True).sum()

    def g(q):
        return _dense(q, k, v, False, mask=mask).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)),
                               np.asarray(jax.grad(g)(q)),
                               rtol=1e-4, atol=1e-5)


def test_flash_varlen_padding_mask():
    """kv_seqlens masks the padded tail (FlashAttnUnpaddedKernel analog)."""
    b, s, h, d = 2, 256, 2, 32
    q, k, v = _rand((b, s, h, d), 16), _rand((b, s, h, d), 17), \
        _rand((b, s, h, d), 18)
    lens = jnp.asarray([200, 128], jnp.int32)
    out = flash_attention_pallas(q, k, v, causal=True, kv_seqlens=lens,
                                 interpret=True)
    ref = _dense(q, k, v, True, seqlens=lens)
    for i, L in enumerate([200, 128]):
        np.testing.assert_allclose(np.asarray(out)[i, :L],
                                   np.asarray(ref)[i, :L],
                                   rtol=1e-5, atol=1e-5)


def test_flash_arbitrary_seq_len():
    """Non-block-multiple lengths pad internally and slice back."""
    b, s, h, d = 1, 200, 2, 32
    q, k, v = _rand((b, s, h, d), 19), _rand((b, s, h, d), 20), \
        _rand((b, s, h, d), 21)
    out = flash_attention_pallas(q, k, v, causal=True, interpret=True)
    assert out.shape == (b, s, h, d)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense(q, k, v, True)),
                               rtol=1e-5, atol=1e-5)

    def f(q):
        return flash_attention_pallas(q, k, v, causal=True,
                                      interpret=True).sum()

    def g(q):
        return _dense(q, k, v, True).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)),
                               np.asarray(jax.grad(g)(q)),
                               rtol=1e-4, atol=1e-5)


def test_flash_short_seq():
    """Sequences below one default block shrink the block instead of 8x pad."""
    b, s, h, d = 2, 48, 2, 32
    q, k, v = _rand((b, s, h, d), 22), _rand((b, s, h, d), 23), \
        _rand((b, s, h, d), 24)
    out = flash_attention_pallas(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense(q, k, v, True)),
                               rtol=1e-5, atol=1e-5)


def test_flash_long_seq_blocked_kv():
    """8k tokens: v1 pinned whole-sequence K/V per program (VMEM blowup);
    v2 streams K/V tiles through the grid, so this must run."""
    b, s, h, d = 1, 8192, 1, 64
    q, k, v = _rand((b, s, h, d), 25), _rand((b, s, h, d), 26), \
        _rand((b, s, h, d), 27)
    out = flash_attention_pallas(q, k, v, causal=True, interpret=True)
    # spot-check a strip against dense (full 8k dense is slow in interpret)
    ref = _dense(q[:, :1024], k[:, :1024], v[:, :1024], True)
    np.testing.assert_allclose(np.asarray(out)[:, :1024], np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_supported_gate():
    assert supported(1024, 64)
    assert supported(1000, 64)   # v2: arbitrary lengths pad internally
    assert supported(64, 64)     # v2: short seqs shrink the block
    assert not supported(1024, 63)   # head dim not 8-aligned


def test_sdpa_routes_by_flag():
    """CPU backend never routes to pallas; the flag gate is honored."""
    from paddle_tpu.nn.functional import _pallas_attention_eligible
    q = paddle.randn([1, 128, 2, 64])
    assert not _pallas_attention_eligible(q, q, None, 0.0)  # cpu backend
    paddle.set_flags({"FLAGS_use_pallas_attention": False})
    try:
        assert not _pallas_attention_eligible(q, q, None, 0.0)
    finally:
        paddle.set_flags({"FLAGS_use_pallas_attention": True})


def test_flash_dropout():
    """In-kernel dropout: deterministic per seed, mean-preserving, bwd
    regenerates the same mask (finite, mask-consistent grads)."""
    b, s, h, d = 1, 256, 2, 32
    q, k, v = _rand((b, s, h, d), 30), _rand((b, s, h, d), 31), \
        _rand((b, s, h, d), 32)
    o1 = flash_attention_pallas(q, k, v, causal=False, dropout_p=0.3,
                                seed=7, interpret=True)
    o2 = flash_attention_pallas(q, k, v, causal=False, dropout_p=0.3,
                                seed=7, interpret=True)
    o3 = flash_attention_pallas(q, k, v, causal=False, dropout_p=0.3,
                                seed=8, interpret=True)
    o0 = flash_attention_pallas(q, k, v, causal=False, interpret=True)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))
    assert not np.allclose(np.asarray(o1), np.asarray(o0))
    # E[dropout(out)] == out: averages should stay in the same ballpark
    assert abs(float(jnp.mean(o1 - o0))) < 0.05

    g = jax.grad(lambda q: flash_attention_pallas(
        q, k, v, causal=False, dropout_p=0.3, seed=7,
        interpret=True).sum())(q)
    assert bool(jnp.isfinite(g).all())
    # same-seed grads are deterministic too
    g2 = jax.grad(lambda q: flash_attention_pallas(
        q, k, v, causal=False, dropout_p=0.3, seed=7,
        interpret=True).sum())(q)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(g2))


def test_block_sparse_attention_matches_dense_masked():
    """Active tiles only: output must equal dense attention under the
    expanded block mask (ref sparse_attention semantics at tile granularity)."""
    from paddle_tpu.ops.pallas.block_sparse_attention import \
        block_sparse_attention_pallas
    b, s, h, d = 1, 512, 2, 32
    q, k, v = _rand((b, s, h, d), 40), _rand((b, s, h, d), 41), \
        _rand((b, s, h, d), 42)
    nb = s // 128
    rng = np.random.RandomState(43)
    bm = (rng.rand(nb, nb) < 0.5)
    bm[:, 0] = True  # every row keeps at least one active tile
    out = block_sparse_attention_pallas(q, k, v, bm, interpret=True)

    mask = np.repeat(np.repeat(bm, 128, 0), 128, 1)
    big = jnp.asarray(np.where(mask, 0.0, -1e30), jnp.float32)
    ref = _dense(q, k, v, False, mask=big[None, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    # gradients flow (dense recompute backward)
    g = jax.grad(lambda q: block_sparse_attention_pallas(
        q, k, v, bm, interpret=True).sum())(q)
    gref = jax.grad(lambda q: _dense(q, k, v, False,
                                     mask=big[None, None]).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-4, atol=1e-5)


def test_sparse_attention_csr_block_alignment_probe():
    from paddle_tpu.nn.functional_extras import _csr_masks
    seq, blk = 256, 128
    nb = seq // blk
    # block-aligned: every row attends exactly to block-col 0
    offs = np.zeros((1, 1, seq + 1), np.int64)
    cols_list = []
    for r in range(seq):
        cols_list.append(np.arange(blk))
        offs[0, 0, r + 1] = offs[0, 0, r] + blk
    cols = np.concatenate(cols_list)[None, None]
    mask, bm = _csr_masks(offs, cols, seq, blk)
    assert bm is not None and bm.shape == (nb, nb)
    assert bm[:, 0].all() and not bm[:, 1:].any()
    assert mask.shape == (1, 1, seq, seq)
    # cached: same pattern returns the identical objects
    mask2, bm2 = _csr_masks(offs, cols, seq, blk)
    assert mask2 is mask and bm2 is bm
    # non-aligned pattern (single element) probes to None
    offs2 = np.zeros((1, 1, seq + 1), np.int64)
    offs2[0, 0, 1:] = 1
    cols2 = np.zeros((1, 1, seq), np.int64)
    _, bm3 = _csr_masks(offs2, cols2, seq, blk)
    assert bm3 is None


def test_block_sparse_empty_row_zero_output():
    """A fully-masked block-row outputs ZERO in fwd AND its bwd recompute
    (review repro: softmax-of-all-masked must not become uniform)."""
    from paddle_tpu.ops.pallas.block_sparse_attention import \
        block_sparse_attention_pallas
    b, s, h, d = 1, 256, 1, 16
    q, k, v = _rand((b, s, h, d), 50), _rand((b, s, h, d), 51), \
        _rand((b, s, h, d), 52)
    bm = np.array([[True, False], [False, False]])  # row 1 fully masked
    out = block_sparse_attention_pallas(q, k, v, bm, interpret=True)
    np.testing.assert_allclose(np.asarray(out)[:, 128:], 0.0)
    g = jax.grad(lambda v_: block_sparse_attention_pallas(
        q, k, v_, bm, interpret=True).sum())(v)
    # masked rows contribute nothing to dv's second half either
    np.testing.assert_allclose(np.asarray(g)[:, 128:], 0.0, atol=1e-6)
