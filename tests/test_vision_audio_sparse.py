"""Tests for paddle.vision / paddle.audio / paddle.sparse / paddle.device
(reference: python/paddle/{vision,audio,sparse,device})."""
import math
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import vision
from paddle_tpu.vision import transforms as T


def _np(t):
    return np.asarray(t._data)


# -- vision.transforms ---------------------------------------------------------

def test_to_tensor_scales_and_chw():
    img = (np.ones((4, 6, 3)) * 255).astype(np.uint8)
    t = T.to_tensor(img)
    assert tuple(t.shape) == (3, 4, 6)
    np.testing.assert_allclose(_np(t), 1.0)


def test_normalize():
    img = np.ones((3, 2, 2), dtype=np.float32)
    out = T.normalize(img, mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5])
    np.testing.assert_allclose(out, 1.0)


def test_resize_shapes():
    img = np.arange(64, dtype=np.uint8).reshape(8, 8, 1)
    assert T.resize(img, (4, 4)).shape == (4, 4, 1)
    assert T.resize(img, 4).shape == (4, 4, 1)
    tall = np.zeros((16, 8, 1), dtype=np.uint8)
    assert T.resize(tall, 4).shape == (8, 4, 1)  # shorter side -> 4


def test_flip_crop_pad():
    img = np.arange(16, dtype=np.float32).reshape(4, 4, 1)
    np.testing.assert_array_equal(T.hflip(img)[:, :, 0], img[:, ::-1, 0])
    np.testing.assert_array_equal(T.vflip(img)[:, :, 0], img[::-1, :, 0])
    c = T.center_crop(img, 2)
    np.testing.assert_array_equal(c[:, :, 0], img[1:3, 1:3, 0])
    p = T.pad(img, 1)
    assert p.shape == (6, 6, 1)


def test_compose_pipeline():
    pipe = T.Compose([T.Resize((8, 8)), T.CenterCrop(4), T.ToTensor(),
                      T.Normalize(mean=0.5, std=0.5)])
    img = (np.random.RandomState(0).rand(16, 16, 3) * 255).astype(np.uint8)
    out = pipe(img)
    assert tuple(out.shape) == (3, 4, 4)


def test_random_crop_pad_if_needed_widens():
    img = np.zeros((32, 20, 3), dtype=np.uint8)
    out = T.RandomCrop(32, pad_if_needed=True)(img)
    assert out.shape == (32, 32, 3)


def test_resize_preserves_float64_values():
    img = np.random.RandomState(0).rand(8, 8, 1)  # float64 in [0, 1]
    out = T.resize(img, (4, 4))
    assert out.dtype == np.float64
    assert 0.2 < out.mean() < 0.8  # not quantized to {0, 1}


# -- vision.models --------------------------------------------------------------

def test_lenet_forward():
    net = vision.LeNet(num_classes=10)
    x = paddle.to_tensor(np.random.randn(2, 1, 28, 28).astype(np.float32))
    assert tuple(net(x).shape) == (2, 10)


def test_mobilenet_v2_forward():
    net = vision.models.mobilenet_v2(scale=0.25, num_classes=7)
    x = paddle.to_tensor(np.random.randn(1, 3, 32, 32).astype(np.float32))
    assert tuple(net(x).shape) == (1, 7)


def test_vgg11_tiny_forward():
    net = vision.models.vgg11(num_classes=5)
    x = paddle.to_tensor(np.random.randn(1, 3, 32, 32).astype(np.float32))
    assert tuple(net(x).shape) == (1, 5)


def test_pretrained_without_local_weights_raises(tmp_path, monkeypatch):
    """pretrained=True now loads LOCAL reference .pdparams weights
    (utils.checkpoint_converter); with no file present it fails loudly
    with placement instructions."""
    monkeypatch.setenv("PADDLE_TPU_PRETRAINED_HOME", str(tmp_path))
    with pytest.raises(FileNotFoundError, match="pretrained=True"):
        vision.models.vgg11(pretrained=True)


# -- vision.datasets -------------------------------------------------------------

def test_mnist_idx_parsing(tmp_path):
    import struct
    imgs = (np.arange(2 * 28 * 28) % 256).astype(np.uint8)
    ip = tmp_path / "images.idx"
    with open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 2, 28, 28))
        f.write(imgs.tobytes())
    lp = tmp_path / "labels.idx"
    with open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 2))
        f.write(np.array([3, 7], dtype=np.uint8).tobytes())
    ds = vision.datasets.MNIST(image_path=str(ip), label_path=str(lp))
    assert len(ds) == 2
    img, label = ds[1]
    assert img.shape == (28, 28) and label == 7


def test_dataset_folder(tmp_path):
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        np.save(d / "a.npy", np.zeros((4, 4)))
    ds = vision.datasets.DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 2
    _, y = ds[1]
    assert y == 1


def test_dataset_download_unavailable():
    with pytest.raises(RuntimeError, match="egress"):
        vision.datasets.MNIST()


# -- vision.ops ------------------------------------------------------------------

def test_box_iou_and_nms():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                     dtype=np.float32)
    iou = _np(vision.ops.box_iou(boxes, boxes))
    assert iou[0, 0] == pytest.approx(1.0)
    assert iou[0, 2] == 0.0
    assert 0.5 < iou[0, 1] < 0.8
    scores = np.array([0.9, 0.8, 0.7], dtype=np.float32)
    keep = _np(vision.ops.nms(boxes, iou_threshold=0.5, scores=scores))
    np.testing.assert_array_equal(keep, [0, 2])  # box 1 suppressed by 0


def test_nms_respects_categories():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], dtype=np.float32)
    scores = np.array([0.9, 0.8], dtype=np.float32)
    keep = _np(vision.ops.nms(boxes, 0.5, scores,
                              category_idxs=np.array([0, 1]),
                              categories=[0, 1]))
    assert len(keep) == 2  # different classes: no suppression


# -- audio -----------------------------------------------------------------------

def test_mel_scale_roundtrip():
    from paddle_tpu.audio import functional as AF
    for htk in (False, True):
        hz = AF.mel_to_hz(AF.hz_to_mel(440.0, htk), htk)
        assert hz == pytest.approx(440.0, rel=1e-6)


def test_fbank_matrix_shape_and_coverage():
    from paddle_tpu.audio import functional as AF
    fb = _np(AF.compute_fbank_matrix(sr=16000, n_fft=512, n_mels=40))
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    assert (fb.sum(axis=1) > 0).all()  # every filter has support


def test_spectrogram_and_mfcc():
    from paddle_tpu.audio.features import MFCC, LogMelSpectrogram, Spectrogram
    sig = paddle.to_tensor(
        np.sin(2 * math.pi * 440 * np.arange(4000) / 16000)
        .astype(np.float32)[None, :])
    spec = Spectrogram(n_fft=256, hop_length=128)(sig)
    assert spec.shape[1] == 129
    logmel = LogMelSpectrogram(sr=16000, n_fft=256, hop_length=128,
                               n_mels=32)(sig)
    assert logmel.shape[1] == 32
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=256, hop_length=128,
                n_mels=32)(sig)
    assert mfcc.shape[1] == 13
    # 440 Hz peak lands in the right fft bin
    power = _np(spec)[0].mean(axis=-1)
    peak_hz = power.argmax() * 16000 / 256
    assert abs(peak_hz - 440) < 65


def test_power_to_db():
    from paddle_tpu.audio import functional as AF
    x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], dtype=np.float32))
    db = _np(AF.power_to_db(x, top_db=None))
    np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)


# -- sparse ----------------------------------------------------------------------

def test_sparse_coo_roundtrip():
    from paddle_tpu import sparse
    idx = np.array([[0, 1, 2], [1, 2, 0]])
    vals = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    st = sparse.sparse_coo_tensor(idx, vals, shape=(3, 3))
    assert st.nnz == 3
    dense = _np(st.to_dense())
    assert dense[0, 1] == 1.0 and dense[1, 2] == 2.0 and dense[2, 0] == 3.0
    back = sparse.to_sparse_coo(paddle.to_tensor(dense))
    np.testing.assert_allclose(_np(back.to_dense()), dense)


def test_sparse_csr_and_crows():
    from paddle_tpu import sparse
    crows = np.array([0, 1, 3])
    cols = np.array([1, 0, 2])
    vals = np.array([5.0, 6.0, 7.0], dtype=np.float32)
    st = sparse.sparse_csr_tensor(crows, cols, vals, shape=(2, 3))
    dense = _np(st.to_dense())
    assert dense[0, 1] == 5.0 and dense[1, 0] == 6.0 and dense[1, 2] == 7.0
    np.testing.assert_array_equal(_np(st.crows()), crows)


def test_sparse_matmul_matches_dense():
    from paddle_tpu import sparse
    rng = np.random.RandomState(0)
    dense = rng.randn(4, 5).astype(np.float32) * (rng.rand(4, 5) > 0.5)
    other = rng.randn(5, 3).astype(np.float32)
    st = sparse.to_sparse_coo(paddle.to_tensor(dense))
    out = sparse.matmul(st, paddle.to_tensor(other))
    np.testing.assert_allclose(_np(out), dense @ other, rtol=1e-4,
                               atol=1e-5)


def test_sparse_unary_and_nn():
    from paddle_tpu import sparse
    dense = np.array([[0.0, -2.0], [3.0, 0.0]], dtype=np.float32)
    st = sparse.to_sparse_coo(paddle.to_tensor(dense))
    np.testing.assert_allclose(_np(sparse.abs(st).to_dense()),
                               np.abs(dense))
    relu_out = sparse.nn.ReLU()(st)
    np.testing.assert_allclose(_np(relu_out.to_dense()),
                               np.maximum(dense, 0))


def test_masked_matmul_sddmm():
    from paddle_tpu import sparse
    rng = np.random.RandomState(1)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4, 3).astype(np.float32)
    mask_dense = np.array([[1, 0, 0], [0, 1, 0], [0, 0, 1]],
                          dtype=np.float32)
    mask = sparse.to_sparse_coo(paddle.to_tensor(mask_dense))
    out = sparse.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                               mask)
    full = a @ b
    np.testing.assert_allclose(_np(out.to_dense()), full * mask_dense,
                               rtol=1e-4, atol=1e-5)


# -- device ----------------------------------------------------------------------

def test_device_api():
    from paddle_tpu import device
    assert device.device_count() >= 1
    assert ":" in device.get_device()
    assert device.get_all_device_type()
    device.synchronize()


def test_stream_event_ordering():
    from paddle_tpu import device
    s = device.current_stream()
    ev = s.record_event()
    x = paddle.to_tensor(np.ones(128, np.float32)) * 2
    ev2 = device.Event()
    ev2.record()
    ev2.synchronize()
    assert ev2.query()
    with device.stream_guard(device.Stream()):
        assert device.current_stream() is not s
    assert device.current_stream() is s


def test_device_memory_stats_nonnegative():
    from paddle_tpu import device
    assert device.memory_allocated() >= 0
    assert device.max_memory_allocated() >= device.memory_allocated() - 1
    assert device.cuda.device_count() >= 1
