"""Runtime SPMD mesh layer (paddle_tpu.distributed.mesh).

The acceptance bars:
  * a 2x2 ``(fsdp, tensor)`` mesh train step is LOSS-EXACT (bitwise)
    vs the same model fused-stepped on one device — ZeRO-3 storage
    sharding with gather-at-use changes placement, not math;
  * the runtime SH/MEM gate refuses bad programs BEFORE compile with
    the same finding codes the static plane prints (SH201 divisibility,
    MEM301 HBM budget);
  * the per-chip live bytes XLA's buffer assignment reports for the
    compiled step agree with ``analysis/memory.py``'s prediction
    (state within 10%; the liveness-walk peak stays a sound upper
    bound);
  * ``MeshRuntime.describe()`` round-trips through
    ``tools/shard_check.py --from-runtime`` — CI lints the specs that
    RUN, not a mirror.

The multi-process 2x2 gloo drill lives in
``test_multiprocess_mesh_train_loss_exact`` (2 real processes x 2 CPU
devices via the launch CLI — fsdp crosses the process boundary — each
rank checking the sharded losses against its own local single-device
reference).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as optim
from paddle_tpu import jit as jit_mod
from paddle_tpu.distributed.mesh import (MeshProgramRejected, MeshRuntime,
                                         TPMemberDied)

pytestmark = pytest.mark.mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHARD_CHECK = os.path.join(REPO, "tools", "shard_check.py")
MESH_AXES = {"data": 1, "fsdp": 2, "tensor": 2}
STEPS = 5


def _make_llama(seed=7):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(seed)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


def _build_step(model, plan):
    opt = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def fn(ids, labels):
        out = model(ids)
        logits = out[0] if isinstance(out, (tuple, list)) else out
        return paddle.nn.functional.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))

    return jit_mod.TrainStep(fn, opt, mesh_plan=plan)


def _batch():
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randint(0, 128, size=(2, 16))),
            paddle.to_tensor(rng.randint(0, 128, size=(2, 16))))


def _losses(step, n=STEPS):
    ids, labels = _batch()
    out = []
    for _ in range(n):
        loss = step(ids, labels)
        out.append(float(np.asarray(loss._data)))
    return out


@pytest.fixture(scope="module")
def sharded_run():
    """One compiled 2x2 mesh train run + its single-device reference
    (module-scoped: the exactness, memory and describe tests share the
    two compiles instead of paying them three times)."""
    base = _losses(_build_step(_make_llama(), None))
    rt = MeshRuntime(MESH_AXES)
    plan = rt.train_plan(budget_gib=16.0)
    step = _build_step(_make_llama(), plan)
    sharded = _losses(step)
    return {"rt": rt, "plan": plan, "step": step,
            "base": base, "sharded": sharded}


# -- mesh construction + spec policies ---------------------------------------

@pytest.mark.quick
def test_runtime_axes_and_spec_policies():
    rt = MeshRuntime(MESH_AXES)
    assert rt.size == 4 and rt.axes == MESH_AXES
    assert tuple(rt.mesh.axis_names) == ("data", "fsdp", "tensor")
    # plan policy: 2D dim0 -> fsdp, trailing divisible dim -> tensor
    assert rt.train_param_spec((8, 4), "w") == ("fsdp", "tensor")
    # norms/1D replicate
    assert rt.train_param_spec((8,), "ln1") == (None,)
    # serving: column-parallel only (trailing dim), vectors replicate
    assert rt.serving_weight_spec((8, 4)) == (None, "tensor")
    assert rt.serving_weight_spec((8,)) == (None,)
    # batch dim0 over data axes when divisible, else replicated
    rt2 = MeshRuntime({"data": 2, "fsdp": 2})
    assert rt2.batch_spec((4, 16)) == ("data", None)
    assert rt2.batch_spec((3, 16)) == (None, None)
    with pytest.raises(ValueError, match="unknown mesh axes"):
        MeshRuntime({"pipeline": 2})
    with pytest.raises(ValueError, match="device"):
        MeshRuntime({"data": 1024})


@pytest.mark.quick
def test_runtime_gate_refuses_with_static_finding_codes():
    rt = MeshRuntime(MESH_AXES)
    # SH201: declared shard dim does not divide
    with pytest.raises(MeshProgramRejected, match="SH201") as ei:
        rt.gate_specs([("w", (7, 5), ("fsdp", None))])
    assert {f.rule for f in ei.value.findings} == {"SH201"}
    # MEM301: predicted bytes over the HBM budget
    with pytest.raises(MeshProgramRejected, match="MEM301") as ei:
        rt.gate_memory(predicted_bytes=2.0 * 1024 ** 3, budget_gib=1.0)
    assert {f.rule for f in ei.value.findings} == {"MEM301"}


def test_mem301_refuses_train_step_before_compile():
    plan = MeshRuntime(MESH_AXES).train_plan(budget_gib=1e-6)
    step = _build_step(_make_llama(), plan)
    ids, labels = _batch()
    with pytest.raises(MeshProgramRejected, match="MEM301"):
        step(ids, labels)


# -- the exactness bar -------------------------------------------------------

def test_sharded_train_step_loss_exact_vs_single_device(sharded_run):
    base, sharded = sharded_run["base"], sharded_run["sharded"]
    assert len(sharded) == STEPS
    assert sharded == base, (
        f"2x2 mesh drifted from single device:\n{base}\nvs\n{sharded}")
    comm = sharded_run["plan"].collective_bytes_by_axis()
    assert comm.get("fsdp", 0) > 0 and comm.get("tensor", 0) > 0, comm


# -- runtime <-> static memory cross-check -----------------------------------

def test_mesh_memory_report_two_sided(sharded_run):
    ids, labels = _batch()
    rep = sharded_run["step"].mesh_memory_report(ids, labels)
    assert rep["within_tolerance"], rep       # state agrees within 10%
    assert rep["peak_bound_sound"], rep       # walk never under-predicts
    assert 0 < rep["measured_state_bytes"] <= rep["measured_peak_bytes"]


# -- describe() -> shard_check --from-runtime --------------------------------

def test_describe_round_trips_through_shard_check(sharded_run, tmp_path):
    rt, plan = sharded_run["rt"], sharded_run["plan"]
    dump = rt.describe(train_plan=plan)
    assert dump["kind"] == "mesh_runtime" and dump["mesh"] == MESH_AXES
    assert dump["params"] and "memory" in dump
    path = tmp_path / "runtime_dump.json"
    path.write_text(json.dumps(dump))

    ok = subprocess.run(
        [sys.executable, SHARD_CHECK, "--from-runtime", str(path), "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    out = json.loads(ok.stdout)
    assert out["mode"] == "from-runtime" and not out["findings"]

    over = subprocess.run(
        [sys.executable, SHARD_CHECK, "--from-runtime", str(path),
         "--hbm-gib", "1e-6"],
        capture_output=True, text=True, cwd=REPO)
    assert over.returncode == 1 and "MEM301" in over.stdout, over.stdout


# -- hapi wiring -------------------------------------------------------------

def test_hapi_prepare_with_mesh_plan_loss_exact():
    from paddle_tpu import nn
    from paddle_tpu.hapi import Model

    def build():
        paddle.seed(11)
        return Model(nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                                   nn.Linear(32, 2)))

    rng = np.random.RandomState(2)
    x = rng.randn(4, 8).astype(np.float32)
    y = rng.randint(0, 2, size=(4,)).astype(np.int64)

    def run(plan):
        m = build()
        m.prepare(optimizer=optim.AdamW(learning_rate=1e-2,
                                        parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss(), jit=True, plan=plan)
        return [float(np.asarray(m.train_batch([x], [y])[0]))
                for _ in range(3)]

    base = run(None)
    plan = MeshRuntime(MESH_AXES).train_plan(budget_gib=16.0)
    assert run(plan) == base

    m = build()
    with pytest.raises(ValueError, match="requires jit=True"):
        m.prepare(optimizer=optim.AdamW(learning_rate=1e-2,
                                        parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss(), plan=plan)


# -- serving shard group -----------------------------------------------------

@pytest.fixture()
def gpt_batcher():
    from paddle_tpu.inference.serving import ContinuousBatcher
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    lm = GPT2ForCausalLM(cfg)
    lm.eval()
    return ContinuousBatcher(lm, compile=False, max_batch=2, s_max=64)


def test_shard_serving_token_exact_and_member_death(gpt_batcher):
    lm = gpt_batcher.model
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 128, size=n).astype(np.int64) for n in (5, 9)]
    refs = [np.asarray(lm.generate(p.reshape(1, -1),
                                   max_new_tokens=8)).reshape(-1)
            for p in prompts]

    group = MeshRuntime({"tensor": 2}).shard_serving(gpt_batcher,
                                                     group_name="g0")
    assert gpt_batcher.shard_group is group and group.degree == 2
    assert group.placed_params["transformer.wte.weight"]["spec"] == \
        [None, "tensor"]
    rids = [gpt_batcher.submit(p, max_new_tokens=8) for p in prompts]
    while gpt_batcher.active or gpt_batcher.pending:
        gpt_batcher.step()
    for rid, ref in zip(rids, refs):
        assert np.array_equal(np.asarray(gpt_batcher.result(rid)), ref)

    # a dead member makes the group unsteppable — non-retryable by design
    group.fail_member(group.members[0], reason="drill")
    with pytest.raises(TPMemberDied, match="g0"):
        gpt_batcher.step()
    from paddle_tpu.resilience.retry import DEFAULT_RETRYABLE
    assert not issubclass(TPMemberDied, DEFAULT_RETRYABLE)


def test_shard_serving_refuses_indivisible_heads(gpt_batcher):
    with pytest.raises(MeshProgramRejected, match="SH201"):
        MeshRuntime({"tensor": 8}).shard_serving(gpt_batcher)


# -- the multi-process drill -------------------------------------------------

def test_multiprocess_mesh_train_loss_exact(tmp_path):
    """2 REAL processes x 2 CPU devices each form a 2x2 (fsdp, tensor)
    gloo mesh — the fsdp (ZeRO-3 gather) axis crosses the process
    boundary, tensor stays intra-process — and train the small llama 5
    fused steps; every rank asserts the sharded losses are
    bitwise-identical to its own local single-device reference run."""
    worker = os.path.join(REPO, "tests", "helpers", "mp_mesh_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PADDLE_MESH_SHAPE"] = "data:1,fsdp:2,tensor:2"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         worker],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env)
    logs = ""
    log_root = tmp_path / "logs"
    if log_root.exists():
        for f in sorted(log_root.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()
    assert proc.returncode == 0, (
        f"launch failed rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}\nlogs:{logs[-4000:]}")
    marks = [ln for ln in logs.splitlines() if "MPMESH_OK" in ln]
    for r in range(2):
        assert any(f"MPMESH_OK rank={r}/2" in ln for ln in marks), (
            f"rank {r} did not finish\n{logs[-4000:]}")
    # every rank converged on the SAME loss trajectory
    assert len({ln.split("losses=")[1] for ln in marks}) == 1, marks


@pytest.mark.slow
@pytest.mark.ckpt
def test_multiprocess_elastic_checkpoint_survives_rank_kill(tmp_path):
    """Save under a process-spanning 2x2 mesh, chaos-kill rank 1 mid
    shard write on the NEXT save (rank 0 must time out on the missing
    ack and leave the step torn), then restart as ONE process on ONE
    device: the restore must fall back to the committed step with a
    typed torn_step finding and continue on the 2x2 world's exact loss
    trajectory."""
    worker = os.path.join(REPO, "tests", "helpers", "mp_ckpt_worker.py")
    root = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PADDLE_MESH_SHAPE"] = "data:1,fsdp:2,tensor:2"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["MP_CKPT_ROOT"] = root
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)

    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         worker],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env)
    logs = ""
    log_root = tmp_path / "logs"
    if log_root.exists():
        for f in sorted(log_root.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()
    assert proc.returncode == 0, (
        f"launch failed rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}\nlogs:{logs[-4000:]}")
    saves = [ln for ln in logs.splitlines() if "MPCKPT_SAVE_OK" in ln]
    assert any("rank=0/2" in ln for ln in saves), logs[-4000:]
    assert any("MPCKPT_TORN rank=0 step=4" in ln
               for ln in logs.splitlines()), logs[-4000:]
    # the loss the 2x2 world computed right after the committed save
    ref_losses = json.loads(saves[0].split("losses=")[1])
    ref_step4 = ref_losses[3]

    # the torn step is on disk exactly as the crash left it; the
    # offline inspector must flag it and still name step 3 sound
    ins = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
         root, "--json"], capture_output=True, text=True, timeout=60,
        cwd=REPO)
    assert ins.returncode == 2, ins.stdout + ins.stderr
    report = json.loads(ins.stdout)
    assert report["latest_sound"] == 3, report

    env_r = dict(env)
    env_r.pop("PADDLE_MESH_SHAPE")
    env_r["MP_CKPT_PHASE"] = "restore"
    proc_r = subprocess.run([sys.executable, worker], capture_output=True,
                            text=True, timeout=420, cwd=REPO, env=env_r)
    assert proc_r.returncode == 0, (
        f"restore phase rc={proc_r.returncode}\n"
        f"stdout:{proc_r.stdout[-2000:]}\nstderr:{proc_r.stderr[-2000:]}")
    restored = [ln for ln in proc_r.stdout.splitlines()
                if "MPCKPT_RESTORE_OK" in ln]
    assert restored and "torn_step" in restored[0], proc_r.stdout[-2000:]
    got_step4 = json.loads(restored[0].split("losses=")[1])[0]
    assert got_step4 == ref_step4, (
        f"elastic restart diverged: {got_step4!r} vs the 2x2 world's "
        f"{ref_step4!r}")
