"""Radix prefix KV cache + in-batcher speculative decoding (round 13).

Three layers, same exactness bar as tests/test_paged_batching.py:

  * pure-host radix-tree units — insert/match/evict/refcount under
    pressure, chain-hash summaries (no model, sub-second);
  * paged-batcher integration — shared-prefix admissions must be
    token-exact vs solo ``generate_paged`` with the cache hitting,
    pages audited (``serving.pages_leaked`` stays 0) through eviction
    pressure and preemption;
  * speculative decoding — ``draft_model=`` output must equal
    non-speculative output token for token across seeds, alone and
    composed with the prefix cache.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.prefix_cache import RadixPrefixCache, chain_hashes
from paddle_tpu.inference.serving import PagedContinuousBatcher
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

pytestmark = pytest.mark.perf


# -- radix-tree units (no model) ----------------------------------------------

def test_radix_match_insert_refcount():
    c = RadixPrefixCache(block_size=4)
    toks = np.arange(11)                       # 2 full blocks + partial
    assert c.match(toks) == []
    created = c.insert(toks, pages=[7, 8], start_block=0, n_blocks=2)
    assert [n.page for n in created] == [7, 8]
    assert all(n.ref == 1 for n in created)    # born pinned by inserter
    path = c.match(toks)
    assert [n.page for n in path] == [7, 8]
    assert c.match(toks, max_blocks=1) == path[:1]
    # a diverging second block shares only the first node
    other = np.concatenate([np.arange(4), np.arange(50, 54)])
    assert [n.page for n in c.match(other)] == [7]
    c.unpin(created)
    with pytest.raises(RuntimeError):          # double release is a bug
        c.unpin(created[:1])


def test_radix_insert_skips_existing_blocks():
    c = RadixPrefixCache(block_size=4)
    toks = np.arange(8)
    c.insert(toks, pages=[0, 1], start_block=0, n_blocks=2)
    # same prefix again: the tree keeps ITS pages, nothing new adopted
    created = c.insert(toks, pages=[5, 6], start_block=2, n_blocks=2)
    assert created == []
    assert sorted(c.pages()) == [0, 1]


def test_radix_evict_lru_unpinned_leaves_only():
    c = RadixPrefixCache(block_size=2)
    hot = c.insert(np.arange(6), [0, 1, 2], 0, 3)       # chain A, pinned
    cold = c.insert(np.array([9, 9, 1, 1]), [3, 4], 0, 2)  # chain B
    c.unpin(cold)                                       # B is idle
    assert c.evictable_pages() == 2
    # pinned chain A is untouchable even under a too-large ask; B frees
    # bottom-up (leaf first)
    assert c.evict(10) == [4, 3]
    assert c.evictions == 2 and len(c) == 3
    assert c.evict(1) == []                             # nothing unpinned
    c.unpin(hot)
    assert c.evictable_pages() == 3


def test_radix_evict_lru_order():
    c = RadixPrefixCache(block_size=2)
    a = c.insert(np.array([1, 1]), [0], 0, 1)
    b = c.insert(np.array([2, 2]), [1], 0, 1)
    c.unpin(a)
    c.unpin(b)                   # released after a -> a is the LRU leaf
    assert c.evict(1) == [0]
    c.pin(b)                     # a re-match touches b…
    c.unpin(b)
    d = c.insert(np.array([3, 3]), [2], 0, 1)
    c.unpin(d)                   # …so b is now OLDER than d
    assert c.evict(2) == [1, 2]


def test_radix_interior_protected_by_pinned_descendant():
    c = RadixPrefixCache(block_size=2)
    nodes = c.insert(np.arange(4), [0, 1], 0, 2)
    c.unpin(nodes[:1])            # parent unpinned, leaf still pinned
    assert c.evictable_pages() == 0
    assert c.evict(2) == []
    c.unpin(nodes[1:])
    assert c.evict(2) == [1, 0]   # bottom-up once fully released


def test_chain_hashes_agree_with_summary():
    c = RadixPrefixCache(block_size=4)
    toks = np.arange(12)
    c.insert(toks, [0, 1, 2], 0, 3)
    s = c.summary()
    assert s["block_size"] == 4
    chain = chain_hashes(toks, 4)
    assert len(chain) == 3
    # every chain hash is advertised at its depth; a foreign prompt's
    # chain diverges from the first block
    assert [s["hashes"][h] for h in chain] == [1, 2, 3]
    assert chain_hashes(np.arange(50, 62), 4)[0] not in s["hashes"]


# -- paged-batcher integration ------------------------------------------------

def _model(seed=0):
    paddle.seed(seed)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _refs(m, prompts, n):
    out = []
    with paddle.no_grad():
        for p in prompts:
            r = m.generate_paged(paddle.to_tensor(
                np.asarray(p, np.int64)[None, :]), n, block_size=16)
            out.append(np.asarray(r._data)[0])
    return out


def _shared_prompts(seed, n, shared_len=40):
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, 128, (shared_len,))
    return [np.concatenate([shared, rng.randint(0, 128, (5 + i,))])
            for i in range(n)]


def _pages_leaked():
    from paddle_tpu.observability.metrics import get_registry
    return get_registry().gauge("serving.pages_leaked", "t").value


def test_prefix_cache_batcher_token_exact_and_hits():
    m = _model()
    prompts = _shared_prompts(3, 4)
    refs = _refs(m, prompts, 8)
    with paddle.no_grad():
        b = PagedContinuousBatcher(m, max_batch=2, s_max=96, block_size=16,
                                   n_pages=24, compile=False,
                                   policy="ondemand", prefix_cache=True)
        rids = [b.submit(p, 8) for p in prompts]
        res = b.run_until_done()
    for rid, ref in zip(rids, refs):
        assert np.array_equal(res[rid], ref)
    st = b.prefix_cache.stats()
    assert st["hit_tokens"] > 0            # later requests reused the prefix
    b.audit_pages()
    assert _pages_leaked() == 0
    # every page is either free or owned by the cache once slots drain
    assert b.free_page_count + b.prefix_cache.cached_pages == b.n_pages


def test_prefix_cache_eviction_pressure_stays_exact():
    """A pool too small to keep every prefix resident: eviction must
    fire, pages must balance, output must stay exact."""
    m = _model()
    rng = np.random.RandomState(5)
    prompts = []
    for k in range(3):                      # 3 distinct 32-token prefixes
        shared = rng.randint(0, 128, (32,))
        prompts += [np.concatenate([shared, rng.randint(0, 128, (6 + i,))])
                    for i in range(2)]
    refs = _refs(m, prompts, 6)
    with paddle.no_grad():
        b = PagedContinuousBatcher(m, max_batch=2, s_max=64, block_size=16,
                                   n_pages=6, compile=False,
                                   policy="ondemand", prefix_cache=True)
        rids = [b.submit(p, 6) for p in prompts]
        res = b.run_until_done()
    for rid, ref in zip(rids, refs):
        assert np.array_equal(res[rid], ref)
    assert b.prefix_cache.evictions > 0     # pressure actually evicted
    b.audit_pages()
    assert _pages_leaked() == 0
    assert b.free_page_count + b.prefix_cache.cached_pages == b.n_pages


def test_prefix_cache_preemption_releases_pages():
    """ondemand preemption with the cache on: preempted requests resume
    exact, and no page leaks out of free ∪ block-table ∪ cache."""
    m = _model()
    prompts = _shared_prompts(7, 4, shared_len=32)
    refs = _refs(m, prompts, 10)
    with paddle.no_grad():
        b = PagedContinuousBatcher(m, max_batch=4, s_max=64, block_size=16,
                                   n_pages=12, compile=False,
                                   policy="ondemand", prefix_cache=True)
        rids = [b.submit(p, 10) for p in prompts]
        res = b.run_until_done()
    for rid, ref in zip(rids, refs):
        assert np.array_equal(res[rid], ref)
    b.audit_pages()
    assert _pages_leaked() == 0


# -- speculative decoding -----------------------------------------------------

@pytest.mark.exact
@pytest.mark.parametrize("draft_seed", [0, 1, 2])
def test_speculative_batcher_token_exact(draft_seed):
    """draft_seed=0 clones the target (high acceptance), others disagree
    (fallback-heavy) — output must be identical either way."""
    m = _model()
    dm = m if draft_seed == 0 else _model(draft_seed)
    prompts = _shared_prompts(11 + draft_seed, 3)
    refs = _refs(m, prompts, 8)
    with paddle.no_grad():
        b = PagedContinuousBatcher(m, max_batch=2, s_max=96, block_size=16,
                                   compile=False, draft_model=dm,
                                   draft_k=3)
        rids = [b.submit(p, 8) for p in prompts]
        res = b.run_until_done()
    for rid, ref in zip(rids, refs):
        assert np.array_equal(res[rid], ref)
    assert b.spec_stats["rounds"] > 0
    if draft_seed == 0:                     # self-draft must mostly match
        assert b.spec_stats["matched"] > 0
    b.audit_pages()


@pytest.mark.exact
def test_speculative_with_prefix_cache_composes():
    m = _model()
    dm = _model(9)
    prompts = _shared_prompts(13, 4)
    refs = _refs(m, prompts, 8)
    with paddle.no_grad():
        b = PagedContinuousBatcher(m, max_batch=2, s_max=96, block_size=16,
                                   n_pages=24, compile=False,
                                   policy="ondemand", prefix_cache=True,
                                   draft_model=dm, draft_k=3,
                                   prompt_buckets="pow2")
        rids = [b.submit(p, 8) for p in prompts]
        res = b.run_until_done()
    for rid, ref in zip(rids, refs):
        assert np.array_equal(res[rid], ref)
    assert b.prefix_cache.hit_tokens > 0
    assert b.spec_stats["rounds"] > 0
    b.audit_pages()
    assert _pages_leaked() == 0


def test_speculative_composition_gates():
    m = _model()
    dm = _model(1)
    with pytest.raises(ValueError):
        PagedContinuousBatcher(m, compile=False, draft_model=dm,
                               draft_k=0)
    with pytest.raises(ValueError):
        PagedContinuousBatcher(m, compile=False, draft_model=dm,
                               do_sample=True)
    with pytest.raises(ValueError):
        PagedContinuousBatcher(m, compile=False, prefix_cache=True,
                               cache_quant="dynamic_int8")
