"""MoE / expert-parallel tests.

Reference coverage model: test/collective/collective_global_scatter.py and
the moe layer unit tests (SURVEY.md §2.8.9); EP sharding exercised on the
8-device CPU mesh.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.incubate.distributed.models.moe import (
    ClipGradForMOEByGlobalNorm, FusedMoEFFN, GShardGate, MoELayer, NaiveGate,
    SwitchGate, global_gather, global_scatter)
from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
    _compute_capacity, _moe_masks_op)
from paddle_tpu.core.tensor import Tensor

D = 8


class Expert(nn.Layer):
    def __init__(self, scale):
        super().__init__()
        self.fc = nn.Linear(D, D)
        self.scale = scale

    def forward(self, x):
        return self.fc(x) * self.scale


def test_naive_gate_topk():
    gate = NaiveGate(D, num_expert=4, topk=2)
    x = paddle.randn([6, D])
    val, idx = gate(x)
    assert val.shape == [6, 2] and idx.shape == [6, 2]
    assert int(idx.numpy().max()) < 4


def test_gshard_gate_aux_loss():
    gate = GShardGate(D, num_expert=4)
    x = paddle.randn([16, D])
    val, idx = gate(x)
    loss = gate.get_loss()
    assert loss is not None and np.isfinite(float(loss))
    # perfectly uniform routing gives loss ~ 1.0; any routing >= ~1
    assert float(loss) > 0.5


def test_switch_gate_top1():
    gate = SwitchGate(D, num_expert=4)
    gate.eval()
    x = paddle.randn([10, D])
    val, idx = gate(x)
    assert val.shape == [10, 1]
    assert gate.get_loss() is not None


def test_dispatch_masks_capacity():
    # 6 tokens, 2 experts, capacity 2: expert 0 requested by 4 tokens -> 2 drop
    topk_idx = paddle.to_tensor(np.array([[0], [0], [0], [0], [1], [1]]))
    topk_val = paddle.to_tensor(np.ones((6, 1), dtype="float32"))
    combine, dispatch = _moe_masks_op(topk_val, topk_idx,
                                      num_experts=2, capacity=2)
    d = dispatch.numpy()
    assert d[:, 0, :].sum() == 2  # expert 0 holds only capacity tokens
    assert d[4:, 1, :].sum() == 2
    assert d[2:4].sum() == 0      # overflow tokens dropped


def test_moe_layer_matches_manual_routing():
    """With capacity ample and top-1 deterministic routing, MoE output equals
    running each token through its selected expert."""
    paddle.seed(3)
    experts = [Expert(1.0), Expert(2.0)]
    gate = NaiveGate(D, num_expert=2, topk=1)
    layer = MoELayer(D, experts, gate=gate, capacity_factor=8.0)
    x = paddle.randn([10, D])
    out = layer(x)

    logits = gate.gate(x)
    sel = logits.numpy().argmax(axis=-1)
    expected = np.zeros((10, D), dtype=np.float32)
    for i in range(10):
        expected[i] = experts[sel[i]](x[i:i + 1]).numpy()[0]
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-4, atol=1e-5)


def test_moe_layer_backward_trains():
    paddle.seed(0)
    layer = MoELayer(D, [Expert(1.0) for _ in range(4)],
                     gate={"type": "gshard"}, capacity_factor=2.0)
    opt = optimizer.AdamW(learning_rate=1e-2,
                          parameters=layer.parameters())
    x = paddle.randn([32, D])
    target = paddle.randn([32, D])
    losses = []
    for _ in range(5):
        out = layer(x)
        loss = ((out - target) ** 2).mean() + 0.01 * layer.l_aux
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert layer.gate.gate.weight.grad is None  # cleared


def test_fused_moe_ffn_matches_loop():
    """FusedMoEFFN == MoELayer with identical per-expert FFN weights."""
    paddle.seed(1)
    E, H = 2, 16
    fused = FusedMoEFFN(D, H, num_expert=E, gate={"type": "naive", "top_k": 1},
                        activation="gelu", capacity_factor=8.0)

    class FFNExpert(nn.Layer):
        def __init__(self, e):
            super().__init__()
            self.e = e

        def forward(self, x):
            h = paddle.matmul(x, Tensor(fused.w1._data[self.e])) + \
                Tensor(fused.b1._data[self.e])
            h = nn.functional.gelu(h)
            return paddle.matmul(h, Tensor(fused.w2._data[self.e])) + \
                Tensor(fused.b2._data[self.e])

    loop = MoELayer(D, [FFNExpert(e) for e in range(E)], gate=fused.gate,
                    capacity_factor=8.0)
    x = paddle.randn([12, D])
    np.testing.assert_allclose(fused(x).numpy(), loop(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_fused_moe_ep_sharded():
    """EP: stacked expert weights sharded over an 8-way ep axis."""
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    mesh = ProcessMesh(np.arange(8), ["ep"])
    layer = FusedMoEFFN(D, 16, num_expert=8,
                        gate={"type": "naive", "top_k": 2},
                        ep_mesh=mesh, ep_axis="ep")
    devs = {d for d in layer.w1._data.sharding.device_set}
    assert len(devs) == 8
    x = paddle.randn([16, D])
    out = layer(x)
    assert out.shape == [16, D]
    (out.sum()).backward()
    assert layer.w1.grad is not None


def test_moe_grad_clip():
    layer = MoELayer(D, [Expert(1.0), Expert(1.0)],
                     gate={"type": "naive", "top_k": 1})
    clip = ClipGradForMOEByGlobalNorm(
        0.01, is_expert_param_func=lambda p: "expert" in (p.name or ""))
    x = paddle.randn([8, D])
    layer(x).sum().backward()
    params = [p for p in layer.parameters() if p.grad is not None]
    clip(params)
    total = sum((p.grad.numpy() ** 2).sum() for p in params)
    assert np.sqrt(total) <= 0.0101


def test_global_scatter_gather_roundtrip():
    import paddle_tpu.distributed as dist
    dist.init_parallel_env()
    world, n, e = 8, 4, 1
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(world, n, D).astype("float32"))
    # each rank sends its 4 rows round-robin: 1 row to each of 4 dst ranks
    counts = np.zeros((world, world * e), dtype=np.int64)
    for r in range(world):
        for j in range(n):
            counts[r, (r + j) % world] += 1
    # receive counts are uniform (each rank receives 4 rows)
    # rows must be sorted by destination: build sorted x
    xs = np.zeros_like(x.numpy())
    for r in range(world):
        order = np.argsort([(r + j) % world for j in range(n)], kind="stable")
        xs[r] = x.numpy()[r][order]
    xs_t = paddle.to_tensor(xs)
    scattered = global_scatter(xs_t, counts, counts)
    assert scattered.shape == [world, n, D]
    back = global_gather(scattered, counts, counts)
    np.testing.assert_allclose(back.numpy(), xs, rtol=1e-6)


def test_moe_gate_topk_misconfig_raises():
    with pytest.raises(AssertionError):
        MoELayer(D, [Expert(1.0), Expert(1.0)],
                 gate={"type": "gshard", "top_k": 1})


def test_moe_group_placement_raises():
    import paddle_tpu.distributed as dist
    g = dist.init_parallel_env()
    with pytest.raises(NotImplementedError):
        MoELayer(D, [Expert(1.0)], moe_group=g)
