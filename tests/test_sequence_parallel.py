"""Sequence-parallel + SEP + ring-attention tests on the 8-device CPU mesh.

Reference coverage model: the sequence_parallel_utils unit tests and
hybrid_strategy tests (SURVEY.md §4); ring attention is the TPU-idiomatic
context-parallel filler (SURVEY.md §5) validated against dense attention.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel import ProcessMesh
from paddle_tpu.nn import functional as F
from paddle_tpu.ops.ring_attention import ring_attention


@pytest.fixture(autouse=True)
def _reset_topology():
    yield
    from paddle_tpu.distributed.fleet import topology
    topology.set_hybrid_communicate_group(None)


def _init_mp(mp=4, sep=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": mp,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": sep}
    fleet.init(is_collective=True, strategy=strategy)


def _dense_attention(q, k, v, causal):
    d = q.shape[-1]
    qt = np.einsum("bshd->bhsd", q).astype(np.float64)
    kt = np.einsum("bshd->bhsd", k).astype(np.float64)
    vt = np.einsum("bshd->bhsd", v).astype(np.float64)
    scores = np.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), dtype=bool))
        scores = np.where(mask, scores, -np.inf)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, vt)
    return np.einsum("bhsd->bshd", out)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 16, 2, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    mesh = ProcessMesh(np.arange(8), ["sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                         causal=causal)
    expected = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(out.numpy(), expected, rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match_dense():
    rng = np.random.RandomState(1)
    b, s, h, d = 1, 8, 1, 4
    qn = rng.randn(b, s, h, d).astype("float32")
    kn = rng.randn(b, s, h, d).astype("float32")
    vn = rng.randn(b, s, h, d).astype("float32")
    mesh = ProcessMesh(np.arange(8), ["sep"])

    q1 = paddle.to_tensor(qn, stop_gradient=False)
    k1 = paddle.to_tensor(kn, stop_gradient=False)
    v1 = paddle.to_tensor(vn, stop_gradient=False)
    ring_attention(q1, k1, v1, mesh=mesh, causal=True).sum().backward()

    q2 = paddle.to_tensor(qn, stop_gradient=False)
    k2 = paddle.to_tensor(kn, stop_gradient=False)
    v2 = paddle.to_tensor(vn, stop_gradient=False)
    F.scaled_dot_product_attention(q2, k2, v2, is_causal=True).sum().backward()

    np.testing.assert_allclose(q1.grad.numpy(), q2.grad.numpy(),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(k1.grad.numpy(), k2.grad.numpy(),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(v1.grad.numpy(), v2.grad.numpy(),
                               rtol=2e-3, atol=2e-4)


def test_sp_linears_match_plain():
    """Column+Row sequence-parallel pair == plain two-layer MLP."""
    _init_mp(mp=4)
    from paddle_tpu.distributed.fleet.utils import (
        ColumnSequenceParallelLinear, RowSequenceParallelLinear, ScatterOp,
        GatherOp)
    paddle.seed(0)
    col = ColumnSequenceParallelLinear(16, 32, gather_output=False)
    row = RowSequenceParallelLinear(32, 16, input_is_parallel=True)
    x = paddle.randn([8, 2, 16])  # [s, b, h]
    xs = ScatterOp.apply(x)
    y = row(F.relu(col(xs)))
    y_full = GatherOp.apply(y)

    ref = paddle.matmul(
        F.relu(paddle.matmul(x, col.weight) + col.bias), row.weight) + row.bias
    np.testing.assert_allclose(y_full.numpy(), ref.numpy(),
                               rtol=2e-4, atol=2e-5)
    devs = col.weight._data.sharding.device_set
    assert len(devs) == 8  # weight lives sharded over the (dp=2)x(mp=4) mesh


def test_sp_param_marking():
    from paddle_tpu.distributed.fleet.utils import (
        is_sequence_parallel_parameter, mark_as_sequence_parallel_parameter,
        register_sequence_parallel_allreduce_hooks)
    ln = nn.LayerNorm(8)
    mark_as_sequence_parallel_parameter(ln.weight)
    assert is_sequence_parallel_parameter(ln.weight)
    assert not is_sequence_parallel_parameter(ln.bias)
    register_sequence_parallel_allreduce_hooks(ln)  # no-op, must not raise


def test_segment_parallel_wrapper():
    _init_mp(mp=1, sep=4)
    paddle.seed(0)

    class TinySeqModel(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(32, 16)
            self.fc = nn.Linear(16, 32)

        def forward(self, ids):
            return self.fc(self.emb(ids))

    model = TinySeqModel()
    wrapped = fleet.distributed_model(model)
    from paddle_tpu.distributed.fleet import SegmentParallel
    assert isinstance(wrapped, SegmentParallel)
    ids = paddle.to_tensor(np.arange(32).reshape(2, 16) % 32)
    out = wrapped(ids)
    assert out.shape == [2, 16, 32]
    out.sum().backward()
    assert model.fc.weight.grad is not None


def test_llama_with_ring_attention_matches_dense():
    """Llama forward with sep ring attention == plain attention path."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    paddle.seed(9)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            num_attention_heads=2, num_key_value_heads=2,
                            vocab_size=64, max_position_embeddings=32)
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.arange(16).reshape(1, 16) % 64)
    with paddle.no_grad():
        ref = model(ids).numpy()
    mesh = ProcessMesh(np.arange(8), ["sep"])
    cfg.sep_mesh = mesh
    with paddle.no_grad():
        out = model(ids).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_gqa_unexpanded_kv():
    """GQA: kv heads stay unexpanded on the ring; matches expanded dense."""
    rng = np.random.RandomState(2)
    b, s, h, kv, d = 1, 16, 4, 2, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, kv, d).astype("float32")
    v = rng.randn(b, s, kv, d).astype("float32")
    mesh = ProcessMesh(np.arange(8), ["sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh, causal=True)
    k_exp = np.repeat(k, h // kv, axis=2)
    v_exp = np.repeat(v, h // kv, axis=2)
    expected = _dense_attention(q, k_exp, v_exp, causal=True)
    np.testing.assert_allclose(out.numpy(), expected, rtol=2e-4, atol=2e-5)


def test_scanned_llama_ring_matches_dense():
    """scan_layers + sep ring attention == scanned dense (VERDICT #6: the
    flagship compiled path can now use context parallelism)."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    paddle.seed(11)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=32,
                            num_attention_heads=4, num_key_value_heads=2,
                            vocab_size=64, max_position_embeddings=32)
    cfg.scan_layers = True
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.arange(32).reshape(2, 16) % 64)
    with paddle.no_grad():
        ref = model(ids).numpy()
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "sep"])
    cfg.sep_mesh = mesh
    cfg.sep_axis = "sep"
    with paddle.no_grad():
        out = model(ids).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_scanned_llama_ring_backward():
    """Gradients flow through scan-of-ring (training path)."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    paddle.seed(12)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=32,
                            num_attention_heads=2, num_key_value_heads=2,
                            vocab_size=64, max_position_embeddings=32)
    cfg.scan_layers = True
    cfg.sep_mesh = ProcessMesh(np.arange(8), ["sep"])
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.arange(16).reshape(1, 16) % 64)
    labels = paddle.to_tensor((np.arange(16).reshape(1, 16) + 1) % 64)
    _, loss = model(ids, labels=labels)
    loss.backward()
    sc = model.model.layers_scanned
    assert sc.q_w.grad is not None
    assert bool(np.isfinite(sc.q_w.grad.numpy()).all())


def _dense_masked(q, k, v, causal, mask=None, seqlens=None):
    """Dense reference with additive/bool mask and per-batch seqlens."""
    d = q.shape[-1]
    qt = np.einsum("bshd->bhsd", q).astype(np.float64)
    kt = np.einsum("bshd->bhsd", k).astype(np.float64)
    vt = np.einsum("bshd->bhsd", v).astype(np.float64)
    scores = np.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    if mask is not None:
        if mask.dtype == bool:
            scores = np.where(mask, scores, -np.inf)
        else:
            scores = scores + mask
    if causal:
        s = q.shape[1]
        scores = np.where(np.tril(np.ones((s, s), bool)), scores, -np.inf)
    if seqlens is not None:
        s = q.shape[1]
        cols = np.arange(s)[None, None, None, :]
        rows = np.arange(s)[None, None, :, None]
        sl = seqlens[:, None, None, None]
        scores = np.where((cols < sl) & (rows < sl), scores, -np.inf)
    scores = scores - np.nanmax(np.where(np.isneginf(scores), np.nan, scores),
                                axis=-1, keepdims=True)
    p = np.exp(scores)
    p = np.where(np.isnan(p), 0.0, p)
    denom = p.sum(axis=-1, keepdims=True)
    p = np.where(denom > 0, p / np.maximum(denom, 1e-20), 0.0)
    out = np.einsum("bhqk,bhkd->bhqd", p, vt)
    return np.einsum("bhsd->bshd", out)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_additive_mask_matches_dense(causal):
    """VERDICT r2 #5: masked batches ride the ring (packed sequences)."""
    rng = np.random.RandomState(3)
    b, s, h, d = 2, 16, 2, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    mask = (rng.randn(b, 1, s, s) * 2).astype("float32")
    mesh = ProcessMesh(np.arange(8), ["sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                         causal=causal, attn_mask=paddle.to_tensor(mask))
    expected = _dense_masked(q, k, v, causal, mask=mask)
    np.testing.assert_allclose(out.numpy(), expected, rtol=2e-4, atol=2e-5)


def test_ring_attention_bool_mask_matches_dense():
    rng = np.random.RandomState(4)
    b, s, h, d = 1, 16, 2, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    keep = rng.rand(b, 1, s, s) > 0.3
    keep[..., 0] = True  # no fully-masked row
    mesh = ProcessMesh(np.arange(8), ["sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh,
                         causal=False, attn_mask=paddle.to_tensor(keep))
    expected = _dense_masked(q, k, v, False, mask=keep)
    np.testing.assert_allclose(out.numpy(), expected, rtol=2e-4, atol=2e-5)


def test_ring_attention_kv_seqlens_matches_dense():
    """Padded batches: per-batch valid lengths thread through the ring the
    way flash v2's kv_seqlens do; padded tail rows come out zero."""
    rng = np.random.RandomState(5)
    b, s, h, d = 2, 16, 2, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    lens = np.asarray([13, 6], np.int32)
    mesh = ProcessMesh(np.arange(8), ["sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh, causal=True,
                         kv_seqlens=paddle.to_tensor(lens)).numpy()
    expected = _dense_masked(q, k, v, True, seqlens=lens)
    for i, L in enumerate(lens):
        np.testing.assert_allclose(out[i, :L], expected[i, :L],
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(out[i, L:], 0.0, atol=1e-6)


def test_ring_attention_masked_grads_match_dense():
    rng = np.random.RandomState(6)
    b, s, h, d = 1, 8, 1, 4
    qn = rng.randn(b, s, h, d).astype("float32")
    kn = rng.randn(b, s, h, d).astype("float32")
    vn = rng.randn(b, s, h, d).astype("float32")
    mask = (rng.randn(b, 1, s, s)).astype("float32")
    mesh = ProcessMesh(np.arange(8), ["sep"])

    q1 = paddle.to_tensor(qn, stop_gradient=False)
    k1 = paddle.to_tensor(kn, stop_gradient=False)
    v1 = paddle.to_tensor(vn, stop_gradient=False)
    ring_attention(q1, k1, v1, mesh=mesh, causal=True,
                   attn_mask=paddle.to_tensor(mask)).sum().backward()

    q2 = paddle.to_tensor(qn, stop_gradient=False)
    k2 = paddle.to_tensor(kn, stop_gradient=False)
    v2 = paddle.to_tensor(vn, stop_gradient=False)
    causal_add = np.where(np.tril(np.ones((s, s), bool)), 0.0,
                          -1e30).astype("float32")
    F.scaled_dot_product_attention(
        q2, k2, v2,
        attn_mask=paddle.to_tensor(mask + causal_add)).sum().backward()

    np.testing.assert_allclose(q1.grad.numpy(), q2.grad.numpy(),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(k1.grad.numpy(), k2.grad.numpy(),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(v1.grad.numpy(), v2.grad.numpy(),
                               rtol=2e-3, atol=2e-4)


def test_llama_ring_with_mask_matches_dense():
    """The flagship's ring path no longer falls back to dense when a mask
    is present (VERDICT r2 weak #7) — masked + context-parallel match."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    paddle.seed(13)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            num_attention_heads=2, num_key_value_heads=2,
                            vocab_size=64, max_position_embeddings=32)
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.arange(16).reshape(1, 16) % 64)
    rng = np.random.RandomState(7)
    mask = paddle.to_tensor((rng.randn(1, 1, 16, 16) * 0.5).astype("float32"))
    with paddle.no_grad():
        ref = model(ids, attn_mask=mask).numpy()
    cfg.sep_mesh = ProcessMesh(np.arange(8), ["sep"])
    with paddle.no_grad():
        out = model(ids, attn_mask=mask).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_scanned_llama_ring_with_mask_matches_dense():
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    paddle.seed(14)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=32,
                            num_attention_heads=4, num_key_value_heads=2,
                            vocab_size=64, max_position_embeddings=32)
    cfg.scan_layers = True
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.arange(32).reshape(2, 16) % 64)
    rng = np.random.RandomState(8)
    mask = paddle.to_tensor((rng.randn(2, 1, 16, 16) * 0.5).astype("float32"))
    with paddle.no_grad():
        ref = model(ids, attn_mask=mask).numpy()
    cfg.sep_mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "sep"])
    cfg.sep_axis = "sep"
    with paddle.no_grad():
        out = model(ids, attn_mask=mask).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_broadcastable_padding_mask():
    """[b,1,1,s] padding masks (the standard broadcastable form) are
    materialized to full rows before the ring shards them (review repro:
    used to crash in shard_map on the size-1 row dim)."""
    rng = np.random.RandomState(9)
    b, s, h, d = 2, 16, 2, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    pad = np.zeros((b, 1, 1, s), np.float32)
    pad[1, ..., 12:] = -1e9
    mesh = ProcessMesh(np.arange(8), ["sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh, causal=False,
                         attn_mask=paddle.to_tensor(pad)).numpy()
    full = np.broadcast_to(pad, (b, 1, s, s))
    expected = _dense_masked(q, k, v, False, mask=full)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ring_attention_per_head_mask_with_mp_axis():
    """[b,h,s,s] masks shard their head dim alongside q's heads (review
    repro: reshape crash when an mp axis shards heads)."""
    rng = np.random.RandomState(10)
    b, s, h, d = 2, 16, 4, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    mask = (rng.randn(b, h, s, s)).astype("float32")
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["mp", "sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                         causal=False, attn_mask=paddle.to_tensor(mask))
    # dense ref with per-head mask
    expected = _dense_masked(q, k, v, False, mask=mask)
    np.testing.assert_allclose(out.numpy(), expected, rtol=2e-4, atol=2e-5)


def test_scanned_llama_selective_recompute_matches_full():
    """recompute_granularity='selective' (dots-saveable checkpoint policy)
    must match full recompute and no-recompute numerics exactly — the
    policy changes WHAT XLA keeps resident, never the math."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    results = {}
    for gran, remat in (("none", False), ("full", True),
                        ("selective", True)):
        paddle.seed(21)
        cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=32,
                                num_attention_heads=2,
                                num_key_value_heads=2, vocab_size=64,
                                max_position_embeddings=32)
        cfg.scan_layers = True
        cfg.use_recompute = remat
        cfg.recompute_granularity = gran if remat else "full"
        m = LlamaForCausalLM(cfg)
        m.train()
        ids = paddle.to_tensor(np.arange(16).reshape(1, 16) % 64)
        _, loss = m(ids, labels=ids)
        loss.backward()
        results[gran] = (float(loss),
                         m.model.layers_scanned.q_w.grad.numpy().copy())
    for gran in ("full", "selective"):
        assert results[gran][0] == results["none"][0]
        np.testing.assert_allclose(results[gran][1], results["none"][1],
                                   rtol=1e-5, atol=1e-6)
    # unknown granularity rejected loudly
    paddle.seed(22)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            num_attention_heads=2, num_key_value_heads=2,
                            vocab_size=64, max_position_embeddings=32)
    cfg.scan_layers = True
    cfg.use_recompute = True
    cfg.recompute_granularity = "bogus"
    m = LlamaForCausalLM(cfg)
    m.train()
    ids = paddle.to_tensor(np.arange(16).reshape(1, 16) % 64)
    with pytest.raises(ValueError, match="recompute_granularity"):
        m(ids, labels=ids)


def test_ring_attention_sep4_mask_and_seqlens():
    """EXPLICIT 4-way sep ring on a (dp, sep) grid (VERDICT r3 #7):
    per-batch kv_seqlens + causality through a 4-hop K/V rotation match
    the dense reference on every valid row."""
    rng = np.random.RandomState(21)
    b, s, h, d = 2, 24, 2, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    lens = np.array([20, 24], np.int64)
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "sep"])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                         causal=True,
                         kv_seqlens=paddle.to_tensor(lens)).numpy()
    ref = _dense_masked(q, k, v, True, seqlens=lens)
    for i, L in enumerate(lens):
        np.testing.assert_allclose(out[i, :L], ref[i, :L],
                                   rtol=2e-4, atol=2e-5)


# -- Ulysses (all-to-all) context parallelism -------------------------------

def _ulysses(*args, **kw):
    from paddle_tpu.ops.ulysses_attention import ulysses_attention
    return ulysses_attention(*args, **kw)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    """DeepSpeed-Ulysses style all-to-all CP: heads<->sequence exchange,
    full attention per head subset, exchange back — must equal dense."""
    rng = np.random.RandomState(30)
    b, s, h, d = 2, 32, 8, 8
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    mesh = ProcessMesh(np.arange(8), ["sep"])
    out = _ulysses(paddle.to_tensor(q), paddle.to_tensor(k),
                   paddle.to_tensor(v), mesh=mesh, causal=causal)
    expected = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(out.numpy(), expected, rtol=2e-4, atol=2e-5)


def test_ulysses_gqa_mask_seqlens_and_grads():
    rng = np.random.RandomState(31)
    b, s, h, kv, d = 2, 24, 8, 4, 8   # GQA rep=2; h, kv divisible by sep=4
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "sep"])
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, kv, d).astype("float32")
    v = rng.randn(b, s, kv, d).astype("float32")
    # GQA + causal + per-batch valid lengths on a (dp, sep) grid
    lens = np.array([20, 24], np.int64)
    out = _ulysses(paddle.to_tensor(q), paddle.to_tensor(k),
                   paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                   causal=True, kv_seqlens=paddle.to_tensor(lens)).numpy()
    ref = _dense_masked(q, np.repeat(k, h // kv, 2),
                        np.repeat(v, h // kv, 2), True, seqlens=lens)
    for i, L in enumerate(lens):
        np.testing.assert_allclose(out[i, :L], ref[i, :L],
                                   rtol=2e-4, atol=2e-5)
    # additive mask + backward through both all-to-alls
    mesh1 = ProcessMesh(np.arange(8), ["sep"])
    q8 = rng.randn(1, 16, 8, 8).astype("float32")
    k8 = rng.randn(1, 16, 8, 8).astype("float32")
    v8 = rng.randn(1, 16, 8, 8).astype("float32")
    mask = (rng.randn(1, 1, 16, 16) * 2).astype("float32")

    qt = paddle.to_tensor(q8)
    qt.stop_gradient = False
    out2 = _ulysses(qt, paddle.to_tensor(k8), paddle.to_tensor(v8),
                    mesh=mesh1, causal=False,
                    attn_mask=paddle.to_tensor(mask))
    out2.sum().backward()
    g = qt.grad.numpy()

    # dense reference gradient via jax on the same math
    import jax
    import jax.numpy as jnp

    def dense_sum(qq):
        qt_ = jnp.einsum("bshd->bhsd", qq)
        kt_ = jnp.einsum("bshd->bhsd", jnp.asarray(k8))
        vt_ = jnp.einsum("bshd->bhsd", jnp.asarray(v8))
        sc = jnp.einsum("bhqd,bhkd->bhqk", qt_, kt_) / np.sqrt(8)
        sc = sc + jnp.asarray(mask)
        p = jax.nn.softmax(sc.astype(jnp.float32), -1).astype(qq.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vt_)
        return o.sum()

    gd = jax.grad(dense_sum)(jnp.asarray(q8))
    np.testing.assert_allclose(g, np.asarray(gd), rtol=2e-3, atol=2e-4)


def test_ulysses_hybrid_mp_sep_shards_heads_jointly():
    """ADVICE r4: on a hybrid (mp, sep) mesh, heads shard jointly over
    (mp, sep) — the head dim must not replicate over mp. Numerics must
    still match dense, including a per-head additive mask."""
    rng = np.random.RandomState(34)
    b, s, h, d = 2, 16, 8, 8          # h divisible by |mp|*|sep| = 8
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["mp", "sep"])
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")
    out = _ulysses(paddle.to_tensor(q), paddle.to_tensor(k),
                   paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                   causal=True).numpy()
    np.testing.assert_allclose(out, _dense_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-5)
    # per-head mask shards over (mp, sep) too
    mask = (rng.randn(b, h, s, s) * 2).astype("float32")
    out2 = _ulysses(paddle.to_tensor(q), paddle.to_tensor(k),
                    paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                    causal=False,
                    attn_mask=paddle.to_tensor(mask)).numpy()
    ref = _dense_masked(q, k, v, False, mask=mask)
    np.testing.assert_allclose(out2, ref, rtol=2e-4, atol=2e-5)
    # h=4 < |mp|*|sep|: joint sharding impossible -> head_axis dropped,
    # still correct (replicated-over-mp fallback)
    q4 = rng.randn(b, s, 4, d).astype("float32")
    k4 = rng.randn(b, s, 4, d).astype("float32")
    v4 = rng.randn(b, s, 4, d).astype("float32")
    out3 = _ulysses(paddle.to_tensor(q4), paddle.to_tensor(k4),
                    paddle.to_tensor(v4), mesh=mesh, axis_name="sep",
                    causal=True).numpy()
    np.testing.assert_allclose(out3, _dense_attention(q4, k4, v4, True),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_hybrid_gqa_headed_mask():
    """GQA (rep=2) with heads jointly sharded over (mp, sep): the
    riskiest layout — kv heads all-to-all split + q/mask head-block
    alignment with rep > 1 on a hybrid mesh — plus a per-head mask."""
    rng = np.random.RandomState(36)
    b, s, h, kv, d = 2, 16, 16, 8, 8  # both divisible by |mp|*|sep|=8
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["mp", "sep"])
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, kv, d).astype("float32")
    v = rng.randn(b, s, kv, d).astype("float32")
    out = _ulysses(paddle.to_tensor(q), paddle.to_tensor(k),
                   paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                   causal=True).numpy()
    ref = _dense_attention(q, np.repeat(k, h // kv, 2),
                           np.repeat(v, h // kv, 2), True)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)
    mask = (rng.randn(b, h, s, s) * 2).astype("float32")
    out2 = _ulysses(paddle.to_tensor(q), paddle.to_tensor(k),
                    paddle.to_tensor(v), mesh=mesh, axis_name="sep",
                    causal=False,
                    attn_mask=paddle.to_tensor(mask)).numpy()
    ref2 = _dense_masked(q, np.repeat(k, h // kv, 2),
                         np.repeat(v, h // kv, 2), False, mask=mask)
    np.testing.assert_allclose(out2, ref2, rtol=2e-4, atol=2e-5)


def test_ulysses_public_impl_seam():
    """VERDICT r4 item 6: ulysses_attention_impl is the scan-safe public
    entry — same cache slots as the wrapper, callable directly."""
    from paddle_tpu.ops.ulysses_attention import (
        _cached_impl, ulysses_attention_impl, validate_ulysses)
    import jax.numpy as jnp
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "sep"])
    jmesh = mesh.jax_mesh
    validate_ulysses(jmesh, "sep", 8, 8, 16)
    impl = ulysses_attention_impl(mesh, "sep", causal=True,
                                  batch_axis=("dp",))
    # identical lru_cache slot as the private constructor
    assert impl is _cached_impl(jmesh, "sep", True, ("dp",), False,
                                False, False, None)
    rng = np.random.RandomState(35)
    q = rng.randn(2, 16, 8, 8).astype("float32")
    k = rng.randn(2, 16, 8, 8).astype("float32")
    v = rng.randn(2, 16, 8, 8).astype("float32")
    out = np.asarray(impl(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, _dense_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_ragged_heads():
    mesh = ProcessMesh(np.arange(8), ["sep"])
    rng = np.random.RandomState(32)
    q = paddle.to_tensor(rng.randn(1, 16, 6, 8).astype("float32"))
    with pytest.raises(ValueError, match="divisible by the context axis"):
        _ulysses(q, q, q, mesh=mesh)


@pytest.mark.parametrize("scan", [False, True])
def test_llama_with_ulysses_matches_dense(scan):
    """cfg.sep_impl='ulysses': BOTH attention paths (unrolled
    LlamaAttention and the scanned stack) swap ring for the all-to-all
    strategy and still match the plain attention path."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    rng = np.random.RandomState(33)
    ids = rng.randint(0, 128, (2, 32))
    paddle.seed(0)
    dense = LlamaForCausalLM(llama_tiny_config(num_attention_heads=8,
                                               num_key_value_heads=8,
                                               scan_layers=scan))
    with paddle.no_grad():
        ref = dense(paddle.to_tensor(ids)).numpy()
    paddle.seed(0)
    cfg = llama_tiny_config(num_attention_heads=8, num_key_value_heads=8,
                            scan_layers=scan)
    cfg.sep_mesh = ProcessMesh(np.arange(8), ["sep"])
    cfg.sep_axis = "sep"
    cfg.sep_impl = "ulysses"
    m = LlamaForCausalLM(cfg)
    with paddle.no_grad():
        out = m(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("scan", [False, True])
def test_llama_sep_impl_auto_selects_and_matches(scan):
    """sep_impl='auto': ulysses when the shape contract holds (h=kv=8
    over sep=8), ring when it cannot (kv=2 not divisible) — both paths
    must run WITHOUT error and match the dense model."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    from paddle_tpu.ops.ulysses_attention import choose_sep_impl
    rng = np.random.RandomState(37)
    ids = rng.randint(0, 128, (2, 32))
    for heads, kvh in ((8, 8), (8, 2)):
        paddle.seed(0)
        dense = LlamaForCausalLM(llama_tiny_config(
            num_attention_heads=heads, num_key_value_heads=kvh,
            scan_layers=scan))
        with paddle.no_grad():
            ref = dense(paddle.to_tensor(ids)).numpy()
        paddle.seed(0)
        cfg = llama_tiny_config(num_attention_heads=heads,
                                num_key_value_heads=kvh, scan_layers=scan)
        cfg.sep_mesh = ProcessMesh(np.arange(8), ["sep"])
        cfg.sep_axis = "sep"
        cfg.sep_impl = "auto"
        m = LlamaForCausalLM(cfg)
        with paddle.no_grad():
            out = m(paddle.to_tensor(ids)).numpy()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)
    # the chooser itself: divisible -> ulysses; ragged kv -> ring
    jm = ProcessMesh(np.arange(8), ["sep"]).jax_mesh
    assert choose_sep_impl(jm, "sep", 8, 8, 32) == "ulysses"
    assert choose_sep_impl(jm, "sep", 8, 2, 32) == "ring"
    # hybrid mesh: joint rule governs (h=8 over |mp|*|sep|=8 ok; seq
    # indivisible by sep -> ring)
    jm2 = ProcessMesh(np.arange(8).reshape(2, 4), ["mp", "sep"]).jax_mesh
    assert choose_sep_impl(jm2, "sep", 8, 8, 32) == "ulysses"
    assert choose_sep_impl(jm2, "sep", 8, 8, 30) == "ring"


def test_llama_ulysses_ragged_heads_error_is_loud():
    """A config ulysses cannot serve (kv not divisible by the sep axis)
    must fail with the documented ValueError, not a shard_map shape
    error from inside the scan trace."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    paddle.seed(0)
    cfg = llama_tiny_config(num_attention_heads=8, num_key_value_heads=2,
                            scan_layers=True)
    cfg.sep_mesh = ProcessMesh(np.arange(8), ["sep"])
    cfg.sep_impl = "ulysses"
    m = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.arange(32).reshape(1, 32) % 128)
    with pytest.raises(ValueError, match="divisible by the context axis"):
        with paddle.no_grad():
            m(ids)
