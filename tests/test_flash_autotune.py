"""Flash-attention block autotuner (CPU-side machinery tests).

Timing only means something on real hardware — `pytest -m tpu` runs the
actual sweep (test_tpu_tier.py). Here we pin the pure machinery:
candidate filtering, the cache, and `_resolve_blocks` (explicit blocks
win; cached tilings are adopted; short sequences and interpret mode skip
the consult) — without ever running a Mosaic kernel.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas import autotune
from paddle_tpu.ops.pallas.flash_attention import (DEFAULT_BLOCK_K,
                                                   DEFAULT_BLOCK_Q,
                                                   _resolve_blocks,
                                                   flash_attention_pallas)


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), jnp.float32)


@pytest.fixture(autouse=True)
def _clean_cache():
    autotune.clear_cache()
    yield
    autotune.clear_cache()


@pytest.fixture
def _flag_on():
    paddle.set_flags({"FLAGS_flash_autotune": True})
    yield
    paddle.set_flags({"FLAGS_flash_autotune": False})


def test_tuning_refuses_off_tpu():
    q = _rand((1, 256, 2, 64))
    with pytest.raises(RuntimeError, match="off TPU"):
        autotune.tune_flash_blocks(q, q, q)


def test_candidate_filter_drops_over_lcm_tilings():
    assert autotune._filter_candidates(64, autotune.CANDIDATES) == []
    got = autotune._filter_candidates(256, autotune.CANDIDATES)
    assert (128, 128) in got and (256, 256) in got
    assert (128, 512) not in got and (512, 128) not in got
    assert autotune._filter_candidates(
        512, autotune.CANDIDATES) == autotune.CANDIDATES


def test_cached_blocks_roundtrip_and_set_best():
    q, k = _rand((1, 256, 4, 64), 1), _rand((1, 256, 2, 64), 2)
    assert autotune.cached_blocks(q, k, True, False, 0.0) is None
    autotune.set_best(q, k, True, False, 0.0, (256, 128))
    assert autotune.cached_blocks(q, k, True, False, 0.0) == (256, 128)
    # a different signature misses
    assert autotune.cached_blocks(q, k, False, False, 0.0) is None


def test_resolve_blocks_explicit_always_wins(_flag_on):
    """A caller forcing the default tiling must GET the default tiling,
    even when the cache prefers another one (review repro)."""
    q, k, v = _rand((1, 512, 2, 64), 3), _rand((1, 512, 2, 64), 4), \
        _rand((1, 512, 2, 64), 5)
    autotune.set_best(q, k, True, False, 0.0, (256, 256))
    assert _resolve_blocks(q, k, v, True, None, 0.0, 128, 128,
                           False) == (128, 128)
    assert _resolve_blocks(q, k, v, True, None, 0.0, 256, None,
                           False) == (256, DEFAULT_BLOCK_K)


def test_resolve_blocks_adopts_cached_tiling(_flag_on):
    q, k, v = _rand((1, 512, 2, 64), 6), _rand((1, 512, 2, 64), 7), \
        _rand((1, 512, 2, 64), 8)
    autotune.set_best(q, k, True, False, 0.0, (256, 128))
    assert _resolve_blocks(q, k, v, True, None, 0.0, None, None,
                           False) == (256, 128)
    # flag off: defaults
    paddle.set_flags({"FLAGS_flash_autotune": False})
    assert _resolve_blocks(q, k, v, True, None, 0.0, None, None,
                           False) == (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    paddle.set_flags({"FLAGS_flash_autotune": True})


def test_resolve_blocks_skips_short_seq_and_interpret(_flag_on):
    """Short sequences (shrink branch governs) and interpret mode never
    consult the cache — no wasted tuning for a discarded answer."""
    q, k, v = _rand((1, 64, 2, 64), 9), _rand((1, 64, 2, 64), 10), \
        _rand((1, 64, 2, 64), 11)
    autotune.set_best(q, k, True, False, 0.0, (256, 128))
    assert _resolve_blocks(q, k, v, True, None, 0.0, None, None,
                           False) == (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    q2, k2, v2 = _rand((1, 512, 2, 64), 12), _rand((1, 512, 2, 64), 13), \
        _rand((1, 512, 2, 64), 14)
    autotune.set_best(q2, k2, True, False, 0.0, (256, 128))
    assert _resolve_blocks(q2, k2, v2, True, None, 0.0, None, None,
                           True) == (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)


def test_block_choice_is_numerics_neutral():
    """Different tilings, identical math (interpret mode, CPU)."""
    b, s, h, d = 1, 256, 2, 64
    q, k, v = _rand((b, s, h, d), 15), _rand((b, s, h, d), 16), \
        _rand((b, s, h, d), 17)
    ref = flash_attention_pallas(q, k, v, causal=True, interpret=True)
    out = flash_attention_pallas(q, k, v, causal=True, block_q=64,
                                 block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
