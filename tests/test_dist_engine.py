"""Tests for the semi-automatic parallel engine: Strategy / DistModel /
distributed.to_static (auto_parallel/api.py:799,987,1405 analogs), on the
8-device CPU mesh (conftest forces JAX_PLATFORMS=cpu x8)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import DistModel, Strategy, to_static
from paddle_tpu.distributed.auto_parallel import (ProcessMesh, Replicate,
                                                  Shard, set_default_mesh,
                                                  shard_tensor)


@pytest.fixture
def mesh():
    m = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
    set_default_mesh(m)
    yield m
    set_default_mesh(None)


def _mlp():
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _batch(mesh, n=8):
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(n, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (n,)))
    place = [Shard(0), Replicate()]
    return (shard_tensor(x, mesh, place), shard_tensor(y, mesh, place))


def test_strategy_defaults_and_config():
    s = Strategy()
    assert not s.sharding.enable
    assert s.amp.dtype == "bfloat16"
    s2 = Strategy({"sharding": {"enable": True, "stage": 2},
                   "gradient_merge": {"enable": True, "k_steps": 4}})
    assert s2.sharding.enable and s2.sharding.stage == 2
    assert s2.gradient_merge.k_steps == 4
    assert "Strategy(" in repr(s2)


def test_dist_model_train_loss_decreases(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.05, parameters=net.parameters())
    model = to_static(net, loss=nn.CrossEntropyLoss(), optimizer=opt)
    x, y = _batch(mesh)
    losses = [float(model(x, y)) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert model._mode == "train"


def test_dist_model_mode_switch(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
    model = DistModel(net, loss=nn.CrossEntropyLoss(), optimizer=opt)
    x, y = _batch(mesh)
    model(x, y)  # train step 1 (discovery)
    model.eval()
    ev = float(model(x, y))
    assert np.isfinite(ev)
    model.predict()
    out = model(x)
    assert tuple(out.shape) == (8, 4)
    model.train()
    tr = float(model(x, y))
    assert np.isfinite(tr)


def test_dist_model_sharding_strategy(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.05, parameters=net.parameters())
    strategy = Strategy({"sharding": {"enable": True, "stage": 2}})
    model = DistModel(net, loss=nn.CrossEntropyLoss(), optimizer=opt,
                      strategy=strategy)
    x, y = _batch(mesh)
    l0 = float(model(x, y))
    l1 = float(model(x, y))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_dist_model_gradient_merge(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.05, parameters=net.parameters())
    strategy = Strategy({"gradient_merge": {"enable": True, "k_steps": 2}})
    model = DistModel(net, loss=nn.CrossEntropyLoss(), optimizer=opt,
                      strategy=strategy)
    x, y = _batch(mesh)
    model(x, y)
    # after 1 micro-batch the grads are pending (no step yet)
    assert model._acc_count == 1
    model(x, y)
    assert model._acc_count == 0  # boundary stepped + cleared


def test_dist_model_amp_strategy(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
    strategy = Strategy({"amp": {"enable": True, "dtype": "bfloat16",
                                 "level": "O2"}})
    model = DistModel(net, loss=nn.CrossEntropyLoss(), optimizer=opt,
                      strategy=strategy)
    x, y = _batch(mesh)
    assert np.isfinite(float(model(x, y)))
    assert np.isfinite(float(model(x, y)))


def test_dist_model_state_dict_roundtrip(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.05, parameters=net.parameters())
    model = DistModel(net, loss=nn.CrossEntropyLoss(), optimizer=opt)
    x, y = _batch(mesh)
    model(x, y)
    sd = model.state_dict()
    assert any(k.startswith("optimizer.") for k in sd)

    net2 = _mlp()
    opt2 = optimizer.AdamW(learning_rate=0.05, parameters=net2.parameters())
    model2 = DistModel(net2, loss=nn.CrossEntropyLoss(), optimizer=opt2)
    model2.set_state_dict(sd)
    model2.predict()
    model.predict()
    np.testing.assert_allclose(np.asarray(model(x)._data),
                               np.asarray(model2(x)._data), rtol=1e-5)


def test_dist_model_stage3_shards_params(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.05, parameters=net.parameters())
    strategy = Strategy({"sharding": {"enable": True, "stage": 3}})
    model = DistModel(net, loss=nn.CrossEntropyLoss(), optimizer=opt,
                      strategy=strategy)
    sharded = [p for p in net.parameters()
               if p._dist_attr is not None and p.ndim > 0
               and p.shape[0] % 4 == 0]
    assert sharded, "stage 3 should shard dim-0-divisible parameters"
    x, y = _batch(mesh)
    assert np.isfinite(float(model(x, y)))


def test_dist_model_missing_label_raises(mesh):
    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.05, parameters=net.parameters())
    model = DistModel(net, loss=nn.CrossEntropyLoss(), optimizer=opt)
    x, _ = _batch(mesh)
    with pytest.raises(ValueError, match="expects"):
        model(x)


def test_strategy_configs_not_shared():
    s1 = Strategy()
    s1.fused_passes.fused_passes_list.append("gemm_epilogue")
    assert Strategy().fused_passes.fused_passes_list == []


def test_executor_unknown_feed_raises():
    from paddle_tpu import static
    prog = static.Program()
    with static.program_guard(prog):
        static.data("x", [None, 4], "float32")
    with pytest.raises(KeyError, match="matches no declared"):
        static.Executor().run(prog, feed={"X": np.ones((1, 4))},
                              fetch_list=[])


def test_static_program_facade():
    from paddle_tpu import static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        w = paddle.to_tensor(np.ones((4, 2), np.float32))
        y = paddle.matmul(x, w)          # canonical: fetch the VARIABLE
        z = paddle.nn.functional.relu(y - 6.0)
    exe = static.Executor()
    out, z_out = exe.run(prog, feed={"x": np.full((3, 4), 2.0, np.float32)},
                         fetch_list=[y, z])
    np.testing.assert_allclose(out, np.full((3, 2), 8.0), rtol=1e-6)
    np.testing.assert_allclose(z_out, np.full((3, 2), 2.0), rtol=1e-6)
    out2, = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y])
    np.testing.assert_allclose(out2, np.full((2, 2), 4.0), rtol=1e-6)
    assert "x" in repr(prog)
    assert static.default_main_program() is not prog  # guard restored


def test_static_executor_callable_fetch():
    from paddle_tpu import static
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        fetch = lambda: x * 3.0  # noqa: E731
    out, = static.Executor().run(
        prog, feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[fetch])
    np.testing.assert_allclose(out, np.full((2, 4), 3.0), rtol=1e-6)


def test_dist_model_wraps_loader(mesh):
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return (np.zeros(16, np.float32), np.int64(i % 4))

        def __len__(self):
            return 8

    net = _mlp()
    opt = optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
    loader = DataLoader(DS(), batch_size=8)
    model = DistModel(net, loader=loader, loss=nn.CrossEntropyLoss(),
                      optimizer=opt)
    assert model.dist_loader() is not None
    assert model.state_dict(mode="param")  # reference spelling accepted
    assert all(k.startswith("optimizer.")
               for k in model.state_dict(mode="opt"))


def test_dist_model_requires_loss_for_train(mesh):
    net = _mlp()
    model = DistModel(net)  # no loss/opt -> predict mode
    assert model._mode == "predict"
    with pytest.raises(ValueError):
        model.train()


def test_strategy_recompute_applies_to_model_config():
    """Strategy.recompute flips a zoo model's native knob (+ granularity)."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    paddle.seed(30)
    cfg = llama_tiny_config(num_hidden_layers=1, hidden_size=32,
                            num_attention_heads=2, num_key_value_heads=2,
                            vocab_size=64, max_position_embeddings=32)
    m = LlamaForCausalLM(cfg)
    from paddle_tpu.distributed import Strategy
    from paddle_tpu.distributed.engine import DistModel
    st = Strategy({"recompute": {"enable": True,
                                 "granularity": "selective"}})
    DistModel(m, loss=lambda out, lbl: out.sum(), optimizer=None,
              strategy=st)
    assert cfg.use_recompute is True
    assert cfg.recompute_granularity == "selective"


def test_strategy_recompute_wraps_generic_sublayers():
    """Generic models: direct sublayers become recompute regions and the
    loss/grads match the unwrapped model exactly."""
    import numpy as np
    from paddle_tpu import nn
    from paddle_tpu.distributed import Strategy
    from paddle_tpu.distributed.engine import DistModel

    def build():
        paddle.seed(31)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                             nn.Linear(16, 8))

    x = paddle.to_tensor(np.random.RandomState(31).randn(4, 8)
                         .astype("float32"))

    ref_net = build()
    ref = ref_net(x)
    ref.sum().backward()
    ref_grad = ref_net[0].weight.grad.numpy().copy()

    net = build()
    st = Strategy({"recompute": {"enable": True}})
    DistModel(net, loss=lambda out, lbl: out.sum(), optimizer=None,
              strategy=st)
    out = net(x)  # call 1 probes output types (direct mode)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(net[0].weight.grad.numpy(), ref_grad,
                               rtol=1e-6)
    # call 2+ runs through fleet.recompute: same numerics, grads replayed
    net[0].weight.clear_grad()
    out2 = net(x)
    np.testing.assert_allclose(out2.numpy(), ref.numpy(), rtol=1e-6)
    out2.sum().backward()
    np.testing.assert_allclose(net[0].weight.grad.numpy(), ref_grad,
                               rtol=1e-6)


def test_strategy_fused_passes_warns_not_silent():
    import warnings as w
    from paddle_tpu import nn
    from paddle_tpu.distributed import Strategy
    from paddle_tpu.distributed.engine import DistModel
    net = nn.Linear(4, 4)
    st = Strategy({"fused_passes": {"enable": True,
                                    "fused_passes_list": ["fuse_gemm"]}})
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        DistModel(net, loss=lambda o, l: o.sum(), strategy=st)
    assert any("absorbed by XLA" in str(r.message) for r in rec)
