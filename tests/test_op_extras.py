"""Long-tail op surface (ops/extras.py, ops/inplace.py, core/shims.py).

Reference test model: test/legacy_test per-op tests — each op checked
against the NumPy/SciPy reference on concrete values.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.RandomState(0)


def _t(a, dtype=None):
    return paddle.to_tensor(np.asarray(a, dtype=dtype or "float32"))


def _np(x):
    return np.asarray(x._data)


class TestSpecialFunctions:
    def test_gammaln_and_incomplete(self):
        from scipy import special
        x = np.abs(RNG.rand(16).astype("float32")) * 5 + 0.1
        np.testing.assert_allclose(_np(paddle.gammaln(_t(x))),
                                   special.gammaln(x), rtol=1e-4, atol=1e-5)
        y = np.abs(RNG.rand(16).astype("float32")) * 3 + 0.1
        np.testing.assert_allclose(_np(paddle.gammainc(_t(x), _t(y))),
                                   special.gammainc(x, y), rtol=1e-4)
        np.testing.assert_allclose(_np(paddle.gammaincc(_t(x), _t(y))),
                                   special.gammaincc(x, y), rtol=1e-4)

    def test_bessel(self):
        from scipy import special
        x = RNG.rand(8).astype("float32") * 3
        np.testing.assert_allclose(_np(paddle.i0(_t(x))), special.i0(x),
                                   rtol=1e-4)
        np.testing.assert_allclose(_np(paddle.i1e(_t(x))), special.i1e(x),
                                   rtol=1e-4)

    def test_multigammaln(self):
        from scipy import special
        x = np.array([3.0, 4.5], dtype="float32")
        np.testing.assert_allclose(_np(paddle.multigammaln(_t(x), 2)),
                                   special.multigammaln(x, 2), rtol=1e-4)

    def test_polygamma(self):
        from scipy import special
        x = np.array([1.5, 2.5], dtype="float32")
        np.testing.assert_allclose(_np(paddle.polygamma(_t(x), 1)),
                                   special.polygamma(1, x), rtol=1e-3)


class TestElementwise:
    def test_log_family(self):
        x = RNG.randn(32).astype("float32")
        y = RNG.randn(32).astype("float32")
        np.testing.assert_allclose(_np(paddle.logaddexp(_t(x), _t(y))),
                                   np.logaddexp(x, y), rtol=1e-5)
        lce = _np(paddle.logcumsumexp(_t(x)))
        ref = np.logaddexp.accumulate(x)
        np.testing.assert_allclose(lce, ref, rtol=1e-4)

    def test_sign_families(self):
        x = RNG.randn(16).astype("float32")
        y = RNG.randn(16).astype("float32")
        np.testing.assert_allclose(_np(paddle.copysign(_t(x), _t(y))),
                                   np.copysign(x, y))
        np.testing.assert_allclose(_np(paddle.heaviside(_t(x), _t(y))),
                                   np.heaviside(x, y))
        assert (_np(paddle.signbit(_t(x))) == np.signbit(x)).all()
        z = np.array([3 + 4j], dtype="complex64")
        np.testing.assert_allclose(_np(paddle.sgn(paddle.to_tensor(z))),
                                   z / np.abs(z), rtol=1e-6)

    def test_float_decomp(self):
        x = np.array([8.0, 0.5, -3.0], dtype="float32")
        m, e = paddle.frexp(_t(x))
        np.testing.assert_allclose(_np(m) * (2.0 ** _np(e)), x)
        np.testing.assert_allclose(
            _np(paddle.ldexp(_t(x), _t([1, 2, 3], "int32"))),
            np.ldexp(x, [1, 2, 3]))

    def test_integer_ops(self):
        a = _t([12, 18, 7], "int32")
        b = _t([8, 12, 21], "int32")
        np.testing.assert_array_equal(_np(paddle.gcd(a, b)), [4, 6, 7])
        np.testing.assert_array_equal(_np(paddle.lcm(a, b)), [24, 36, 21])
        np.testing.assert_array_equal(
            _np(paddle.bitwise_left_shift(_t([1, 2], "int32"),
                                          _t([2, 3], "int32"))), [4, 16])

    def test_angles(self):
        x = np.array([0.0, np.pi / 2, np.pi], dtype="float32")
        np.testing.assert_allclose(_np(paddle.rad2deg(_t(x))),
                                   [0, 90, 180], atol=1e-4)
        np.testing.assert_allclose(_np(paddle.deg2rad(_t([180.0]))),
                                   [np.pi], rtol=1e-6)

    def test_renorm(self):
        x = RNG.randn(4, 8).astype("float32") * 5
        out = _np(paddle.renorm(_t(x), p=2.0, axis=0, max_norm=1.0))
        norms = np.linalg.norm(out, axis=1)
        assert (norms <= 1.0 + 1e-4).all()


class TestConstructionsAndViews:
    def test_diag_embed(self):
        x = RNG.randn(2, 3).astype("float32")
        out = _np(paddle.diag_embed(_t(x)))
        assert out.shape == (2, 3, 3)
        np.testing.assert_allclose(out[0], np.diag(x[0]))

    def test_vander_polar_complex(self):
        x = np.array([1.0, 2.0, 3.0], dtype="float32")
        np.testing.assert_allclose(_np(paddle.vander(_t(x))), np.vander(x))
        r = _t([1.0, 2.0])
        th = _t([0.0, np.pi / 2])
        out = _np(paddle.polar(r, th))
        np.testing.assert_allclose(out, [1 + 0j, 2j], atol=1e-6)
        c = _np(paddle.complex(_t([1.0]), _t([2.0])))
        assert c.dtype == np.complex64 and c[0] == 1 + 2j

    def test_tri_indices_and_combinations(self):
        out = _np(paddle.tril_indices(3, 3, 0))
        ref = np.stack(np.tril_indices(3))
        np.testing.assert_array_equal(out, ref)
        x = _t([1.0, 2.0, 3.0])
        combs = _np(paddle.combinations(x, 2))
        np.testing.assert_allclose(combs, [[1, 2], [1, 3], [2, 3]])

    def test_stacks_and_splits(self):
        a = RNG.randn(2, 3).astype("float32")
        np.testing.assert_allclose(_np(paddle.hstack([_t(a), _t(a)])),
                                   np.hstack([a, a]))
        np.testing.assert_allclose(_np(paddle.vstack([_t(a), _t(a)])),
                                   np.vstack([a, a]))
        np.testing.assert_allclose(_np(paddle.column_stack([_t(a), _t(a)])),
                                   np.column_stack([a, a]))
        parts = paddle.tensor_split(_t(np.arange(10, dtype="float32")), 3)
        ref = np.array_split(np.arange(10), 3)
        for p, r in zip(parts, ref):
            np.testing.assert_allclose(_np(p), r)
        assert len(paddle.vsplit(_t(RNG.randn(4, 2)), 2)) == 2

    def test_atleast(self):
        assert paddle.atleast_1d(_t(3.0)).shape == [1]
        assert paddle.atleast_2d(_t([1.0, 2.0])).shape == [1, 2]
        assert paddle.atleast_3d(_t([[1.0]])).shape == [1, 1, 1]

    def test_slice_and_strided(self):
        x = np.arange(24, dtype="float32").reshape(4, 6)
        out = _np(paddle.slice(_t(x), [0, 1], [1, 2], [3, 5]))
        np.testing.assert_allclose(out, x[1:3, 2:5])
        out = _np(paddle.strided_slice(_t(x), [1], [0], [6], [2]))
        np.testing.assert_allclose(out, x[:, 0:6:2])
        out = _np(paddle.crop(_t(x), shape=[2, 3], offsets=[1, 1]))
        np.testing.assert_allclose(out, x[1:3, 1:4])

    def test_as_strided_and_unfold(self):
        x = np.arange(12, dtype="float32")
        out = _np(paddle.as_strided(_t(x), [3, 4], [4, 1]))
        np.testing.assert_allclose(out, x.reshape(3, 4))
        out = _np(paddle.unfold(_t(x), 0, 4, 2))
        assert out.shape == (5, 4)
        np.testing.assert_allclose(out[1], x[2:6])

    def test_reverse_add_n(self):
        x = RNG.randn(3, 2).astype("float32")
        np.testing.assert_allclose(_np(paddle.reverse(_t(x), 0)), x[::-1])
        np.testing.assert_allclose(
            _np(paddle.add_n([_t(x), _t(x), _t(x)])), 3 * x, rtol=1e-6)

    def test_diagonal_scatter_and_masked_scatter(self):
        x = np.zeros((3, 3), dtype="float32")
        y = np.array([1.0, 2.0, 3.0], dtype="float32")
        out = _np(paddle.diagonal_scatter(_t(x), _t(y)))
        np.testing.assert_allclose(out, np.diag(y))
        m = np.array([True, False, True], dtype=bool)
        out = _np(paddle.masked_scatter(_t([0.0, 0.0, 0.0]),
                                        paddle.to_tensor(m),
                                        _t([5.0, 6.0])))
        np.testing.assert_allclose(out, [5.0, 0.0, 6.0])


class TestSearchStats:
    def test_index_sample_multiplex(self):
        x = np.arange(12, dtype="float32").reshape(3, 4)
        idx = np.array([[0, 2], [1, 3], [0, 0]], dtype="int32")
        out = _np(paddle.index_sample(_t(x), paddle.to_tensor(idx)))
        np.testing.assert_allclose(out, np.take_along_axis(x, idx, 1))
        a = _t([[1.0, 1.0], [2.0, 2.0]])
        b = _t([[3.0, 3.0], [4.0, 4.0]])
        sel = paddle.to_tensor(np.array([[1], [0]], dtype="int32"))
        np.testing.assert_allclose(_np(paddle.multiplex([a, b], sel)),
                                   [[3, 3], [2, 2]])

    def test_nanmedian_pdist(self):
        x = np.array([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]],
                     dtype="float32")
        np.testing.assert_allclose(_np(paddle.nanmedian(_t(x))), 3.5)
        pts = RNG.randn(5, 3).astype("float32")
        from scipy.spatial.distance import pdist as sp_pdist
        np.testing.assert_allclose(_np(paddle.pdist(_t(pts))),
                                   sp_pdist(pts), rtol=1e-4)

    def test_unique_consecutive(self):
        x = _t([1, 1, 2, 2, 3, 1, 1], "int32")
        out, inv, counts = paddle.unique_consecutive(
            x, return_inverse=True, return_counts=True)
        np.testing.assert_array_equal(_np(out), [1, 2, 3, 1])
        np.testing.assert_array_equal(_np(counts), [2, 2, 1, 2])

    def test_histogramdd(self):
        pts = RNG.randn(100, 2).astype("float32")
        hist, edges = paddle.histogramdd(_t(pts), bins=4)
        ref_h, ref_e = np.histogramdd(pts, bins=4)
        np.testing.assert_allclose(_np(hist), ref_h)

    def test_cumulative_trapezoid(self):
        y = np.array([1.0, 2.0, 3.0], dtype="float32")
        out = _np(paddle.cumulative_trapezoid(_t(y), dx=1.0))
        np.testing.assert_allclose(out, [1.5, 4.0])


class TestInplaceVariants:
    def test_math_inplace(self):
        x = _t([1.0, 4.0, 9.0])
        ref_id = x
        out = paddle.sqrt_(x)
        assert out is ref_id
        np.testing.assert_allclose(_np(x), [1.0, 2.0, 3.0])
        paddle.add_(x, _t([1.0, 1.0, 1.0]))
        np.testing.assert_allclose(_np(x), [2.0, 3.0, 4.0])
        x.tanh_()
        np.testing.assert_allclose(_np(x), np.tanh([2.0, 3.0, 4.0]),
                                   rtol=1e-6)

    def test_shape_inplace(self):
        x = _t(np.arange(6, dtype="float32"))
        x.reshape_([2, 3])
        assert x.shape == [2, 3]
        x.transpose_([1, 0])
        assert x.shape == [3, 2]
        x.squeeze_(0) if x.shape[0] == 1 else None
        y = _t(np.arange(4, dtype="float32").reshape(2, 2))
        paddle.t_(y)
        assert y.shape == [2, 2]

    def test_inplace_on_grad_leaf_raises(self):
        x = _t([1.0, 2.0])
        x.stop_gradient = False
        with pytest.raises(RuntimeError):
            paddle.sqrt_(x)

    def test_random_fills(self):
        paddle.seed(0)
        x = _t(np.zeros(1000))
        paddle.normal_(x, mean=2.0, std=0.5)
        assert abs(float(_np(x).mean()) - 2.0) < 0.1
        g = _t(np.zeros(1000))
        paddle.geometric_(g, 0.5)
        assert (_np(g) >= 1).all()

    def test_floor_mod_alias(self):
        a = _t([7.0, -7.0])
        out = paddle.floor_mod(a, _t([3.0, 3.0]))
        np.testing.assert_allclose(_np(out), [1.0, 2.0])


class TestShims:
    def test_iinfo_finfo(self):
        ii = paddle.iinfo("int32")
        assert ii.max == 2**31 - 1 and ii.bits == 32
        fi = paddle.finfo(paddle.float32)
        assert fi.bits == 32 and fi.eps > 0

    def test_dtype_and_bool(self):
        import jax.numpy as jnp
        assert paddle.dtype("float32") == jnp.float32
        assert paddle.bool == paddle.bool_

    def test_is_predicates(self):
        assert paddle.is_tensor(_t([1.0]))
        assert not paddle.is_tensor([1.0])
        assert paddle.is_floating_point(_t([1.0]))
        assert paddle.is_integer(_t([1], "int32"))
        assert paddle.is_complex(paddle.complex(_t([1.0]), _t([0.0])))

    def test_shape_rank_t(self):
        x = _t(np.zeros((2, 5)))
        np.testing.assert_array_equal(_np(paddle.shape(x)), [2, 5])
        assert int(_np(paddle.rank(x))) == 2
        assert paddle.t(x).shape == [5, 2]

    def test_batch_reader(self):
        reader = paddle.batch(lambda: iter(range(7)), batch_size=3)
        batches = list(reader())
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]

    def test_rng_state_roundtrip(self):
        paddle.seed(42)
        st = paddle.get_rng_state()
        a = _np(paddle.rand([4]))
        paddle.set_rng_state(st)
        b = _np(paddle.rand([4]))
        np.testing.assert_allclose(a, b)

    def test_create_parameter(self):
        p = paddle.create_parameter([3, 4], dtype="float32")
        assert p.shape == [3, 4] and p.trainable
        b = paddle.create_parameter([4], is_bias=True)
        np.testing.assert_allclose(_np(b), 0.0)

    def test_broadcast_shape(self):
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]

    def test_lazy_guard_and_misc(self):
        with paddle.LazyGuard():
            import paddle_tpu.nn as nn
            lin = nn.Linear(2, 2)
        assert lin.weight.shape == [2, 2]
        paddle.disable_signal_handler()
        paddle.set_printoptions(precision=4)

    def test_random_tail(self):
        paddle.seed(0)
        out = paddle.binomial(_t([10] * 200, "int32"), _t([0.5] * 200))
        m = float(_np(out).mean())
        assert 4.0 < m < 6.0
        g = paddle.standard_gamma(_t([2.0] * 500))
        assert abs(float(_np(g).mean()) - 2.0) < 0.3


class TestReviewRegressions:
    """Cases from code review: non-default dims/axes and inplace targets."""

    def test_diag_embed_custom_dims(self):
        x = np.arange(6, dtype="float32").reshape(2, 3)
        out = _np(paddle.diag_embed(_t(x), dim1=0, dim2=1))
        assert out.shape == (3, 3, 2)
        for b in range(2):
            for i in range(3):
                assert out[i, i, b] == x[b, i]

    def test_unfold_2d_layout(self):
        x = np.arange(40, dtype="float32").reshape(4, 10)
        out = _np(paddle.unfold(_t(x), 0, 2, 2))
        assert out.shape == (2, 10, 2)          # size appended LAST
        np.testing.assert_allclose(out[0, :, 1], x[1])

    def test_renorm_negative_axis(self):
        x = RNG.randn(4, 8).astype("float32") * 5
        out = _np(paddle.renorm(_t(x), p=2.0, axis=-1, max_norm=1.0))
        assert (np.linalg.norm(out, axis=0) <= 1.0 + 1e-4).all()

    def test_where_inplace_targets_x(self):
        cond = paddle.to_tensor(np.array([True, False]))
        a = _t([1.0, 2.0])
        b = _t([9.0, 9.0])
        r = paddle.where_(cond, a, b)
        assert r is a
        np.testing.assert_allclose(_np(a), [1.0, 9.0])
        np.testing.assert_array_equal(_np(cond), [True, False])

    def test_tri_indices_dtype(self):
        out = paddle.tril_indices(3, dtype="int64")
        assert "int" in str(out.dtype)
        out32 = paddle.triu_indices(3, dtype="int32")
        assert str(out32.dtype) == "int32"
