"""Two-rank cross-process pipeline worker.

The reference's per-rank pipeline pattern
(fleet/meta_parallel/pipeline_parallel.py:440 + p2p_communication.py:313):
each RANK owns one stage; activations go forward over p2p, boundary
cotangents come back. Here the transport is the multi-process eager p2p
(2-endpoint mesh ppermute over Gloo/ICI) and the per-stage backward is the
tape with an explicit cotangent — the cross-process twin of the
single-controller plan executor in fleet/pipeline_parallel.py.
"""
import numpy as np


def main():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn, optimizer
    from paddle_tpu.autograd.engine import run_backward

    dist.init_parallel_env()
    rank = dist.get_rank()
    assert dist.get_world_size() == 2

    paddle.seed(100 + rank)  # each rank initializes only ITS stage
    rng = np.random.RandomState(0)
    x_np = rng.randn(8, 16).astype("float32")
    y_np = rng.randint(0, 4, (8,))

    steps = 4
    losses = []
    if rank == 0:
        stage = nn.Sequential(nn.Linear(16, 32), nn.ReLU())
        opt = optimizer.AdamW(learning_rate=5e-2,
                              parameters=stage.parameters())
        for _ in range(steps):
            h = stage(paddle.to_tensor(x_np))
            dist.send(h, dst=1)
            cot = paddle.to_tensor(np.zeros((8, 32), np.float32))
            dist.recv(cot, src=1)  # boundary cotangent comes back
            run_backward([h], [cot])
            opt.step()
            opt.clear_grad()
    else:
        head = nn.Linear(32, 4)
        lossf = nn.CrossEntropyLoss()
        opt = optimizer.AdamW(learning_rate=5e-2,
                              parameters=head.parameters())
        for _ in range(steps):
            h_in = paddle.to_tensor(np.zeros((8, 32), np.float32))
            dist.recv(h_in, src=0)
            h_in.stop_gradient = False
            loss = lossf(head(h_in), paddle.to_tensor(y_np))
            loss.backward()
            dist.send(h_in.grad, dst=0)
            losses.append(float(loss))
            opt.step()
            opt.clear_grad()
        assert losses[-1] < losses[0], losses
        print(f"MPPIPE_LOSSES {losses[0]:.4f}->{losses[-1]:.4f}", flush=True)
    print(f"MPPIPE_OK rank={rank}", flush=True)


if __name__ == "__main__":
    main()
