"""Kill-a-rank E2E worker (VERDICT r3 #8).

Launched by paddle_tpu.distributed.launch (2 ranks, CPU) with a marker
directory as argv[1]. First pod attempt: rank 1 stops participating
mid-training (writes the marker, then hangs — the canonical dead/stuck
peer, invisible to process-exit watching alone); rank 0 blocks in the
next all_reduce, its collective watchdog flags the frozen peer within
its timeout and ABORTS the process, which the launch controller's watch
loop sees as a pod failure and restarts. Second attempt (marker
present): every rank trains to completion.

Reference seam: comm_task_manager.cc's watchdog paired with
launch/controllers/collective.py:272's restart-on-failure watch loop.
"""
import os
import sys
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    marker_dir = sys.argv[1]
    os.makedirs(marker_dir, exist_ok=True)
    marker = os.path.join(marker_dir, "rank1_died_once")

    dist.init_parallel_env()
    rank = dist.get_rank()
    n = dist.get_world_size()
    assert jax.process_count() == n

    def abort_on_desync(report):
        kind = report.get("kind")
        print(f"MPKILL_WATCHDOG rank={rank} {report}", flush=True)
        # abort ONLY on a definitively dead/frozen peer: strictly behind
        # my seq, or missing from the store. A same-seq done=True peer
        # (classified 'behind' by the scanner) is just a transient
        # straggler window on a loaded box — aborting there would burn
        # the restart budget on a healthy world.
        frozen = [r for r, s in report.get("peers_behind", {}).items()
                  if s < report["seq"]] + report.get("peers_missing", [])
        if kind != "stuck" or not frozen:
            return
        # surface the hang as a process failure the launcher's watch
        # loop can act on (the rank itself is stuck inside the gloo
        # collective and can never raise from python)
        os._exit(3)

    wd = dist.enable_collective_watchdog(timeout=4.0, poll=0.5,
                                         on_desync=abort_on_desync)
    assert wd is not None

    for step in range(5):
        if rank == 1 and step == 3 and not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("1")
            print(f"MPKILL_DYING rank={rank} step={step}", flush=True)
            sys.stdout.flush()
            time.sleep(120)  # a hung rank, not a clean exit; the pod
            os._exit(9)      # teardown SIGTERMs this sleep
        t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
        dist.all_reduce(t)
        np.testing.assert_allclose(t.numpy(), np.full((4,), n * (n + 1) / 2))

    print(f"MPKILL_OK rank={rank}/{n}", flush=True)


if __name__ == "__main__":
    main()
