"""Copy unrolled Llama weights into a scanned model's stacked layout.

One helper for both parity suites (dense in test_models.py, MoE in
test_llama_moe.py) so the attention/ln/embed/head copying can't drift
between them when the stacked layout changes.
"""
import jax.numpy as jnp


def copy_unrolled_to_scanned(m_u, m_s):
    sc = m_s.model.layers_scanned

    def stack(getter):
        return jnp.stack([getter(l)._data for l in m_u.model.layers])

    sc.q_w._set_data(stack(lambda l: l.self_attn.q_proj.weight))
    sc.k_w._set_data(stack(lambda l: l.self_attn.k_proj.weight))
    sc.v_w._set_data(stack(lambda l: l.self_attn.v_proj.weight))
    sc.o_w._set_data(stack(lambda l: l.self_attn.o_proj.weight))
    if m_s.config.num_experts > 1:
        sc.router_w._set_data(stack(lambda l: l.mlp.moe.gate.gate.weight))
        sc.router_b._set_data(stack(lambda l: l.mlp.moe.gate.gate.bias))
        sc.moe_gate_w._set_data(stack(lambda l: l.mlp.moe.gate_w))
        sc.moe_up_w._set_data(stack(lambda l: l.mlp.moe.up_w))
        sc.moe_down_w._set_data(stack(lambda l: l.mlp.moe.down_w))
    else:
        sc.gate_w._set_data(stack(lambda l: l.mlp.gate_proj.weight))
        sc.up_w._set_data(stack(lambda l: l.mlp.up_proj.weight))
        sc.down_w._set_data(stack(lambda l: l.mlp.down_proj.weight))
    sc.ln1_w._set_data(stack(lambda l: l.input_layernorm.weight))
    sc.ln2_w._set_data(stack(lambda l: l.post_attention_layernorm.weight))
    m_s.model.embed_tokens.weight._set_data(
        m_u.model.embed_tokens.weight._data)
    m_s.model.norm.weight._set_data(m_u.model.norm.weight._data)
    if m_s.lm_head is not None:
        m_s.lm_head.weight._set_data(m_u.lm_head.weight._data)
