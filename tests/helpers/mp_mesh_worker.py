"""Per-rank worker for the multi-process SPMD mesh train test.

Launched by paddle_tpu.distributed.launch (2 processes x 2 CPU devices
each). Every rank forms the world, runs 5 fused train steps UNSHARDED
on its own local device (the bitwise reference), then re-initializes
the same model and runs the same 5 steps through
``MeshRuntime.from_env()`` — a 2x2 ``(fsdp, tensor)`` gloo mesh
spanning all 4 devices, with the fsdp (ZeRO-3 gather) axis crossing
the process boundary. The losses must match the local reference
EXACTLY (same accumulation order is the mesh layer's ``zero3_gather``
contract), proving the multi-process mesh changes placement, not math.
"""
import os

import numpy as np

STEPS = 5


def _make_model(seed):
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(seed)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


def _run(model, plan):
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as optim
    from paddle_tpu import jit as jit_mod

    opt = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def fn(ids, labels):
        out = model(ids)
        logits = out[0] if isinstance(out, (tuple, list)) else out
        return paddle.nn.functional.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))

    step = jit_mod.TrainStep(fn, opt, mesh_plan=plan)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, size=(2, 16))
    labels = rng.randint(0, 128, size=(2, 16))
    losses = []
    for _ in range(STEPS):
        loss = step(paddle.to_tensor(ids), paddle.to_tensor(labels))
        arr = loss._data if hasattr(loss, "_data") else loss
        # replicated scalar: every process holds the full value
        losses.append(float(np.asarray(arr.addressable_data(0)
                                       if hasattr(arr, "addressable_data")
                                       else arr)))
    return losses


def main():
    import jax

    from paddle_tpu.distributed.mesh import MeshRuntime

    import paddle_tpu.distributed as dist

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])

    # the world must form before ANY jax computation (jax.distributed
    # contract) — then the reference runs unsharded on this rank's own
    # local device only
    dist.init_parallel_env()
    base = _run(_make_model(7), None)

    rt = MeshRuntime.from_env()   # reuses the world, spans all 4 devices
    assert jax.process_count() == world, jax.process_count()
    assert rt.multiprocess and rt.size == 4, (rt.axes, rt.size)
    assert rt.axes == {"data": 1, "fsdp": 2, "tensor": 2}, rt.axes

    plan = rt.train_plan(budget_gib=16.0)
    sharded = _run(_make_model(7), plan)

    diff = max(abs(a - b) for a, b in zip(base, sharded))
    assert diff == 0.0, (
        f"rank {rank}: sharded losses drifted from the local reference "
        f"(max |diff|={diff});\nbase={base}\nsharded={sharded}")
    comm = plan.collective_bytes_by_axis()
    assert comm.get("fsdp", 0) > 0 and comm.get("tensor", 0) > 0, comm

    print(f"MPMESH_OK rank={rank}/{world} losses={sharded}", flush=True)


if __name__ == "__main__":
    main()
