"""Per-rank worker for the multi-process E2E collective test.

Launched by paddle_tpu.distributed.launch (2 ranks, CPU). Forms a real
jax.distributed world through init_parallel_env, then exercises every eager
collective across processes, the TCPStore control plane, and a sharded
checkpoint save->load. Reference model for the test shape:
test/collective/test_communication_api_base.py:59-74 (spawn ranks, assert
per-rank results).
"""
import os
import sys
import tempfile

import numpy as np


def main():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    rank_env = int(os.environ["PADDLE_TRAINER_ID"])
    world_env = int(os.environ["PADDLE_TRAINERS_NUM"])
    ckpt_dir = sys.argv[1]

    dist.init_parallel_env()
    assert jax.process_count() == world_env, (
        f"world not formed: process_count={jax.process_count()}")
    rank = dist.get_rank()
    n = dist.get_world_size()
    assert rank == rank_env and n == world_env, (rank, n)

    # --- the launcher env auto-armed the watchdog at init_parallel_env;
    # re-arming must swap it cleanly (disable-then-enable), and every
    # collective below publishes progress with no desync report
    from paddle_tpu.distributed.watchdog import get_watchdog
    assert get_watchdog() is not None, "env auto-arm did not fire"
    wd = dist.enable_collective_watchdog(timeout=60.0)
    assert wd is not None and get_watchdog() is wd

    # --- all_reduce: each rank contributes rank+1 -> sum = n(n+1)/2
    t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t)
    np.testing.assert_allclose(t.numpy(), np.full((4,), n * (n + 1) / 2))
    assert wd.seq >= 1, "watchdog did not observe the collective"
    assert wd.check_once() is None, "healthy run flagged a desync"

    # --- all_gather: slice i came from rank i
    gathered = []
    dist.all_gather(gathered,
                    paddle.to_tensor(np.full((2,), float(rank), np.float32)))
    assert len(gathered) == n
    for i, s in enumerate(gathered):
        np.testing.assert_allclose(s.numpy(), np.full((2,), float(i)))

    # --- broadcast from rank 1
    b = paddle.to_tensor(np.full((3,), float(rank * 10 + 5), np.float32))
    dist.broadcast(b, src=1)
    np.testing.assert_allclose(b.numpy(), np.full((3,), 15.0))

    # --- reduce to rank 1 (others keep their input)
    r = paddle.to_tensor(np.full((2,), float(rank + 1), np.float32))
    dist.reduce(r, dst=1)
    expect = n * (n + 1) / 2 if rank == 1 else float(rank + 1)
    np.testing.assert_allclose(r.numpy(), np.full((2,), expect))

    # --- reduce_scatter: input [n*2] (chunk c = mine), output my summed chunk
    chunks = np.arange(n * 2, dtype=np.float32) + 100 * rank
    rs = paddle.to_tensor(np.zeros((2,), np.float32))
    dist.reduce_scatter(rs, paddle.to_tensor(chunks))
    base = np.arange(n * 2, dtype=np.float32).reshape(n, 2)[rank]
    expect_rs = base * n + 100 * sum(range(n))
    np.testing.assert_allclose(rs.numpy(), expect_rs)

    # --- alltoall: out[i] = rank i's chunk addressed to me
    in_list = [paddle.to_tensor(np.full((2,), float(rank * 10 + j),
                                        np.float32)) for j in range(n)]
    out_list = []
    dist.alltoall(in_list, out_list)
    assert len(out_list) == n
    for i, o in enumerate(out_list):
        np.testing.assert_allclose(o.numpy(), np.full((2,), i * 10 + rank))

    # --- alltoall_single
    src = np.arange(n * 3, dtype=np.float32) + 1000 * rank
    out_single = dist.alltoall_single(paddle.to_tensor(src))
    expect_rows = np.stack([
        (np.arange(n * 3, dtype=np.float32) + 1000 * i).reshape(n, 3)[rank]
        for i in range(n)])
    np.testing.assert_allclose(out_single.numpy(),
                               expect_rows.reshape(-1))

    # --- ragged alltoall_single (per-rank split sizes differ)
    if n == 2:
        if rank == 0:
            send = np.arange(4, dtype=np.float32) * 10      # [r0:1, r1:3]
            in_sp, out_sp = [1, 3], [1, 2]
            expect_rag = np.array([0.0, 100.0, 101.0], np.float32)
        else:
            send = np.arange(3, dtype=np.float32) + 100     # [r0:2, r1:1]
            in_sp, out_sp = [2, 1], [3, 1]
            expect_rag = np.array([10.0, 20.0, 30.0, 102.0], np.float32)
        got = dist.alltoall_single(paddle.to_tensor(send),
                                   in_split_sizes=in_sp,
                                   out_split_sizes=out_sp)
        np.testing.assert_allclose(got.numpy(), expect_rag)

    # --- scatter from rank 0
    sc_out = paddle.to_tensor(np.zeros((2,), np.float32))
    if rank == 0:
        sc_list = [paddle.to_tensor(np.full((2,), float(7 + i), np.float32))
                   for i in range(n)]
        dist.scatter(sc_out, sc_list, src=0)
    else:
        dist.scatter(sc_out, src=0)
    np.testing.assert_allclose(sc_out.numpy(), np.full((2,), 7.0 + rank))

    # --- p2p: rank 0 -> rank 1 (both endpoints run the ppermute program)
    payload = np.arange(6, dtype=np.float32).reshape(2, 3)
    if rank == 0:
        dist.send(paddle.to_tensor(payload), dst=1)
    elif rank == 1:
        box = paddle.to_tensor(np.zeros((2, 3), np.float32))
        dist.recv(box, src=0)
        np.testing.assert_allclose(box.numpy(), payload)

    # --- device barrier + TCPStore control-plane barrier
    dist.barrier()
    store = dist.get_bootstrap_store()
    assert store is not None, "TCPStore bootstrap missing"
    store.barrier("e2e_test", world_size=n)

    # --- object collectives over the store
    objs = []
    dist.all_gather_object(objs, {"rank": rank, "tag": "mp"})
    assert [o["rank"] for o in objs] == list(range(n)), objs
    blist = [{"from": rank}] if True else []
    dist.broadcast_object_list(blist, src=0)
    assert blist == [{"from": 0}], blist

    # --- sharded checkpoint: save a dp-sharded global array, reload, compare
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    mesh = jax.sharding.Mesh(np.array(jax.devices(), object), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    full = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    garr = jax.make_array_from_process_local_data(
        sharding, full[rank:rank + 1], (n, 4))
    sd = {"w": paddle.Tensor(garr)}
    save_state_dict(sd, ckpt_dir)
    store.barrier("ckpt_saved", world_size=n)

    target = jax.make_array_from_process_local_data(
        sharding, np.zeros((1, 4), np.float32), (n, 4))
    sd2 = {"w": paddle.Tensor(target)}
    load_state_dict(sd2, ckpt_dir)
    got = np.asarray(sd2["w"]._data.addressable_data(0))
    np.testing.assert_allclose(got, full[rank:rank + 1])

    print(f"MPWORKER_OK rank={rank}/{n}", flush=True)


if __name__ == "__main__":
    main()
