"""Per-rank worker for the elastic sharded-checkpoint drill.

Two phases, selected by ``MP_CKPT_PHASE``:

``save`` — launched by paddle_tpu.distributed.launch as 2 processes x 2
CPU devices forming a 2x2 ``(fsdp, tensor)`` gloo mesh. Every rank
trains 3 fused hapi steps, publishes a two-phase sharded checkpoint
(per-rank shards + acks, rank 0's manifest + COMMITTED), trains one
more step (the reference loss the restore must reproduce), then arms a
``checkpoint.shard_write:kill_rank:rank=1`` scenario and saves again:
rank 1 dies mid-shard-write, rank 0's ack wait times out, and the step
must be left TORN (no COMMITTED) rather than half-published.

``restore`` — a plain SINGLE process (no launcher, one device). The
restart restores the newest committed step from the mesh-spanning
checkpoint — elastically, onto a world a quarter the size — and the
continuation loss must be bitwise-identical to the loss the 2x2 world
computed before the kill.
"""
import os

import numpy as np

ROOT = os.environ.get("MP_CKPT_ROOT", "/tmp/mp_ckpt_root")


def _build(plan):
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.hapi import Model
    paddle.seed(7)
    m = Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2)))
    m.prepare(optimizer=optim.AdamW(learning_rate=1e-2,
                                    parameters=m.parameters()),
              loss=nn.CrossEntropyLoss(), jit=True, plan=plan)
    return m


def _batches():
    rng = np.random.RandomState(0)
    return (rng.randn(4, 8).astype(np.float32),
            rng.randint(0, 2, (4,)).astype(np.int64))


def _steps(m, n):
    x, y = _batches()
    return [float(np.asarray(m.train_batch([x], [y])[0]))
            for _ in range(n)]


def phase_save():
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.mesh import MeshRuntime
    from paddle_tpu.resilience import (AckTimeout, ShardedCheckpointManager,
                                       arm_scenario, disarm)

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    dist.init_parallel_env()
    rt = MeshRuntime.from_env()
    assert rt.multiprocess and rt.axes == {"data": 1, "fsdp": 2,
                                           "tensor": 2}, rt.axes

    m = _build(rt.train_plan(budget_gib=16.0))
    losses = _steps(m, 3)
    mgr = ShardedCheckpointManager(ROOT, runtime=rt, ack_timeout=10.0)
    m.save_checkpoint(mgr, step=3)
    losses += _steps(m, 1)  # the loss the elastic restart must reproduce
    print(f"MPCKPT_SAVE_OK rank={rank}/{world} losses={losses}",
          flush=True)

    # chaos: rank 1 dies on its first step-4 shard write; rank 0 must
    # time out on the missing ack and leave the step torn, not publish.
    # exit_code=0 because the launcher SIGTERMs every peer within ~1s of
    # a nonzero exit — rank 0 needs to survive its own ack timeout
    arm_scenario("seed=0; checkpoint.shard_write:kill_rank:rank=1,"
                 "count=1,exit_code=0")
    try:
        m.save_checkpoint(mgr, step=4)
        raise AssertionError(
            f"rank {rank}: the half-dead save published step 4")
    except AckTimeout as exc:
        print(f"MPCKPT_TORN rank={rank} step=4 ({exc})", flush=True)
    finally:
        disarm()


def phase_restore():
    from paddle_tpu.resilience import ShardedCheckpointManager

    m = _build(None)  # one process, one device: a quarter of the world
    mgr = ShardedCheckpointManager(ROOT)
    step = m.resume_from(mgr)
    assert step == 3, f"restore fell back to {step}, want 3"
    kinds = [f.kind for f in mgr.findings]
    assert "torn_step" in kinds, \
        f"torn step 4 produced no typed finding (got {kinds})"
    losses = _steps(m, 1)
    print(f"MPCKPT_RESTORE_OK step={step} findings={kinds} "
          f"losses={losses}", flush=True)


if __name__ == "__main__":
    if os.environ.get("MP_CKPT_PHASE") == "restore":
        phase_restore()
    else:
        phase_save()
        # rank 1 is dead by design, so the jax.distributed shutdown
        # barrier at interpreter exit can never complete — the
        # coordination client would abort the process (exit 250) while
        # waiting for it. The drill is over; leave without the barrier.
        os._exit(0)
