"""Fleet-telemetry kill drill worker (3 ranks, CPU).

Launched by paddle_tpu.distributed.launch with the telemetry dir as
argv[1]. Every rank spools metrics/spans/collective enter-exit to its
rank shard and journals to its flight ring. The script then makes the
fleet misbehave on purpose:

  * step 2: rank 1 sleeps before entering the all_reduce — an arrival
    skew the aggregator must flag as a ``straggler``;
  * step 4: chaos ``kill_rank`` takes rank 2 down with ``os._exit`` ON
    ENTRY to the collective (enter spooled, no exit; the chaos event is
    the last thing in its ring) — the ``missing_rank`` signature;
  * ranks 0/1 hit the dead collective: gloo surfaces the dead peer as
    an immediate transport error, which they catch, journal as a
    ``peer_failure``, then keep their shard warm past the
    missing-rank silence threshold before exiting 0 so the launcher
    stays green and the shards stay parseable. The collective
    watchdog rides along as a backstop in case the transport error
    never surfaces (a genuinely hung peer instead of a dead one).

The parent test aggregates the shards and replays the rings.
"""
import os
import sys
import time

import numpy as np


def main():
    os.environ["PADDLE_TELEMETRY_DIR"] = sys.argv[1]

    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.observability.fleet import (spool_event,
                                                spool_metrics)
    from paddle_tpu.observability.flight import flight_record
    from paddle_tpu.observability.trace_context import new_trace
    from paddle_tpu.resilience import arm_scenario

    dist.init_parallel_env()
    rank = dist.get_rank()
    n = dist.get_world_size()
    assert jax.process_count() == n
    print(f"MPFLEET_START rank={rank}/{n}", flush=True)

    def abort_on_desync(report):
        frozen = [r for r, s in report.get("peers_behind", {}).items()
                  if s < report["seq"]] + report.get("peers_missing", [])
        if report.get("kind") != "stuck" or not frozen:
            return
        print(f"MPFLEET_WATCHDOG rank={rank} frozen={frozen}",
              flush=True)
        spool_event("watchdog_abort", frozen=list(frozen),
                    seq=report["seq"])
        flight_record("watchdog_abort", frozen=list(frozen))
        os._exit(0)  # survivors exit clean; shards stay parseable

    wd = dist.enable_collective_watchdog(timeout=4.0, poll=0.5,
                                         on_desync=abort_on_desync)
    assert wd is not None

    # 5th collective.enter hit (= step 4 below) kills rank 2 on entry
    arm_scenario("collective.enter:kill_rank:rank=2,after=4,exit_code=0")

    # spans + snapshots are written per step so every rank's shard holds
    # them BEFORE the kill; nothing after the loop runs in this drill
    ctx = new_trace("fleet_drill", rank=rank)
    for step in range(8):
        if rank == 1 and step == 2:
            time.sleep(0.6)  # straggle into this collective
        sp = ctx.begin("step", step=step)
        t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
        try:
            dist.all_reduce(t)
        except Exception as e:
            # gloo reports the killed peer as a transport error; an
            # uncaught raise would drag the survivors through the JAX
            # coordination-service fatal (nonzero exit, ~60s heartbeat
            # wait). Catch it, journal it, and hold the shard open past
            # the silence threshold so the dead rank's gap is
            # measurable against live survivors.
            sp.end()
            print(f"MPFLEET_PEERDOWN rank={rank} step={step}",
                  flush=True)
            spool_event("peer_failure", step=step,
                        error=type(e).__name__)
            flight_record("peer_failure", step=step)
            time.sleep(2.4)
            spool_metrics()
            spool_event("survivor_exit", step=step)
            os._exit(0)  # skip atexit: no distributed shutdown barrier
        sp.end()
        np.testing.assert_allclose(
            t.numpy(), np.full((4,), n * (n + 1) / 2))
        spool_metrics()
        if step == 3 and rank == 2:
            print("MPFLEET_VICTIM_ALIVE rank=2 step=3", flush=True)
    # unreachable when the drill works: the kill fires at step 4 and the
    # survivors watchdog-abort. The parent test asserts this marker is
    # ABSENT to prove the fault actually fired.
    ctx.finish(steps=8)
    print(f"MPFLEET_OK rank={rank}/{n}", flush=True)


if __name__ == "__main__":
    main()
