"""ASGD / Rprop / LBFGS and incubate optimizer tier.

Reference test model: test/legacy_test/test_asgd_op.py, test_rprop_op.py,
test_lbfgs.py (closure API), test/legacy_test/test_bfgs.py (functional
minimizers on quadratics/Rosenbrock), test_lars_momentum_op.py,
test_distributed_fused_lamb_op* (single-rank path here).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core.tensor import Parameter
from paddle_tpu.incubate.optimizer import (DistributedFusedLamb,
                                           GradientMergeOptimizer,
                                           LarsMomentumOptimizer,
                                           minimize_bfgs, minimize_lbfgs)


def _param(a):
    return Parameter(np.asarray(a, dtype="float32"))


class TestNewOptimizers:
    @pytest.mark.parametrize("cls,kw", [
        (optimizer.ASGD, {"batch_num": 2}),
        (optimizer.Rprop, {}),
    ])
    def test_converges_on_quadratic(self, cls, kw):
        w = _param([3.0, -2.0])
        opt = cls(learning_rate=0.05, parameters=[w], **kw)
        for _ in range(200):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float((w * w).sum()._data) < 1e-2

    def test_asgd_averages_last_n_grads(self):
        # with batch_num=2 the step direction is the mean of the last 2 grads
        w = _param([0.0])
        opt = optimizer.ASGD(learning_rate=1.0, batch_num=2, parameters=[w])
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        for g in (2.0, 4.0):
            w.grad = Tensor(jnp.asarray([g], jnp.float32))
            opt.step()
        # step1: d=2, count=1 -> w=-2; step2: d=2+4, count=2 -> w=-2-3=-5
        np.testing.assert_allclose(np.asarray(w._data), [-5.0], atol=1e-6)

    def test_rprop_grows_and_shrinks_step(self):
        w = _param([1.0])
        opt = optimizer.Rprop(learning_rate=0.1, parameters=[w],
                              etas=(0.5, 1.2),
                              learning_rate_range=(1e-5, 1.0))
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        w.grad = Tensor(jnp.asarray([1.0], jnp.float32))
        opt.step()   # first step: lr stays 0.1 (prev grad 0 -> sign 0)
        p1 = float(w._data[0])
        w.grad = Tensor(jnp.asarray([1.0], jnp.float32))
        opt.step()   # same sign: lr *= 1.2
        p2 = float(w._data[0])
        assert abs((p1 - p2)) > abs(1.0 - p1)

    def test_lbfgs_rosenbrock(self):
        w = _param([-1.0, 1.5])
        opt = optimizer.LBFGS(parameters=[w], line_search_fn="strong_wolfe",
                              max_iter=40)

        def closure():
            loss = (1 - w[0]) ** 2 + 10 * (w[1] - w[0] ** 2) ** 2
            loss.backward()
            return loss

        f = opt.step(closure)
        assert f < 1e-6
        np.testing.assert_allclose(np.asarray(w._data), [1.0, 1.0], atol=1e-3)

    def test_lbfgs_requires_closure(self):
        w = _param([1.0])
        opt = optimizer.LBFGS(parameters=[w])
        with pytest.raises(ValueError):
            opt.step()


class TestFunctionalMinimizers:
    def _target(self):
        return paddle.to_tensor(np.array([1.0, -2.0, 3.0], dtype="float32"))

    def test_minimize_bfgs(self):
        t = self._target()

        def obj(x):
            return ((x - t) ** 2).sum()

        conv, calls, pos, val, grad, hess = minimize_bfgs(
            obj, paddle.to_tensor(np.zeros(3, dtype="float32")))
        assert bool(np.asarray(conv._data))
        np.testing.assert_allclose(np.asarray(pos._data),
                                   np.asarray(t._data), atol=1e-4)
        assert list(hess.shape) == [3, 3]

    def test_minimize_lbfgs(self):
        t = self._target()

        def obj(x):
            return ((x - t) ** 2).sum()

        conv, calls, pos, val, grad = minimize_lbfgs(
            obj, paddle.to_tensor(np.zeros(3, dtype="float32")))
        assert bool(np.asarray(conv._data))
        np.testing.assert_allclose(np.asarray(pos._data),
                                   np.asarray(t._data), atol=1e-4)

    def test_minimize_bfgs_rejects_bad_hessian(self):
        def obj(x):
            return (x ** 2).sum()

        bad = paddle.to_tensor(
            np.array([[1.0, 2.0], [0.0, 1.0]], dtype="float32"))
        with pytest.raises(ValueError):
            minimize_bfgs(obj, paddle.to_tensor(np.zeros(2, dtype="float32")),
                          initial_inverse_hessian_estimate=bad)


class TestIncubateOptimizers:
    def _train(self, opt_factory, steps=5):
        paddle.seed(7)
        net = nn.Linear(6, 4)
        opt = opt_factory(net)
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(steps):
            x = paddle.to_tensor(rng.randn(16, 6).astype("float32"))
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss._data))
        return losses

    def test_lars_momentum_trains(self):
        paddle.seed(7)
        net = nn.Linear(6, 4)
        opt = LarsMomentumOptimizer(learning_rate=0.5, lars_coeff=0.1,
                                    parameters=net.parameters())
        x = paddle.to_tensor(np.ones((16, 6), dtype="float32"))
        losses = []
        for _ in range(10):
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss._data))
        assert losses[-1] < losses[0]

    def test_distributed_fused_lamb_trains(self):
        losses = self._train(lambda net: DistributedFusedLamb(
            learning_rate=0.05, parameters=net.parameters()))
        assert losses[-1] < losses[0]

    def test_distributed_fused_lamb_grad_accumulation(self):
        # two DISTINCT micro-batches without user clear_grad must equal one
        # big batch: catches double-counting of the first micro-batch
        rng = np.random.RandomState(3)
        xa = rng.randn(4, 4).astype("float32")
        xb = rng.randn(4, 4).astype("float32")

        def fresh():
            paddle.seed(7)
            return nn.Linear(4, 4)

        net1 = fresh()
        opt1 = DistributedFusedLamb(learning_rate=0.05,
                                    parameters=net1.parameters(),
                                    gradient_accumulation_steps=2)
        w0 = np.asarray(net1.weight._data).copy()
        for x in (xa, xb):
            loss = (net1(paddle.to_tensor(x)) ** 2).mean()
            loss.backward()
            opt1.step()   # no clear_grad between micro-steps
        assert not np.allclose(np.asarray(net1.weight._data), w0)

        net2 = fresh()
        opt2 = DistributedFusedLamb(learning_rate=0.05,
                                    parameters=net2.parameters(),
                                    gradient_accumulation_steps=2)
        for x in (xa, xb):
            loss = (net2(paddle.to_tensor(x)) ** 2).mean()
            loss.backward()
            opt2.step()
            opt2.clear_grad()   # the "clean" usage
        np.testing.assert_allclose(np.asarray(net1.weight._data),
                                   np.asarray(net2.weight._data), atol=1e-6)

    def test_gradient_merge(self):
        paddle.seed(7)
        net = nn.Linear(4, 4)
        inner = optimizer.SGD(learning_rate=0.1,
                              parameters=net.parameters())
        opt = GradientMergeOptimizer(inner, k_steps=2, avg=True)
        w0 = np.asarray(net.weight._data).copy()
        x = paddle.to_tensor(np.ones((4, 4), dtype="float32"))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        np.testing.assert_allclose(np.asarray(net.weight._data), w0)
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        assert not np.allclose(np.asarray(net.weight._data), w0)
