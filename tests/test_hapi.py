"""Tests for the hapi Model API (hapi/model.py:1054 analog), metrics
(paddle.metric), callbacks, and paddle.summary."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import Model, nn, optimizer
from paddle_tpu.hapi.callbacks import EarlyStopping, ModelCheckpoint
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall


class XorDS(Dataset):
    """Tiny separable problem: label = x0 > x1."""

    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = (self.x[:, 0] > self.x[:, 1]).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp():
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))


def _model():
    m = Model(_mlp())
    m.prepare(optimizer=optimizer.Adam(learning_rate=0.05,
                                       parameters=m.parameters()),
              loss=nn.CrossEntropyLoss(),
              metrics=Accuracy())
    return m


# -- metrics -----------------------------------------------------------------

def test_accuracy_metric():
    m = Accuracy()
    pred = np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4], [0.3, 0.7]])
    label = np.array([0, 1, 1, 1])
    correct = m.compute(pred, label)
    m.update(correct)
    assert m.accumulate() == pytest.approx(0.75)
    m.reset()
    assert m.accumulate() == 0.0


def test_accuracy_topk():
    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.5, 0.4], [0.1, 0.2, 0.7]])
    label = np.array([2, 1])
    m.update(m.compute(pred, label))
    top1, top2 = m.accumulate()
    assert top1 == pytest.approx(0.0)
    assert top2 == pytest.approx(1.0)
    assert m.name() == ["acc_top1", "acc_top2"]


def test_precision_recall():
    p, r = Precision(), Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.7])   # rint -> 1,1,0,1
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == pytest.approx(2 / 3)   # tp=2 fp=1
    assert r.accumulate() == pytest.approx(2 / 3)   # tp=2 fn=1


def test_auc_perfect_and_random():
    auc = Auc()
    scores = np.array([[0.1, 0.9]] * 50 + [[0.9, 0.1]] * 50)
    labels = np.array([1] * 50 + [0] * 50)
    auc.update(scores, labels)
    assert auc.accumulate() == pytest.approx(1.0, abs=1e-3)
    auc.reset()
    auc.update(np.array([[0.5, 0.5]] * 10), np.array([0, 1] * 5))
    assert auc.accumulate() == pytest.approx(0.5, abs=1e-6)


# -- Model -------------------------------------------------------------------

def test_model_fit_reduces_loss_and_reports_acc(capsys):
    m = _model()
    ds = XorDS(64)
    m.fit(ds, batch_size=16, epochs=8, verbose=0)
    res = m.evaluate(ds, batch_size=16, verbose=0)
    assert res["acc"] > 0.9
    loss_val = res["loss"][0] if isinstance(res["loss"], list) else res["loss"]
    assert loss_val is not None and np.isfinite(loss_val)


def test_model_fit_with_dataloader_and_eval_data():
    m = _model()
    train = DataLoader(XorDS(48, seed=1), batch_size=12)
    val = DataLoader(XorDS(24, seed=2), batch_size=12)
    m.fit(train, val, epochs=3, verbose=0)
    out = m.evaluate(val, verbose=0)
    assert "acc" in out and "loss" in out


def test_model_train_eval_predict_batch():
    m = _model()
    x = np.random.randn(4, 8).astype(np.float32)
    y = np.array([0, 1, 0, 1])
    loss1, _ = m.train_batch([x], [y])
    loss2, _ = m.eval_batch([x], [y])
    assert np.isfinite(loss1[0]) and np.isfinite(loss2[0])
    preds = m.predict_batch([x])
    assert preds[0].shape == (4, 2)


def test_model_predict_stacked():
    m = _model()
    ds = XorDS(20, seed=3)
    outs = m.predict(ds, batch_size=8, stack_outputs=True, verbose=0)
    assert len(outs) == 1 and outs[0].shape == (20, 2)


def test_model_save_load(tmp_path):
    m = _model()
    ds = XorDS(32)
    m.fit(ds, batch_size=16, epochs=2, verbose=0)
    path = str(tmp_path / "ckpt" / "model")
    m.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")

    m2 = _model()
    m2.load(path)
    x = np.random.randn(4, 8).astype(np.float32)
    np.testing.assert_allclose(m.predict_batch([x])[0],
                               m2.predict_batch([x])[0], rtol=1e-6)


def test_model_checkpoint_callback(tmp_path):
    m = _model()
    save_dir = str(tmp_path / "cbk")
    m.fit(XorDS(16), batch_size=8, epochs=2, verbose=0,
          callbacks=[ModelCheckpoint(save_freq=1, save_dir=save_dir)])
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))


def test_early_stopping_stops():
    m = _model()
    es = EarlyStopping(monitor="acc", mode="max", patience=0, verbose=0)
    # with patience=0 and a metric that stops improving, training halts early
    m.fit(XorDS(64), eval_data=XorDS(16, seed=9), batch_size=16, epochs=50,
          eval_freq=1, verbose=0, callbacks=[es])
    assert m.stop_training


def test_num_iters_limits_training():
    m = _model()
    seen = []

    class Counter(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            seen.append(step)

    m.fit(XorDS(64), batch_size=8, epochs=10, num_iters=3, verbose=0,
          callbacks=[Counter()])
    assert len(seen) == 3


def test_summary_counts_params(capsys):
    net = _mlp()
    info = paddle.summary(net, (4, 8))
    captured = capsys.readouterr().out
    expected = 8 * 32 + 32 + 32 * 2 + 2
    assert info["total_params"] == expected
    assert "Linear" in captured and f"{expected:,}" in captured


def test_summary_via_model():
    m = _model()
    info = m.summary(input_size=(2, 8))
    assert info["trainable_params"] == info["total_params"]


def test_accuracy_column_labels():
    # [N, 1] integer labels (canonical shape) must not be argmaxed away
    m = Accuracy()
    pred = np.array([[0.1, 0.9], [0.8, 0.2]])
    label = np.array([[1], [0]])
    m.update(m.compute(pred, label))
    assert m.accumulate() == pytest.approx(1.0)


def test_lr_scheduler_callback_steps_fit():
    from paddle_tpu.optimizer.lr import StepDecay
    sched = StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
    net = _mlp()
    m = Model(net)
    m.prepare(optimizer=optimizer.Adam(learning_rate=sched,
                                       parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    m.fit(XorDS(16), batch_size=8, epochs=3, verbose=0)
    # stepped once per epoch: 0.1 -> 0.05 -> 0.025 -> 0.0125
    assert m._optimizer.get_lr() == pytest.approx(0.1 * 0.5 ** 3)


def test_predict_unlabeled_dataset():
    class TestDS(Dataset):
        def __getitem__(self, i):
            return np.zeros(8, dtype=np.float32)  # inputs only, no label

        def __len__(self):
            return 6

    m = _model()  # loss prepared, but predict data has no labels
    outs = m.predict(TestDS(), batch_size=3, stack_outputs=True, verbose=0)
    assert outs[0].shape == (6, 2)


def test_grad_accumulation_flushes_epoch_tail():
    m = _model()
    # 4 steps/epoch with accumulate=3: the final step must still update
    m.fit(XorDS(32), batch_size=8, epochs=1, verbose=0,
          accumulate_grad_batches=3)
    for p in m.parameters():
        assert p._grad is None  # cleared by the forced tail update


def test_input_spec():
    from paddle_tpu.static import InputSpec
    s = InputSpec([None, 8], "float32", name="x")
    t = s._zeros(4)
    assert tuple(t.shape) == (4, 8)
    s2 = InputSpec.from_tensor(t)
    assert s2.shape == (4, 8)
    assert s.batch(3).shape == (3, None, 8)
    assert s.unbatch().shape == (None, 8)
