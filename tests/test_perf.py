"""Perf layer: bucket ladders, compile counters, recompile guards,
coalesced transfer, device prefetcher.

The recompile guards are the PR's acceptance tests: N steady-state train
steps and a mixed-length serving run must stop compiling after warmup —
``compile.miss`` flat IS the "kill the recompiles" contract, enforced
here so a future change that reintroduces per-shape churn fails CI.

Tier-1 lane (marker: perf) under a time budget — everything here runs on
tiny shapes.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.perf import (BucketLadder, ShapeBuckets, compile_metrics,
                             resolve_ladder)
from paddle_tpu.perf.buckets import pad_amount

pytestmark = pytest.mark.perf

TIME_BUDGET_S = 60


@pytest.fixture(autouse=True)
def _time_budget():
    t0 = time.perf_counter()
    yield
    assert time.perf_counter() - t0 < TIME_BUDGET_S, \
        "perf test exceeded its time budget"


def _misses():
    return compile_metrics()["compile_cache_misses"]


# -- bucket ladders ----------------------------------------------------------

def test_pow2_ladder_rungs():
    assert list(BucketLadder.pow2(1, 32)) == [1, 2, 4, 8, 16, 32]
    # hi that is not a power of two becomes the top rung
    assert list(BucketLadder.pow2(1, 48))[-1] == 48


def test_fixed_ladder_rungs():
    assert list(BucketLadder.fixed(16, 64)) == [16, 32, 48, 64]
    assert list(BucketLadder.fixed(16, 40)) == [16, 32, 40]


def test_bucket_lookup_and_identity_above_top():
    ladder = BucketLadder([4, 8, 16])
    assert ladder.bucket(1) == 4
    assert ladder.bucket(8) == 8
    assert ladder.bucket(9) == 16
    # above the top rung: identity, never truncation
    assert ladder.bucket(17) == 17
    assert ladder.bucket(1000) == 1000
    # non-positive sizes pass through
    assert ladder.bucket(0) == 0
    assert ladder.bucket(-3) == -3


def test_custom_ladder_must_be_strictly_increasing():
    with pytest.raises(ValueError):
        BucketLadder([4, 4, 8])
    with pytest.raises(ValueError):
        BucketLadder([8, 4])
    with pytest.raises(ValueError):
        BucketLadder([])
    with pytest.raises(ValueError):
        BucketLadder([0, 4])


def test_resolve_ladder_specs():
    assert resolve_ladder(None) is None
    assert list(resolve_ladder("pow2", hi=16)) == [1, 2, 4, 8, 16]
    assert list(resolve_ladder("fixed:8", hi=24)) == [8, 16, 24]
    assert list(resolve_ladder([16, 4, 8])) == [4, 8, 16]  # sorted
    ladder = BucketLadder([2, 4, 64])
    assert list(resolve_ladder(ladder, hi=8)) == [2, 4, 8]  # capped
    with pytest.raises(ValueError):
        resolve_ladder("fixed:8")  # needs hi
    with pytest.raises(ValueError):
        resolve_ladder("fibonacci", hi=8)


def test_pad_amount():
    ladder = BucketLadder([4, 8])
    assert pad_amount(ladder, 3) == 1
    assert pad_amount(ladder, 4) == 0
    assert pad_amount(ladder, 100) == 0  # out of ladder: no padding
    assert pad_amount(None, 3) == 0


def test_shape_buckets_empty_and_per_axis():
    sb = ShapeBuckets({0: "pow2", 1: [128, 256]}, hi={0: 8})
    assert sb.bucket_for(()) == ()  # empty (scalar) shape maps to itself
    assert sb.bucket_for((3, 100)) == (4, 128)
    assert sb.bucket_for((3, 300, 7)) == (4, 300, 7)  # axis 1 above ladder;
    # axis 2 has no ladder -> passthrough


# -- recompile guards (the acceptance tests) ---------------------------------

def test_train_steps_stop_compiling_after_warmup():
    """10 steady-state fused train steps: compile.miss must be flat after
    step 1 (one discovery/build miss, then pure cache hits)."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    model = paddle.hapi.Model(net)
    model.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                    parameters=net.parameters()),
                  loss=nn.MSELoss(), jit=True)
    rng = np.random.RandomState(0)
    x = rng.rand(8, 4).astype("float32")
    y = rng.rand(8, 1).astype("float32")

    model.train_batch([x], [y])  # warmup: the one allowed miss
    m_after_warmup = _misses()
    losses = [model.train_batch([x], [y])[0] for _ in range(10)]
    assert len(losses) == 10
    assert all(np.isfinite(l) for l in losses)
    assert _misses() == m_after_warmup, \
        "steady-state train steps recompiled — the recompile bug is back"


def test_serving_mixed_lengths_bounded_compiles():
    """Mixed prompt lengths drawn from <= 3 buckets: after the first wave,
    a second wave of new lengths from the SAME buckets adds zero misses."""
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    from paddle_tpu.inference.serving import ContinuousBatcher

    paddle.seed(0)
    cfg = GPT2Config(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    bat = ContinuousBatcher(m, max_batch=4, s_max=32, compile=True)
    rng = np.random.RandomState(0)

    # lengths spanning exactly 3 pow2 buckets: {4}, {5..8}, {9..16}
    for L in [3, 5, 9, 4, 6, 12]:
        bat.submit(rng.randint(1, 96, size=L), max_new_tokens=3)
    out = bat.run_until_done()
    assert len(out) == 6
    m_wave1 = _misses()

    # new lengths, same buckets -> zero new compiles
    for L in [4, 7, 11, 8, 16]:
        bat.submit(rng.randint(1, 96, size=L), max_new_tokens=3)
    out = bat.run_until_done()
    assert len(out) == 5
    assert _misses() == m_wave1, \
        "serving recompiled for prompt lengths inside known buckets"


def test_serving_pad_waste_metric_counts():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    from paddle_tpu.inference.serving import ContinuousBatcher
    from paddle_tpu.observability.metrics import get_registry

    paddle.seed(0)
    cfg = GPT2Config(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    # rung-labeled since round 13: waste is attributable per resolved
    # bucket without re-deriving the ladder
    waste = get_registry().counter("serving.bucket_pad_waste", "test",
                                   labelnames=("rung",)).labels(rung="8")
    before = waste.value
    bat = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
    bat.submit(np.arange(1, 6), max_new_tokens=2)   # len 5 -> bucket 8: +3
    bat.submit(np.arange(1, 9), max_new_tokens=2)   # len 8 -> exact rung
    bat.run_until_done()
    assert waste.value - before == 3


def test_bucketed_serving_matches_unbucketed():
    """Bucket padding must not change generated tokens (greedy)."""
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    from paddle_tpu.inference.serving import ContinuousBatcher

    paddle.seed(0)
    cfg = GPT2Config(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 96, size=L) for L in (3, 5, 11)]

    outs = {}
    for buckets in ("pow2", None):
        bat = ContinuousBatcher(m, max_batch=4, s_max=32, compile=False,
                                prompt_buckets=buckets)
        rids = [bat.submit(p, max_new_tokens=4) for p in prompts]
        res = bat.run_until_done()
        outs[buckets] = [res[r] for r in rids]
    for a, b in zip(outs["pow2"], outs[None]):
        np.testing.assert_array_equal(a, b)


# -- persistent cache env gate -----------------------------------------------

def test_persistent_cache_env_gate(tmp_path, monkeypatch):
    from paddle_tpu.perf import compile_cache as cc

    monkeypatch.setattr(cc, "_PERSISTENT_STATE", None)
    monkeypatch.setenv("PADDLE_COMPILE_CACHE", "")
    assert cc.maybe_enable_persistent_cache() is False
    monkeypatch.setattr(cc, "_PERSISTENT_STATE", None)
    monkeypatch.setenv("PADDLE_COMPILE_CACHE", "0")
    assert cc.maybe_enable_persistent_cache() is False
    monkeypatch.setattr(cc, "_PERSISTENT_STATE", None)
    monkeypatch.setenv("PADDLE_COMPILE_CACHE", str(tmp_path / "xla"))
    assert cc.maybe_enable_persistent_cache() is True
    import jax
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla")
    # leave the process with the cache disabled again
    monkeypatch.setattr(cc, "_PERSISTENT_STATE", None)
    monkeypatch.setenv("PADDLE_COMPILE_CACHE", "")
    assert cc.maybe_enable_persistent_cache() is False


# -- input pipeline ----------------------------------------------------------

def test_coalesced_device_put_roundtrip():
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.perf.prefetch import coalesced_device_put

    batch = {"x": np.arange(6, dtype="float32").reshape(2, 3),
             "y": [np.ones(2, dtype="int64"), "tag"],
             "n": 7}
    out = coalesced_device_put(batch)
    assert isinstance(out["x"], Tensor)
    np.testing.assert_array_equal(out["x"].numpy(), batch["x"])
    assert isinstance(out["y"][0], Tensor)
    np.testing.assert_array_equal(out["y"][0].numpy(), batch["y"][0])
    assert out["y"][1] == "tag"
    assert out["n"] == 7


def test_device_prefetcher_delivers_in_order_and_closes():
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.perf.prefetch import DevicePrefetcher

    batches = [{"x": np.full((2, 2), i, dtype="float32")} for i in range(6)]
    pf = DevicePrefetcher(iter(batches), depth=2)
    got = list(pf)
    assert len(got) == 6
    for i, b in enumerate(got):
        assert isinstance(b["x"], Tensor)
        assert float(b["x"].numpy()[0, 0]) == float(i)
    pf.close()  # idempotent


def test_device_prefetcher_surfaces_source_errors():
    from paddle_tpu.perf.prefetch import DevicePrefetcher

    def boom():
        yield {"x": np.zeros(2, dtype="float32")}
        raise RuntimeError("source died")

    pf = DevicePrefetcher(boom(), depth=2)
    next(pf)
    with pytest.raises(RuntimeError, match="source died"):
        while True:
            next(pf)


def test_dataloader_prefetch_to_device_yields_tensors():
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.io.dataloader import DataLoader

    data = [(np.full(3, i, dtype="float32"), np.int64(i)) for i in range(10)]
    dl = DataLoader(data, batch_size=4, prefetch_to_device=True)
    seen = []
    for xb, yb in dl:
        assert isinstance(xb, Tensor) and isinstance(yb, Tensor)
        seen += yb.numpy().tolist()
    assert seen == list(range(10))


def test_dataloader_tail_batch_bucketing():
    from paddle_tpu.io.dataloader import DataLoader

    data = [(np.full(2, i, dtype="float32"), np.int64(i)) for i in range(11)]
    dl = DataLoader(data, batch_size=4, batch_buckets="pow2")
    shapes = [tuple(xb.shape) for xb, _ in dl]
    # tail of 3 pads to the bucket rung 4 by repeating the last sample
    assert shapes == [(4, 2), (4, 2), (4, 2)]
    *_, (xb, yb) = iter(DataLoader(data, batch_size=4,
                                   batch_buckets="pow2"))
    assert yb.numpy().tolist() == [8, 9, 10, 10]


def test_async_loader_close_during_inflight_transfer(monkeypatch):
    """close() while a transfer is IN FLIGHT: the issued transfer is
    allowed to land, queued-but-unissued work is cancelled typed, and —
    the lock-discipline invariant close() documents — the intake lock
    is never held across the worker-join deadline. The witness's
    hold-time accounting proves the last part: with a payload that
    stalls the worker ~0.2s, a close() that awaited the join under
    ``AsyncLoader._intake`` would show a comparable max hold."""
    import threading

    from paddle_tpu.perf.prefetch import AsyncLoader, TransferCancelled
    from paddle_tpu.utils import locks

    monkeypatch.setenv("PADDLE_LOCK_WITNESS", "1")
    locks.reset_witness()
    ld = AsyncLoader(depth=4, workers=1)
    entered = threading.Event()

    def slow_payload():
        entered.set()
        time.sleep(0.2)
        return {"x": np.ones(2, dtype="float32")}

    inflight = ld.submit(slow_payload)
    assert entered.wait(2.0), "worker never picked up the transfer"
    queued = ld.submit({"y": np.zeros(2, dtype="float32")})
    ld.close(timeout=2.0)

    got = inflight.result(timeout=2.0)
    np.testing.assert_array_equal(np.asarray(got["x"]), np.ones(2))
    with pytest.raises(TransferCancelled):
        queued.result(timeout=2.0)

    held = locks.get_witness().max_hold("AsyncLoader._intake")
    assert held < 0.1, (
        f"intake lock held {held:.3f}s — close() awaited the worker "
        f"join (or the in-flight transfer) while holding it")


def test_device_prefetcher_close_during_inflight_transfer(monkeypatch):
    """close() while the feeder is INSIDE a transfer: close must return
    within its bound, retire cleanly once the transfer lands, and — per
    the intake-lock discipline — never await the feeder join while
    holding ``DevicePrefetcher._intake`` (witness hold accounting)."""
    import threading

    from paddle_tpu.perf.prefetch import DevicePrefetcher
    from paddle_tpu.utils import locks

    monkeypatch.setenv("PADDLE_LOCK_WITNESS", "1")
    locks.reset_witness()
    entered = threading.Event()

    def slow_transfer(batch):
        entered.set()
        time.sleep(0.2)
        return batch

    batches = [{"x": np.full(2, i, dtype="float32")} for i in range(8)]
    pf = DevicePrefetcher(iter(batches), depth=1, transfer=slow_transfer)
    assert entered.wait(2.0), "feeder never started a transfer"
    t0 = time.perf_counter()
    pf.close(timeout=2.0)
    assert time.perf_counter() - t0 < 2.0
    assert pf._retired and not pf._thread.is_alive()
    pf.close()  # idempotent after retirement

    held = locks.get_witness().max_hold("DevicePrefetcher._intake")
    assert held < 0.1, (
        f"intake lock held {held:.3f}s — close() awaited the feeder "
        f"join while holding it")
