"""Pipeline-parallel tests on the 8-device CPU mesh.

Reference coverage model: test/collective/fleet hybrid_parallel_pp_*.py —
1F1B and interleave train_batch losses must match the same model trained
without pipelining (SURVEY.md §4).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import (LayerDesc, PipelineLayer,
                                          PipelineParallel, SharedLayerDesc)
from paddle_tpu.distributed.fleet.pp_layers import SegmentLayers


@pytest.fixture(autouse=True)
def _reset_topology():
    yield
    from paddle_tpu.distributed.fleet import topology
    topology.set_hybrid_communicate_group(None)


HIDDEN = 16


class Block(nn.Layer):
    def __init__(self, seed_shift=0):
        super().__init__()
        self.fc = nn.Linear(HIDDEN, HIDDEN)
        self.act = nn.ReLU()

    def forward(self, x):
        return self.act(self.fc(x))


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(HIDDEN, 4)

    def forward(self, x):
        return self.fc(x)


def _loss_fn(out, label):
    return nn.functional.cross_entropy(out, label).mean()


def _make_descs(n_blocks=4):
    return [LayerDesc(Block) for _ in range(n_blocks)] + [LayerDesc(Head)]


def _data(batch=8, seed=0):
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(batch, HIDDEN).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 4, (batch,)))
    return x, y


def test_segment_uniform():
    parts = SegmentLayers(list(range(10)), 4, "uniform").do_segment()
    assert parts == [0, 3, 6, 8, 10]
    assert parts[-1] == 10


def test_segment_by_layer_name():
    descs = [LayerDesc(Head)] + [LayerDesc(Block) for _ in range(4)] + \
        [LayerDesc(Head)]
    parts = SegmentLayers(descs, 2, "layer:Block").do_segment()
    # two Blocks per stage; pre/post layers attach to first/last stages
    assert parts[0] == 0 and parts[-1] == 6
    assert parts[1] == 3  # Head + 2 Blocks | 2 Blocks + Head


def _train_reference(descs_builder, data, steps=2, lr=0.1):
    """Same model, no pipelining, sequential forward."""
    paddle.seed(42)
    layers = [d.build_layer() for d in descs_builder()]
    model = nn.Sequential(*layers)
    opt = optimizer.SGD(learning_rate=lr, parameters=model.parameters())
    losses = []
    x, y = data
    for _ in range(steps):
        out = model(x)
        loss = _loss_fn(out, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _train_pipeline(data, pp=4, accumulate_steps=4, vpp=None, steps=2,
                    lr=0.1, recompute_interval=0):
    paddle.seed(42)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": pp, "sharding_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": accumulate_steps}
    fleet.init(is_collective=True, strategy=strategy)
    kwargs = {}
    if vpp:
        kwargs["num_virtual_pipeline_stages"] = vpp
    model = PipelineLayer(layers=_make_descs(), loss_fn=_loss_fn,
                          recompute_interval=recompute_interval, **kwargs)
    model = fleet.distributed_model(model)
    opt = optimizer.SGD(learning_rate=lr, parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)
    losses = []
    x, y = data
    for _ in range(steps):
        loss = model.train_batch([x, y], opt)
        losses.append(float(loss))
    return losses


def test_pipeline_1f1b_matches_sequential():
    data = _data()
    ref = _train_reference(_make_descs, data)
    pp = _train_pipeline(data, pp=4, accumulate_steps=4)
    np.testing.assert_allclose(pp, ref, rtol=1e-5, atol=1e-6)


def test_pipeline_accumulate_gt_stages():
    data = _data()
    ref = _train_reference(_make_descs, data)
    pp = _train_pipeline(data, pp=2, accumulate_steps=8)
    np.testing.assert_allclose(pp, ref, rtol=1e-5, atol=1e-6)


def test_pipeline_interleave_matches_sequential():
    data = _data()
    ref = _train_reference(_make_descs, data)
    # 5 layers, 2 stages * 2 virtual chunks -> chunks of 2/1/1/1 round-robin
    pp = _train_pipeline(data, pp=2, accumulate_steps=4, vpp=2)
    np.testing.assert_allclose(pp, ref, rtol=1e-5, atol=1e-6)


def test_pipeline_recompute_matches():
    data = _data()
    ref = _train_reference(_make_descs, data)
    pp = _train_pipeline(data, pp=2, accumulate_steps=2,
                         recompute_interval=1)
    np.testing.assert_allclose(pp, ref, rtol=1e-5, atol=1e-6)


def test_pipeline_eval_batch():
    data = _data()
    _ = _train_reference(_make_descs, data, steps=1)
    paddle.seed(42)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 4, "sharding_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": 4}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(
        PipelineLayer(layers=_make_descs(), loss_fn=_loss_fn))
    x, y = data
    loss = model.eval_batch([x, y])
    assert np.isfinite(float(loss))


class TiedEmbed(nn.Layer):
    def __init__(self):
        super().__init__()
        self.weight = self.create_parameter([4, HIDDEN])

    def forward(self, x):
        # as input embedding: one-hot matmul
        return paddle.matmul(x, self.weight)


def test_shared_layer_grads_synced():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(7)

    def head_fwd(layer, x):
        return paddle.matmul(x, layer.weight, transpose_y=True)

    descs = [
        SharedLayerDesc("embed", TiedEmbed),
        LayerDesc(Block),
        LayerDesc(Block),
        SharedLayerDesc("embed", TiedEmbed, forward_func=head_fwd),
    ]
    model = PipelineLayer(layers=descs, num_stages=2,
                          loss_fn=lambda out, lbl:
                          nn.functional.cross_entropy(out, lbl).mean())
    model = fleet.distributed_model(model)
    groups = model._layers.shared_groups()
    assert len(groups["embed"][1]) == 2

    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 4, (4,)))
    model.train_batch([x, y], opt)
    w0, w1 = [getattr(l, "weight") for l in groups["embed"][1]]
    np.testing.assert_allclose(w0.numpy(), w1.numpy(), rtol=1e-6)


def test_pipeline_eval_batch_outputs():
    """eval_batch(compute_loss=False) returns the stitched full-batch output."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": 4}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(
        PipelineLayer(layers=_make_descs(), loss_fn=_loss_fn))
    x, y = _data(batch=8)
    out = model.eval_batch([x, y], compute_loss=False)
    assert out.shape == [8, 4]


def test_schedule_plans_validity_and_liveness():
    """FThenB/1F1B/VPP plans respect deps; 1F1B bounds in-flight activations
    at ~num_stages while FThenB holds all micros (the GPipe profile)."""
    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        generate_schedule, max_inflight_per_stage, validate_schedule)
    S, M = 4, 8
    for kind, C in [("FThenB", 4), ("1F1B", 4), ("VPP", 8)]:
        plan = generate_schedule(kind, S, C, M)
        validate_schedule(plan, C, M)
    gpipe = max_inflight_per_stage(generate_schedule("FThenB", S, 4, M), S)
    f1b1 = max_inflight_per_stage(generate_schedule("1F1B", S, 4, M), S)
    assert gpipe == [M] * S
    assert f1b1 == [S, S - 1, S - 2, S - 3]  # classic descending profile


def test_vpp_issue_order_is_chunk_interleaved():
    """The interleave engine must ISSUE chunk-staggered units (VERDICT #4:
    'interleave is a name, not a schedule' — now it is a schedule)."""
    import paddle_tpu.distributed as dist
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 4}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    layers = PipelineLayer(_make_descs(7), num_stages=2, loss_fn=_loss_fn,
                           topology=hcg.topology(),
                           num_virtual_pipeline_stages=2)
    from paddle_tpu.distributed.fleet.pipeline_parallel import \
        PipelineParallelWithInterleave
    pp = PipelineParallelWithInterleave(layers, hcg, strategy)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=pp.parameters())
    x, y = _data(batch=8)
    pp.train_batch([x, y], opt)
    trace = pp.schedule_trace
    # the plan interleaves: some F of chunk>=1 is issued before the LAST F
    # of chunk 0, and backwards start before all forwards finish
    f_units = [(i, c, m) for i, (k, c, m) in enumerate(trace) if k == "F"]
    last_f0 = max(i for i, c, m in f_units if c == 0)
    first_f1 = min(i for i, c, m in f_units if c >= 1)
    assert first_f1 < last_f0
    first_b = min(i for i, (k, c, m) in enumerate(trace) if k == "B")
    last_f = max(i for i, (k, c, m) in enumerate(trace) if k == "F")
    assert first_b < last_f


def test_fthenb_schedule_mode():
    """strategy.pipeline_configs['schedule_mode'] switches the static plan
    (pipeline_scheduler_pass.py FThenB analog) and still trains."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 4,
                                 "schedule_mode": "FThenB"}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    layers = PipelineLayer(_make_descs(3), num_stages=2, loss_fn=_loss_fn,
                           topology=hcg.topology())
    pp = PipelineParallel(layers, hcg, strategy)
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=pp.parameters())
    x, y = _data(batch=8)
    l0 = float(pp.train_batch([x, y], opt))
    l1 = float(pp.train_batch([x, y], opt))
    assert np.isfinite(l0) and l1 < l0
    kinds = [k for k, _, _ in pp.schedule_trace]
    nf = kinds.count("F")
    assert all(k == "F" for k in kinds[:nf])  # every F precedes every B


def test_schedule_plans_parameter_sweep():
    """Every (kind, S, V, M) combo — including M not divisible by S —
    yields a valid plan (review regression: ragged micro groups)."""
    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        generate_schedule, validate_schedule)
    import pytest as _pytest
    for kind in ("FThenB", "1F1B", "VPP"):
        for S in (2, 3, 4):
            for V in (1, 2, 3):
                if kind == "VPP" and V == 1:
                    continue
                for M in (1, 2, 3, 5, 8):
                    C = S * V
                    if V > 1 and kind != "FThenB" and M % S:
                        # Megatron constraint, rejected loudly
                        with _pytest.raises(ValueError, match="divisible"):
                            generate_schedule(kind, S, C, M)
                        continue
                    plan = generate_schedule(kind, S, C, M)
                    validate_schedule(plan, C, M)
