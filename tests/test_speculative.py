"""Greedy speculative decoding over the paged cache.

Exactness bar: generate_paged_speculative(target, draft, ...) must equal
target.generate(...) token for token, for ANY draft — a good draft only
changes how many target dispatches that takes, never the output. This is
the defining property of greedy draft/verify decoding and what makes the
feature safe to enable by default in serving.

Beyond-reference feature (the reference snapshot has no in-tree
speculative decoding); the paged cache makes rejection rollback free —
host-owned dec_lens bounds every read, stale rows are overwritten on the
next append (see GPT2ForCausalLM._speculative_loop).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config

from test_paged_batching import _retry_load_flake


def _gpt(seed, layers=2, hidden=64):
    paddle.seed(seed)
    cfg = GPT2Config(vocab_size=128, hidden_size=hidden,
                     num_hidden_layers=layers, num_attention_heads=4,
                     max_position_embeddings=96, dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _llama(seed):
    paddle.seed(seed)
    m = LlamaForCausalLM(llama_tiny_config(max_position_embeddings=96))
    m.eval()
    return m


def _ref(m, prompt, n):
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None])
    with paddle.no_grad():
        return m.generate(ids, max_new_tokens=n).numpy()[0]


def test_speculative_matches_greedy_any_draft():
    """Output == target greedy regardless of the draft: a same-family
    smaller draft, an unrelated (different-seed) draft, and the target
    itself as its own draft (always-accept path)."""
    _retry_load_flake(_any_draft_body, attempts=3)


def _any_draft_body():
    target = _gpt(0)
    rng = np.random.RandomState(50)
    prompt = rng.randint(0, 128, (11,))
    want = _ref(target, prompt, 14)
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None])
    for draft in (_gpt(1, layers=1, hidden=32), _gpt(7), target):
        out, st = target.generate_paged_speculative(
            ids, 14, draft, draft_k=4, block_size=8, return_stats=True)
        np.testing.assert_array_equal(out.numpy()[0], want)
        assert st["rounds"] > 0
    # the self-draft must accept every proposal (it IS the target)
    out, st = target.generate_paged_speculative(
        ids, 14, target, draft_k=4, block_size=8, return_stats=True)
    assert st["acceptance_rate"] == 1.0
    assert st["tokens_per_target_dispatch"] > 1.0


def test_speculative_llama_and_cross_family():
    """Llama target with a Llama draft AND with a GPT-2 draft (both
    families speak the shared paged-state convention)."""
    _retry_load_flake(_cross_family_body, attempts=3)


def _cross_family_body():
    target = _llama(0)
    rng = np.random.RandomState(51)
    prompt = rng.randint(0, 128, (9,))
    want = _ref(target, prompt, 12)
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None])
    for draft in (_llama(3), _gpt(4)):
        out = target.generate_paged_speculative(ids, 12, draft,
                                                draft_k=3, block_size=8)
        np.testing.assert_array_equal(out.numpy()[0], want)


def test_speculative_eos_and_budget_edges():
    _retry_load_flake(_edges_body, attempts=3)


def _edges_body():
    target = _gpt(0)
    draft = _gpt(2, layers=1, hidden=32)
    rng = np.random.RandomState(52)
    prompt = rng.randint(0, 128, (10,))
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None])
    full = _ref(target, prompt, 12)
    gen = full[len(prompt):]
    # force an EOS mid-generation: output truncates exactly there
    eos = int(gen[4])
    out = target.generate_paged_speculative(ids, 12, draft, draft_k=4,
                                            block_size=8, eos_id=eos)
    np.testing.assert_array_equal(out.numpy()[0], full[:len(prompt) + 5])
    # max_new_tokens == 1: no draft round at all, still exact
    out1 = target.generate_paged_speculative(ids, 1, draft, draft_k=4,
                                             block_size=8)
    np.testing.assert_array_equal(out1.numpy()[0], _ref(target, prompt, 1))
    # max_new_tokens == 0 returns the prompt unchanged, like generate()
    out0 = target.generate_paged_speculative(ids, 0, draft, draft_k=4,
                                             block_size=8)
    np.testing.assert_array_equal(out0.numpy()[0], prompt)
    # budget not a multiple of draft_k: the tail rounds shrink k
    out2 = target.generate_paged_speculative(ids, 6, draft, draft_k=4,
                                             block_size=8)
    np.testing.assert_array_equal(out2.numpy()[0], _ref(target, prompt, 6))


def test_speculative_guards():
    target = _gpt(0)
    ids = paddle.to_tensor(np.zeros((1, 8), np.int64))
    with pytest.raises(ValueError, match="draft_k"):
        target.generate_paged_speculative(ids, 4, target, draft_k=0)
    with pytest.raises(ValueError, match="single-sequence"):
        target.generate_paged_speculative(
            paddle.to_tensor(np.zeros((2, 8), np.int64)), 4, target)
    paddle.seed(9)
    other = GPT2ForCausalLM(GPT2Config(vocab_size=64, hidden_size=32,
                                       num_hidden_layers=1,
                                       num_attention_heads=2,
                                       max_position_embeddings=64,
                                       dropout=0.0))
    with pytest.raises(ValueError, match="vocab"):
        target.generate_paged_speculative(ids, 4, other)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        target.generate_paged_speculative(
            paddle.to_tensor(np.zeros((1, 90), np.int64)), 20, target)
