"""Tests for the serving slice (jit AOT save/load + inference Predictor),
rpc, auto_tuner, hub, onnx shim, and the PS stub."""
import multiprocessing as mp
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.static import InputSpec


def _mlp():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


# -- AOT save/load -------------------------------------------------------------

def test_jit_save_load_stablehlo_roundtrip(tmp_path):
    net = _mlp()
    net.eval()
    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 8], "float32", "x")])
    assert os.path.exists(path + ".pdmodel")
    assert os.path.exists(path + ".pdiparams")

    loaded = jit.load(path)
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 8)
                         .astype(np.float32))
    ref = np.asarray(net(x)._data)
    out = np.asarray(loaded(x)._data)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_jit_save_load_padded_tp_layer(tmp_path):
    """code-review r5: jit.save/load with a Megatron-padded TP layer.
    pdiparams stores LOGICAL shapes (interchange), the exported program
    binds PADDED shapes (param_pads metadata re-pads at load), and the
    export must thread the params as real inputs — NOT bake the live
    weights in as constants: after swapping pdiparams for different
    weights, the loaded program's output must change accordingly."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.framework import io as fio

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    V = 130                          # pads to 132 over mp=4
    paddle.seed(21)
    net = fleet.ColumnParallelLinear(8, V, gather_output=True)
    net.eval()
    path = str(tmp_path / "padded")
    jit.save(net, path, input_spec=[InputSpec([None, 8], "float32", "x")])
    # checkpoint on disk carries the true shapes
    state = fio.load(path + ".pdiparams")
    assert list(state["weight"].shape) == [8, V]
    assert list(state["bias"].shape) == [V]

    loaded = jit.load(path)
    x = paddle.to_tensor(np.random.RandomState(1).randn(3, 8)
                         .astype(np.float32))
    ref = np.asarray(net(x)._data)
    np.testing.assert_allclose(np.asarray(loaded(x)._data), ref,
                               rtol=1e-5, atol=1e-6)
    # swap the weights on disk: the program must follow them
    rng = np.random.RandomState(2)
    new_w = rng.randn(8, V).astype(np.float32)
    new_b = rng.randn(V).astype(np.float32)
    fio.save({"weight": paddle.to_tensor(new_w),
              "bias": paddle.to_tensor(new_b)}, path + ".pdiparams")
    loaded2 = jit.load(path)
    out2 = np.asarray(loaded2(x)._data)
    expect2 = x.numpy() @ new_w + new_b
    np.testing.assert_allclose(out2, expect2, rtol=1e-4, atol=1e-5)
    assert not np.allclose(out2, ref)


def test_jit_save_params_only(tmp_path):
    net = _mlp()
    path = str(tmp_path / "params_model")
    jit.save(net, path)  # no input_spec
    state = jit.load(path)
    assert isinstance(state, dict)
    assert set(state) == set(net.state_dict())


def test_inference_predictor(tmp_path):
    from paddle_tpu import inference

    net = _mlp()
    net.eval()
    path = str(tmp_path / "serve")
    jit.save(net, path, input_spec=[InputSpec([None, 8], "float32", "x")])

    config = inference.Config(path + ".pdmodel")
    predictor = inference.create_predictor(config)
    names = predictor.get_input_names()
    assert names == ["x"]
    x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, np.asarray(net(paddle.to_tensor(x))._data),
                               rtol=1e-5, atol=1e-6)
    # list-style run
    outs = predictor.run([x])
    np.testing.assert_allclose(outs[0], out, rtol=1e-6)


def test_predictor_rejects_params_only(tmp_path):
    from paddle_tpu import inference
    net = _mlp()
    path = str(tmp_path / "noexport")
    jit.save(net, path)
    with pytest.raises(ValueError, match="params-only"):
        inference.create_predictor(inference.Config(path))


class _TwoInput(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)

    def forward(self, x, mask):
        return self.fc(x) * mask


def test_jit_save_multi_input_shared_batch_dim(tmp_path):
    net = _TwoInput()
    net.eval()
    path = str(tmp_path / "two_in")
    jit.save(net, path, input_spec=[InputSpec([None, 8], "float32", "x"),
                                    InputSpec([None, 4], "float32", "mask")])
    loaded = jit.load(path)
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    m = np.ones((5, 4), np.float32)
    out = loaded(paddle.to_tensor(x), paddle.to_tensor(m))
    np.testing.assert_allclose(
        np.asarray(out._data),
        np.asarray(net(paddle.to_tensor(x), paddle.to_tensor(m))._data),
        rtol=1e-5, atol=1e-6)


def test_hapi_inference_save_load_roundtrip(tmp_path):
    from paddle_tpu import Model, optimizer as opt_mod
    m = Model(_mlp())
    m.prepare(optimizer=opt_mod.Adam(learning_rate=0.01,
                                     parameters=m.parameters()),
              loss=nn.CrossEntropyLoss())
    path = str(tmp_path / "hapi_infer")
    m.save(path, training=False)  # jit.save layout (.pdiparams)
    m2 = Model(_mlp())
    m2.prepare(loss=nn.CrossEntropyLoss())
    m2.load(path, reset_optimizer=True)  # falls back to .pdiparams
    x = np.random.RandomState(2).randn(3, 8).astype(np.float32)
    np.testing.assert_allclose(m.predict_batch([x])[0],
                               m2.predict_batch([x])[0], rtol=1e-5)


# -- rpc -----------------------------------------------------------------------

def _rpc_child(port, out_q):
    try:
        from paddle_tpu.distributed import rpc
        rpc.init_rpc("worker1", rank=1, world_size=2,
                     master_endpoint=f"127.0.0.1:{port}")
        # workers stay up until shutdown barrier
        rpc.shutdown()
        out_q.put(("ok", None))
    except Exception as e:  # pragma: no cover
        out_q.put(("err", repr(e)))


def _double(x):
    return x * 2


def test_rpc_two_workers():
    import socket
    from paddle_tpu.distributed import rpc
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()

    ctx = mp.get_context("spawn")
    out_q = ctx.Queue()
    child = ctx.Process(target=_rpc_child, args=(port, out_q))
    child.start()
    try:
        rpc.init_rpc("worker0", rank=0, world_size=2,
                     master_endpoint=f"127.0.0.1:{port}")
        info = rpc.get_worker_info("worker1")
        assert info.rank == 1
        assert rpc.rpc_sync("worker1", _double, args=(21,)) == 42
        fut = rpc.rpc_async("worker1", _double, args=(5,))
        assert fut.wait(timeout=30) == 10
        assert len(rpc.get_all_worker_infos()) == 2
    finally:
        rpc.shutdown()
        child.join(timeout=30)
    status, err = out_q.get(timeout=10)
    assert status == "ok", err


# -- auto_tuner ----------------------------------------------------------------

def test_auto_tuner_prunes_and_ranks():
    from paddle_tpu.distributed.auto_tuner import AutoTuner, TuneConfig
    cfg = TuneConfig(world_size=8, num_layers=8, hidden_size=1024,
                     num_heads=16, vocab_size=32000, seq_length=2048,
                     global_batch_size=32, hbm_bytes=16e9)
    tuner = AutoTuner(cfg)
    cands = tuner.candidates()
    assert cands, "search space should not be empty"
    for c in cands:
        assert c["dp_degree"] * c["mp_degree"] * c["pp_degree"] == 8
        assert 16 % c["mp_degree"] == 0
        assert c["sharding_degree"] <= c["dp_degree"]
    best = tuner.search(top_k=3)
    assert len(best) == 3
    assert best[0]["metric"] >= best[1]["metric"] >= best[2]["metric"]


def test_auto_tuner_measured_trials():
    from paddle_tpu.distributed.auto_tuner import AutoTuner, TuneConfig
    cfg = TuneConfig(world_size=4, num_layers=4, hidden_size=256,
                     num_heads=8, vocab_size=1000, seq_length=128,
                     global_batch_size=8)
    # fake measurement: prefer pure dp
    tuner = AutoTuner(cfg, run_fn=lambda c: float(c["dp_degree"]))
    top = tuner.search(top_k=1)[0]
    assert top["dp_degree"] == 4
    assert tuner.best()["metric"] == 4.0


def test_auto_tuner_memory_prune():
    from paddle_tpu.distributed.auto_tuner import AutoTuner, TuneConfig
    tiny_mem = TuneConfig(world_size=8, hbm_bytes=1e6)  # nothing fits
    assert AutoTuner(tiny_mem).candidates() == []


# -- hub / onnx / ps ------------------------------------------------------------

def test_hub_local_repo(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(scale=1):\n"
        "    'builds the tiny model'\n"
        "    return ('model', scale)\n")
    from paddle_tpu import hub
    assert "tiny_model" in hub.list(str(tmp_path))
    assert "tiny" in hub.help(str(tmp_path), "tiny_model")
    assert hub.load(str(tmp_path), "tiny_model", scale=3) == ("model", 3)
    with pytest.raises(RuntimeError, match="network"):
        hub.list("any", source="github")


def test_onnx_export_falls_back_to_stablehlo(tmp_path):
    net = _mlp()
    path = str(tmp_path / "m.onnx")
    with pytest.raises(RuntimeError, match="StableHLO"):
        paddle.onnx.export(net, path,
                           input_spec=[InputSpec([None, 8], "float32")])
    assert os.path.exists(str(tmp_path / "m") + ".pdmodel")


def test_ps_stub_raises_with_guidance():
    from paddle_tpu.distributed import ps
    with pytest.raises(NotImplementedError, match="SPMD"):
        ps.init_server()


class TestServingDepth:
    def test_weight_only_quantize_linear_layers(self):
        from paddle_tpu import nn
        paddle.seed(3)
        m = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
        x = paddle.randn([4, 32])
        with paddle.no_grad():
            ref = m(x).numpy()
        n = nn.quant.quantize_linear_layers(m)
        assert n == 2
        from paddle_tpu.nn.quant import WeightOnlyLinear
        assert isinstance(m[0], WeightOnlyLinear)
        with paddle.no_grad():
            got = m(x).numpy()
        # int8 per-channel drift stays small
        assert np.abs(got - ref).max() < 0.1 * np.abs(ref).max() + 0.05

    def test_weight_only_gpt2_decode(self):
        from paddle_tpu import nn
        from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
        paddle.seed(4)
        cfg = GPT2Config(vocab_size=128, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         max_position_embeddings=64)
        model = GPT2ForCausalLM(cfg)
        model.eval()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (1, 16)))
        with paddle.no_grad():
            ref = model(ids).numpy()
        n = nn.quant.quantize_linear_layers(model)
        assert n >= 2 * cfg.num_hidden_layers
        with paddle.no_grad():
            got = model(ids).numpy()
        assert got.shape == ref.shape
        # quantization drift is bounded; argmax token mostly preserved
        agree = (got[0, -1].argmax() == ref[0, -1].argmax())
        assert np.isfinite(got).all() and (
            agree or np.abs(got - ref).max() < 1.0)

    def test_bucket_batching_predictor(self, tmp_path):
        from paddle_tpu import jit, nn
        from paddle_tpu.inference import (BucketBatchingPredictor, Config,
                                          create_predictor)
        from paddle_tpu.static import InputSpec
        paddle.seed(5)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        m.eval()
        path = str(tmp_path / "served")
        jit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        pred = create_predictor(Config(path))
        batcher = BucketBatchingPredictor(pred, buckets=(2, 4, 8))

        rng = np.random.RandomState(0)
        reqs = [[rng.randn(1, 8).astype("float32")] for _ in range(3)]
        outs = batcher.run_batch(reqs)  # 3 requests -> bucket 4 (padded)
        assert len(outs) == 3
        for r, o in zip(reqs, outs):
            direct = pred.run([r[0]])[0]
            np.testing.assert_allclose(o[0], direct, rtol=1e-5, atol=1e-6)
        with pytest.raises(ValueError):
            batcher.run_batch([[rng.randn(1, 8).astype("float32")]] * 9)
