"""Continuous batching over the PAGED (block) KV cache.

Reference serving loop analog: block_multihead_attention + request
scheduling (incubate/nn/functional/block_multihead_attention.py:19).
Exactness bar: every request's output equals its single-request
generate_paged()/generate() result regardless of arrival order, slot
reuse, page-pool pressure, or preemption.

Known flake (rare, CPU-backend-only): under heavy host load, compiled
serving paths have intermittently produced a LATE token differing from
the eager/reference path (observed across several test files, including
runs that predate the fused/chunked features). The repeated controlled
runs point at load-dependent partial-sum ordering in the CPU backend's
threaded matmuls flipping argmax near-ties on these tiny random-weight
vocabularies — not at the serving logic, which is bitwise-deterministic
in its host scheduling. The single-executable asserts print their cache
keys on failure so a signature-drift recurrence is diagnosable.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import PagedContinuousBatcher
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM


def _model():
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _ref(m, prompt, n):
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None, :])
    with paddle.no_grad():
        return m.generate(ids, max_new_tokens=n).numpy()[0]


# session-wide retry accounting: one or two load flips across a whole
# heavy parallel run are the documented CPU symptom; MORE than that in
# one session is evidence of a real nondeterminism/scheduling bug that
# retries must not paper over (ADVICE r3).
_RETRY_BUDGET = [3]


def _retry_load_flake(body, attempts=2):
    """Run an exact-token scenario up to `attempts` times (see the module
    docstring: heavy host load can flip argmax near-ties in the CPU
    backend's threaded matmuls — a CPU-ONLY symptom). A LOGIC regression
    fails every attempt and still fails the test; a load flip passes the
    retry — but LOUDLY, debited from a small per-session budget.

    Gating (VERDICT r3 #9): on TPU the same scenarios must be exact on
    the first try, so the helper never retries there; setting
    PADDLE_EXACT_STRICT=1 disables retries everywhere (CI strict mode).
    """
    import os
    import warnings

    import jax
    if (os.environ.get("PADDLE_EXACT_STRICT") == "1"
            or jax.devices()[0].platform == "tpu"):
        attempts = 1
    for i in range(attempts):
        try:
            body()
            return
        except AssertionError as e:
            if i + 1 == attempts:
                raise
            if _RETRY_BUDGET[0] <= 0:
                raise AssertionError(
                    "exact-token retry budget exhausted this session — "
                    "this is no longer the rare CPU load flake; "
                    "investigate as a real bug") from e
            _RETRY_BUDGET[0] -= 1
            warnings.warn(
                f"exact-token attempt {i + 1} failed and was retried "
                f"(documented CPU load flake; {_RETRY_BUDGET[0]} session "
                f"retries left): {str(e)[:300]}")


def test_paged_batch_matches_solo_generate():
    m = _model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 9, 12, 7)]
    ns = [6, 4, 8, 5]
    b = PagedContinuousBatcher(m, max_batch=4, s_max=32, block_size=8,
                               compile=False)
    rids = [b.submit(p, n) for p, n in zip(prompts, ns)]
    outs = b.run_until_done()
    for rid, p, n in zip(rids, prompts, ns):
        np.testing.assert_array_equal(outs[rid], _ref(m, p, n),
                                      err_msg=f"request {rid}")
    # every page returned to the pool after the run
    assert b.free_page_count == b.n_pages
    assert (b._bt == b._scratch).all()


def test_paged_slot_and_page_reuse():
    """More requests than slots: later arrivals admit into freed slots and
    recycled pages mid-run, still token-exact."""
    m = _model()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 128, (s,)) for s in (4, 6, 8, 5, 7, 9)]
    ns = [3, 7, 4, 6, 5, 4]
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               compile=False)
    rids = [b.submit(p, n) for p, n in zip(prompts[:3], ns[:3])]
    for _ in range(3):
        b.step()
    rids += [b.submit(p, n) for p, n in zip(prompts[3:], ns[3:])]
    outs = b.run_until_done()
    # earlier finishers were popped by the first steps' bookkeeping only
    # if finished; collect any remaining
    for rid, p, n in zip(rids, prompts, ns):
        got = outs.get(rid)
        if got is None:
            got = b.pop_result(rid)
        np.testing.assert_array_equal(got, _ref(m, p, n),
                                      err_msg=f"request {rid}")
    assert b.free_page_count == b.n_pages


def test_ondemand_growth_allocates_lazily():
    """ondemand admits with only the prompt's pages and grows across block
    boundaries; outputs stay exact and the pool drains/refills."""
    m = _model()
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, 128, (5,))
    n = 14  # crosses two block_size=8 boundaries from row 5
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               policy="ondemand", compile=False)
    rid = b.submit(prompt, n)
    b.step()
    used_after_admit = b.n_pages - b.free_page_count
    assert used_after_admit == 1  # ceil((5+1)/8) pages only, not worst case
    outs = b.run_until_done()
    np.testing.assert_array_equal(outs[rid], _ref(m, prompt, n))
    assert b.free_page_count == b.n_pages


def test_ondemand_preemption_is_exact():
    """Pool too small for both requests' full lengths: the later request
    must be preempted (pages freed, re-queued) and still finish with
    exactly its solo continuation (recompute-on-resume)."""
    m = _model()
    rng = np.random.RandomState(3)
    p0 = rng.randint(0, 128, (6,))
    p1 = rng.randint(0, 128, (6,))
    # block_size 4, 6 pages total: each request needs up to
    # ceil((6+10)/4) = 4 pages; both can admit (2+2) but can't both grow
    b = PagedContinuousBatcher(m, max_batch=2, s_max=24, block_size=4,
                               n_pages=6, policy="ondemand", compile=False)
    r0 = b.submit(p0, 10)
    r1 = b.submit(p1, 10)
    preempted = False
    for _ in range(100):
        before_pending = len(b._pending)
        b.step()
        if len(b._pending) > before_pending:
            preempted = True
        if not b._pending and not b._slot_req:
            break
    outs = {r0: b.pop_result(r0), r1: b.pop_result(r1)}
    assert preempted, "pool pressure should have forced a preemption"
    np.testing.assert_array_equal(outs[r0], _ref(m, p0, 10))
    np.testing.assert_array_equal(outs[r1], _ref(m, p1, 10))
    assert b.free_page_count == b.n_pages


@pytest.mark.smoke
def test_compiled_paged_batcher_matches_eager():
    # the ONE compiled-serving exactness test kept in the smoke tier
    # (the heavier chunked/fused compiled tests run in the full suite)
    m = _model()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 9, 7)]
    ns = [6, 4, 5]

    def body():
        be = PagedContinuousBatcher(m, max_batch=4, s_max=32, block_size=8,
                                    compile=False)
        bc = PagedContinuousBatcher(m, max_batch=4, s_max=32, block_size=8,
                                    compile=True)
        re_ = [be.submit(p, n) for p, n in zip(prompts, ns)]
        rc = [bc.submit(p, n) for p, n in zip(prompts, ns)]
        oe = be.run_until_done()
        oc = bc.run_until_done()
        for a, b_ in zip(re_, rc):
            np.testing.assert_array_equal(oe[a], oc[b_])
        # one decode executable across every step/occupancy (the state's
        # static ints must survive the compiled-call round trip)
        assert len(bc._step_fn._cache) == 1

    _retry_load_flake(body)


def test_paged_capacity_errors():
    m = _model()
    b = PagedContinuousBatcher(m, max_batch=2, s_max=16, block_size=8,
                               compile=False)
    with pytest.raises(ValueError, match="exceeds slot capacity"):
        b.submit(np.zeros(10, np.int64), 8)
    small = PagedContinuousBatcher(m, max_batch=1, s_max=16, block_size=8,
                                   n_pages=1, compile=False)
    with pytest.raises(ValueError, match="pool"):
        small.submit(np.zeros(6, np.int64), 8)
    # admission always emits one token, so zero-token requests can't
    # honor the exactness-vs-generate contract and must be rejected
    with pytest.raises(ValueError, match="max_new_tokens"):
        b.submit(np.zeros(4, np.int64), 0)


def _llama():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny_config(vocab_size=128))
    m.eval()
    return m


@pytest.mark.smoke
def test_llama_paged_generate_matches_dense():
    """GQA paged route (block_gqa_attention: unexpanded kv heads, RoPE at
    timeline positions) reproduces the dense-cache decode exactly,
    including across page boundaries."""
    m = _llama()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 7)).astype(np.int64))
    with paddle.no_grad():
        dense = m.generate(ids, max_new_tokens=8).numpy()
        paged = m.generate_paged(ids, max_new_tokens=8,
                                 block_size=4).numpy()
    np.testing.assert_array_equal(dense, paged)


def test_llama_paged_batcher_token_exact():
    """The SAME PagedContinuousBatcher (model-agnostic paged-state
    protocol) serves the GQA flagship, preemption included."""
    m = _llama()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 9, 12)]
    ns = [6, 8, 5]
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=4,
                               n_pages=10, policy="ondemand",
                               compile=False)
    rids = [b.submit(p, n) for p, n in zip(prompts, ns)]
    outs = b.run_until_done()
    for rid, p, n in zip(rids, prompts, ns):
        ids = paddle.to_tensor(np.asarray(p, np.int64)[None, :])
        with paddle.no_grad():
            ref = m.generate(ids, max_new_tokens=n).numpy()[0]
        np.testing.assert_array_equal(outs[rid], ref,
                                      err_msg=f"request {rid}")
    assert b.free_page_count == b.n_pages


def test_llama_compiled_paged_step_matches_eager():
    from paddle_tpu import jit
    m = _llama()
    rng = np.random.RandomState(2)
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 6)).astype(np.int64))
    with paddle.no_grad():
        ref = m.generate_paged(ids, max_new_tokens=6, block_size=4).numpy()
        step = jit.to_static(m.paged_decode_step)
        out = m.generate_paged(ids, max_new_tokens=6, block_size=4,
                               decode_fn=step).numpy()
    np.testing.assert_array_equal(ref, out)


def test_sampled_paged_batching_runs():
    """Sampling through the paged batcher: shapes/lifecycle sane (exact
    match vs solo is not defined across interleavings of one shared rng)."""
    m = _model()
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 7)]
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               compile=False, do_sample=True,
                               temperature=0.8, top_k=20, seed=0)
    rids = [b.submit(p, 6) for p in prompts]
    outs = b.run_until_done()
    for rid, p in zip(rids, prompts):
        assert outs[rid].shape == (len(p) + 6,)
    assert b.free_page_count == b.n_pages


def test_batcher_stats():
    """Serving observability: counters reflect steps, tokens, occupancy,
    completions, and preemptions."""
    m = _model()
    rng = np.random.RandomState(6)
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               compile=False)
    rids = [b.submit(rng.randint(0, 128, (5,)), 4) for _ in range(2)]
    b.run_until_done()
    s = b.stats()
    assert s["completed_requests"] == 2
    assert s["generated_tokens"] == 8          # 2 requests x 4 tokens
    assert s["steps"] == 3                     # admission tok + 3 decode steps
    assert s["mean_active_slots"] == 2.0
    assert s["slot_utilization"] == 1.0
    assert s["tokens_per_sec"] > 0
    assert s["pending_now"] == 0 and s["active_now"] == 0


# -- chunked prefill (one executable for every prompt length) --------------

def test_chunked_prefill_token_exact_mixed_lengths():
    """Fixed-width append chunks reproduce the one-shot prefill exactly
    for prompts shorter, equal, and longer than the chunk — including a
    zero-padded tail chunk — for both families."""
    for mk in (_model, _llama):
        m = mk()
        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, 128, (s,)) for s in (3, 8, 13, 17)]
        b = PagedContinuousBatcher(m, max_batch=4, s_max=40, block_size=8,
                                   prefill_chunk=8, compile=False)
        rids = [b.submit(p, 6) for p in prompts]
        outs = b.run_until_done()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], _ref(m, p, 6),
                                          err_msg=f"{mk.__name__} {rid}")
        assert b.free_page_count == b.n_pages


def test_chunked_prefill_single_executable():
    """The point of chunking: serving many distinct prompt lengths
    compiles exactly ONE prefill executable (vs one per length on the
    unchunked path)."""
    m = _model()
    rng = np.random.RandomState(8)
    prompts = [rng.randint(0, 128, (s,)) for s in (3, 7, 9, 14)]

    def body():
        b = PagedContinuousBatcher(m, max_batch=4, s_max=40, block_size=8,
                                   prefill_chunk=8, compile=True)
        rids = [b.submit(p, 4) for p in prompts]
        outs = b.run_until_done()
        assert len(b._chunk_fn._cache) == 1, \
            list(b._chunk_fn._cache)      # one signature ever
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], _ref(m, p, 4))

    _retry_load_flake(body)


def test_chunked_prefill_with_preemption():
    """Chunked admission composes with on-demand growth + preemption
    (resume re-prefills prompt+generated through the chunk path)."""
    m = _model()
    rng = np.random.RandomState(9)
    p0 = rng.randint(0, 128, (6,))
    p1 = rng.randint(0, 128, (6,))
    b = PagedContinuousBatcher(m, max_batch=2, s_max=24, block_size=4,
                               n_pages=6, policy="ondemand",
                               prefill_chunk=4, compile=False)
    r0, r1 = b.submit(p0, 10), b.submit(p1, 10)
    outs = b.run_until_done()
    assert b.stats()["preemptions"] >= 1
    np.testing.assert_array_equal(outs[r0], _ref(m, p0, 10))
    np.testing.assert_array_equal(outs[r1], _ref(m, p1, 10))


def test_chunked_prefill_tail_clamped_to_capacity():
    """Chunk width not aligned to capacity: the tail chunk shortens
    instead of overflowing the block table (review finding)."""
    m = _model()
    rng = np.random.RandomState(10)
    # s_max=40, block_size=8 -> capacity 40; C=16: a 35-token prompt pads
    # to 48 unclamped, which would index a 6th block in a 5-block table
    p = rng.randint(0, 128, (35,))
    b = PagedContinuousBatcher(m, max_batch=1, s_max=40, block_size=8,
                               prefill_chunk=16, compile=False)
    rid = b.submit(p, 5)
    outs = b.run_until_done()
    np.testing.assert_array_equal(outs[rid], _ref(m, p, 5))
    assert b.free_page_count == b.n_pages


# -- fused admission (vLLM unified scheduling) -----------------------------

def test_fused_admission_token_exact_both_families():
    """One fused executable advances all decode slots AND one admission
    chunk per step; every request still matches its solo decode."""
    for mk in (_model, _llama):
        m = mk()
        rng = np.random.RandomState(12)
        prompts = [rng.randint(0, 128, (s,)) for s in (5, 11, 17, 8, 22)]
        b = PagedContinuousBatcher(m, max_batch=3, s_max=40, block_size=8,
                                   prefill_chunk=8, fused_admission=True,
                                   compile=False)
        rids = [b.submit(p, 6) for p in prompts]
        outs = b.run_until_done()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], _ref(m, p, 6),
                                          err_msg=f"{mk.__name__} {rid}")
        assert b.free_page_count == b.n_pages


def test_fused_admission_single_executable_and_overlap():
    """The fused step is ONE compiled executable at every occupancy and
    prompt length, and decode genuinely progresses while a prompt
    admits (total steps ~ max of the two, not their sum)."""
    m = _model()
    rng = np.random.RandomState(13)
    long_decode = rng.randint(0, 128, (4,))
    long_prompt = rng.randint(0, 128, (32,))   # 4 chunks at C=8

    def body():
        b = PagedContinuousBatcher(m, max_batch=2, s_max=48, block_size=8,
                                   prefill_chunk=8, fused_admission=True,
                                   compile=True)
        r0 = b.submit(long_decode, 12)
        b.step()                               # r0 admitted (4-tok, 1 chunk)
        r1 = b.submit(long_prompt, 4)
        outs = b.run_until_done()
        assert len(b._fused_fn._cache) == 1, list(b._fused_fn._cache)
        np.testing.assert_array_equal(outs[r0], _ref(m, long_decode, 12))
        np.testing.assert_array_equal(outs[r1], _ref(m, long_prompt, 4))
        # overlap: r0's 12 decode steps cover r1's 4 admission chunks —
        # the run fits in far fewer steps than the sequential sum (~13 vs 21)
        assert b.stats()["steps"] <= 16

    _retry_load_flake(body)


def test_fused_admission_guards():
    m = _model()
    with pytest.raises(ValueError, match="fused_admission needs"):
        PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               fused_admission=True, compile=False)
    with pytest.raises(ValueError, match="exceeds s_max"):
        PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               prefill_chunk=64, compile=False)


def test_fused_admission_abort_under_pool_pressure():
    """ondemand + fused: when a live decode needs a page and only the
    in-flight admission holds them, the admission is aborted (requeued,
    pages freed) instead of failing the step — and everything still
    finishes token-exact."""
    m = _model()
    rng = np.random.RandomState(14)
    p0 = rng.randint(0, 128, (4,))
    p1 = rng.randint(0, 128, (13,))
    # 6 pages of 4 rows: p0 admits with 2 pages and must grow to 4;
    # p1's 2-chunk admission reserves 4 — the pool cannot hold both
    # timelines (4 + 5 > 6), forcing preemption/abort mid-run
    b = PagedContinuousBatcher(m, max_batch=2, s_max=24, block_size=4,
                               n_pages=6, policy="ondemand",
                               prefill_chunk=8, fused_admission=True,
                               compile=False)
    r0 = b.submit(p0, 10)
    r1 = b.submit(p1, 4)
    outs = b.run_until_done(max_steps=300)
    assert b.stats()["preemptions"] >= 1
    np.testing.assert_array_equal(outs[r0], _ref(m, p0, 10))
    np.testing.assert_array_equal(outs[r1], _ref(m, p1, 4))
    assert b.free_page_count == b.n_pages


def test_fused_admission_capacity_divisibility_guard():
    m = _model()
    # cap = ceil(40/8)*8 = 40, C=12 does not divide it
    with pytest.raises(ValueError, match="multiple of prefill_chunk"):
        PagedContinuousBatcher(m, max_batch=2, s_max=40, block_size=8,
                               prefill_chunk=12, fused_admission=True,
                               compile=False)


# -- multi-step decode blocks (decode_block=K) -------------------------------

def test_decode_block_token_exact_vs_single_step():
    """decode_block=K runs K decode steps in ONE executable with
    on-device greedy feedback; tokens must equal the per-step engine's
    exactly — including an EOS finish and a budget (< K) truncation
    mid-block."""
    _retry_load_flake(_decode_block_body, attempts=3)


def _decode_block_body():
    m = _model()
    rng = np.random.RandomState(40)
    prompts = [rng.randint(0, 128, (n,)) for n in (7, 12, 5)]
    budgets = [9, 3, 14]               # 3 < K exercises truncation
    kw = dict(max_batch=4, s_max=32, block_size=8, compile=True)

    ref = PagedContinuousBatcher(m, **kw)
    rids = [ref.submit(p, n) for p, n in zip(prompts, budgets)]
    expected = ref.run_until_done()

    blk = PagedContinuousBatcher(m, decode_block=4, **kw)
    rids2 = [blk.submit(p, n) for p, n in zip(prompts, budgets)]
    outs = blk.run_until_done()
    for r1, r2 in zip(rids, rids2):
        np.testing.assert_array_equal(outs[r2], expected[r1])
    # the block path actually ran (a fallback-only run would also be
    # token-exact, which must not mask a dead feature)
    assert blk.stats()["decode_blocks"] > 0
    assert blk.stats()["generated_tokens"] == sum(budgets)
    assert blk.free_page_count == blk.n_pages


def test_decode_block_eos_mid_block():
    """A request hitting EOS inside a K-block is finished at the EOS
    position; the block's overshoot tokens are discarded."""
    _retry_load_flake(_decode_block_eos_body, attempts=3)


def _decode_block_eos_body():
    m = _model()
    rng = np.random.RandomState(41)
    p = rng.randint(0, 128, (9,))
    ref = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                                 eos_id=None, compile=True)
    r = ref.submit(p, 12)
    full = ref.run_until_done()[r]
    gen = full[len(p):]
    # pick the 3rd generated token as a forced EOS: it lands mid-block
    eos = int(gen[2])
    want = full[:len(p) + 3]

    blk = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                                 eos_id=eos, decode_block=4, compile=True)
    r2 = blk.submit(p, 12)
    out = blk.run_until_done()[r2]
    np.testing.assert_array_equal(out, want)
    assert blk.stats()["decode_blocks"] > 0


def test_decode_block_ondemand_pool_pressure_falls_back():
    """With a pool too small to back a whole K-block, _block_backed
    declines (never preempts) and the per-step path serves the work —
    exactness holds either way."""
    _retry_load_flake(_decode_block_pressure_body, attempts=3)


def _decode_block_pressure_body():
    m = _model()
    rng = np.random.RandomState(42)
    p0 = rng.randint(0, 128, (9,))
    p1 = rng.randint(0, 128, (9,))
    b = PagedContinuousBatcher(m, max_batch=2, s_max=24, block_size=4,
                               n_pages=7, policy="ondemand",
                               decode_block=8, compile=True)
    r0 = b.submit(p0, 8)
    r1 = b.submit(p1, 8)
    outs = b.run_until_done(max_steps=400)
    np.testing.assert_array_equal(outs[r0], _ref(m, p0, 8))
    np.testing.assert_array_equal(outs[r1], _ref(m, p1, 8))
    assert b.free_page_count == b.n_pages


def test_decode_block_guards():
    m = _model()
    with pytest.raises(ValueError, match="decode_block must be >= 2"):
        PagedContinuousBatcher(m, decode_block=1, compile=False)
    with pytest.raises(ValueError, match="greedy"):
        PagedContinuousBatcher(m, decode_block=4, do_sample=True,
                               compile=False)


def test_decode_block_composes_with_fused_admission():
    """fused_admission drains admissions through the fused executable;
    once the queue is empty its idle steps flow through _decode_tail,
    where the K-block takes over. Tokens must match the non-block fused
    engine."""
    _retry_load_flake(_decode_block_fused_body, attempts=3)


def _decode_block_fused_body():
    m = _model()
    rng = np.random.RandomState(43)
    prompts = [rng.randint(0, 128, (n,)) for n in (9, 14)]
    kw = dict(max_batch=2, s_max=32, block_size=8, prefill_chunk=8,
              fused_admission=True, compile=True)
    ref = PagedContinuousBatcher(m, **kw)
    rids = [ref.submit(p, 10) for p in prompts]
    expected = ref.run_until_done()
    blk = PagedContinuousBatcher(m, decode_block=4, **kw)
    rids2 = [blk.submit(p, 10) for p in prompts]
    outs = blk.run_until_done()
    for r1, r2 in zip(rids, rids2):
        np.testing.assert_array_equal(outs[r2], expected[r1])
    assert blk.stats()["decode_blocks"] > 0


def test_decode_block_llama_family():
    """The K-block executable is model-agnostic: the Llama paged decode
    step (GQA + RoPE through the block cache) must be token-exact under
    decode_block too — this is the composition the TPU tier runs on
    hardware (test_tpu_tier.py::test_fused_serving_on_tpu)."""
    _retry_load_flake(_decode_block_llama_body, attempts=3)


def _decode_block_llama_body():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny_config())
    m.eval()
    rng = np.random.RandomState(44)
    prompts = [rng.randint(0, 128, (n,)) for n in (9, 13)]
    kw = dict(max_batch=2, s_max=32, block_size=8, compile=True)
    ref = PagedContinuousBatcher(m, **kw)
    rids = [ref.submit(p, 8) for p in prompts]
    expected = ref.run_until_done()
    blk = PagedContinuousBatcher(m, decode_block=4, **kw)
    rids2 = [blk.submit(p, 8) for p in prompts]
    outs = blk.run_until_done()
    for r1, r2 in zip(rids, rids2):
        np.testing.assert_array_equal(outs[r2], expected[r1])
    assert blk.stats()["decode_blocks"] > 0
