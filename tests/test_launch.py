"""Launcher + elastic tests.

Reference coverage model: test/legacy_test launch tests + fleet/elastic unit
tests (SURVEY.md §2.11/2.12) — real subprocesses, single host.
"""
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.launch import (Container, KVClient, KVServer,
                                           Pod, Watcher, launch)
from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus


def test_kv_server_roundtrip():
    server = KVServer().start()
    try:
        c = KVClient(server.endpoint)
        assert c.get("missing") is None
        c.put("ep/0", "host0:1234")
        assert c.get("ep/0") == "host0:1234"
        assert c.get_all()["ep/0"] == "host0:1234"
        assert c.wait("ep/0", timeout=1) == "host0:1234"
        with pytest.raises(TimeoutError):
            c.wait("never", timeout=0.5)
    finally:
        server.stop()


def test_container_and_pod(tmp_path):
    ok = Container([sys.executable, "-c", "print('hello rank')"],
                   env={}, log_path=str(tmp_path / "log.0"), rank=0)
    bad = Container([sys.executable, "-c", "import sys; sys.exit(3)"],
                    env={}, rank=1)
    pod = Pod()
    pod.add_container(ok)
    pod.add_container(bad)
    pod.deploy()
    code = pod.join()
    assert code == 3
    assert "hello rank" in ok.logs()


def test_launch_cli_success(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import os\n"
        "print('rank', os.environ['PADDLE_TRAINER_ID'],\n"
        "      'of', os.environ['PADDLE_TRAINERS_NUM'])\n")
    code = launch(["--nproc_per_node", "2", "--log_dir", str(tmp_path),
                   str(script)])
    assert code == 0
    logs = sorted(p.name for p in tmp_path.glob("workerlog.*"))
    assert logs == ["workerlog.0", "workerlog.1"]
    assert "rank 0 of 2" in (tmp_path / "workerlog.0").read_text()


def test_launch_cli_restart_budget(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text("import sys; sys.exit(7)\n")
    t0 = time.time()
    code = launch(["--max_restarts", "1", "--log_dir", str(tmp_path),
                   str(script)])
    assert code == 7
    assert time.time() - t0 < 60


def test_watcher_detects_dead_peer():
    server = KVServer().start()
    try:
        c = KVClient(server.endpoint)
        w0 = Watcher(c, my_rank=0, nnodes=2, ttl=1.0)
        w1 = Watcher(c, my_rank=1, nnodes=2, ttl=1.0)
        w0.heartbeat()
        w1.heartbeat()
        assert w0.dead_peers() == []
        time.sleep(1.2)
        w0.heartbeat()  # rank 1 stops beating
        assert w0.dead_peers() == [1]
    finally:
        server.stop()


def test_elastic_manager_membership_and_scale():
    server = KVServer().start()
    try:
        managers = [ElasticManager(server.endpoint, "job1", r, np=3,
                                   min_np=2, max_np=4, heartbeat_ttl=1.0)
                    for r in range(3)]
        for i, m in enumerate(managers):
            m.register(f"host{i}:80")
        m0 = managers[0]
        assert m0.alive_nodes() == [0, 1, 2]
        assert not m0.need_scale()
        assert m0.status() == ElasticStatus.HOLD

        # rank 2 dies: 2 alive, within [min_np, max_np] -> RESTART (scale-in)
        time.sleep(1.2)
        managers[0].heartbeat()
        managers[1].heartbeat()
        assert m0.alive_nodes() == [0, 1]
        assert m0.need_scale()
        assert m0.status() == ElasticStatus.RESTART

        # below quorum -> HOLD for peers
        time.sleep(1.2)
        managers[0].heartbeat()
        assert m0.status() == ElasticStatus.HOLD

        assert m0.wait_for_np(1, timeout=2)
    finally:
        server.stop()


def test_launch_elastic_restarts_on_elastic_exit(tmp_path):
    """launch_elastic: elastic exit code triggers a restart; a marker file
    makes the second attempt succeed."""
    from paddle_tpu.distributed.launch.main import Context, _parse

    server = KVServer().start()
    try:
        script = tmp_path / "flaky.py"
        marker = tmp_path / "ran_once"
        script.write_text(
            "import os, sys\n"
            f"m = {str(repr(str(marker)))}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close()\n"
            "    sys.exit(101)\n"  # ELASTIC_EXIT_CODE
            "print('recovered')\n")
        args, script_args = _parse(["--max_restarts", "2",
                                    "--log_dir", str(tmp_path), str(script)])
        ctx = Context(args, script_args)
        ctx.master = server.endpoint
        mgr = ElasticManager(server.endpoint, "job-el", 0, np=1,
                             heartbeat_ttl=5.0)
        from paddle_tpu.distributed.fleet.elastic import launch_elastic
        assert launch_elastic(ctx, manager=mgr) == 0
        assert "recovered" in (tmp_path / "workerlog.0").read_text()
    finally:
        server.stop()


def test_launch_elastic_plain_failure_propagates(tmp_path):
    from paddle_tpu.distributed.launch.main import Context, _parse

    server = KVServer().start()
    try:
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(9)\n")
        args, script_args = _parse(["--max_restarts", "2", str(script)])
        ctx = Context(args, script_args)
        ctx.master = server.endpoint
        mgr = ElasticManager(server.endpoint, "job-el2", 0, np=1,
                             heartbeat_ttl=5.0)
        from paddle_tpu.distributed.fleet.elastic import launch_elastic
        assert launch_elastic(ctx, manager=mgr) == 9
    finally:
        server.stop()


def test_per_rank_log_collation(tmp_path):
    """The launcher merges per-rank workerlogs into one rank-prefixed
    collated.log (reference launcher log aggregation)."""
    import subprocess
    import sys
    script = tmp_path / "w.py"
    script.write_text(
        "import os\n"
        "print('hello from rank', os.environ['PADDLE_TRAINER_ID'], "
        "flush=True)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         str(script)],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-1500:]
    collated = (tmp_path / "logs" / "collated.log").read_text()
    assert "[rank 0] hello from rank 0" in collated
    assert "[rank 1] hello from rank 1" in collated


def test_monitor_gauges_and_peaks():
    from paddle_tpu.utils import monitor
    monitor.stat_reset("test.gauge")
    monitor.stat_update("test.gauge", 5)
    monitor.stat_update("test.gauge", 3)
    monitor.stat_update("test.gauge", -6)
    assert monitor.stat_get("test.gauge") == 2
    assert monitor.stat_peak("test.gauge") == 8
    assert monitor.get_monitor_values().get("test.gauge") == 2
    mem = monitor.sample_device_memory()
    assert isinstance(mem, dict)
    monitor.stat_reset("test.gauge")
    assert monitor.stat_get("test.gauge") == 0
