"""Tests for the native C++ runtime tier (csrc/native.cc).

Covers the TCPStore (tcp_store.h:121 analog) incl. cross-process use, the
blocking queue (data_loader.cc analog), the host tracer, and the stat
registry — plus their integration points (profiler RecordEvent, DataLoader
buffer reader).
"""
import multiprocessing as mp
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import native


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_native_extension_builds():
    # the C++ extension must actually be present in this image (the pure-
    # Python fallback exists for degraded environments only)
    assert native.native_available(), native.native_error()


def test_store_set_get_add():
    port = _free_port()
    s = native.TCPStore("127.0.0.1", port, is_master=True, world_size=1)
    s.set("k", b"v1")
    assert s.get("k") == b"v1"
    s.set("k", "v2")  # str coerced to bytes
    assert s.get("k") == b"v2"
    assert s.add("ctr", 3) == 3
    assert s.add("ctr", -1) == 2
    assert s.check("ctr")
    assert not s.check("nope")
    assert sorted(s.list_keys("")) == ["ctr", "k"]
    s.delete_key("k")
    assert not s.check("k")
    s.close()


def test_store_blocking_get_timeout():
    port = _free_port()
    s = native.TCPStore("127.0.0.1", port, is_master=True, world_size=1)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        s.get("missing", timeout=0.3)
    assert time.monotonic() - t0 >= 0.25
    s.close()


def test_store_blocking_get_wakes_on_set():
    port = _free_port()
    s = native.TCPStore("127.0.0.1", port, is_master=True, world_size=1)
    s2 = native.TCPStore("127.0.0.1", port, is_master=False, world_size=1)
    result = {}

    def waiter():
        result["v"] = s2.get("late", timeout=5.0)

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.2)
    s.set("late", b"arrived")
    th.join(timeout=5)
    assert result.get("v") == b"arrived"
    s2.close()
    s.close()


def _store_child(port, rank, out_q):
    try:
        st = native.TCPStore("127.0.0.1", port, is_master=False,
                             world_size=3, timeout=10.0)
        st.set(f"rank{rank}", str(rank).encode())
        st.barrier("init", world_size=3, timeout=10.0)
        got = sorted(st.get(f"rank{r}") for r in range(3))
        out_q.put((rank, got))
        st.close()
    except Exception as e:  # pragma: no cover
        out_q.put((rank, repr(e)))


def test_store_cross_process_barrier():
    """Rank-0 hosts the store; two child processes rendezvous through it —
    the bootstrap pattern of init_parallel_env (parallel.py:943 analog)."""
    port = _free_port()
    master = native.TCPStore("127.0.0.1", port, is_master=True, world_size=3)
    master.set("rank0", b"0")
    ctx = mp.get_context("spawn")
    out_q = ctx.Queue()
    procs = [ctx.Process(target=_store_child, args=(port, r, out_q))
             for r in (1, 2)]
    for p in procs:
        p.start()
    master.barrier("init", world_size=3, timeout=30.0)
    results = [out_q.get(timeout=30) for _ in procs]
    for p in procs:
        p.join(timeout=10)
    for rank, got in results:
        assert got == [b"0", b"1", b"2"], (rank, got)
    master.close()


def test_blocking_queue_fifo_and_close():
    q = native.BlockingQueue(4)
    for i in range(4):
        q.push(i)
    assert q.size() == 4
    assert [q.pop() for _ in range(4)] == [0, 1, 2, 3]
    q.close()
    with pytest.raises(StopIteration):
        q.pop(timeout=0.5)
    q.release()


def test_blocking_queue_capacity_blocks_producer():
    q = native.BlockingQueue(1)
    q.push("a")
    assert q.push("b", timeout=0.2) is False  # full
    assert q.pop() == "a"
    assert q.push("b", timeout=0.2) is True
    q.close()
    q.release()


def test_blocking_queue_threaded_producer_consumer():
    q = native.BlockingQueue(2)
    n = 50

    def produce():
        for i in range(n):
            q.push(np.full((4,), i))
        q.close()

    th = threading.Thread(target=produce)
    th.start()
    got = []
    while True:
        try:
            got.append(int(q.pop(timeout=10.0)[0]))
        except StopIteration:
            break
    th.join()
    assert got == list(range(n))
    q.release()


def test_tracer_spans():
    native.tracer_clear()
    native.tracer_enable(True)
    try:
        i = native.tracer_begin("outer")
        j = native.tracer_begin("inner")
        native.tracer_end(j)
        native.tracer_end(i)
        native.tracer_instant("mark")
        evs = native.tracer_drain()
    finally:
        native.tracer_enable(False)
    names = [e[0] for e in evs]
    assert set(names) == {"outer", "inner", "mark"}
    by = {e[0]: e for e in evs}
    assert by["inner"][2] >= by["outer"][2]          # starts nested
    assert by["inner"][3] <= by["outer"][3]          # ends nested
    assert by["mark"][2] == by["mark"][3]            # instant
    assert native.tracer_drain() == []               # drained


def test_tracer_disabled_is_noop():
    native.tracer_enable(False)
    i = native.tracer_begin("skipped")
    native.tracer_end(i)
    assert native.tracer_drain() == []


def test_stats_current_and_peak():
    native.stat_reset("test_mem")
    assert native.stat_update("test_mem", 100) == 100
    assert native.stat_update("test_mem", 50) == 150
    assert native.stat_update("test_mem", -120) == 30
    cur, peak = native.stat_get("test_mem")
    assert (cur, peak) == (30, 150)
    assert "test_mem" in native.stat_all()
    native.stat_reset("test_mem")
    assert native.stat_get("test_mem") == (0, 0)


def test_profiler_uses_native_tracer():
    import paddle_tpu.profiler as profiler
    with profiler.Profiler(targets=[profiler.ProfilerTarget.CPU]) as prof:
        with profiler.RecordEvent("native_span"):
            time.sleep(0.01)
    names = [e.name for e in prof.events]
    assert "native_span" in names
    ev = next(e for e in prof.events if e.name == "native_span")
    assert ev.end_ns - ev.start_ns >= 5_000_000  # >= 5ms


def test_dataloader_buffer_reader():
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return np.full((3,), i, dtype=np.float32), np.int64(i)

        def __len__(self):
            return 12

    loader = DataLoader(DS(), batch_size=4, shuffle=False, num_workers=0,
                        use_buffer_reader=True)
    seen = []
    for x, y in loader:
        assert isinstance(x, paddle.Tensor)
        seen.extend(np.asarray(y._data).tolist())
    assert seen == list(range(12))
    # second epoch works (fresh buffer thread)
    assert sum(1 for _ in loader) == 3


def test_dataloader_buffer_reader_propagates_worker_error():
    from paddle_tpu.io import DataLoader, Dataset

    class Bad(Dataset):
        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom")
            return np.zeros(2)

        def __len__(self):
            return 8

    loader = DataLoader(Bad(), batch_size=2, num_workers=0,
                        use_buffer_reader=True)
    with pytest.raises(ValueError, match="boom"):
        for _ in loader:
            pass
