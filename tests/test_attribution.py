"""Cost attribution plane: per-request waterfalls, the goodput/waste
ledger, and streaming anomaly findings
(paddle_tpu.observability.{waterfall,ledger,anomaly}).

The acceptance bars:
  * a gateway request reconstructs into a COMPLETE waterfall whose
    per-segment self times tile the root span exactly — the invariant
    the ledger's chip-second balance rides on (charged == summed span
    time within 1%);
  * a torn fleet spool (crashed rank, half-written tail line, missing
    root span) degrades to PARTIAL waterfalls flagged ``incomplete`` —
    never an exception;
  * on the shared-prefix workload the ledger reproduces the round-13
    story from traces alone: prefill critical-path time shrinks
    consistent with the measured prefix hit rate, and goodput_frac
    strictly improves cache-on vs cache-off (pad waste priced out);
  * the failover drill's duplicated re-prefill is priced as
    ``waste.requeue_recompute`` and the streaming detector names the
    SURVIVOR replica in a ``tpot_spike`` finding (the remediator's
    input signal).

Everything is single-threaded and deterministic modulo wall-clock
noise; timing assertions use wide ratio bounds.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.gateway import Gateway
from paddle_tpu.inference.serving import (ContinuousBatcher,
                                          PagedContinuousBatcher)
from paddle_tpu.observability import (AnomalyDetector, GatewayProbe,
                                      build_waterfalls,
                                      critical_path_summary, get_recorder,
                                      ledger_from_waterfalls,
                                      render_waterfall,
                                      waterfalls_from_fleet)
from paddle_tpu.observability.export import snapshot_series
from paddle_tpu.resilience import arm_scenario, disarm

pytestmark = pytest.mark.attr


@pytest.fixture(autouse=True)
def _disarm():
    disarm()
    yield
    disarm()


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 128, size=n).astype(np.int64) for n in sizes]


def _batcher(lm, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("s_max", 64)
    return ContinuousBatcher(lm, compile=False, **kw)


def _trace_mark():
    """Recorder watermark: trace ids recorded BEFORE the workload."""
    return set(get_recorder().trace_ids())


def _waterfalls_since(pre_ids, gids):
    """Waterfalls for exactly these gateway requests: traces newer than
    the watermark, matched back by the root span's gid tag."""
    spans = [s for s in get_recorder().spans()
             if s.trace_id not in pre_ids]
    return [w for w in build_waterfalls(spans) if w.gid in set(gids)]


# -- waterfall reconstruction -------------------------------------------------

def test_waterfall_reconstructs_complete_request(lm):
    prompts = _prompts(3, (5, 9, 7))
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    pre = _trace_mark()
    gids = [gw.submit(p, 6, tenant="wf") for p in prompts]
    gw.run_until_done()
    wfs = _waterfalls_since(pre, gids)
    assert len(wfs) == len(gids)
    for wf in wfs:
        assert not wf.incomplete
        assert wf.tenant == "wf" and wf.gid in gids
        # the serving phases a complete request must traverse
        assert {"queue", "admit", "prefill", "decode"} <= set(wf.phases)
        path_names = [h["name"] for h in wf.critical_path]
        assert {"queue", "prefill", "decode"} <= set(path_names)
        # THE invariant: segment self times tile the root span exactly
        assert sum(s.self_s for s in wf.segments) == \
            pytest.approx(wf.total_s, rel=1e-9)
        assert wf.ttft_s > 0.0 and wf.tpot_s is not None
        assert wf.replicas and wf.replicas[0] in ("r0", "r1")
        # the renderer holds together on real data
        text = render_waterfall(wf)
        assert "critical path:" in text and "prefill" in text


def test_ledger_balances_chip_seconds_and_publishes(lm):
    prompts = _prompts(4, (6, 8, 5, 9))
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    pre = _trace_mark()
    gids = [gw.submit(p, 5, tenant=t, session_id=t)
            for p, t in zip(prompts, ("acme", "acme", "zeta", "zeta"))]
    gw.run_until_done()
    wfs = _waterfalls_since(pre, gids)
    led = ledger_from_waterfalls(wfs)
    s = led.summary()
    # charged chip-seconds == summed span time within 1% (here: exact,
    # every trace is complete so self times tile each root span)
    wall = sum(w.total_s for w in wfs)
    assert abs(s["charged_seconds"] - wall) <= 0.01 * wall
    assert 0.0 < s["chip_seconds"] <= s["charged_seconds"]
    assert 0.0 < s["goodput_frac"] <= 1.0
    assert set(s["by_tenant"]) == {"acme", "zeta"}
    assert {"admit", "prefill", "decode"} <= set(s["by_phase"])
    led.publish()
    series = snapshot_series()
    names = {x["name"] for x in series}
    assert {"ledger.goodput_frac", "ledger.waste_seconds",
            "ledger.chip_seconds"} <= names
    cats = {x["labels"]["category"] for x in series
            if x["name"] == "ledger.waste_seconds"}
    assert {"bucket_pad", "requeue_recompute", "evicted_prefix_recompute",
            "speculation_rejected", "recompile"} <= cats
    tenants = {x["labels"]["tenant"] for x in series
               if x["name"] == "ledger.chip_seconds"}
    assert {"acme", "zeta"} <= tenants


def test_torn_fleet_spool_yields_partial_waterfalls(tmp_path):
    """A crashed rank's spool — root span never closed (absent), decode
    span missing, half-written tail line — must degrade to a partial
    waterfall flagged ``incomplete``, never raise."""
    def span(sid, parent, name, t0, t1, **tags):
        return {"kind": "span", "t": t0, "t_end": t1, "trace_id": "T1",
                "span_id": sid, "parent_id": parent, "name": name,
                "start_ns": int(t0 * 1e9), "end_ns": int(t1 * 1e9),
                "duration_s": t1 - t0, "tags": tags}

    lines = [json.dumps({"kind": "meta", "rank": 0, "host": "h0"})]
    # root "gateway.request" was still open at crash time -> no record;
    # the queue/admit/prefill spans reference the missing parent
    lines += [json.dumps(span("q1", "root1", "queue", 10.0, 10.2)),
              json.dumps(span("a1", "root1", "admit", 10.2, 10.9,
                              replica="r0")),
              json.dumps(span("p1", "a1", "prefill", 10.3, 10.7,
                              prompt_tokens=32, prefix_hit=0))]
    torn = json.dumps(span("d1", "a1", "decode", 10.7, 11.0))[:37]
    with open(tmp_path / "rank00000.jsonl", "w") as fh:
        fh.write("\n".join(lines) + "\n" + torn)

    wfs = waterfalls_from_fleet(str(tmp_path))
    assert len(wfs) == 1
    wf = wfs[0]
    assert wf.incomplete                      # missing root + torn tail
    assert {"queue", "admit", "prefill"} <= set(wf.phases)
    assert "decode" not in wf.phases          # the torn line dropped
    assert wf.total_s == pytest.approx(0.9, rel=1e-6)  # torn decode gone
    # downstream consumers stay well-defined on partial data
    led = ledger_from_waterfalls(wfs)
    assert led.summary()["incomplete"] == 1
    assert led.chip_s > 0.0
    assert "[INCOMPLETE]" in render_waterfall(wf)


# -- the round-13 story, reproduced from traces alone -------------------------

def test_shared_prefix_goodput_and_prefill_shrink_cache_on_vs_off(lm):
    """Two identically-driven paged gateways, radix prefix cache on vs
    off. From the traces alone the ledger must show (a) prefill
    critical-path time shrinking consistent with the measured hit rate
    and (b) goodput_frac strictly improving — cache-on admissions land
    on exact pow2 rungs (zero pad) while cache-off pays bucket_pad."""
    rng = np.random.RandomState(7)
    sys_prompts = [rng.randint(0, 128, (80,)).astype(np.int64)  # 10 blocks
                   for _ in range(2)]
    tails = [rng.randint(0, 128, (8 if i % 2 else 16,)).astype(np.int64)
             for i in range(8)]
    warm_tails = [rng.randint(0, 128, (n,)).astype(np.int64)
                  for n in (8, 8, 16)]

    stats = {}
    for label, cached in (("off", False), ("on", True)):
        gw = Gateway(policy="affinity")
        # ONE replica: affinity load-spill to a cold peer would silently
        # dilute the hit rate; n_pages sized so the measured window
        # never evicts — every measured hit is the full 80-row prefix
        gw.add_replica("r0", PagedContinuousBatcher(
            lm, max_batch=4, s_max=112, block_size=8, n_pages=256,
            compile=False, prefix_cache=cached, prompt_buckets="pow2"))
        # warm: per system prompt, one cold full prefill (seeds the
        # radix tree) then one suffix admission at EACH measured tail
        # rung — every prefill shape the measured window uses compiles
        # here, outside the clock
        for si, sysp in enumerate(sys_prompts):
            for wt in warm_tails:
                gw.submit(np.concatenate([sysp, wt]), 4,
                          tenant="warm", session_id=f"s{si}")
        gw.run_until_done()
        pre = set(get_recorder().trace_ids())
        gids = [gw.submit(np.concatenate([sys_prompts[i % 2], t]), 6,
                          tenant="r13", session_id=f"s{i % 2}")
                for i, t in enumerate(tails)]
        gw.run_until_done()
        spans = [s for s in get_recorder().spans()
                 if s.trace_id not in pre]
        wfs = [w for w in build_waterfalls(spans) if w.tenant == "r13"]
        assert len(wfs) == len(gids) and not any(w.incomplete for w in wfs)
        stats[label] = {
            "led": ledger_from_waterfalls(wfs),
            "cp": critical_path_summary(wfs),
            "hit": sum(w.prefix_hit_tokens for w in wfs),
            "prompt": sum(w.prompt_tokens for w in wfs),
        }

    hit_rate = stats["on"]["hit"] / stats["on"]["prompt"]
    # 80 cached rows of each 88/96-row prompt — the round-13 headline
    # hit rate (0.87), reproduced from the prefill spans' tags alone
    assert hit_rate == pytest.approx(640 / 736)
    assert stats["off"]["hit"] == 0
    # (a) prefill critical-path shrink consistent with the hit rate:
    # cache-on computes <= (1 - hit_rate) of the rows; demand at least
    # ~a third of that saving on the clock — the rest is fixed
    # per-admission dispatch overhead, which dominates at this tiny
    # model scale (bench_gateway shows the full-size shrink)
    pf_on = stats["on"]["cp"]["prefill"]
    pf_off = stats["off"]["cp"]["prefill"]
    assert pf_on < pf_off * (1.0 - 0.3 * hit_rate), (pf_on, pf_off,
                                                     hit_rate)
    # (b) goodput strictly improves: cache-on suffixes land on exact
    # rungs (8/16 -> zero pad) while cache-off pads 88/96 -> 112
    led_on, led_off = stats["on"]["led"], stats["off"]["led"]
    assert led_off.waste["bucket_pad"] > 0.0
    assert led_on.waste["bucket_pad"] == 0.0
    assert led_on.goodput_frac > led_off.goodput_frac


# -- failover: waste pricing + anomaly naming the survivor --------------------

def test_failover_prices_requeue_waste_and_anomaly_names_survivor(lm):
    """The replica-death drill, read back through the attribution plane:
    total charged chip-seconds balance the span record within 1%, the
    survivor's duplicated re-prefill is priced as
    ``waste.requeue_recompute``, and the ONLINE detector (GatewayProbe)
    emits a tpot_spike finding naming the survivor — whose step time
    jumps when it absorbs the dead replica's re-prefills."""
    prompts = _prompts(6, (5, 9, 7, 11))
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    probe = GatewayProbe(gw, AnomalyDetector(threshold=4.0,
                                             min_samples=6))
    pre = _trace_mark()
    gids = [gw.submit(p, 10) for p in prompts]
    arm_scenario("seed=0; serving.step:transient_error:after=6,count=3")
    for _ in range(1000):
        if not gw._has_work():
            break
        gw.step()
    probe.close()
    alive = [r for r in gw.pool.replicas() if r.alive]
    assert len(alive) == 1
    survivor = alive[0].name
    wfs = _waterfalls_since(pre, gids)
    led = ledger_from_waterfalls(wfs)
    # chip-second balance holds through the failover: every interrupted
    # span was closed (interrupted=1), so self times still tile roots
    wall = sum(w.total_s for w in wfs)
    assert abs(led.charged_s - wall) <= 0.01 * wall
    assert led.waste["requeue_recompute"] > 0.0
    assert sum(w.requeue_overhead_s for w in wfs) > 0.0
    spikes = [f for f in probe.findings if f.kind == "tpot_spike"
              and f.detail["key"] == survivor]
    assert spikes, (survivor,
                    [f.to_dict() for f in probe.findings])
    # findings are fleet-typed: the remediator consumes one format
    d = spikes[0].to_dict()
    assert d["kind"] == "tpot_spike" and d["detail"]["score"] >= 4.0


# -- detector unit behavior ---------------------------------------------------

def test_anomaly_detector_streaming_unit():
    det = AnomalyDetector(threshold=6.0, min_samples=8, window=64)
    # warmup: even a 100x value must NOT fire before min_samples
    assert det.observe("tpot", "r0", 100.0) is None
    for _ in range(7):
        assert det.observe("tpot", "r0", 1.0) is None
    # in-family samples never fire; the early outlier is median-immune
    assert det.observe("tpot", "r0", 1.04) is None
    f = det.observe("tpot", "r0", 5.0)
    assert f is not None and f.kind == "tpot_spike"
    assert f.detail["key"] == "r0" and f.detail["score"] >= 6.0
    assert f.skew_s == pytest.approx(4.0, abs=0.1)
    # series are independent: a fresh key restarts its warmup
    assert det.observe("tpot", "r1", 5.0) is None
    assert det.baseline("tpot", "r0")["median"] == pytest.approx(1.0,
                                                                 abs=0.1)
    assert [x.seq for x in det.findings] == [1]


# -- TP member attribution (satellite) ----------------------------------------

class _FakeShardGroup:
    """Duck-typed distributed.mesh.ShardGroup: 2 healthy members."""
    name = "tp0"
    degree = 2
    members = ["tp0/tensor0", "tp0/tensor1"]
    failed_members: list = []

    def heartbeat(self):
        return None

    def describe(self):
        return {"name": self.name, "members": list(self.members)}


def test_tp_member_labels_in_metrics_and_span_baggage(lm):
    b0 = _batcher(lm)
    b0.shard_group = _FakeShardGroup()
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", b0)
    pre = _trace_mark()
    gids = [gw.submit(p, 4) for p in _prompts(9, (5, 7))]
    gw.run_until_done()
    # per-member step-time attribution: one observation per HEALTHY
    # member per step, labelled {replica, member}
    pairs = {(x["labels"]["replica"], x["labels"]["member"])
             for x in snapshot_series()
             if x["name"] == "replica.step_seconds"}
    assert {("r0", "tp0/tensor0"), ("r0", "tp0/tensor1")} <= pairs
    # span baggage: admits carry the group + member list so waterfalls
    # show WHICH shards a request rode on
    wfs = _waterfalls_since(pre, gids)
    admits = [s for w in wfs for s in w.segments if s.name == "admit"]
    assert admits
    for seg in admits:
        assert seg.tags["tp_group"] == "tp0"
        assert seg.tags["tp_members"] == "tp0/tensor0,tp0/tensor1"
        assert seg.tags["replica"] == "r0"


def test_plain_replica_member_label_falls_back_to_replica_name(lm):
    gw = Gateway(policy="least_loaded")
    gw.add_replica("solo", _batcher(lm))
    gw.submit(_prompts(11, (6,))[0], 3)
    gw.run_until_done()
    pairs = {(x["labels"]["replica"], x["labels"]["member"])
             for x in snapshot_series()
             if x["name"] == "replica.step_seconds"}
    assert ("solo", "solo") in pairs
