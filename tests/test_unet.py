"""Diffusion UNet family (models/unet.py — the SD kernel mix as a
first-class model: time-conditioned UNet, DDPM objective, DDIM sampler).
Coverage model: the family must be trainable end to end, conditioning
must matter, and the sampler must run off one static-shape forward.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (UNetModel, ddim_sample, ddpm_loss,
                               unet_tiny_config)


def _model(**over):
    paddle.seed(0)
    return UNetModel(unet_tiny_config(**over))


def test_forward_shapes_and_time_conditioning():
    m = _model()
    m.eval()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 3, 16, 16).astype(np.float32))
    t1 = paddle.to_tensor(np.array([10, 10], np.int64))
    t2 = paddle.to_tensor(np.array([900, 900], np.int64))
    with paddle.no_grad():
        o1 = m(x, t1)
        o2 = m(x, t2)
    assert list(o1.shape) == [2, 3, 16, 16]
    # the timestep embedding must actually steer the prediction
    assert np.abs(o1.numpy() - o2.numpy()).max() > 1e-4


def test_cross_attention_context_matters():
    m = _model(context_dim=24)
    m.eval()
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(2, 3, 16, 16).astype(np.float32))
    t = paddle.to_tensor(np.array([5, 5], np.int64))
    c1 = paddle.to_tensor(rng.randn(2, 7, 24).astype(np.float32))
    c2 = paddle.to_tensor(rng.randn(2, 7, 24).astype(np.float32))
    with paddle.no_grad():
        o1 = m(x, t, c1)
        o2 = m(x, t, c2)
    assert np.abs(o1.numpy() - o2.numpy()).max() > 1e-4


def test_ddpm_training_reduces_loss():
    from paddle_tpu import jit, optimizer
    m = _model()
    opt = optimizer.AdamW(learning_rate=3e-4, parameters=m.parameters())
    step = jit.TrainStep(lambda x, t, n: ddpm_loss(m, x, t, n), opt)
    rng = np.random.RandomState(2)
    x = paddle.to_tensor(rng.randn(2, 3, 16, 16).astype(np.float32))
    t = paddle.to_tensor(rng.randint(0, 1000, (2,)).astype(np.int64))
    n = paddle.to_tensor(rng.randn(2, 3, 16, 16).astype(np.float32))
    losses = [float(step(x, t, n)._data) for _ in range(6)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


@pytest.mark.smoke  # the diffusion-family smoke representative (light)
def test_ddim_sampler_shapes():
    m = _model()
    m.eval()
    out = ddim_sample(m, (1, 3, 16, 16), num_steps=4)
    assert list(out.shape) == [1, 3, 16, 16]
    assert np.isfinite(out.numpy()).all()


def test_grads_reach_every_parameter():
    """Skip connections + time MLP + attention: one backward touches the
    whole tree (a dead branch would silently undertrain)."""
    m = _model(context_dim=16)
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(1, 3, 16, 16).astype(np.float32))
    t = paddle.to_tensor(np.array([42], np.int64))
    n = paddle.to_tensor(rng.randn(1, 3, 16, 16).astype(np.float32))
    ctx = paddle.to_tensor(rng.randn(1, 4, 16).astype(np.float32))
    loss = ddpm_loss(m, x, t, n, context=ctx)
    loss.backward()
    missing = [name for name, p in m.named_parameters()
               if p.grad is None]
    assert not missing, missing


def test_data_parallel_unet_step():
    """DP over the 8-device CPU mesh: batch-sharded DDPM step compiles."""
    from paddle_tpu.distributed.auto_parallel import (ProcessMesh, Replicate,
                                                      Shard, shard_tensor)
    mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
    m = _model()
    rng = np.random.RandomState(4)
    x = shard_tensor(
        paddle.to_tensor(rng.randn(8, 3, 16, 16).astype(np.float32)),
        mesh, [Shard(0)])
    t = paddle.to_tensor(rng.randint(0, 1000, (8,)).astype(np.int64))
    n = paddle.to_tensor(rng.randn(8, 3, 16, 16).astype(np.float32))
    loss = ddpm_loss(m, x, t, n)
    loss.backward()
    assert np.isfinite(float(loss))
