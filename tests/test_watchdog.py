"""Collective desync watchdog (comm_task_manager.cc analog).

Unit-level: two watchdog instances over one shared store simulate two
ranks; the detector must flag a straggler (peer advanced) and a
mismatched collective (same seq, different op), poison later entries,
and stay silent for healthy lockstep progress.
"""
import time

import numpy as np
import pytest

from paddle_tpu.distributed.watchdog import CollectiveWatchdog, DesyncError


class _DictStore:
    def __init__(self):
        self.d = {}

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self.d[key] = value

    def get(self, key, timeout=None):
        if key not in self.d:
            raise KeyError(key)
        return self.d[key]


def _pair(timeout=0.2):
    store = _DictStore()
    a = CollectiveWatchdog(store, 0, 2, timeout=timeout, poll=999)
    b = CollectiveWatchdog(store, 1, 2, timeout=timeout, poll=999)
    return store, a, b


def test_lockstep_progress_is_clean():
    _, a, b = _pair()
    for i in range(3):
        a.enter("all_reduce", "(4,):float32")
        b.enter("all_reduce", "(4,):float32")
        assert a.check_once() is None
        assert b.check_once() is None
        a.exit()
        b.exit()


def test_straggler_detected():
    """Rank 0 stuck inside seq 1 while rank 1 advanced to seq 3."""
    _, a, b = _pair(timeout=0.05)
    a.enter("all_reduce", "x")
    for _ in range(3):
        b.enter("all_reduce", "x")
        b.exit()
    time.sleep(0.08)
    report = a.check_once()
    assert report is not None and report["kind"] == "stuck"
    assert report["peers_ahead"] == {1: 3}
    # later collectives on the stuck rank surface the diagnosis as an error
    a._inside = False
    with pytest.raises(DesyncError, match="stuck"):
        a.enter("all_reduce", "x")


def test_mismatched_collective_detected_immediately():
    """Same seq, different op: program divergence flags without waiting
    for the timeout."""
    _, a, b = _pair(timeout=999)
    a.enter("all_reduce", "(4,):float32")
    b.enter("broadcast", "(4,):float32")
    report = a.check_once()
    assert report is not None and report["kind"] == "mismatch"
    assert report["peer_op"] == "broadcast"


def test_spec_difference_tolerated():
    """Same op, different tensor spec is NOT a desync: ragged
    alltoall_single legitimately ships different shapes per rank."""
    _, a, b = _pair(timeout=999)
    a.enter("all_reduce", "(4,):float32")
    b.enter("all_reduce", "(8,):float32")
    assert a.check_once() is None


def test_send_recv_asymmetry_tolerated():
    """P2P pairs are different ops on purpose — no mismatch flag."""
    _, a, b = _pair(timeout=999)
    a.enter("send", "(4,):float32")
    b.enter("recv", "(4,):float32")
    assert a.check_once() is None
    assert b.check_once() is None


def test_dead_rank_detected():
    """The canonical hang: a peer frozen BEHIND (dead / never arrived)
    while this rank waits inside the collective past the timeout."""
    _, a, b = _pair(timeout=0.05)
    b.enter("all_reduce", "x")
    b.exit()                      # b died after seq 1
    a.enter("all_reduce", "x")
    a.exit()
    a.enter("all_reduce", "x")    # a at seq 2, b frozen at seq 1
    time.sleep(0.08)
    report = a.check_once()
    assert report is not None and report["kind"] == "stuck"
    assert report["peers_behind"] == {1: 1}


def test_all_ranks_slow_is_reported_not_poisoned():
    """Everyone inside the same collective past the timeout: visibility
    report only — a big transfer must not be killed."""
    store, a, b = _pair(timeout=0.05)
    seen = []
    a.on_desync = seen.append
    a.enter("all_reduce", "x")
    b.enter("all_reduce", "x")
    time.sleep(0.08)
    assert a.check_once() is None
    assert seen and seen[0]["kind"] == "slow"
    a.exit()
    a.enter("all_reduce", "x")  # NOT poisoned


def test_collective_entry_points_call_watchdog(monkeypatch):
    """The decorated collectives publish through an armed watchdog."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import collective, watchdog

    store = _DictStore()
    wd = CollectiveWatchdog(store, 0, 1, timeout=999, poll=999)
    monkeypatch.setattr(watchdog, "_ACTIVE", [wd])
    # single-controller collectives take rank-stacked tensors (dim0 == 8)
    t = paddle.to_tensor(np.ones((8, 4), np.float32))
    collective.all_reduce(t)
    import json
    rec = json.loads(store.d["collective_wd/0"].decode())
    assert rec["op"] == "all_reduce" and rec["done"] is True
    assert rec["seq"] == 1


def test_stale_attempt_peer_benign_then_escalates():
    """Pod-incarnation filtering (round 4): a peer whose record carries an
    older attempt is benign while it could still be restarting — but if it
    NEVER republishes, the 3x-timeout grace expires and it escalates into
    a stuck report (measured from the un-re-armed enter time, so the SLOW
    branch's re-arm cannot push the horizon away forever)."""
    store = _DictStore()
    old = CollectiveWatchdog(store, 1, 2, timeout=0.3, poll=999, attempt=0)
    old.enter("all_reduce", "x")   # rank 1 publishes under attempt 0...
    old.stop()                     # ...and dies without republishing
    a = CollectiveWatchdog(store, 0, 2, timeout=0.3, poll=999, attempt=1)
    a.enter("all_reduce", "x")
    seen = []
    a.on_desync = seen.append
    time.sleep(0.4)                # > timeout, well under 3x=0.9: benign
    assert a.check_once() is None
    assert seen and seen[-1]["kind"] == "slow"
    time.sleep(0.7)                # past 3x timeout since enter
    report = a.check_once()
    assert report is not None and report["kind"] == "stuck", report
    assert report["peers_stale_attempt"] == [1]
    assert 1 in report["peers_missing"]


def test_poison_write_is_lock_guarded_against_reset_race():
    """Regression (CC404): ``check_once`` runs on the watchdog thread
    and used to write ``_poison`` bare; ``reset()`` read-and-clears it
    under ``_lock`` on the app thread, so a report could resurrect one
    reset() had just cleared. The write now happens under the lock —
    proven here by interposing on the instance lock and recording
    whether it was held at the moment ``_poison`` was assigned."""
    import threading

    _, a, b = _pair(timeout=999)
    a.enter("all_reduce", "x")
    b.enter("broadcast", "x")

    held_at_write = []

    class _SpyLock:
        def __init__(self, inner):
            self._inner = inner

        def __enter__(self):
            self._inner.acquire()
            return self

        def __exit__(self, *exc):
            self._inner.release()
            return False

    spy = _SpyLock(threading.Lock())

    orig_setattr = CollectiveWatchdog.__setattr__

    def spying_setattr(self_, name, value):
        if name == "_poison" and value is not None:
            held_at_write.append(spy._inner.locked())
        orig_setattr(self_, name, value)

    a._lock = spy
    CollectiveWatchdog.__setattr__ = spying_setattr
    try:
        report = a.check_once()
    finally:
        CollectiveWatchdog.__setattr__ = orig_setattr
    assert report is not None and report["kind"] == "mismatch"
    assert held_at_write == [True], \
        "_poison written without holding _lock (reset() race reopened)"
    # and the poisoned state still round-trips through reset()
    with pytest.raises(DesyncError):
        a.enter("all_reduce", "x")
    assert a.reset() == report
    a.enter("all_reduce", "x")  # clean after reset


def test_watchdog_source_is_cc404_clean():
    """The static rule that found the race keeps guarding the fix."""
    import os

    from paddle_tpu.analysis import concurrency
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu", "distributed",
        "watchdog.py")
    with open(src) as fh:
        fs = concurrency.analyze_source(fh.read(), "watchdog.py")
    assert "CC404" not in {f.rule for f in fs}
