"""Multi-process E2E: real ranks, real jax.distributed world (VERDICT #3).

Uses the launch CLI to spawn 2 processes on CPU; each forms the world via
init_parallel_env (PJRT distributed runtime + TCPStore control plane), runs
every eager collective across ranks (Gloo transport on CPU — ICI on TPU),
and round-trips a sharded checkpoint. Reference model:
test/collective/test_communication_api_base.py:59-74.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "helpers", "mp_worker.py")


def _launch_env():
    """Child env: 1 CPU device per process, axon sitecustomize stripped
    (a wedged TPU relay must not hang the CPU-only world)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)  # conftest's 8-device forcing: 1 dev/proc here
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    return env


@pytest.mark.quick
def test_two_rank_world(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         WORKER, ckpt_dir],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=_launch_env())
    logs = ""
    log_root = tmp_path / "logs"
    if log_root.exists():
        for f in sorted(log_root.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()
    assert proc.returncode == 0, (
        f"launch failed rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}\nlogs:{logs[-4000:]}")
    for r in range(2):
        assert f"MPWORKER_OK rank={r}/2" in logs, (
            f"rank {r} did not finish\n{logs[-4000:]}")
