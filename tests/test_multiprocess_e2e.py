"""Multi-process E2E: real ranks, real jax.distributed world (VERDICT #3).

Uses the launch CLI to spawn 2 processes on CPU; each forms the world via
init_parallel_env (PJRT distributed runtime + TCPStore control plane), runs
every eager collective across ranks (Gloo transport on CPU — ICI on TPU),
and round-trips a sharded checkpoint. Reference model:
test/collective/test_communication_api_base.py:59-74.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "helpers", "mp_worker.py")


def _launch_env():
    """Child env: 1 CPU device per process, axon sitecustomize stripped
    (a wedged TPU relay must not hang the CPU-only world)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # auto-arm the collective watchdog from env (the worker re-arms
    # manually too, exercising the disable-then-enable path)
    env["PADDLE_COLLECTIVE_WATCHDOG"] = "1"
    env.pop("XLA_FLAGS", None)  # conftest's 8-device forcing: 1 dev/proc here
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    return env


def _run_launch(tmp_path, script, *args, launch_args=()):
    """Launch `script` across 2 ranks; return (proc, merged worker logs)."""
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         *launch_args, script, *args],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=_launch_env())
    logs = ""
    log_root = tmp_path / "logs"
    if log_root.exists():
        for f in sorted(log_root.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()
    return proc, logs


def test_two_rank_world(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    proc, logs = _run_launch(tmp_path, WORKER, ckpt_dir)
    assert proc.returncode == 0, (
        f"launch failed rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}\nlogs:{logs[-4000:]}")
    for r in range(2):
        assert f"MPWORKER_OK rank={r}/2" in logs, (
            f"rank {r} did not finish\n{logs[-4000:]}")


PIPE_WORKER = os.path.join(REPO, "tests", "helpers", "mp_pipeline_worker.py")


def test_two_rank_pipeline(tmp_path):
    """Per-rank pipeline parallelism across REAL processes: activations
    forward / cotangents back over eager p2p, per-stage tape backward —
    the reference's multi-host PP seam (pipeline_parallel.py:440) on the
    multi-process runtime."""
    proc, logs = _run_launch(tmp_path, PIPE_WORKER)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-1500:]}\n"
        f"stderr:{proc.stderr[-1500:]}\nlogs:{logs[-4000:]}")
    assert "MPPIPE_OK rank=0" in logs and "MPPIPE_OK rank=1" in logs, logs
    assert "MPPIPE_LOSSES" in logs


def test_two_node_launch(tmp_path):
    """Multi-NODE path: two launcher invocations (--nnodes 2, distinct
    --node_rank, shared --master) each spawn their node's worker; rank 0's
    launcher binds the KV master, peers connect — the real pod topology on
    one host."""
    import socket

    def _three_port_base():
        # the job binds p (KV), p+1 (coordinator), p+2 (TCPStore)
        for _ in range(32):
            with socket.socket() as probe:
                probe.bind(("127.0.0.1", 0))
                base = probe.getsockname()[1]
            socks = []
            try:
                for off in range(3):
                    s = socket.socket()
                    s.bind(("127.0.0.1", base + off))
                    socks.append(s)
                return base
            except OSError:
                continue
            finally:
                for s in socks:
                    s.close()
        raise RuntimeError("no free 3-port window")

    def _attempt(attempt_dir):
        """One two-launcher run on a freshly probed port window. The probe
        closes its sockets before the launchers bind (unavoidable TOCTOU),
        so the CALLER retries on bind-race signatures rather than trusting
        one window."""
        import signal as _signal
        import time as _time

        port = _three_port_base()
        ckpt = str(attempt_dir / "ckpt")
        env = _launch_env()
        procs = []
        for node in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--nnodes", "2", "--node_rank", str(node),
                 "--master", f"127.0.0.1:{port}",
                 "--log_dir", str(attempt_dir / f"logs{node}"),
                 WORKER, ckpt],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=REPO, env=env, start_new_session=True))

        def _kill_group(p):
            # each launcher leads its own session; killing the GROUP takes
            # its spawned rank workers down too (a bare p.kill() would
            # orphan them to spin through the remaining attempts)
            try:
                os.killpg(os.getpgid(p.pid), _signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

        # poll both: when one launcher dies nonzero (e.g. the master lost
        # the bind race), take its sibling down immediately instead of
        # letting it wait out the full timeout against a dead master
        deadline = _time.monotonic() + 300
        while _time.monotonic() < deadline:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                break
            if any(rc not in (None, 0) for rc in rcs):
                _time.sleep(5)  # grace for the sibling to notice on its own
                for p in procs:
                    if p.poll() is None:
                        _kill_group(p)
                break
            _time.sleep(0.5)
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                _kill_group(p)
                out, _ = p.communicate()
            outs.append(out or "")
        logs = ""
        for node in range(2):
            root = attempt_dir / f"logs{node}"
            if root.exists():
                for f in sorted(root.iterdir()):
                    logs += f"\n--- node{node}/{f.name} ---\n" + f.read_text()
        return procs, outs, logs

    for attempt in range(3):
        adir = tmp_path / f"attempt{attempt}"
        adir.mkdir()
        procs, outs, logs = _attempt(adir)
        if all(p.returncode == 0 for p in procs):
            break
        blob = "".join(outs) + logs
        if "Address already in use" not in blob and "EADDRINUSE" not in blob:
            break  # a real failure, not the port race — report it
    assert all(p.returncode == 0 for p in procs), (
        f"rcs={[p.returncode for p in procs]}\n"
        f"out0:{outs[0][-1500:]}\nout1:{outs[1][-1500:]}\nlogs:{logs[-4000:]}")
    for r in range(2):
        assert f"MPWORKER_OK rank={r}/2" in logs, logs[-4000:]


KILL_WORKER = os.path.join(REPO, "tests", "helpers", "mp_kill_worker.py")


def test_kill_a_rank_watchdog_detects_and_elastic_restarts(tmp_path):
    """VERDICT r3 #8: rank 1 goes dead mid-step (hangs — no clean exit);
    rank 0's collective watchdog flags the frozen peer and aborts; the
    launch controller's watch loop restarts the pod; the restarted world
    completes training. Reference: comm_task_manager.cc +
    launch/controllers/collective.py:272."""
    marker_dir = str(tmp_path / "markers")
    proc, logs = _run_launch(tmp_path, KILL_WORKER, marker_dir,
                             launch_args=("--max_restarts", "2"))
    assert proc.returncode == 0, (
        f"launch failed rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}\nlogs:{logs[-4000:]}")
    # attempt 1: rank 1 died, rank 0's watchdog named the frozen peer
    assert "MPKILL_DYING rank=1" in logs, logs[-4000:]
    assert "MPKILL_WATCHDOG rank=0" in logs, logs[-4000:]
    assert "'kind': 'stuck'" in logs, logs[-4000:]
    # the controller restarted rather than giving up
    assert "restarting pod (attempt 1" in proc.stderr, proc.stderr[-2000:]
    # attempt 2: the restarted world trained to completion on every rank
    for r in range(2):
        assert f"MPKILL_OK rank={r}/2" in logs, logs[-4000:]
