"""Op-level compiled-program observatory (observability.opprof).

The acceptance bars:
  * per-op FLOPs/bytes extracted from a tiny model's compiled HLO are
    arithmetically exact for the dominant op (dot = 2*M*N*K) and agree
    with XLA's own ``cost_analysis`` module totals;
  * the op-class taxonomy is stable and SHARED with
    ``tools/analyze_xplane.py`` (one bucket scheme for TPU xplane
    captures and CPU cost-model profiles; ``_canon`` behavior for
    existing PROFILES_SUMMARY.json fields unchanged);
  * an injected recompile (second batch shape through the
    shape-polymorphic TrainStep) produces a second capture whose diff
    NAMES at least one op + the fingerprint flip + recompile growth;
  * ``roofline.gap_attribution_opclass`` gauges tile each phase total
    that ``roofline_attr`` reports exactly (all 7 classes published);
  * ``tools/bench_guard.py`` ``opprof:`` lane exits 1 on a synthetic
    20% top-op cost-share regression and skips dry-run wrappers;
  * ``tools/profile_report.py --json`` / ``telemetry_dump --opprof``
    smoke in the lint lane.

Everything runs on the CPU backend inside the 60s opprof budget.
"""
import json
import math
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, optimizer
from paddle_tpu.observability import opprof, roofline_attr
from paddle_tpu.observability.metrics import get_registry

pytestmark = pytest.mark.opprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_opprof():
    opprof.enable()
    opprof.reset_captures()
    yield
    opprof.disable()
    opprof.reset_captures()


def _tiny_train_step(label="train_step", in_dim=16, out_dim=8):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(in_dim, 32), nn.Tanh(),
                          nn.Linear(32, out_dim))
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=model.parameters())

    def loss_fn(x, y):
        d = model(x) - y
        return (d * d).mean()

    step = jit.TrainStep(loss_fn, opt, opprof_label=label)
    rng = np.random.RandomState(0)

    def batch(b):
        return (paddle.to_tensor(rng.rand(b, in_dim).astype("float32")),
                paddle.to_tensor(rng.rand(b, out_dim).astype("float32")))

    return step, batch


# -- cost extraction ----------------------------------------------------------

def test_hlo_cost_extraction_exact_dot_flops():
    import jax
    import jax.numpy as jnp

    def f(w, x):
        return jnp.tanh(x @ w).sum()

    m, k, n = 4, 8, 16
    compiled = jax.jit(f).lower(jnp.ones((k, n), jnp.float32),
                                jnp.ones((m, k), jnp.float32)).compile()
    prof = opprof.profile_compiled(compiled, label="probe")
    by_class = {}
    for r in prof.ops:
        by_class.setdefault(r["class"], 0.0)
        by_class[r["class"]] += r["flops"]
    # dot = 2*M*N*K, exactly — the number every MFU quote divides by
    assert by_class["matmul"] == 2 * m * n * k
    # XLA's own module totals agree on flops within the reduce-count
    # convention (ours counts reduce elements, XLA's varies by backend)
    tot = prof.totals()
    assert tot["flops"] == pytest.approx(
        prof.xla_totals.get("flops", tot["flops"]), rel=0.25)
    # bytes accessed: parser vs XLA exact on this fusion-free module
    assert tot["bytes"] == pytest.approx(
        prof.xla_totals.get("bytes accessed", tot["bytes"]), rel=0.25)
    # deterministic: same HLO text -> same fingerprint and same rows
    prof2 = opprof.profile_hlo_text(compiled.as_text(), label="probe")
    assert prof2.fingerprint == prof.fingerprint
    assert prof2.ops == prof.ops


def test_scan_body_expands_by_known_trip_count():
    import jax
    import jax.numpy as jnp

    trips = 16

    def g(w, x):
        def body(c, _):
            return jnp.tanh(c @ w), None
        out, _ = jax.lax.scan(body, x, None, length=trips)
        return out.sum()

    compiled = jax.jit(g).lower(jnp.ones((8, 8), jnp.float32),
                                jnp.ones((4, 8), jnp.float32)).compile()
    prof = opprof.profile_compiled(compiled, label="scan")
    dots = [r for r in prof.ops if r["class"] == "matmul"]
    assert dots, "scan-body dot not surfaced"
    # the while body's dot costs trip_count * (2*4*8*8): a scan-heavy
    # model (scan_layers=True Llama) must not undercount its stack
    assert sum(r["flops"] for r in dots) == trips * 2 * 4 * 8 * 8
    assert dots[0]["count"] == trips


# -- taxonomy -----------------------------------------------------------------

def test_taxonomy_stability_and_shared_with_analyze_xplane():
    # the bucket scheme is closed and ordered
    assert opprof.OP_CLASSES == ("matmul", "attention", "collective",
                                 "elementwise", "reduce",
                                 "data-movement", "quant", "other")
    expect = {
        "dot_general": "matmul", "convolution": "matmul",
        "all_reduce": "collective", "reduce-scatter": "collective",
        "collective_permute.3": "collective",
        "reduce_sum": "reduce", "reduce.12": "reduce",
        "tanh": "elementwise", "add.7": "elementwise",
        "copy": "data-movement", "transpose.2": "data-movement",
        "broadcast_in_dim": "data-movement",
        "custom-call": "other",
    }
    for name, cls in expect.items():
        assert opprof.classify_op(name) == cls, name
    # attention context wins over the opcode (an attention dot is an
    # attention-optimization target, not a projection-matmul one)
    assert opprof.classify_op("dot_general",
                              "decoder/flash_attention/dot") == "attention"
    assert opprof.classify_op("fusion.7", "mha/softmax") == "attention"
    # quant scopes win over BOTH the opcode and an enclosing attention
    # scope: the inline cache dequant lives inside the attention calc,
    # and its cost is the quant lane's attribution target
    assert opprof.classify_op("convert.3",
                              "decoder/cachekv_dequant/convert") == "quant"
    assert opprof.classify_op("multiply",
                              "mha/cachekv_quant/mul") == "quant"
    assert opprof.classify_op("fusion.2",
                              "model/weight_dequant/mul") == "quant"
    # analyze_xplane delegates to the SAME module: identical buckets,
    # and its _canon keeps the historical (fold=False) key spelling
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_ax", os.path.join(REPO, "tools", "analyze_xplane.py"))
    ax = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ax)
    assert ax._OPPROF.OP_CLASSES == opprof.OP_CLASSES
    for name, cls in expect.items():
        assert ax._OPPROF.classify_op(name) == cls, name
    assert ax._canon("fusion.123") == "fusion"
    assert ax._canon("dot_general.5") == "dot_general"  # underscore kept
    assert ax._canon("copy42") == "copy"


# -- capture hooks + diff -----------------------------------------------------

def test_trainstep_capture_and_recompile_diff_names_ops():
    step, batch = _tiny_train_step(label="t.train_step")
    x, y = batch(4)
    step(x, y)   # eager discovery
    step(x, y)   # first compiled execution -> capture 1
    assert opprof.recompile_counts() == {"t.train_step": 1}
    x2, y2 = batch(6)
    step(x2, y2)  # injected recompile: shape retrace -> capture 2
    assert opprof.recompile_counts() == {"t.train_step": 2}
    profs = opprof.get_captures()["t.train_step"]
    assert profs[0].fingerprint != profs[1].fingerprint
    old = {"captures": {"t.train_step": profs[0].to_dict()},
           "recompiles": {"t.train_step": 1}}
    new = {"captures": {"t.train_step": profs[1].to_dict()},
           "recompiles": {"t.train_step": 2}}
    d = opprof.diff(old, new, share_tol=0.0)
    named = d["appeared"] + d["disappeared"] + [c["op"]
                                               for c in d["changed"]]
    assert named, "recompile diff named no ops"
    assert d["fingerprint_changed"] == ["t.train_step"]
    assert d["recompile_growth"]["t.train_step"] == {"old": 1, "new": 2}


def test_static_function_capture_under_label():
    @jit.to_static
    def f(a):
        return paddle.tanh(a) * 2.0

    f._opprof_label = "t.static_fn"
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    with paddle.no_grad():
        f(x)  # trace
        f(x)  # warm transition -> capture
        f(x)  # warm: no further capture
    caps = opprof.get_captures()
    assert "t.static_fn" in caps and len(caps["t.static_fn"]) == 1
    classes = {r["class"] for r in caps["t.static_fn"][0].ops}
    assert "elementwise" in classes


def test_disabled_is_free_and_capture_never_raises():
    opprof.disable()
    step, batch = _tiny_train_step(label="t.off")
    x, y = batch(4)
    step(x, y)
    step(x, y)
    assert opprof.get_captures() == {}
    # a broken jitted object must not take down the caller
    opprof.enable()
    class Broken:
        def lower(self, *a, **k):
            raise RuntimeError("boom")
    assert opprof.maybe_capture("t.broken", Broken(), (1,)) is None
    assert "t.broken" not in opprof.get_captures()


# -- gap attribution ----------------------------------------------------------

def test_gap_attribution_opclass_tiles_phase_totals(tmp_path,
                                                    monkeypatch):
    model = {"configs": [
        {"config": "toy", "params": 1000, "batch": 1, "seq": 100,
         "t_compute_ms": 40.0, "t_memory_ms": 60.0, "bound": "memory",
         "tokens_per_s_bound": 1000.0, "measured_mfu_ceiling": 0.6},
    ]}
    p = tmp_path / "ROOFLINE.json"
    p.write_text(json.dumps(model))
    monkeypatch.setenv("PADDLE_ROOFLINE", str(p))
    roofline_attr.clear_cache()
    try:
        step, batch = _tiny_train_step(label="t.gap.train_step")
        x, y = batch(4)
        step(x, y)
        step(x, y)  # capture (label contains 'train' -> headline)
        attr = roofline_attr.observe_train_step(0.120, observed_mfu=0.2,
                                                tokens=100)
        assert attr is not None
        fam = get_registry().get("roofline.gap_attribution_opclass")
        assert fam is not None, "opclass gauges not published"
        split = {}
        for ch in fam.children():
            split.setdefault(ch.labels["phase"], {})[
                ch.labels["op_class"]] = ch.value
        phase_totals = {"compute": attr["compute_frac"],
                        "memory": attr["memory_frac"],
                        "overhead": attr["overhead_frac"]}
        for phase, total in phase_totals.items():
            parts = split[phase]
            # ALL classes published (zeros included: no stale values)
            assert set(parts) == set(opprof.OP_CLASSES)
            # the classes tile the phase total exactly (fp residual is
            # folded into the largest part by _tile_exactly)
            assert math.fsum(parts.values()) == pytest.approx(
                total, abs=1e-12)
            assert all(v >= 0.0 for v in parts.values())
        # a nonzero phase splits into at least one nonzero class
        assert any(v > 0 for v in split["compute"].values())
        # comm phases route entirely to the collective class
        split2 = opprof.attribute_gap(
            {"compute_frac": 0.2, "memory_frac": 0.1,
             "overhead_frac": 0.3, "comm_fracs": {"fsdp": 0.15}},
            opprof.get_captures()["t.gap.train_step"][-1])
        assert split2["comm:fsdp"]["collective"] == pytest.approx(0.15)
        assert math.fsum(split2["comm:fsdp"].values()) == \
            pytest.approx(0.15, abs=1e-12)
    finally:
        roofline_attr.clear_cache()


def test_gap_attribution_without_capture_is_silent():
    assert opprof.publish_gap_attribution(
        {"compute_frac": 0.5, "memory_frac": 0.2,
         "overhead_frac": 0.3}) is None


# -- artifacts + drift gate ---------------------------------------------------

def _fake_artifact(top_share, n_recompiles=0, flops=1e6):
    return {
        "kind": "opprof", "tpu": False,
        "captures": {"bench.train_step": {
            "label": "bench.train_step", "fingerprint": "f" * 16,
            "ops": [{"op": "dot_general", "class": "matmul",
                     "flops": flops, "bytes": 1e3, "out_bytes": 1e3,
                     "transcendentals": 0.0, "count": 1}],
            "xla_totals": {}}},
        "recompiles": {"bench.train_step": 1 + n_recompiles},
        "fingerprints": {"bench.train_step": ["f" * 16]},
        "capture_failures": 0,
        "headline": {"label": "bench.train_step",
                     "fingerprint": "f" * 16, "top_class": "matmul",
                     "top_share": top_share,
                     "top_op_classes": [["matmul", top_share]],
                     "n_recompiles": n_recompiles},
    }


def test_artifact_write_load_diff_roundtrip(tmp_path):
    step, batch = _tiny_train_step(label="t.art.train_step")
    x, y = batch(4)
    step(x, y)
    step(x, y)
    path = opprof.write_artifact(str(tmp_path))
    assert path and os.path.basename(path) == "OPPROF_r00.json"
    doc = opprof.load_artifact(path)
    assert doc is not None and "bench" not in doc["headline"]["label"]
    assert doc["headline"]["top_share"] > 0
    # numbering continues; a second write lands r01 and diffs clean
    x2, y2 = batch(6)
    step(x2, y2)
    path2 = opprof.write_artifact(str(tmp_path))
    assert os.path.basename(path2) == "OPPROF_r01.json"
    doc2 = opprof.load_artifact(path2)
    d = opprof.diff(doc, doc2, share_tol=0.0)
    assert (d["appeared"] or d["disappeared"] or d["changed"]
            or d["fingerprint_changed"])
    # a driver dry-run wrapper is NOT an artifact
    wrapper = tmp_path / "OPPROF_r02.json"
    wrapper.write_text(json.dumps({"n": 2, "cmd": "x", "rc": 1,
                                   "tail": ""}))
    assert opprof.load_artifact(str(wrapper)) is None


def test_bench_guard_opprof_lane_gates_synthetic_regression(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bg", os.path.join(REPO, "tools", "bench_guard.py"))
    bg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bg)
    # 3 healthy rounds at top_share 0.5, then a 20% cost-share
    # regression (0.5 -> 0.6 => headroom 0.5 -> 0.4)
    for i, share in enumerate((0.5, 0.5, 0.5, 0.6)):
        (tmp_path / f"OPPROF_r{i:02d}.json").write_text(
            json.dumps(_fake_artifact(share)))
    # a dry-run wrapper round skips cleanly (like multichip:)
    (tmp_path / "OPPROF_r04.json").write_text(
        json.dumps({"n": 4, "cmd": "python bench.py", "rc": 124,
                    "tail": "timeout"}))
    report = bg.run_check(str(tmp_path))
    key = "opprof:opprof_top_share_headroom/cpu"
    assert key in report["series"]
    res = report["series"][key]
    assert res["n_points"] == 4  # the wrapper contributed no point
    assert res["status"] == "regression"
    assert report["status"] == "regression"
    # the recompile-health series stayed flat -> pass
    assert report["series"][
        "opprof:opprof_recompile_health/cpu"]["status"] == "pass"
    # CLI contract: --check exits 1 on the regression
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_guard.py"),
         "--check", "--dir", str(tmp_path)],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "opprof" in proc.stdout


def test_bench_guard_opprof_lane_passes_flat_history(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bg2", os.path.join(REPO, "tools", "bench_guard.py"))
    bg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bg)
    for i in range(3):
        (tmp_path / f"OPPROF_r{i:02d}.json").write_text(
            json.dumps(_fake_artifact(0.5)))
    report = bg.run_check(str(tmp_path))
    assert report["status"] == "pass"


# -- CLI gates (lint lane) ----------------------------------------------------

@pytest.mark.lint
@pytest.mark.quick
def test_profile_report_cli_names_injected_recompile():
    """profile_report --json is part of the lint lane: the demo
    workload's injected recompile must produce a diff that names at
    least one op, a fingerprint flip, and recompile growth."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "profile_report.py"), "--json"],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    d = payload["diff"]
    named = d["appeared"] + d["disappeared"] + [c["op"]
                                               for c in d["changed"]]
    assert named, "demo recompile diff named no ops"
    assert d["fingerprint_changed"]
    assert payload["recompiles"]["demo.train_step"] == 2
    # gap split tiles its phases
    for phase, parts in payload["gap_attribution"].items():
        assert set(parts) == set(opprof.OP_CLASSES)
    # budget guard: this boots jax and compiles twice
    assert elapsed < 60.0, f"profile_report took {elapsed:.1f}s"


@pytest.mark.lint
@pytest.mark.quick
def test_profile_report_artifact_mode_reads_committed_round():
    """Artifact mode is jax-free and must stay snappy over the
    committed OPPROF_r*.json rounds."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "profile_report.py"),
         "--artifacts", "--json"],
        cwd=REPO, capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["headline"]["top_share"] > 0
    assert elapsed < 10.0, f"artifact mode took {elapsed:.1f}s"


@pytest.mark.lint
@pytest.mark.quick
def test_telemetry_dump_opprof_view():
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "telemetry_dump.py"), "--opprof"],
        cwd=REPO, capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "# opprof OPPROF_r" in proc.stdout
    assert "gap attribution" in proc.stdout
    # stdlib-only path: no jax boot allowed in this view
    assert elapsed < 10.0, f"--opprof view took {elapsed:.1f}s"
