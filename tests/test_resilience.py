"""End-to-end fault tolerance: chaos injection, crash-safe checkpointing,
and recovery policies (paddle_tpu.resilience).

The acceptance drills:
  * a checkpoint save killed mid-write at an ARBITRARY byte offset leaves
    the previous checkpoint restorable BIT-IDENTICALLY;
  * a train loop under injected NaN gradients completes with the bad
    steps skipped/counted (and rolls back after K consecutive);
  * a serving batcher under deadline pressure + overload rejects with
    TYPED errors while its stats stay consistent.
"""
import os
import pickle
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.resilience import (CheckpointManager, DeadlineExceeded,
                                   HealthState, Overloaded, RetryGiveUp,
                                   RetryPolicy, StepGuard,
                                   TransientChaosError, TornWrite,
                                   arm_scenario, disarm, fault_point,
                                   get_chaos, parse_scenario,
                                   validate_checkpoint)
from paddle_tpu.resilience.recovery import HealthStateMachine

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with chaos off (process-global state)."""
    disarm()
    yield
    disarm()


# -- chaos registry -----------------------------------------------------------

def test_fault_point_noop_when_disarmed():
    assert fault_point("dataloader.next") is None
    assert get_chaos().hits("dataloader.next") == 0  # fast path never counts


def test_parse_scenario_roundtrip():
    seed, specs = parse_scenario(
        "seed=7; kv.request:transient_error:p=0.5,count=3; "
        "checkpoint.write:torn_write:offset=128,after=1")
    assert seed == 7
    assert [(s.point, s.kind) for s in specs] == [
        ("kv.request", "transient_error"), ("checkpoint.write", "torn_write")]
    assert specs[0].p == 0.5 and specs[0].count == 3
    assert specs[1].offset == 128 and specs[1].after == 1


def test_parse_scenario_rejects_garbage():
    with pytest.raises(ValueError):
        parse_scenario("justapoint")
    with pytest.raises(ValueError):
        parse_scenario("p:unknown_kind")
    with pytest.raises(ValueError):
        parse_scenario("p:delay:bogus_key=1")


def test_chaos_deterministic_replay():
    """Same seed + same call sequence -> the SAME hits fire, twice."""
    def drill():
        arm_scenario("seed=11; serving.step:transient_error:p=0.4")
        fired = []
        for i in range(50):
            try:
                fault_point("serving.step")
                fired.append(False)
            except TransientChaosError:
                fired.append(True)
        disarm()
        return fired

    a, b = drill(), drill()
    assert a == b
    assert any(a) and not all(a)   # p=0.4 actually mixes


def test_chaos_after_and_count_windows():
    arm_scenario("seed=0; train.step:nan_grad:after=2,count=2")
    out = [fault_point("train.step") for _ in range(6)]
    assert [s is not None for s in out] == [False, False, True, True,
                                            False, False]
    assert out[2].kind == "nan_grad"


def test_arm_from_env(monkeypatch):
    from paddle_tpu.resilience.chaos import arm_from_env
    monkeypatch.setenv("PADDLE_CHAOS",
                       "seed=5; dataloader.next:delay:delay_s=0.0")
    reg = arm_from_env()
    assert reg is not None and reg.armed
    assert fault_point("dataloader.next") is None  # delay returns None
    assert reg.specs("dataloader.next")[0].fired == 1


# -- retry policy -------------------------------------------------------------

def test_retry_backoff_math():
    pol = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.5,
                      jitter=0.0)
    assert [pol.backoff(i) for i in range(5)] == [0.1, 0.2, 0.4, 0.5, 0.5]
    import random
    assert pol.delay(1, random.Random(0)) == pytest.approx(0.2)
    jit = RetryPolicy(base_delay=0.1, jitter=0.5, seed=1)
    d = jit.delay(0, random.Random(1))
    assert 0.05 <= d <= 0.1      # backoff * (1 - 0.5*U[0,1))


def test_retry_succeeds_after_transients():
    sleeps = []
    pol = RetryPolicy(max_attempts=5, base_delay=0.01, jitter=0.0,
                      sleep_fn=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise ConnectionError("blip")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert calls["n"] == 4
    assert sleeps == pytest.approx([0.01, 0.02, 0.04])


def test_retry_gives_up_and_chains():
    pol = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0,
                      sleep_fn=lambda s: None)
    with pytest.raises(RetryGiveUp) as ei:
        pol.call(lambda: (_ for _ in ()).throw(TimeoutError("slow")))
    assert isinstance(ei.value.last, TimeoutError)
    assert isinstance(ei.value.__cause__, TimeoutError)


def test_retry_nonretryable_raises_unwrapped():
    pol = RetryPolicy(sleep_fn=lambda s: None)
    with pytest.raises(ValueError):
        pol.call(lambda: (_ for _ in ()).throw(ValueError("semantics")))


def test_retry_giveup_types_beat_retryable():
    import urllib.error
    pol = RetryPolicy(giveup=(urllib.error.HTTPError,),
                      sleep_fn=lambda s: None)

    def http404():
        raise urllib.error.HTTPError("u", 404, "nf", {}, None)

    with pytest.raises(urllib.error.HTTPError):  # unwrapped, not retried
        pol.call(http404)


def test_retry_deadline_caps_attempts():
    pol = RetryPolicy(max_attempts=100, base_delay=10.0, jitter=0.0,
                      deadline=0.0, sleep_fn=lambda s: None)
    with pytest.raises(RetryGiveUp):
        pol.call(lambda: (_ for _ in ()).throw(ConnectionError("x")))


def test_retry_retries_injected_chaos():
    arm_scenario("seed=0; kv.request:transient_error:count=2")
    pol = RetryPolicy(max_attempts=4, base_delay=0.0, jitter=0.0,
                      sleep_fn=lambda s: None)

    def body():
        fault_point("kv.request")
        return "through"

    assert pol.call(body) == "through"
    assert get_chaos().specs("kv.request")[0].fired == 2


# -- crash-safe checkpointing -------------------------------------------------

def _state(val: float):
    return {"w": paddle.to_tensor(np.full((4, 6), val, np.float32)),
            "b": paddle.to_tensor(np.arange(8, dtype=np.float32) * val)}


def _fill_zeros_like(sd):
    return {k: paddle.zeros(list(v.shape), dtype="float32")
            for k, v in sd.items()}


@pytest.mark.parametrize("offset", [0, 1, 17, 100, 10_000])
@pytest.mark.parametrize("after", [0, 1])
def test_torn_checkpoint_save_restores_prior_state(tmp_path, offset, after):
    """THE acceptance drill: kill a save mid-write at byte `offset` of its
    `after`-th file; restore_latest() hands back the previous checkpoint
    bit-for-bit."""
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    golden = _state(3.25)
    assert mgr.save(golden, step=1).endswith("step_000000000001")
    ok, reason = mgr.validate(1)
    assert ok, reason

    arm_scenario(f"seed=0; checkpoint.write:torn_write:"
                 f"offset={offset},after={after},count=1")
    with pytest.raises(TornWrite):
        mgr.save(_state(9.75), step=2)
    disarm()

    assert mgr.steps() == [1]                     # nothing half-published
    assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp-")]
    target = _fill_zeros_like(golden)
    assert mgr.restore_latest(target) == 1
    for k in golden:
        np.testing.assert_array_equal(target[k].numpy(), golden[k].numpy())


def test_torn_write_on_raw_save_leaves_final_files_intact(tmp_path):
    """Satellite: save_state_dict's own writes are temp+replace now — a
    torn write corrupts only a dead .tmp file, never the published one."""
    from paddle_tpu.distributed import load_state_dict, save_state_dict
    golden = _state(1.5)
    save_state_dict(golden, str(tmp_path))
    arm_scenario("seed=0; checkpoint.write:torn_write:offset=33,count=1")
    with pytest.raises(TornWrite):
        save_state_dict(_state(-2.0), str(tmp_path))
    disarm()
    target = _fill_zeros_like(golden)
    load_state_dict(target, str(tmp_path))
    for k in golden:
        np.testing.assert_array_equal(target[k].numpy(), golden[k].numpy())


def test_restore_latest_skips_corrupt_newest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=5)
    old = _state(7.0)
    mgr.save(old, step=1)
    mgr.save(_state(8.0), step=2)
    # flip one byte inside step 2's data file -> checksum mismatch
    step2 = os.path.join(str(tmp_path), "step_000000000002")
    data = [f for f in os.listdir(step2) if f.startswith("data_")][0]
    p = os.path.join(step2, data)
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(p, "wb").write(bytes(raw))

    ok, reason = mgr.validate(2)
    assert not ok and ("checksum" in reason or "unreadable" in reason)
    target = _fill_zeros_like(old)
    assert mgr.restore_latest(target) == 1
    assert mgr.invalid_skipped == 1
    np.testing.assert_array_equal(target["w"].numpy(), old["w"].numpy())


def test_restore_latest_skips_uncommitted(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(1.0), step=3)
    mgr.save(_state(2.0), step=4)
    os.remove(os.path.join(str(tmp_path), "step_000000000004", "COMMITTED"))
    target = _fill_zeros_like(_state(0.0))
    assert mgr.restore_latest(target) == 3
    np.testing.assert_array_equal(target["w"].numpy(),
                                  np.full((4, 6), 1.0, np.float32))


def test_restore_latest_none_when_empty(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.restore_latest(_fill_zeros_like(_state(0.0))) is None
    assert mgr.latest_step() is None


@pytest.mark.ckpt
def test_restore_latest_emits_typed_findings(tmp_path):
    """A fallback is never silent: every step restore_latest discards on
    the way down leaves a typed CheckpointFinding naming what was wrong
    and which step was skipped, newest first."""
    mgr = CheckpointManager(str(tmp_path), keep_last=5)
    old = _state(7.0)
    mgr.save(old, step=1)
    mgr.save(_state(8.0), step=2)
    step2 = os.path.join(str(tmp_path), "step_000000000002")
    data = [f for f in os.listdir(step2) if f.startswith("data_")][0]
    p = os.path.join(step2, data)
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    mgr.save(_state(9.0), step=3)
    os.remove(os.path.join(str(tmp_path), "step_000000000003", "COMMITTED"))

    target = _fill_zeros_like(old)
    assert mgr.restore_latest(target) == 1
    np.testing.assert_array_equal(target["w"].numpy(), old["w"].numpy())
    assert [f.step for f in mgr.findings] == [3, 2]
    kinds = [f.kind for f in mgr.findings]
    assert kinds[0] == "uncommitted"
    assert kinds[1] in ("checksum_mismatch", "unreadable")
    for f in mgr.findings:
        d = f.to_dict()
        assert d["reason"] and d["kind"] == f.kind and d["step"] == f.step
    # findings are PER RESTORE: a second call re-diagnoses from scratch
    assert mgr.restore_latest(_fill_zeros_like(old)) == 1
    assert [f.step for f in mgr.findings] == [3, 2]


@pytest.mark.ckpt
def test_retention_only_counts_committed_steps(tmp_path):
    """Torn/uncommitted step dirs must not age the last GOOD checkpoint
    out of the keep-last window — only committed steps advance the
    retention horizon."""
    mgr = CheckpointManager(str(tmp_path), keep_last=2)
    mgr.save(_state(1.0), step=1)
    for s in (2, 3):
        mgr.save(_state(float(s)), step=s)
        os.remove(os.path.join(
            str(tmp_path), f"step_{s:012d}", "COMMITTED"))
    mgr.save(_state(4.0), step=4)
    # steps 2 and 3 are junk: with only two committed steps (1, 4) the
    # horizon must not pass step 1
    assert 1 in mgr.steps() and 4 in mgr.steps()
    target = _fill_zeros_like(_state(0.0))
    assert mgr.restore_latest(target) == 4


def test_retention_keeps_last_n(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=2)
    for s in (1, 2, 3, 4):
        mgr.save(_state(float(s)), step=s)
    assert mgr.steps() == [3, 4]


def test_async_save_publishes_and_wait_reraises(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(5.0), step=10, blocking=False)
    mgr.wait()
    assert mgr.steps() == [10]
    ok, reason = mgr.validate(10)
    assert ok, reason
    arm_scenario("seed=0; checkpoint.write:torn_write:offset=5,count=1")
    mgr.save(_state(6.0), step=11, blocking=False)
    with pytest.raises(TornWrite):
        mgr.wait()
    disarm()
    assert mgr.steps() == [10]


def test_transient_chaos_save_retries_through(tmp_path):
    """An injected transient_error at checkpoint.write retries under the
    manager's policy and the save still publishes."""
    mgr = CheckpointManager(str(tmp_path))
    arm_scenario("seed=0; checkpoint.write:transient_error:count=1")
    mgr.save(_state(4.0), step=1)
    disarm()
    ok, reason = mgr.validate(1)
    assert ok, reason


def test_old_checkpoints_without_checksums_still_validate(tmp_path):
    """Back-compat: chunks pickled before the checksum field existed have
    NO ``checksum`` attribute; validation must pass them."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(2.5), step=1)
    step1 = os.path.join(str(tmp_path), "step_000000000001")
    for fn in os.listdir(step1):
        if not fn.startswith("metadata."):
            continue
        p = os.path.join(step1, fn)
        with open(p, "rb") as f:
            meta = pickle.load(f)
        for tmeta in meta.state_dict_metadata.values():
            for chunk in tmeta.chunks:
                if hasattr(chunk, "checksum"):
                    del chunk.checksum     # what an old pickle restores to
        with open(p, "wb") as f:
            pickle.dump(meta, f)
    ok, reason = validate_checkpoint(step1)
    assert ok, reason
    target = _fill_zeros_like(_state(0.0))
    assert mgr.restore_latest(target) == 1


# -- training: NaN-step guard -------------------------------------------------

def _hapi_model():
    from paddle_tpu.hapi import Model
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = Model(net)
    m.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                      parameters=m.parameters()),
              loss=nn.CrossEntropyLoss())
    return m


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 2, (16,)).astype(np.int64))
    return x, y


def _weights(m):
    return {k: v.numpy().copy() for k, v in m.network.state_dict().items()}


def test_step_guard_skips_injected_nan_steps():
    m = _hapi_model()
    guard = m.enable_step_guard()
    x, y = _batch()
    arm_scenario("seed=0; train.step:nan_grad:after=1,count=2")
    losses = [float(np.asarray(m.train_batch(x, y)[0])) for _ in range(5)]
    disarm()
    assert guard.skipped == 2
    assert guard.steps == 5
    assert [not np.isfinite(v) for v in losses] == [False, True, True,
                                                    False, False]
    # weights stayed finite: the NaN losses never reached backward
    assert all(np.isfinite(w).all() for w in _weights(m).values())


def test_step_guard_rolls_back_to_checkpoint(tmp_path):
    m = _hapi_model()
    mgr = CheckpointManager(str(tmp_path))
    guard = m.enable_step_guard(rollback_after=2, checkpoint_manager=mgr,
                                include_optimizer=False)
    x, y = _batch()
    m.train_batch(x, y)              # take one real step first
    m.save_checkpoint(mgr, step=1)
    golden = _weights(m)
    m.train_batch(x, y)              # drift past the checkpoint
    assert any(not np.array_equal(golden[k], w)
               for k, w in _weights(m).items())

    arm_scenario("seed=0; train.step:nan_grad:count=2")  # 2 consecutive
    m.train_batch(x, y)
    m.train_batch(x, y)
    disarm()
    assert guard.rollbacks == 1
    assert guard.skipped == 2
    now = _weights(m)
    for k in golden:                 # bit-identical restore
        np.testing.assert_array_equal(now[k], golden[k])
    # training continues normally after the rollback
    out = m.train_batch(x, y)
    assert np.isfinite(np.asarray(out[0])).all()


def test_step_guard_counters_reset_on_finite():
    g = StepGuard(rollback_after=3)
    nan = float("nan")
    assert [g.observe(v) for v in (nan, nan, 1.0, nan, nan)] == \
        ["skip", "skip", "ok", "skip", "skip"]
    assert g.consecutive == 2        # the finite loss reset the streak
    assert g.skipped == 4 and g.rollbacks == 0


# -- serving: shedding, deadlines, health -------------------------------------

def _tiny_lm():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def test_serving_sheds_typed_overloaded():
    from paddle_tpu.inference.serving import ContinuousBatcher
    b = ContinuousBatcher(_tiny_lm(), max_batch=2, s_max=32, compile=False,
                          max_queue_depth=2)
    prompt = np.arange(4)
    b.submit(prompt, 4)
    b.submit(prompt, 4)
    with pytest.raises(Overloaded):
        b.submit(prompt, 4)
    st = b.stats()
    assert st["requests_shed"] == 1
    assert b.health.state == HealthState.DEGRADED
    # the queued work still completes; stats stay consistent
    outs = b.run_until_done()
    assert len(outs) == 2
    assert b.stats()["completed_requests"] == 2


def test_serving_deadline_expires_with_typed_error():
    from paddle_tpu.inference.serving import ContinuousBatcher
    b = ContinuousBatcher(_tiny_lm(), max_batch=2, s_max=32, compile=False)
    rid_dead = b.submit(np.arange(4), 8, deadline_s=0.0)   # already expired
    rid_live = b.submit(np.arange(4), 3)
    time.sleep(0.001)
    done = []
    for _ in range(20):
        done += b.step()
        if not b._has_work():
            break
    assert done == [rid_live]
    with pytest.raises(DeadlineExceeded):
        b.result(rid_dead)
    with pytest.raises(DeadlineExceeded):
        b.pop_result(rid_dead)
    st = b.stats()
    assert st["deadline_expired"] == 1
    assert st["completed_requests"] == 1


def test_serving_expired_and_shed_counters_disjoint():
    """A request that expires while QUEUED must not also cause (or count
    as) a shed: submit purges dead-on-arrival queue entries before the
    capacity check, so the freed spot admits live work instead of
    rejecting it. Regression for the deadline-expiry × shed interaction."""
    from paddle_tpu.inference.serving import ContinuousBatcher
    b = ContinuousBatcher(_tiny_lm(), max_batch=2, s_max=32, compile=False,
                          max_queue_depth=2)
    rid_dead = b.submit(np.arange(4), 4, deadline_s=0.0)  # expires in queue
    rid_live = b.submit(np.arange(4), 4)
    time.sleep(0.001)
    # queue reads full (2/2), but the expired entry must be purged — this
    # submit is ADMITTED, not shed
    rid_late = b.submit(np.arange(4), 4)
    outs = b.run_until_done()
    assert sorted(outs) == [rid_live, rid_late]
    with pytest.raises(DeadlineExceeded):
        b.result(rid_dead)
    st = b.stats()
    assert st["deadline_expired"] == 1
    assert st["requests_shed"] == 0          # disjoint: expired ≠ shed
    assert st["completed_requests"] == 2


def test_serving_active_request_deadline_releases_slot():
    """A request expiring MID-DECODE frees its slot for the queue."""
    from paddle_tpu.inference.serving import ContinuousBatcher
    b = ContinuousBatcher(_tiny_lm(), max_batch=1, s_max=32, compile=False,
                          default_deadline_s=1000.0)
    rid_a = b.submit(np.arange(4), 20, deadline_s=0.05)
    rid_b = b.submit(np.arange(4), 2)
    b.step()                          # admits A (B waits: one slot)
    assert b.active == 1
    time.sleep(0.06)                  # A's deadline lapses mid-decode
    done = []
    for _ in range(20):
        done += b.step()
        if not b._has_work():
            break
    assert done == [rid_b]            # B got A's slot
    with pytest.raises(DeadlineExceeded):
        b.result(rid_a)
    assert b.stats()["deadline_expired"] == 1


def test_paged_batcher_shares_shed_and_deadline_policy():
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    b = PagedContinuousBatcher(_tiny_lm(), max_batch=2, s_max=32,
                               block_size=8, compile=False,
                               max_queue_depth=1)
    b.submit(np.arange(4), 3)
    with pytest.raises(Overloaded):
        b.submit(np.arange(4), 3)
    outs = b.run_until_done()
    assert len(outs) == 1
    assert b.stats()["requests_shed"] == 1


def test_serving_step_chaos_drives_health_state():
    from paddle_tpu.inference.serving import ContinuousBatcher
    b = ContinuousBatcher(_tiny_lm(), max_batch=2, s_max=32, compile=False)
    b.submit(np.arange(4), 6)
    arm_scenario("seed=0; serving.step:transient_error:count=3")
    for _ in range(3):
        with pytest.raises(TransientChaosError):
            b.step()
    disarm()
    assert b.health.state == HealthState.UNREADY   # 3 consecutive failures
    assert not b.health.ready()
    outs = b.run_until_done()                      # recovers and finishes
    assert len(outs) == 1
    assert b.health.ready()


def test_health_state_machine_transitions():
    h = HealthStateMachine(capacity=10, degraded_hold_s=0.0,
                           unready_after=2, engine="test")
    assert h.state == HealthState.STARTING and not h.ready()
    h.on_step_ok(queue_depth=0)
    assert h.state == HealthState.READY and h.ready()
    h.on_step_ok(queue_depth=9)          # above 0.8 * capacity
    assert h.state == HealthState.DEGRADED and h.ready()
    h.on_step_error()
    h.on_step_error()
    assert h.state == HealthState.UNREADY and not h.ready()
    h.on_step_ok(queue_depth=0)
    assert h.state == HealthState.READY
    h.drain()
    assert h.state == HealthState.UNREADY
    h.on_step_ok(queue_depth=0)          # drained: stays down until reset
    assert h.state == HealthState.UNREADY
    h.reset()
    assert h.state == HealthState.STARTING


# -- control plane: KV retry, elastic re-registration, watchdog reset ---------

def test_kvclient_retries_through_injected_faults():
    from paddle_tpu.distributed.launch import KVClient, KVServer
    server = KVServer().start()
    try:
        c = KVClient(server.endpoint,
                     retry=RetryPolicy(max_attempts=5, base_delay=0.0,
                                       jitter=0.0, sleep_fn=lambda s: None))
        arm_scenario("seed=0; kv.request:transient_error:count=2")
        c.put("k", "v")                  # retries through both faults
        assert c.get("k") == "v"
        disarm()
        assert c.get("missing") is None  # 404 semantics survive the retry
        c.delete("k")
        assert c.get("k") is None
    finally:
        server.stop()


def test_elastic_heartbeat_survives_master_restart():
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.launch import KVServer
    server = KVServer().start()
    fast = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0,
                       deadline=0.5, sleep_fn=lambda s: None)
    try:
        em = ElasticManager(server.endpoint, "job9", rank=0, np=1,
                            retry=fast)
        em.register("host-a:8000")
        assert em.heartbeat()
        assert em.alive_nodes() == [0]

        port = server.port
        server.stop()                     # master dies
        assert em.heartbeat() is False    # tolerated, not raised
        assert em.alive_nodes() == [0]    # cached membership, not []

        server = _restart_kv(port)        # ...and comes back EMPTY
        assert em.heartbeat() is True
        assert em.reregistrations == 1    # nodes/<rank> was re-put
        assert em.client.get("elastic/job9/nodes/0") == "host-a:8000"
    finally:
        server.stop()


def _restart_kv(port):
    from paddle_tpu.distributed.launch import KVServer
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            return KVServer(port=port).start()
        except OSError:
            time.sleep(0.05)          # TIME_WAIT on the old socket
    raise RuntimeError("could not rebind KV port")


def test_watchdog_reset_clears_poison():
    from paddle_tpu.distributed import watchdog as wd
    from paddle_tpu.distributed.watchdog import (CollectiveWatchdog,
                                                 DesyncError)

    class _Store:
        def __init__(self):
            self._kv = {}

        def set(self, k, v):
            self._kv[k] = v

        def get(self, k):
            return self._kv.get(k)

    w = CollectiveWatchdog(_Store(), rank=0, world_size=1, timeout=60.0)
    w._poison = {"type": "timeout", "op": "all_reduce"}
    with pytest.raises(DesyncError):
        w.enter("all_reduce")
    report = w.reset()
    assert report and report["type"] == "timeout"
    w.enter("all_reduce")             # clean again
    w.exit()
    # the module-level helper is exported and None-safe when no process
    # watchdog is enabled
    from paddle_tpu.distributed import reset_watchdog
    if wd.get_watchdog() is None:
        assert reset_watchdog() is None


# -- telemetry wiring ---------------------------------------------------------

def test_resilience_metrics_reach_registry(tmp_path):
    from paddle_tpu.observability.metrics import get_registry
    reg = get_registry()
    fam = reg.counter("faults_injected_total",
                      "chaos faults fired, by point and kind",
                      labelnames=("point", "kind"))
    before = fam.labels(point="dataloader.next",
                        kind="transient_error").value
    arm_scenario("seed=0; dataloader.next:transient_error:count=1")
    with pytest.raises(TransientChaosError):
        fault_point("dataloader.next")
    disarm()
    after = fam.labels(point="dataloader.next",
                       kind="transient_error").value
    assert after == before + 1

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(1.0), step=1)
    assert mgr.restore_latest(_fill_zeros_like(_state(0.0))) == 1
    hist = reg.get("checkpoint_restore_seconds")
    assert hist is not None and hist.count >= 1
