"""Weight-only int8 serving through the full decode stack.

Reference surface: nn/quant/quantized_linear.py weight_only_linear powering
the serving predictor's int8 path. The machinery invariant under test:
every serving route (dense KV, paged KV, continuous batchers, compiled
steps) must be TOKEN-EXACT against the quantized model's own solo
generate — quantization changes the logits, never the serving algebra.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import (ContinuousBatcher,
                                          PagedContinuousBatcher)
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
from paddle_tpu.nn.quant import quantize_linear_layers


def _quantized_gpt2(algo="weight_only_int8"):
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    n = quantize_linear_layers(m, algo)
    assert n > 0
    return m


def test_int8_logits_close_to_fp():
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (1, 6)).astype(np.int64))
    with paddle.no_grad():
        fp = m(ids).numpy()
    quantize_linear_layers(m)
    with paddle.no_grad():
        q8 = m(ids).numpy()
    rel = np.abs(q8 - fp).max() / (np.abs(fp).max() + 1e-9)
    assert rel < 0.05, rel


@pytest.mark.smoke
def test_quantized_paged_matches_quantized_dense():
    m = _quantized_gpt2()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 128, (2, 6)).astype(np.int64))
    with paddle.no_grad():
        dense = m.generate(ids, max_new_tokens=7).numpy()
        paged = m.generate_paged(ids, max_new_tokens=7, block_size=8).numpy()
    np.testing.assert_array_equal(dense, paged)


def test_quantized_batchers_token_exact():
    m = _quantized_gpt2()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 8)]

    def solo(p, n):
        ids = paddle.to_tensor(np.asarray(p, np.int64)[None])
        with paddle.no_grad():
            return m.generate(ids, max_new_tokens=n).numpy()[0]

    with paddle.no_grad():
        dense_b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        rids = [dense_b.submit(p, 5) for p in prompts]
        outs = dense_b.run_until_done()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid], solo(p, 5))

    paged_b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                                     policy="ondemand", compile=False)
    rids = [paged_b.submit(p, 5) for p in prompts]
    outs = paged_b.run_until_done()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid], solo(p, 5))


def test_quantized_compiled_decode_matches_eager():
    from paddle_tpu import jit
    m = _quantized_gpt2()
    ids = paddle.to_tensor(
        np.random.RandomState(3).randint(0, 128, (2, 6)).astype(np.int64))
    with paddle.no_grad():
        ref = m.generate_paged(ids, max_new_tokens=6, block_size=8).numpy()
        step = jit.to_static(m.paged_decode_step)
        out = m.generate_paged(ids, max_new_tokens=6, block_size=8,
                               decode_fn=step).numpy()
    np.testing.assert_array_equal(ref, out)


def test_int4_serving_runs():
    m = _quantized_gpt2("weight_only_int4")
    ids = paddle.to_tensor(
        np.random.RandomState(4).randint(0, 128, (1, 5)).astype(np.int64))
    with paddle.no_grad():
        dense = m.generate(ids, max_new_tokens=5).numpy()
        paged = m.generate_paged(ids, max_new_tokens=5, block_size=8).numpy()
    np.testing.assert_array_equal(dense, paged)


# -- cache-KV int8 (reference block_multihead_attention static quant mode) --

def _llama_eval():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny_config(vocab_size=128))
    m.eval()
    return m


def test_cachekv_int8_close_to_fp_cache():
    """Static per-head int8 cache: paged logits track the fp-cache paged
    logits; pools actually hold int8."""
    m = _llama_eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 8)).astype(np.int64))
    with paddle.no_grad():
        fp_logits, _ = m.paged_prefill(ids, block_size=8)
        scales = m.calibrate_cachekv_int8(ids)
        assert len(scales) == m.config.num_hidden_layers
        q_logits, q_state = m.paged_prefill(ids, block_size=8)
    assert str(q_state["layers"][0][0].dtype) in ("paddle.int8", "int8")
    rel = (np.abs(q_logits.numpy() - fp_logits.numpy()).max()
           / (np.abs(fp_logits.numpy()).max() + 1e-9))
    assert rel < 0.05, rel
    m.calibrate_cachekv_int8(None)      # disable restores fp pools
    with paddle.no_grad():
        _, state2 = m.paged_prefill(ids, block_size=8)
    assert "int8" not in str(state2["layers"][0][0].dtype)


def test_cachekv_int8_serving_algebra_exact():
    """Quantized-cache generate_paged vs the quantized-cache batcher must
    be token-exact (the int8 cache changes logits, never the scheduler)."""
    m = _llama_eval()
    rng = np.random.RandomState(1)
    calib = paddle.to_tensor(rng.randint(0, 128, (2, 10)).astype(np.int64))
    with paddle.no_grad():
        m.calibrate_cachekv_int8(calib)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 8)]

    def solo(p, n):
        ids = paddle.to_tensor(np.asarray(p, np.int64)[None])
        with paddle.no_grad():
            return m.generate_paged(ids, max_new_tokens=n,
                                    block_size=8).numpy()[0]

    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               compile=False)
    assert str(b._state["layers"][0][0].dtype).endswith("int8")
    rids = [b.submit(p, 5) for p in prompts]
    outs = b.run_until_done()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid], solo(p, 5))


def test_cachekv_int8_mha_functional():
    """block_multihead_attention's static cachekv-int8 mode: int8 pools +
    per-head scales reproduce the fp-cache output within quant noise."""
    import jax.numpy as jnp
    from paddle_tpu.incubate.nn.functional.decode_attention import \
        block_multihead_attention
    rng = np.random.RandomState(2)
    b, h, d, bs, bps, s = 2, 4, 16, 8, 2, 6
    n_blocks = b * bps
    qkv = paddle.to_tensor(rng.randn(b * s, 3 * h * d).astype(np.float32))
    bt = paddle.to_tensor(
        np.arange(n_blocks, dtype=np.int32).reshape(b, bps))
    enc = paddle.to_tensor(np.full((b,), s, np.int32))
    dec = paddle.to_tensor(np.zeros((b,), np.int32))
    cu = paddle.to_tensor(np.arange(b + 1, dtype=np.int32) * s)

    kc = paddle.zeros([n_blocks, h, bs, d], dtype="float32")
    vc = paddle.zeros([n_blocks, h, bs, d], dtype="float32")
    fp_out, _, fkc, fvc = block_multihead_attention(
        qkv, kc, vc, enc, dec, enc, None, None, cu, cu, bt, block_size=bs)

    amax_k = np.abs(np.asarray(fkc._data)).max(axis=(0, 2, 3)) + 1e-6
    amax_v = np.abs(np.asarray(fvc._data)).max(axis=(0, 2, 3)) + 1e-6
    kq = paddle.to_tensor((127.0 / amax_k).astype(np.float32))
    vq = paddle.to_tensor((127.0 / amax_v).astype(np.float32))
    kdq = paddle.to_tensor((amax_k / 127.0).astype(np.float32))
    vdq = paddle.to_tensor((amax_v / 127.0).astype(np.float32))
    kc8 = paddle.zeros([n_blocks, h, bs, d], dtype="int8")
    vc8 = paddle.zeros([n_blocks, h, bs, d], dtype="int8")
    q_out, _, qkc, qvc = block_multihead_attention(
        qkv, kc8, vc8, enc, dec, enc, None, None, cu, cu, bt,
        cache_k_quant_scales=kq, cache_v_quant_scales=vq,
        cache_k_dequant_scales=kdq, cache_v_dequant_scales=vdq,
        block_size=bs)
    assert str(qkc.dtype).endswith("int8")
    rel = (np.abs(q_out.numpy() - fp_out.numpy()).max()
           / (np.abs(fp_out.numpy()).max() + 1e-9))
    assert rel < 0.05, rel


def test_cachekv_scale_contract_errors():
    """Partial scale sets and int8-pool-without-scales are loud errors,
    never silent truncation (review finding)."""
    from paddle_tpu.incubate.nn.functional.decode_attention import \
        block_gqa_attention
    rng = np.random.RandomState(3)
    b, h, kvh, d, bs, bps, s = 1, 4, 2, 8, 4, 2, 3
    q = paddle.to_tensor(rng.randn(b * s, h, d).astype(np.float32))
    k = paddle.to_tensor(rng.randn(b * s, kvh, d).astype(np.float32))
    v = paddle.to_tensor(rng.randn(b * s, kvh, d).astype(np.float32))
    bt = paddle.to_tensor(np.arange(b * bps, dtype=np.int32).reshape(b, bps))
    enc = paddle.to_tensor(np.full((b,), s, np.int32))
    dec = paddle.to_tensor(np.zeros((b,), np.int32))
    cu = paddle.to_tensor(np.arange(b + 1, dtype=np.int32) * s)
    sc = paddle.to_tensor(np.ones((kvh,), np.float32))
    kc8 = paddle.zeros([b * bps, kvh, bs, d], dtype="int8")
    vc8 = paddle.zeros([b * bps, kvh, bs, d], dtype="int8")
    kcf = paddle.zeros([b * bps, kvh, bs, d], dtype="float32")
    vcf = paddle.zeros([b * bps, kvh, bs, d], dtype="float32")
    # int8 pool, no scales
    with pytest.raises(ValueError, match="int8 cache pool"):
        block_gqa_attention(q, k, v, kc8, vc8, enc, dec, enc, cu, bt,
                            block_size=bs)
    # partial scales
    with pytest.raises(ValueError, match="all four"):
        block_gqa_attention(q, k, v, kc8, vc8, enc, dec, enc, cu, bt,
                            block_size=bs, cache_k_dequant_scales=sc)
    # scales against an fp pool
    with pytest.raises(ValueError, match="allocate int8"):
        block_gqa_attention(q, k, v, kcf, vcf, enc, dec, enc, cu, bt,
                            block_size=bs, cache_k_quant_scales=sc,
                            cache_v_quant_scales=sc,
                            cache_k_dequant_scales=sc,
                            cache_v_dequant_scales=sc)


def test_cachekv_int8_gpt2_paged():
    """The MHA family gets the same cache-int8 wiring: calibrated GPT-2
    paged decode runs on int8 pools and the serving algebra stays exact."""
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(5)
    calib = paddle.to_tensor(rng.randint(0, 128, (2, 10)).astype(np.int64))
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 6)).astype(np.int64))
    with paddle.no_grad():
        fp = m.generate_paged(ids, max_new_tokens=6, block_size=8).numpy()
        m.calibrate_cachekv_int8(calib)
        _, state = m.paged_prefill(ids, block_size=8)
        assert str(state["layers"][0][0].dtype).endswith("int8")
        q8 = m.generate_paged(ids, max_new_tokens=6, block_size=8).numpy()
    # int8 cache tracks fp decode on a tiny model: compare only the
    # GENERATED suffix (the echoed prompt always matches)
    assert (fp[:, 6:] == q8[:, 6:]).mean() > 0.8
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               compile=False)
    rid = b.submit(np.asarray(ids.numpy()[0]), 5)
    outs = b.run_until_done()
    with paddle.no_grad():
        solo = m.generate_paged(paddle.to_tensor(ids.numpy()[:1]),
                                max_new_tokens=5, block_size=8).numpy()[0]
    np.testing.assert_array_equal(outs[rid], solo)
    m.calibrate_cachekv_int8(None)


def test_cachekv_dynamic_quant_gqa():
    """Dynamic cachekv-int8 (reference DynamicQuantCacheKernel): prefill
    with no scales computes per-(sequence, head) scales and returns them;
    decode consumes them; output tracks the fp path within quant noise."""
    from paddle_tpu.incubate.nn.functional.decode_attention import \
        block_gqa_attention
    rng = np.random.RandomState(7)
    b, h, kvh, d, bs, bps, s = 2, 4, 2, 16, 8, 3, 6
    n_blocks = b * bps

    def mk(shape):
        return paddle.to_tensor(rng.randn(*shape).astype(np.float32))

    q, k, v = mk((b * s, h, d)), mk((b * s, kvh, d)), mk((b * s, kvh, d))
    bt = paddle.to_tensor(np.arange(n_blocks, dtype=np.int32).reshape(b, bps))
    enc = paddle.to_tensor(np.full((b,), s, np.int32))
    dec0 = paddle.to_tensor(np.zeros((b,), np.int32))
    cu = paddle.to_tensor(np.arange(b + 1, dtype=np.int32) * s)

    # fp reference: prefill + one decode step
    kcf = paddle.zeros([n_blocks, kvh, bs, d], dtype="float32")
    vcf = paddle.zeros([n_blocks, kvh, bs, d], dtype="float32")
    fp_out, kcf, vcf = block_gqa_attention(q, k, v, kcf, vcf, enc, dec0,
                                           enc, cu, bt, block_size=bs)
    q1, k1, v1 = mk((b, h, d)), mk((b, kvh, d)), mk((b, kvh, d))
    dec1 = paddle.to_tensor(np.full((b,), s, np.int32))
    one = paddle.to_tensor(np.ones((b,), np.int32))
    cu1 = paddle.to_tensor(np.arange(b + 1, dtype=np.int32))
    zero = paddle.to_tensor(np.zeros((b,), np.int32))
    fp_dec, _, _ = block_gqa_attention(q1, k1, v1, kcf, vcf, zero, dec1,
                                       one, cu1, bt, block_size=bs)

    # dynamic int8: prefill computes + returns [B, KV] scales
    kc8 = paddle.zeros([n_blocks, kvh, bs, d], dtype="int8")
    vc8 = paddle.zeros([n_blocks, kvh, bs, d], dtype="int8")
    q_out, kc8, vc8, scales = block_gqa_attention(
        q, k, v, kc8, vc8, enc, dec0, enc, cu, bt, block_size=bs,
        use_dynamic_cachekv_quant=True, compute_dynamic_scales=True)
    kq, vq, kdq, vdq = scales
    assert list(kq.shape) == [b, kvh]
    rel = (np.abs(q_out.numpy() - fp_out.numpy()).max()
           / (np.abs(fp_out.numpy()).max() + 1e-9))
    assert rel < 0.05, rel
    # decode consumes the prefill's scales
    q_dec, kc8, vc8 = block_gqa_attention(
        q1, k1, v1, kc8, vc8, zero, dec1, one, cu1, bt, block_size=bs,
        cache_k_quant_scales=kq, cache_v_quant_scales=vq,
        cache_k_dequant_scales=kdq, cache_v_dequant_scales=vdq,
        use_dynamic_cachekv_quant=True)
    rel = (np.abs(q_dec.numpy() - fp_dec.numpy()).max()
           / (np.abs(fp_dec.numpy()).max() + 1e-9))
    assert rel < 0.08, rel


def test_cachekv_dynamic_quant_mha_prefill_returns_scales():
    from paddle_tpu.incubate.nn.functional.decode_attention import \
        block_multihead_attention
    rng = np.random.RandomState(8)
    b, h, d, bs, bps, s = 2, 4, 16, 8, 2, 5
    n_blocks = b * bps
    qkv = paddle.to_tensor(rng.randn(b * s, 3 * h * d).astype(np.float32))
    bt = paddle.to_tensor(np.arange(n_blocks, dtype=np.int32).reshape(b, bps))
    enc = paddle.to_tensor(np.full((b,), s, np.int32))
    dec = paddle.to_tensor(np.zeros((b,), np.int32))
    cu = paddle.to_tensor(np.arange(b + 1, dtype=np.int32) * s)
    kc8 = paddle.zeros([n_blocks, h, bs, d], dtype="int8")
    vc8 = paddle.zeros([n_blocks, h, bs, d], dtype="int8")
    out = block_multihead_attention(
        qkv, kc8, vc8, enc, dec, enc, None, None, cu, cu, bt,
        block_size=bs, use_dynamic_cachekv_quant=True,
        compute_dynamic_scales=True)
    assert len(out) == 5
    kq, vq, kdq, vdq = out[4]
    assert list(kq.shape) == [b, h]
    np.testing.assert_allclose(kq.numpy() * kdq.numpy(),
                               np.ones((b, h)), rtol=1e-5)


def test_cachekv_dynamic_decode_without_scales_raises():
    """A dynamic call that forgot the prefill's scales must error loudly
    — EVEN under jit tracing (ADVICE r3: scale computation is an explicit
    compute_dynamic_scales opt-in, not inferred from scale absence), and
    a decode-shaped call that wrongly opts in is caught by the
    concrete-length guard."""
    from paddle_tpu.incubate.nn.functional.decode_attention import \
        block_gqa_attention
    rng = np.random.RandomState(9)
    b, h, kvh, d, bs, bps = 1, 2, 2, 8, 4, 2
    q = paddle.to_tensor(rng.randn(b, h, d).astype(np.float32))
    k = paddle.to_tensor(rng.randn(b, kvh, d).astype(np.float32))
    v = paddle.to_tensor(rng.randn(b, kvh, d).astype(np.float32))
    bt = paddle.to_tensor(np.arange(b * bps, dtype=np.int32).reshape(b, bps))
    zero = paddle.to_tensor(np.zeros((b,), np.int32))
    dec = paddle.to_tensor(np.full((b,), 3, np.int32))
    one = paddle.to_tensor(np.ones((b,), np.int32))
    cu = paddle.to_tensor(np.arange(b + 1, dtype=np.int32))
    kc8 = paddle.zeros([b * bps, kvh, bs, d], dtype="int8")
    vc8 = paddle.zeros([b * bps, kvh, bs, d], dtype="int8")
    # no scales, no opt-in: static python error (survives tracing)
    with pytest.raises(ValueError, match="compute_dynamic_scales"):
        block_gqa_attention(q, k, v, kc8, vc8, zero, dec, one, cu, bt,
                            block_size=bs, use_dynamic_cachekv_quant=True)
    # decode-shaped call that wrongly opts in: concrete-length guard
    with pytest.raises(ValueError, match="decode-mode"):
        block_gqa_attention(q, k, v, kc8, vc8, zero, dec, one, cu, bt,
                            block_size=bs, use_dynamic_cachekv_quant=True,
                            compute_dynamic_scales=True)
    # opt-in together with given scales: ambiguous, rejected
    ones = paddle.to_tensor(np.ones((b, kvh), np.float32))
    with pytest.raises(ValueError, match="ambiguous"):
        block_gqa_attention(q, k, v, kc8, vc8, zero, dec, one, cu, bt,
                            block_size=bs, use_dynamic_cachekv_quant=True,
                            compute_dynamic_scales=True,
                            cache_k_quant_scales=ones,
                            cache_v_quant_scales=ones,
                            cache_k_dequant_scales=ones,
                            cache_v_dequant_scales=ones)


def test_dynamic_int8_batcher_end_to_end():
    """cache_quant='dynamic_int8': each sequence's prefill computes its
    own per-(slot, head) scales, decode consumes them from the state,
    eviction resets the rows — across slot reuse and compiled steps."""
    m = _llama_eval()
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 9, 7, 12)]

    def ref(p, n):
        ids = paddle.to_tensor(np.asarray(p, np.int64)[None])
        with paddle.no_grad():
            return m.generate(ids, max_new_tokens=n).numpy()[0]

    # more requests than slots: slot + scale-row reuse under compile
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               cache_quant="dynamic_int8", compile=True)
    assert str(b._state["layers"][0][0].dtype).endswith("int8")
    rids = [b.submit(p, 6) for p in prompts]
    outs = b.run_until_done()
    agrees = []
    for rid, p in zip(rids, prompts):
        r = ref(p, 6)
        agrees.append((outs[rid][len(p):] == r[len(p):]).mean())
    assert np.mean(agrees) > 0.8, agrees
    # pool + scale rows fully reclaimed
    assert b.free_page_count == b.n_pages
    for layer in b._scales_np:
        for k in layer:
            np.testing.assert_array_equal(layer[k],
                                          np.ones_like(layer[k]))


def test_dynamic_int8_chunked_short_prompts_match_unchunked():
    """VERDICT r3 #5: dynamic cachekv-int8 composes with chunked prefill.
    For prompts no longer than the chunk width, chunk 1 IS the whole
    prompt (pad tail masked out of the scale stats), so the chunked
    batcher must be TOKEN-EXACT against the unchunked dynamic batcher."""
    from test_paged_batching import _retry_load_flake
    m = _llama_eval()
    rng = np.random.RandomState(12)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 8, 3, 7)]

    def run(chunk):
        paddle.seed(0)
        b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                                   cache_quant="dynamic_int8",
                                   prefill_chunk=chunk, compile=True)
        rids = [b.submit(p, 6) for p in prompts]
        outs = b.run_until_done()
        return [outs[r] for r in rids], b

    state = {}

    def body():
        # retry wrapper (suite-wide CPU discipline): chunked and unchunked
        # prefill are DIFFERENT executables (padded C vs exact L shapes),
        # so tiny-model argmax near-ties can flip between them on the
        # threaded CPU backend; the quantization contract itself is
        # deterministic and a logic bug reproduces across retries
        chunked, cb = run(8)
        unchunked, _ = run(None)
        for c, u in zip(chunked, unchunked):
            np.testing.assert_array_equal(c, u)
        state["cb"] = cb

    _retry_load_flake(body, attempts=3)
    cb = state["cb"]
    # pool + scale rows fully reclaimed after the chunked run
    assert cb.free_page_count == cb.n_pages
    for layer in cb._scales_np:
        for k in layer:
            np.testing.assert_array_equal(layer[k], np.ones_like(layer[k]))


def test_dynamic_int8_chunked_long_prompts_scale_consistent():
    """Prompts LONGER than the chunk width: scales come from the first
    chunk's rows and every later chunk + decode quantizes with them.
    Pin the batcher against a manual model-level chunk loop implementing
    the same contract (first chunk computes, rest consume), and sanity-
    check agreement with the fp solo path."""
    from test_paged_batching import _retry_load_flake
    _retry_load_flake(_long_prompt_body, attempts=3)


def _long_prompt_body():
    # eager manual loop vs compiled batcher executables: different fp
    # reduction orders can flip tiny-model argmax near-ties on the CPU
    # backend — hence the retry wrapper above; the scale-threading
    # contract itself is deterministic
    m = _llama_eval()
    rng = np.random.RandomState(13)
    C, bs = 8, 8
    prompt = rng.randint(0, 128, (19,))
    new = 5

    # -- manual reference: chunked prefill + greedy paged decode ---------
    bps = 32 // bs
    bt = paddle.to_tensor(np.arange(bps, dtype=np.int32).reshape(1, bps))
    pool = m.paged_alloc(bps + 1, bs, cache_dtype="int8")
    L = len(prompt)
    padded_len = -(-L // C) * C
    padded = np.zeros((padded_len,), np.int64)
    padded[:L] = prompt
    scales = None
    logits = None
    with paddle.no_grad():
        dec = 0
        while dec < padded_len:
            w = min(C, padded_len - dec)
            has_last = 0 <= (L - 1) - dec < w
            at = (L - 1) - dec if has_last else 0
            ids_t = paddle.to_tensor(padded[None, dec:dec + w])
            dec_t = paddle.to_tensor(np.array([dec], np.int32))
            at_t = paddle.to_tensor(np.array([at], np.int32))
            if scales is None:
                lg, pool, scales = m.paged_prefill_into(
                    ids_t, pool, bt, bs, dec_base=dec_t, logits_at=at_t,
                    dynamic_cache_scales=True,
                    dynamic_scale_valid=paddle.to_tensor(
                        np.array([min(L - dec, w)], np.int32)))
            else:
                lg, pool = m.paged_prefill_into(
                    ids_t, pool, bt, bs, dec_base=dec_t, logits_at=at_t,
                    cache_scales=scales)
            if has_last:
                logits = lg
            dec += w
        toks = [int(np.argmax(logits.numpy()[0]))]
        state = {"layers": pool, "block_tables": bt,
                 "dec_lens": paddle.to_tensor(np.array([L], np.int32)),
                 "block_size": bs, "capacity": bps * bs,
                 "zeros_b": paddle.to_tensor(np.zeros((1,), np.int32)),
                 "ones_b": paddle.to_tensor(np.ones((1,), np.int32)),
                 "cu_b": paddle.to_tensor(np.arange(2, dtype=np.int32)),
                 "cache_scales": scales}
        for _ in range(new - 1):
            lg, state = m.paged_decode_step(
                paddle.to_tensor(np.array([toks[-1]], np.int64)), state)
            toks.append(int(np.argmax(lg.numpy()[0])))
    expected = np.concatenate([prompt, np.asarray(toks)])

    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=bs,
                               cache_quant="dynamic_int8",
                               prefill_chunk=C, compile=True)
    rid = b.submit(prompt, new)
    outs = b.run_until_done()
    np.testing.assert_array_equal(outs[rid], expected)

    # quant noise must not derail generation vs the fp model
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None])
    with paddle.no_grad():
        ref = m.generate(ids, max_new_tokens=new).numpy()[0]
    agree = (outs[rid][L:] == ref[L:]).mean()
    assert agree >= 0.6, (outs[rid][L:], ref[L:])


def test_chunked_int8_clip_telemetry():
    """ADVICE r4 (serving.py:605): later-chunk K/V saturation against
    first-window scales must be observable — a running clip-rate counter
    in stats() and a one-time RuntimeWarning above 1% saturation."""
    import warnings
    m = _llama_eval()
    bs, C = 8, 8
    b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=bs,
                               cache_quant="dynamic_int8",
                               prefill_chunk=C, compile=True)
    # the counter exists and starts clean
    assert b.stats()["cachekv_clip_rate"] == 0.0
    # long prompt -> rest chunks run -> elements get counted
    rng = np.random.RandomState(14)
    rid = b.submit(rng.randint(0, 128, (19,)), 3)
    b.run_until_done()
    assert b._stat_cachekv_elems > 0
    rate = b.stats()["cachekv_clip_rate"]
    assert 0.0 <= rate <= 1.0
    # plant a fully-saturated chunk and drive the recorder directly: the
    # running rate must move and the warning must fire exactly once
    kc, vc = b._state["layers"][0]
    sat = kc._data.at[:].set(127)
    kc._set_data(sat)
    bt_row = paddle.to_tensor(np.arange(4, dtype=np.int32).reshape(1, 4))
    before = b._stat_cachekv_clipped
    b._warned_cachekv_clip = False
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        b._record_chunk_saturation(bt_row, dec=8, nvalid=8)
        b._record_chunk_saturation(bt_row, dec=8, nvalid=8)
    assert b._stat_cachekv_clipped > before
    clip_warns = [w for w in caught
                  if issubclass(w.category, RuntimeWarning)
                  and "top quantization bin" in str(w.message)]
    assert len(clip_warns) == 1, [str(w.message) for w in caught]
    # baseline-relative threshold: a peaked-but-unclipped distribution
    # (rest rate <= 3x the first chunk's own top-bin rate) must NOT warn
    b._warned_cachekv_clip = False
    with warnings.catch_warnings(record=True) as caught2:
        warnings.simplefilter("always")
        b._record_chunk_saturation(bt_row, dec=8, nvalid=8, baseline=0.9)
    assert not [w for w in caught2
                if issubclass(w.category, RuntimeWarning)
                and "top quantization bin" in str(w.message)]


def test_dynamic_int8_rejects_bad_configs():
    m = _llama_eval()
    with pytest.raises(ValueError, match="unknown cache_quant"):
        PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               cache_quant="int4", compile=False)
    with pytest.raises(ValueError, match="not supported"):
        PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               cache_quant="dynamic_int8", prefill_chunk=8,
                               fused_admission=True, compile=False)
    with pytest.raises(ValueError, match="prefill_chunk >= 2"):
        PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                               cache_quant="dynamic_int8", prefill_chunk=1,
                               compile=False)


def test_static_cachekv_int8_with_fused_admission_token_exact():
    """The fused-admission executable already threads STATIC per-head
    cache scales (paged_fused_step passes _cachekv_scales); pin the whole
    combination end-to-end: a calibrated model served through the fused
    decode+prefill batcher is token-exact vs its own solo paged generate
    (dynamic x fused remains excluded; static calibration is the
    documented route)."""
    from test_paged_batching import _retry_load_flake
    m = _llama_eval()
    rng = np.random.RandomState(17)
    calib = paddle.to_tensor(rng.randint(0, 128, (2, 12)).astype(np.int64))
    with paddle.no_grad():
        m.calibrate_cachekv_int8(calib)
    try:
        prompts = [rng.randint(0, 128, (s,)) for s in (5, 11, 8)]

        def body():
            b = PagedContinuousBatcher(m, max_batch=2, s_max=32,
                                       block_size=8, prefill_chunk=8,
                                       fused_admission=True, compile=True)
            assert str(b._state["layers"][0][0].dtype).endswith("int8")
            rids = [b.submit(p, 5) for p in prompts]
            outs = b.run_until_done()
            for rid, p in zip(rids, prompts):
                ids = paddle.to_tensor(np.asarray(p, np.int64)[None])
                with paddle.no_grad():
                    ref = m.generate_paged(ids, max_new_tokens=5,
                                           block_size=8).numpy()[0]
                np.testing.assert_array_equal(outs[rid], ref)

        _retry_load_flake(body, attempts=3)
    finally:
        m.calibrate_cachekv_int8(None)
