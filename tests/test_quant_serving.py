"""Weight-only int8 serving through the full decode stack.

Reference surface: nn/quant/quantized_linear.py weight_only_linear powering
the serving predictor's int8 path. The machinery invariant under test:
every serving route (dense KV, paged KV, continuous batchers, compiled
steps) must be TOKEN-EXACT against the quantized model's own solo
generate — quantization changes the logits, never the serving algebra.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import (ContinuousBatcher,
                                          PagedContinuousBatcher)
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
from paddle_tpu.nn.quant import quantize_linear_layers


def _quantized_gpt2(algo="weight_only_int8"):
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    n = quantize_linear_layers(m, algo)
    assert n > 0
    return m


def test_int8_logits_close_to_fp():
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (1, 6)).astype(np.int64))
    with paddle.no_grad():
        fp = m(ids).numpy()
    quantize_linear_layers(m)
    with paddle.no_grad():
        q8 = m(ids).numpy()
    rel = np.abs(q8 - fp).max() / (np.abs(fp).max() + 1e-9)
    assert rel < 0.05, rel


@pytest.mark.smoke
def test_quantized_paged_matches_quantized_dense():
    m = _quantized_gpt2()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 128, (2, 6)).astype(np.int64))
    with paddle.no_grad():
        dense = m.generate(ids, max_new_tokens=7).numpy()
        paged = m.generate_paged(ids, max_new_tokens=7, block_size=8).numpy()
    np.testing.assert_array_equal(dense, paged)


def test_quantized_batchers_token_exact():
    m = _quantized_gpt2()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 128, (s,)) for s in (5, 8)]

    def solo(p, n):
        ids = paddle.to_tensor(np.asarray(p, np.int64)[None])
        with paddle.no_grad():
            return m.generate(ids, max_new_tokens=n).numpy()[0]

    with paddle.no_grad():
        dense_b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        rids = [dense_b.submit(p, 5) for p in prompts]
        outs = dense_b.run_until_done()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid], solo(p, 5))

    paged_b = PagedContinuousBatcher(m, max_batch=2, s_max=32, block_size=8,
                                     policy="ondemand", compile=False)
    rids = [paged_b.submit(p, 5) for p in prompts]
    outs = paged_b.run_until_done()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(outs[rid], solo(p, 5))


def test_quantized_compiled_decode_matches_eager():
    from paddle_tpu import jit
    m = _quantized_gpt2()
    ids = paddle.to_tensor(
        np.random.RandomState(3).randint(0, 128, (2, 6)).astype(np.int64))
    with paddle.no_grad():
        ref = m.generate_paged(ids, max_new_tokens=6, block_size=8).numpy()
        step = jit.to_static(m.paged_decode_step)
        out = m.generate_paged(ids, max_new_tokens=6, block_size=8,
                               decode_fn=step).numpy()
    np.testing.assert_array_equal(ref, out)


def test_int4_serving_runs():
    m = _quantized_gpt2("weight_only_int4")
    ids = paddle.to_tensor(
        np.random.RandomState(4).randint(0, 128, (1, 5)).astype(np.int64))
    with paddle.no_grad():
        dense = m.generate(ids, max_new_tokens=5).numpy()
        paged = m.generate_paged(ids, max_new_tokens=5, block_size=8).numpy()
    np.testing.assert_array_equal(dense, paged)
