"""Durable sessions (round 20): crash-safe manifests + pipelined resume.

Five layers, <60s total:

  * manifest durability — publish/load roundtrip, the atomic
    temp+``os.replace`` pattern under a chaos torn write at
    ``kv.session_publish`` (typed ``publish_torn``/``torn_manifest``
    findings, the previous manifest stays sound), whole-document and
    per-entry CRC rejection, chain-hash drift, model-identity mismatch,
    and the ``kv.session_resume`` chaos seam degrading to None;
  * pin-through-demotion — a paused session's chain cascades host→disk
    under churn but never OUT of the last tier (``session_pin_drops``
    stays 0) while an unpinned control chain of the same shape drops;
    resume rides tiered promotion and stays bitwise token-exact against
    the uninterrupted two-turn reference, serial == pipelined;
  * transfer plumbing — ``AsyncLoader.close()`` fails QUEUED transfers
    with ``TransferCancelled`` deterministically while the in-flight
    one is allowed to land;
  * fleet drills — pause → kill the pinned replica → rescale → resume
    on a survivor (manifest-resolved, bitwise exact, pages audited), the
    mid-promotion replica kill finished by the survivor, drain/requeue
    preserving session pins, and a second gateway process resolving the
    session from the shared store alone;
  * tooling — the agentic traffic population (seed-deterministic,
    resumes audited by ``drive``), ``telemetry_dump --sessions``,
    ``tools/session_inspect.py`` verdicts, and the ``session:``
    bench_guard lane gating a synthetic goodput regression.
"""
import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import PagedContinuousBatcher
from paddle_tpu.inference.session_store import (SessionManifest,
                                                SessionStore,
                                                model_identity)
from paddle_tpu.resilience import arm_scenario, disarm

pytestmark = pytest.mark.session

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BLOCK_BYTES = 2 * 2 * 16 * 64 * 4      # layers x k/v x block x hidden x f32


@pytest.fixture(autouse=True)
def _disarm():
    disarm()
    yield
    disarm()


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _ref(lm, prompt, n):
    return np.asarray(lm.generate(np.asarray(prompt).reshape(1, -1),
                                  max_new_tokens=n)).reshape(-1)


def _tiered(lm, tmp, host_blocks=2, disk_blocks=64, slots=3, chunk=2,
            **kw):
    """Tiered batcher with a shared-store mount under ``tmp``: host tier
    sized in BLOCKS (so tests control exactly how far churn cascades),
    disk tier + manifest store on the shared volume."""
    kw.setdefault("max_batch", 2)
    kw.setdefault("s_max", 96)
    kw.setdefault("block_size", 16)
    kw.setdefault("n_pages", 14)
    kw.setdefault("compile", False)
    kw.setdefault("policy", "ondemand")
    kw.setdefault("prefix_cache", True)
    kw.setdefault("host_kv_gib", host_blocks * BLOCK_BYTES * 1.05 / 2**30)
    kw.setdefault("disk_kv_dir", os.path.join(str(tmp), "kv_disk"))
    kw.setdefault("disk_kv_gib", disk_blocks * BLOCK_BYTES * 1.05 / 2**30)
    kw.setdefault("session_store", os.path.join(str(tmp), "sessions"))
    kw.setdefault("promo_slots", slots)
    kw.setdefault("promo_chunk_blocks", chunk)
    return PagedContinuousBatcher(lm, **kw)


def _run(bt, prompt, n):
    rid = bt.submit(np.asarray(prompt, np.int64), n)
    return bt.run_until_done(max_steps=60000)[rid]


def _churn(bt, seed=3, n=10, length=51):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        bt.submit(rng.randint(0, 128, (length,)).astype(np.int64), 4)
    bt.run_until_done(max_steps=60000)


# -- manifest durability ------------------------------------------------------

def test_manifest_roundtrip_sessions_and_delete(tmp_path):
    from paddle_tpu.inference.prefix_cache import chain_hashes
    store = SessionStore(str(tmp_path))
    toks = list(range(40))
    m = SessionManifest(session_id="alpha/1 weird", token_ids=toks,
                        block_size=16, model="GPT2:deadbeef")
    assert m.chain == chain_hashes(toks, 16) and m.n_blocks == 2
    assert store.publish(m)
    assert store.sessions() == ["alpha/1 weird"]
    got = store.load("alpha/1 weird", expect_model="GPT2:deadbeef")
    assert got is not None
    assert got.token_ids == toks and got.chain == m.chain
    assert got.covered_tokens == 32
    assert store.findings == []
    assert store.delete("alpha/1 weird")
    assert store.load("alpha/1 weird") is None
    assert store.findings[-1].kind == "missing"


def test_publish_torn_write_typed_finding_and_heal(tmp_path):
    store = SessionStore(str(tmp_path))
    m = SessionManifest(session_id="s", token_ids=list(range(32)),
                        block_size=16)
    arm_scenario("seed=0; kv.session_publish:torn_write:offset=25,count=1")
    assert store.publish(m) is False
    assert store.findings[-1].kind == "publish_torn"
    # crash debris: only a .tmp exists — no reader trusts it
    assert os.path.exists(store.path_for("s") + ".tmp")
    assert store.load("s") is None
    assert store.findings[-1].kind == "torn_manifest"
    # the seam heals once chaos passes; the next publish is atomic
    assert store.publish(m) is True
    assert store.load("s").token_ids == list(range(32))


def test_torn_publish_never_clobbers_previous_manifest(tmp_path):
    store = SessionStore(str(tmp_path))
    v1 = SessionManifest(session_id="s", token_ids=list(range(32)),
                         block_size=16)
    assert store.publish(v1)
    arm_scenario("seed=0; kv.session_publish:torn_write:offset=9,count=1")
    v2 = SessionManifest(session_id="s", token_ids=list(range(48)),
                         block_size=16)
    assert store.publish(v2) is False
    disarm()
    got = store.load("s")            # previous manifest is still sound
    assert got is not None and got.token_ids == list(range(32))


def test_load_rejects_corruption_and_model_mismatch(tmp_path):
    import zlib
    store = SessionStore(str(tmp_path))
    m = SessionManifest(session_id="s", token_ids=list(range(48)),
                        block_size=16, model="GPT2:cafe0000")
    assert store.publish(m)
    fpath = store.path_for("s")
    sound = open(fpath, "rb").read()

    # 1. flip a token, keep the recorded CRCs -> document checksum
    doc = json.loads(sound)
    doc["tokens"][5] ^= 1
    open(fpath, "wb").write(json.dumps(doc, sort_keys=True).encode())
    assert store.load("s") is None
    assert store.findings[-1].kind == "checksum_mismatch"

    # 2. re-seal the document CRC over the drifted chain entry -> the
    # per-entry layer catches what the document layer now misses
    doc = json.loads(sound)
    doc["blocks"][1]["h"] = "0" * 16
    body = {k: v for k, v in doc.items() if k != "crc"}
    doc["crc"] = zlib.crc32(
        json.dumps(body, sort_keys=True).encode()) & 0xFFFFFFFF
    open(fpath, "wb").write(json.dumps(doc, sort_keys=True).encode())
    assert store.load("s") is None
    assert store.findings[-1].kind == "hash_drift"

    # 3. sound bytes, wrong serving model -> typed mismatch, no resume
    open(fpath, "wb").write(sound)
    assert store.load("s", expect_model="GPT2:00000001") is None
    assert store.findings[-1].kind == "model_mismatch"
    assert store.load("s", expect_model="GPT2:cafe0000") is not None


def test_resume_fault_chaos_seam_degrades_to_none(tmp_path):
    store = SessionStore(str(tmp_path))
    m = SessionManifest(session_id="s", token_ids=list(range(32)),
                        block_size=16)
    assert store.publish(m)
    arm_scenario("seed=0; kv.session_resume:transient_error:count=1")
    assert store.load("s") is None
    assert store.findings[-1].kind == "resume_fault"
    assert store.load("s") is not None       # fault exhausted


# -- pin-through-demotion + pipelined resume ---------------------------------

def test_session_pin_survives_churn_resume_rides_promotion(lm, tmp_path):
    """The tentpole property: churn cascades a paused session's chain
    down the tiers but never out; the resume promotes it back and the
    two-turn conversation is bitwise identical to never pausing."""
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    control = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (5,)).astype(np.int64)
    base1 = _ref(lm, prompt, 6)
    base2 = _ref(lm, np.concatenate([base1, cont]), 6)

    bt = _tiered(lm, tmp_path, host_blocks=2, disk_blocks=6)
    try:
        with paddle.no_grad():
            out1 = _run(bt, prompt, 6)
            np.testing.assert_array_equal(out1, base1)
            _run(bt, control, 6)             # same shape, NOT pinned
            assert bt.pause_session("conv", out1) is True
            _churn(bt)
            pins = bt._session_pins["conv"]
            assert len(pins) == 3
            res = {n.residency for n in pins}
            assert "gone" not in res and res != {"device"}, res
            st = bt.prefix_cache.stats()
            assert st["session_pin_drops"] == 0
            # the unpinned control chain was dropped by the same churn
            assert len(bt.prefix_cache.match(control)) < 3

            toks = bt.resume_session("conv")
            np.testing.assert_array_equal(toks, out1)
            out2 = _run(bt, np.concatenate([toks, cont]), 6)
            np.testing.assert_array_equal(out2, base2)
            assert bt.prefix_cache.stats()["promotions"] > 0
            bt.audit_pages()
    finally:
        bt.close()


def test_serial_and_pipelined_resume_bitwise_equal(lm, tmp_path):
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (4,)).astype(np.int64)
    outs = []
    for name, (slots, chunk) in (("serial", (1, None)),
                                 ("pipelined", (3, 1))):
        bt = _tiered(lm, tmp_path / name, host_blocks=2, disk_blocks=6,
                     slots=slots, chunk=chunk)
        try:
            with paddle.no_grad():
                out1 = _run(bt, prompt, 6)
                bt.pause_session("conv", out1)
                _churn(bt)
                toks = bt.resume_session("conv")
                outs.append(_run(bt, np.concatenate([toks, cont]), 6))
                bt.audit_pages()
        finally:
            bt.close()
    np.testing.assert_array_equal(outs[0], outs[1])


def test_torn_publish_drill_full_reprefill_token_exact(lm, tmp_path):
    """Replica A's publish tears mid-write and A dies. Replica B shares
    only the store: the resume finds debris (typed finding), degrades to
    a full re-prefill from the caller's context, token-exact."""
    rng = np.random.RandomState(13)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (5,)).astype(np.int64)
    a = _tiered(lm, tmp_path)
    try:
        with paddle.no_grad():
            out1 = _run(a, prompt, 6)
            arm_scenario(
                "seed=0; kv.session_publish:torn_write:offset=40,count=1")
            assert a.pause_session("conv", out1) is False
            assert a.session_store.findings[-1].kind == "publish_torn"
    finally:
        a.close()
    disarm()
    b = _tiered(lm, tmp_path)                # fresh process, same volume
    try:
        with paddle.no_grad():
            assert b.resume_session("conv") is None
            assert b.session_store.findings[-1].kind == "torn_manifest"
            # caller's fallback context -> full prefill, still exact
            out2 = _run(b, np.concatenate([out1, cont]), 6)
            np.testing.assert_array_equal(
                out2, _ref(lm, np.concatenate([out1, cont]), 6))
            b.audit_pages()
    finally:
        b.close()


# -- transfer plumbing --------------------------------------------------------

def test_async_loader_close_cancels_queued_deterministically():
    """The satellite-1 contract: close() fails every QUEUED transfer
    with TransferCancelled (never issued, device untouched) while the
    in-flight one lands normally."""
    from paddle_tpu.perf.prefetch import AsyncLoader, TransferCancelled
    ld = AsyncLoader(depth=4, workers=1)
    gate, started = threading.Event(), threading.Event()

    def slow():
        started.set()
        assert gate.wait(10.0)
        return [np.arange(3, dtype=np.float32)]

    f1 = ld.submit(slow)
    assert started.wait(10.0)                # worker is INSIDE f1
    f2 = ld.submit([np.ones(2, np.float32)])
    f3 = ld.submit([np.ones(4, np.float32)])
    opener = threading.Timer(0.15, gate.set)
    opener.start()
    try:
        ld.close(timeout=10.0)
    finally:
        opener.join()
    for f in (f2, f3):
        with pytest.raises(TransferCancelled):
            f.result(timeout=1.0)
    np.testing.assert_array_equal(
        np.asarray(f1.result(timeout=1.0)[0]), np.arange(3))
    assert not any(t.is_alive() for t in ld._threads)


# -- fleet drills -------------------------------------------------------------

def _gateway(lm, tmp, names=("r0", "r1")):
    from paddle_tpu.inference.gateway import Gateway
    gw = Gateway(policy="affinity",
                 session_store=os.path.join(str(tmp), "sessions"))
    for i, name in enumerate(names):
        gw.add_replica(name, _tiered(lm, os.path.join(str(tmp), name)))
    return gw


def _close_fleet(gw):
    for r in gw.pool.replicas():
        if r.alive:
            r.batcher.close()


def test_acceptance_drill_kill_rescale_resume_bitwise(lm, tmp_path):
    """THE acceptance drill: pause a session, kill its replica, rescale
    the fleet, resume — the resumed turn is bitwise identical to the
    uninterrupted conversation and no survivor leaks a page."""
    rng = np.random.RandomState(17)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (5,)).astype(np.int64)
    base1 = _ref(lm, prompt, 6)
    base2 = _ref(lm, np.concatenate([base1, cont]), 6)

    gw = _gateway(lm, tmp_path)
    with paddle.no_grad():
        gid = gw.submit(prompt, 6, session_id="conv")
        while gw._has_work():
            gw.step()
        np.testing.assert_array_equal(gw.pop_result(gid), base1)
        assert gw.pause_session("conv") is True
        victim = gw._session_last_replica["conv"]
        assert "conv" in gw.pool.get(victim).batcher._session_pins

        # the pinned replica's host dies mid-request (the error kind
        # bypasses the retry policy; prefix affinity routes this
        # throwaway onto the replica holding the session's chain, and
        # its requeue lands on the survivor)
        arm_scenario(f"seed=0; gateway.step.{victim}:transient_error"
                     f":count=1")
        doomed = gw.submit(prompt, 4)
        for _ in range(2000):
            gw.step()
            if not gw.pool.get(victim).alive:
                break
        disarm()
        assert not gw.pool.get(victim).alive
        while gw._has_work():
            gw.step()
        gw.pop_result(doomed)

        gw.add_replica("r2", _tiered(lm, tmp_path / "r2"))   # rescale
        gid2 = gw.resume_session("conv", new_tokens=cont,
                                 max_new_tokens=6)
        while gw._has_work():
            gw.step()
        np.testing.assert_array_equal(gw.pop_result(gid2), base2)
        assert gw.stats()["failures"] == 0
        for r in gw.pool.replicas():
            if r.alive:
                r.batcher.audit_pages()      # raises on any leaked page
    _close_fleet(gw)


def test_mid_promotion_replica_kill_survivor_finishes(lm, tmp_path):
    """Kill the session's replica WHILE its resume promotion is in
    flight: the request requeues and the survivor finishes it by full
    prefill, token-exact."""
    rng = np.random.RandomState(19)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (5,)).astype(np.int64)
    base1 = _ref(lm, prompt, 6)
    base2 = _ref(lm, np.concatenate([base1, cont]), 6)

    gw = _gateway(lm, tmp_path)
    with paddle.no_grad():
        gid = gw.submit(prompt, 6, session_id="conv")
        while gw._has_work():
            gw.step()
        np.testing.assert_array_equal(gw.pop_result(gid), base1)
        gw.pause_session("conv")
        victim = gw._session_last_replica["conv"]
        vb = gw.pool.get(victim).batcher
        with paddle.no_grad():
            _churn(vb)                       # demote the pinned chain
        assert any(n.residency != "device"
                   for n in vb._session_pins["conv"])

        # affinity routes the resume back to ``victim``; its first step
        # opens the promotion stream, the second kills the host under it
        arm_scenario(f"seed=0; gateway.step.{victim}:transient_error"
                     f":after=1,count=1")
        gid2 = gw.resume_session("conv", new_tokens=cont,
                                 max_new_tokens=6)
        for _ in range(4000):
            if not gw._has_work():
                break
            gw.step()
        disarm()
        assert not gw.pool.get(victim).alive
        s = gw.stats()
        assert s["requeued"] > 0 and s["failures"] == 0
        np.testing.assert_array_equal(gw.pop_result(gid2), base2)
        for r in gw.pool.replicas():
            if r.alive:
                r.batcher.audit_pages()
    _close_fleet(gw)


def test_drain_requeue_preserves_session_pins(lm, tmp_path):
    """Remediation's drain path must not orphan paused sessions: pins
    survive the drain and a later resume on the drained replica's warm
    cache still works."""
    rng = np.random.RandomState(23)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (4,)).astype(np.int64)
    gw = _gateway(lm, tmp_path)
    with paddle.no_grad():
        gid = gw.submit(prompt, 6, session_id="conv")
        while gw._has_work():
            gw.step()
        out1 = gw.pop_result(gid)
        gw.pause_session("conv")
        victim = gw._session_last_replica["conv"]
        gw.drain_replica(victim, requeue=True)
        assert "conv" in gw.pool.get(victim).batcher._session_pins
        gid2 = gw.resume_session("conv", new_tokens=cont,
                                 max_new_tokens=6)
        while gw._has_work():
            gw.step()
        np.testing.assert_array_equal(
            gw.pop_result(gid2),
            _ref(lm, np.concatenate([out1, cont]), 6))
    _close_fleet(gw)


def test_fresh_gateway_resolves_session_from_manifest_alone(lm, tmp_path):
    """Replica-independence: a gateway process that never served the
    session (no local record, no fallback) resumes it purely from the
    shared manifest."""
    rng = np.random.RandomState(29)
    prompt = rng.randint(0, 128, (48,)).astype(np.int64)
    cont = rng.randint(0, 128, (5,)).astype(np.int64)
    gw1 = _gateway(lm, tmp_path, names=("a0",))
    with paddle.no_grad():
        gid = gw1.submit(prompt, 6, session_id="conv")
        while gw1._has_work():
            gw1.step()
        out1 = gw1.pop_result(gid)
        assert gw1.pause_session("conv") is True
    _close_fleet(gw1)

    gw2 = _gateway(lm, tmp_path, names=("b0",))   # same shared volume
    with paddle.no_grad():
        gid2 = gw2.resume_session("conv", new_tokens=cont,
                                  max_new_tokens=6)
        while gw2._has_work():
            gw2.step()
        np.testing.assert_array_equal(
            gw2.pop_result(gid2),
            _ref(lm, np.concatenate([out1, cont]), 6))
    _close_fleet(gw2)


# -- tooling ------------------------------------------------------------------

def test_traffic_agentic_population_deterministic_and_audited(lm,
                                                              tmp_path):
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    try:
        import traffic
    finally:
        sys.path.pop(0)
    spec = traffic.TrafficSpec(
        seed=5, steps=8, vocab=128, base_rate=0.4, pattern="steady",
        prompt_lo=8, prompt_hi=20, new_lo=4, new_hi=6, shared_frac=0.0,
        session_frac=0.0, agentic_frac=1.0, agentic_turns_lo=1,
        agentic_turns_hi=2, agentic_gap_lo=1, agentic_gap_hi=3,
        agentic_cont_lo=3, agentic_cont_hi=5)
    a, b = traffic.generate(spec), traffic.generate(spec)
    flat_a = [r for step in a for r in step]
    flat_b = [r for step in b for r in step]
    assert [r.session_id for r in flat_a] == [r.session_id
                                              for r in flat_b]
    assert all(np.array_equal(x.prompt, y.prompt)
               for x, y in zip(flat_a, flat_b))
    assert all(r.session_id.startswith("agent") and r.turns_left >= 1
               for r in flat_a)
    assert sum(r.turns_left for r in flat_a) > 0

    gw = _gateway(lm, tmp_path, names=("r0",))
    try:
        with paddle.no_grad():
            res = traffic.drive(gw, a, ttft_slo_s=60.0,
                                exact_ref=lambda p, n: _ref(lm, p, n))
    finally:
        _close_fleet(gw)
    assert res.resumed > 0
    assert res.resume_exact == res.resumed
    assert res.resume_mismatch == 0 and res.failed == 0
    assert res.summary()["resumed"] == res.resumed
    _close_fleet(gw)


def test_telemetry_dump_sessions_timeline(tmp_path, monkeypatch,
                                          capsys):
    from paddle_tpu.observability import fleet
    monkeypatch.setenv("PADDLE_TELEMETRY_DIR", str(tmp_path))
    fleet.reset_spool()
    try:
        fleet.spool_event("session", op="publish", session="conv",
                          blocks=3, tokens=54)
        fleet.spool_event("session", op="finding", session="conv",
                          finding="torn_manifest", detail="tmp debris")
        fleet.spool_event("session", op="resume", session="conv",
                          source="manifest", tokens=59, gid=4)
    finally:
        fleet.reset_spool()
    spec = importlib.util.spec_from_file_location(
        "telemetry_dump", os.path.join(REPO, "tools",
                                       "telemetry_dump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--fleet", str(tmp_path), "--sessions"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "# session timeline" in out
    assert "publish" in out and "resume" in out
    assert "1 finding(s)" in out and "torn_manifest" in out


def test_session_inspect_cli_verdicts_on_a_real_store(tmp_path, capsys):
    store = SessionStore(str(tmp_path))
    store.publish(SessionManifest(session_id="good",
                                  token_ids=list(range(48)),
                                  block_size=16))
    store.publish(SessionManifest(session_id="bad",
                                  token_ids=list(range(32)),
                                  block_size=16))
    p = store.path_for("bad")
    doc = json.loads(open(p, "rb").read())
    doc["tokens"][0] ^= 1
    open(p, "wb").write(json.dumps(doc, sort_keys=True).encode())
    spec = importlib.util.spec_from_file_location(
        "session_inspect", os.path.join(REPO, "tools",
                                        "session_inspect.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 2
    assert "BAD" in out and "sound manifests: 1/2" in out
    # the offline recompute agrees with the store's own validator
    rep = mod.inspect_root(str(tmp_path))
    assert {r["session"]: r["ok"] for r in rep["manifests"]} == {
        "good": True, "bad": False}


def test_bench_guard_session_lane_gates_goodput(tmp_path):
    import subprocess
    hist = [510.0, 540.0, 555.0, 566.0]
    for i, v in enumerate(hist, start=2):
        (tmp_path / f"BENCH_SESSION_r{i:02d}.json").write_text(
            json.dumps({"metric": "session_resume_goodput", "value": v,
                        "unit": "tokens/s",
                        "detail": {"tpu": False,
                                   "time_to_resume_ms": 400.0 - 4 * i}}))

    def guard(args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "bench_guard.py")] + args,
            capture_output=True, text=True)

    ok = guard(["--check", "--dir", str(tmp_path), "--json"])
    assert ok.returncode == 0, ok.stdout + ok.stderr
    report = json.loads(ok.stdout)
    key = "session:session_resume_goodput/cpu"
    assert report["series"][key]["status"] == "pass"
    assert all(k.startswith("session:") for k in report["series"])
    # a 20% goodput collapse (and the slower resume behind it) gates
    (tmp_path / "BENCH_SESSION_r06.json").write_text(
        json.dumps({"metric": "session_resume_goodput",
                    "value": 0.8 * hist[-1], "unit": "tokens/s",
                    "detail": {"tpu": False,
                               "time_to_resume_ms": 520.0}}))
    bad = guard(["--check", "--dir", str(tmp_path), "--json"])
    assert bad.returncode == 1
    assert json.loads(bad.stdout)["series"][key]["status"] == \
        "regression"
