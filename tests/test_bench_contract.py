"""Bench artifact contract (VERDICT r3 #2).

The driver records exactly one JSON line from `python bench.py` per round.
Round 3 lost its TPU number because the relay was wedged at bench time and
the CPU fallback carried no pointer to the healthy-window snapshot. These
tests pin the contract so that can never happen silently again:

- the orchestrator always emits one parseable line with the metric fields;
- a non-TPU fallback line embeds the most recent BENCH_TPU_SNAPSHOT.json
  (honestly labeled, with its capture timestamp) as detail.last_tpu.

Runs the real orchestrator in a subprocess with a 5 s probe budget — the
probe fails fast whether the relay is wedged or merely cold, so the run
deterministically exercises the fallback path on any host.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
SNAPSHOT = os.path.join(REPO, "BENCH_TPU_SNAPSHOT.json")

pytestmark = pytest.mark.slow


def _clean_env():
    env = dict(os.environ)
    env["GRAFT_BENCH_PROBE_TIMEOUT"] = "5"
    # if a warm healthy relay lets the 5s probe pass, cap the TPU leg too
    # (the orchestrator clamps the budget at >=300s) so the subprocess
    # timeout below is never exceeded on any host
    env["GRAFT_BENCH_TPU_TIMEOUT"] = "60"
    env["GRAFT_BENCH_CPU_TIMEOUT"] = "240"
    # the bench parent must stay wedge-immune regardless of this pytest
    # process's own backend setup
    env.pop("JAX_PLATFORMS", None)
    return env


def test_fallback_line_carries_last_tpu_snapshot():
    out = subprocess.run([sys.executable, BENCH], env=_clean_env(),
                         cwd=REPO, capture_output=True, text=True,
                         timeout=800)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line, got: {out.stdout!r}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "detail"):
        assert key in rec, rec
    assert rec["metric"] == "llama_train_tokens_per_sec_per_chip"
    if rec["detail"].get("tpu"):
        pytest.skip("relay healthy — this run produced a real TPU line")
    # the 5s probe cannot pass even on a healthy relay (cold init >90s),
    # so from here the line is the CPU fallback: it must carry the last
    # hardware number when a snapshot exists on disk.
    if os.path.exists(SNAPSHOT) and json.load(open(SNAPSHOT)).get(
            "detail", {}).get("tpu"):
        last = rec["detail"].get("last_tpu")
        assert last is not None, rec
        assert last["detail"]["tpu"] is True
        assert last["detail"].get("captured_at"), last
        assert last["value"] > 0


def test_snapshot_loader_rejects_non_tpu_files(tmp_path, monkeypatch):
    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    fake = tmp_path / "snap.json"
    fake.write_text(json.dumps({"value": 1.0, "detail": {"tpu": False}}))
    monkeypatch.setattr(bench, "SNAPSHOT_PATH", str(fake))
    assert bench._last_snapshot() is None
    fake.write_text("not json")
    assert bench._last_snapshot() is None
    fake.write_text(json.dumps(
        {"value": 2.0, "detail": {"tpu": True}}))
    snap = bench._last_snapshot()
    assert snap is not None and snap["detail"]["captured_at"]


def test_serving_snapshot_loader_simulated_wedge(tmp_path, monkeypatch):
    """VERDICT r4 #8: when bench_decode falls back to CPU (wedged relay),
    its JSON must embed the last SERVING_TPU_SNAPSHOT.json — and the
    loader must reject CPU lines, junk, and missing timestamps."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_decode_mod", os.path.join(REPO, "benchmarks",
                                         "bench_decode.py"))
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)
    fake = tmp_path / "serving_snap.json"
    monkeypatch.setattr(bd, "SERVING_SNAPSHOT_PATH", str(fake))
    # no file -> no snapshot
    assert bd._last_serving_snapshot() is None
    # CPU record must never masquerade as hardware evidence
    fake.write_text(json.dumps({"value": 1.0, "detail": {"tpu": False}}))
    assert bd._last_serving_snapshot() is None
    # hardware record without a capture timestamp is not trustworthy
    fake.write_text(json.dumps({"value": 2.0, "detail": {"tpu": True}}))
    assert bd._last_serving_snapshot() is None
    fake.write_text("not json")
    assert bd._last_serving_snapshot() is None
    good = {"metric": "paged_serving_decode_tokens_per_sec", "value": 3.5,
            "detail": {"tpu": True, "captured_at": "2026-08-01T00:00:00Z"}}
    fake.write_text(json.dumps(good))
    snap = bd._last_serving_snapshot()
    assert snap is not None and snap["value"] == 3.5


def test_roofline_model_runs_and_is_compute_bound():
    """tools/roofline.py: the analysis pre-staged for VERDICT r3 #1's
    'where does the time go' deliverable. Pin the schema and the headline
    conclusion: every bench tier is COMPUTE-bound on v5e with a
    measured-MFU ceiling far above the 0.50 bar — so a sub-0.5
    measurement indicts kernel/fusion efficiency, not HBM bandwidth."""
    out = subprocess.run([sys.executable,
                          os.path.join(REPO, "tools", "roofline.py")],
                         cwd=REPO, capture_output=True, text=True,
                         timeout=60)
    assert out.returncode == 0, out.stderr
    rec = json.load(open(os.path.join(REPO, "ROOFLINE.json")))
    names = {c["config"] for c in rec["configs"]}
    assert {"large", "medium", "small"} <= names
    for c in rec["configs"]:
        assert c["bound"] == "compute", c
        assert c["measured_mfu_ceiling"] > 0.5, c
        assert c["hbm_bytes"]["total"] > 0


def test_roofline_configs_mirror_bench():
    """tools/roofline.py hardcodes the bench tier dimensions; if bench.py
    is retuned without updating the mirror, the roofline table silently
    describes a config that no longer runs. Parse bench.py's LlamaConfig
    literals and pin the correspondence."""
    import re

    src = open(BENCH).read()
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "roofline_mod", os.path.join(REPO, "tools", "roofline.py"))
    roof = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(roof)
    mirror = {name: dict(V=V, H=H, I=I, L=L, heads=heads, kvh=kvh,
                         batch=batch, seq=seq)
              for (name, V, H, I, L, heads, kvh, batch, seq, _remat)
              in roof.BENCH_CONFIGS}

    # every TPU-tier LlamaConfig literal in bench.py main(), in chain
    # order large -> medium -> small
    pat = re.compile(
        r"LlamaConfig\(vocab_size=(\d+), hidden_size=(\d+),\s*"
        r"intermediate_size=(\d+), num_hidden_layers=(\d+),\s*"
        r"num_attention_heads=(\d+), num_key_value_heads=(\d+)")
    found = [tuple(map(int, m.groups())) for m in pat.finditer(src)]
    # drop the CPU-proxy config (vocab 256)
    found = [f for f in found if f[0] != 256]
    assert len(found) == 3, found
    # the batch/seq assignments follow the same large/medium/small order
    # (the CPU proxy's is last)
    bs_pat = re.compile(r"batch, seq, iters = (\d+), (\d+), (\d+)")
    bs = [tuple(map(int, m.groups())) for m in bs_pat.finditer(src)][:3]
    assert len(bs) == 3, bs
    for name, f, (batch, seq, _iters) in zip(
            ("large", "medium", "small"), found, bs):
        V, H, I, L, heads, kvh = f
        m = mirror[name]
        assert (V, H, I, L, heads, kvh, batch, seq) == (
            m["V"], m["H"], m["I"], m["L"], m["heads"], m["kvh"],
            m["batch"], m["seq"]), (
            f"{name}: bench.py={f}+{(batch, seq)} roofline={m} — "
            f"update tools/roofline.py")
