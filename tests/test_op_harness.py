"""Registry-wide OpTest harness (VERDICT #7).

Reference model: test/legacy_test/op_test.py:420 — every op checked for
(a) forward vs a NumPy reference where one exists, (b) analytic gradient vs
central finite differences in float64 (`check_grad`), and (c) a bf16 smoke,
sweeping the whole registry instead of hand-picked cases. Ops whose inputs
cannot be synthesized generically (int/index/bool inputs, structural attrs,
randomness) are EXPLICITLY whitelisted, mirroring test/white_list/ — a new
op must either pass the harness or be added there with a reason.
"""
import functools
import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (populates OP_REGISTRY)
from paddle_tpu.ops.registry import OP_REGISTRY

from op_harness_recipes import ADAPTERS, RECIPES, WHITELIST


def _seed_of(name):
    """Stable per-op seed (hash() is randomized per interpreter run)."""
    return zlib.crc32(name.encode()) % (2 ** 31)


def _floatify(tree):
    """Sum every float leaf (loss-like scalar for grad checks); complex
    leaves contribute sum(|x|^2) so FFT-family ops stay on the
    differentiable float path."""
    total = None
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "dtype"):
            continue
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            term = jnp.sum(leaf.astype(jnp.float64))
        elif jnp.issubdtype(leaf.dtype, jnp.complexfloating):
            term = jnp.sum(jnp.abs(leaf).astype(jnp.float64) ** 2)
        else:
            continue
        total = term if total is None else total + term
    return total


def _finite(tree):
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            if not bool(jnp.isfinite(leaf).all()):
                return False
    return True


_RANGES = [(0.3, 0.9), (1.2, 1.9), (-0.8, -0.2)]
_SHAPES = [(3, 4), (4,), (2, 3, 4)]


def _try_call(fn, args, need_float=True):
    try:
        out = fn(*args)
    except Exception:
        return None
    if need_float and _floatify(out) is None:
        return None
    if not _finite(out):
        return None
    return out


def synthesize(name, fn):
    """Find (args) of float64 arrays on which fn runs and is finite."""
    rng = np.random.RandomState(_seed_of(name))
    for arity in (1, 2, 3):
        for shape in _SHAPES:
            for lo, hi in _RANGES:
                args = [jnp.asarray(rng.uniform(lo, hi, shape))
                        for _ in range(arity)]
                if _try_call(fn, args) is not None:
                    return args
    return None


def synthesize_mixed(name, fn):
    """Second-chance synthesis for ops needing integer/bool operands
    (indices, comparisons, shifts): int32, bool, and (float, int) combos.
    Output need not be float (comparisons etc. are forward-only checks)."""
    rng = np.random.RandomState(_seed_of(name))

    def ints(shape, hi=3):
        return jnp.asarray(rng.randint(0, hi, shape), jnp.int32)

    def floats(shape):
        return jnp.asarray(rng.uniform(0.3, 0.9, shape))

    candidates = []
    for shape in _SHAPES[:2]:
        candidates += [
            # float-containing combos FIRST: gather/take/embedding etc.
            # must keep a float surface (and its grads), not degrade to a
            # degenerate all-int domain
            (floats(shape), ints(shape)),
            (ints(shape), floats(shape)),
            (floats(shape), floats(shape), ints(shape)),
            (jnp.asarray(rng.rand(*shape) > 0.5),
             floats(shape), floats(shape)),
            (ints(shape),),
            (ints(shape), ints(shape)),
            (jnp.asarray(rng.rand(*shape) > 0.5),),
        ]
    for args in candidates:
        if _try_call(fn, list(args), need_float=False) is not None:
            return list(args)
    return None


def _has_float_arg(args):
    return any(hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
               for a in args)


@functools.lru_cache(maxsize=None)
def _plan(name):
    """Lazy per-op synthesis so COLLECTION stays cheap (the sweep used to
    synthesize all ~400 ops at import, taxing every pytest run).

    Resolution order: explicit recipe (op_harness_recipes.RECIPES, the
    structural-attr ops) → generic float synthesis → mixed int/bool
    synthesis → None (must then be in WHITELIST)."""
    entry = OP_REGISTRY[name]
    if name in RECIPES:
        rng = np.random.RandomState(_seed_of(name))
        r_args, r_kwargs = RECIPES[name](rng)
        r_kwargs = dict(r_kwargs)
        wrap = r_kwargs.pop("_wrap", None)
        fn = ADAPTERS[wrap](entry["fn"]) if wrap else entry["fn"]
        if r_kwargs:
            fn = functools.partial(fn, **r_kwargs)
        out = _try_call(fn, list(r_args), need_float=False)
        # a recipe that stops running is a bug, not a skip
        assert out is not None, f"recipe for '{name}' fails to execute"
        diff = (entry["differentiable"] and _has_float_arg(r_args)
                and _floatify(out) is not None)
        return fn, list(r_args), diff
    args = synthesize(name, entry["fn"])
    if args is None:
        args = synthesize_mixed(name, entry["fn"])
        if args is None:
            return None
        # mixed ops keep their grad check IF a float surface exists AND
        # the output is float-reducible (gather/take/embedding...)
        has_float = any(jnp.issubdtype(a.dtype, jnp.floating)
                        for a in args)
        out_ok = _floatify(_try_call(entry["fn"], args,
                                     need_float=False)) is not None
        return (entry["fn"], args,
                entry["differentiable"] and has_float and out_ok)
    return entry["fn"], args, entry["differentiable"]


_ALL_OPS = sorted(OP_REGISTRY)

# Ops whose loss is non-deterministic across calls (fresh PRNG draw inside
# the op): finite differences are meaningless; grads are still required to
# exist and be finite, and each has a dedicated distributional test.
_NO_FD = {
    "gumbel_softmax": "fresh gumbel noise per call (test_activation pins "
                      "the distribution; straight-through grad is exact "
                      "by construction)",
    "flash_attention_pallas": "f32 kernel accumulation noise dominates "
                              "central differences at any usable eps; "
                              "grads are pinned against the dense "
                              "reference in tests/test_pallas_kernels.py",
}

# f32-internal ops where fp64 central differences at eps=1e-5 hit the
# kernel's own rounding noise: relaxed (atol, rtol) for the FD comparison.
# Their exact gradients are pinned against dense references elsewhere
# (tests/test_pallas_kernels.py, tests/test_nn.py attention tests).
_FD_TOL = {
    "scaled_dot_product_attention": (2e-3, 0.5),
}


# numpy forward references for ops whose semantics match a numpy call
_NP_REF = {
    "add": np.add, "subtract": np.subtract, "multiply": np.multiply,
    "divide": np.divide, "maximum": np.maximum, "minimum": np.minimum,
    "sin": np.sin, "cos": np.cos, "tan": np.tan, "sinh": np.sinh,
    "cosh": np.cosh, "tanh": np.tanh, "asin": np.arcsin, "acos": np.arccos,
    "atan": np.arctan, "asinh": np.arcsinh, "exp": np.exp, "expm1": np.expm1,
    "log": np.log, "log2": np.log2, "log10": np.log10, "log1p": np.log1p,
    "sqrt": np.sqrt, "rsqrt": lambda x: 1 / np.sqrt(x), "abs": np.abs,
    "floor": np.floor, "ceil": np.ceil, "round": np.round,
    "sign": np.sign, "square": np.square, "reciprocal": np.reciprocal,
    "pow": np.power, "fmax": np.fmax, "fmin": np.fmin,
    "remainder": np.remainder, "fmod": np.fmod, "hypot": np.hypot,
    "logaddexp": np.logaddexp, "trunc": np.trunc, "exponent": None,
}
_NP_REF = {k: v for k, v in _NP_REF.items() if v is not None}


def test_registry_fully_covered():
    """Coverage pin: the synthesizable fraction must not silently regress."""
    covered = sum(1 for n in _ALL_OPS if _plan(n) is not None)
    covered_frac = covered / len(OP_REGISTRY)
    assert covered_frac >= 0.90, (
        f"harness coverage dropped to {covered_frac:.0%}")


def test_whitelist_is_exact():
    """The skip set must equal the NAMED whitelist in both directions
    (test/white_list/ discipline, op_test.py:420): a new op either passes
    the harness or gets a whitelist entry with a reason; a whitelisted op
    that becomes synthesizable must be removed from the list."""
    skipped = {n for n in _ALL_OPS if _plan(n) is None}
    unlisted = skipped - set(WHITELIST)
    stale = set(WHITELIST) - skipped
    assert not unlisted, (
        f"ops skipped without a whitelist entry+reason: {sorted(unlisted)}")
    assert not stale, (
        f"stale whitelist entries (now synthesizable): {sorted(stale)}")


@pytest.mark.parametrize("name", _ALL_OPS)
def test_op_forward_and_grad(name):
    plan = _plan(name)
    if plan is None:
        pytest.skip(f"{name}: no generic float synthesis (whitelisted)")
    fn, args, differentiable = plan
    out = fn(*args)
    assert _finite(out), f"{name}: non-finite forward"

    if name in _NP_REF:
        ref = _NP_REF[name](*[np.asarray(a) for a in args])
        got = jax.tree_util.tree_leaves(out)[0]
        np.testing.assert_allclose(np.asarray(got, np.float64),
                                   np.asarray(ref, np.float64),
                                   rtol=1e-6, atol=1e-6,
                                   err_msg=f"{name}: forward vs numpy")

    if not differentiable:
        return

    def loss(*a):
        """Random-cotangent reduction: sum(out * w) with fixed random w.

        A uniform all-ones cotangent (plain .sum()) lets transposed or
        permuted gradients pass; the random weighting makes the vjp
        direction generic (VERDICT r2 #4). w is reseeded per call so
        finite-difference evaluations see the identical weights."""
        out = fn(*a)
        wrng = np.random.RandomState(_seed_of(name) ^ 0x5EED)
        total = None
        for leaf in jax.tree_util.tree_leaves(out):
            if not hasattr(leaf, "dtype"):
                continue
            w = jnp.asarray(wrng.uniform(0.5, 1.5, np.shape(leaf)))
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                term = jnp.sum(leaf.astype(jnp.float64) * w)
            elif jnp.issubdtype(leaf.dtype, jnp.complexfloating):
                term = jnp.sum(jnp.abs(leaf).astype(jnp.float64) ** 2 * w)
            else:
                continue
            total = term if total is None else total + term
        return total if total is not None else jnp.float64(0)

    # differentiate only the float ARRAY arguments (int/bool operands and
    # structural attrs — ints, strings, shape lists — carry no gradient)
    float_pos = tuple(i for i, a in enumerate(args)
                      if hasattr(a, "dtype")
                      and jnp.issubdtype(a.dtype, jnp.floating))
    if not float_pos:
        pytest.skip(f"{name}: no float argument to differentiate")
    try:
        grads = jax.grad(loss, argnums=float_pos)(*args)
    except Exception:
        pytest.skip(f"{name}: jax.grad unsupported on synthesized inputs")

    if name in _NO_FD:
        for g in grads:
            assert bool(jnp.isfinite(jnp.asarray(g)).all()), (
                f"{name}: non-finite gradient")
        return

    eps = 1e-5
    fd_atol, fd_rtol = _FD_TOL.get(name, (1e-3, 1e-2))
    for i, g in zip(float_pos, grads):
        flat = np.asarray(args[i]).ravel()
        # probe a few coordinates (full FD over every element is O(n) evals)
        idx = np.linspace(0, flat.size - 1, min(4, flat.size)).astype(int)
        for j in idx:
            # preserve each operand's dtype — only the float arg under
            # test is perturbed (int/bool operands must stay integral;
            # non-array structural args pass through untouched)
            ap = [np.asarray(a).copy() if hasattr(a, "dtype") else a
                  for a in args]
            am = [np.asarray(a).copy() if hasattr(a, "dtype") else a
                  for a in args]
            ap[i] = ap[i].astype(np.float64)
            am[i] = am[i].astype(np.float64)
            ap[i].ravel()[j] += eps
            am[i].ravel()[j] -= eps
            fp = float(loss(*[jnp.asarray(a) if hasattr(a, "dtype") else a
                              for a in ap]))
            fm = float(loss(*[jnp.asarray(a) if hasattr(a, "dtype") else a
                              for a in am]))
            fd = (fp - fm) / (2 * eps)
            an = float(np.asarray(g).ravel()[j])
            assert abs(fd - an) <= fd_atol + fd_rtol * abs(fd), (
                f"{name}: grad mismatch at arg{i}[{j}]: fd={fd} vs "
                f"analytic={an}")


@pytest.mark.parametrize("name", _ALL_OPS)
def test_op_bf16_smoke(name):
    plan = _plan(name)
    if plan is None:
        pytest.skip(f"{name}: no generic float synthesis (whitelisted)")
    fn, args, _ = plan
    bf_args = [a.astype(jnp.bfloat16)
               if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                         jnp.floating)
               else a
               for a in args]
    if all(b is a for b, a in zip(bf_args, args)):
        pytest.skip(f"{name}: no float arg to cast (int/bool-only op)")
    try:
        out = fn(*bf_args)
    except Exception:
        pytest.skip(f"{name}: no bf16 path on synthesized inputs")
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            assert bool(jnp.isfinite(leaf.astype(jnp.float32)).all()), (
                f"{name}: non-finite bf16 forward")
