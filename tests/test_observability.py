"""Unified telemetry: registry semantics, spans, exporters, integration.

The registry is process-global (native-tier cells are keyed by series
name in the cross-thread stat store), so tests use per-test metric names
or fresh MetricsRegistry instances plus delta assertions — never absolute
values of shared series.
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.observability import (attach_context, capture_context,
                                      load_jsonl, render_prometheus, span,
                                      span_path, write_jsonl)
from paddle_tpu.observability.metrics import (MetricsRegistry, get_registry)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_labels_and_monotonicity():
    reg = MetricsRegistry()
    fam = reg.counter("obs_t1_reqs", "x", labelnames=("engine",))
    fam.labels(engine="dense").inc()
    fam.labels(engine="dense").inc(4)
    fam.labels(engine="paged").inc(2)
    assert fam.labels(engine="dense").value == 5
    assert fam.labels(engine="paged").value == 2
    with pytest.raises(ValueError):
        fam.labels(engine="dense").inc(-1)
    with pytest.raises(ValueError):
        fam.labels(wrong="dense")


def test_registration_is_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    a = reg.counter("obs_t2_c", "x")
    assert reg.counter("obs_t2_c") is a
    with pytest.raises(ValueError):
        reg.gauge("obs_t2_c")
    reg.counter("obs_t2_lab", labelnames=("a",))
    with pytest.raises(ValueError):
        reg.counter("obs_t2_lab", labelnames=("b",))


def test_gauge_tracks_peak():
    reg = MetricsRegistry()
    g = reg.gauge("obs_t3_depth", "x")
    g.set(3)
    g.set(9)
    g.set(2)
    assert g.value == 2
    assert g.peak == 9


def test_histogram_buckets_and_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("obs_t4_lat", "x", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(56.05)
    assert h.bucket_counts() == [1, 2, 1, 1]   # last = +Inf overflow
    # exact below the reservoir cap: quantiles come from the sorted sample
    assert h.quantile(0.5) == 0.5
    assert h.quantile(0.99) == 50.0


def test_histogram_quantile_edge_cases():
    reg = MetricsRegistry()
    h = reg.histogram("obs_t4b_edge", "x", buckets=(0.1, 1.0))
    # empty: no estimate, not a crash
    assert h.quantile(0.5) is None
    assert h.quantile(0.0) is None and h.quantile(1.0) is None
    # singleton: every quantile is the one sample
    h.observe(0.7)
    assert h.quantile(0.0) == h.quantile(0.5) == h.quantile(1.0) == 0.7
    # extremes are the EXACT tracked min/max, not reservoir artifacts
    for v in (0.2, 3.0, 0.05, 1.5):
        h.observe(v)
    assert h.quantile(0.0) == 0.05
    assert h.quantile(1.0) == 3.0
    # out-of-range q raises instead of silently clamping
    with pytest.raises(ValueError):
        h.quantile(-0.01)
    with pytest.raises(ValueError):
        h.quantile(1.01)


def test_histogram_quantile_extremes_survive_reservoir_eviction():
    from paddle_tpu.observability.metrics import _RESERVOIR_CAP
    reg = MetricsRegistry()
    h = reg.histogram("obs_t4c_extremes", "x", buckets=(0.5,))
    h.observe(-123.0)                     # global min, observed FIRST
    for i in range(_RESERVOIR_CAP * 4):   # likely evicts the early sample
        h.observe(float(i % 100))
    h.observe(9999.0)                     # global max
    assert h.quantile(0.0) == -123.0
    assert h.quantile(1.0) == 9999.0


def test_histogram_quantile_sane_past_reservoir_cap():
    from paddle_tpu.observability.metrics import _RESERVOIR_CAP
    reg = MetricsRegistry()
    h = reg.histogram("obs_t5_big", "x", buckets=(0.5,))
    n = _RESERVOIR_CAP * 4
    for i in range(n):
        h.observe(i / n)   # uniform on [0, 1)
    assert h.count == n
    q50 = h.quantile(0.5)
    assert 0.3 < q50 < 0.7  # unbiased estimate of the true 0.5


def test_thread_safety_counter():
    reg = MetricsRegistry()
    c = reg.counter("obs_t6_mt", "x")

    def burst():
        for _ in range(1000):
            c.inc()

    ts = [threading.Thread(target=burst) for _ in range(8)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert c.value == 8000


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_builds_path():
    assert span_path() == ""
    with span("outer"):
        assert span_path() == "outer"
        with span("inner") as s:
            assert span_path() == "outer/inner"
            assert s.path == "outer/inner"
        assert span_path() == "outer"
    assert span_path() == ""


def test_span_durations_reach_registry():
    hist = get_registry().get("span_duration_seconds")
    with span("obs_t7_marker"):
        time.sleep(0.01)
    child = hist.labels(span="obs_t7_marker")
    assert child.count >= 1
    assert child.sum >= 0.009


def test_span_context_propagates_across_threads():
    seen = {}

    def worker(token):
        with attach_context(token):
            with span("stage"):
                seen["path"] = span_path()
        seen["after"] = span_path()

    with span("producer"):
        t = threading.Thread(target=worker, args=(capture_context(),))
        t.start()
        t.join()
    assert seen["path"] == "producer/stage"
    assert seen["after"] == ""


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def _sample_registry():
    reg = MetricsRegistry()
    reg.counter("obs_exp_reqs", "reqs", labelnames=("engine",)) \
        .labels(engine="dense").inc(7)
    g = reg.gauge("obs_exp_depth", "depth")
    g.set(4)
    g.set(1)
    h = reg.histogram("obs_exp_lat", "lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    return reg


def test_prometheus_rendering():
    text = render_prometheus(registry=_sample_registry())
    assert '# TYPE obs_exp_reqs counter' in text
    assert 'obs_exp_reqs{engine="dense"} 7' in text
    assert 'obs_exp_depth 1' in text
    assert 'obs_exp_depth_peak 4' in text
    # cumulative buckets + +Inf + sum/count
    assert 'obs_exp_lat_bucket{le="0.1"} 1' in text
    assert 'obs_exp_lat_bucket{le="1"} 2' in text
    assert 'obs_exp_lat_bucket{le="+Inf"} 3' in text
    assert 'obs_exp_lat_count 3' in text
    assert 'obs_exp_lat_quantile{quantile="0.5"} 0.5' in text


def test_jsonl_round_trip(tmp_path):
    reg = _sample_registry()
    path = str(tmp_path / "snap.jsonl")
    write_jsonl(path, registry=reg, series=reg.snapshot(
        include_native=False))
    series = load_jsonl(path)
    # re-rendered snapshot is value-identical to the live render
    assert render_prometheus(series=series) == render_prometheus(
        series=reg.snapshot(include_native=False))
    with open(path) as f:
        meta = json.loads(f.readline())
    assert meta["__meta__"]["format"] == "paddle_tpu.observability/1"
    assert meta["__meta__"]["series"] == len(series)


def test_jsonl_rejects_corrupt_lines(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text('{"name": "ok", "type": "counter", "value": 1}\n'
                    '{"name": "trunc', encoding="utf-8")
    with pytest.raises(json.JSONDecodeError):
        load_jsonl(str(path))


def test_exporter_overhead_under_one_percent():
    """bench guard: rendering a snapshot must cost <1% of a tight 100k
    counter-inc loop — exporting may never be the hot path."""
    reg = MetricsRegistry()
    c = reg.counter("obs_overhead_c", "x")
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        c.inc()
    loop = time.perf_counter() - t0
    t0 = time.perf_counter()
    render_prometheus(registry=reg)
    render = time.perf_counter() - t0
    assert render < 0.01 * loop, (
        f"render {render * 1e6:.0f}us vs loop {loop * 1e6:.0f}us")


# ---------------------------------------------------------------------------
# monitor shim — one store per process
# ---------------------------------------------------------------------------

def test_monitor_shim_shares_registry_store():
    from paddle_tpu.utils import monitor
    monitor.stat_reset("obs_shim_g")
    assert monitor.stat_update("obs_shim_g", 5) == 5
    assert monitor.stat_update("obs_shim_g", -2) == 3
    assert monitor.stat_peak("obs_shim_g") == 5
    # the registry snapshot sees the same cell (no shadow store)
    series = {s["name"]: s for s in get_registry().snapshot()}
    assert series["obs_shim_g"]["value"] == 3.0
    assert monitor.get_monitor_values()["obs_shim_g"] == 3
    monitor.stat_reset("obs_shim_g")
    assert monitor.stat_get("obs_shim_g") == 0


# ---------------------------------------------------------------------------
# profiler export filename collision fix
# ---------------------------------------------------------------------------

def test_chrome_export_handlers_never_collide(tmp_path):
    from paddle_tpu import profiler

    d = str(tmp_path)
    for _ in range(2):   # two handlers, same worker name, same second
        p = profiler.Profiler(
            on_trace_ready=profiler.export_chrome_tracing(d, "w"))
        p.start()
        with profiler.RecordEvent("e"):
            pass
        p.stop()
    traces = list(tmp_path.glob("w_time_*.paddle_trace.json"))
    assert len(traces) == 2, [t.name for t in traces]


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

def test_continuous_batcher_populates_serving_metrics():
    from paddle_tpu.inference.serving import ContinuousBatcher
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

    reg = get_registry()

    def dense(name):
        return reg.get(name).labels(engine="dense")

    before_reqs = dense("serving_requests_total").value \
        if reg.get("serving_requests_total") else 0
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=32, num_hidden_layers=1,
                     num_attention_heads=2, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(3)
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        rids = [b.submit(rng.randint(0, 128, (5,)), 4) for _ in range(3)]
        outs = b.run_until_done()
    assert set(outs) == set(rids)

    assert dense("serving_requests_total").value == before_reqs + 3
    # all drained: depth gauge back to zero, but its peak saw the queue
    assert dense("serving_queue_depth").value == 0
    assert dense("serving_queue_depth").peak >= 1
    ttft = dense("serving_ttft_seconds")
    assert ttft.count >= 3
    assert sum(ttft.bucket_counts()) == ttft.count
    assert dense("serving_tokens_total").value >= 12
    # the local stats() contract survived the refactor
    s = b.stats()
    assert s["completed_requests"] == 3
    assert s["generated_tokens"] == 12
    assert s["pending_now"] == 0 and s["active_now"] == 0
    b.reset_stats()
    assert b.stats()["completed_requests"] == 0
    # per-instance reset must NOT clear the process-wide cumulative series
    assert dense("serving_requests_total").value == before_reqs + 3


def test_prometheus_dump_after_serving_has_populated_families():
    from paddle_tpu.inference.serving import _ServingStats
    _ServingStats("dense")   # idempotent: children are shared by series key
    text = render_prometheus()
    assert "# TYPE serving_requests_total counter" in text
    assert "# TYPE serving_ttft_seconds histogram" in text
    assert "# TYPE serving_queue_depth gauge" in text
