"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the custom_cpu-plugin analog of the
reference's GPU-free collective tests, test/custom_runtime/ — SURVEY.md §4):
multi-chip sharding is validated without TPU hardware. Env must be set before
jax imports anywhere.
"""
import os

# Force the CPU backend with 8 virtual devices. The axon TPU sitecustomize may
# already have registered the TPU plugin, but backends initialize lazily, so
# switching jax_platforms before first device use still lands on CPU.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
# float64 for numeric-gradient checks (OpTest.check_grad runs fp64 refs too)
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield
