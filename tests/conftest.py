"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the custom_cpu-plugin analog of the
reference's GPU-free collective tests, test/custom_runtime/ — SURVEY.md §4):
multi-chip sharding is validated without TPU hardware. Env must be set before
jax imports anywhere.
"""
import os
import sys


def _tpu_tier_requested() -> bool:
    """True when this pytest invocation targets the real-TPU tier.

    `pytest -m tpu` (or running test_tpu_tier.py directly, or setting
    PADDLE_TPU_RUN_TPU_TESTS=1) must keep the ambient TPU backend instead
    of forcing the virtual CPU mesh — the tier exists to compile the Pallas
    kernels with Mosaic and exercise the hardware PRNG path.
    """
    if os.environ.get("PADDLE_TPU_RUN_TPU_TESTS") == "1":
        return True
    argv = sys.argv
    for i, a in enumerate(argv):
        prev = argv[i - 1] if i else ""
        # positional test-path selection of the tier file — but NOT
        # exclusion forms (--ignore=..., --deselect ...), which mean the
        # opposite.
        if not a.startswith("-") and prev not in ("--ignore", "--deselect") \
                and os.path.basename(a.split("::")[0]).startswith(
                    "test_tpu_tier"):
            return True
        # -m tpu / -mtpu / -m=tpu (and the -k spellings)
        if a in ("-m", "-k") and i + 1 < len(argv) \
                and argv[i + 1].strip() == "tpu":
            return True
        if a in ("-mtpu", "-ktpu", "-m=tpu", "-k=tpu"):
            return True
    return False


# The interpret self-check (PADDLE_TPU_TIER_INTERPRET=1) runs the tier's
# test logic on the normal 8-device CPU mesh — only a real-hardware run
# keeps the ambient TPU backend.
TPU_TIER = (_tpu_tier_requested()
            and os.environ.get("PADDLE_TPU_TIER_INTERPRET") != "1")

if not TPU_TIER:
    # Force the CPU backend with 8 virtual devices. The axon TPU
    # sitecustomize may already have registered the TPU plugin, but backends
    # initialize lazily, so switching jax_platforms before first device use
    # still lands on CPU.
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if not TPU_TIER:
    jax.config.update("jax_platforms", "cpu")
    # float64 for numeric-gradient checks (OpTest runs fp64 refs too);
    # TPU has no f64, so the real-hardware tier keeps x64 off.
    jax.config.update("jax_enable_x64", True)
else:
    # persistent compilation cache: Mosaic compiles ride the slow
    # remote-compile tunnel; cache hits make tier reruns near-free
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield


# -- quick tier (VERDICT weak #8): one representative fast test per subsystem
# so `pytest -m quick` verifies every layer in <2 min. Retuned in round 5
# (VERDICT r4 weak #6): the five heaviest members (ring dense x2, kv-cache
# decode, two-rank world, bert backbone — 283s of 364s on a 1-core host)
# swapped for lighter same-subsystem representatives; the heavy versions
# still run in smoke/full.
_QUICK_TESTS = {
    "tests/test_autograd.py::test_simple_backward",
    "tests/test_bert_debugging_utils.py::test_check_numerics_direct",
    "tests/test_dist_checkpoint.py::test_save_load_replicated",
    "tests/test_dist_engine.py::test_strategy_defaults_and_config",
    "tests/test_distributed.py::test_world_setup",
    "tests/test_fused_kernels.py::test_rmsnorm_pallas_forward_matches_reference",
    "tests/test_hapi.py::test_accuracy_metric",
    "tests/test_io.py::test_tensor_dataset_and_subset",
    "tests/test_jit.py::test_to_static_matches_eager",
    "tests/test_launch.py::test_kv_server_roundtrip",
    "tests/test_models.py::test_llama_forward_shapes",
    "tests/test_moe.py::test_naive_gate_topk",
    "tests/test_native.py::test_native_extension_builds",
    "tests/test_nn.py::test_linear",
    "tests/test_optimizer.py::test_optimizers_decrease_loss",
    "tests/test_pipeline.py::test_segment_uniform",
    "tests/test_profiler.py::test_make_scheduler_states",
    "tests/test_quant_asp.py::test_quant_dequant_rounds_to_grid",
    "tests/test_rnn.py::test_simple_rnn_cell_matches_numpy",
    "tests/test_sequence_parallel.py::test_ulysses_public_impl_seam",
    "tests/test_sot.py::TestSOTSegments::test_replay_skips_python_and_matches_eager",
    "tests/test_tensor.py::test_to_tensor_and_numpy",
    "tests/test_vision_ops.py::TestRoIOps::test_roi_align_constant_image",
}


# -- smoke tier (VERDICT r2 #8): ~one FILE per subsystem, <=5 min total, so
# inter-round regressions surface without the >25-min full suite. Files
# chosen to cover: tensor/core, autograd, jit/sot, distributed runtime,
# optimizers, io, serving decode, sharded checkpoint, quant, launcher,
# profiler. test_dryrun_clean.py (multi-chip SPMD remat pin) moved to the
# slow tier in round 4: the driver runs the full dryrun every round and
# one variant's compile alone would eat a third of the smoke budget.
_SMOKE_FILES = {
    "test_tensor.py",
    "test_autograd.py",
    "test_jit.py",
    "test_sot.py",
    "test_distributed.py",
    "test_optimizer.py",
    "test_io.py",
    "test_decode.py",
    "test_dist_checkpoint.py",
    "test_quant_asp.py",
    "test_launch.py",
    "test_profiler.py",
}


# heavy members of smoke files whose coverage is duplicated by a lighter
# sibling in the same file — excluded so the tier stays under its 5:00
# budget (VERDICT r3 weak #6; they still run in the full suite). Keep
# this list minimal: a test with UNIQUE coverage (e.g. the only int8
# decode) or a quick-tier member (quick must stay a subset of smoke)
# does not belong here.
_SMOKE_EXCLUDE = {
    "tests/test_decode.py::test_paged_decode_cross_block_boundary",
}


# -- strict exactness lane (VERDICT r4 #5): the token-exact serving/
# paged/quant/speculative suites, run with PADDLE_EXACT_STRICT=1 so the
# CPU load-flake retry is OFF and exactness must hold first-try:
#   PADDLE_EXACT_STRICT=1 python -m pytest -m exact -q
_EXACT_FILES = {
    "test_paged_batching.py",
    "test_quant_serving.py",
    "test_speculative.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.nodeid.split("[")[0]
        if base in _QUICK_TESTS:
            item.add_marker(pytest.mark.quick)
        if os.path.basename(str(item.fspath)) in _SMOKE_FILES \
                and base not in _SMOKE_EXCLUDE:
            item.add_marker(pytest.mark.smoke)
        if os.path.basename(str(item.fspath)) in _EXACT_FILES:
            item.add_marker(pytest.mark.exact)
