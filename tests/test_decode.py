"""Incremental (KV-cache) decode tests — the serving path (VERDICT r2 #6).

Reference coverage model: the decode parity tests around
masked_multihead_attention / block_multihead_attention
(test/legacy_test/test_masked_multihead_attention_op.py): an incremental
step over the cache must produce exactly the tokens the full-context
forward produces.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM


def _tiny(dropout=0.0):
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=dropout)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m, cfg


def _greedy_full_recompute(m, ids, n):
    cur = np.asarray(ids._data)
    for _ in range(n):
        logits = m(paddle.to_tensor(cur))
        nxt = np.asarray(logits._data)[:, -1].argmax(-1)[:, None]
        cur = np.concatenate([cur, nxt], axis=1)
    return cur.tolist()


def test_kv_cache_decode_matches_full_recompute():
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 10)))
    with paddle.no_grad():
        out = m.generate(ids, max_new_tokens=6).numpy().tolist()
        ref = _greedy_full_recompute(m, ids, 6)
    assert out == ref


def test_compiled_decode_step_matches_eager():
    """jit.to_static(decode_step): one executable serves every step."""
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 128, (2, 12)))
    with paddle.no_grad():
        ref = m.generate(ids, max_new_tokens=8).numpy().tolist()
        step = jit.to_static(m.decode_step)
        out = m.generate(ids, max_new_tokens=8,
                         decode_fn=step).numpy().tolist()
    assert out == ref


@pytest.mark.quick
def test_prefill_cache_layout():
    m, cfg = _tiny()
    b, s, s_max = 2, 7, 16
    ids = paddle.to_tensor(np.random.RandomState(2).randint(0, 128, (b, s)))
    with paddle.no_grad():
        logits, caches, t = m.prefill(ids, s_max)
    L = cfg.num_hidden_layers
    h, d = cfg.num_attention_heads, cfg.head_dim
    assert list(caches.shape) == [L, 2, b, h, s_max, d]
    assert list(logits.shape) == [b, 1, cfg.vocab_size]
    assert t.numpy().ravel().tolist() == [s, s]
    # rows beyond the prompt are zero until decode writes them
    tail = caches.numpy()[:, :, :, :, s:, :]
    np.testing.assert_allclose(tail, 0.0)


def test_generate_respects_cache_bound():
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(3).randint(0, 128, (1, 8)))
    with pytest.raises(ValueError, match="s_max"):
        m.generate(ids, max_new_tokens=16, s_max=12)


def test_int8_decode_runs():
    """Weight-only int8 + KV cache: the serving combo stays greedy-stable."""
    from paddle_tpu import nn
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(4).randint(0, 128, (1, 8)))
    with paddle.no_grad():
        nn.quant.quantize_linear_layers(m)
        out = m.generate(ids, max_new_tokens=4)
        ref = _greedy_full_recompute(m, ids, 4)
    assert out.numpy().tolist() == ref


def test_paged_decode_matches_dense_cache():
    """vLLM-style paged block cache (block_multihead_attention route) must
    be token-exact against the dense-cache path."""
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(5).randint(0, 128, (2, 10)))
    with paddle.no_grad():
        ref = m.generate(ids, max_new_tokens=6).numpy().tolist()
        out = m.generate_paged(ids, max_new_tokens=6,
                               block_size=8).numpy().tolist()
    assert out == ref


def test_paged_decode_cross_block_boundary():
    """Decode steps that cross a page boundary append into the next
    physical block via the block table."""
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(6).randint(0, 128, (1, 6)))
    with paddle.no_grad():
        # block_size 4: prompt fills 1.5 pages, decode crosses into page 3
        out = m.generate_paged(ids, max_new_tokens=8,
                               block_size=4).numpy().tolist()
        ref = _greedy_full_recompute(m, ids, 8)
    assert out == ref


def test_compiled_paged_decode_step_matches_eager():
    """to_static over the paged step: the state pytree has static shapes,
    so one executable serves every paged decode step too."""
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(7).randint(0, 128, (2, 10)))
    with paddle.no_grad():
        ref = m.generate_paged(ids, max_new_tokens=6,
                               block_size=8).numpy().tolist()
        step = jit.to_static(m.paged_decode_step)
        out = m.generate_paged(ids, max_new_tokens=6, block_size=8,
                               decode_fn=step).numpy().tolist()
    assert out == ref


def test_sampling_generate():
    """do_sample draws reproducibly (seeded), respects top-k truncation,
    and temperature→0 collapses to greedy."""
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(8).randint(0, 128, (2, 8)))
    with paddle.no_grad():
        greedy = m.generate(ids, max_new_tokens=6).numpy().tolist()
        s1 = m.generate(ids, max_new_tokens=6, do_sample=True,
                        temperature=1.0, seed=7).numpy().tolist()
        s2 = m.generate(ids, max_new_tokens=6, do_sample=True,
                        temperature=1.0, seed=7).numpy().tolist()
        s3 = m.generate(ids, max_new_tokens=6, do_sample=True,
                        temperature=1.0, seed=8).numpy().tolist()
        cold = m.generate(ids, max_new_tokens=6, do_sample=True,
                          temperature=1e-4, seed=7).numpy().tolist()
        k1 = m.generate(ids, max_new_tokens=6, do_sample=True, top_k=1,
                        seed=7).numpy().tolist()
    assert s1 == s2            # seeded determinism
    assert s1 != s3            # seed matters
    assert cold == greedy      # temperature -> 0 is greedy
    assert k1 == greedy        # top-k=1 is greedy
    # nucleus: with top_p tiny, also collapses to greedy
    with paddle.no_grad():
        p0 = m.generate(ids, max_new_tokens=6, do_sample=True,
                        top_p=1e-9, seed=7).numpy().tolist()
    assert p0 == greedy


def test_eos_early_stop():
    """eos_id parity with the reference's generation loop: rows stop at
    their EOS, later positions pad, the loop exits early when every row
    is done, and the output shape is unchanged."""
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(9).randint(0, 128, (2, 6)))
    with paddle.no_grad():
        base = m.generate(ids, max_new_tokens=8).numpy()
    # pick each row's first greedy token as its "EOS" so row 0 stops at
    # step 1; use a token row 1 never emits to keep it running
    eos = int(base[0, 6])
    with paddle.no_grad():
        out = m.generate(ids, max_new_tokens=8, eos_id=eos,
                         pad_id=0).numpy()
    assert out.shape == base.shape
    row0 = out[0, 6:]
    assert row0[0] == eos
    assert (row0[1:] == 0).all()          # padded after EOS
    # rows that never hit EOS match the plain greedy continuation
    row1_plain = base[1, 6:]
    if eos not in row1_plain:
        np.testing.assert_array_equal(out[1, 6:], row1_plain)
    # default pad is the EOS token itself: every position from the EOS on
    # must be eos (row 0 stops at its FIRST generated token)
    with paddle.no_grad():
        out2 = m.generate(ids, max_new_tokens=8, eos_id=eos).numpy()
    assert (out2[0, 6:] == eos).all()


def test_eos_all_rows_early_exit_pads_to_shape():
    """A 1-row batch whose first token is its EOS forces the all-rows-done
    early exit; the output must still be right-padded to
    [B, S + max_new_tokens]."""
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(10).randint(0, 128, (1, 5)))
    with paddle.no_grad():
        base = m.generate(ids, max_new_tokens=6).numpy()
    e0 = int(base[0, 5])
    with paddle.no_grad():
        out = m.generate(ids, max_new_tokens=6, eos_id=e0, pad_id=1).numpy()
    assert out.shape == base.shape
    assert out[0, 5] == e0
    assert (out[0, 6:] == 1).all()
