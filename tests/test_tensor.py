"""Tensor basics: creation, properties, operators, indexing.

Modeled on the reference's test/legacy_test tensor API tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_and_numpy():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_inference():
    assert paddle.to_tensor([1, 2]).dtype == np.dtype("int64") or \
        paddle.to_tensor([1, 2]).dtype == np.dtype("int32")
    assert paddle.to_tensor([1.5]).dtype == paddle.float32
    assert paddle.to_tensor(np.float64([1.5])).dtype == paddle.float32
    assert paddle.to_tensor([1.5], dtype="float64").dtype == np.dtype("float64")


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([2, 3]).numpy().sum() == 6
    assert paddle.full([2, 2], 7).numpy().sum() == 28
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    assert paddle.eye(3).numpy().trace() == 3
    assert paddle.linspace(0, 1, 5).shape == [5]


def test_operators():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2.0 + a).numpy(), [3, 4, 5])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    assert bool((a < b).all())
    assert bool((a == a).all())


def test_matmul_operator():
    a = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
    b = paddle.to_tensor(np.random.randn(4, 5).astype("float32"))
    np.testing.assert_allclose((a @ b).numpy(), a.numpy() @ b.numpy(),
                               rtol=1e-5)


def test_indexing():
    a = paddle.to_tensor(np.arange(24).reshape(2, 3, 4).astype("float32"))
    assert a[0].shape == [3, 4]
    assert a[:, 1].shape == [2, 4]
    assert a[..., -1].shape == [2, 3]
    assert a[0, 1, 2].item() == 6.0
    mask = a > 12
    assert a[mask].shape == [11]
    idx = paddle.to_tensor([0, 1])
    assert a[idx].shape == [2, 3, 4]


def test_setitem():
    a = paddle.zeros([3, 3])
    a[1, :] = 5.0
    assert a.numpy()[1].tolist() == [5, 5, 5]
    a[0, 0] = 1.0
    assert a.numpy()[0, 0] == 1


def test_methods():
    a = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
    assert a.mean().shape == []
    assert a.sum(axis=0).shape == [4]
    assert a.reshape([4, 3]).shape == [4, 3]
    assert a.transpose([1, 0]).shape == [4, 3]
    assert a.T.shape == [4, 3]
    assert a.unsqueeze(0).shape == [1, 3, 4]
    assert a.flatten().shape == [12]
    assert a.astype("int32").dtype == np.dtype("int32")
    assert a.exp().shape == [3, 4]
    assert a.clip(-1, 1).numpy().max() <= 1.0


def test_inplace_set_value():
    a = paddle.ones([2, 2])
    a.set_value(np.zeros((2, 2), "float32"))
    assert a.numpy().sum() == 0


def test_detach_clone():
    a = paddle.to_tensor([1.0], stop_gradient=False)
    d = a.detach()
    assert d.stop_gradient
    c = a.clone()
    assert not c.stop_gradient


def test_item_and_len():
    assert paddle.to_tensor([42.0]).item() == 42.0
    assert len(paddle.zeros([5, 2])) == 5
    assert float(paddle.to_tensor(3.5)) == 3.5


def test_manipulation_ops():
    a = paddle.to_tensor(np.arange(6).reshape(2, 3).astype("float32"))
    b = paddle.concat([a, a], axis=0)
    assert b.shape == [4, 3]
    s = paddle.split(b, 2, axis=0)
    assert len(s) == 2 and s[0].shape == [2, 3]
    st = paddle.stack([a, a], axis=0)
    assert st.shape == [2, 2, 3]
    assert paddle.tile(a, [2, 2]).shape == [4, 6]
    assert paddle.flip(a, axis=1).numpy()[0, 0] == 2
    vals, idx = paddle.topk(paddle.to_tensor([1.0, 9.0, 3.0]), k=2)
    np.testing.assert_array_equal(vals.numpy(), [9, 3])
    np.testing.assert_array_equal(idx.numpy(), [1, 2])


def test_where_and_gather():
    cond = paddle.to_tensor([True, False, True])
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([9.0, 9.0, 9.0])
    np.testing.assert_array_equal(paddle.where(cond, a, b).numpy(), [1, 9, 3])
    idx = paddle.to_tensor([2, 0])
    np.testing.assert_array_equal(paddle.gather(a, idx).numpy(), [3, 1])


def test_random_reproducible():
    paddle.seed(7)
    a = paddle.randn([4])
    paddle.seed(7)
    b = paddle.randn([4])
    np.testing.assert_array_equal(a.numpy(), b.numpy())


def test_cast_roundtrip():
    a = paddle.to_tensor([1.5, 2.5])
    assert paddle.cast(a, "bfloat16").dtype == paddle.bfloat16
    assert paddle.cast(a, "int64").numpy().tolist() == [1, 2]
