"""SOT segment compiler (jit/sot.py).

Reference test model: test/sot/* — graph-break functions keep working,
sub-graphs before/after the break compile, guards route control flow, and
novel branches extend the cache instead of erroring.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import jit


def _arr(x):
    return np.asarray(x._data)


class TestSOTSegments:
    def test_replay_skips_python_and_matches_eager(self):
        calls = {"n": 0}

        @jit.to_static(full_graph=False)
        def f(x):
            calls["n"] += 1
            y = x * 2 + 1
            if float(y.sum()) > 0:
                z = y * 3
            else:
                z = y - 5
            return z.sum()

        xp = paddle.to_tensor(np.ones(4, dtype="float32"))
        for _ in range(3):  # trace-attempt, eager fallback, SOT record
            f(xp)
        n0 = calls["n"]
        out = f(xp)
        assert calls["n"] == n0, "replay must not run the python body"
        assert float(out._data) == 36.0

    def test_guard_trie_routes_both_branches(self):
        calls = {"n": 0}

        @jit.to_static(full_graph=False)
        def f(x):
            calls["n"] += 1
            if bool((x.sum() > 0)):
                return (x * 3).sum()
            return (x - 5).sum()

        xp = paddle.to_tensor(np.ones(4, dtype="float32"))
        xn = paddle.to_tensor(-np.ones(4, dtype="float32"))
        for _ in range(3):
            f(xp)
        for _ in range(2):
            f(xn)  # novel guard -> re-record extends the trie
        n0 = calls["n"]
        assert float(f(xn)._data) == -24.0
        assert float(f(xp)._data) == 12.0
        assert calls["n"] == n0, "both branches should replay compiled"

    def test_gradient_through_segments(self):
        @jit.to_static(full_graph=False)
        def f(x):
            y = x * 2 + 1
            if float(y.sum()) > 0:
                return (y * 3).sum()
            return y.sum()

        xw = paddle.to_tensor(np.ones(4, dtype="float32"))
        for _ in range(3):
            f(xw)
        x = paddle.to_tensor(np.ones(4, dtype="float32"))
        x.stop_gradient = False
        out = f(x)
        out.backward()
        np.testing.assert_allclose(_arr(x.grad), np.full(4, 6.0), atol=1e-6)

    def test_int_guard_and_multiple_breaks(self):
        @jit.to_static(full_graph=False)
        def f(x):
            k = int(x.sum())          # break 1 (int guard)
            y = x * k
            if bool(y.max() > 2):     # break 2 (bool guard)
                y = y + 10
            return y.sum()

        x2 = paddle.to_tensor(np.full(2, 2.0, dtype="float32"))
        for _ in range(3):
            f(x2)
        # k = 4, y = 8 each, max(8) > 2 -> +10 -> sum = 36
        assert float(f(x2)._data) == 36.0

    def test_state_mutation_replayed(self):
        counter = paddle.to_tensor(np.zeros(1, dtype="float32"))

        @jit.to_static(full_graph=False)
        def f(x):
            new = counter + 1
            counter._set_data(new._data)
            if float(x.sum()) > 0:
                return x * counter
            return x

        x = paddle.to_tensor(np.ones(2, dtype="float32"))
        for _ in range(3):
            f(x)
        c3 = float(counter._data[0])
        f(x)  # replay must still bump the counter
        assert float(counter._data[0]) == c3 + 1

    def test_rng_trace_falls_back_to_eager(self):
        calls = {"n": 0}

        @jit.to_static(full_graph=False)
        def f(x):
            calls["n"] += 1
            import paddle_tpu.nn.functional as F
            y = F.dropout(x, p=0.5, training=True)
            if float(x.sum()) > 0:
                return y.sum()
            return x.sum()

        x = paddle.to_tensor(np.ones(64, dtype="float32"))
        outs = {float(f(x)._data) for _ in range(6)}
        # 6 calls = 7 body executions: the aborted whole-graph compile
        # attempt on call 2 also runs the body once before breaking
        assert calls["n"] == 7, "rng traces must stay eager (fresh masks)"
        assert len(outs) > 1, "dropout masks must differ call to call"

    def test_arg_mutation_hits_current_call_tensor(self):
        # mutation of an ARG tensor must apply to the tensor passed at
        # replay time, not the recording-time object
        @jit.to_static(full_graph=False)
        def f(x):
            doubled = x * 2
            x._set_data(doubled._data)
            if float(x.sum()) > 0:
                return x + 1
            return x

        f(paddle.to_tensor(np.array([2.0, 1.0], dtype="float32")))
        f(paddle.to_tensor(np.array([2.0, 1.0], dtype="float32")))
        t_rec = paddle.to_tensor(np.array([2.0, 1.0], dtype="float32"))
        f(t_rec)  # the SOT recording call mutates its own arg eagerly
        np.testing.assert_allclose(_arr(t_rec), [4.0, 2.0])
        fresh = paddle.to_tensor(np.array([2.0, 1.0], dtype="float32"))
        out = f(fresh)  # replay
        np.testing.assert_allclose(_arr(fresh), [4.0, 2.0])
        np.testing.assert_allclose(_arr(out), [5.0, 3.0])
        # the recording-time arg must NOT be re-mutated by the replay
        np.testing.assert_allclose(_arr(t_rec), [4.0, 2.0])

    def test_unstable_guards_pin_to_eager(self):
        calls = {"n": 0}

        @jit.to_static(full_graph=False)
        def f(x):
            calls["n"] += 1
            if float(x.sum()) > 1e9:   # guard value varies every call
                return x * 2
            return x + 1

        from paddle_tpu.jit.sot import SOTCache
        cap = SOTCache.MAX_RECORDINGS_WITHOUT_REPLAY
        # every call has a different sum -> every guard misses
        for i in range(cap + 6):
            f(paddle.to_tensor(np.full(2, float(i), dtype="float32")))
        # after the cap, the signature pins to eager: python runs every call
        n0 = calls["n"]
        f(paddle.to_tensor(np.full(2, 777.0, dtype="float32")))
        assert calls["n"] == n0 + 1

    def test_full_graph_true_still_raises(self):
        @jit.to_static(full_graph=True)
        def f(x):
            if float(x.sum()) > 0:
                return x * 2
            return x

        x = paddle.to_tensor(np.ones(2, dtype="float32"))
        f(x)
        import pytest
        with pytest.raises(RuntimeError):
            f(x)


class TestPythonStateGuards:
    """VERDICT #9: python-state changes must re-record, not replay stale
    (reference SOT guards python values, function_graph.py:143)."""

    def test_closure_flag_flip_rerecords(self):
        from paddle_tpu.jit.sot import SOTCache
        flag = {"on": True}
        calls = {"n": 0}

        scale_on = 3.0

        def fn(x):
            calls["n"] += 1
            if bool(x.sum() > -1e9):  # always-true break -> segments
                return x * (scale_on if use_scale else 1.0)
            return x

        use_scale = True
        cache = SOTCache(fn)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        out1 = cache.run((x,), {})
        np.testing.assert_allclose(out1.numpy(), 3.0)
        out2 = cache.run((x,), {})  # replay
        np.testing.assert_allclose(out2.numpy(), 3.0)

        use_scale = False  # closure flip: stale replay would still give 3.0
        out3 = cache.run((x,), {})
        np.testing.assert_allclose(out3.numpy(), 1.0)
        use_scale = True
        np.testing.assert_allclose(cache.run((x,), {}).numpy(), 3.0)

    def test_layer_attribute_flip_rerecords(self):
        from paddle_tpu import nn
        from paddle_tpu.jit.sot import SOTCache

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.double = True

            def forward(self, x):
                if bool(x.sum() > -1e9):
                    return x * (2.0 if self.double else 1.0)
                return x

        m = M()
        cache = SOTCache(m.forward)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(cache.run((x,), {}).numpy(), 2.0)
        m.double = False
        np.testing.assert_allclose(cache.run((x,), {}).numpy(), 1.0)

    def test_self_mutating_guarded_state(self):
        """A function that FLIPS its own guarded state must key the trace
        by the pre-call fingerprint (stale-replay repro from review)."""
        from paddle_tpu.jit.sot import SOTCache
        state = {"first": True}

        first = True

        def fn(x):
            nonlocal first
            if bool(x.sum() > -1e9):
                if first:
                    first = False
                    return x * 2.0
                return x * 1.0
            return x

        cache = SOTCache(fn)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(cache.run((x,), {}).numpy(), 2.0)
        np.testing.assert_allclose(cache.run((x,), {}).numpy(), 1.0)
        np.testing.assert_allclose(cache.run((x,), {}).numpy(), 1.0)
