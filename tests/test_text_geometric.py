"""Tests for paddle.text (viterbi), paddle.geometric (segment/message
passing), and incubate.optimizer (LookAhead/ModelAverage)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric, nn, optimizer, text


def _np(t):
    return np.asarray(t._data)


# -- geometric -----------------------------------------------------------------

def test_segment_ops():
    data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]],
                                     dtype=np.float32))
    seg = paddle.to_tensor(np.array([0, 0, 1, 1]))
    np.testing.assert_allclose(_np(geometric.segment_sum(data, seg)),
                               [[4, 6], [12, 14]])
    np.testing.assert_allclose(_np(geometric.segment_mean(data, seg)),
                               [[2, 3], [6, 7]])
    np.testing.assert_allclose(_np(geometric.segment_max(data, seg)),
                               [[3, 4], [7, 8]])
    np.testing.assert_allclose(_np(geometric.segment_min(data, seg)),
                               [[1, 2], [5, 6]])


def test_segment_empty_segment_is_zero():
    data = paddle.to_tensor(np.ones((2, 3), np.float32))
    seg = paddle.to_tensor(np.array([0, 2]))  # segment 1 empty
    out = _np(geometric.segment_max(data, seg))
    np.testing.assert_allclose(out[1], 0.0)


def test_send_u_recv():
    x = paddle.to_tensor(np.array([[1.], [2.], [4.]], dtype=np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2, 0]))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
    out = _np(geometric.send_u_recv(x, src, dst, reduce_op="sum"))
    # dst0 <- x[0]; dst1 <- x[0]+x[2]; dst2 <- x[1]
    np.testing.assert_allclose(out, [[1.], [5.], [2.]])
    out_max = _np(geometric.send_u_recv(x, src, dst, reduce_op="max"))
    np.testing.assert_allclose(out_max, [[1.], [4.], [2.]])


def test_send_ue_recv_and_send_uv():
    x = paddle.to_tensor(np.array([[1.], [2.]], dtype=np.float32))
    e = paddle.to_tensor(np.array([[10.], [20.]], dtype=np.float32))
    src = paddle.to_tensor(np.array([0, 1]))
    dst = paddle.to_tensor(np.array([1, 0]))
    out = _np(geometric.send_ue_recv(x, e, src, dst, "add", "sum"))
    np.testing.assert_allclose(out, [[22.], [11.]])
    uv = _np(geometric.send_uv(x, x, src, dst, "mul"))
    np.testing.assert_allclose(uv, [[2.], [2.]])


def test_send_u_recv_gradient():
    x = paddle.to_tensor(np.array([[1.], [2.], [3.]], dtype=np.float32),
                         stop_gradient=False)
    src = paddle.to_tensor(np.array([0, 1]))
    dst = paddle.to_tensor(np.array([1, 1]))
    out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
    out.sum().backward()
    np.testing.assert_allclose(_np(x.grad), [[1.], [1.], [0.]])


# -- text.viterbi --------------------------------------------------------------

def _brute_force_viterbi(pot, trans, length):
    """All-paths max over the first `length` steps (no bos/eos)."""
    import itertools
    n = pot.shape[-1]
    best, best_path = -np.inf, None
    for path in itertools.product(range(n), repeat=length):
        s = pot[0, path[0]]
        for i in range(1, length):
            s += trans[path[i - 1], path[i]] + pot[i, path[i]]
        if s > best:
            best, best_path = s, path
    return best, list(best_path)


def test_viterbi_matches_brute_force():
    rng = np.random.RandomState(0)
    b, t, n = 2, 5, 3
    pot = rng.randn(b, t, n).astype(np.float32)
    trans = rng.randn(n, n).astype(np.float32)
    lengths = np.array([5, 3])
    scores, paths = text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lengths), include_bos_eos_tag=False)
    for i in range(b):
        ref_s, ref_p = _brute_force_viterbi(pot[i], trans, lengths[i])
        assert float(_np(scores)[i]) == pytest.approx(ref_s, rel=1e-5)
        got = _np(paths)[i][:lengths[i]].tolist()
        assert got == ref_p


def test_viterbi_decoder_layer():
    rng = np.random.RandomState(1)
    pot = paddle.to_tensor(rng.randn(1, 4, 2).astype(np.float32))
    trans = paddle.to_tensor(rng.randn(2, 2).astype(np.float32))
    dec = text.ViterbiDecoder(trans, include_bos_eos_tag=False)
    scores, paths = dec(pot, paddle.to_tensor(np.array([4])))
    assert _np(paths).shape == (1, 4)
    assert np.isfinite(float(_np(scores)[0]))


def test_text_datasets_raise_offline():
    with pytest.raises(RuntimeError, match="egress"):
        text.Imdb()


# -- incubate.optimizer --------------------------------------------------------

def test_lookahead_syncs_slow_weights():
    from paddle_tpu.incubate.optimizer import LookAhead
    net = nn.Linear(4, 4)
    inner = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    opt = LookAhead(inner, alpha=0.5, k=2)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    w0 = _np(net.weight).copy()
    for i in range(2):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    # after k=2 steps, weights = slow + 0.5*(fast - slow): between w0 and fast
    w = _np(net.weight)
    assert not np.allclose(w, w0)
    sd = opt.state_dict()
    assert "@lookahead_k_count" in sd


def test_viterbi_bos_eos_convention():
    # reference convention: last two tags of the SAME [N, N] transition are
    # BOS (n-2) / EOS (n-1); start scores = BOS row, stop = EOS column
    n = 4  # 2 real tags + bos + eos
    pot = np.zeros((1, 2, n), dtype=np.float32)
    trans = np.zeros((n, n), dtype=np.float32)
    trans[n - 2, 1] = 5.0  # BOS strongly prefers starting at tag 1
    trans[0, n - 1] = 5.0  # ending at tag 0 is strongly rewarded
    scores, paths = text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(np.array([2])), include_bos_eos_tag=True)
    p = _np(paths)[0]
    assert p[0] == 1   # start steered by BOS row
    assert p[-1] == 0  # end steered by EOS column


def test_lookahead_state_roundtrip_preserves_slow_weights():
    from paddle_tpu.incubate.optimizer import LookAhead
    net = nn.Linear(3, 3)
    opt = LookAhead(optimizer.SGD(learning_rate=0.1,
                                  parameters=net.parameters()),
                    alpha=0.5, k=5)
    x = paddle.to_tensor(np.ones((1, 3), np.float32))
    for _ in range(3):  # mid-window
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    slow_before = {i: np.asarray(opt._slow[id(p)]).copy()
                   for i, p in enumerate(opt._parameter_list)}
    sd = opt.state_dict()

    net2 = nn.Linear(3, 3)
    net2.set_state_dict(net.state_dict())
    opt2 = LookAhead(optimizer.SGD(learning_rate=0.1,
                                   parameters=net2.parameters()),
                     alpha=0.5, k=5)
    opt2.set_state_dict(sd)
    assert opt2._k_count == 3
    for i, p in enumerate(opt2._parameter_list):
        np.testing.assert_allclose(np.asarray(opt2._slow[id(p)]),
                                   slow_before[i], rtol=1e-7)


def test_model_average_apply_restore():
    from paddle_tpu.incubate.optimizer import ModelAverage
    net = nn.Linear(2, 2)
    inner = optimizer.SGD(learning_rate=0.5, parameters=net.parameters())
    avg = ModelAverage(0.15, parameters=net.parameters(),
                       min_average_window=10, max_average_window=20)
    x = paddle.to_tensor(np.ones((1, 2), np.float32))
    snapshots = []
    for _ in range(4):
        loss = (net(x) ** 2).mean()
        loss.backward()
        inner.step()
        inner.clear_grad()
        avg.step()
        snapshots.append(_np(net.weight).copy())
    current = _np(net.weight).copy()
    with avg.apply():
        averaged = _np(net.weight).copy()
        expect = np.mean(snapshots, axis=0)
        np.testing.assert_allclose(averaged, expect, rtol=1e-5)
    np.testing.assert_allclose(_np(net.weight), current, rtol=1e-7)
