"""nn layer tests (reference coverage model: test/legacy_test layer tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_linear():
    layer = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    out = layer(x)
    assert out.shape == [2, 3]
    np.testing.assert_allclose(
        out.numpy(), x.numpy() @ layer.weight.numpy() + layer.bias.numpy(),
        rtol=1e-5)


def test_layer_parameters_and_state_dict():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    params = net.parameters()
    assert len(params) == 4
    sd = net.state_dict()
    assert set(sd.keys()) == {"0.weight", "0.bias", "2.weight", "2.bias"}
    # roundtrip
    new = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    missing, unexpected = new.set_state_dict(sd)
    assert not missing and not unexpected
    np.testing.assert_array_equal(new[0].weight.numpy(), net[0].weight.numpy())


def test_buffers_in_state_dict():
    bn = nn.BatchNorm2D(3)
    sd = bn.state_dict()
    assert "weight" in sd and "_mean" in sd and "_variance" in sd


def test_train_eval_propagates():
    net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    net.eval()
    assert not net[1].training
    net.train()
    assert net[1].training


def test_dropout_train_vs_eval():
    x = paddle.ones([1000])
    layer = nn.Dropout(0.5)
    out = layer(x)
    assert 0.2 < float((out.numpy() == 0).mean()) < 0.8
    layer.eval()
    np.testing.assert_array_equal(layer(x).numpy(), x.numpy())


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor([[1, 0, 3]])
    out = emb(idx)
    assert out.shape == [1, 3, 4]
    np.testing.assert_array_equal(out.numpy()[0, 1], np.zeros(4))


def test_conv2d_shape_and_grad():
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    x.stop_gradient = False
    out = conv(x)
    assert out.shape == [2, 8, 8, 8]
    out.sum().backward()
    assert x.grad.shape == [2, 3, 16, 16]
    assert conv.weight.grad is not None


def test_conv2d_matches_manual():
    # 1x1 conv == pointwise matmul
    conv = nn.Conv2D(3, 5, 1, bias_attr=False)
    x = paddle.randn([1, 3, 4, 4])
    out = conv(x).numpy()  # [1,5,4,4]
    w = conv.weight.numpy().reshape(5, 3)
    expected = np.einsum("oc,nchw->nohw", w, x.numpy())
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_batch_norm_updates_stats():
    bn = nn.BatchNorm1D(4)
    x = paddle.randn([16, 4]) * 3 + 1
    bn(x)
    assert not np.allclose(bn._mean.numpy(), np.zeros(4))
    bn.eval()
    m = bn._mean.numpy().copy()
    bn(x)
    np.testing.assert_array_equal(bn._mean.numpy(), m)  # frozen in eval


def test_layer_norm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 5, 8]) * 4 + 2
    out = ln(x).numpy()
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1, atol=1e-2)


def test_group_norm():
    gn = nn.GroupNorm(2, 4)
    x = paddle.randn([2, 4, 8, 8])
    out = gn(x)
    assert out.shape == [2, 4, 8, 8]


def test_pools():
    x = paddle.randn([1, 2, 8, 8])
    assert nn.MaxPool2D(2)(x).shape == [1, 2, 4, 4]
    assert nn.AvgPool2D(2)(x).shape == [1, 2, 4, 4]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
    np.testing.assert_allclose(
        nn.AdaptiveAvgPool2D(1)(x).numpy().reshape(1, 2),
        x.numpy().mean(axis=(2, 3)), rtol=1e-5)


def test_avg_pool_exclusive_padding():
    x = paddle.ones([1, 1, 4, 4])
    out = nn.AvgPool2D(3, stride=1, padding=1)(x)
    # exclusive=True: corners average over 4 real elements -> still 1.0
    np.testing.assert_allclose(out.numpy(), np.ones((1, 1, 4, 4)), rtol=1e-6)


def test_losses():
    logits = paddle.to_tensor([[2.0, 1.0, 0.1]])
    label = paddle.to_tensor([0])
    loss = nn.CrossEntropyLoss()(logits, label)
    expected = -np.log(np.exp(2.0) / np.exp([2.0, 1.0, 0.1]).sum())
    np.testing.assert_allclose(loss.item(), expected, rtol=1e-5)

    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([1.5, 1.5])
    np.testing.assert_allclose(nn.MSELoss()(a, b).item(), 0.25, rtol=1e-6)
    np.testing.assert_allclose(nn.L1Loss()(a, b).item(), 0.5, rtol=1e-6)


def test_cross_entropy_ignore_index():
    logits = paddle.randn([4, 5])
    label = paddle.to_tensor([0, 1, -100, 2])
    loss = F.cross_entropy(logits, label, ignore_index=-100)
    manual = F.cross_entropy(logits[paddle.to_tensor([0, 1, 3])],
                             paddle.to_tensor([0, 1, 2]))
    np.testing.assert_allclose(loss.item(), manual.item(), rtol=1e-5)


def test_cross_entropy_soft_label():
    logits = paddle.randn([2, 3])
    soft = paddle.to_tensor([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]])
    loss = F.cross_entropy(logits, soft, soft_label=True)
    assert loss.shape == []


def test_multi_head_attention():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 5, 16])
    out = mha(q, q, q)
    assert out.shape == [2, 5, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=2, dim_feedforward=32)
    enc = nn.TransformerEncoder(layer, 2)
    src = paddle.randn([2, 6, 16])
    out = enc(src)
    assert out.shape == [2, 6, 16]
    # independent copies: params must not be shared
    p0 = enc.layers[0].linear1.weight
    p1 = enc.layers[1].linear1.weight
    assert p0 is not p1


def test_sequential_container():
    net = nn.Sequential(("fc1", nn.Linear(2, 3)), ("fc2", nn.Linear(3, 4)))
    assert net.fc1.weight.shape == [2, 3]
    assert len(net) == 2


def test_layerlist():
    layers = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    layers.append(nn.Linear(2, 2))
    assert len(layers) == 4
    assert len(layers.parameters()) == 8


def test_apply_and_hooks():
    net = nn.Linear(2, 2)
    calls = []
    net.register_forward_post_hook(lambda l, i, o: calls.append(1))
    net(paddle.randn([1, 2]))
    assert calls == [1]


def test_to_dtype():
    net = nn.Linear(2, 2)
    net.to(dtype="bfloat16")
    assert net.weight.dtype == paddle.bfloat16


def test_activations():
    x = paddle.to_tensor([-1.0, 0.0, 2.0])
    np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
    assert nn.GELU()(x).shape == [3]
    np.testing.assert_allclose(nn.Sigmoid()(x).numpy(),
                               1 / (1 + np.exp([1.0, 0.0, -2.0])), rtol=1e-5)
    sm = nn.Softmax()(x).numpy()
    np.testing.assert_allclose(sm.sum(), 1.0, rtol=1e-6)


def test_scaled_dot_product_attention_matches_naive():
    b, s, h, d = 2, 4, 2, 8
    q = paddle.randn([b, s, h, d])
    k = paddle.randn([b, s, h, d])
    v = paddle.randn([b, s, h, d])
    out = F.scaled_dot_product_attention(q, k, v).numpy()
    qn = q.numpy().transpose(0, 2, 1, 3)
    kn = k.numpy().transpose(0, 2, 1, 3)
    vn = v.numpy().transpose(0, 2, 1, 3)
    scores = qn @ kn.transpose(0, 1, 3, 2) / np.sqrt(d)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    expected = (probs @ vn).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_causal_attention():
    b, s, h, d = 1, 4, 1, 4
    q = paddle.randn([b, s, h, d])
    k = paddle.randn([b, s, h, d])
    v = paddle.randn([b, s, h, d])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    # first position only attends to itself
    np.testing.assert_allclose(out.numpy()[0, 0, 0], v.numpy()[0, 0, 0],
                               rtol=1e-5)
