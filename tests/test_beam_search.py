"""Beam search over the KV cache (reference generation's beam mode).

Exactness bar: with num_beams >= vocab and two generated tokens, beam
search enumerates every continuation of the top-V first tokens — i.e. the
EXHAUSTIVE optimum — so the result must equal a brute-force argmax over
all V^2 sequences scored by teacher-forced full forwards. Plus: the beam
cache reorder must keep per-beam KV states consistent (checked implicitly
by the exhaustive match), and beam=1 equals greedy.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM


def _gpt(vocab=8):
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, max_position_embeddings=32,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m, cfg


def _exhaustive_best(m, prompt, vocab, steps):
    """Brute force: score every vocab^steps continuation with ONE batched
    teacher-forced forward; return the argmax sequence."""
    from itertools import product
    cands = np.array(list(product(range(vocab), repeat=steps)), np.int64)
    n = cands.shape[0]
    seqs = np.concatenate(
        [np.repeat(prompt[None, :], n, axis=0), cands], axis=1)
    with paddle.no_grad():
        logits = np.asarray(m(paddle.to_tensor(seqs))._data)
    lp = logits.astype(np.float64)
    lp = lp - lp.max(-1, keepdims=True)
    lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
    s = prompt.shape[0]
    scores = np.zeros(n)
    for j in range(steps):
        # token at position s+j is predicted by logits at position s+j-1
        scores += lp[np.arange(n), s + j - 1, seqs[:, s + j]]
    return seqs[scores.argmax()]


def test_beam_equals_exhaustive_when_wide_enough():
    vocab = 8
    m, cfg = _gpt(vocab)
    prompt = np.random.RandomState(0).randint(0, vocab, (6,))
    with paddle.no_grad():
        out = m.generate_beam(
            paddle.to_tensor(prompt[None, :]), max_new_tokens=2,
            num_beams=vocab).numpy()[0]
    best = _exhaustive_best(m, prompt, vocab, 2)
    np.testing.assert_array_equal(out, best)


def test_beam_one_equals_greedy():
    m, cfg = _gpt(32)
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 32, (2, 7)))
    with paddle.no_grad():
        beam = m.generate_beam(ids, max_new_tokens=5,
                               num_beams=1).numpy().tolist()
        greedy = m.generate(ids, max_new_tokens=5).numpy().tolist()
    assert beam == greedy


def test_beam_score_at_least_greedy():
    """Wider beams can only match or beat greedy's total log-prob (greedy
    survives pruning: its prefix is always a top-1 continuation)."""
    vocab = 16
    m, cfg = _gpt(vocab)
    prompt = np.random.RandomState(2).randint(0, vocab, (5,))

    def score(seq):
        with paddle.no_grad():
            logits = np.asarray(m(paddle.to_tensor(seq[None, :]))._data)[0]
        lp = logits.astype(np.float64)
        lp = lp - lp.max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        s = prompt.shape[0]
        return sum(lp[s + j - 1, seq[s + j]] for j in range(3))

    with paddle.no_grad():
        ids = paddle.to_tensor(prompt[None, :])
        beam = m.generate_beam(ids, max_new_tokens=3, num_beams=6).numpy()[0]
        greedy = m.generate(ids, max_new_tokens=3).numpy()[0]
    assert score(beam) >= score(greedy) - 1e-9


def test_llama_beam_search_gqa():
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=32,
                            num_attention_heads=2, num_key_value_heads=1,
                            vocab_size=8, max_position_embeddings=32)
    m = LlamaForCausalLM(cfg)
    m.eval()
    prompt = np.random.RandomState(3).randint(0, 8, (5,))
    with paddle.no_grad():
        out = m.generate_beam(paddle.to_tensor(prompt[None, :]),
                              max_new_tokens=2, num_beams=8).numpy()[0]
    best = _exhaustive_best(m, prompt, 8, 2)
    np.testing.assert_array_equal(out, best)
