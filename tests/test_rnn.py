"""Tests for the recurrent layer family (nn/layer/rnn.py analog):
cells, RNN/BiRNN wrappers, multi-layer SimpleRNN/LSTM/GRU."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _np(t):
    return np.asarray(t._data)


def _x(b=3, t=5, i=4, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(b, t, i).astype(np.float32))


# -- cells ---------------------------------------------------------------------

def test_simple_rnn_cell_matches_numpy():
    cell = nn.SimpleRNNCell(4, 6)
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 4)
                         .astype(np.float32))
    h = paddle.to_tensor(np.random.RandomState(2).randn(2, 6)
                         .astype(np.float32))
    out, h2 = cell(x, h)
    expect = np.tanh(_np(x) @ _np(cell.weight_ih).T + _np(cell.bias_ih)
                     + _np(h) @ _np(cell.weight_hh).T + _np(cell.bias_hh))
    np.testing.assert_allclose(_np(out), expect, rtol=1e-5, atol=1e-6)
    assert out is h2 or np.allclose(_np(out), _np(h2))


def test_lstm_cell_gate_math():
    cell = nn.LSTMCell(4, 6)
    x = paddle.to_tensor(np.random.RandomState(3).randn(2, 4)
                         .astype(np.float32))
    h0 = np.random.RandomState(4).randn(2, 6).astype(np.float32)
    c0 = np.random.RandomState(5).randn(2, 6).astype(np.float32)
    out, (h, c) = cell(x, (paddle.to_tensor(h0), paddle.to_tensor(c0)))

    def sig(a):
        return 1 / (1 + np.exp(-a))

    gates = (_np(x) @ _np(cell.weight_ih).T + _np(cell.bias_ih)
             + h0 @ _np(cell.weight_hh).T + _np(cell.bias_hh))
    i, f, g, o = np.split(gates, 4, axis=-1)
    c_ref = sig(f) * c0 + sig(i) * np.tanh(g)
    h_ref = sig(o) * np.tanh(c_ref)
    np.testing.assert_allclose(_np(c), c_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(h), h_ref, rtol=1e-4, atol=1e-5)


def test_gru_cell_interpolates_state():
    cell = nn.GRUCell(3, 5)
    x = paddle.to_tensor(np.zeros((2, 3), np.float32))
    h0 = paddle.to_tensor(np.random.RandomState(0).randn(2, 5)
                          .astype(np.float32))
    _, h = cell(x, h0)
    # h' = u*h + (1-u)*c is a convex combination: bounded by [min, max] of
    # (h0, c) with c in (-1, 1)
    assert np.all(np.abs(_np(h)) <= np.maximum(np.abs(_np(h0)), 1.0) + 1e-6)


# -- RNN wrapper ---------------------------------------------------------------

def test_rnn_unrolls_cell():
    cell = nn.SimpleRNNCell(4, 6)
    rnn = nn.RNN(cell)
    x = _x()
    out, h = rnn(x)
    assert tuple(out.shape) == (3, 5, 6)
    assert tuple(h.shape) == (3, 6)
    # manual unroll must match
    hh = paddle.to_tensor(np.zeros((3, 6), np.float32))
    for t in range(5):
        _, hh = cell(x[:, t], hh)
    np.testing.assert_allclose(_np(h), _np(hh), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(out)[:, -1], _np(hh), rtol=1e-4,
                               atol=1e-5)


def test_rnn_reverse_and_time_major():
    cell = nn.SimpleRNNCell(4, 6)
    fw = nn.RNN(cell)
    bw = nn.RNN(cell, is_reverse=True)
    x = _x()
    x_rev = paddle.to_tensor(np.asarray(x._data)[:, ::-1].copy())
    out_bw, _ = bw(x)
    out_fw_on_rev, _ = fw(x_rev)
    np.testing.assert_allclose(_np(out_bw), _np(out_fw_on_rev)[:, ::-1],
                               rtol=1e-4, atol=1e-5)

    tm = nn.RNN(cell, time_major=True)
    out_tm, _ = tm(paddle.to_tensor(np.moveaxis(np.asarray(x._data), 1, 0)))
    out_ref, _ = fw(x)
    np.testing.assert_allclose(np.moveaxis(_np(out_tm), 0, 1), _np(out_ref),
                               rtol=1e-4, atol=1e-5)


def test_rnn_sequence_length_masks():
    cell = nn.SimpleRNNCell(2, 3)
    rnn = nn.RNN(cell)
    x = _x(b=2, t=4, i=2)
    lens = paddle.to_tensor(np.array([4, 2]))
    out, h = rnn(x, sequence_length=lens)
    # sample 1: outputs at t>=2 are zero, final state = state at t=1
    np.testing.assert_allclose(_np(out)[1, 2:], 0.0)
    out_full, _ = rnn(x)
    np.testing.assert_allclose(_np(h)[1], _np(out_full)[1, 1], rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(_np(h)[0], _np(out_full)[0, 3], rtol=1e-4,
                               atol=1e-5)


# -- multi-layer nets ----------------------------------------------------------

def test_lstm_shapes_and_training():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = _x()
    out, (h_n, c_n) = lstm(x)
    assert tuple(out.shape) == (3, 5, 8)
    # stacked reference layout: [num_layers * num_directions, B, H]
    assert tuple(h_n.shape) == (2, 3, 8)
    assert tuple(c_n.shape) == (2, 3, 8)
    # last layer's final h equals the last output step
    np.testing.assert_allclose(_np(h_n)[-1], _np(out)[:, -1], rtol=1e-4,
                               atol=1e-5)

    opt = optimizer.Adam(learning_rate=0.01, parameters=lstm.parameters())
    tgt = paddle.to_tensor(np.random.RandomState(9).randn(3, 8)
                           .astype(np.float32))
    losses = []
    for _ in range(6):
        out, _ = lstm(x)
        loss = ((out[:, -1] - tgt) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gru_bidirectional():
    gru = nn.GRU(4, 8, num_layers=1, direction="bidirect")
    x = _x()
    out, h_n = gru(x)
    assert tuple(out.shape) == (3, 5, 16)  # fw + bw concat
    assert tuple(h_n.shape) == (2, 3, 8)   # [L * D, B, H]


def test_lstm_accepts_stacked_initial_states():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = _x()
    h0 = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 8)
                          .astype(np.float32))
    c0 = paddle.to_tensor(np.random.RandomState(1).randn(2, 3, 8)
                          .astype(np.float32))
    out, (h_n, c_n) = lstm(x, (h0, c0))
    assert tuple(h_n.shape) == (2, 3, 8)
    # nonzero initial state must change the outcome vs zero init
    out0, _ = lstm(x)
    assert not np.allclose(_np(out), _np(out0))


def test_rnn_cell_without_biases():
    cell = nn.SimpleRNNCell(4, 6, bias_ih_attr=False, bias_hh_attr=False)
    assert cell.bias_ih is None
    out, _ = cell(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert tuple(out.shape) == (2, 6)
    lstm_cell = nn.LSTMCell(4, 6, bias_ih_attr=False, bias_hh_attr=False)
    out2, (h, c) = lstm_cell(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert tuple(h.shape) == (2, 6)


def test_simple_rnn_relu_activation():
    rnn = nn.SimpleRNN(4, 8, activation="relu")
    out, _ = rnn(_x())
    assert tuple(out.shape) == (3, 5, 8)


def test_rnn_in_compiled_trainstep():
    from paddle_tpu import jit
    lstm = nn.LSTM(4, 8)
    opt = optimizer.Adam(learning_rate=0.01, parameters=lstm.parameters())

    def loss_fn(x, y):
        out, _ = lstm(x)
        return ((out[:, -1] - y) ** 2).mean()

    step = jit.TrainStep(loss_fn, opt)
    x = _x()
    y = paddle.to_tensor(np.zeros((3, 8), np.float32))
    l0 = float(step(x, y))
    l1 = float(step(x, y))  # compiled pass (scan inside one executable)
    l2 = float(step(x, y))
    assert l2 < l0 and np.isfinite(l1)


# -- new misc layers -----------------------------------------------------------

def test_fold_inverts_unfold_with_overlap():
    x = paddle.to_tensor(np.random.RandomState(0).randn(1, 2, 6, 6)
                         .astype(np.float32))
    unf = nn.Unfold(kernel_sizes=2, strides=2)
    folded = nn.Fold(output_sizes=(6, 6), kernel_sizes=2, strides=2)
    # non-overlapping stride=kernel: fold(unfold(x)) == x exactly
    np.testing.assert_allclose(_np(folded(unf(x))), _np(x), rtol=1e-5,
                               atol=1e-6)


def test_zeropad2d_and_pairwise_distance():
    x = paddle.to_tensor(np.ones((1, 1, 2, 2), np.float32))
    padded = nn.ZeroPad2D([1, 1, 1, 1])(x)
    assert tuple(padded.shape) == (1, 1, 4, 4)
    assert float(padded[0, 0, 0, 0]) == 0.0

    a = paddle.to_tensor(np.array([[0.0, 0.0]], np.float32))
    b = paddle.to_tensor(np.array([[3.0, 4.0]], np.float32))
    d = nn.PairwiseDistance()(a, b)
    assert float(d) == pytest.approx(5.0, rel=1e-4)


def test_bilinear_and_alpha_dropout():
    bl = nn.Bilinear(3, 4, 2)
    x1 = paddle.to_tensor(np.random.RandomState(0).randn(5, 3)
                          .astype(np.float32))
    x2 = paddle.to_tensor(np.random.RandomState(1).randn(5, 4)
                          .astype(np.float32))
    out = bl(x1, x2)
    assert tuple(out.shape) == (5, 2)
    ref = np.einsum("bi,oij,bj->bo", _np(x1), _np(bl.weight), _np(x2)) \
        + _np(bl.bias)
    np.testing.assert_allclose(_np(out), ref, rtol=1e-4, atol=1e-5)

    ad = nn.AlphaDropout(p=0.3)
    ad.train()
    big = paddle.to_tensor(np.random.RandomState(2).randn(10000)
                           .astype(np.float32))
    out = ad(big)
    # mean/std approximately preserved (the point of alpha dropout)
    assert abs(float(out.mean()) - float(big.mean())) < 0.1
    assert abs(float(out.std()) - float(big.std())) < 0.15
    ad.eval()
    np.testing.assert_allclose(_np(ad(big)), _np(big))
