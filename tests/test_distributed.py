"""Distributed stack tests on the 8-device CPU mesh.

Reference coverage model: test/collective/ (single-host multi-rank collective
tests) and test/auto_parallel/ (SPMD + reshard tests) — SURVEY.md §4.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import jit, nn, optimizer
from paddle_tpu.distributed import fleet


@pytest.fixture(autouse=True)
def _reset_topology():
    yield
    from paddle_tpu.distributed.fleet import topology
    topology.set_hybrid_communicate_group(None)


def test_world_setup():
    g = dist.init_parallel_env()
    assert dist.get_world_size() == 8
    assert dist.get_rank() == 0
    assert dist.is_initialized()


def test_all_reduce_sum_max():
    t = paddle.to_tensor(np.arange(8, dtype="float32").reshape(8, 1))
    dist.all_reduce(t)
    np.testing.assert_allclose(t.numpy(), np.full((8, 1), 28.0))
    t = paddle.to_tensor(np.arange(8, dtype="float32").reshape(8, 1))
    dist.all_reduce(t, op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(t.numpy(), np.full((8, 1), 7.0))


def test_all_gather():
    t = paddle.to_tensor(np.arange(8, dtype="float32").reshape(8, 1))
    out = []
    dist.all_gather(out, t)
    assert len(out) == 8
    assert out[5].numpy().tolist() == [5.0]


def test_broadcast():
    t = paddle.to_tensor(np.arange(8, dtype="float32").reshape(8, 1))
    dist.broadcast(t, src=3)
    np.testing.assert_allclose(t.numpy(), np.full((8, 1), 3.0))


def test_reduce_scatter():
    t = paddle.to_tensor(np.ones((8, 16), dtype="float32"))
    out = dist.reduce_scatter(t)
    np.testing.assert_allclose(out.numpy(), np.full((8, 2), 8.0))


def test_alltoall():
    ins = paddle.to_tensor(np.arange(64, dtype="float32").reshape(8, 8))
    outs = dist.alltoall(ins)
    np.testing.assert_allclose(np.asarray(outs.numpy()).reshape(8, 8),
                               ins.numpy().T)


def test_barrier():
    dist.barrier()


def test_new_group():
    g = dist.new_group([0, 1, 2, 3])
    assert g.nranks == 4
    t = paddle.to_tensor(np.ones((4, 2), dtype="float32"))
    dist.all_reduce(t, group=g)
    np.testing.assert_allclose(t.numpy(), np.full((4, 2), 4.0))


def test_stacked_shape_check():
    t = paddle.to_tensor(np.ones((3, 2), dtype="float32"))
    with pytest.raises(ValueError, match="rank-stacked"):
        dist.all_reduce(t)


def test_shard_tensor_and_reshard():
    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    t = paddle.randn([16, 32])
    ts = dist.shard_tensor(t, mesh, [dist.Shard(0), dist.Shard(1)])
    spec = ts._data.sharding.spec

    def _names(e):
        return e if isinstance(e, tuple) else (e,)

    assert "x" in _names(spec[0]) and "y" in _names(spec[1])
    rep = dist.reshard(ts, mesh, [dist.Replicate(), dist.Replicate()])
    np.testing.assert_allclose(rep.numpy(), ts.numpy())
    placements = dist.get_placements(ts)
    assert placements[0] == dist.Shard(0)


def test_dtensor_roundtrip():
    mesh = dist.ProcessMesh(np.arange(8), dim_names=["x"])
    t = paddle.randn([8, 4])
    d = dist.dtensor_from_local(t, mesh, [dist.Shard(0)])
    local = dist.dtensor_to_local(d)
    assert local.shape[0] == 1  # one shard per device
    full = dist.unshard_dtensor(d)
    np.testing.assert_allclose(full.numpy(), t.numpy())


def test_sharded_matmul_correctness():
    """GSPMD matmul on sharded operands == dense matmul (the SPMD-rule
    correctness analog, infermeta/spmd_rules/matmul.cc)."""
    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    a = paddle.randn([16, 64])
    b = paddle.randn([64, 32])
    a_s = dist.shard_tensor(a, mesh, [dist.Shard(0)])
    b_s = dist.shard_tensor(b, mesh, [dist.Replicate(), dist.Shard(1)])
    out = paddle.matmul(a_s, b_s)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(),
                               rtol=1e-4, atol=1e-5)


def _init_fleet(dp=2, mp=4, pp=1, sharding=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": pp, "sharding_degree": sharding,
                               "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def test_fleet_topology():
    hcg = _init_fleet(dp=2, mp=4)
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 4
    assert hcg.mesh.shape == [2, 1, 1, 1, 4]
    topo = hcg.topology()
    assert topo.get_comm_list("model")[0] == [0, 1, 2, 3]
    assert topo.get_comm_list("data")[0] == [0, 4]


def test_tp_training_decreases_loss_and_keeps_sharding():
    _init_fleet(dp=2, mp=4)

    class TPMLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = fleet.ColumnParallelLinear(32, 64, gather_output=False)
            self.fc2 = fleet.RowParallelLinear(64, 8, input_is_parallel=True)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.gelu(self.fc1(x)))

    paddle.seed(0)
    model = fleet.distributed_model(TPMLP())
    opt = fleet.distributed_optimizer(
        optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters()))
    lossf = nn.CrossEntropyLoss()
    step = jit.TrainStep(lambda x, y: lossf(model(x), y), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)))
    losses = [float(step(x, y)) for _ in range(8)]
    assert losses[-1] < losses[0]
    spec = model.fc1.weight._data.sharding.spec
    assert spec[1] == "mp"


def test_tp_matches_dense_model():
    """TP-sharded model must compute the same math as its dense twin."""
    _init_fleet(dp=1, mp=8)
    paddle.seed(7)
    col = fleet.ColumnParallelLinear(16, 32, gather_output=True)
    row = fleet.RowParallelLinear(32, 8)
    x = paddle.randn([4, 16])
    out = row(col(x))
    expected = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
        @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-4, atol=1e-5)


def test_vocab_parallel_embedding():
    _init_fleet(dp=1, mp=8)
    emb = fleet.VocabParallelEmbedding(64, 16)
    idx = paddle.to_tensor(np.random.randint(0, 64, (4, 10)))
    out = emb(idx)
    assert out.shape == [4, 10, 16]
    np.testing.assert_allclose(out.numpy(),
                               emb.weight.numpy()[idx.numpy()], rtol=1e-6)


def test_group_sharded_stage3():
    m = nn.Sequential(nn.Linear(32, 64), nn.GELU(), nn.Linear(64, 8))
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    m, opt = dist.sharding.group_sharded_parallel(
        m, opt, level="p_g_os", group=dist.init_parallel_env())
    spec = m[0].weight._data.sharding.spec
    assert spec[0] is not None  # param dim0 sharded
    lossf = nn.CrossEntropyLoss()
    step = jit.TrainStep(lambda x, y: lossf(m(x), y), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)))
    losses = [float(step(x, y)) for _ in range(6)]
    assert losses[-1] < losses[0]
    st = list(opt._accumulators["moment1"].values())[0]
    assert st.sharding.spec[0] is not None  # states sharded


def test_group_sharded_stage1_states_only():
    m = nn.Linear(32, 8)
    opt = optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    m, opt = dist.sharding.group_sharded_parallel(
        m, opt, level="os", group=dist.init_parallel_env())
    # params replicated
    assert all(e is None for e in m.weight._data.sharding.spec)
    (m(paddle.randn([4, 32])).sum()).backward()
    opt.step()
    st = list(opt._accumulators["moment1"].values())[0]
    assert st.sharding.spec[0] is not None


def test_data_parallel_wrapper():
    dp = paddle.DataParallel(nn.Linear(8, 4))
    out = dp(paddle.randn([16, 8]))
    assert out.shape == [16, 4]
    with dp.no_sync():
        pass
    assert len(dp.parameters()) == 2


def test_recompute_matches_direct():
    x = paddle.randn([4, 16])
    x.stop_gradient = False
    lin = nn.Linear(16, 16)
    y = fleet.recompute(lambda t: lin(t).tanh(), x)
    y.sum().backward()
    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    lin(x2).tanh().sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), x2.grad.numpy(), rtol=1e-5)


def test_recompute_with_dropout_rng_replay():
    paddle.seed(5)
    x = paddle.randn([64, 64])
    x.stop_gradient = False
    drop = nn.Dropout(0.5)
    y = fleet.recompute(lambda t: drop(t * 2), x)
    y.sum().backward()
    # grad must match the SAME mask as forward: grad = 2/keep where kept
    g = x.grad.numpy()
    out = y.numpy()
    kept = out != 0
    np.testing.assert_allclose(g[kept], np.full(kept.sum(), 4.0), rtol=1e-6)
    np.testing.assert_allclose(g[~kept], 0.0)


def test_recompute_sequential():
    seq = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 8))
    x = paddle.randn([2, 8])
    x.stop_gradient = False
    y = fleet.recompute_sequential({"segments": 2}, seq, x)
    y.sum().backward()
    assert x.grad is not None


def test_shard_optimizer():
    m = nn.Linear(64, 8)
    opt = optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
    mesh = dist.ProcessMesh(np.arange(8), dim_names=["x"])
    opt = dist.shard_optimizer(opt, mesh)
    m(paddle.randn([4, 64])).sum().backward()
    opt.step()
    st = list(opt._accumulators["moment1"].values())[0]
    assert st.sharding.spec[0] == "x"


def test_rng_state_tracker():
    from paddle_tpu.distributed.fleet.random_ctrl import RNGStatesTracker
    tr = RNGStatesTracker()
    tr.add("mp", 123)
    with tr.rng_state("mp"):
        a = paddle.randn([4])
    with tr.rng_state("mp"):
        b = paddle.randn([4])
    assert not np.allclose(a.numpy(), b.numpy())  # stream advances
    tr2 = RNGStatesTracker()
    tr2.add("mp", 123)
    with tr2.rng_state("mp"):
        c = paddle.randn([4])
    np.testing.assert_allclose(a.numpy(), c.numpy())  # same seed -> same draw


def test_reduce_scatter_max_op():
    t = paddle.to_tensor(
        np.tile(np.arange(8, dtype="float32").reshape(8, 1), (1, 16)))
    out = dist.reduce_scatter(t, op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(out.numpy(), np.full((8, 2), 7.0))


def test_all_gather_object_world_sized():
    objs = []
    dist.all_gather_object(objs, {"a": 1})
    assert len(objs) == 8


def test_broadcast_src_not_in_group_raises():
    g = dist.new_group([4, 5, 6, 7])
    t = paddle.to_tensor(np.ones((4, 2), dtype="float32"))
    with pytest.raises(ValueError, match="not in group"):
        dist.broadcast(t, src=0, group=g)


def test_p2p_ambiguity_raises():
    from paddle_tpu.distributed import collective as coll
    coll._P2P_BUF.clear()
    a = paddle.to_tensor([1.0]); b = paddle.to_tensor([2.0])
    dist.send(a, dst=1)
    dist.send(b, dst=2)
    t = paddle.zeros([1])
    with pytest.raises(RuntimeError, match="ambiguous"):
        dist.recv(t, src=0)
    coll._P2P_BUF.clear()
    dist.send(a, dst=1)
    dist.recv(t, src=0)
    np.testing.assert_allclose(t.numpy(), [1.0])


def test_recompute_kwarg_tensor_gets_grad():
    x = paddle.randn([4, 8])
    x.stop_gradient = False
    lin = nn.Linear(8, 8)
    y = fleet.recompute(lambda t=None: lin(t).tanh(), t=x)
    y.sum().backward()
    assert x.grad is not None
    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    lin(x2).tanh().sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), x2.grad.numpy(), rtol=1e-5)


def test_stage2_grad_sharding_consumed():
    m = nn.Sequential(nn.Linear(32, 64), nn.GELU(), nn.Linear(64, 8))
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    m, opt = dist.sharding.group_sharded_parallel(
        m, opt, level="os_g", group=dist.init_parallel_env())
    lossf = nn.CrossEntropyLoss()
    step = jit.TrainStep(lambda x, y: lossf(m(x), y), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)))
    losses = [float(step(x, y)) for _ in range(5)]
    assert losses[-1] < losses[0]
    gs = opt._group_sharded
    assert gs.grad_sharding((64, 8)) is not None  # policy active for div dims


def test_init_parallel_env_multihost_env_gating(monkeypatch):
    """Single-process: multi-host bootstrap must not trigger; with the
    launcher env set but nnodes=1 it stays inert too."""
    from paddle_tpu.distributed import collective as C
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:29999")
    C._maybe_init_multihost()
    assert C.get_bootstrap_store() is None


def test_group_sharded_stage3_offload():
    """ZeRO-offload (VERDICT #8): optimizer states land in host memory and
    the compiled step still trains (XLA streams them at the step boundary)."""
    m = nn.Sequential(nn.Linear(32, 64), nn.GELU(), nn.Linear(64, 8))
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    m, opt = dist.sharding.group_sharded_parallel(
        m, opt, level="p_g_os", offload=True,
        group=dist.init_parallel_env())
    lossf = nn.CrossEntropyLoss()
    step = jit.TrainStep(lambda x, y: lossf(m(x), y), opt)
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(16, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)))
    losses = [float(step(x, y)) for _ in range(6)]
    assert losses[-1] < losses[0]
    st = list(opt._accumulators["moment1"].values())[0]
    assert st.sharding.memory_kind == "pinned_host"
    assert any(s is not None for s in st.sharding.spec)


def test_group_sharded_stage3_nondivisible_uses_other_dim():
    """A dim0-odd matrix shards on its other dim instead of replicating."""
    m = nn.Linear(30, 64)  # weight [30, 64]: 30 % 8 != 0, 64 % 8 == 0
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    m, opt = dist.sharding.group_sharded_parallel(
        m, opt, level="p_g_os", group=dist.init_parallel_env())
    spec = m.weight._data.sharding.spec
    assert spec[0] is None and spec[1] is not None  # sharded, NOT replicated


def test_group_sharded_offload_stage1_rejected():
    m = nn.Linear(8, 4)
    opt = optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    with pytest.raises(ValueError):
        dist.sharding.group_sharded_parallel(
            m, opt, level="os", offload=True,
            group=dist.init_parallel_env())


def test_device_topology_surface():
    """ICI-topology device-manager tier (VERDICT L2 gap): attributes,
    slice summary, and topology-ordered mesh construction."""
    from paddle_tpu.device import topology as topo
    assert topo.device_count() == 8
    attrs = topo.device_attributes()
    assert {"id", "platform", "process_index"} <= set(attrs)
    summary = topo.topology_summary()
    assert summary["num_devices"] == 8
    mesh = topo.create_ici_mesh((2, 4), ["dp", "mp"])
    assert mesh.shape == [2, 4]
    assert mesh.dim_names == ["dp", "mp"]
    # the mesh is usable for real sharding work
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.auto_parallel import Shard, Replicate, shard_tensor
    t = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
    shard_tensor(t, mesh, [Shard(0), Replicate()])
    assert t._data.sharding.spec[0] == "dp"


def test_tp4_uneven_vocab_embedding_head_and_parallel_ce():
    """>2-way TP with a vocab NOT divisible by mp (VERDICT r3 #7):
    4-way vocab-sharded embedding (130 % 4 != 0 — GSPMD pads the ragged
    shard), a column-parallel lm head with 4-way-sharded bias, and
    ParallelCrossEntropy over the vocab-sharded logits must match dense
    math inside one compiled step, and a compiled TP train step over the
    uneven shards must still learn.

    Reference: fleet/layers/mpu/mp_layers.py:46,335,743 (the reference
    computes the ragged last shard explicitly; GSPMD's padded sharding
    absorbs it here)."""
    _init_fleet(dp=2, mp=4)
    V, E = 130, 32
    paddle.seed(3)
    emb = fleet.VocabParallelEmbedding(V, E)
    head = fleet.ColumnParallelLinear(E, V, gather_output=True)
    lossf = fleet.ParallelCrossEntropy()
    rng = np.random.RandomState(5)
    ids = paddle.to_tensor(rng.randint(0, V, (8, 6)))
    labels = paddle.to_tensor(rng.randint(0, V, (8, 6)))

    def f(ids, labels):
        return lossf(head(emb(ids)), labels).mean()

    loss = jit.to_static(f)(ids, labels)
    # dense twin: the params are padded to 132 rows/cols (Megatron vocab
    # padding); the layer slices logits back to V
    logits = (emb.weight.numpy()[ids.numpy()] @ head.weight.numpy()
              + head.bias.numpy())[..., :V]
    x = logits - logits.max(-1, keepdims=True)
    logp = x - np.log(np.exp(x).sum(-1, keepdims=True))
    ref = -np.take_along_axis(logp, labels.numpy()[..., None],
                              -1).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4, atol=1e-5)

    opt = optimizer.AdamW(learning_rate=5e-2,
                          parameters=list(emb.parameters())
                          + list(head.parameters()))
    step = jit.TrainStep(f, opt)
    losses = [float(step(ids, labels)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # the uneven vocab dim really is sharded over mp
    assert emb.weight._data.sharding.spec[0] == "mp"
    assert head.bias._data.sharding.spec[0] == "mp"


def test_tp_padded_checkpoint_interchange():
    """ADVICE r4 (mp_layers.py:73,76): padded TP checkpoints interchange
    — state_dict saves the LOGICAL shape (pad tail sliced off),
    set_state_dict accepts true-shape external checkpoints (zero-fills
    the tail) and other-degree padded ones (strips then re-pads), and
    phantom vocab rows are exactly zero so a tied lm-head leaks no
    softmax mass."""
    _init_fleet(dp=2, mp=4)
    V, E = 130, 32                       # 130 % 4 != 0 -> padded to 132
    paddle.seed(11)
    emb = fleet.VocabParallelEmbedding(V, E)
    head = fleet.ColumnParallelLinear(E, V, gather_output=True)
    row = fleet.RowParallelLinear(V, E)
    assert emb.weight.shape == [132, E]
    # pad regions are exactly zero after init (Megatron practice)
    np.testing.assert_array_equal(emb.weight.numpy()[V:], 0.0)
    np.testing.assert_array_equal(head.weight.numpy()[:, V:], 0.0)
    np.testing.assert_array_equal(head.bias.numpy()[V:], 0.0)
    np.testing.assert_array_equal(row.weight.numpy()[V:], 0.0)
    # state_dict carries the TRUE shapes
    assert list(emb.state_dict()["weight"].shape) == [V, E]
    hsd = head.state_dict()
    assert list(hsd["weight"].shape) == [E, V]
    assert list(hsd["bias"].shape) == [V]
    assert list(row.state_dict()["weight"].shape) == [V, E]
    # a true-shape external/reference checkpoint loads (pad-on-load)
    rng = np.random.RandomState(0)
    ext = rng.randn(V, E).astype("float32")
    missing, unexpected = emb.set_state_dict({"weight": ext})
    assert not missing and not unexpected
    np.testing.assert_array_equal(emb.weight.numpy()[:V], ext)
    np.testing.assert_array_equal(emb.weight.numpy()[V:], 0.0)
    # another degree's padded checkpoint (e.g. mp=8 -> 136 rows) loads:
    # its zero tail is stripped to the logical shape, then re-padded
    padded8 = np.concatenate([ext, np.zeros((6, E), "float32")])
    emb.set_state_dict({"weight": padded8})
    np.testing.assert_array_equal(emb.weight.numpy()[:V], ext)
    # save -> load roundtrip across layers preserves logical content
    sd = head.state_dict()
    w_logical = sd["weight"].numpy().copy()
    head2 = fleet.ColumnParallelLinear(E, V, gather_output=True)
    head2.set_state_dict({k: v.numpy() for k, v in sd.items()})
    np.testing.assert_array_equal(head2.weight.numpy()[:, :V], w_logical)
    # a GENUINE mismatch (wrong non-pad dim) still fails loudly
    import pytest as _pytest
    with _pytest.raises(ValueError, match="shape mismatch"):
        emb.set_state_dict({"weight": rng.randn(V, E + 1).astype("float32")})
    # a smaller vocab is NOT silently zero-padded (code-review r5): only
    # the exact logical size pads on load
    with _pytest.raises(ValueError, match="shape mismatch"):
        emb.set_state_dict({"weight": rng.randn(5, E).astype("float32")})
    # a larger array with a NONZERO tail is a real 136-vocab model, not
    # another degree's pad — truncating it would discard real rows
    big = rng.randn(136, E).astype("float32")
    with _pytest.raises(ValueError, match="shape mismatch"):
        emb.set_state_dict({"weight": big})
