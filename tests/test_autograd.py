"""Autograd engine tests.

Mirrors the reference's eager autograd coverage (test/legacy_test backward
tests + test/autograd): backward correctness vs analytic grads, accumulation,
no_grad, paddle.grad, hooks, PyLayer.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_backward():
    x = paddle.to_tensor(np.random.randn(4, 3).astype("float32"),
                         stop_gradient=False)
    w = paddle.to_tensor(np.random.randn(3, 5).astype("float32"),
                         stop_gradient=False)
    y = paddle.matmul(x, w)
    z = (y * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * y.numpy() @ w.numpy().T,
                               rtol=1e-5)
    np.testing.assert_allclose(w.grad.numpy(), x.numpy().T @ (2 * y.numpy()),
                               rtol=1e-5)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach_blocks_grad():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * 3
    z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_no_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_shared_subexpression():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x          # y = x^2
    z = y + y          # z = 2 x^2 -> dz/dx = 4x = 12
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_paddle_grad():
    x = paddle.to_tensor([4.0], stop_gradient=False)
    y = x ** 3
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [48.0])
    assert x.grad is None  # paddle.grad does not write .grad


def test_grad_allow_unused():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    u = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x, u])
    y = x * 2  # first grad() consumed the graph
    g = paddle.grad(y, [x, u], allow_unused=True)
    assert g[1] is None


def test_non_scalar_backward_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])


def test_non_scalar_backward_raises():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    with pytest.raises(RuntimeError):
        y.backward()


def test_multi_output_op_backward():
    x = paddle.to_tensor(np.arange(6).reshape(2, 3).astype("float32"),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=0)
    (a.sum() * 2 + b.sum() * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[2, 2, 2], [3, 3, 3]])


def test_tensor_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    seen = []
    y.register_hook(lambda g: seen.append(g.numpy()) or g * 10)
    (y * 3).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [60.0])


def test_pylayer():
    class Square(paddle.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor()
            return grad * 2 * x

    t = paddle.to_tensor([3.0], stop_gradient=False)
    out = Square.apply(t)
    out.backward()
    np.testing.assert_allclose(t.grad.numpy(), [6.0])


def test_pylayer_multi_io():
    class AddMul(paddle.PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            ctx.save_for_backward(a, b)
            return a + b, a * b

        @staticmethod
        def backward(ctx, ga, gm):
            a, b = ctx.saved_tensor()
            return ga + gm * b, ga + gm * a

    a = paddle.to_tensor([2.0], stop_gradient=False)
    b = paddle.to_tensor([5.0], stop_gradient=False)
    s, m = AddMul.apply(a, b)
    (s + m).backward()
    np.testing.assert_allclose(a.grad.numpy(), [6.0])
    np.testing.assert_allclose(b.grad.numpy(), [3.0])


def test_numeric_gradient_check():
    """Finite-difference check (OpTest.check_grad analog, op_test.py:420)."""
    rng = np.random.RandomState(0)
    xv = rng.randn(3, 4).astype("float64")

    def f(v):
        t = paddle.to_tensor(v, dtype="float64", stop_gradient=False)
        out = paddle.tanh(paddle.matmul(t, t.T)).sum()
        return t, out

    t, out = f(xv)
    out.backward()
    analytic = t.grad.numpy()
    eps = 1e-6
    numeric = np.zeros_like(xv)
    for i in range(xv.shape[0]):
        for j in range(xv.shape[1]):
            xp = xv.copy(); xp[i, j] += eps
            xm = xv.copy(); xm[i, j] -= eps
            _, op = f(xp)
            _, om = f(xm)
            numeric[i, j] = (op.item() - om.item()) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, rtol=1e-5, atol=1e-7)


def test_double_backward_create_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x ** 3
    (g,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g.numpy(), [12.0])
    (g2,) = paddle.grad(g, x)
    np.testing.assert_allclose(g2.numpy(), [12.0])  # d2y/dx2 = 6x
