"""Model zoo tests (reference: test/book/ end-to-end smoke + vision model tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.models import (GPT2Config, GPT2ForCausalLM, LlamaForCausalLM,
                               llama_tiny_config, resnet18)


def test_llama_forward_shapes():
    cfg = llama_tiny_config()
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 16)))
    logits = model(ids)
    assert list(logits.shape) == [2, 16, cfg.vocab_size]


def test_llama_gqa_heads():
    cfg = llama_tiny_config(num_attention_heads=4, num_key_value_heads=1)
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (1, 8)))
    logits = model(ids)
    assert list(logits.shape) == [1, 8, cfg.vocab_size]


def test_llama_train_step_loss_decreases():
    cfg = llama_tiny_config()
    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters())
    ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 16)))
    labels = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 16)))
    losses = []
    for _ in range(5):
        _, loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_llama_causality():
    """Changing a future token must not change past logits (causal mask)."""
    cfg = llama_tiny_config()
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = np.random.randint(0, cfg.vocab_size, (1, 12))
    l1 = model(paddle.to_tensor(ids)).numpy()
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 1) % cfg.vocab_size
    l2 = model(paddle.to_tensor(ids2)).numpy()
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)


def test_gpt2_forward_and_tied_head():
    cfg = GPT2Config(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=32)
    model = GPT2ForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(np.random.randint(0, 96, (2, 10)))
    logits, loss = model(ids, labels=ids)
    assert list(logits.shape) == [2, 10, 96]
    assert np.isfinite(float(loss.numpy()))


def test_gpt2_train_step():
    cfg = GPT2Config(vocab_size=64, hidden_size=32, num_hidden_layers=1,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=16, dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=5e-3, parameters=model.parameters())
    ids = paddle.to_tensor(np.random.randint(0, 64, (2, 8)))
    losses = []
    for _ in range(5):
        _, loss = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_resnet18_forward_train_eval():
    model = resnet18(num_classes=10)
    x = paddle.to_tensor(np.random.randn(2, 3, 32, 32).astype("float32"))
    out = model(x)
    assert list(out.shape) == [2, 10]
    model.eval()
    out = model(x)
    assert list(out.shape) == [2, 10]


def test_resnet_backward():
    model = resnet18(num_classes=4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    x = paddle.to_tensor(np.random.randn(2, 3, 32, 32).astype("float32"))
    label = paddle.to_tensor(np.array([1, 2]))
    loss = nn.functional.cross_entropy(model(x), label)
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(loss.numpy()))


def test_llama_recompute_matches_baseline_trajectory():
    """use_recompute=True re-runs decoder layers in backward; the training
    trajectory through the compiled TrainStep must match exactly."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config

    def run(use_rc):
        paddle.seed(0)
        cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                                num_attention_heads=4, num_key_value_heads=2,
                                vocab_size=128, max_position_embeddings=64,
                                use_recompute=use_rc)
        m = LlamaForCausalLM(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = jit.TrainStep(lambda i, l: m(i, labels=l)[1], opt)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (2, 16)))
        lbl = paddle.to_tensor(rng.randint(0, 128, (2, 16)))
        return [float(step(ids, lbl)) for _ in range(3)]

    base = run(False)
    rc = run(True)
    assert all(abs(a - b) < 2e-3 for a, b in zip(base, rc)), (base, rc)


class TestScanLayers:
    """ScannedLlamaLayers: one lax.scan over stacked weights — numerics
    must match the unrolled stack exactly (compile-time optimization only)."""

    def _copy_unrolled_to_scanned(self, m_u, m_s):
        from tests.helpers.llama_weights import copy_unrolled_to_scanned
        copy_unrolled_to_scanned(m_u, m_s)

    def test_matches_unrolled(self):
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
        paddle.seed(0)
        m_u = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=3))
        m_s = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=3,
                                                 scan_layers=True))
        self._copy_unrolled_to_scanned(m_u, m_s)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (2, 16)))
        m_u.eval()
        m_s.eval()
        with paddle.no_grad():
            out_u = np.asarray(m_u(ids)._data)
            out_s = np.asarray(m_s(ids)._data)
        np.testing.assert_allclose(out_u, out_s, atol=1e-4)

    def test_trains_and_param_count_matches(self):
        from paddle_tpu import jit, optimizer
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
        paddle.seed(0)
        m_u = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=2))
        m_s = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=2,
                                                 scan_layers=True))
        assert m_u.num_params() == m_s.num_params()
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=m_s.parameters())
        step = jit.TrainStep(lambda i, l: m_s(i, labels=l)[1], opt)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (2, 16)))
        labels = paddle.to_tensor(rng.randint(0, 128, (2, 16)))
        losses = [float(step(ids, labels)._data) for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_remat_inside_scan(self):
        from paddle_tpu import jit, optimizer
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
        m = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=2,
                                               scan_layers=True,
                                               use_recompute=True))
        opt = optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = jit.TrainStep(lambda i, l: m(i, labels=l)[1], opt)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (2, 16)))
        labels = paddle.to_tensor(rng.randint(0, 128, (2, 16)))
        assert np.isfinite(float(step(ids, labels)._data))
