"""vision.ops detection suite.

Reference test model: test/legacy_test/test_roi_align_op.py,
test_roi_pool_op, test_deformable_conv_op, test_yolo_box_op,
test_yolov3_loss_op, test_prior_box_op, test_box_coder_op,
test_matrix_nms_op, test_generate_proposals_v2_op.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import ops as V

RNG = np.random.RandomState(11)


def _t(a, d="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=d))


def _np(x):
    return np.asarray(x._data)


class TestRoIOps:
    def test_roi_align_constant_image(self):
        x = _t(np.ones((1, 2, 8, 8)))
        out = V.roi_align(x, _t([[1.0, 1.0, 5.0, 5.0]]), output_size=3)
        assert list(out.shape) == [1, 2, 3, 3]
        np.testing.assert_allclose(_np(out), 1.0, atol=1e-5)

    def test_roi_align_gradient_image(self):
        # linear ramp along x: aligned RoIAlign samples reproduce the ramp
        ramp = np.tile(np.arange(8, dtype="float32"), (8, 1))
        x = _t(ramp[None, None])
        out = V.roi_align(x, _t([[2.0, 2.0, 6.0, 6.0]]), output_size=2,
                          aligned=True)
        vals = _np(out)[0, 0]
        assert vals[0, 0] < vals[0, 1]          # increases along x
        np.testing.assert_allclose(vals[0], vals[1], atol=1e-4)  # flat in y

    def test_roi_pool_max_semantics(self):
        x = np.zeros((1, 1, 8, 8), "float32")
        x[0, 0, 3, 3] = 9.0
        out = V.roi_pool(_t(x), _t([[0.0, 0.0, 7.0, 7.0]]), output_size=2)
        assert _np(out).max() == 9.0

    def test_psroi_pool_channel_groups(self):
        # 8 channels, 2x2 bins -> 2 output channels
        x = _t(RNG.rand(1, 8, 8, 8))
        out = V.psroi_pool(x, _t([[0.0, 0.0, 8.0, 8.0]]), output_size=2)
        assert list(out.shape) == [1, 2, 2, 2]

    def test_batched_rois(self):
        x = _t(RNG.rand(2, 2, 8, 8))
        boxes = _t([[0.0, 0.0, 4.0, 4.0], [1.0, 1.0, 6.0, 6.0],
                    [2.0, 2.0, 7.0, 7.0]])
        nums = _t([2, 1], "int32")
        out = V.roi_align(x, boxes, boxes_num=nums, output_size=2)
        assert list(out.shape) == [3, 2, 2, 2]


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        x = _t(RNG.randn(1, 2, 6, 6))
        w = _t(RNG.randn(3, 2, 3, 3) * 0.2)
        off = _t(np.zeros((1, 18, 4, 4)))
        out = V.deform_conv2d(x, off, w)
        ref = F.conv2d(x, w)
        np.testing.assert_allclose(_np(out), _np(ref), atol=1e-4)

    def test_integer_offset_shifts_sampling(self):
        x = _t(RNG.randn(1, 1, 6, 6))
        w = _t(np.ones((1, 1, 1, 1)))
        # offset dy=0, dx=1 everywhere: output = x shifted left by 1
        off = np.zeros((1, 2, 6, 6), "float32")
        off[0, 1] = 1.0
        out = V.deform_conv2d(x, _t(off), w)
        np.testing.assert_allclose(_np(out)[0, 0, :, :-1],
                                   _np(x)[0, 0, :, 1:], atol=1e-5)

    def test_layer_class(self):
        layer = V.DeformConv2D(2, 4, 3, padding=1)
        x = _t(RNG.randn(1, 2, 5, 5))
        off = _t(np.zeros((1, 18, 5, 5)))
        assert list(layer(x, off).shape) == [1, 4, 5, 5]


class TestYolo:
    def test_yolo_box_shapes_and_range(self):
        na, cls = 3, 4
        x = _t(RNG.randn(2, na * (5 + cls), 4, 4))
        img = _t([[64, 64], [64, 64]], "int32")
        boxes, scores = V.yolo_box(x, img, [10, 13, 16, 30, 33, 23], cls)
        assert list(boxes.shape) == [2, 48, 4]
        assert list(scores.shape) == [2, 48, 4]
        b = _np(boxes)
        assert (b >= 0).all() and (b <= 64).all()   # clip_bbox
        s = _np(scores)
        assert (s >= 0).all() and (s <= 1).all()

    def test_yolo_loss_decreases_with_fit(self):
        na, cls = 3, 4
        gtb = _t([[[0.5, 0.5, 0.4, 0.4]]])
        gtl = _t([[1]], "int64")
        kwargs = dict(anchors=[10, 13, 16, 30, 33, 23],
                      anchor_mask=[0, 1, 2], class_num=cls,
                      ignore_thresh=0.7, downsample_ratio=32)
        bad = _t(RNG.randn(1, na * (5 + cls), 4, 4) * 3)
        l_bad = float(_np(V.yolo_loss(bad, gtb, gtl, **kwargs))[0])
        l_zero = float(_np(V.yolo_loss(
            _t(np.zeros((1, na * (5 + cls), 4, 4))), gtb, gtl,
            **kwargs))[0])
        assert np.isfinite(l_bad) and np.isfinite(l_zero)


class TestBoxOps:
    def test_prior_box(self):
        pb, pv = V.prior_box(_t(RNG.randn(1, 3, 4, 4)),
                             _t(RNG.randn(1, 3, 32, 32)),
                             min_sizes=[8.0], aspect_ratios=[1.0, 2.0],
                             flip=True, clip=True)
        assert _np(pb).shape == (4, 4, 3, 4)
        assert (_np(pb) >= 0).all() and (_np(pb) <= 1).all()

    def test_box_coder_roundtrip(self):
        priors = _t([[10.0, 10.0, 30.0, 30.0], [5.0, 5.0, 15.0, 25.0]])
        var = _t(np.full((2, 4), 0.1, "float32"))
        targets = _t([[12.0, 8.0, 33.0, 28.0], [6.0, 7.0, 17.0, 21.0]])
        enc = V.box_coder(priors, var, targets, "encode_center_size")
        dec = V.box_coder(priors, var, enc, "decode_center_size")
        np.testing.assert_allclose(_np(dec), _np(targets), atol=1e-3)

    def test_matrix_nms_suppresses_overlaps(self):
        boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                           [20, 20, 30, 30]]], "float32")
        scores = np.zeros((1, 2, 3), "float32")
        scores[0, 1] = [0.9, 0.85, 0.8]     # class 1 (0 = background)
        dets, nums = V.matrix_nms(_t(boxes), _t(scores), 0.1, 0.0, 10, 10)
        d = _np(dets)
        assert int(_np(nums)[0]) == 3
        # the overlapping box's score decays below the isolated ones
        decayed = sorted(d[:, 1])
        assert decayed[0] < 0.85

    def test_fpn_distribute(self):
        rois = np.array([[0, 0, 16, 16], [0, 0, 200, 200]], "float32")
        outs, restore, nums = V.distribute_fpn_proposals(
            _t(rois), 2, 5, 4, 224)
        sizes = [o.shape[0] for o in outs]
        assert sum(sizes) == 2
        # 16px roi -> clipped to min level 2; 200px -> level 3 (log2 rule)
        assert sizes[0] == 1 and sizes[1] == 1

    def test_generate_proposals(self):
        props, scores = V.generate_proposals(
            _t(RNG.rand(1, 3, 4, 4)), _t(RNG.randn(1, 12, 4, 4) * 0.1),
            _t([[64, 64]], "int32"), _t(RNG.rand(48, 4) * 32),
            _t(np.full((48, 4), 0.1, "float32")), post_nms_top_n=5)
        assert _np(props).shape[1] == 4
        assert _np(props).shape[0] <= 5
        b = _np(props)
        assert (b[:, 2] >= b[:, 0]).all() and (b[:, 3] >= b[:, 1]).all()
