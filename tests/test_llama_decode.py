"""Llama incremental (KV-cache) decode — the flagship's serving path.

Same exactness bar as the GPT-2 decode suite: every incremental token
must equal the full-context recompute, through GQA (kv heads < q heads),
RoPE applied at per-batch positions, the compiled step, and the MoE
variant.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config


def _tiny(**over):
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=64,
                            num_attention_heads=4, num_key_value_heads=2,
                            vocab_size=128, max_position_embeddings=64,
                            **over)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _greedy_full(m, ids, n):
    cur = np.asarray(ids._data)
    with paddle.no_grad():
        for _ in range(n):
            logits = m(paddle.to_tensor(cur))
            nxt = np.asarray(logits._data)[:, -1].argmax(-1)[:, None]
            cur = np.concatenate([cur, nxt], axis=1)
    return cur.tolist()


def test_llama_kv_decode_matches_full_recompute_gqa():
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 10)))
    with paddle.no_grad():
        out = m.generate(ids, max_new_tokens=6).numpy().tolist()
    assert out == _greedy_full(m, ids, 6)


def test_llama_compiled_decode_step_matches_eager():
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 128, (2, 9)))
    with paddle.no_grad():
        ref = m.generate(ids, max_new_tokens=7).numpy().tolist()
        step = jit.to_static(m.decode_step)
        out = m.generate(ids, max_new_tokens=7,
                         decode_fn=step).numpy().tolist()
    assert out == ref


def test_llama_moe_decode_matches_full_recompute():
    """The MoE flagship serves through the same cache path (routing runs
    per decode token)."""
    m, cfg = _tiny(num_experts=4, moe_top_k=2, moe_capacity_factor=4.0)
    ids = paddle.to_tensor(np.random.RandomState(2).randint(0, 128, (2, 8)))
    with paddle.no_grad():
        out = m.generate(ids, max_new_tokens=5).numpy().tolist()
    assert out == _greedy_full(m, ids, 5)


def test_llama_decode_rejects_scan_layers():
    m, cfg = _tiny(scan_layers=True)
    ids = paddle.to_tensor(np.random.RandomState(3).randint(0, 128, (1, 8)))
    with pytest.raises(ValueError, match="unrolled"):
        m.generate(ids, max_new_tokens=4)


def test_llama_generate_bounds():
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(4).randint(0, 128, (1, 8)))
    with pytest.raises(ValueError, match="s_max"):
        m.generate(ids, max_new_tokens=16, s_max=12)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        m.generate(ids, max_new_tokens=200, s_max=256)


def test_llama_sampling_seeded():
    m, cfg = _tiny()
    ids = paddle.to_tensor(np.random.RandomState(5).randint(0, 128, (2, 8)))
    with paddle.no_grad():
        greedy = m.generate(ids, max_new_tokens=5).numpy().tolist()
        s1 = m.generate(ids, max_new_tokens=5, do_sample=True,
                        seed=7).numpy().tolist()
        s2 = m.generate(ids, max_new_tokens=5, do_sample=True,
                        seed=7).numpy().tolist()
        cold = m.generate(ids, max_new_tokens=5, do_sample=True,
                          temperature=1e-4, seed=7).numpy().tolist()
    assert s1 == s2
    assert cold == greedy
