"""tools/tpu_burndown.py orchestration checks (VERDICT r3 #3).

The hardware behavior (per-unit Mosaic compiles) can only run in a healthy
relay window; what CAN be pinned on CPU is the orchestration contract the
round-3 postmortem demands: the relay-killing dropout-PRNG compile runs
LAST, every unit is its own subprocess, and a failed health probe aborts
the run and names the culprit.
"""
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tools", "tpu_burndown.py")


def _load():
    spec = importlib.util.spec_from_file_location("tpu_burndown", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.quick
def test_unit_order_prng_last_and_phases_partition():
    mod = _load()
    names = [u[0] for u in mod.UNITS]
    phases = [u[2] for u in mod.UNITS]
    # the compile that wedged the relay for 8h must be the final contact
    assert names[-2:] == ["dropout_prng_fwd", "dropout_prng_bwd"]
    assert phases[-2:] == ["risky", "risky"]
    # safe units (validated on hardware in round 3, or multi-chip skips)
    # all come before any first-contact compile
    first_risky = phases.index("risky")
    assert all(p == "safe" for p in phases[:first_risky])
    assert all(p == "risky" for p in phases[first_risky:])
    # every unit node exists in the tier file
    tier = open(os.path.join(REPO, "tests", "test_tpu_tier.py")).read()
    for _, node, _, _ in mod.UNITS:
        assert f"def {node}(" in tier, node


def test_interpret_run_and_abort_on_wedge(tmp_path):
    """Drive the real script twice on CPU: a passing unit completes and is
    recorded; then a poisoned probe (impossible probe timeout -> fail)
    must abort with rc=2 and record the culprit."""
    report = tmp_path / "report.json"
    env = dict(os.environ, GRAFT_BURNDOWN_REPORT=str(report),
               GRAFT_BURNDOWN_LOG=str(tmp_path / "log.txt"))
    out = subprocess.run(
        [sys.executable, SCRIPT, "--interpret", "--units", "rmsnorm"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(report.read_text())
    assert rec["units"]["rmsnorm"]["status"] == "passed"
    assert rec["last_run"]["result"] == "completed"

    # dead-relay simulation: every probe fails -> nothing runs at all
    out = subprocess.run(
        [sys.executable, SCRIPT, "--interpret", "--units", "adamw"],
        env=dict(env, GRAFT_BURNDOWN_PROBE_CMD="false"),
        cwd=REPO, capture_output=True, text=True, timeout=420)
    rec = json.loads(report.read_text())
    assert out.returncode == 0
    assert rec["last_run"]["result"] == "relay_down"
    assert "adamw" not in rec["units"]

    # mid-run wedge: initial probe passes, the probe AFTER the unit fails
    # (scripted via a counter file) -> rc=2, culprit named, later units
    # never start
    counter = tmp_path / "probe_count"
    probe_cmd = (f"c=$(cat {counter} 2>/dev/null || echo 0); "
                 f"echo $((c+1)) > {counter}; test $c -lt 1")
    out = subprocess.run(
        [sys.executable, SCRIPT, "--interpret", "--units",
         "adamw,block_sparse"],
        env=dict(env, GRAFT_BURNDOWN_PROBE_CMD=probe_cmd),
        cwd=REPO, capture_output=True, text=True, timeout=420)
    rec = json.loads(report.read_text())
    assert out.returncode == 2, out.stdout + out.stderr
    assert rec["last_run"]["result"] == "aborted_after=adamw"
    assert rec["units"]["adamw"]["wedged_relay"] is True
    assert "block_sparse" not in rec["units"]


def test_heal_playbook_references_exist():
    """Every python entry the heal playbook invokes must exist — a
    dangling reference would burn the round's only hardware window on a
    file-not-found. Also pin the stage order contract: bench first,
    measured peaks + roofline before the burndown tiers, risky last."""
    import re
    lines = [ln for ln in
             open(os.path.join(REPO, ".on_heal_playbook.sh"))
             if not ln.lstrip().startswith("#")]   # comments don't run
    order = []
    for ln in lines:
        # any interpreter invocation counts; a path the file-exists
        # check can't see (unmatchable chars) must FAIL, not be skipped
        for m in re.finditer(r"python3?\s+(\S+\.py)", ln):
            path = m.group(1)
            assert re.fullmatch(r"[A-Za-z0-9_/.-]+", path), \
                f"unparseable playbook entry: {path!r}"
            order.append(path)
    assert order, "playbook parses no python entries?"
    for path in order:
        assert os.path.exists(os.path.join(REPO, path)), path
    assert order.index("bench.py") < order.index("tools/measure_peaks.py")
    assert order.index("tools/measure_peaks.py") \
        < order.index("tools/roofline.py")
    # burndown runs twice (safe then risky), after the roofline re-emit
    burn = [i for i, r in enumerate(order) if r == "tools/tpu_burndown.py"]
    assert len(burn) == 2
    assert order.index("tools/roofline.py") < burn[0]
    assert order.index("benchmarks/bench_decode.py") < burn[1]
