"""Optimizer tests (reference model: test/legacy_test/test_adam*, test_sgd*)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _train(opt_ctor, steps=20):
    paddle.seed(0)
    net = nn.Linear(4, 1, bias_attr=False)
    opt = opt_ctor(net.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(64, 4).astype("float32"))
    target_w = np.array([[1.0], [-2.0], [0.5], [3.0]], "float32")
    y = paddle.to_tensor(x.numpy() @ target_w)
    losses = []
    for _ in range(steps):
        loss = nn.MSELoss()(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(loss.item())
    return losses


@pytest.mark.parametrize("ctor", [
    lambda p: optimizer.SGD(learning_rate=0.1, parameters=p),
    lambda p: optimizer.Momentum(learning_rate=0.1, parameters=p),
    lambda p: optimizer.Adam(learning_rate=0.1, parameters=p),
    lambda p: optimizer.AdamW(learning_rate=0.1, parameters=p),
    lambda p: optimizer.RMSProp(learning_rate=0.01, parameters=p),
    lambda p: optimizer.Adagrad(learning_rate=0.5, parameters=p),
    lambda p: optimizer.Adamax(learning_rate=0.1, parameters=p),
    lambda p: optimizer.Lamb(learning_rate=0.1, parameters=p),
    lambda p: optimizer.Adadelta(learning_rate=10.0, parameters=p),
])
def test_optimizers_decrease_loss(ctor):
    losses = _train(ctor, steps=60)
    assert losses[-1] < losses[0] * 0.9


def test_sgd_exact_update():
    p = paddle.core.tensor.Parameter(np.array([1.0, 2.0], "float32"))
    opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
    (p * paddle.to_tensor([3.0, 4.0])).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.3, 2.0 - 0.4], rtol=1e-6)


def test_adamw_decoupled_decay():
    p = paddle.core.tensor.Parameter(np.array([1.0], "float32"))
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[p])
    p.sum().backward()
    opt.step()
    # adamw: p = p*(1 - lr*wd) - lr*mhat/(sqrt(vhat)+eps); grad=1 -> mhat/vhat^.5 ~= 1
    expected = 1.0 * (1 - 0.1 * 0.5) - 0.1 * 1.0 / (1.0 + 1e-8)
    np.testing.assert_allclose(p.numpy(), [expected], rtol=1e-4)


def test_grad_clip_global_norm():
    p1 = paddle.core.tensor.Parameter(np.zeros(3, "float32"))
    p2 = paddle.core.tensor.Parameter(np.zeros(4, "float32"))
    clip = optimizer.ClipGradByGlobalNorm(1.0)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p1, p2], grad_clip=clip)
    (p1.sum() * 3.0 + p2.sum() * 4.0).backward()
    opt.step()
    total = np.sqrt((p1.numpy() ** 2).sum() + (p2.numpy() ** 2).sum())
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_lr_scheduler():
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    p = paddle.core.tensor.Parameter(np.array([1.0], "float32"))
    opt = optimizer.SGD(learning_rate=sched, parameters=[p])
    assert abs(opt.get_lr() - 0.1) < 1e-9
    sched.step()
    sched.step()
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_cosine_schedule():
    sched = optimizer.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    vals = []
    for _ in range(11):
        vals.append(sched())
        sched.step()
    np.testing.assert_allclose(vals[0], 1.0)
    np.testing.assert_allclose(vals[10], 0.0, atol=1e-9)


def test_linear_warmup():
    sched = optimizer.lr.LinearWarmup(learning_rate=0.1, warmup_steps=5,
                                      start_lr=0.0, end_lr=0.1)
    vals = [sched()]
    for _ in range(6):
        sched.step()
        vals.append(sched())
    assert vals[0] == 0.0
    np.testing.assert_allclose(vals[5], 0.1, rtol=1e-6)


def test_optimizer_state_dict_roundtrip():
    net = nn.Linear(2, 2)
    opt = optimizer.Adam(learning_rate=0.1, parameters=net.parameters())
    net(paddle.randn([4, 2])).sum().backward()
    opt.step()
    sd = opt.state_dict()
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=net.parameters())
    opt2.set_state_dict(sd)
    for name in ("moment1", "moment2"):
        for pid, arr in opt._accumulators[name].items():
            np.testing.assert_array_equal(np.asarray(arr),
                                          np.asarray(opt2._accumulators[name][pid]))


def test_multi_precision_master_weights():
    p = paddle.core.tensor.Parameter(np.array([1.0], "float32"))
    p._set_data(p._data.astype("bfloat16"))
    opt = optimizer.AdamW(learning_rate=0.01, parameters=[p],
                          multi_precision=True)
    for _ in range(3):
        p.sum().backward()
        opt.step()
        opt.clear_grad()
    assert p.dtype == paddle.bfloat16
    assert id(p) in opt._master_weights
    assert opt._master_weights[id(p)].dtype == np.dtype("float32")
