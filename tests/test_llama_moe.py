"""Mixtral-style MoE Llama (routed SwiGLU experts + expert parallelism).

Reference surface: incubate/distributed/models/moe composed into the
decoder MLP — the reference trains MoE transformers through the same
machinery. Numerics here: routing/capacity on the CPU mesh, aux loss in
the LM loss, EP+TP+DP sharded steps (unrolled AND scanned), and exact
scanned-vs-unrolled parity.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.distributed.auto_parallel import (ProcessMesh, Replicate,
                                                  Shard, shard_tensor)
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.models.llama import LlamaMoEMLP


def _moe_cfg(**over):
    return llama_tiny_config(num_hidden_layers=2, hidden_size=32,
                             num_attention_heads=2, num_key_value_heads=2,
                             vocab_size=64, max_position_embeddings=32,
                             num_experts=4, moe_top_k=2,
                             moe_capacity_factor=4.0, **over)


def test_moe_llama_forward_and_aux_loss():
    paddle.seed(0)
    cfg = _moe_cfg()
    m = LlamaForCausalLM(cfg)
    assert isinstance(m.model.layers[0].mlp, LlamaMoEMLP)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 64, (2, 16)))
    logits, loss = m(ids, labels=ids)
    assert list(logits.shape) == [2, 16, 64]
    assert np.isfinite(float(loss))
    # the gshard gate produced a load-balancing aux loss on every layer
    for layer in m.model.layers:
        assert layer.mlp.l_aux is not None
        assert np.isfinite(float(layer.mlp.l_aux))
    # aux loss really lands in the LM loss
    base = float(loss)
    cfg.moe_aux_coeff = 0.0
    _, loss0 = m(ids, labels=ids)
    assert base != float(loss0)


def test_moe_llama_trains():
    paddle.seed(1)
    cfg = _moe_cfg()
    m = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=3e-3, parameters=m.parameters())
    rng = np.random.RandomState(1)
    ids = paddle.to_tensor(rng.randint(0, 64, (2, 16)))
    losses = []
    for _ in range(4):
        _, loss = m(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # expert weights received gradients through the routed path
    _, loss = m(ids, labels=ids)
    loss.backward()
    gw = m.model.layers[0].mlp.moe.gate_w.grad
    assert gw is not None and bool(np.isfinite(gw.numpy()).all())
    assert float(np.abs(gw.numpy()).max()) > 0


def test_moe_llama_ep_tp_dp_sharded_step():
    """One fwd+bwd with experts over ep, TP over mp, batch over dp."""
    from paddle_tpu.models import shard_llama
    paddle.seed(2)
    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["dp", "ep", "mp"])
    cfg = _moe_cfg()
    cfg.ep_mesh = mesh
    cfg.ep_axis = "ep"
    m = LlamaForCausalLM(cfg)
    shard_llama(m, mesh, mp_axis="mp", batch_axes=("dp",), ep_axis="ep")
    ids = shard_tensor(
        paddle.to_tensor(np.random.RandomState(2).randint(0, 64, (4, 16))),
        mesh, [Shard(0), Replicate(), Replicate()])
    logits, loss = m(ids, labels=ids)
    loss.backward()
    assert np.isfinite(float(loss))
    gw = m.model.layers[0].mlp.moe.down_w.grad
    assert gw is not None and bool(np.isfinite(gw.numpy()).all())


def test_moe_scanned_ep_tp_sharded_step():
    """Scanned MoE under the same mesh: stacked [L, E, ...] expert banks
    Shard(1) over ep + TP over mp compile and step on the CPU mesh."""
    from paddle_tpu.models import shard_llama
    paddle.seed(5)
    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["dp", "ep", "mp"])
    cfg = _moe_cfg(scan_layers=True)
    m = LlamaForCausalLM(cfg)
    shard_llama(m, mesh, mp_axis="mp", batch_axes=("dp",), ep_axis="ep")
    ids = shard_tensor(
        paddle.to_tensor(np.random.RandomState(5).randint(0, 64, (4, 16))),
        mesh, [Shard(0), Replicate(), Replicate()])
    logits, loss = m(ids, labels=ids)
    loss.backward()
    assert np.isfinite(float(loss))
    gw = m.model.layers_scanned.moe_down_w.grad
    assert gw is not None and bool(np.isfinite(gw.numpy()).all())


from tests.helpers.llama_weights import \
    copy_unrolled_to_scanned as _copy_moe_unrolled_to_scanned  # noqa: E402


def test_moe_scanned_matches_unrolled():
    """scan_layers + MoE: the scanned routed-expert body (pure-jnp gshard
    gate + capacity masks) must reproduce the unrolled _LlamaExpertBank
    numerics exactly, aux loss included."""
    paddle.seed(0)
    m_u = LlamaForCausalLM(_moe_cfg())
    m_s = LlamaForCausalLM(_moe_cfg(scan_layers=True))
    assert m_u.num_params() == m_s.num_params()
    _copy_moe_unrolled_to_scanned(m_u, m_s)
    ids = paddle.to_tensor(np.random.RandomState(3).randint(0, 64, (2, 16)))
    m_u.eval()
    m_s.eval()
    with paddle.no_grad():
        lu, loss_u = m_u(ids, labels=ids)
        ls, loss_s = m_s(ids, labels=ids)
    np.testing.assert_allclose(np.asarray(lu._data), np.asarray(ls._data),
                               atol=1e-4)
    assert abs(float(loss_u) - float(loss_s)) < 1e-4
    # aux landed in both paths
    aux_u = sum(float(l.mlp.l_aux) for l in m_u.model.layers)
    aux_s = float(m_s.model.layers_scanned.l_aux)
    assert abs(aux_u - aux_s) < 1e-4


def test_moe_scanned_trains():
    from paddle_tpu import jit
    paddle.seed(2)
    m = LlamaForCausalLM(_moe_cfg(scan_layers=True))
    opt = optimizer.AdamW(learning_rate=3e-3, parameters=m.parameters())
    step = jit.TrainStep(lambda i, l: m(i, labels=l)[1], opt)
    rng = np.random.RandomState(2)
    ids = paddle.to_tensor(rng.randint(0, 64, (2, 16)))
    losses = [float(step(ids, ids)._data) for _ in range(4)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_routing_covers_experts():
    """Top-2 routing over random tokens should touch most experts (the
    aux loss pushes balance; here just sanity that dispatch isn't
    degenerate to one expert)."""
    paddle.seed(3)
    cfg = _moe_cfg()
    m = LlamaForCausalLM(cfg)
    mlp = m.model.layers[0].mlp
    x = paddle.to_tensor(
        np.random.RandomState(3).randn(64, cfg.hidden_size)
        .astype("float32"))
    out = mlp(x)
    assert list(out.shape) == [64, cfg.hidden_size]
    topv, topi = mlp.moe.gate(x)
    used = set(np.asarray(topi._data).ravel().tolist())
    assert len(used) >= 2


def test_moe_sharded_checkpoint_roundtrip(tmp_path):
    """EP-sharded expert weights survive distributed save/load, including
    a reshard-on-load to a different mesh layout."""
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    paddle.seed(4)
    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["dp", "ep", "mp"])
    cfg = _moe_cfg()
    cfg.ep_mesh = mesh
    cfg.ep_axis = "ep"
    src = LlamaForCausalLM(cfg)
    sd = {n: p for n, p in src.named_parameters()}
    save_state_dict(sd, str(tmp_path))

    # reload into a model on a DIFFERENT mesh factorization
    paddle.seed(5)
    mesh2 = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["ep", "mp"])
    cfg2 = _moe_cfg()
    cfg2.ep_mesh = mesh2
    cfg2.ep_axis = "ep"
    dst = LlamaForCausalLM(cfg2)
    target = {n: p for n, p in dst.named_parameters()}
    load_state_dict(target, str(tmp_path))
    for n, p in src.named_parameters():
        np.testing.assert_allclose(target[n].numpy(), p.numpy(),
                                   err_msg=n)
