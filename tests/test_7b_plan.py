"""Llama-2-7B flagship memory plan (VERDICT r3 #4).

Pins the round-4 deliverable: the FULL 7B sharded train step (fwd + bwd +
AdamW, bf16 compute / fp32 master) AOT-compiles for a 16-chip v5e-16
topology and the ZeRO-3 + full-remat variant fits under 16 GiB/chip at
global batch 16 x seq 2048 — per XLA's own buffer-assignment numbers, no
parameter ever materialized. The scaled-down same-structure step executes
a real training step on the 8-device mesh (loss decreases).

Reference: BASELINE.md config 3 (the north-star scale);
fleet/meta_parallel/sharding/group_sharded_stage2.py:46 /
group_sharded_stage3.py:85.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tools", "plan_7b.py")
PLAN = os.path.join(REPO, "PLAN_7B.json")

pytestmark = pytest.mark.slow


def test_7b_s3_full_compiles_and_fits_v5e16():
    out = subprocess.run(
        [sys.executable, SCRIPT, "--variants", "s3_full", "--execute"],
        cwd=REPO, capture_output=True, text=True, timeout=1700)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    rec = json.load(open(PLAN))
    v = {x["name"]: x for x in rec["variants"]}
    assert "s3_full" in v
    s3f = v["s3_full"]
    assert s3f["batch"] == 16 and s3f["seq"] == 2048
    # ~6.6B params (untied lm head + MHA 7B dims)
    assert s3f["n_params"] > 6.5e9
    assert s3f["fits_v5e_16gib"] is True, s3f
    assert s3f["per_chip_live_gib"] <= 16.0
    # the scaled-down same-structure step really trained
    ex = rec["scaled_execute"]
    assert ex["ok"] is True, ex


def test_plan_slice_7b_record_is_coherent():
    """VERDICT r4 #2: PLAN_7B.json must carry MEASURED per-layer numbers
    from tools/slice_7b.py — true-7B-dimension layers executed through
    the full sharded s3_full step, and an AOT linear-in-L memory fit
    whose 32-layer extrapolation agrees with the recorded full compile."""
    if not os.path.exists(PLAN):
        pytest.skip("PLAN_7B.json not generated yet")
    rec = json.load(open(PLAN))
    if "slice_7b" not in rec:
        pytest.skip("slice_7b not recorded in this report")
    s = rec["slice_7b"]
    assert s["ok"] is True, s
    # both slices executed a real step with decreasing finite loss
    by_l = {e["L"]: e for e in s["executed"]}
    assert by_l[1]["ok"] and by_l[2]["ok"]
    assert s["per_layer_step_s"] > 0
    # the linear-in-L memory fit must reproduce the recorded 32L compile
    # within 5% — this is the evidence that buffer assignment scales the
    # way the plan assumes
    assert s["recorded_full_32L_live_gib"] is not None
    err = abs(s["linear_extrapolation_error_gib"])
    assert err / s["recorded_full_32L_live_gib"] < 0.05, s
    # fit depths exclude L=1 (non-monotone buffer assignment at trivial
    # scan depth — see tools/slice_7b.py)
    assert min(m["L"] for m in s["aot_memory_batch16_seq2048"]) >= 2


def test_plan_json_carries_all_variants_when_present():
    """After a full `python tools/plan_7b.py` run the report quantifies
    stage-2 honestly: replicated 7B bf16 weights cannot fit a 16 GiB
    chip (the reference runs stage-2 on 80 GB GPUs — BASELINE.md's 'or
    stage3' exists for exactly this)."""
    if not os.path.exists(PLAN):
        pytest.skip("PLAN_7B.json not generated yet")
    rec = json.load(open(PLAN))
    v = {x["name"]: x for x in rec["variants"]}
    if "s2" not in v:
        pytest.skip("s2 variant not in this report")
    assert v["s2"]["fits_v5e_16gib"] is False
    assert v["s2"]["per_chip_live_gib"] > 16.0
