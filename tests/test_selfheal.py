"""Self-healing fleet drills: the telemetry -> remediation loop
(paddle_tpu.resilience.remediator + gateway.autoscaler) under the
deterministic traffic harness (benchmarks/traffic.py).

The acceptance bars:
  * a chaos straggler delay on ONE replica makes the remediator NAME
    and drain exactly that replica (token-exact requeue: every request
    still completes), and TTFT returns in-SLO within a bounded number
    of steps after the drain;
  * the identical schedule with NO fault executes ZERO actions (the
    loop is quiet on a healthy fleet);
  * hysteresis means K CONSECUTIVE firings — one isolated spike never
    drains anything;
  * the per-(action, target) cooldown forbids drain -> drain churn on
    one replica, and the global flap guard escalates (freeze doubling)
    instead of oscillating under a persistent fault;
  * the autoscaler rides the existing drain/remove lifecycle: scale-up
    under queue pressure, scale-down drains (not kills) its own
    addition once idle.

Everything is single-threaded and deterministic; chaos delays are the
only wall-clock dependence.
"""
import os
import sys
import types

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.gateway import Autoscaler, Gateway
from paddle_tpu.inference.serving import ContinuousBatcher
from paddle_tpu.observability.anomaly import AnomalyDetector, GatewayProbe
from paddle_tpu.observability.fleet import FleetFinding
from paddle_tpu.resilience import arm_scenario, disarm
from paddle_tpu.resilience.remediator import (AutoRemediator, FlapGuard,
                                              PolicyRule,
                                              remediate_enabled)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))
import traffic  # noqa: E402

pytestmark = pytest.mark.selfheal

# separation: honest prefill-heavy steps run 2-4x the decode-step
# median (robust z up to ~10 on these tiny models), so the detector
# threshold sits above that and the injected delay far above it; the
# TTFT SLO is one honest traffic meets and the straggler breaks
TTFT_SLO_S = 0.15
STRAGGLE_S = 0.4


@pytest.fixture(autouse=True)
def _disarm():
    disarm()
    yield
    disarm()


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _factory(lm):
    # batch headroom matters for the drill: after the straggler drains,
    # ONE survivor must absorb the requeued load with slack (throughput
    # 8 slots / ~7 steps-per-request >> 0.5 arrivals/step), else queue
    # wait alone breaches the TTFT SLO forever
    def make(name):
        return ContinuousBatcher(lm, max_batch=8, s_max=96,
                                 compile=False)
    return make


def _spec(**kw):
    kw.setdefault("seed", 5)
    kw.setdefault("steps", 30)
    kw.setdefault("vocab", 128)
    # light enough that ONE replica sustains it in-SLO (post-drain the
    # drill must recover, not drown the survivor in queueing TTFT) but
    # with requests long enough that a loaded replica stays busy on
    # CONSECUTIVE ticks — sparse one-shot work can never meet hysteresis
    kw.setdefault("base_rate", 0.5)
    kw.setdefault("prompt_lo", 6)
    kw.setdefault("prompt_hi", 16)
    kw.setdefault("new_lo", 5)
    kw.setdefault("new_hi", 8)
    kw.setdefault("shared_len", 12)
    return traffic.TrafficSpec(**kw)


def _rig(lm, policy):
    """Gateway + probe/detector/remediator, baselines warmed on healthy
    steps (chaos arms AFTER this returns)."""
    make = _factory(lm)
    gw = Gateway(policy="least_loaded", max_queue_depth=128)
    gw.add_replica("r0", make("r0"))
    gw.add_replica("r1", make("r1"))
    detector = AnomalyDetector(threshold=15.0, min_samples=8)
    probe = GatewayProbe(gw, detector)
    rem = AutoRemediator(gw, detector=detector, policy=policy,
                         replica_factory=make,
                         flap_guard=FlapGuard(max_actions=4,
                                              window_s=30.0))
    rng = np.random.RandomState(7)
    # warm EVERY prompt rung the traffic will hit (pow2 buckets): a
    # first-touch prefill compile mid-run would register as a huge step
    # and fire a false per-replica spike. Loop until BOTH replicas'
    # detector series are past warmup — routing does not split work
    # evenly on small batches.
    for _ in range(8):
        for n in (6, 10, 20, 28):
            gw.submit(rng.randint(0, 128, (n,)), 4, tenant="warmup")
        gw.run_until_done()
        if all((t := detector._tracks.get(("tpot", r))) is not None
               and t.count >= detector.min_samples + 2
               for r in ("r0", "r1")):
            break
    gw.reset_stats()
    return gw, rem, probe


DRAIN_POLICY = (PolicyRule("tpot_spike", "drain_replica", hysteresis=2,
                           cooldown_s=30.0),)


# -- the chaos drill ----------------------------------------------------------

def test_straggler_drill_names_and_drains_the_right_replica(lm):
    """One replica goes slow; the loop drains THAT replica and TTFT
    returns in-SLO within a bounded number of steps of the action."""
    gw, rem, probe = _rig(lm, DRAIN_POLICY)
    arm_scenario(f"seed=0; gateway.step.r1:delay:"
                 f"delay_s={STRAGGLE_S},after=1,count=10000")
    drain_step = []

    def tick(step):
        for act in rem.tick():
            if act.executed and not drain_step:
                drain_step.append(step)
    try:
        res = traffic.drive(gw, traffic.generate(_spec()), TTFT_SLO_S,
                            tick=tick)
    finally:
        disarm()
        probe.close()

    executed = rem.executed()
    assert executed, "remediator never acted on the straggler"
    assert all(a.kind == "drain_replica" and a.target == "r1"
               for a in executed), \
        f"wrong action(s): {[(a.kind, a.target) for a in executed]}"
    assert len(executed) == 1          # once — no churn on one fault
    # the drained replica left the routable set but was NOT killed
    rep = gw.pool.get("r1")
    assert rep.alive and not rep.routable()
    # token-exactness: drive() raises on any lost/duplicated token
    # through the drain requeue, so completing the schedule IS the
    # proof; nothing may be lost outright either
    assert res.failed == 0 and res.completions == res.submitted
    # recovery: once the straggler is out, completions return in-SLO
    # within a bounded window (delayed stragglers already in flight
    # still finish late — allow them to clear)
    assert res.first_breach_step is not None
    assert drain_step, "no executed action step recorded"
    assert res.last_breach_step <= drain_step[0] + 25, (
        f"TTFT never recovered: drained at step {drain_step[0]}, "
        f"last breach at {res.last_breach_step}")


def test_no_fault_control_run_takes_zero_actions(lm):
    """The IDENTICAL schedule with no chaos: a quiet loop."""
    gw, rem, probe = _rig(lm, DRAIN_POLICY)
    try:
        res = traffic.drive(gw, traffic.generate(_spec()), TTFT_SLO_S,
                            tick=lambda s: rem.tick())
    finally:
        probe.close()
    assert rem.executed() == []
    assert res.failed == 0 and res.completions == res.submitted
    assert len(gw.pool.routable()) == 2


# -- gating: hysteresis, cooldown, flap guard ---------------------------------

def _stub_detector():
    return types.SimpleNamespace(findings=[])


def _spike(seq, key="r1"):
    return FleetFinding(kind="tpot_spike", op="tpot", seq=seq,
                        detail={"key": key, "score": 9.9})


def _bare_gateway(lm):
    make = _factory(lm)
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", make("r0"))
    gw.add_replica("r1", make("r1"))
    return gw, make


def test_single_spike_below_hysteresis_never_acts(lm):
    gw, make = _bare_gateway(lm)
    det = _stub_detector()
    rem = AutoRemediator(gw, detector=det, policy=DRAIN_POLICY,
                         replica_factory=make, clock=lambda: 0.0)
    det.findings.append(_spike(1))
    assert rem.tick(now=0.0) == []          # streak 1 < hysteresis 2
    rem.tick(now=1.0)                       # quiet tick resets streak
    det.findings.append(_spike(2))
    assert rem.tick(now=2.0) == []          # streak back to 1
    assert rem.executed() == []
    assert gw.pool.get("r1").routable


def test_consecutive_spikes_drain_then_cooldown_suppresses_churn(lm):
    gw, make = _bare_gateway(lm)
    det = _stub_detector()
    rem = AutoRemediator(gw, detector=det, policy=DRAIN_POLICY,
                         replica_factory=make, clock=lambda: 0.0)
    det.findings.append(_spike(1))
    rem.tick(now=0.0)
    det.findings.append(_spike(2))
    acts = rem.tick(now=1.0)
    assert [a.decision for a in acts] == ["executed"]
    assert acts[0].target == "r1"
    assert not gw.pool.get("r1").routable()
    # the same signal keeps firing inside the 30s cooldown: decided
    # but suppressed — the replica is never drained twice
    for t in (2.0, 3.0):
        det.findings.append(_spike(10 + int(t)))
        det.findings.append(_spike(11 + int(t)))
        for a in rem.tick(now=t):
            assert a.decision == "cooldown"
    assert len(rem.executed()) == 1


def test_last_routable_replica_is_never_drained(lm):
    gw, make = _bare_gateway(lm)
    det = _stub_detector()
    rem = AutoRemediator(gw, detector=det, policy=DRAIN_POLICY,
                         replica_factory=make, clock=lambda: 0.0)
    gw.drain_replica("r0")                  # only r1 left routable
    det.findings.append(_spike(1))
    rem.tick(now=0.0)
    det.findings.append(_spike(2))
    acts = rem.tick(now=1.0)
    assert [a.decision for a in acts] == ["last_replica"]
    assert gw.pool.get("r1").routable()


def test_flap_guard_escalates_instead_of_oscillating():
    t = [0.0]
    g = FlapGuard(max_actions=2, window_s=10.0, freeze_s=20.0,
                  clock=lambda: t[0])
    assert g.check()[0]
    g.record()
    t[0] = 1.0
    assert g.check()[0]
    g.record()
    t[0] = 2.0
    ok, why = g.check()
    assert (ok, why) == (False, "flap_budget")     # budget spent
    assert g.frozen_until == pytest.approx(22.0)   # frozen 20s
    t[0] = 10.0
    assert g.check() == (False, "flap_frozen")
    # past the freeze AND the window pruned the old actions: allowed
    # (but NOT calm yet — frozen time does not count toward re-arming)
    t[0] = 23.0
    assert g.check()[0]
    # a second breach before a full calm window doubles the freeze
    g.record()
    t[0] = 23.5
    g.record()
    t[0] = 24.0
    ok, why = g.check()
    assert (ok, why) == (False, "flap_budget")
    assert g.escalations == 2
    assert g.frozen_until == pytest.approx(24.0 + 40.0)  # 20 * 2


def test_remediator_freezes_under_oscillating_fault(lm):
    """A fault that keeps re-firing across targets hits the flap budget
    and the remediator FREEZES (escalate-don't-oscillate) rather than
    draining/restoring forever."""
    gw, make = _bare_gateway(lm)
    for n in ("r2", "r3", "r4"):
        gw.add_replica(n, make(n))
    det = _stub_detector()
    policy = (PolicyRule("tpot_spike", "drain_replica", hysteresis=1,
                         cooldown_s=0.5),)
    guard = FlapGuard(max_actions=2, window_s=60.0, freeze_s=120.0,
                      clock=lambda: 0.0)
    rem = AutoRemediator(gw, detector=det, policy=policy,
                         replica_factory=make, flap_guard=guard,
                         clock=lambda: 0.0)
    seq = [0]

    def fire(key, now):
        seq[0] += 1
        det.findings.append(_spike(seq[0], key=key))
        return rem.tick(now=now)

    assert fire("r0", 0.0)[0].executed
    assert fire("r1", 1.0)[0].executed
    # budget (2 per window) spent: every further proposal is rejected,
    # the guard freezes, and NOTHING else is drained
    decisions = [a.decision for now, key in ((2.0, "r2"), (3.0, "r3"))
                 for a in fire(key, now)]
    assert decisions and all(d in ("flap_budget", "flap_frozen")
                             for d in decisions)
    assert len(rem.executed()) == 2
    assert len(gw.pool.routable()) == 3
    assert rem.summary()["flap_escalations"] >= 1


# -- autoscaler lifecycle -----------------------------------------------------

def test_autoscaler_scales_up_under_queue_pressure_and_drains_back(lm):
    gw, make = _bare_gateway(lm)
    t = [0.0]
    asc = Autoscaler(gw, make, min_replicas=2, max_replicas=3,
                     queue_high=4, queue_low=0, hysteresis=2,
                     cooldown_s=1.0, clock=lambda: t[0])
    rng = np.random.RandomState(3)
    for _ in range(12):
        gw.submit(rng.randint(0, 128, (8,)), 4)
    assert asc.tick() is None               # streak 1
    t[0] = 2.0
    assert asc.tick() == "scale_up:auto0"   # streak 2 -> add
    assert "auto0" in gw.pool
    gw.run_until_done()
    # idle now: two consecutive low-pressure ticks past cooldown drain
    # the addition back out through the normal lifecycle
    t[0] = 4.0
    assert asc.tick() is None
    t[0] = 6.0
    assert asc.tick() == "scale_down:auto0"
    gw.run_until_done()
    t[0] = 8.0
    asc.tick()                              # _finalize removes it
    assert "auto0" not in gw.pool
    assert len(gw.pool.routable()) == 2


def test_remediate_env_gate(monkeypatch):
    monkeypatch.setenv("PADDLE_REMEDIATE", "0")
    assert not remediate_enabled()
    monkeypatch.setenv("PADDLE_REMEDIATE", "dry")
    assert remediate_enabled()
    monkeypatch.delenv("PADDLE_REMEDIATE")
    assert remediate_enabled()


def test_dry_run_journals_but_never_touches_the_pool(lm):
    gw, make = _bare_gateway(lm)
    det = _stub_detector()
    rem = AutoRemediator(gw, detector=det, policy=DRAIN_POLICY,
                         replica_factory=make, dry_run=True,
                         clock=lambda: 0.0)
    det.findings.append(_spike(1))
    rem.tick(now=0.0)
    det.findings.append(_spike(2))
    acts = rem.tick(now=1.0)
    assert [a.decision for a in acts] == ["dry_run"]
    assert gw.pool.get("r1").routable()
    assert rem.executed() == []
