"""Elastic mesh-sharded checkpointing (resilience.sharded_checkpoint).

The acceptance bars:
  * two-phase commit: per-rank shard chunks + CRC + ``SHARD_OK`` acks
    (phase 1), rank 0's MANIFEST.json and COMMITTED only after every
    ack arrived (phase 2) — a crash anywhere before the marker leaves
    the step torn, never half-published;
  * elastic restore: state saved on a 2x2 ``(fsdp, tensor)`` mesh
    restores onto 1x4, 4x1, and a single device, and the CONTINUED
    loss trajectory is bitwise-identical to uninterrupted training;
  * every discarded step on the restore path is a typed
    ``CheckpointFinding`` (torn_step / missing_ack / uncommitted /
    checksum_mismatch), never a silent fallback;
  * ``tools/ckpt_inspect.py`` reaches the same verdicts offline.

The process-spanning variant (2 real processes, rank 1 chaos-killed
mid-shard-write) lives in tests/test_mesh.py.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.distributed.mesh import MeshRuntime
from paddle_tpu.hapi import Model
from paddle_tpu.resilience import (AckTimeout, ShardedCheckpointManager,
                                   TornWrite, arm_scenario, disarm,
                                   validate_sharded_checkpoint)

pytestmark = pytest.mark.ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state(fill):
    return {"w": paddle.to_tensor(
        np.full((4, 6), fill, np.float32)),
        "nested": {"b": paddle.to_tensor(
            np.arange(8, dtype=np.float32))},
        "meta": {"epoch": int(fill), "note": "drill"}}


def _zeros():
    # restore fills the leaves the target declares, so the placeholder
    # dict mirrors the saved structure
    return {"w": paddle.to_tensor(np.zeros((4, 6), np.float32)),
            "nested": {"b": paddle.to_tensor(
                np.zeros(8, np.float32))},
            "meta": {"epoch": -1, "note": ""}}


def _step_dir(root, step):
    return os.path.join(str(root), f"step_{step:012d}")


# -- two-phase layout ---------------------------------------------------------

def test_two_phase_layout_and_roundtrip(tmp_path):
    mgr = ShardedCheckpointManager(str(tmp_path), ack_timeout=5)
    src = _state(3.0)
    mgr.save(src, step=7)
    d = _step_dir(tmp_path, 7)
    names = sorted(os.listdir(d))
    assert "MANIFEST.json" in names and "COMMITTED" in names
    assert "SHARD_OK.rank00000" in names
    assert any(n.startswith("shard-rank00000-") for n in names)
    man = json.load(open(os.path.join(d, "MANIFEST.json")))
    assert man["step"] == 7 and man["world_size"] == 1
    assert set(man["tensors"]) == {"w", "nested.b"}
    for entry in man["tensors"].values():
        for ch in entry["chunks"]:
            assert {"file", "cid", "offset", "shape", "crc"} <= set(ch)
    assert man["extra"]["meta.epoch"] == 3
    ok, reason = validate_sharded_checkpoint(d)
    assert ok, reason

    target = _zeros()
    mgr2 = ShardedCheckpointManager(str(tmp_path))
    assert mgr2.restore_latest(target) == 7
    np.testing.assert_array_equal(target["w"].numpy(), src["w"].numpy())
    np.testing.assert_array_equal(target["nested"]["b"].numpy(),
                                  src["nested"]["b"].numpy())
    assert target["meta"]["epoch"] == 3 and target["meta"]["note"] == "drill"
    assert mgr2.findings == []


def test_async_save_publishes_and_wait_reraises(tmp_path):
    mgr = ShardedCheckpointManager(str(tmp_path), ack_timeout=5)
    mgr.save(_state(1.0), step=1, blocking=False)
    mgr.wait()
    ok, reason = mgr.validate(1)
    assert ok, reason
    arm_scenario("seed=0; checkpoint.publish:torn_write:offset=16,count=1")
    mgr.save(_state(2.0), step=2, blocking=False)
    with pytest.raises(TornWrite):
        mgr.wait()
    disarm()
    assert mgr.latest_step() == 1 or not os.path.exists(
        os.path.join(_step_dir(tmp_path, 2), "COMMITTED"))


def test_ack_timeout_leaves_step_torn(tmp_path):
    """Rank 0 of a declared 2-rank world never sees rank 1's ack: the
    save must abort typed (AckTimeout) without publishing, and the next
    restore must fall back over the torn step with a finding."""
    good = ShardedCheckpointManager(str(tmp_path), ack_timeout=5)
    good.save(_state(1.0), step=1)
    mgr = ShardedCheckpointManager(str(tmp_path), rank=0, world_size=2,
                                   ack_timeout=0.3, poll_interval=0.02)
    with pytest.raises(AckTimeout):
        mgr.save(_state(2.0), step=2)
    assert not os.path.exists(os.path.join(_step_dir(tmp_path, 2),
                                           "COMMITTED"))
    target = _zeros()
    back = ShardedCheckpointManager(str(tmp_path))
    assert back.restore_latest(target) == 1
    kinds = [f.kind for f in back.findings]
    assert kinds and kinds[0] in ("missing_ack", "torn_step"), kinds


# -- chaos drills over the seams ---------------------------------------------

def test_torn_shard_write_classified_torn_step(tmp_path):
    mgr = ShardedCheckpointManager(str(tmp_path), ack_timeout=5)
    mgr.save(_state(1.0), step=1)
    arm_scenario("seed=0; checkpoint.shard_write:torn_write:offset=8,"
                 "count=1")
    with pytest.raises(TornWrite):
        mgr.save(_state(2.0), step=2)
    disarm()
    ok, reason = validate_sharded_checkpoint(_step_dir(tmp_path, 2))
    assert not ok and "torn" in reason, reason
    target = _zeros()
    back = ShardedCheckpointManager(str(tmp_path))
    assert back.restore_latest(target) == 1
    assert [f.kind for f in back.findings] == ["torn_step"]
    np.testing.assert_array_equal(target["w"].numpy(),
                                  np.full((4, 6), 1.0, np.float32))


def test_fallback_chain_emits_one_typed_finding_per_bad_step(tmp_path):
    mgr = ShardedCheckpointManager(str(tmp_path), keep_last=10,
                                   ack_timeout=5)
    mgr.save(_state(1.0), step=10)
    for s in (20, 30, 40):
        mgr.save(_state(float(s)), step=s)
    # step 20: strip manifest AND marker -> torn (shards but no publish)
    os.remove(os.path.join(_step_dir(tmp_path, 20), "MANIFEST.json"))
    os.remove(os.path.join(_step_dir(tmp_path, 20), "COMMITTED"))
    # step 30: delete the ack a committed manifest references
    os.remove(os.path.join(_step_dir(tmp_path, 30), "SHARD_OK.rank00000"))
    # step 40: flip a byte inside the shard payload -> checksum/unreadable
    d40 = _step_dir(tmp_path, 40)
    shard = [n for n in os.listdir(d40) if n.startswith("shard-")][0]
    p = os.path.join(d40, shard)
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) - 8] ^= 0xFF
    open(p, "wb").write(bytes(raw))

    target = _zeros()
    back = ShardedCheckpointManager(str(tmp_path))
    assert back.restore_latest(target) == 10
    np.testing.assert_array_equal(target["w"].numpy(),
                                  np.full((4, 6), 1.0, np.float32))
    assert [f.step for f in back.findings] == [40, 30, 20]
    kinds = [f.kind for f in back.findings]
    assert kinds[0] in ("checksum_mismatch", "unreadable", "missing_shard")
    assert kinds[1] == "missing_ack"
    assert kinds[2] == "torn_step"


def test_ckpt_inspect_cli_agrees_with_restore(tmp_path):
    mgr = ShardedCheckpointManager(str(tmp_path), ack_timeout=5)
    mgr.save(_state(1.0), step=1)
    arm_scenario("seed=0; checkpoint.publish:torn_write:offset=16,count=1")
    with pytest.raises(TornWrite):
        mgr.save(_state(2.0), step=2)
    disarm()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
         str(tmp_path), "--json"], capture_output=True, text=True,
        timeout=60, cwd=REPO)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["latest_sound"] == 1
    bad = [s for s in report["steps"] if not s["ok"]]
    assert len(bad) == 1 and "COMMITTED" in bad[0]["reason"] \
        or (bad and "torn" in bad[0]["reason"]), report


def test_postmortem_names_rank_dead_mid_checkpoint_save(tmp_path):
    """A rank whose last ring entry is an unacked ckpt.save_begin died
    inside the two-phase save window; build_postmortem must call it a
    suspect death with the step, while a rank that acked (or aborted on
    ack_timeout) walks free."""
    from paddle_tpu.observability.flight import FlightRecorder, \
        build_postmortem
    r0 = FlightRecorder(str(tmp_path / "flight-rank00000.ring"),
                        slots=8, slot_size=256, rank=0)
    r0.record("ckpt.save_begin", step=4, rank=0)
    r0.record("ckpt.shard_ack", step=4, rank=0)
    r0.record("ckpt.ack_timeout", step=4, waited=["rank00001"])
    r0.close()
    r1 = FlightRecorder(str(tmp_path / "flight-rank00001.ring"),
                        slots=8, slot_size=256, rank=1)
    r1.record("ckpt.save_begin", step=4, rank=1)
    r1.close()  # chaos kill between shard write and ack
    pm = build_postmortem(str(tmp_path))
    assert pm["ranks"]["0"]["suspect_death"] is None
    assert pm["ranks"]["0"]["open_checkpoints"] == []
    v = pm["ranks"]["1"]["suspect_death"]
    assert v is not None and v["kind"] == "ckpt.save_begin" \
        and v["step"] == 4
    assert pm["ranks"]["1"]["open_checkpoints"] == [4]


# -- dtype fidelity -----------------------------------------------------------

def test_bf16_raw_bit_roundtrip(tmp_path):
    import jax.numpy as jnp
    src = {"h": paddle.to_tensor(
        jnp.asarray(np.linspace(-3, 3, 16, dtype=np.float32),
                    jnp.bfloat16))}
    mgr = ShardedCheckpointManager(str(tmp_path), ack_timeout=5)
    mgr.save(src, step=1)
    target = {"h": paddle.to_tensor(jnp.zeros(16, jnp.bfloat16))}
    back = ShardedCheckpointManager(str(tmp_path))
    assert back.restore_latest(target) == 1
    assert target["h"].numpy().dtype == src["h"].numpy().dtype
    assert bytes(target["h"].numpy().tobytes()) == \
        bytes(src["h"].numpy().tobytes())


# -- elastic rescale-on-restore ----------------------------------------------

def _build_model(plan):
    paddle.seed(11)
    m = Model(nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2)))
    m.prepare(optimizer=optim.AdamW(learning_rate=1e-2,
                                    parameters=m.parameters()),
              loss=nn.CrossEntropyLoss(), jit=True, plan=plan)
    return m


def _train(m, n):
    rng = np.random.RandomState(2)
    x = rng.randn(4, 8).astype(np.float32)
    y = rng.randint(0, 2, size=(4,)).astype(np.int64)
    return [float(np.asarray(m.train_batch([x], [y])[0]))
            for _ in range(n)]


@pytest.fixture(scope="module")
def rescale_run(tmp_path_factory):
    """One 2x2 reference trajectory + a committed mid-run checkpoint,
    shared by every rescale target."""
    rt = MeshRuntime({"data": 1, "fsdp": 2, "tensor": 2})
    full = _train(_build_model(rt.train_plan(budget_gib=16.0)), 6)
    root = str(tmp_path_factory.mktemp("rescale") / "ckpt")
    m = _build_model(rt.train_plan(budget_gib=16.0))
    first = _train(m, 3)
    m.save_checkpoint(
        ShardedCheckpointManager(root, runtime=rt, ack_timeout=5), step=3)
    return {"root": root, "full": full, "first": first}


@pytest.mark.parametrize("axes", [
    {"data": 1, "fsdp": 1, "tensor": 4},
    {"data": 1, "fsdp": 4, "tensor": 1},
    None,
])
def test_rescale_restore_continues_bitwise(rescale_run, axes):
    """Save on 2x2 (fsdp, tensor), restore on a DIFFERENT world, keep
    training: the combined trajectory must equal uninterrupted training
    bit for bit. This is the elastic contract — mesh shape is a
    placement choice, the checkpoint pins the math."""
    if axes is None:
        plan, rt = None, None
    else:
        rt = MeshRuntime(axes)
        plan = rt.train_plan(budget_gib=16.0)
    m = _build_model(plan)
    mgr = ShardedCheckpointManager(rescale_run["root"])
    assert m.resume_from(mgr, runtime=rt) == 3
    rest = _train(m, 3)
    assert rescale_run["first"] + rest == rescale_run["full"], (
        f"resumed-on-{axes} trajectory diverged:\n"
        f"  uninterrupted: {rescale_run['full']}\n"
        f"  resumed:       {rescale_run['first'] + rest}")


def test_step_guard_rolls_back_past_torn_async_save(tmp_path):
    """The async-window fault story: a background save tears at
    publish, divergence strikes, and the StepGuard rollback must land
    on the previous COMMITTED step — the torn step is skipped with a
    typed finding, never half-restored."""
    m = _build_model(None)
    mgr = ShardedCheckpointManager(str(tmp_path / "g"), ack_timeout=5)
    guard = m.enable_step_guard(rollback_after=2, checkpoint_manager=mgr)
    rng = np.random.RandomState(3)
    x = rng.randn(4, 8).astype(np.float32)
    y = rng.randint(0, 2, size=(4,)).astype(np.int64)
    m.train_batch([x], [y])
    m.save_checkpoint(mgr, step=1)
    golden = {k: v.numpy().copy()
              for k, v in m.network.state_dict().items()}
    m.train_batch([x], [y])  # drift past the committed step
    arm_scenario("seed=0; checkpoint.publish:torn_write:offset=16,count=1")
    try:
        m.save_checkpoint(mgr, step=2, blocking=False)
        with pytest.raises(TornWrite):
            mgr.wait()
    finally:
        disarm()
    arm_scenario("seed=0; train.step:nan_grad:count=2")
    m.train_batch([x], [y])
    m.train_batch([x], [y])
    disarm()
    assert guard.rollbacks == 1
    now = {k: v.numpy() for k, v in m.network.state_dict().items()}
    for k in golden:
        np.testing.assert_array_equal(now[k], golden[k])
    assert any(f.step == 2 and f.kind in ("torn_step", "uncommitted")
               for f in mgr.findings), [f.to_dict() for f in mgr.findings]


def test_fit_auto_resume_is_bitwise(tmp_path):
    """Model.fit(checkpoint=...) end to end, single device: train 2
    epochs with periodic saves, rebuild, fit to 3 epochs — the resumed
    run restores, fast-forwards the loader, and lands exactly on the
    uninterrupted trajectory."""

    class DS:
        def __init__(self, n=8):
            r = np.random.RandomState(5)
            self.x = r.randn(n, 8).astype(np.float32)
            self.y = r.randint(0, 2, size=(n, 1)).astype(np.int64)

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    def run_fit(m, ck, epochs):
        seen = []
        orig = m.train_batch

        def spy(ins, lbls=None, update=True):
            out = orig(ins, lbls, update)
            v = out[0] if isinstance(out, (list, tuple)) else out
            while isinstance(v, (list, tuple)):
                v = v[0]
            seen.append(float(v))
            return out

        m.train_batch = spy
        m.fit(DS(), batch_size=4, epochs=epochs, shuffle=False, verbose=0,
              checkpoint=ck)
        return seen

    full = run_fit(_build_model(None), None, 3)
    root = str(tmp_path / "fitck")
    a = run_fit(_build_model(None),
                ShardedCheckpointManager(root, ack_timeout=5), 2)
    b = run_fit(_build_model(None),
                ShardedCheckpointManager(root, ack_timeout=5), 3)
    assert a + b == full, (a, b, full)
    assert len(b) == len(full) - len(a)  # resumed work, not repeated
