"""Vision model zoo part 2 (vision/models_extra.py + resnext/wide).

Reference test model: test/legacy_test/test_vision_models.py —每个
architecture gets a forward-shape check; parameter counts pin the
architectures to their published sizes (weights can't be diffed offline).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _x(size=64):
    return paddle.to_tensor(
        np.random.RandomState(0).randn(1, 3, size, size).astype("float32")
        / 10)


def _n_params(m):
    return sum(int(np.prod(p.shape)) for p in m.parameters())


class TestZooForward:
    @pytest.mark.parametrize("name", [
        "alexnet", "squeezenet1_0", "squeezenet1_1", "densenet121",
        "mobilenet_v3_small", "mobilenet_v3_large", "shufflenet_v2_x0_5",
        "shufflenet_v2_x1_0",
    ])
    def test_forward_shape(self, name):
        m = getattr(M, name)(num_classes=4)
        m.eval()
        out = m(_x())
        assert list(out.shape) == [1, 4]

    def test_googlenet_aux_heads(self):
        m = M.googlenet(num_classes=4)
        m.eval()
        out, aux1, aux2 = m(_x(96))
        assert list(out.shape) == [1, 4]
        assert list(aux1.shape) == [1, 4]
        assert list(aux2.shape) == [1, 4]

    def test_pretrained_raises_offline(self):
        with pytest.raises(Exception):
            M.alexnet(pretrained=True)


class TestZooArchitectures:
    """Parameter counts at num_classes=1000 pin each architecture to its
    published size (strong structural check without pretrained weights)."""

    @pytest.mark.parametrize("ctor,expected_m", [
        (M.alexnet, 61.10),
        (M.squeezenet1_0, 1.25),
        (M.densenet121, 7.98),
        (M.inception_v3, 23.83),
        (M.mobilenet_v3_large, 5.48),
        (M.mobilenet_v3_small, 2.55),
        (M.shufflenet_v2_x1_0, 2.28),
        (M.resnext50_32x4d, 25.03),
        (M.wide_resnet50_2, 68.88),
    ])
    def test_param_count(self, ctor, expected_m):
        n = _n_params(ctor()) / 1e6
        assert abs(n - expected_m) / expected_m < 0.03, \
            f"{ctor.__name__}: {n:.2f}M params, expected ~{expected_m}M"

    def test_resnext_grouped_conv(self):
        m = M.resnext50_32x4d(num_classes=4)
        # the 3x3 stage of the first bottleneck must be 32-grouped, width 128
        blk = m.layer1.blocks[0]
        assert blk.conv2.groups == 32
        assert blk.conv2.weight.shape[0] == 128

    def test_wide_resnet_width(self):
        m = M.wide_resnet50_2(num_classes=4)
        blk = m.layer1.blocks[0]
        assert blk.conv2.weight.shape[0] == 128  # 64 * (128/64) = 128

    def test_training_step_on_small_model(self):
        from paddle_tpu import nn, optimizer
        m = M.shufflenet_v2_x0_5(num_classes=4)
        opt = optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
        lf = nn.CrossEntropyLoss()
        x = _x()
        y = paddle.to_tensor(np.array([1], dtype="int64"))
        loss = lf(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        assert np.isfinite(float(loss._data))
