"""Per-op synthesis recipes for the OpTest harness (VERDICT r2 #4).

The generic synthesizer covers ops taking plain float tensors; everything
with structural attributes (axes lists, pad configs, window shapes, index
operands, factorized-matrix inputs) gets an explicit recipe here — the
reference expresses the same knowledge per-op in each
test/legacy_test/test_*_op.py setUp. A recipe is
``name -> fn(rng) -> (args, kwargs)``; the harness calls the op as
``op(*args, **kwargs)``, differentiates the float positional args, and
runs the bf16 smoke on them.
"""
import numpy as np

import jax
import jax.numpy as jnp


def _f(rng, shape, lo=0.3, hi=0.9):
    return jnp.asarray(rng.uniform(lo, hi, shape))


def _i(rng, shape, hi, lo=0):
    return jnp.asarray(rng.randint(lo, hi, shape), jnp.int32)


def _spd(rng, n):
    a = rng.uniform(-1, 1, (n, n))
    return jnp.asarray(a @ a.T + n * np.eye(n))


def _well_conditioned(rng, n):
    return jnp.asarray(rng.uniform(-1, 1, (n, n)) + n * np.eye(n))


RECIPES = {
    # -- pooling / resizing --------------------------------------------------
    "adaptive_avg_pool1d": lambda rng: ((_f(rng, (2, 3, 8)), 4), {}),
    "adaptive_avg_pool2d": lambda rng: ((_f(rng, (2, 3, 8, 8)), 4), {}),
    "adaptive_avg_pool3d": lambda rng: ((_f(rng, (1, 2, 4, 4, 4)), 2), {}),
    "adaptive_max_pool2d": lambda rng: ((_f(rng, (1, 2, 8, 8)), 4), {}),
    "interpolate": lambda rng: ((_f(rng, (1, 2, 4, 4)),),
                                {"scale_factor": 2, "mode": "bilinear"}),
    "pixel_shuffle": lambda rng: ((_f(rng, (1, 4, 3, 3)), 2), {}),
    "pixel_unshuffle": lambda rng: ((_f(rng, (1, 1, 4, 4)), 2), {}),
    "channel_shuffle": lambda rng: ((_f(rng, (1, 4, 4, 4)), 2), {}),
    "local_response_norm": lambda rng: ((_f(rng, (1, 3, 4, 4)), 3), {}),
    "maxout": lambda rng: ((_f(rng, (1, 4, 3, 3)), 2), {}),
    "temporal_shift": lambda rng: ((_f(rng, (4, 4, 3, 3)), 2), {}),

    # -- convolution ---------------------------------------------------------
    "conv2d": lambda rng: ((_f(rng, (1, 3, 8, 8), -0.5, 0.5),
                            _f(rng, (4, 3, 3, 3), -0.5, 0.5)),
                           {"padding": 1}),
    "conv3d": lambda rng: ((_f(rng, (1, 2, 4, 4, 4), -0.5, 0.5),
                            _f(rng, (3, 2, 3, 3, 3), -0.5, 0.5)),
                           {"padding": 1}),
    "conv2d_transpose": lambda rng: ((_f(rng, (1, 3, 4, 4), -0.5, 0.5),
                                      _f(rng, (3, 4, 3, 3), -0.5, 0.5)), {}),
    "unfold": lambda rng: ((_f(rng, (1, 2, 6, 6)), [2, 2]),
                           {"strides": 2}),
    "fold": lambda rng: ((_f(rng, (1, 12, 4)), [4, 4], [2, 2]),
                         {"strides": 2}),

    # -- norm layers ---------------------------------------------------------
    "group_norm": lambda rng: ((_f(rng, (2, 4, 3, 3)), 2), {}),

    # -- attention -----------------------------------------------------------
    "scaled_dot_product_attention": lambda rng: (
        (_f(rng, (1, 8, 2, 16), -0.5, 0.5),
         _f(rng, (1, 8, 2, 16), -0.5, 0.5),
         _f(rng, (1, 8, 2, 16), -0.5, 0.5)), {}),
    "flash_attention_pallas": lambda rng: (
        (_f(rng, (1, 128, 2, 32), -0.5, 0.5).astype(jnp.float32),
         _f(rng, (1, 128, 2, 32), -0.5, 0.5).astype(jnp.float32),
         _f(rng, (1, 128, 2, 32), -0.5, 0.5).astype(jnp.float32)),
        {"interpret": True}),

    # -- shape / layout ------------------------------------------------------
    "reshape": lambda rng: ((_f(rng, (3, 4)), [4, 3]), {}),
    "transpose": lambda rng: ((_f(rng, (2, 3, 4)), [1, 0, 2]), {}),
    "swapaxes": lambda rng: ((_f(rng, (2, 3, 4)), 0, 1), {}),
    "moveaxis": lambda rng: ((_f(rng, (2, 3, 4)), 0, 2), {}),
    "flip": lambda rng: ((_f(rng, (3, 4)), [0]), {}),
    "reverse": lambda rng: ((_f(rng, (3, 4)), [1]), {}),
    "broadcast_to": lambda rng: ((_f(rng, (3, 1)), [3, 4]), {}),
    "expand": lambda rng: ((_f(rng, (3, 1)), [3, 4]), {}),
    "unflatten": lambda rng: ((_f(rng, (6, 4)), 0, [2, 3]), {}),
    "chunk": lambda rng: ((_f(rng, (6, 4)), 3, 0), {}),
    "as_strided": lambda rng: ((_f(rng, (16,)), [3, 4], [4, 1]), {}),
    "cast": lambda rng: ((_f(rng, (3, 4)), "float32"), {}),
    "pad": lambda rng: ((_f(rng, (2, 3, 4, 4)), [1, 1, 1, 1]), {}),
    "broadcast_shape_op": lambda rng: (([2, 3, 4], [3, 1]), {}),
    "slice": lambda rng: ((_f(rng, (4, 5)), [0, 1], [0, 1], [3, 4]), {}),
    "strided_slice": lambda rng: ((_f(rng, (4, 6)), [0, 1], [0, 0],
                                   [4, 6], [2, 2]), {}),
    "slice_scatter": lambda rng: ((_f(rng, (4, 6)), _f(rng, (2, 3)),
                                   [0, 1], [0, 0], [4, 6], [2, 2]), {}),
    "select_scatter": lambda rng: ((_f(rng, (3, 4)), _f(rng, (3,)), 1, 2),
                                   {}),
    "diagonal_scatter": lambda rng: ((_f(rng, (4, 4)), _f(rng, (4,))), {}),
    "set_item": lambda rng: ((_f(rng, (3, 4)), 1, 0.5), {}),

    # -- indexing / scatter-gather ------------------------------------------
    "one_hot": lambda rng: ((_i(rng, (3,), 5), 5), {}),
    "gather_nd": lambda rng: ((_f(rng, (3, 4)), _i(rng, (2, 2), 3)), {}),
    "take_along_axis": lambda rng: ((_f(rng, (3, 4)), _i(rng, (3, 2), 4),
                                     1), {}),
    "put_along_axis": lambda rng: ((_f(rng, (3, 4)), _i(rng, (3, 1), 4),
                                    0.5, 1), {}),
    "index_add": lambda rng: ((_f(rng, (3, 4)), _i(rng, (2,), 3), 0,
                               _f(rng, (2, 4))), {}),
    "index_fill": lambda rng: ((_f(rng, (3, 4)), _i(rng, (2,), 3), 0, 0.5),
                               {}),
    "index_put": lambda rng: ((_f(rng, (3, 4)),
                               (_i(rng, (2,), 3), _i(rng, (2,), 4)),
                               _f(rng, (2,))), {}),
    "masked_scatter": lambda rng: ((_f(rng, (3, 4)),
                                    jnp.asarray(rng.rand(3, 4) > 0.5),
                                    _f(rng, (12,))), {}),
    "scatter": lambda rng: ((_f(rng, (3, 4)), _i(rng, (2,), 3),
                             _f(rng, (2, 4))), {}),
    "scatter_nd_add": lambda rng: ((_f(rng, (3, 4)), _i(rng, (2, 1), 3),
                                    _f(rng, (2, 4))), {}),
    # unpacked-array wrapper: float args must be top-level positionals or
    # the harness's grad + bf16 checks silently skip (list args carry no
    # .dtype)
    "multiplex": lambda rng: ((_f(rng, (3, 4)), _f(rng, (3, 4)),
                               _i(rng, (3, 1), 2)), {"_wrap": "multiplex"}),
    "shard_index": lambda rng: ((_i(rng, (3, 1), 6), 6, 2, 0), {}),
    "tril_indices": lambda rng: ((4, 4), {}),
    "triu_indices": lambda rng: ((4,), {}),

    # -- sort / select -------------------------------------------------------
    "sort": lambda rng: ((_f(rng, (3, 4)),), {}),
    "argsort": lambda rng: ((_f(rng, (3, 4)),), {}),
    "topk": lambda rng: ((_f(rng, (3, 4)), 2), {}),
    "kthvalue": lambda rng: ((_f(rng, (3, 4)), 2), {}),

    # -- linalg --------------------------------------------------------------
    "cholesky": lambda rng: ((_spd(rng, 3),), {}),
    "cholesky_solve": lambda rng: ((_f(rng, (3, 2)),
                                    jnp.linalg.cholesky(_spd(rng, 3))), {}),
    "det": lambda rng: ((_well_conditioned(rng, 3),), {}),
    "slogdet": lambda rng: ((_well_conditioned(rng, 3),), {}),
    "inverse": lambda rng: ((_well_conditioned(rng, 3),), {}),
    "solve": lambda rng: ((_well_conditioned(rng, 3), _f(rng, (3, 2))), {}),
    "triangular_solve": lambda rng: ((jnp.triu(_well_conditioned(rng, 3)),
                                      _f(rng, (3, 2))), {}),
    "matrix_power": lambda rng: ((_well_conditioned(rng, 3), 2), {}),
    "matrix_exp": lambda rng: ((_f(rng, (3, 3), -0.3, 0.3),), {}),
    "multi_dot": lambda rng: ((_f(rng, (2, 3)), _f(rng, (3, 4)),
                               _f(rng, (4, 2))), {"_wrap": "multi_dot"}),
    "eig": lambda rng: ((_well_conditioned(rng, 3),), {}),
    "eigvals": lambda rng: ((_well_conditioned(rng, 3),), {}),
    "eigh": lambda rng: ((_spd(rng, 3),), {}),
    "eigvalsh": lambda rng: ((_spd(rng, 3),), {}),
    "lu_unpack": lambda rng: (
        (lambda lu_piv: (lu_piv[0], lu_piv[1].astype(jnp.int32) + 1))(
            jax.scipy.linalg.lu_factor(_well_conditioned(rng, 3))), {}),

    # -- losses --------------------------------------------------------------
    "dice_loss": lambda rng: ((_f(rng, (4, 3)), _i(rng, (4, 1), 3)), {}),
    "nll_loss": lambda rng: ((jnp.log(_f(rng, (3, 4))), _i(rng, (3,), 4)),
                             {}),
    "multi_margin_loss": lambda rng: ((_f(rng, (3, 4)), _i(rng, (3,), 4)),
                                      {}),
    "npair_loss": lambda rng: ((_f(rng, (3, 4)), _f(rng, (3, 4)),
                                _i(rng, (3,), 3)), {}),
    "hsigmoid_loss": lambda rng: ((_f(rng, (3, 5)), _i(rng, (3,), 4), 4,
                                   _f(rng, (3, 5), -0.5, 0.5)), {}),

    # -- signal / frames -----------------------------------------------------
    "frame_op": lambda rng: ((_f(rng, (8,)), 4, 2), {}),
    "overlap_add_op": lambda rng: ((_f(rng, (4, 3)), 2), {}),

    # -- special math --------------------------------------------------------
    "polygamma": lambda rng: ((_f(rng, (3, 4), 1.2, 1.9), 1), {}),
    "multigammaln": lambda rng: ((_f(rng, (3, 4), 3.0, 4.0), 2), {}),
    "renorm": lambda rng: ((_f(rng, (3, 4)), 2.0, 0, 1.0), {}),

    # -- dropout (fixed key: deterministic under grad/FD) --------------------
    "dropout": lambda rng: ((_f(rng, (3, 4)), 0.3, None, "upscale_in_train",
                             jax.random.PRNGKey(0)), {}),
    "alpha_dropout_op": lambda rng: ((_f(rng, (3, 4)),
                                      jax.random.PRNGKey(0), 0.3), {}),

    # -- vision / geometry ---------------------------------------------------
    "affine_grid": lambda rng: ((_f(rng, (2, 2, 3), -0.5, 0.5),
                                 [2, 3, 4, 4]), {}),
    "grid_sample": lambda rng: ((_f(rng, (1, 2, 4, 4)),
                                 _f(rng, (1, 3, 3, 2), -0.9, 0.9)), {}),
    "bilinear": lambda rng: ((_f(rng, (2, 3)), _f(rng, (2, 4)),
                              _f(rng, (5, 3, 4), -0.5, 0.5)), {}),
    "einsum_op": lambda rng: (("ij,jk->ik", _f(rng, (2, 3)),
                               _f(rng, (3, 4))), {}),

    # -- graph / segment -----------------------------------------------------
    "segment_sum_op": lambda rng: ((_f(rng, (6, 3)),
                                    jnp.asarray([0, 0, 1, 1, 2, 2],
                                                jnp.int32), 3), {}),
    "segment_mean_op": lambda rng: ((_f(rng, (6, 3)),
                                     jnp.asarray([0, 0, 1, 1, 2, 2],
                                                 jnp.int32), 3), {}),
    "segment_max_op": lambda rng: ((_f(rng, (6, 3)),
                                    jnp.asarray([0, 0, 1, 1, 2, 2],
                                                jnp.int32), 3), {}),
    "segment_min_op": lambda rng: ((_f(rng, (6, 3)),
                                    jnp.asarray([0, 0, 1, 1, 2, 2],
                                                jnp.int32), 3), {}),
    "send_u_recv_op": lambda rng: ((_f(rng, (4, 3)), _i(rng, (5,), 4),
                                    _i(rng, (5,), 4), "sum", 4), {}),
    "send_ue_recv_op": lambda rng: ((_f(rng, (4, 3)), _f(rng, (5, 3)),
                                     _i(rng, (5,), 4), _i(rng, (5,), 4),
                                     "add", "sum", 4), {}),
    "send_uv_op": lambda rng: ((_f(rng, (4, 3)), _f(rng, (4, 3)),
                                _i(rng, (5,), 4), _i(rng, (5,), 4), "add"),
                               {}),

    # -- sequence / decode ---------------------------------------------------
    "gather_tree": lambda rng: ((_i(rng, (4, 2, 3), 3),
                                 _i(rng, (4, 2, 3), 3)), {}),
    "viterbi_decode_op": lambda rng: ((_f(rng, (2, 4, 3), -1, 1),
                                       _f(rng, (3, 3), -1, 1),
                                       jnp.asarray([4, 3], jnp.int64),
                                       False), {}),
}


# Adapters for ops whose natural signature takes a LIST of tensors: the
# harness needs float args as top-level positionals so grad/bf16 checks see
# them. A recipe opts in via kwargs={"_wrap": "<name>"}.
ADAPTERS = {
    "multi_dot": lambda fn: (lambda a, b, c: fn([a, b, c])),
    "multiplex": lambda fn: (lambda a, b, idx: fn([a, b], idx)),
}


# Named whitelist: ops the harness intentionally does NOT synthesize, each
# with the reason — the reference gates every exception by name the same
# way (test/white_list/, op_test.py:420). test_whitelist_is_exact pins that
# this list matches reality in both directions.
WHITELIST = {
    "_adaptive_max_nd": "private helper behind adaptive_max_pool{1,2,3}d "
                        "(covered via the public recipes + test_nn.py)",
    "_avg_pool": "private helper behind avg_pool{1,2,3}d (public ops are "
                 "generically synthesized; window semantics in test_nn.py)",
    "_max_pool": "private helper behind max_pool{1,2,3}d (same coverage as "
                 "_avg_pool)",
    "_batch_norm_eval": "private helper behind batch_norm (running-stat "
                        "plumbing exercised in test_nn.py BatchNorm tests)",
    "_batch_norm_train": "private helper behind batch_norm (same coverage "
                         "as _batch_norm_eval)",
    "_conv_transpose_nd": "private helper behind conv{1,2,3}d_transpose "
                          "(conv2d_transpose recipe covers the path)",
    "_ctc_loss_impl": "private helper behind ctc_loss; needs coupled "
                      "log-prob/label/length structure (test_loss.py "
                      "pins numerics against reference values)",
    "_rnnt_loss_impl": "private helper behind rnnt_loss; same structural "
                       "coupling as _ctc_loss_impl (test_loss.py)",
}
