"""API-parity pins: every name in the reference's __all__ lists must exist.

Reference: python/paddle/**/__init__.py __all__ declarations (snapshot
mounted at /root/reference). These tests freeze the parity the build has
reached so a regression (lost export, renamed symbol) fails loudly.
Namespaces are checked structurally (hasattr), not behaviorally — behavior
is covered by the per-subsystem test files.
"""
import ast
import importlib
import os

import pytest

_REF = "/root/reference/python/paddle/"

NAMESPACES = [
    "", "nn", "nn.functional", "nn.initializer", "linalg", "fft", "signal",
    "distributed", "distributed.fleet", "vision", "vision.transforms",
    "vision.ops", "vision.models", "vision.datasets", "sparse", "sparse.nn",
    "amp", "metric", "distribution", "io", "jit", "static", "static.nn",
    "autograd", "device", "text", "audio", "geometric", "incubate",
    "profiler", "quantization", "utils", "optimizer", "optimizer.lr",
    "regularizer",
]


def _ref_all(ns):
    rel = ns.replace(".", "/")
    for cand in (os.path.join(_REF, rel, "__init__.py"),
                 os.path.join(_REF, rel + ".py")):
        if not os.path.exists(cand):
            continue
        for node in ast.walk(ast.parse(open(cand).read())):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        try:
                            return ast.literal_eval(node.value)
                        except Exception:
                            return None
    return None


@pytest.mark.parametrize("ns", NAMESPACES)
def test_namespace_parity(ns):
    ref = _ref_all(ns)
    if ref is None:
        pytest.skip(f"reference has no literal __all__ for {ns!r}")
    mod = importlib.import_module("paddle_tpu" + ("." + ns if ns else ""))
    missing = [n for n in ref if not hasattr(mod, n)]
    assert not missing, (f"paddle.{ns or '<top>'} lost parity: "
                         f"{len(missing)} missing: {missing[:20]}")


DEEP_NAMESPACES = [
    "nn.utils", "nn.quant", "incubate.nn", "incubate.nn.functional",
    "incubate.autograd", "distributed.fleet.utils", "utils.cpp_extension",
    "amp.debugging",
]


@pytest.mark.parametrize("ns", DEEP_NAMESPACES)
def test_deep_namespace_parity(ns):
    ref = _ref_all(ns)
    if ref is None:
        pytest.skip(f"reference has no literal __all__ for {ns!r}")
    mod = importlib.import_module("paddle_tpu." + ns)
    missing = [n for n in ref if not hasattr(mod, n)]
    assert not missing, f"paddle.{ns} missing: {missing}"
