"""paddle.io DataLoader stack tests.

Reference coverage model: test/legacy_test/test_dataloader_*.py,
test_batch_sampler.py, test_dataset*.py (SURVEY.md §4).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (BatchSampler, ChainDataset, ComposeDataset,
                           ConcatDataset, DataLoader, Dataset,
                           DistributedBatchSampler, IterableDataset,
                           RandomSampler, SequenceSampler, Subset,
                           TensorDataset, WeightedRandomSampler,
                           default_collate_fn, get_worker_info, random_split)


class SquareDataset(Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.array([i], dtype=np.float32), np.array(i * i,
                                                         dtype=np.int64)

    def __len__(self):
        return self.n


class CountStream(IterableDataset):
    def __init__(self, n=17):
        self.n = n

    def __iter__(self):
        info = get_worker_info()
        lo, hi = 0, self.n
        if info is not None and info.num_workers > 1:
            per = (self.n + info.num_workers - 1) // info.num_workers
            lo, hi = info.id * per, min((info.id + 1) * per, self.n)
        for i in range(lo, hi):
            yield np.array([i], dtype=np.float32)


def test_tensor_dataset_and_subset():
    xs = paddle.to_tensor(np.arange(12, dtype="float32").reshape(6, 2))
    ys = paddle.to_tensor(np.arange(6))
    ds = TensorDataset([xs, ys])
    assert len(ds) == 6
    x0, y0 = ds[2]
    assert float(y0) == 2
    sub = Subset(ds, [0, 5])
    assert len(sub) == 2 and float(sub[1][1]) == 5


def test_compose_chain_concat():
    d1, d2 = SquareDataset(4), SquareDataset(4)
    comp = ComposeDataset([d1, d2])
    assert len(comp[0]) == 4
    cat = ConcatDataset([d1, d2])
    assert len(cat) == 8
    np.testing.assert_allclose(cat[5][0], d2[1][0])
    chain = ChainDataset([CountStream(3), CountStream(2)])
    assert sum(1 for _ in chain) == 5


def test_random_split():
    a, b = random_split(SquareDataset(10), [7, 3])
    assert len(a) == 7 and len(b) == 3
    ids = sorted([a.indices[i] for i in range(7)] +
                 [b.indices[i] for i in range(3)])
    assert ids == list(range(10))


def test_samplers():
    ds = SquareDataset(10)
    assert list(SequenceSampler(ds)) == list(range(10))
    rs = list(RandomSampler(ds))
    assert sorted(rs) == list(range(10))
    ws = list(WeightedRandomSampler([0.0, 0.0, 1.0], 5))
    assert ws == [2] * 5


def test_batch_sampler():
    ds = SquareDataset(10)
    bs = BatchSampler(ds, batch_size=3, drop_last=False)
    batches = list(bs)
    assert len(bs) == 4 and len(batches) == 4
    assert batches[-1] == [9]
    bs2 = BatchSampler(ds, batch_size=3, drop_last=True)
    assert len(list(bs2)) == 3


def test_distributed_batch_sampler_partitions():
    ds = SquareDataset(20)
    seen = []
    for rank in range(4):
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                    rank=rank)
        for b in s:
            seen.extend(b)
    assert sorted(seen) == list(range(20))
    # set_epoch changes shuffle order
    s = DistributedBatchSampler(ds, batch_size=5, num_replicas=1, rank=0,
                                shuffle=True)
    s.set_epoch(0)
    e0 = [i for b in s for i in b]
    s.set_epoch(1)
    e1 = [i for b in s for i in b]
    assert e0 != e1 and sorted(e0) == sorted(e1)


def test_dataloader_single_process():
    loader = DataLoader(SquareDataset(10), batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 3
    x, y = batches[0]
    assert x.shape == [4, 1] and y.shape == [4]
    np.testing.assert_allclose(y.numpy(), [0, 1, 4, 9])


def test_dataloader_collate_dict():
    class DictDs(Dataset):
        def __getitem__(self, i):
            return {"x": np.float32(i), "y": np.array([i, i])}

        def __len__(self):
            return 4

    batch = next(iter(DataLoader(DictDs(), batch_size=4)))
    assert batch["x"].shape == [4]
    assert batch["y"].shape == [4, 2]


def test_dataloader_multiprocess_ordered():
    loader = DataLoader(SquareDataset(32), batch_size=4, num_workers=2)
    got = [b[1].numpy() for b in loader]
    expect = [np.arange(i, i + 4) ** 2 for i in range(0, 32, 4)]
    assert len(got) == 8
    for g, e in zip(got, expect):
        np.testing.assert_allclose(g, e)


def test_dataloader_multiprocess_worker_error():
    class Bad(Dataset):
        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom")
            return np.float32(i)

        def __len__(self):
            return 8

    loader = DataLoader(Bad(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(loader)


def test_dataloader_iterable_dataset():
    loader = DataLoader(CountStream(10), batch_size=4)
    batches = list(loader)
    assert sum(b.shape[0] for b in batches) == 10


def test_dataloader_iterable_multiworker():
    loader = DataLoader(CountStream(16), batch_size=4, num_workers=2)
    vals = sorted(int(v) for b in loader for v in b.numpy().ravel())
    assert vals == list(range(16))


def test_shard_dataloader():
    from paddle_tpu.distributed import ProcessMesh, shard_dataloader
    mesh = ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
    loader = DataLoader(SquareDataset(16), batch_size=8)
    sharded = shard_dataloader(loader, mesh, shard_dims="dp")
    x, y = next(iter(sharded))
    assert len(x._data.sharding.device_set) == 8
    assert len(sharded) == 2


def test_dataloader_iterable_drop_last():
    loader = DataLoader(CountStream(10), batch_size=4, drop_last=True)
    batches = list(loader)
    assert all(b.shape[0] == 4 for b in batches)
    assert sum(b.shape[0] for b in batches) == 8


def test_random_sampler_generator_exhausts_cleanly():
    got = list(RandomSampler(SquareDataset(10), generator=iter([1, 2]),
                             num_samples=5))
    assert got == [1, 2]


def test_tensor_dataset_multiworker():
    xs = paddle.to_tensor(np.arange(16, dtype="float32").reshape(8, 2))
    ys = paddle.to_tensor(np.arange(8))
    loader = DataLoader(TensorDataset([xs, ys]), batch_size=4, num_workers=2)
    batches = list(loader)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[1][1].numpy(), [4, 5, 6, 7])
