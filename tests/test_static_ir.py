"""Jaxpr-IR program + pass surface (static.ir).

Reference model: the graph-pass unit tests around
fluid/framework/ir/pass.h passes (dead_code_elimination_pass,
constant_folding_pass) — each pass must shrink the program as claimed and
preserve semantics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import ir


def _rand(shape, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(*shape).astype("float32"))


@pytest.mark.quick
def test_trace_inspect_and_run():
    def fn(x, y):
        return paddle.tanh(x) + y * 2.0

    x, y = _rand((3, 4), 0), _rand((3, 4), 1)
    prog = ir.IrProgram.trace(fn, x, y)
    assert prog.num_ops() >= 2
    assert "tanh" in str(prog)
    out = prog(x, y)
    ref = fn(x, y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref._data),
                               rtol=1e-6)
    compiled = prog.compile()
    np.testing.assert_allclose(np.asarray(compiled(x, y)),
                               np.asarray(ref._data), rtol=1e-6)


def test_dead_code_elimination_removes_unused():
    def fn(x):
        dead = paddle.exp(x) * 3.0   # never reaches the output
        live = paddle.tanh(x)
        return live + 1.0

    x = _rand((3, 4), 2)
    prog = ir.IrProgram.trace(fn, x)
    before = prog.num_ops()
    opt = ir.apply_pass(prog, "dead_code_elimination")
    assert opt.num_ops() < before
    assert not any("exp" in op for op in opt.ops())
    np.testing.assert_allclose(np.asarray(opt(x)), np.asarray(prog(x)),
                               rtol=1e-6)
    assert opt.applied_passes == ["dead_code_elimination"]


def test_constant_folding_folds_literal_chain():
    def fn(x):
        import paddle_tpu as pp
        c = pp.to_tensor(np.float32(2.0)) * pp.to_tensor(np.float32(3.0))
        return x * c

    x = _rand((4,), 3)
    prog = ir.IrProgram.trace(fn, x)
    opt = ir.apply_pass(prog, "constant_folding")
    # the 2*3 multiply folded into a const: one fewer op
    assert opt.num_ops() < prog.num_ops()
    np.testing.assert_allclose(np.asarray(opt(x)), np.asarray(prog(x)),
                               rtol=1e-6)


def test_cse_dedups_identical_subexpressions():
    def fn(x):
        a = paddle.tanh(x)
        b = paddle.tanh(x)    # identical subexpression
        return a + b

    x = _rand((3, 3), 4)
    prog = ir.IrProgram.trace(fn, x)
    n_tanh_before = sum("tanh" in op for op in prog.ops())
    opt = ir.apply_pass(prog, "common_subexpression_elimination")
    n_tanh_after = sum("tanh" in op for op in opt.ops())
    assert n_tanh_before == 2 and n_tanh_after == 1
    np.testing.assert_allclose(np.asarray(opt(x)), np.asarray(prog(x)),
                               rtol=1e-6)


def test_pass_pipeline_and_registry():
    assert set(ir.list_passes()) >= {"dead_code_elimination",
                                     "constant_folding",
                                     "common_subexpression_elimination"}

    def fn(x):
        dead = paddle.exp(x)
        a = paddle.tanh(x)
        b = paddle.tanh(x)
        return a + b

    x = _rand((2, 2), 5)
    prog = ir.IrProgram.trace(fn, x)
    opt = ir.apply_pass(prog, ["dead_code_elimination",
                               "common_subexpression_elimination"])
    assert opt.num_ops() < prog.num_ops()
    np.testing.assert_allclose(np.asarray(opt(x)), np.asarray(prog(x)),
                               rtol=1e-6)
    with pytest.raises(KeyError, match="unknown pass"):
        ir.apply_pass(prog, "no_such_pass")


def test_custom_registered_pass():
    @ir.register_pass("noop_test_pass")
    def noop(closed):
        return closed
    try:
        def fn(x):
            return x + 1.0
        prog = ir.IrProgram.trace(fn, _rand((2,), 6))
        opt = ir.apply_pass(prog, "noop_test_pass")
        assert opt.applied_passes == ["noop_test_pass"]
    finally:
        ir.PASS_REGISTRY.pop("noop_test_pass", None)
