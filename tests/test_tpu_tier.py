"""Real-TPU tier: Mosaic-compile every Pallas kernel + hardware-PRNG checks.

Run with `pytest -m tpu` (conftest then keeps the ambient TPU backend
instead of forcing the virtual CPU mesh). Every other test file runs the
kernels under `interpret=True`; this tier is the first-contact suite for
real hardware — it compiles each kernel with Mosaic (no interpret), pins
numerics against dense references on-device, runs the dropout
seed-coordinate and keep-rate checks on the `pltpu.prng_*` path (the
interpret tests only ever exercise the murmur-hash branch), and captures
jax.profiler traces for the pipeline schedules (1F1B vs VPP) and the
flagship attention step so bubble/overlap behavior is quotable.

Reference coverage model: the device-side kernel tests the reference runs
per-GPU-arch (test/legacy_test/test_flash_attention.py driving
phi/kernels/gpu/flash_attn_kernel.cu:128) — here the device is a TPU chip
and the compile path is Mosaic.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu

PROFILE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "profiles")

# PADDLE_TPU_TIER_INTERPRET=1 runs the same tests interpreted on CPU — a
# logic self-check for the tier while hardware is unavailable. The real
# tier (no env, `pytest -m tpu` on a TPU host) compiles with Mosaic.
INTERPRET = os.environ.get("PADDLE_TPU_TIER_INTERPRET") == "1"


def _require_tpu():
    if INTERPRET:
        return
    from paddle_tpu.ops import pallas as _pl
    if not _pl.on_tpu():
        pytest.skip("no TPU backend available (run under the ambient axon "
                    "env; conftest keeps it when -m tpu is used)")


def _flash(*args, **kw):
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_pallas
    kw.setdefault("interpret", INTERPRET)
    return flash_attention_pallas(*args, **kw)


def _bsparse(*args, **kw):
    from paddle_tpu.ops.pallas.block_sparse_attention import \
        block_sparse_attention_pallas
    kw.setdefault("interpret", INTERPRET)
    return block_sparse_attention_pallas(*args, **kw)


def _dense(q, k, v, causal, mask=None, seqlens=None):
    d = q.shape[-1]
    hq, hkv = q.shape[2], k.shape[2]
    if hkv != hq:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = jnp.einsum("bshd->bhsd", q)
    kt = jnp.einsum("bshd->bhsd", k)
    vt = jnp.einsum("bshd->bhsd", v)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    if mask is not None:
        s = s + mask
    if causal:
        n = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool)), s, -1e30)
    if seqlens is not None:
        n = q.shape[1]
        cols = jnp.arange(n)[None, None, None, :]
        rows = jnp.arange(n)[None, None, :, None]
        sl = seqlens[:, None, None, None]
        s = jnp.where((cols < sl) & (rows < sl), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.einsum("bhsd->bshd", out)


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), jnp.float32)


# -- flash attention v2: Mosaic compile + numerics --------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_flash_mosaic_forward(causal):
    _require_tpu()
    b, s, h, d = 2, 512, 2, 64
    q, k, v = _rand((b, s, h, d), 0), _rand((b, s, h, d), 1), \
        _rand((b, s, h, d), 2)
    out = _flash(q, k, v, causal=causal)  # Mosaic compile
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense(q, k, v, causal)),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_mosaic_grads(causal):
    _require_tpu()
    b, s, h, d = 1, 512, 1, 64
    q, k, v = _rand((b, s, h, d), 3), _rand((b, s, h, d), 4), \
        _rand((b, s, h, d), 5)

    got = jax.grad(lambda q, k, v: _flash(
        q, k, v, causal=causal).sum(), argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(lambda q, k, v: _dense(
        q, k, v, causal).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-2, atol=5e-2)


def test_flash_mosaic_gqa_mask_varlen():
    _require_tpu()
    # GQA
    b, s, hq, hkv, d = 2, 512, 4, 2, 64
    q = _rand((b, s, hq, d), 6)
    k, v = _rand((b, s, hkv, d), 7), _rand((b, s, hkv, d), 8)
    out = _flash(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense(q, k, v, True)),
                               rtol=2e-2, atol=2e-2)
    # additive mask
    b, s, h, d = 1, 512, 2, 64
    q, k, v = _rand((b, s, h, d), 9), _rand((b, s, h, d), 10), \
        _rand((b, s, h, d), 11)
    mask = jnp.asarray(np.random.RandomState(12).randn(b, 1, s, s) * 2,
                       jnp.float32)
    out = _flash(q, k, v, causal=False, attn_mask=mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v, False, mask=mask)),
        rtol=2e-2, atol=2e-2)
    # varlen padding
    lens = jnp.asarray([400, 256], jnp.int32)
    q2, k2, v2 = _rand((2, s, h, d), 13), _rand((2, s, h, d), 14), \
        _rand((2, s, h, d), 15)
    out2 = _flash(q2, k2, v2, causal=True, kv_seqlens=lens)
    ref2 = _dense(q2, k2, v2, True, seqlens=lens)
    for i, L in enumerate([400, 256]):
        np.testing.assert_allclose(np.asarray(out2)[i, :L],
                                   np.asarray(ref2)[i, :L],
                                   rtol=2e-2, atol=2e-2)


def test_flash_mosaic_arbitrary_and_short_seq():
    _require_tpu()
    for (b, s, h, d), seed in (((1, 200, 2, 64), 16), ((2, 48, 2, 64), 19)):
        q, k, v = _rand((b, s, h, d), seed), _rand((b, s, h, d), seed + 1), \
            _rand((b, s, h, d), seed + 2)
        out = _flash(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, True)),
                                   rtol=2e-2, atol=2e-2)


# -- dropout on the hardware PRNG path --------------------------------------

def test_flash_dropout_hw_prng_determinism_and_keep_rate():
    """VERDICT r2 weak #3: the pltpu.prng_seed/prng_random_bits branch of
    _keep_mask has only ever run interpreted (murmur branch). On hardware:
    same seed → identical outputs; different seed → different; keep-rate
    statistics match dropout_p; expectation is preserved."""
    _require_tpu()
    b, s, h, d = 1, 512, 2, 64
    q, k = _rand((b, s, h, d), 30), _rand((b, s, h, d), 31)
    v = jnp.ones((b, s, h, d), jnp.float32)
    p = 0.5
    o1 = _flash(q, k, v, causal=False, dropout_p=p, seed=7)
    o2 = _flash(q, k, v, causal=False, dropout_p=p, seed=7)
    o3 = _flash(q, k, v, causal=False, dropout_p=p, seed=8)
    o0 = _flash(q, k, v, causal=False)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))
    assert not np.allclose(np.asarray(o1), np.asarray(o0))
    # with v == 1, each output element is (sum of kept probs) / (1-p):
    # E == 1, and the dispersion across rows is a keep-rate statistic.
    m = float(jnp.mean(o1))
    assert abs(m - 1.0) < 0.05, f"dropout mean {m} != 1 (keep-rate broken)"
    sd = float(jnp.std(o1))
    assert sd > 0.01, "dropout produced no variance — mask degenerate"


def test_flash_dropout_hw_prng_fwd_bwd_seed_coordinates():
    """A seed-coordinate mismatch between _fwd_kernel (b, qi, ki) and the
    bwd kernels would regenerate a DIFFERENT mask in the backward and
    silently corrupt grads only on TPU. Pin it with a directional
    finite-difference check: with a fixed seed the masked function is
    smooth, so autodiff must match (f(q+hu) - f(q-hu)) / 2h."""
    _require_tpu()
    b, s, h, d = 1, 256, 2, 64
    q, k, v = _rand((b, s, h, d), 33), _rand((b, s, h, d), 34), \
        _rand((b, s, h, d), 35)

    def f(q_):
        return _flash(q_, k, v, causal=True, dropout_p=0.3,
                                      seed=7).sum()

    g = jax.grad(f)(q)
    assert bool(jnp.isfinite(g).all())
    u = _rand((b, s, h, d), 36)
    u = u / jnp.linalg.norm(u.ravel())
    hstep = 1e-1
    fd = (f(q + hstep * u) - f(q - hstep * u)) / (2 * hstep)
    ad = jnp.vdot(g, u)
    # f32 attention + finite differences: loose bound, but a wrong bwd mask
    # (30% of entries flipped) misses by O(1), far outside it.
    np.testing.assert_allclose(float(fd), float(ad), rtol=0.15, atol=0.05)
    # determinism of the bwd path itself
    g2 = jax.grad(f)(q)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(g2))


# -- block-sparse + fused kernels -------------------------------------------

def test_block_sparse_mosaic():
    _require_tpu()
    b, s, h, d = 1, 512, 2, 64
    q, k, v = _rand((b, s, h, d), 40), _rand((b, s, h, d), 41), \
        _rand((b, s, h, d), 42)
    nb = s // 128
    rng = np.random.RandomState(43)
    bm = (rng.rand(nb, nb) < 0.5)
    bm[:, 0] = True
    out = _bsparse(q, k, v, bm)
    mask = np.repeat(np.repeat(bm, 128, 0), 128, 1)
    big = jnp.asarray(np.where(mask, 0.0, -1e30), jnp.float32)
    ref = _dense(q, k, v, False, mask=big[None, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda q_: _bsparse(
        q_, k, v, bm).sum())(q)
    gref = jax.grad(lambda q_: _dense(q_, k, v, False,
                                      mask=big[None, None]).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=5e-2, atol=5e-2)


def test_rmsnorm_mosaic():
    _require_tpu()
    from paddle_tpu.ops.pallas.fused_ops import rms_norm_pallas
    x = _rand((64, 512), 50)
    w = _rand((512,), 51)

    def ref(x_, w_):
        r = jax.lax.rsqrt(jnp.mean(x_ * x_, -1, keepdims=True) + 1e-6)
        return x_ * r * w_

    out = rms_norm_pallas(x, w, interpret=INTERPRET)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(x, w)),
                               rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda x_: rms_norm_pallas(x_, w, interpret=INTERPRET).sum())(x)
    gref = jax.grad(lambda x_: ref(x_, w).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=5e-2, atol=5e-2)


def test_adamw_mosaic():
    _require_tpu()
    from paddle_tpu.ops.pallas.fused_ops import adamw_pallas
    n = 4096
    p = _rand((n,), 60)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    g = _rand((n,), 61)
    lr, b1, b2, eps, wd = 1e-3, 0.9, 0.999, 1e-8, 0.01
    np_, nm, nv = adamw_pallas(p, m, v, g, lr=lr, beta1=b1, beta2=b2,
                               eps=eps, weight_decay=wd,
                               beta1_pow=b1, beta2_pow=b2, interpret=INTERPRET)
    # reference AdamW (step 1: beta powers are beta^1)
    rm = b1 * m + (1 - b1) * g
    rv = b2 * v + (1 - b2) * g * g
    mh = rm / (1 - b1)
    vh = rv / (1 - b2)
    rp = p - lr * (mh / (jnp.sqrt(vh) + eps) + wd * p)
    np.testing.assert_allclose(np.asarray(nm), np.asarray(rm), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(nv), np.asarray(rv), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(np_), np.asarray(rp), rtol=1e-4,
                               atol=1e-6)


# -- profiles: pipeline bubbles + flagship attention step -------------------

def _profile(name, fn):
    os.makedirs(PROFILE_DIR, exist_ok=True)
    out = os.path.join(PROFILE_DIR, name)
    with jax.profiler.trace(out):
        fn()
    # xplane capture lands under <out>/plugins/profile/<ts>/*.xplane.pb
    found = []
    for root, _dirs, files in os.walk(out):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane trace captured under {out}"
    return out


def test_pipeline_bubble_profiles():
    """Device-level bubble evidence for the schedule plans (VERDICT r2
    missing #6): trace one train_batch under 1F1B and under VPP; the two
    xplane traces land in profiles/ for the round report."""
    _require_tpu()
    if len(jax.devices()) < 2:
        pytest.skip("pipeline bubble profile needs >=2 devices (SPMD "
                    "rank-stacked pipeline maps one rank per chip); run "
                    "the CPU-mesh self-check via PADDLE_TPU_TIER_INTERPRET=1")
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
    from paddle_tpu.distributed.fleet import topology as topo

    HIDDEN = 128

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(HIDDEN, HIDDEN)

        def forward(self, x):
            return nn.functional.relu(self.fc(x))

    def loss_fn(out, label):
        return nn.functional.cross_entropy(out, label).mean()

    def run(vpp, name):
        topo.set_hybrid_communicate_group(None)
        paddle.seed(42)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1}
        strategy.pipeline_configs = {"accumulate_steps": 4}
        fleet.init(is_collective=True, strategy=strategy)
        kwargs = {"num_virtual_pipeline_stages": vpp} if vpp else {}
        descs = [LayerDesc(Block) for _ in range(4)]
        model = PipelineLayer(layers=descs, loss_fn=loss_fn, **kwargs)
        model = fleet.distributed_model(model)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, HIDDEN).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, HIDDEN, (8,)))
        model.train_batch([x, y], opt)  # warmup/compile outside the trace
        _profile(name, lambda: model.train_batch([x, y], opt))

    run(None, "pp_1f1b")
    run(2, "pp_vpp")


def test_flagship_attention_step_profile():
    """Trace one flash-attention Llama forward+backward on the chip (ring
    overlap itself needs >=2 devices; on one chip this captures the
    Mosaic-compiled attention inside the scanned flagship so kernel/HBM
    behavior is visible in the xplane)."""
    _require_tpu()
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config

    cfg = llama_tiny_config(num_hidden_layers=2, hidden_size=256,
                            num_attention_heads=4, num_key_value_heads=2,
                            vocab_size=512, max_position_embeddings=1024)
    cfg.scan_layers = True
    paddle.set_flags({"FLAGS_use_pallas_attention": True})
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 512, (2, 1024)))

    def step():
        logits, loss = model(ids, labels=ids)
        loss.backward()

    step()  # compile outside the trace
    _profile("llama_flash_step", step)


def test_flash_autotune_sweep():
    """One on-device tuning sweep: every candidate measured (or recorded
    as failed), winner cached, and the flagged kernel path adopts it."""
    _require_tpu()
    if INTERPRET:
        pytest.skip("tuning times real kernels; meaningless interpreted")
    import paddle_tpu as paddle
    from paddle_tpu.ops.pallas import autotune
    q, k, v = _rand((1, 1024, 4, 64), 70), _rand((1, 1024, 4, 64), 71), \
        _rand((1, 1024, 4, 64), 72)
    best, results = autotune.tune_flash_blocks(q, k, v, causal=True,
                                               iters=3)
    assert best in results and results[best] is not None
    assert autotune.cached_blocks(q, k, True, False, 0.0) == best
    timed = {c: t for c, t in results.items() if t is not None}
    assert timed, results
    # the flagged path must now produce identical numerics at the winner
    paddle.set_flags({"FLAGS_flash_autotune": True})
    try:
        out = _flash(q, k, v, causal=True)
        ref = _flash(q, k, v, causal=True, block_q=best[0],
                     block_k=best[1])
    finally:
        paddle.set_flags({"FLAGS_flash_autotune": False})
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_overlap_trace():
    """Multi-chip only: capture an xplane trace of the double-buffered
    ring so the ppermute/compute overlap is inspectable on real ICI
    (VERDICT r2 missing #6's last leg)."""
    _require_tpu()
    if len(jax.devices()) < 2:
        pytest.skip("ring overlap needs >=2 chips (sep axis of size >1)")
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    from paddle_tpu.ops.ring_attention import ring_attention

    n = len(jax.devices())
    mesh = ProcessMesh(np.arange(n), ["sep"])
    # real chips get a meaningful size; the CPU self-check stays tiny
    b, s, h, d = (1, 512 * n, 4, 128) if not INTERPRET else (1, 16 * n, 2, 8)
    rng = np.random.RandomState(0)
    q = paddle.to_tensor(rng.randn(b, s, h, d).astype("float32"))
    k = paddle.to_tensor(rng.randn(b, s, h, d).astype("float32"))
    v = paddle.to_tensor(rng.randn(b, s, h, d).astype("float32"))
    ring_attention(q, k, v, mesh=mesh, causal=True)  # compile outside
    _profile("ring_overlap",
             lambda: ring_attention(q, k, v, mesh=mesh, causal=True))


def test_paged_exactness_retry_free_on_tpu():
    """VERDICT r3 #9: the CPU suites retry exact-token scenarios once
    because host load flips argmax near-ties in threaded CPU matmuls; on
    TPU the same scenarios must be exact on the FIRST try. Drive the
    paged batcher (unchunked + chunked prefill) against solo generate
    with no retry wrapper — and pin that the retry helper itself is a
    no-op on this backend."""
    _require_tpu()
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config

    from test_paged_batching import _retry_load_flake

    if not INTERPRET:
        # the helper must never retry on TPU: a failing body raises on
        # the FIRST attempt (attempts forced to 1)
        calls = []

        def failing():
            calls.append(1)
            raise AssertionError("probe")

        with pytest.raises(AssertionError, match="probe"):
            _retry_load_flake(failing, attempts=5)
        assert len(calls) == 1, "retry helper must no-op on TPU"

    paddle.seed(0)
    cfg = llama_tiny_config(vocab_size=512, hidden_size=128,
                            num_hidden_layers=2,
                            max_position_embeddings=256)
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 512, (s,)) for s in (9, 33, 50)]

    def solo(p, n):
        ids = paddle.to_tensor(np.asarray(p, np.int64)[None])
        with paddle.no_grad():
            return m.generate(ids, max_new_tokens=n).numpy()[0]

    for chunk in (None, 16):
        b = PagedContinuousBatcher(m, max_batch=2, s_max=128,
                                   block_size=16, prefill_chunk=chunk,
                                   compile=True)
        rids = [b.submit(p, 8) for p in prompts]
        outs = b.run_until_done()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], solo(p, 8))


def test_fused_serving_on_tpu():
    """Fused-admission continuous batching (decode + prefill chunks in
    one executable) token-exact with throughput reporting. PRE-STAGED
    for hardware (validated in interpret/CPU mode; the heal playbook's
    `pytest -m tpu` stage gives it its first on-chip run — the relay
    was wedged when this landed, see TPU_PROBES.log)."""
    _require_tpu()
    import time

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import PagedContinuousBatcher
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config

    paddle.seed(0)
    cfg = llama_tiny_config(vocab_size=1024, hidden_size=256,
                            num_hidden_layers=4,
                            max_position_embeddings=512)
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 1024, (s,)) for s in (17, 64, 128, 41)]
    b = PagedContinuousBatcher(m, max_batch=4, s_max=256, block_size=32,
                               prefill_chunk=64, fused_admission=True,
                               compile=True)
    rids = [b.submit(p, 16) for p in prompts]
    t0 = time.perf_counter()
    outs = b.run_until_done()
    dt = time.perf_counter() - t0
    for rid, p in zip(rids, prompts):
        ids = paddle.to_tensor(np.asarray(p, np.int64)[None])
        with paddle.no_grad():
            ref = m.generate(ids, max_new_tokens=16).numpy()[0]
        np.testing.assert_array_equal(outs[rid], ref)
    s = b.stats()
    print(f"[tpu] fused serving: {s['generated_tokens']} tokens in "
          f"{dt:.1f}s ({s['generated_tokens']/dt:.1f} tok/s), "
          f"occupancy {s['mean_active_slots']:.2f}")

    # decode_block=8: the K-step executable (on-device argmax feedback)
    # gets its first hardware compile here; token-exact vs the per-step
    # result above, and the per-dispatch amortization is the serving
    # lever through the relay (bench_decode enables it on TPU)
    bb = PagedContinuousBatcher(m, max_batch=4, s_max=256, block_size=32,
                                prefill_chunk=64, fused_admission=True,
                                decode_block=8, compile=True)
    rids_b = [bb.submit(p, 16) for p in prompts]
    t0 = time.perf_counter()
    outs_b = bb.run_until_done()
    dt_b = time.perf_counter() - t0
    for rid, rid_b in zip(rids, rids_b):
        np.testing.assert_array_equal(outs_b[rid_b], outs[rid])
    sb = bb.stats()
    print(f"[tpu] fused serving decode_block=8: "
          f"{sb['generated_tokens']} tokens in {dt_b:.1f}s "
          f"({sb['generated_tokens']/dt_b:.1f} tok/s vs "
          f"{s['generated_tokens']/dt:.1f} per-step)")
