"""Compiled-path tests (reference coverage model: test/dygraph_to_static)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, optimizer


def test_to_static_matches_eager():
    net = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 4))
    net.eval()
    static_fwd = jit.to_static(lambda x: net(x))
    x = paddle.randn([16, 8])
    eager = net(x).numpy()
    np.testing.assert_allclose(static_fwd(x).numpy(), eager, rtol=1e-5)
    np.testing.assert_allclose(static_fwd(x).numpy(), eager, rtol=1e-5)


def test_to_static_sees_param_updates():
    net = nn.Linear(4, 2)
    sfn = jit.to_static(lambda x: net(x))
    x = paddle.randn([3, 4])
    out1 = sfn(x); out1 = sfn(x)
    net.weight._set_data(net.weight._data * 2.0)
    net.bias._set_data(net.bias._data * 0.0)
    np.testing.assert_allclose(sfn(x).numpy(),
                               x.numpy() @ net.weight.numpy(), rtol=1e-5)


def test_to_static_shape_polymorphism_recompiles():
    net = nn.Linear(4, 2)
    sfn = jit.to_static(lambda x: net(x))
    assert sfn(paddle.randn([2, 4])).shape == [2, 2]
    assert sfn(paddle.randn([7, 4])).shape == [7, 2]
    assert len(sfn._cache) == 2


def test_to_static_rng_advances():
    drop = nn.Dropout(0.5)
    sfn = jit.to_static(lambda x: drop(x))
    a = paddle.ones([1000])
    sfn(a)
    r2, r3 = sfn(a), sfn(a)
    assert not np.allclose(r2.numpy(), r3.numpy())


def test_to_static_layer_decorator():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return self.fc(x) * 2

    m = jit.to_static(M())
    x = paddle.randn([2, 4])
    np.testing.assert_allclose(m(x).numpy(),
                               (x.numpy() @ m.fc.weight.numpy()
                                + m.fc.bias.numpy()) * 2, rtol=1e-5)


def test_train_step_matches_eager():
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters(),
                          grad_clip=optimizer.ClipGradByGlobalNorm(1.0))
    lossf = nn.CrossEntropyLoss()
    step = jit.TrainStep(lambda x, y: lossf(model(x), y), opt)

    paddle.seed(3)
    model2 = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    opt2 = optimizer.AdamW(learning_rate=1e-2, parameters=model2.parameters(),
                           grad_clip=optimizer.ClipGradByGlobalNorm(1.0))

    rng = np.random.RandomState(0)
    xs = paddle.to_tensor(rng.randn(32, 16).astype("float32"))
    ys = paddle.to_tensor(rng.randint(0, 4, (32,)))
    jit_losses = [float(step(xs, ys)) for _ in range(10)]
    eager_losses = []
    for _ in range(10):
        loss = lossf(model2(xs), ys)
        loss.backward(); opt2.step(); opt2.clear_grad()
        eager_losses.append(float(loss))
    np.testing.assert_allclose(jit_losses, eager_losses, rtol=1e-4, atol=1e-5)
    assert jit_losses[-1] < jit_losses[0]


def test_train_step_updates_bn_buffers():
    model = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8), nn.ReLU(),
                          nn.Linear(8, 2))
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    lossf = nn.CrossEntropyLoss()
    step = jit.TrainStep(lambda x, y: lossf(model(x), y), opt)
    x = paddle.randn([32, 8])
    y = paddle.to_tensor(np.random.randint(0, 2, (32,)))
    step(x, y)
    m1 = model[1]._mean.numpy().copy()
    step(x, y)
    assert not np.allclose(m1, model[1]._mean.numpy())


def test_train_step_with_lr_scheduler():
    model = nn.Linear(4, 2)
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
    opt = optimizer.SGD(learning_rate=sched, parameters=model.parameters())
    lossf = nn.MSELoss()
    step = jit.TrainStep(lambda x, y: lossf(model(x), y), opt)
    x = paddle.randn([8, 4]); y = paddle.randn([8, 2])
    step(x, y)
    w1 = model.weight.numpy().copy()
    sched.step()
    step(x, y)  # compiled run must pick up the new lr (lr is an input)
    w2 = model.weight.numpy()
    assert not np.allclose(w1, w2)


def test_save_load_roundtrip(tmp_path):
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    p = str(tmp_path / "model")
    paddle.save(net.state_dict(), p + ".pdparams")
    loaded = paddle.load(p + ".pdparams")
    net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net2.set_state_dict(loaded)
    x = paddle.randn([2, 4])
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)


def test_async_save(tmp_path):
    from paddle_tpu.framework import io as fio
    net = nn.Linear(4, 4)
    p = str(tmp_path / "async.pdparams")
    paddle.async_save(net.state_dict(), p)
    fio.wait_async_saves()
    loaded = paddle.load(p)
    np.testing.assert_array_equal(loaded["weight"].numpy(), net.weight.numpy())


def test_compiled_forward_supports_backward():
    """Training through a to_static-compiled forward (review regression)."""
    net = nn.Linear(4, 2)
    sfn = jit.to_static(lambda x: net(x))
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    lossf = nn.MSELoss()
    x = paddle.randn([8, 4]); y = paddle.randn([8, 2])
    losses = []
    for i in range(5):
        loss = lossf(sfn(x), y)
        loss.backward()
        assert net.weight.grad is not None, f"grad missing at step {i}"
        opt.step(); opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_train_step_partial_training_no_tracer_leak():
    lossf = nn.MSELoss()
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    opt = optimizer.SGD(learning_rate=0.1, parameters=m[2].parameters())
    step = jit.TrainStep(lambda a, b: lossf(m(a), b), opt)
    x = paddle.randn([8, 4]); y = paddle.randn([8, 2])
    step(x, y); step(x, y)
    g = m[0].weight.grad
    assert g is not None
    g.numpy()  # concrete, not a leaked tracer


def test_to_static_setitem_state_mutation():
    c = paddle.zeros([1])

    def inc(x):
        c[0] = c[0] + 1.0
        return x + c

    sfn = jit.to_static(inc)
    a = paddle.zeros([1])
    vals = [float(sfn(a)) for _ in range(4)]
    assert vals == [1.0, 2.0, 3.0, 4.0]
    assert float(c) == 4.0


def test_train_step_honors_value_clip():
    p = paddle.core.tensor.Parameter(np.zeros(3, "float32"))
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p],
                        grad_clip=optimizer.ClipGradByValue(1e-3))
    step = jit.TrainStep(lambda t: (p * t).sum(), opt)
    t = paddle.ones([3])
    for _ in range(3):
        step(t)
    assert np.abs(p.numpy()).max() <= 3e-3 + 1e-9


def test_train_step_multi_precision_masters():
    import jax.numpy as jnp
    p = paddle.core.tensor.Parameter(np.array([1.0], "float32"))
    p._set_data(p._data.astype("bfloat16"))
    opt = optimizer.AdamW(learning_rate=0.01, parameters=[p],
                          multi_precision=True)
    step = jit.TrainStep(lambda t: (p * t).sum(), opt)
    for _ in range(3):
        step(paddle.ones([1]))
    assert opt._master_weights[id(p)].dtype == jnp.float32
    assert p.dtype == paddle.bfloat16


def test_to_static_graph_break_fallback():
    """Data-dependent python control flow: full_graph=False falls back to
    eager per signature (the SOT graph-break semantics); full_graph=True
    raises with guidance (reference full-graph mode)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import jit

    calls = {"n": 0}

    @jit.to_static(full_graph=False)
    def branchy(x):
        calls["n"] += 1
        if float(x.sum()) > 0:       # concretizes a tensor -> graph break
            return x * 2
        return x - 1

    pos = paddle.to_tensor(np.ones(4, np.float32))
    neg = paddle.to_tensor(-np.ones(4, np.float32))
    # call 1: eager discovery (works); call 2: compiled trace raises ->
    # falls back to eager and keeps working, with correct branch per value
    np.testing.assert_allclose(np.asarray(branchy(pos)._data), 2.0)
    np.testing.assert_allclose(np.asarray(branchy(pos)._data), 2.0)
    np.testing.assert_allclose(np.asarray(branchy(neg)._data), -2.0)
    np.testing.assert_allclose(np.asarray(branchy(pos)._data), 2.0)
    assert calls["n"] >= 4  # every call ran the python (eager fallback)

    @jit.to_static(full_graph=True)
    def branchy_full(x):
        if float(x.sum()) > 0:
            return x * 2
        return x - 1

    branchy_full(pos)  # discovery pass is eager: fine
    import pytest
    with pytest.raises(RuntimeError, match="data-dependent"):
        branchy_full(pos)  # compiled pass: hard error with guidance


def test_to_static_no_fallback_for_clean_functions():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import jit

    @jit.to_static(full_graph=False)
    def clean(x):
        return (x * 3).sum()

    x = paddle.to_tensor(np.ones(8, np.float32))
    assert float(clean(x)) == 24.0
    assert float(clean(x)) == 24.0
    # stayed compiled: no fallback flag on the cache entry
    entry = clean.concrete_program(x)
    assert entry is not None and not entry.get("fallback")


def test_to_static_batch_buckets():
    """SURVEY §7 hard part (d): bounded compilations for dynamic batch —
    leading dims pad to the next bucket and outputs slice back exactly."""
    from paddle_tpu import jit, nn
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    eager = lambda x: net(x)
    static = jit.to_static(net.forward, batch_buckets=(4, 8, 16))

    rng = np.random.RandomState(0)
    for b in (3, 5, 7, 2, 8, 11):
        x = paddle.to_tensor(rng.randn(b, 8).astype("float32"))
        np.testing.assert_allclose(static(x).numpy(), eager(x).numpy(),
                                   rtol=1e-6, atol=1e-6)
    # six distinct batch sizes -> at most three compiled signatures
    assert len(static._cache) <= 3, list(static._cache)
